"""Telemetry smoke: serve a real executor-backed pipeline, scrape
``GET /metrics`` MID-RUN twice, and assert the core series are present,
well-formed, and increasing. Driven by tools/ci/smoke_metrics.sh under a
hard timeout (a wedged scrape or pipeline hangs rather than fails).

Also covers the breaker/failover surface (PR 8 series): a 2-channel
DistributedServer is served, drained, and scraped so
``serving_channel_breaker_state``, ``serving_failover_total``, and an
observed ``serving_drain_seconds`` are asserted on a live exposition —
plus the incident-diagnosis read surfaces (``/debug/flight``,
``/debug/threads``) and the scrape-time ``serving_slo_*`` gauges.

And the performance observatory (runtime/perfwatch.py + the recompile
sentinel): the executor is AOT-warmed, a deliberately shape-drifted
request is posted, and ``executor_recompiles_total{reason=
"shape_drift"}`` must move on the live exposition; the device-memory
gauges are in CORE_SERIES and ``GET /debug/memory`` must answer
mid-run with a record per local device.

And the distributed-tracing surface (round 16): a request with a known
``traceparent`` must echo the same trace id, report it on
``/span/<rid>``, and land it as a histogram-bucket exemplar on the
Accept-negotiated OpenMetrics exposition.

And the incident-capture surface (round 17, runtime/capture.py): every
200 must echo an ``X-Output-Digest`` header that is exactly the sha256
of its reply bytes, and a deliberately pre-expired-deadline request
(shed 504 before scoring) must move the
``capture_records_total{reason="deadline"}`` series between scrapes —
the labeled VALUE delta, since every reason series pre-registers at 0.

Exit 0 = every assertion held; any failure prints the offending series
and exits nonzero.
"""
import http.client
import json
import re
import sys
import urllib.request

import numpy as np

PROM_LINE = re.compile(
    r"^(# TYPE [a-zA-Z_:][a-zA-Z0-9_:]* (counter|gauge|histogram)"
    r"|[a-zA-Z_:][a-zA-Z0-9_:]*(\{[^{}]*\})? "
    r"[+-]?([0-9]*\.?[0-9]+([eE][+-]?[0-9]+)?|inf|nan))$")

# one representative series per instrumented subsystem: executor
# (pipeline stages + dispatch), serving (queue/batching/replies),
# compile cache (registered at import — 0 until a store is configured),
# and the span layer.
CORE_SERIES = [
    "synapseml_compile_cache_store_hits_total",
    "synapseml_compile_cache_store_misses_total",
    "synapseml_serving_requests_total",
    "synapseml_serving_replies_total",
    "synapseml_serving_batch_size",
    "synapseml_serving_queue_wait_seconds",
    "synapseml_serving_queue_depth",
    "synapseml_serving_score_seconds",
    "synapseml_executor_submit_total",
    "synapseml_executor_dispatch_total",
    "synapseml_executor_bucket_total",
    "synapseml_executor_stage_seconds",
    "synapseml_executor_compute_seconds",
    "synapseml_executor_drain_seconds",
    "synapseml_executor_inflight_batches",
    "synapseml_request_stage_seconds",
    # SLO accounting gauges (runtime/slo.py), registered per server
    "synapseml_serving_slo_availability",
    "synapseml_serving_slo_availability_burn_rate",
    "synapseml_serving_slo_latency_good_fraction",
    "synapseml_serving_slo_latency_burn_rate",
    "synapseml_serving_slo_latency_threshold_ms",
    # performance observatory (runtime/perfwatch.py + the recompile
    # sentinel in runtime/executor.py, docs/observability.md):
    # post-warmup recompile counters/compile timings register at
    # executor import, duty-cycle + device-memory gauges at executor
    # construction (servers register lazily, only when a jax backend
    # already exists — a jax-free front-end must not init one)
    "synapseml_executor_recompiles_total",
    "synapseml_executor_compile_seconds",
    "synapseml_executor_duty_cycle",
    "synapseml_device_hbm_bytes_in_use",
    "synapseml_device_hbm_peak_bytes",
    "synapseml_device_live_buffer_count",
    # roofline cost observatory (runtime/costmodel.py): per-signature
    # flops/bytes gauges + per-device-kind achieved/roofline — all
    # register at warmup() time (the ex.warmup below), sampled at
    # scrape time only
    "synapseml_executor_signature_flops",
    "synapseml_executor_signature_bytes",
    "synapseml_executor_achieved_flops_per_sec",
    "synapseml_executor_roofline_fraction",
    # incident capture (runtime/capture.py): reason-labeled record
    # counters pre-register at import, the drop path and file-size
    # gauge beside them
    "synapseml_capture_records_total",
    "synapseml_capture_dropped_total",
    "synapseml_capture_bytes",
]

# the breaker/failover/drain surface (docs/robustness.md, PR 8): these
# register on a DistributedServer, so they are asserted on the
# dedicated scrape below, not the ContinuousServer one
CHANNEL_SERIES = [
    "synapseml_serving_channel_breaker_state",
    "synapseml_serving_failover_total",
    "synapseml_serving_drain_seconds",
]

INCREASING = [
    "synapseml_serving_requests_total",
    "synapseml_executor_submit_total",
]


def series_total(text: str, name: str) -> float:
    """Sum every sample of one family (any label set)."""
    total = 0.0
    for ln in text.splitlines():
        if ln.startswith(name) and not ln.startswith(name + "_"):
            total += float(ln.rsplit(" ", 1)[1])
    return total


def channel_phase() -> int:
    """Breaker/failover/drain + debug-surface coverage: serve a
    2-channel DistributedServer, score through it, drain it, and
    assert the PR 8 series and the /debug read surfaces on its live
    exposition."""
    from synapseml_tpu.io.serving import DistributedServer, make_reply

    def pipeline(table):
        replies = np.empty(table.num_rows, dtype=object)
        for i, v in enumerate(table["value"]):
            replies[i] = make_reply({"echo": v})
        return table.with_column("reply", replies)

    ds = DistributedServer("metrics_channels", n_channels=2)
    ds.serve(pipeline, max_batch=8)
    try:
        host = ds.url.split("//")[1].rstrip("/")

        def get_json(path):
            with urllib.request.urlopen(urllib.request.Request(
                    f"http://{host}{path}"), timeout=30) as r:
                assert r.status == 200, (path, r.status)
                return json.loads(r.read())

        for k in range(4):
            req = urllib.request.Request(
                ds.url, data=json.dumps({"x": [float(k)]}).encode(),
                method="POST",
                headers={"Content-Type": "application/json"})
            with urllib.request.urlopen(req, timeout=30) as r:
                assert r.status == 200, r.status
        ds.drain(5000)  # observes serving_drain_seconds
        with urllib.request.urlopen(urllib.request.Request(
                f"http://{host}/metrics"), timeout=30) as r:
            text = r.read().decode()
        missing = [s for s in CHANNEL_SERIES if s not in text]
        if missing:
            print("missing channel series:", *missing, sep="\n  ")
            return 1
        if series_total(text,
                        "synapseml_serving_drain_seconds_count") < 1:
            print("serving_drain_seconds never observed a drain")
            return 1
        for ch in ("0", "1"):
            want = ('synapseml_serving_channel_breaker_state{'
                    f'channel="{ch}"')
            if want not in text:
                print(f"no breaker-state gauge for channel {ch}")
                return 1

        # incident-diagnosis read surfaces (docs/observability.md)
        flight = get_json("/debug/flight")
        if not flight.get("threads") or "events" not in flight:
            print(f"/debug/flight snapshot malformed: "
                  f"{sorted(flight)}")
            return 1
        names = {t["name"] for t in get_json("/debug/threads")}
        if "chan-scorer-metrics_channels-0" not in names:
            print(f"/debug/threads misses the channel scorers "
                  f"({sorted(names)})")
            return 1
        print(f"channel-surface ok: breaker/failover/drain series + "
              f"debug surfaces live ({len(names)} threads)")
        return 0
    finally:
        ds.stop()


def main() -> int:
    from synapseml_tpu.io.serving import ContinuousServer, make_reply
    from synapseml_tpu.runtime.executor import BatchedExecutor

    ex = BatchedExecutor(lambda x: (x * 3.0 + 1.0,), min_bucket=8)
    # arm the recompile sentinel: AOT-warm the 2-feature signature the
    # normal posts below ride, so the deliberately drifted post becomes
    # a counted post-warmup recompile on the live exposition
    ex.warmup([((2,), np.float32)], buckets=[8])

    def pipeline(table):
        feats = np.stack([np.asarray(v["x"], np.float32)
                          for v in table["value"]])
        (out,) = ex(feats)
        replies = np.empty(table.num_rows, dtype=object)
        for i in range(table.num_rows):
            replies[i] = make_reply({"y": out[i].tolist()})
        return table.with_column("reply", replies)

    cs = ContinuousServer("metrics_smoke", pipeline, max_batch=16).start()
    try:
        host = cs.url.split("//")[1].rstrip("/")
        conn = http.client.HTTPConnection(host, timeout=30)

        def post():
            conn.request("POST", "/", json.dumps({"x": [1.0, 2.0]}).encode(),
                         {"Content-Type": "application/json"})
            resp = conn.getresponse()
            body = resp.read()
            assert resp.status == 200, (resp.status, body)
            return resp

        def scrape() -> str:
            conn.request("GET", "/metrics")
            resp = conn.getresponse()
            text = resp.read().decode()
            assert resp.status == 200, resp.status
            ctype = resp.getheader("Content-Type", "")
            assert ctype.startswith("text/plain"), ctype
            return text

        for _ in range(5):
            post()
        first = scrape()  # mid-run: the server keeps serving after this

        bad = [ln for ln in first.rstrip("\n").splitlines()
               if not PROM_LINE.match(ln)]
        if bad:
            print("malformed exposition lines:", *bad[:5], sep="\n  ")
            return 1
        missing = [s for s in CORE_SERIES if s not in first]
        if missing:
            print("missing core series:", *missing, sep="\n  ")
            return 1

        rid = post().getheader("X-Request-Id")
        for _ in range(4):
            post()

        # recompile sentinel (docs/observability.md): a shape-drifted
        # request AFTER warmup — 5 features vs the warmed 2 — must
        # surface as executor_recompiles_total on the live exposition,
        # under the shape_drift reason SPECIFICALLY (all four reason
        # series pre-register at 0, so only a value delta on the
        # labeled series proves the classification)
        drift_series = ('synapseml_executor_recompiles_total'
                        '{reason="shape_drift"}')
        recompiles_before = series_total(
            first, "synapseml_executor_recompiles_total")
        drift_before = series_total(first, drift_series)
        conn.request("POST", "/",
                     json.dumps({"x": [1.0] * 5}).encode(),
                     {"Content-Type": "application/json"})
        resp = conn.getresponse()
        drift_body = resp.read()
        assert resp.status == 200, (resp.status, drift_body)

        second = scrape()
        for name in INCREASING:
            v1, v2 = series_total(first, name), series_total(second, name)
            if not v2 > v1:
                print(f"series {name} did not increase: {v1} -> {v2}")
                return 1
        recompiles_after = series_total(
            second, "synapseml_executor_recompiles_total")
        if not recompiles_after > recompiles_before:
            print("post-warmup shape drift did not move "
                  f"executor_recompiles_total: {recompiles_before} -> "
                  f"{recompiles_after}")
            return 1
        drift_after = series_total(second, drift_series)
        if not drift_after > drift_before:
            print("the drifted post was not classified shape_drift: "
                  f"{drift_series} {drift_before} -> {drift_after}")
            return 1

        # incident capture (runtime/capture.py, round 17): the digest
        # echo first — a 200's X-Output-Digest must be exactly the
        # sha256 of the reply bytes the client read
        import hashlib

        conn.request("POST", "/",
                     json.dumps({"x": [2.0, 3.0]}).encode(),
                     {"Content-Type": "application/json"})
        resp = conn.getresponse()
        dig_body = resp.read()
        dig_hdr = resp.getheader("X-Output-Digest")
        assert resp.status == 200, (resp.status, dig_body)
        if dig_hdr != hashlib.sha256(dig_body).hexdigest():
            print(f"X-Output-Digest echo wrong: header {dig_hdr!r} vs "
                  f"sha256 {hashlib.sha256(dig_body).hexdigest()}")
            return 1
        # then the tail-based retention decision: a request already
        # past its deadline at batch-form time sheds 504 — an SLO
        # breach the capture sink must keep, visible as a VALUE delta
        # on the reason-labeled series
        cap_series = ('synapseml_capture_records_total'
                      '{reason="deadline"}')
        cap_before = series_total(scrape(), cap_series)
        conn.request("POST", "/",
                     json.dumps({"x": [4.0, 5.0]}).encode(),
                     {"Content-Type": "application/json",
                      "X-Deadline-Ms": "0.001"})
        resp = conn.getresponse()
        resp.read()
        assert resp.status == 504, resp.status
        # the 504 flushes to the client BEFORE the capture append (a
        # reply never waits on the dump volume), so the counter may
        # trail the reply by a beat — poll briefly
        import time as _time

        deadline = _time.monotonic() + 5.0
        cap_after = series_total(scrape(), cap_series)
        while cap_after <= cap_before and _time.monotonic() < deadline:
            _time.sleep(0.05)
            cap_after = series_total(scrape(), cap_series)
        if not cap_after > cap_before:
            print("the deadline-shed 504 was not captured: "
                  f"{cap_series} {cap_before} -> {cap_after}")
            return 1

        # device-memory surface (runtime/perfwatch.py): /debug/memory
        # answers mid-run with one record per local device
        conn.request("GET", "/debug/memory")
        resp = conn.getresponse()
        mem = json.loads(resp.read())
        assert resp.status == 200, resp.status
        if not mem.get("devices") or "totals" not in mem:
            print(f"/debug/memory snapshot malformed: {sorted(mem)}")
            return 1
        if any("bytes_in_use" not in d for d in mem["devices"]):
            print("/debug/memory device records miss bytes_in_use")
            return 1

        # roofline cost surface (runtime/costmodel.py): /debug/cost
        # serves the per-signature table LIVE mid-run — the warmed
        # 2-feature signature must be present with a captured
        # flops/bytes ledger and a bound classification, and the
        # payload must carry the peak-provenance + attribution notes
        # perf_report relies on offline
        conn.request("GET", "/debug/cost")
        resp = conn.getresponse()
        cost = json.loads(resp.read())
        assert resp.status == 200, resp.status
        for key in ("entries", "peaks", "attribution", "per_kind"):
            if key not in cost:
                print(f"/debug/cost payload missing {key!r}: "
                      f"{sorted(cost)}")
                return 1
        if not cost["entries"]:
            print("/debug/cost has no cost-table entries after warmup")
            return 1
        ent = cost["entries"][0]
        need_fields = {"signature", "flops", "bytes_accessed", "bound",
                       "achieved_fraction", "attainable_flops_per_sec"}
        if not need_fields <= set(ent):
            print(f"/debug/cost entry missing fields: "
                  f"{sorted(need_fields - set(ent))}")
            return 1
        if not any(e.get("captured") and e.get("flops", 0) > 0
                   for e in cost["entries"]):
            print("/debug/cost: no entry captured a flops ledger")
            return 1
        if any(e.get("bound") not in ("compute", "memory", "unknown")
               for e in cost["entries"]):
            print("/debug/cost: invalid bound classification")
            return 1

        # the span surface answers for a real completed request
        conn.request("GET", f"/span/{rid}")
        resp = conn.getresponse()
        span = json.loads(resp.read())
        assert resp.status == 200, resp.status
        stages = set(span["stages"])
        need = {"queue_wait", "batch_form", "stage", "compute", "drain"}
        if not need <= stages:
            print(f"span {rid} missing stages: {sorted(need - stages)}")
            return 1

        # distributed-trace round trip (docs/observability.md,
        # "Distributed tracing"): a request with a KNOWN traceparent
        # must echo our leg's traceparent under the same trace id,
        # /span/<rid> must report that trace id, and the
        # Accept-negotiated OpenMetrics exposition must carry a bucket
        # exemplar naming it
        known_tid = "feedfacecafebeef" * 2
        conn.request("POST", "/",
                     json.dumps({"x": [1.0, 2.0]}).encode(),
                     {"Content-Type": "application/json",
                      "traceparent":
                          f"00-{known_tid}-1234567890abcdef-01"})
        resp = conn.getresponse()
        tr_body = resp.read()
        assert resp.status == 200, (resp.status, tr_body)
        tr_rid = resp.getheader("X-Request-Id")
        echo = resp.getheader("traceparent") or ""
        if not echo.startswith(f"00-{known_tid}-"):
            print(f"traceparent echo lost the caller's trace id: "
                  f"{echo!r}")
            return 1
        conn.request("GET", f"/span/{tr_rid}")
        resp = conn.getresponse()
        tr_span = json.loads(resp.read())
        assert resp.status == 200, resp.status
        if tr_span.get("trace_id") != known_tid:
            print(f"span {tr_rid} does not carry the caller's trace "
                  f"id: {tr_span.get('trace_id')!r}")
            return 1
        conn.request("GET", "/metrics",
                     headers={"Accept":
                              "application/openmetrics-text"})
        resp = conn.getresponse()
        om = resp.read().decode()
        om_ctype = resp.getheader("Content-Type", "")
        assert resp.status == 200, resp.status
        if not om_ctype.startswith("application/openmetrics-text"):
            print(f"OpenMetrics Accept negotiation failed: "
                  f"Content-Type {om_ctype!r}")
            return 1
        if "# EOF" not in om or '# {trace_id="' not in om:
            print("OpenMetrics exposition carries no exemplar")
            return 1
        if f'trace_id="{known_tid}"' not in om:
            print("the known trace id never landed as a latency-"
                  "bucket exemplar")
            return 1

        print("metrics smoke ok:",
              f"{len(first.splitlines())} exposition lines,",
              "requests="
              f"{series_total(second, 'synapseml_serving_requests_total'):.0f},",
              f"recompiles={recompiles_after:.0f},",
              f"memory devices={len(mem['devices'])},",
              f"cost signatures={len(cost['entries'])},",
              f"span stages={sorted(stages)},",
              "traceparent round trip + exemplar ok")
    finally:
        cs.stop()
    return channel_phase()


if __name__ == "__main__":
    sys.exit(main())
