"""Doc-drift gate — thin wrapper over synlint's DR pack.

The real check lives in ``tools/analysis/rules_drift.py`` (DR001: series
registered in code with no catalog row; DR002: catalog row naming a
series no code registers; DR003: committed Grafana dashboard out of sync
with the catalog) so that metric-catalog, dashboard, and env-knob drift
all report through the ONE ``python -m tools.analysis --fail-on-new``
gate. This entrypoint stays for muscle memory and for the metrics-smoke
CI job's focused invocation: it runs the analyzer over the package and
reports only the drift findings.

Exit codes match the old tool: 0 = in sync, 1 = drift, 2 = could not
collect (unparseable package / missing catalog).
"""
import argparse
import os
import sys

HERE = os.path.dirname(os.path.abspath(__file__))
ROOT = os.path.abspath(os.path.join(HERE, os.pardir, os.pardir))
sys.path.insert(0, ROOT)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--package",
                    default=os.path.join(ROOT, "synapseml_tpu"))
    args = ap.parse_args(argv)

    from tools.analysis.engine import analyze_program

    findings, _prog, _stats = analyze_program([args.package], root=ROOT)
    syn = [f for f in findings if f.rule == "SYN000"]
    drift = [f for f in findings if f.rule.startswith("DR")]
    if syn:
        for f in syn:
            print(f.render(), file=sys.stderr)
        print("doc-drift check could not collect names", file=sys.stderr)
        return 2
    if any("catalog missing" in f.message for f in drift):
        for f in drift:
            print(f.render(), file=sys.stderr)
        return 2
    if drift:
        print("metric catalog / dashboard drift "
              "(docs/observability.md — see docs/analysis.md, DR rules):")
        for f in drift:
            print(f"  {f.render()}")
        return 1
    print("doc-drift ok: registered series, catalog rows, and the "
          "generated dashboard agree (synlint DR pack)")
    return 0


if __name__ == "__main__":
    sys.exit(main())
