"""Doc-drift gate: the metric catalog in docs/observability.md must
match the metrics the code actually registers — both directions.

Code side: an AST pass over ``synapseml_tpu/`` collecting every string
literal passed as the first argument to a telemetry registration call
(``counter`` / ``gauge`` / ``gauge_fn`` / ``histogram``, bare or
attribute-qualified) whose name carries one of the gated prefixes
(``serving_``, ``executor_``, ``faults_``, ``blackbox_``,
``device_``, ``fleet_``, ``process_``). The
registry qualifies names dynamically (``synapseml_`` wire prefix), so
the literal at the call site IS the catalog name.

Doc side: the catalog TABLE rows, parsed by the SAME parser the
Grafana-dashboard generator uses (``tools.k8s.gen_dashboard.
catalog_rows``) — one parser, so a metric cannot satisfy this gate
yet be missing from the generated dashboard (a prose-only mention
does not count as a catalog row).

A series registered in code with no catalog row fails; a catalog row
naming a series no code registers fails. Dashboards, alerts, and the
runbook all read the catalog — this gate is what keeps them honest.
Wired into tools/ci/pipeline.yaml (metrics-smoke job); pure AST +
regex, no jax import, fast.
"""
import argparse
import ast
import os
import sys

PREFIXES = ("serving_", "executor_", "faults_", "blackbox_", "device_",
            "fleet_", "process_", "trace_", "capture_", "gbdt_",
            "onnx_", "autotune_")
REGISTER_FNS = {"counter", "gauge", "gauge_fn", "histogram"}

HERE = os.path.dirname(os.path.abspath(__file__))
ROOT = os.path.abspath(os.path.join(HERE, os.pardir, os.pardir))
sys.path.insert(0, ROOT)  # tools.k8s.gen_dashboard (shared parser)


def code_metric_names(package_dir: str) -> dict:
    """{metric_name: [file:line, ...]} for every gated registration."""
    names: dict = {}
    for dirpath, _dirs, files in os.walk(package_dir):
        if "__pycache__" in dirpath:
            continue
        for fn in files:
            if not fn.endswith(".py"):
                continue
            path = os.path.join(dirpath, fn)
            with open(path, encoding="utf-8") as fh:
                try:
                    tree = ast.parse(fh.read(), filename=path)
                except SyntaxError as e:  # pragma: no cover - repo gate
                    print(f"unparseable {path}: {e}", file=sys.stderr)
                    return {}
            rel = os.path.relpath(path, ROOT)
            for node in ast.walk(tree):
                if not isinstance(node, ast.Call) or not node.args:
                    continue
                fnode = node.func
                fname = (fnode.attr if isinstance(fnode, ast.Attribute)
                         else fnode.id if isinstance(fnode, ast.Name)
                         else None)
                if fname not in REGISTER_FNS:
                    continue
                arg = node.args[0]
                if not (isinstance(arg, ast.Constant)
                        and isinstance(arg.value, str)):
                    continue
                if arg.value.startswith(PREFIXES):
                    names.setdefault(arg.value, []).append(
                        f"{rel}:{node.lineno}")
    return names


def doc_metric_names(doc_path: str) -> set:
    """Gated names with a catalog TABLE row — via the dashboard
    generator's parser, so gate and dashboard see the same rows."""
    from tools.k8s.gen_dashboard import catalog_rows

    return {name for name, _labels, _kind, _meaning
            in catalog_rows(doc_path)
            if name.startswith(PREFIXES)}


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--package",
                    default=os.path.join(ROOT, "synapseml_tpu"))
    ap.add_argument("--docs", default=os.path.join(
        ROOT, "docs", "observability.md"))
    args = ap.parse_args(argv)

    code = code_metric_names(args.package)
    doc = doc_metric_names(args.docs)
    if not code or not doc:
        print("doc-drift check could not collect names "
              f"(code={len(code)}, doc={len(doc)})")
        return 2

    undocumented = sorted(set(code) - doc)
    unregistered = sorted(doc - set(code))
    rc = 0
    if undocumented:
        rc = 1
        print("registered in code but missing a catalog row in "
              f"{os.path.relpath(args.docs, ROOT)}:")
        for n in undocumented:
            print(f"  {n}  ({', '.join(code[n][:3])})")
    if unregistered:
        rc = 1
        print("catalog rows naming series no code registers:")
        for n in unregistered:
            print(f"  {n}")
    if rc == 0:
        print(f"doc-drift ok: {len(code)} registered series all "
              f"cataloged, {len(doc)} catalog rows all registered")
    return rc


if __name__ == "__main__":
    sys.exit(main())
