"""Tensor-parallel serving proof (docs/perf.md "Round 18"): the whole
tp×dp stack on a REAL serving subprocess, on a forced-8-device CPU
platform (the TPU-slice stand-in) —

1. a serving replica scores a TRANSFORMER (int32 token ids — the
   pipeline feeds the graph's declared input dtype) at
   ``--tensor-parallel 2`` with capture armed at head-sample 1.0, AOT
   warmed against a shared ``ExecutableStore``;
2. ``/debug/memory`` must show ``tp_param_bytes`` RESIDENT on at least
   two devices — the weights actually rest sharded, not replicated;
3. after warmup, live traffic must leave
   ``executor_recompiles_total`` at ZERO — the mesh layout is folded
   into every warmup signature, so resharded serving never compiles
   on the scoring path;
4. the capture file is replayed OFFLINE at ``--tensor-parallel 4``
   (tools/replay.py's resharding canary): every record must reproduce
   a bit-identical digest — the registry's default gather formulation
   makes tp=2 and tp=4 replies bitwise equal, so any divergence is a
   real determinism break;
5. a deliberately perturbed record must make the replay exit 2 with a
   divergence report naming the rid — the canary actually bites.

Driven by tools/ci/smoke_tp.sh under a hard timeout: a wedged tp
warmup hangs rather than fails, so it becomes a fast exit-124.
"""
import hashlib
import json
import os
import re
import signal
import subprocess
import sys
import tempfile
import threading
import urllib.error
import urllib.request

SEQ_LEN = 16
VOCAB = 100
REQUESTS = 10


def series_total(text: str, name: str) -> float:
    total = 0.0
    for ln in text.splitlines():
        if ln.startswith(name) and not ln.startswith(name + "_"):
            total += float(ln.rsplit(" ", 1)[1])
    return total


def get(url: str, timeout: float = 15.0):
    with urllib.request.urlopen(urllib.request.Request(url),
                                timeout=timeout) as r:
        return r.status, r.read()


def post(url: str, obj, timeout: float = 120.0):
    req = urllib.request.Request(
        url, data=json.dumps(obj).encode(), method="POST",
        headers={"Content-Type": "application/json"})
    try:
        with urllib.request.urlopen(req, timeout=timeout) as r:
            return r.status, r.read(), dict(r.headers.items())
    except urllib.error.HTTPError as e:
        return e.code, e.read(), dict(e.headers.items()) if e.headers else {}


def main() -> int:
    from synapseml_tpu.onnx import zoo

    work = tempfile.mkdtemp(prefix="tp_proof_")
    model_path = os.path.join(work, "model.onnx")
    with open(model_path, "wb") as fh:
        fh.write(zoo.transformer_encoder(VOCAB, 64, 4, 128, 2,
                                         seq_len=SEQ_LEN, seed=3))
    cache_dir = os.path.join(work, "cache")
    cap_dir = os.path.join(work, "capture")

    env = dict(os.environ)
    env.pop("SYNAPSEML_FAULTS", None)
    env.setdefault("PYTHONPATH", os.getcwd())
    env["SYNAPSEML_CAPTURE_HEAD_SAMPLE"] = "1.0"  # keep every reply
    proc = subprocess.Popen(
        [sys.executable, "-m", "synapseml_tpu.io.serving",
         "--host", "127.0.0.1", "--port", "0", "--name", "tp_proof",
         "--model", model_path, "--devices", "all",
         "--tensor-parallel", "2", "--cache-dir", cache_dir,
         # bucket 1 rides the tp_rep layout, 8 the dp-sharded one —
         # warming both proves the mesh-folded signatures cover the
         # layouts traffic will actually dispatch
         "--warmup", "1,8",
         "--dump-dir", cap_dir, "--drain-timeout-ms", "4000"],
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
        text=True, env=env)
    capture_file = os.path.join(cap_dir, f"capture-{proc.pid}.jsonl")
    try:
        lines, url_box = [], {}
        url_found = threading.Event()

        def read_stdout():
            for line in proc.stdout:
                lines.append(line)
                if not url_found.is_set():
                    m = re.search(r"serving \[.*\] on (http://\S+/)",
                                  line)
                    if m:
                        url_box["url"] = m.group(1)
                        url_found.set()

        threading.Thread(target=read_stdout, daemon=True).start()
        if not url_found.wait(600.0):
            print("FAIL: serving subprocess never announced its URL\n"
                  + "".join(lines[-30:]))
            return 1
        url = url_box["url"]
        base = url.rstrip("/")
        print(f"tp=2 replica up at {url}", flush=True)

        # post-warmup floor: nothing below may move this counter
        _, m0 = get(base + "/metrics")
        recompiles0 = series_total(
            m0.decode(), "synapseml_executor_recompiles_total")

        digests = []
        for i in range(REQUESTS):
            tokens = [(7 * i + 3 * k) % VOCAB for k in range(SEQ_LEN)]
            status, body, headers = post(url, {"features": tokens})
            if status != 200:
                print(f"FAIL: request {i} scored {status}: "
                      f"{body[:300]!r}")
                return 1
            digest = headers.get("X-Output-Digest")
            if digest != hashlib.sha256(body).hexdigest():
                print(f"FAIL: X-Output-Digest missing/wrong on "
                      f"request {i}: {digest!r}")
                return 1
            digests.append(digest)
        if len(set(digests)) < 2:
            print("FAIL: distinct payloads scored to identical "
                  "replies — the scorer is not scoring")
            return 1

        # the weights actually REST sharded: tp_param_bytes on >= 2
        # devices, and no single device holds the whole placement
        _, mem_b = get(base + "/debug/memory")
        mem = json.loads(mem_b)
        per_dev = {d["device"]: d.get("tp_param_bytes", 0)
                   for d in mem.get("devices", [])}
        resident = {d: b for d, b in per_dev.items() if b > 0}
        total = mem.get("totals", {}).get("tp_param_bytes", 0)
        if len(resident) < 2:
            print(f"FAIL: tp_param_bytes resident on "
                  f"{len(resident)} device(s), need >= 2: {per_dev}")
            return 1
        if max(resident.values()) >= total:
            print(f"FAIL: one device holds the entire placement "
                  f"({max(resident.values())} of {total} B) — "
                  "weights are replicated, not sharded")
            return 1
        print(f"shard gauges ok: {len(resident)} devices, max/device "
              f"{max(resident.values())} of {total} B total", flush=True)

        _, m1 = get(base + "/metrics")
        recompiles1 = series_total(
            m1.decode(), "synapseml_executor_recompiles_total")
        if recompiles1 != recompiles0:
            print(f"FAIL: executor recompiled post-warmup "
                  f"({recompiles0} -> {recompiles1}) — a mesh layout "
                  "escaped the warmup signatures")
            return 1

        proc.send_signal(signal.SIGTERM)
        rc = proc.wait(timeout=30)
        if rc != 0:
            print(f"FAIL: serving exited {rc}\n" + "".join(lines[-30:]))
            return 1
        print(f"tp=2 phase ok: {REQUESTS} scored, 0 recompiles, "
              "clean drain", flush=True)

        # --- resharding canary: replay the capture at tp=4 ----------
        report_path = os.path.join(work, "report.json")
        rp = subprocess.run(
            [sys.executable, "tools/replay.py", capture_file,
             "--model", model_path, "--cache-dir", cache_dir,
             "--devices", "all", "--tensor-parallel", "4",
             "--out", report_path],
            capture_output=True, text=True, env=env, timeout=600)
        print(rp.stdout.strip(), flush=True)
        if rp.returncode != 0:
            print(f"FAIL: tp=4 replay exited {rp.returncode}: "
                  f"{rp.stderr[-2000:]}")
            return 1
        with open(report_path, encoding="utf-8") as fh:
            report = json.load(fh)
        if report["diverged"]:
            print(f"FAIL: tp=2 -> tp=4 resharding diverged: "
                  f"{report['diverged'][:3]}")
            return 1
        if report["matched"] < REQUESTS:
            print(f"FAIL: replay matched only {report['matched']} of "
                  f"{REQUESTS}")
            return 1
        if report.get("recompiles") != 0:
            print(f"FAIL: tp=4 replay recompiled on the scoring path "
                  f"({report.get('recompiles')})")
            return 1

        # --- a perturbed digest must fail loudly --------------------
        perturbed = os.path.join(work, "perturbed.jsonl")
        flipped = None
        with open(capture_file, encoding="utf-8") as src, \
                open(perturbed, "w", encoding="utf-8") as dst:
            for line in src:
                try:
                    rec = json.loads(line)
                except json.JSONDecodeError:
                    continue
                if flipped is None and rec.get("status_code") == 200:
                    rec["output_digest"] = "0" * 64
                    flipped = rec["rid"]
                dst.write(json.dumps(rec) + "\n")
        rp2 = subprocess.run(
            [sys.executable, "tools/replay.py", perturbed,
             "--model", model_path, "--cache-dir", cache_dir,
             "--devices", "all", "--tensor-parallel", "4"],
            capture_output=True, text=True, env=env, timeout=600)
        if rp2.returncode != 2:
            print(f"FAIL: perturbed replay exited {rp2.returncode}, "
                  f"wanted 2: {rp2.stdout[-1000:]}")
            return 1
        if flipped not in rp2.stdout:
            print(f"FAIL: divergence report does not name the "
                  f"perturbed rid {flipped}: {rp2.stdout[-1000:]}")
            return 1
        print(f"tp proof ok: {report['matched']} records bit-identical "
              f"across tp=2 -> tp=4, shard gauges on {len(resident)} "
              f"devices, 0 recompiles, perturbed rid {flipped[:8]}... "
              "exits 2")
        return 0
    finally:
        if proc.poll() is None:
            proc.kill()


if __name__ == "__main__":
    sys.exit(main())
