#!/usr/bin/env bash
# Telemetry scrape smoke (docs/observability.md).
#
# Serves a real BatchedExecutor-backed ContinuousServer, scrapes
# GET /metrics MID-RUN twice, and asserts the core executor/serving/span
# series are present, well-formed Prometheus text, and increasing — then
# fetches the span breakdown for a completed request id. A wedged
# pipeline or scrape HANGS rather than fails, so the hard wall-clock
# timeout turns it into a fast red X (exit 124) instead of a stuck job.
#
# Usage: tools/ci/smoke_metrics.sh   [SMOKE_TIMEOUT=seconds]
set -euo pipefail
cd "$(dirname "$0")/../.."
export JAX_PLATFORMS=cpu
export PYTHONPATH="$PWD${PYTHONPATH:+:$PYTHONPATH}"
exec timeout -k 10 "${SMOKE_TIMEOUT:-180}" \
  python tools/ci/metrics_check.py
