#!/usr/bin/env bash
# Fast deadlock guard for the async executor pipeline + serving layer.
#
# The submit/drain executor (runtime/executor.py) and the 3-stage serving
# query (io/serving.py) are thread pipelines: a wedged drain or reply
# thread would HANG the full tier-1 suite rather than fail it. This
# target runs just those suites under a hard wall-clock timeout so a
# deadlock surfaces as a fast red X (exit 124) instead of a stuck job.
#
# Usage: tools/ci/smoke_pipeline.sh   [SMOKE_TIMEOUT=seconds]
set -euo pipefail
cd "$(dirname "$0")/../.."
exec timeout -k 10 "${SMOKE_TIMEOUT:-300}" env JAX_PLATFORMS=cpu \
  python -m pytest tests/test_executor_pipeline.py tests/test_serving.py \
  tests/test_faults.py tests/test_channel_failover.py \
  tests/test_blackbox.py tests/test_perfwatch.py tests/test_fleet.py \
  tests/test_costmodel.py tests/test_tracing.py tests/test_capture.py \
  tests/test_predict_kernels.py tests/test_analysis.py \
  -q -p no:cacheprovider
