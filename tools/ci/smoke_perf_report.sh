#!/usr/bin/env bash
# Roofline cost-observatory gate (docs/perf.md "Roofline methodology").
#
# Runs the bounded `bench.py --fast` subset with cost capture live,
# then asserts tools/perf_report.py can attribute EVERY selected bench
# group from the one committed-shaped artifact: a flops/bytes cost
# signature per device group (captured at warmup from XLA's own
# compiled cost model, runtime/costmodel.py), a compute/memory-bound
# class, an achieved-vs-roofline fraction, and a complete report
# schema (--check exits 2 on any unattributed group). A wedged bench
# or report HANGS rather than fails, so the hard wall-clock timeout
# turns it into a fast red X (exit 124) instead of a stuck job.
#
# Usage: tools/ci/smoke_perf_report.sh   [SMOKE_TIMEOUT=seconds]
set -euo pipefail
cd "$(dirname "$0")/../.."
export JAX_PLATFORMS=cpu
export PYTHONPATH="$PWD${PYTHONPATH:+:$PYTHONPATH}"
out="$(mktemp /tmp/bench_cost_XXXXXX.json)"
report="${out%.json}.md"
trap 'rm -f "$out" "$report"' EXIT
timeout -k 10 "${SMOKE_TIMEOUT:-600}" \
  python bench.py --fast --out "$out" > /dev/null
timeout -k 10 60 \
  python tools/perf_report.py "$out" --check --out "$report"
# the report is a real artifact, not just an exit code: show the
# ranked table so the CI log answers "what is the bottleneck" directly
sed -n '/## Ranked bottlenecks/,/## Per-group/p' "$report" | head -20
