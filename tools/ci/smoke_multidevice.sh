#!/usr/bin/env bash
# Hard-timeout smoke for the multi-device data-parallel executor.
#
# Forces an 8-device virtual CPU platform (the TPU-slice stand-in) and
# runs the multi-device suite alone: sharded-vs-single bit-identity,
# stream() ordering, the n=1 degenerate path, ragged final buckets, and
# the round-robin fallback for odd topologies. Like smoke_pipeline.sh,
# a wedged dispatch across devices would HANG rather than fail — the
# timeout turns that into a fast exit-124.
#
# Usage: tools/ci/smoke_multidevice.sh   [SMOKE_TIMEOUT=seconds]
set -euo pipefail
cd "$(dirname "$0")/../.."
exec timeout -k 10 "${SMOKE_TIMEOUT:-300}" env JAX_PLATFORMS=cpu \
  XLA_FLAGS="--xla_force_host_platform_device_count=8" \
  python -m pytest tests/test_executor_multidevice.py -q -p no:cacheprovider
