"""Noise-aware bench regression gate (docs/perf.md "Regression gate").

Bench numbers on a shared CI box drift — identical-config legs on this
class of runner have measured ±30% (throughput) to ±100% (latency)
run-to-run jitter (docs/observability.md "Overhead methodology"). A
naive "new < old" comparison would page on noise daily and train
everyone to ignore it. This gate is built to catch *step-function*
regressions (a 2× cold start, a serving path that grew a sync) while
staying silent inside the measured noise envelope:

1. **min-of-N**: every run is appended to a ``bench_history.jsonl``
   (one strict-JSON run per line — the same files ``bench.py --out``
   writes); the gate evaluates the **best** value per metric over the
   last N runs (min for latency-unit metrics, max for throughput).
   Noise is one-sided — contention only ever makes a box *slower* — so
   best-of-N estimates the box's capability, not its worst moment.
2. **Per-metric noise tolerances**: the committed baseline
   (``tools/ci/bench_baseline.json``) carries an explicit tolerance
   per metric — the measured jitter envelope of that metric on the CI
   runner class, plus safety margin. A latency metric regresses when
   ``best > baseline * (1 + tol)``; a throughput metric when
   ``best < baseline * (1 - tol)``.
3. A metric in the baseline that is **missing** from every evaluated
   run is a failure too — silently losing a metric would defeat the
   gate exactly when a bench crashes.

Exit codes: 0 = within tolerance, 2 = regression (or vanished metric),
1 = usage/malformed input. Importable: :func:`evaluate` is the pure
comparison (tests/test_perfwatch.py pins pass-on-jitter and
fail-on-20%-regression on synthetic fixtures).

Usage::

    python bench.py --fast --out run1.json
    python bench.py --fast --out run2.json
    python tools/ci/bench_check.py --baseline tools/ci/bench_baseline.json \
        --history /tmp/bench_history.jsonl --n 2 run1.json run2.json

    # refresh the committed baseline from the runs (keeps tolerances):
    python tools/ci/bench_check.py --write-baseline \
        --baseline tools/ci/bench_baseline.json run1.json run2.json
"""
from __future__ import annotations

import argparse
import json
import os
import sys
import time
from typing import Any, Dict, List, Optional, Tuple

# default per-metric tolerances for --write-baseline (fractional; the
# rationale lives in docs/perf.md "Regression gate"): latency metrics
# on this runner class drift up to ±100% leg-to-leg, min-of-N pulls the
# estimate toward the floor but a 1.0 band is still needed to keep the
# gate quiet on contended runners; the cold-start A/B adds XLA-compile
# variance on top. The gate is a tripwire for 2-3x steps, not percent
# drift — percent-level claims ride the TPU driver's BENCH history.
DEFAULT_TOLERANCE = 0.5
TOLERANCES = {
    "serving_roundtrip_p50_ms": 1.0,
    "serving_scored_roundtrip_p50_ms": 1.0,
    "serving_scored_concurrent_p50_ms": 1.0,
    "serving_cold_start_first_batch_ms": 1.5,
    # round-15 routed scoring lanes (throughput: max-of-N, a 0.75 band
    # trips below 1/4 of baseline — a step, not scheduler noise on a
    # contended CPU runner)
    "gbdt_predict_rows_per_sec_per_chip": 0.75,
    "onnx_int8_rows_per_sec_per_chip": 0.75,
    # round-16 autotuner-routed resnet50_fast lanes (CI-sized twin;
    # throughput, same 0.75 collapse band as the other routed lanes)
    "onnx_resnet50_images_per_sec_per_chip": 0.75,
    "onnx_resnet50_hostfeed_images_per_sec": 0.75,
    # round-19 decode serving (tokens/s throughput keeps the routed-
    # lane collapse band; TTFT/ITL are scheduler-latency metrics with
    # the cold-start-class variance of a contended CPU runner)
    "decode_serving_tokens_per_sec": 0.75,
    "decode_serving_ttft_p50_ms": 1.5,
    "decode_serving_itl_p50_ms": 1.5,
}

# units whose metrics are better when SMALLER (latency-domain); every
# other unit is a rate/throughput where bigger is better
_LOWER_IS_BETTER_UNITS = ("ms", "s", "seconds")


def lower_is_better(unit: str) -> bool:
    return (unit or "").strip().lower() in _LOWER_IS_BETTER_UNITS


def flatten_metrics(run: Dict[str, Any]) -> Dict[str, Dict[str, Any]]:
    """{metric: {value, unit}} over the headline entry + every
    ``secondary`` entry of one bench payload. Non-numeric values (a
    nulled-out NaN) are skipped — "missing" is the honest reading."""
    out: Dict[str, Dict[str, Any]] = {}
    entries = [run] + list(run.get("secondary") or [])
    for e in entries:
        name = e.get("metric")
        value = e.get("value")
        if isinstance(name, str) and isinstance(value, (int, float)) \
                and not isinstance(value, bool):
            out[name] = {"value": float(value),
                         "unit": str(e.get("unit", ""))}
    return out


def best_of(runs: List[Dict[str, Any]], metric: str,
            unit: str) -> Optional[float]:
    """Best value of one metric across runs: min for latency-domain
    units, max otherwise; None when absent from every run."""
    values = []
    for run in runs:
        rec = flatten_metrics(run).get(metric)
        if rec is not None:
            values.append(rec["value"])
    if not values:
        return None
    return min(values) if lower_is_better(unit) else max(values)


def evaluate(runs: List[Dict[str, Any]],
             baseline: Dict[str, Any]) -> Tuple[List[Dict[str, Any]],
                                                List[Dict[str, Any]]]:
    """Compare best-of-``runs`` against ``baseline``; returns
    ``(rows, regressions)`` where each row describes one baseline
    metric's verdict and ``regressions`` is the failing subset."""
    metrics = baseline.get("metrics") or {}
    default_tol = float(
        (baseline.get("defaults") or {}).get("tolerance",
                                             DEFAULT_TOLERANCE))
    rows: List[Dict[str, Any]] = []
    regressions: List[Dict[str, Any]] = []
    for name in sorted(metrics):
        spec = metrics[name]
        base = float(spec["value"])
        unit = str(spec.get("unit", ""))
        tol = float(spec.get("tolerance", default_tol))
        lower = lower_is_better(unit)
        best = best_of(runs, name, unit)
        if best is None:
            row = {"metric": name, "unit": unit, "baseline": base,
                   "best": None, "tolerance": tol, "ratio": None,
                   "status": "missing"}
            rows.append(row)
            regressions.append(row)
            continue
        if lower:
            limit = base * (1 + tol)
            regressed = best > limit
        else:
            # a throughput tolerance >= 1.0 would push the limit to or
            # below 0 and silently disable the gate — clamp so even a
            # deliberately loose band still trips on a collapse
            limit = base * (1 - min(tol, 0.9))
            regressed = best < limit
        ratio = (best / base) if base else float("inf")
        row = {"metric": name, "unit": unit, "baseline": base,
               "best": best, "tolerance": tol,
               "ratio": round(ratio, 3),
               "status": "regressed" if regressed else "ok"}
        rows.append(row)
        if regressed:
            regressions.append(row)
    return rows, regressions


DEFAULT_HISTORY_MAX = 500


def append_history(path: str, runs: List[Dict[str, Any]],
                   max_lines: int = DEFAULT_HISTORY_MAX) -> None:
    """One strict-JSON run per line, stamped — the bench's flight
    history. Appends, then ROTATES the file down to its newest
    ``max_lines`` lines: on a persistent runner the history used to
    grow without bound (every CI run appended forever). Rotation works
    on raw lines — a torn tail line (a killed writer) neither crashes
    it nor survives a rotation that drops it, and the gate's reader
    already skips malformed lines either way."""
    os.makedirs(os.path.dirname(os.path.abspath(path)), exist_ok=True)
    torn_tail = False
    try:
        with open(path, "rb") as fh:
            fh.seek(0, os.SEEK_END)
            if fh.tell() > 0:
                fh.seek(-1, os.SEEK_END)
                torn_tail = fh.read(1) != b"\n"
    except OSError:
        pass
    with open(path, "a", encoding="utf-8") as fh:
        if torn_tail:
            # a killed writer left a line without its newline: close it
            # off so the next record starts a line of its own instead
            # of being swallowed into the torn one (the reader skips
            # the malformed line either way)
            fh.write("\n")
        for run in runs:
            rec = {"ts": round(time.time(), 3), "run": run}
            fh.write(json.dumps(rec, allow_nan=False) + "\n")
    if max_lines <= 0:
        return
    try:
        with open(path, encoding="utf-8", errors="replace") as fh:
            lines = fh.readlines()
    except OSError:
        return
    if len(lines) <= max_lines:
        return
    # tmp-then-rename: a reader (or a crash) mid-rotation sees either
    # the old full file or the new tail, never a half-written one
    tmp = path + ".tmp"
    with open(tmp, "w", encoding="utf-8") as fh:
        fh.writelines(lines[-max_lines:])
    os.replace(tmp, path)


def load_history(path: str, n: int) -> List[Dict[str, Any]]:
    """The last ``n`` runs from a history file (malformed lines are
    skipped with a warning — a torn tail line must not kill the gate)."""
    runs: List[Dict[str, Any]] = []
    with open(path, encoding="utf-8") as fh:
        for i, line in enumerate(fh):
            line = line.strip()
            if not line:
                continue
            try:
                rec = json.loads(line)
            except ValueError:
                print(f"bench_check: skipping malformed history line "
                      f"{i + 1}", file=sys.stderr)
                continue
            runs.append(rec.get("run", rec))
    return runs[-n:]


def write_baseline(path: str, runs: List[Dict[str, Any]],
                   default_tolerance: float = DEFAULT_TOLERANCE) -> dict:
    """Baseline = best-of-``runs`` per metric + the per-metric
    tolerance table; committed next to the pipeline so every future
    perf claim lands against a recorded reference."""
    names: Dict[str, str] = {}
    for run in runs:
        for name, rec in flatten_metrics(run).items():
            names.setdefault(name, rec["unit"])
    metrics = {}
    for name, unit in sorted(names.items()):
        best = best_of(runs, name, unit)
        if best is None:
            continue
        metrics[name] = {
            "value": best, "unit": unit,
            "tolerance": TOLERANCES.get(name, default_tolerance),
        }
    baseline = {
        "generated_by": "tools/ci/bench_check.py --write-baseline",
        "generated_at": time.strftime("%Y-%m-%dT%H:%M:%SZ",
                                      time.gmtime()),
        "n_runs": len(runs),
        "defaults": {"tolerance": default_tolerance},
        "metrics": metrics,
    }
    with open(path, "w", encoding="utf-8") as fh:
        json.dump(baseline, fh, indent=2, allow_nan=False)
        fh.write("\n")
    return baseline


def _print_report(rows: List[Dict[str, Any]], n_runs: int) -> None:
    width = max([len(r["metric"]) for r in rows] + [6])
    print(f"bench_check: best-of-{n_runs} vs baseline")
    for r in rows:
        best = "MISSING" if r["best"] is None else f"{r['best']:.4g}"
        ratio = "" if r["ratio"] is None else f" ({r['ratio']:.2f}x)"
        mark = "FAIL" if r["status"] != "ok" else " ok "
        print(f"  [{mark}] {r['metric']:<{width}} best={best}"
              f" baseline={r['baseline']:.4g} {r['unit']}"
              f" tol=±{r['tolerance']:.0%}{ratio}")


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("runs", nargs="*", metavar="RUN_JSON",
                    help="bench run files (bench.py --out); appended "
                         "to --history when given")
    ap.add_argument("--baseline",
                    default=os.path.join(os.path.dirname(
                        os.path.abspath(__file__)),
                        "bench_baseline.json"))
    ap.add_argument("--history", metavar="JSONL",
                    help="append runs here and evaluate over its tail")
    ap.add_argument("--n", type=int, default=3,
                    help="evaluate best-of over the last N runs "
                         "(default 3)")
    ap.add_argument("--history-max", type=int,
                    default=DEFAULT_HISTORY_MAX,
                    help="rotate --history down to its newest K lines "
                         "on append (0 = never rotate; default "
                         f"{DEFAULT_HISTORY_MAX})")
    ap.add_argument("--write-baseline", action="store_true",
                    help="write best-of-runs as the new baseline "
                         "instead of gating")
    ap.add_argument("--tolerance", type=float, default=DEFAULT_TOLERANCE,
                    help="default tolerance for --write-baseline "
                         "metrics without a per-metric entry")
    args = ap.parse_args(argv)

    new_runs: List[Dict[str, Any]] = []
    for path in args.runs:
        try:
            with open(path, encoding="utf-8") as fh:
                new_runs.append(json.load(fh))
        except (OSError, ValueError) as e:
            print(f"bench_check: cannot read run {path}: {e}")
            return 1

    if args.write_baseline:
        if not new_runs:
            print("bench_check: --write-baseline needs run files")
            return 1
        baseline = write_baseline(args.baseline, new_runs,
                                  args.tolerance)
        print(f"wrote {args.baseline}: {len(baseline['metrics'])} "
              f"metrics from {len(new_runs)} run(s)")
        return 0

    if args.history:
        if new_runs:
            append_history(args.history, new_runs,
                           max_lines=args.history_max)
        try:
            runs = load_history(args.history, args.n)
        except OSError as e:
            print(f"bench_check: cannot read history {args.history}: {e}")
            return 1
    else:
        runs = new_runs[-args.n:]
    if not runs:
        print("bench_check: no runs to evaluate (pass run files or "
              "--history)")
        return 1

    try:
        with open(args.baseline, encoding="utf-8") as fh:
            baseline = json.load(fh)
    except (OSError, ValueError) as e:
        print(f"bench_check: cannot read baseline {args.baseline}: {e}")
        return 1

    rows, regressions = evaluate(runs, baseline)
    if not rows:
        print("bench_check: baseline holds no metrics")
        return 1
    _print_report(rows, len(runs))
    if regressions:
        print(f"bench_check: {len(regressions)} regression(s) past "
              "tolerance — see docs/perf.md \"Regression gate\"")
        return 2
    print(f"bench_check ok: {len(rows)} metrics within tolerance")
    return 0


if __name__ == "__main__":
    sys.exit(main())
