#!/usr/bin/env bash
# Hard-timeout smoke for the fleet autoscaling loop (tools/fleet/
# controller.py + runtime/autoscale.py, docs/deployment.md "Fleet
# operations").
#
# Drives tools/ci/chaos_check.py --fleet: a controller brings up 2
# REAL model-scoring serving subprocesses on one shared
# ExecutableStore, an open-loop Poisson ramp (tools/loadgen.py
# --targets) pushes duty cycle over the policy line, and the phase
# asserts the whole closed loop — scale-up 2->3 with a recompile-free
# warm boot from the shared store, SLO green through a mid-load
# replica SIGKILL, and a SIGTERM drain-clean scale-down with zero
# dropped admitted requests. A wedged replica or controller loop hangs
# rather than fails, so the timeout turns it into a fast exit-124.
#
# Usage: tools/ci/smoke_fleet.sh   [SMOKE_TIMEOUT=seconds]
set -euo pipefail
cd "$(dirname "$0")/../.."
export JAX_PLATFORMS=cpu
export PYTHONPATH="$PWD${PYTHONPATH:+:$PYTHONPATH}"
exec timeout -k 10 "${SMOKE_TIMEOUT:-600}" \
  python tools/ci/chaos_check.py --fleet
