"""Cross-process warm-restart check (driven by smoke_warm_restart.sh).

Two phases against ONE shared compile-cache directory:

- ``A`` (the first replica): fresh model + empty cache. Asserts every
  bucket signature was freshly COMPILED and PERSISTED, scores a batch,
  and saves the outputs + timing to the state file.
- ``B`` (the restarted replica — a brand new process): same model bytes,
  same cache dir. Asserts every signature was LOADED from the store
  (zero fresh XLA compiles — the whole point of the cache), that the
  first-batch time-to-result measured via the ``bench.first_batch_ms``
  metric hook is finite and recorded, and that the scored outputs are
  BIT-IDENTICAL to process A's.

Usage: warm_restart_check.py {A|B} <cache_dir> <state_file.npz>
"""
import json
import sys

import numpy as np


def build_model(cache_dir):
    from synapseml_tpu.onnx import ONNXModel, zoo

    model = ONNXModel(model_bytes=zoo.mlp([16, 32], num_classes=4, seed=0))
    model.set(compile_cache_dir=cache_dir, mini_batch_size=32)
    return model


def main():
    phase, cache_dir, state_file = sys.argv[1], sys.argv[2], sys.argv[3]
    import bench
    from synapseml_tpu.data.table import Table

    model = build_model(cache_dir)
    # two batch sizes -> two buckets (8 and 32), so the check covers a
    # real ladder, not one lucky signature
    rng = np.random.default_rng(0)
    big = rng.standard_normal((20, 16)).astype(np.float32)
    small = rng.standard_normal((3, 16)).astype(np.float32)

    ms, report, out_big = bench.first_batch_ms(
        model, Table({"input": big}), buckets=[8, 32])
    out_small = model.transform(Table({"input": small}))
    col = model.graph.output_names[0]
    big_col = np.asarray(out_big[col])
    small_col = np.asarray(out_small[col])
    print(f"[{phase}] first_batch_ms={ms:.1f} {report!r}", flush=True)

    assert not report.errors, report.errors
    if phase == "A":
        assert report.compiled == len(report.entries), \
            f"cold process did not compile everything: {report!r}"
        persisted = sum(1 for e in report.entries if e.get("persisted"))
        assert persisted == len(report.entries), \
            f"cold process persisted {persisted}/{len(report.entries)}"
        np.savez(state_file, big=big_col, small=small_col,
                 first_batch_ms=ms)
        return 0

    assert phase == "B", phase
    # THE invariant: a restarted replica deserializes, never recompiles
    assert report.loaded == len(report.entries), \
        f"warm restart recompiled: {report!r} {report.entries}"
    assert ms > 0.0, ms  # the metric hook measured the restart
    prev = np.load(state_file)
    assert np.array_equal(big_col, prev["big"]), \
        "outputs diverged across restart (bucket 32)"
    assert np.array_equal(small_col, prev["small"]), \
        "outputs diverged across restart (bucket 8)"
    print(json.dumps({
        "metric": "serving_cold_start_first_batch_ms",
        "cold_ms": round(float(prev["first_batch_ms"]), 1),
        "warm_restart_ms": round(ms, 1),
        "executables_loaded": report.loaded,
        "outputs_bit_identical": True,
    }), flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
