#!/usr/bin/env bash
# Lock-sanitizer smoke (docs/analysis.md "Dynamic sanitizer"): run the
# chaos and decode smokes with SYNAPSEML_LOCKSAN=1 so every sanitized
# lock in the serving stack — breaker trips, drain-thread kills,
# scrape-vs-drain interleavings, decode scheduler wait loops — executes
# under runtime lock-order/blocking/deadlock detection, with each
# process dumping its observed-graph artifact into
# SYNAPSEML_LOCKSAN_OUT. Then close the static<->dynamic loop:
# `python -m tools.analysis --observed` diffs the merged observed
# graph against synlint's CC002 closure and gates (--fail-on-new) on
# model-gap edges AND on any runtime inversion/blocking/deadlock
# finding the sanitizer recorded — zero findings or red X. The env
# vars are exported BEFORE the interpreters start so the import-time
# enable path is itself under test. A deadlocked pipeline HANGS rather
# than fails, so the hard wall-clock timeouts turn it into a fast
# exit-124; the artifact directory survives for CI upload either way.
#
# Usage: tools/ci/smoke_locksan.sh   [SMOKE_TIMEOUT=seconds]
#                                    [LOCKSAN_OUT=dir]
set -euo pipefail
cd "$(dirname "$0")/../.."
export JAX_PLATFORMS=cpu
export PYTHONPATH="$PWD${PYTHONPATH:+:$PYTHONPATH}"
export SYNAPSEML_LOCKSAN=1
export SYNAPSEML_LOCKSAN_OUT="${LOCKSAN_OUT:-/tmp/locksan-smoke}"
rm -rf "$SYNAPSEML_LOCKSAN_OUT"
mkdir -p "$SYNAPSEML_LOCKSAN_OUT"

SMOKE_TIMEOUT="${SMOKE_TIMEOUT:-360}" bash tools/ci/smoke_chaos.sh
SMOKE_TIMEOUT="${SMOKE_TIMEOUT:-600}" bash tools/ci/smoke_decode.sh

ls "$SYNAPSEML_LOCKSAN_OUT"/locksan-*.json >/dev/null  # artifacts exist
timeout -k 10 120 \
  python -m tools.analysis --observed "$SYNAPSEML_LOCKSAN_OUT" \
  --fail-on-new
echo "locksan smoke ok: observed graph cross-checked clean" \
  "($(ls "$SYNAPSEML_LOCKSAN_OUT"/locksan-*.json | wc -l) artifacts)"
