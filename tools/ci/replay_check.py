"""Incident capture & deterministic replay proof (docs/observability.md,
"Incident capture & replay"): the full loop on a REAL model-scoring
serving subprocess —

1. a serving replica (MLP ONNX model, AOT-warmed against a shared
   ExecutableStore, capture armed with head-sample 1.0 so healthy
   requests are kept too) takes open-loop loadgen traffic;
2. a poison payload (a non-numeric feature the scorer's ``np.asarray``
   deterministically rejects) rides a coalesced burst so the
   poison-bisection isolates it to a 400 while its healthy batch-mates
   score 200;
3. the live ``/metrics`` must show ``capture_records_total`` moving for
   both the ``poison`` and ``head_sample`` reasons, and
   ``/debug/capture`` must list the records;
4. after a SIGTERM drain, the capture file is replayed OFFLINE in a
   FRESH interpreter (``tools/replay.py --model --cache-dir``): every
   healthy record must reproduce a bit-identical output digest, the
   poison record must reproduce its 400, warmup must deserialize
   every signature from the store (compiled == 0) and the recompile
   sentinel must read ZERO — the replay compiled nothing;
5. a deliberately perturbed record (flipped digest) must make the
   harness exit 2 with a divergence report naming the rid.

"It broke once" becomes a committed, re-runnable artifact. Driven by
tools/ci/smoke_replay.sh under a hard timeout: a wedged warmup or
replay hangs rather than fails, so it becomes a fast exit-124.
"""
import hashlib
import json
import os
import re
import signal
import subprocess
import sys
import tempfile
import threading
import time
import urllib.error
import urllib.request

POISON_FEATURES = ["not-a-number"] + [1.0] * 15  # np.asarray -> ValueError
FEATURE_DIM = 16


def series_total(text: str, name: str) -> float:
    total = 0.0
    for ln in text.splitlines():
        if ln.startswith(name) and not ln.startswith(name + "_"):
            total += float(ln.rsplit(" ", 1)[1])
    return total


def get(url: str, timeout: float = 15.0):
    with urllib.request.urlopen(urllib.request.Request(url),
                                timeout=timeout) as r:
        return r.status, r.read()


def post(url: str, obj, timeout: float = 60.0):
    req = urllib.request.Request(
        url, data=json.dumps(obj).encode(), method="POST",
        headers={"Content-Type": "application/json"})
    try:
        with urllib.request.urlopen(req, timeout=timeout) as r:
            return r.status, r.read(), dict(r.headers.items())
    except urllib.error.HTTPError as e:
        body = e.read()
        return e.code, body, dict(e.headers.items()) if e.headers else {}


def poison_burst(url: str, attempts: int = 3):
    """One coalesced burst of 8 concurrent posts, exactly one poisoned:
    the 25ms coalesce window batches them, the bisection isolates the
    poison to a 400 while the mates score 200. Retried a couple of
    times — an unlucky singleton drain replies 500 (the bisection only
    runs at n>1), which is not the contract under test."""
    for _ in range(attempts):
        results = [None] * 8
        barrier = threading.Barrier(8)

        def client(i):
            body = (POISON_FEATURES if i == 3
                    else [float((i + k) % 7) for k in range(FEATURE_DIM)])
            barrier.wait(timeout=30)
            results[i] = post(url, {"features": body})

        threads = [threading.Thread(target=client, args=(i,),
                                    daemon=True) for i in range(8)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=60)
        if any(r is None for r in results):
            return None, "a burst client hung"
        statuses = [r[0] for r in results]
        if statuses[3] == 400 and statuses.count(200) == 7:
            return results, None
        time.sleep(0.2)
    return None, f"burst never isolated the poison to a 400 ({statuses})"


def main() -> int:
    from synapseml_tpu.onnx import zoo
    from tools.loadgen import run_load

    work = tempfile.mkdtemp(prefix="replay_proof_")
    model_path = os.path.join(work, "model.onnx")
    with open(model_path, "wb") as fh:
        fh.write(zoo.mlp([16, 32], num_classes=4, seed=0))
    cache_dir = os.path.join(work, "cache")
    cap_dir = os.path.join(work, "capture")

    env = dict(os.environ)
    env.pop("SYNAPSEML_FAULTS", None)
    env.setdefault("PYTHONPATH", os.getcwd())
    # keep EVERY healthy reply: the proof replays normal scoring next
    # to the breach (production default is 0.01)
    env["SYNAPSEML_CAPTURE_HEAD_SAMPLE"] = "1.0"
    proc = subprocess.Popen(
        [sys.executable, "-m", "synapseml_tpu.io.serving",
         "--host", "127.0.0.1", "--port", "0", "--name", "replay_proof",
         "--model", model_path, "--cache-dir", cache_dir,
         "--warmup", "auto", "--coalesce-ms", "25",
         "--dump-dir", cap_dir, "--drain-timeout-ms", "4000"],
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
        text=True, env=env)
    capture_file = os.path.join(cap_dir, f"capture-{proc.pid}.jsonl")
    try:
        lines, url_box = [], {}
        url_found = threading.Event()

        def read_stdout():
            for line in proc.stdout:
                lines.append(line)
                if not url_found.is_set():
                    m = re.search(r"serving \[.*\] on (http://\S+/)",
                                  line)
                    if m:
                        url_box["url"] = m.group(1)
                        url_found.set()

        threading.Thread(target=read_stdout, daemon=True).start()
        # generous: --warmup auto compiles the full bucket ladder on a
        # cold cache (the replay below then proves the store pays out)
        if not url_found.wait(420.0):
            print("FAIL: serving subprocess never announced its URL")
            return 1
        url = url_box["url"]
        base = url.rstrip("/")
        print(f"replica up at {url}", flush=True)

        _, before_b = get(base + "/metrics")
        before = before_b.decode()

        # open-loop healthy traffic (digest-bearing 200s to replay)
        s = run_load(url, rps=30, duration_s=1.5, shapes=[FEATURE_DIM],
                     seed=11, timeout=30.0,
                     payload_fn=lambda i, shape: {
                         "features": [float((i + k) % 7)
                                      for k in range(shape)]})
        if s["hung"] or s["by_status"].get("200", 0) < 10:
            print(f"FAIL: healthy load did not score: {s['by_status']} "
                  f"hung={s['hung']}")
            return 1

        burst, err = poison_burst(url)
        if err:
            print(f"FAIL: {err}")
            return 1
        # the mates' replies carry the digest the replay must reproduce
        mate_digest = burst[0][2].get("X-Output-Digest")
        if not mate_digest or mate_digest != hashlib.sha256(
                burst[0][1]).hexdigest():
            print(f"FAIL: X-Output-Digest missing/wrong on a burst "
                  f"mate: {mate_digest!r}")
            return 1

        # mid-run telemetry: the reason-labeled capture counters moved.
        # Replies flush to clients BEFORE the capture append, so the
        # counters may trail the burst by a beat — poll briefly
        def _capture_deltas():
            _, after_b = get(base + "/metrics")
            after = after_b.decode()
            out = {}
            for reason in ("poison", "head_sample"):
                series = ('synapseml_capture_records_total'
                          f'{{reason="{reason}"}}')
                out[reason] = (series_total(after, series)
                               - series_total(before, series))
            return out

        floors = {"poison": 1, "head_sample": 10}
        deadline = time.monotonic() + 10.0
        deltas = _capture_deltas()
        while (any(deltas[r] < f for r, f in floors.items())
               and time.monotonic() < deadline):
            time.sleep(0.2)
            deltas = _capture_deltas()
        short = {r: d for r, d in deltas.items() if d < floors[r]}
        if short:
            print(f"FAIL: capture_records_total deltas short of their "
                  f"floors: {short}")
            return 1

        # /debug/capture lists the breach with its file location
        _, dbg_b = get(base + "/debug/capture?n=64")
        dbg = json.loads(dbg_b)
        if not dbg.get("records") or not any(
                r.get("reason") == "poison" for r in dbg["records"]):
            print(f"FAIL: /debug/capture shows no poison record "
                  f"({len(dbg.get('records', []))} records)")
            return 1
        if dbg.get("path") != capture_file:
            print(f"FAIL: /debug/capture path {dbg.get('path')!r} != "
                  f"{capture_file!r}")
            return 1

        proc.send_signal(signal.SIGTERM)
        rc = proc.wait(timeout=30)
        if rc != 0:
            print(f"FAIL: serving exited {rc}")
            return 1
        print("capture phase ok: poison=400 isolated, counters moved, "
              "clean drain", flush=True)

        # --- offline replay in a FRESH interpreter ------------------
        report_path = os.path.join(work, "report.json")
        rp = subprocess.run(
            [sys.executable, "tools/replay.py", capture_file,
             "--model", model_path, "--cache-dir", cache_dir,
             "--keep-outputs", "--out", report_path],
            capture_output=True, text=True, env=env, timeout=420)
        print(rp.stdout.strip(), flush=True)
        if rp.returncode != 0:
            print(f"FAIL: offline replay exited {rp.returncode}: "
                  f"{rp.stderr[-2000:]}")
            return 1
        with open(report_path, encoding="utf-8") as fh:
            report = json.load(fh)
        if report["diverged"]:
            print(f"FAIL: replay diverged: {report['diverged'][:3]}")
            return 1
        if report["matched"] < 10 or report["reproduced_errors"] < 1:
            print(f"FAIL: replay matched={report['matched']} "
                  f"reproduced_errors={report['reproduced_errors']}")
            return 1
        # the zero-recompile proof: warmup deserialized EVERY signature
        # from the store the serving process seeded, and nothing
        # compiled on the scoring path either
        if report.get("recompiles") != 0:
            print(f"FAIL: replay recompiled "
                  f"({report.get('recompiles')}) — the shared store "
                  "did not pay out")
            return 1
        wu = report.get("warmup", {})
        if wu.get("compiled", 1) != 0 or wu.get("loaded", 0) < 1:
            print(f"FAIL: replay warmup was not store-fed: {wu}")
            return 1

        # --- a perturbed record must fail loudly --------------------
        perturbed = os.path.join(work, "perturbed.jsonl")
        flipped = None
        with open(capture_file, encoding="utf-8") as src, \
                open(perturbed, "w", encoding="utf-8") as dst:
            for line in src:
                try:
                    rec = json.loads(line)
                except json.JSONDecodeError:
                    continue
                if flipped is None and rec.get("status_code") == 200:
                    rec["output_digest"] = "0" * 64
                    flipped = rec["rid"]
                dst.write(json.dumps(rec) + "\n")
        rp2 = subprocess.run(
            [sys.executable, "tools/replay.py", perturbed,
             "--model", model_path, "--cache-dir", cache_dir],
            capture_output=True, text=True, env=env, timeout=420)
        if rp2.returncode != 2:
            print(f"FAIL: perturbed replay exited {rp2.returncode}, "
                  f"wanted 2: {rp2.stdout[-1000:]}")
            return 1
        if flipped not in rp2.stdout:
            print(f"FAIL: divergence report does not name the "
                  f"perturbed rid {flipped}: {rp2.stdout[-1000:]}")
            return 1
        print(f"replay proof ok: {report['matched']} bit-identical, "
              f"poison 400 reproduced, 0 recompiles "
              f"({wu.get('loaded')} store-loaded), perturbed rid "
              f"{flipped[:8]}... exits 2")
        return 0
    finally:
        if proc.poll() is None:
            proc.kill()


if __name__ == "__main__":
    sys.exit(main())
