#!/usr/bin/env bash
# Tensor-parallel serving smoke (tools/ci/tp_check.py, docs/perf.md
# "Round 18 — tensor-parallel serving"): on a forced-8-device virtual
# CPU platform, a real serving subprocess scores a transformer at
# --tensor-parallel 2 — shard gauges must show weights resident on
# >= 2 devices, post-warmup recompiles must stay ZERO, and the
# captured traffic must replay bit-identically at --tensor-parallel 4
# (exit 2 on divergence). A wedged tp warmup would HANG rather than
# fail — the timeout turns that into a fast exit-124.
#
# Usage: tools/ci/smoke_tp.sh   [SMOKE_TIMEOUT=seconds]
set -euo pipefail
cd "$(dirname "$0")/../.."
export JAX_PLATFORMS=cpu
export XLA_FLAGS="--xla_force_host_platform_device_count=8"
export PYTHONPATH="$PWD${PYTHONPATH:+:$PYTHONPATH}"
exec timeout -k 10 "${SMOKE_TIMEOUT:-900}" \
  python tools/ci/tp_check.py
