#!/usr/bin/env bash
# Round-15 scoring-kernel canary: the fused Pallas traversal kernel's
# interpret-mode parity suite + int8-lane bit-exactness run under a hard
# wall (tests/test_predict_kernels.py — deep trees, multiclass, NaN
# rows, N=0/N=1 edges, binned + iforest variants, router semantics),
# then the probe-fallback contract is exercised EXPLICITLY: with
# SYNAPSEML_GBDT_PALLAS=0 (and on any non-TPU backend) a routed predict
# must answer through the XLA path with the route counter proving it —
# kill switch and fallback are load-bearing, not decorative.
#
# Usage: tools/ci/smoke_kernels.sh   [SMOKE_TIMEOUT=seconds]
set -euo pipefail
cd "$(dirname "$0")/../.."

timeout -k 10 "${SMOKE_TIMEOUT:-420}" env JAX_PLATFORMS=cpu \
  python -m pytest tests/test_predict_kernels.py -q -p no:cacheprovider

# kill-switch fallback proof: routed predict under SYNAPSEML_GBDT_PALLAS=0
# answers via XLA (counter asserted), bit-identical to the default route
timeout -k 10 120 env JAX_PLATFORMS=cpu SYNAPSEML_GBDT_PALLAS=0 \
  SYNAPSEML_ONNX_INT8=0 python - <<'PY'
import numpy as np
from synapseml_tpu.gbdt.boosting import BoostParams, train
from synapseml_tpu.runtime import telemetry

rng = np.random.default_rng(0)
x = rng.normal(size=(512, 6))
y = (x[:, 0] > 0).astype(np.float64)
b = train(BoostParams(objective="binary", num_iterations=4,
                      num_leaves=7), x, y)
p1 = b.predict(x[:100])
counters = telemetry.snapshot()["counters"]
xla = counters.get('synapseml_gbdt_predict_route_total{backend="xla"}', 0)
pallas = counters.get(
    'synapseml_gbdt_predict_route_total{backend="pallas"}', 0)
assert xla >= 1 and pallas == 0, (xla, pallas)
print(f"kill-switch fallback ok: xla={int(xla)} pallas={int(pallas)}")
PY
