#!/usr/bin/env bash
# Chaos smoke (docs/robustness.md): run the serving stack with
# SYNAPSEML_FAULTS injecting probabilistic compute faults under
# concurrent load, then a deterministic drain-thread kill — and assert
# non-faulted requests still succeed, nothing ever hangs, and /metrics
# shows the injections/restarts/sheds. The env var is exported BEFORE
# the interpreter starts so the import-time fault-arming path is itself
# under test. Then the channel failure domain (docs/robustness.md,
# "channel failure domains"): kill one DistributedServer channel at
# prob 1.0 under open-loop tools/loadgen.py traffic — failover keeps
# every request 200 (bit-identical), the breaker trips
# CLOSED->OPEN->HALF_OPEN->CLOSED, goodput recovers — and finally a
# SIGTERM rolling-restart drain of a real serving subprocess (zero
# dropped accepted requests, 503 + Retry-After for new ones, clean
# exit inside --drain-timeout-ms). A wedged pipeline HANGS rather than
# fails, so the hard wall-clock timeout turns it into a fast red X
# (exit 124).
#
# Usage: tools/ci/smoke_chaos.sh   [SMOKE_TIMEOUT=seconds]
set -euo pipefail
cd "$(dirname "$0")/../.."
export JAX_PLATFORMS=cpu
export PYTHONPATH="$PWD${PYTHONPATH:+:$PYTHONPATH}"
export SYNAPSEML_FAULTS="${SYNAPSEML_FAULTS:-compute:0.1}"
exec timeout -k 10 "${SMOKE_TIMEOUT:-360}" \
  python tools/ci/chaos_check.py
