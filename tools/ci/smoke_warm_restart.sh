#!/usr/bin/env bash
# Hard-timeout smoke for the persistent compile cache's warm-restart
# guarantee (runtime/compile_cache.py).
#
# Process A scores against an EMPTY cache dir (compiles + persists every
# bucket executable); process B — a genuine OS-level restart, no shared
# interpreter state — points at the same dir and must (1) LOAD every
# executable instead of compiling (asserted from the WarmupReport),
# (2) record its first-batch time-to-result via the bench.first_batch_ms
# metric hook, and (3) produce BIT-IDENTICAL outputs to A. Any cache
# miss, skew, or corruption would surface as a recompile (assertion) —
# and a wedged deserialization would HANG, which the timeout turns into
# a fast exit-124.
#
# Usage: tools/ci/smoke_warm_restart.sh   [SMOKE_TIMEOUT=seconds]
set -euo pipefail
cd "$(dirname "$0")/../.."
TMP="$(mktemp -d)"
trap 'rm -rf "$TMP"' EXIT
export JAX_PLATFORMS=cpu
export PYTHONPATH="$PWD${PYTHONPATH:+:$PYTHONPATH}"  # bench.py lives at the root
timeout -k 10 "${SMOKE_TIMEOUT:-300}" \
  python tools/ci/warm_restart_check.py A "$TMP/cache" "$TMP/state.npz"
timeout -k 10 "${SMOKE_TIMEOUT:-300}" \
  python tools/ci/warm_restart_check.py B "$TMP/cache" "$TMP/state.npz"
echo "warm-restart smoke ok"
