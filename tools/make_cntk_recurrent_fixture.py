"""Generate the committed recurrent CNTK fixture (tests/fixtures/).

A bidirectional RNN tagger-shape model: forward PastValue recurrence and
backward FutureValue recurrence over the same projected input, spliced
on the feature axis, with a linear head — the smallest graph exercising
the whole recurrent-reader surface (two independent cycles, both
directions, downstream consumption of scan outputs). The bytes are
committed together with frozen expected outputs so later reader changes
are tested against a frozen artifact. Caveat: the artifact is written by
this repo's own CntkModelBuilder, so it guards against regression, not
against a misreading of the CNTK wire format itself — format parity
rests on the protoc cross-check (tests/test_cntk_format.py wire tests)
and, for the cuDNN blob layout, on the torch.nn.{LSTM,GRU,RNN} oracle
(test_optimized_rnn_stack_matches_torch). If an environment with the
real `cntk` package ever becomes available, regenerate this fixture
with a genuine CNTK export (as tools/make_lightgbm_fixtures.py does for
LightGBM); the reference executes such models natively via
Function.load — deep-learning/.../cntk/SerializableFunction.scala:85-143.

Run from the repo root:  python tools/make_cntk_recurrent_fixture.py
"""
import os
import sys

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from synapseml_tpu.dl.cntk_format import (  # noqa: E402
    CntkModelBuilder, OP_FUTURE_VALUE, OP_PAST_VALUE, OP_PLUS, OP_SPLICE,
    OP_TANH, OP_TIMES)

FEAT, HIDDEN, OUT = 5, 4, 3


def build(seed=11):
    rng = np.random.default_rng(seed)
    Wf = (rng.normal(size=(FEAT, HIDDEN)) * 0.4).astype(np.float32)
    Wb = (rng.normal(size=(FEAT, HIDDEN)) * 0.4).astype(np.float32)
    Wo = (rng.normal(size=(2 * HIDDEN, OUT)) * 0.4).astype(np.float32)
    bias = (rng.normal(size=(OUT,)) * 0.1).astype(np.float32)

    b = CntkModelBuilder("birnn")
    x = b.add_input((FEAT,))
    zero = b.add_parameter(np.zeros((), np.float32))

    wxf = b.add_op(OP_TIMES, [x, b.add_parameter(Wf.T)], {"outputRank": 1})
    pvf = b.add_op(OP_PAST_VALUE, ["__f__", zero], {"offset": 1})
    hf = b.add_op(OP_TANH, [b.add_op(OP_PLUS, [wxf, pvf])])
    b.set_input(pvf, 0, hf)

    wxb = b.add_op(OP_TIMES, [x, b.add_parameter(Wb.T)], {"outputRank": 1})
    fvb = b.add_op(OP_FUTURE_VALUE, ["__b__", zero], {"offset": 1})
    hb = b.add_op(OP_TANH, [b.add_op(OP_PLUS, [wxb, fvb])])
    b.set_input(fvb, 0, hb)

    both = b.add_op(OP_SPLICE, [hf, hb], {"axis": 0})  # feature axis
    y = b.add_op(OP_TIMES, [both, b.add_parameter(Wo.T)],
                 {"outputRank": 1})
    y = b.add_op(OP_PLUS, [y, b.add_parameter(bias)])
    return b.to_bytes(y), (Wf, Wb, Wo, bias)


def reference(x, Wf, Wb, Wo, bias):
    n, t, _ = x.shape
    hf = np.zeros((n, HIDDEN), np.float32)
    hb = np.zeros((n, HIDDEN), np.float32)
    outf = np.zeros((n, t, HIDDEN), np.float32)
    outb = np.zeros((n, t, HIDDEN), np.float32)
    for i in range(t):
        hf = np.tanh(x[:, i] @ Wf + hf)
        outf[:, i] = hf
    for i in range(t - 1, -1, -1):
        hb = np.tanh(x[:, i] @ Wb + hb)
        outb[:, i] = hb
    return np.concatenate([outf, outb], axis=-1) @ Wo + bias


def main():
    here = os.path.dirname(os.path.abspath(__file__))
    fixtures = os.path.join(os.path.dirname(here), "tests", "fixtures")
    os.makedirs(fixtures, exist_ok=True)
    blob, (Wf, Wb, Wo, bias) = build()
    x = np.random.default_rng(21).normal(size=(2, 6, FEAT)) \
        .astype(np.float32)
    expected = reference(x, Wf, Wb, Wo, bias).astype(np.float32)
    with open(os.path.join(fixtures, "cntk_rnn.model"), "wb") as fh:
        fh.write(blob)
    np.savez(os.path.join(fixtures, "cntk_rnn_io.npz"),
             input=x, expected=expected)
    print(f"wrote cntk_rnn.model ({len(blob)} bytes) + io.npz "
          f"expected shape {expected.shape}")


if __name__ == "__main__":
    main()
