"""Offline deterministic replay of captured incidents.

The capture sink (``synapseml_tpu/runtime/capture.py``) keeps the exact
input bytes of every SLO-breaching request plus a sha256 **output
digest** of the reply that went out. This harness closes the loop: load
a capture file, rebuild the scoring pipeline from the same model (the
recorded model content hash is verified against the file you hand it —
replaying yesterday's incident against today's weights would "diverge"
meaninglessly), warm it from the shared ``ExecutableStore`` (the
recompile sentinel proves the replay compiled nothing new), re-score
every record, and diff the recomputed digests against the captured
ones:

- a captured **200** must reproduce a 200 with a bit-identical digest;
- a captured **400** (a poison payload the bisection isolated) must
  reproduce its error — a poison record that suddenly scores clean is
  a divergence too (the rollout changed behavior);
- sheds and infrastructure errors (429/503/504/5xx) are environmental,
  not properties of the payload — they are reported as skipped, never
  replayed for a verdict.

Exit codes: **0** every replayable record reproduced, **2** any
divergence (per-record report: rid, trace_id, captured vs replayed
digest, max-abs-diff when the record retained its reply and
``--keep-outputs`` is set), **1** usage/model-mismatch/empty-capture
errors.

``--serve URL`` replays against a LIVE endpoint instead: each payload
is POSTed in recorded order and the reply's ``X-Output-Digest`` header
is compared — the "did this rollout change scores?" canary, no model
file needed on the operator's side.

Usage::

    python tools/replay.py capture.jsonl --model model.onnx \
        [--cache-dir /cache/compile] [--keep-outputs] \
        [--limit N] [--out report.json]
    python tools/replay.py capture.jsonl --serve http://host:8898/
"""
from __future__ import annotations

import argparse
import hashlib
import json
import sys
import urllib.error
import urllib.request
from typing import Any, Dict, List, Optional, Tuple

REPLAYABLE = (200, 400)


def _max_abs_diff(a: bytes, b: bytes) -> Optional[float]:
    """Max absolute difference between the numeric leaves of two JSON
    bodies walked in parallel, or None when shapes/types disagree (a
    structural divergence is reported via the digests either way)."""
    try:
        da, db = json.loads(a), json.loads(b)
    except (json.JSONDecodeError, UnicodeDecodeError):
        return None

    worst = [0.0]

    def walk(x, y) -> bool:
        if isinstance(x, bool) or isinstance(y, bool):
            return x == y
        if isinstance(x, (int, float)) and isinstance(y, (int, float)):
            worst[0] = max(worst[0], abs(float(x) - float(y)))
            return True
        if isinstance(x, list) and isinstance(y, list):
            return (len(x) == len(y)
                    and all(walk(xi, yi) for xi, yi in zip(x, y)))
        if isinstance(x, dict) and isinstance(y, dict):
            return (set(x) == set(y)
                    and all(walk(x[k], y[k]) for k in x))
        return x == y

    return worst[0] if walk(da, db) else None


def _load_records(paths: List[str]) -> List[Dict[str, Any]]:
    from synapseml_tpu.runtime import capture as cap

    records: List[Dict[str, Any]] = []
    for p in paths:
        records.extend(cap.scan(p))
    return records


def _echo_pipeline():
    """The serving entry's no-model echo pipeline, replicated byte-for-
    byte (``make_reply`` over the parsed JSON value) so echo captures
    replay to identical digests."""
    import numpy as np

    from synapseml_tpu.io.serving import make_reply

    def pipeline(table):
        replies = np.empty(table.num_rows, dtype=object)
        for i, v in enumerate(table["value"]):
            replies[i] = make_reply(v)
        return table.with_column("reply", replies)

    return pipeline


def _score_one(pipeline, rec: Dict[str, Any], payload: bytes
               ) -> Tuple[int, str, Optional[bytes], Optional[str]]:
    """Re-score one captured payload through the rebuilt pipeline:
    ``(status, digest, reply_bytes, error)``. A pipeline exception maps
    to 400 — exactly the verdict the serving bisection hands a
    confirmed poison singleton."""
    import numpy as np

    from synapseml_tpu.data.table import Table
    from synapseml_tpu.io.http import HTTPRequestData
    from synapseml_tpu.io.serving import ID_COL, REQUEST_COL, parse_request

    ids = np.array([rec.get("rid") or "replay"], dtype=object)
    reqs = np.empty(1, dtype=object)
    reqs[:] = [HTTPRequestData(
        url=rec.get("path") or "/", method=rec.get("method") or "POST",
        headers={"Content-Type": rec.get("content_type")
                 or "application/json"},
        entity=payload)]
    try:
        table = parse_request(Table({ID_COL: ids, REQUEST_COL: reqs}))
        resp = pipeline(table)["reply"][0]
        body = resp.entity or b""
        return (resp.status_code,
                hashlib.sha256(body).hexdigest(), body, None)
    except Exception as e:  # noqa: BLE001 - the poison-reproduce path
        return 400, "", None, repr(e)[:300]


def _post_one(url: str, rec: Dict[str, Any], payload: bytes,
              timeout: float) -> Tuple[Any, str, Optional[bytes]]:
    """--serve mode: one POST of a captured payload; ``(status,
    digest_header, reply_bytes)`` — socket death reports ``"error"``."""
    req = urllib.request.Request(
        url, data=payload, method="POST",
        headers={"Content-Type": rec.get("content_type")
                 or "application/json"})
    try:
        with urllib.request.urlopen(req, timeout=timeout) as r:
            return (r.status, r.headers.get("X-Output-Digest") or "",
                    r.read())
    except urllib.error.HTTPError as e:
        try:
            body = e.read()
        except Exception:  # noqa: BLE001 - best-effort drain
            body = None
        return (e.code,
                (e.headers.get("X-Output-Digest") or ""
                 if e.headers is not None else ""), body)
    except Exception:  # noqa: BLE001 - refused/reset/timeout
        return "error", "", None


def _recompiles() -> float:
    """Total post-warmup recompiles this process counted — the
    PR-10 sentinel. Zero after an offline replay is the proof the
    shared ExecutableStore really did hand back every executable."""
    from synapseml_tpu.runtime import telemetry as tm

    return sum(m.value for _lbl, m in
               tm.series("executor_recompiles_total"))


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("captures", nargs="+",
                    help="capture-<pid>.jsonl file(s) to replay")
    ap.add_argument("--model", default=None,
                    help="ONNX model file to rebuild the pipeline from "
                         "(verified against the records' model content "
                         "hash); omit for echo-pipeline captures")
    ap.add_argument("--cache-dir", default=None,
                    help="shared compile-cache/ExecutableStore dir — "
                         "point it at the serving volume so warmup "
                         "deserializes instead of compiling (the "
                         "report's recompiles field proves it)")
    ap.add_argument("--devices", default=None,
                    help="rebuild the pipeline dp-sharded over this "
                         "device spec ('all' or a count) — with "
                         "--tensor-parallel this is the resharding "
                         "canary: a capture served at one tp degree "
                         "must replay bit-identically at another")
    ap.add_argument("--tensor-parallel", type=int, default=1,
                    help="tensor-parallel ways for the rebuilt "
                         "pipeline (requires --devices; must divide "
                         "the pool). Default partition rules keep "
                         "replies bitwise identical across tp "
                         "degrees, so any divergence here is real")
    ap.add_argument("--partition-rules", default=None,
                    help="partition-rule override: a JSON "
                         "[regex, axes] list or the 'megatron' "
                         "preset (NOTE: megatron opts into sharded "
                         "compute — ~1e-6 drift vs the captured "
                         "digests is expected, divergence is not "
                         "a verdict)")
    ap.add_argument("--serve", default=None, metavar="URL",
                    help="replay against a LIVE endpoint instead of "
                         "rebuilding the pipeline (verifies the "
                         "X-Output-Digest reply header)")
    ap.add_argument("--keep-outputs", action="store_true",
                    help="retain replayed reply bodies in the "
                         "divergence report and compute max-abs-diff "
                         "against records that kept theirs")
    ap.add_argument("--limit", type=int, default=0,
                    help="replay at most this many records (0 = all)")
    ap.add_argument("--timeout", type=float, default=30.0,
                    help="--serve mode per-request timeout")
    ap.add_argument("--out", default=None,
                    help="write the full report as JSON here")
    args = ap.parse_args(argv)

    records = _load_records(args.captures)
    if not records:
        print(f"error: no records in {', '.join(args.captures)} "
              "(empty, missing, or fully torn file)")
        return 1
    replayable = [r for r in records
                  if r.get("status_code") in REPLAYABLE
                  and (r.get("payload") is not None
                       or r.get("payload_b64") is not None)]
    skipped = len(records) - len(replayable)
    limited_out = 0
    if args.limit > 0:
        # accounted, never silent: a partial verification must not
        # read as full coverage (the same no-silent-caps rule the
        # vacuous-pass exits enforce)
        limited_out = max(0, len(replayable) - args.limit)
        replayable = replayable[:args.limit]
    if not replayable:
        print(f"error: {len(records)} records but none replayable "
              "(only sheds/timeouts/5xx — environmental outcomes, "
              "not payload properties)")
        return 1

    report: Dict[str, Any] = {
        "files": args.captures,
        "mode": "serve" if args.serve else "offline",
        "records": len(records),
        "replayable": len(replayable),
        "skipped": skipped,
        "limited_out": limited_out,
    }

    pipeline = None
    if not args.serve:
        from synapseml_tpu.runtime import compile_cache as cc

        hashes = {r.get("model_hash") for r in replayable}
        hashes.discard(None)
        if hashes and not args.model:
            print("error: records carry a model content hash "
                  f"({sorted(hashes)[0][:16]}...) — pass --model "
                  "<the model file the incident was served from>")
            return 1
        if args.model:
            from synapseml_tpu.io.serving import _model_pipeline

            rules = args.partition_rules
            if rules and rules != "megatron":
                rules = json.loads(rules)
            pipeline, model = _model_pipeline(
                args.model, devices=args.devices,
                cache_dir=args.cache_dir,
                tensor_parallel=args.tensor_parallel,
                partition_rules=rules)
            # hash the constructed model's PAYLOAD, exactly as serving
            # stamped it (content_hash over model.model_payload): a
            # raw-file hash would wrongly refuse any model whose
            # loader re-encodes the proto (external-data sidecars)
            local_hash = cc.content_hash(model.model_payload or b"")
            report["model_hash"] = local_hash
            if hashes and hashes != {local_hash}:
                print("error: model hash mismatch — capture was served "
                      f"from {sorted(hashes)[0][:16]}..., --model "
                      f"{args.model} hashes to {local_hash[:16]}... "
                      "(a diff against different weights is "
                      "meaningless; find the incident's model)")
                return 1
            # warm every bucket signature BEFORE scoring: with the
            # serving volume's shared store this deserializes instead
            # of compiling, and the sentinel (report["recompiles"])
            # proves nothing compiled on the scoring path either
            try:
                rep = model.warmup()
                report["warmup"] = {"signatures": len(rep.entries),
                                    "loaded": rep.loaded,
                                    "compiled": rep.compiled,
                                    "errors": len(rep.errors)}
            except Exception as e:  # noqa: BLE001 - degrade to lazy
                report["warmup"] = {"error": repr(e)[:200]}
        else:
            pipeline = _echo_pipeline()

    from synapseml_tpu.runtime import capture as cap

    diverged: List[Dict[str, Any]] = []
    transport_errors: List[Dict[str, Any]] = []
    matched = reproduced_errors = undecodable = 0
    for rec in replayable:
        payload = cap.payload_bytes(rec)
        if payload is None:
            # corrupt payload_b64: count it — a file where NOTHING
            # decodes must end inconclusive, not "ok: 0 bit-identical"
            undecodable += 1
            continue
        cap_status = rec.get("status_code")
        cap_digest = rec.get("output_digest") or ""
        if args.serve:
            rep_status, rep_digest, rep_body = _post_one(
                args.serve, rec, payload, args.timeout)
            rep_err = None
        else:
            rep_status, rep_digest, rep_body, rep_err = _score_one(
                pipeline, rec, payload)
        entry = {
            "rid": rec.get("rid"),
            "trace_id": rec.get("trace_id"),
            "reason": rec.get("reason"),
            "captured_status": cap_status,
            "replayed_status": rep_status,
            "captured_digest": cap_digest,
            "replayed_digest": rep_digest,
        }
        if rep_err:
            entry["replayed_error"] = rep_err
        if args.serve and (rep_status == "error"
                           or rep_status in (429, 503, 504)):
            # the POST never reached the scoring path: refused/reset/
            # timeout, or the endpoint shed it (admission 429, drain
            # 503, deadline 504). That is the ENVIRONMENT failing —
            # the same statuses the offline replayable filter calls
            # environmental — never evidence the rollout changed
            # scores; report unverifiable, not diverged
            transport_errors.append(entry)
            continue
        if cap_status == 400:
            # the poison contract: the payload itself must still be
            # the problem — a clean score means behavior changed. In
            # --serve mode a sequential replay presents the poison as
            # a SINGLETON batch, and serving's bisection only isolates
            # to 400 at n>1 (a failing singleton legally replies 500),
            # so either error status reproduces the poison live
            if rep_status == 400 or (args.serve and rep_status == 500):
                reproduced_errors += 1
                continue
            diverged.append(entry)
            continue
        if rep_status == 200 and rep_digest == cap_digest:
            matched += 1
            continue
        if args.keep_outputs:
            kept = cap.reply_bytes(rec)
            if kept is not None and rep_body is not None:
                entry["max_abs_diff"] = _max_abs_diff(kept, rep_body)
            if rep_body is not None:
                try:
                    entry["replayed_reply"] = rep_body.decode("utf-8")
                except UnicodeDecodeError:
                    pass
        diverged.append(entry)

    report.update({
        "matched": matched,
        "reproduced_errors": reproduced_errors,
        "undecodable": undecodable,
        "diverged": diverged,
    })
    if args.serve:
        report["transport_errors"] = transport_errors
    else:
        report["recompiles"] = _recompiles()

    if args.out:
        with open(args.out, "w", encoding="utf-8") as fh:
            json.dump(report, fh, indent=2, default=repr)
    for d in diverged:
        extra = (f" max_abs_diff={d['max_abs_diff']!r}"
                 if "max_abs_diff" in d else "")
        print(f"DIVERGED rid={d['rid']} trace={d['trace_id']} "
              f"status {d['captured_status']}->{d['replayed_status']} "
              f"digest {str(d['captured_digest'])[:16]}... -> "
              f"{str(d['replayed_digest'])[:16]}...{extra}")
    verdict = "DIVERGED" if diverged else (
        "INCONCLUSIVE" if transport_errors or matched == 0 else "ok")
    rec_note = (f" transport_errors={len(transport_errors)}"
                if args.serve
                else f" recompiles={report['recompiles']:.0f}")
    lim_note = (f", {limited_out} limited out (--limit)"
                if limited_out else "")
    print(f"replay {verdict}: {matched} bit-identical, "
          f"{reproduced_errors} errors reproduced, {len(diverged)} "
          f"diverged, {skipped} skipped, {undecodable} undecodable"
          f"{lim_note} (of {len(records)} records){rec_note}")
    if diverged:
        return 2
    if transport_errors:
        # nothing diverged, but some records never got verified: an
        # unreachable or shedding endpoint must not read as a clean
        # rollout
        return 1
    if matched == 0:
        # no captured-200 record scored clean: an all-error run
        # (poison-only file, broken --cache-dir, version skew) or an
        # all-undecodable file is indistinguishable from a broken
        # replay environment — crediting it would false-pass the
        # exact determinism gate this harness is. Healthy
        # head-samples exist so a replay always has a should-score
        # record to prove the environment with.
        print("inconclusive: zero records verified bit-identical — "
              "replay a capture that includes healthy head-sampled "
              "records, or fix the environment first.")
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
