"""Fleet controller: telemetry-driven autoscaling over serving replicas.

The closed observability loop (ROADMAP "fleet-scale serving"): every
gauge PRs 6-10 built — ``executor_duty_cycle``, the SLO burn rates,
the recompile sentinel, ``cache_skew`` — becomes a **control signal**
here. The controller polls each replica's ``/metrics`` +
``/health/ready``, reduces the scrape to a
:class:`~synapseml_tpu.runtime.autoscale.ReplicaSample`, and acts on
the pure policy in :mod:`synapseml_tpu.runtime.autoscale` (hysteresis,
cooldowns, min/max clamps, and the never-scale-on-blindness rails).

Two backends:

- **local** (:class:`LocalProcessBackend`): spawns REAL
  ``python -m synapseml_tpu.io.serving`` subprocesses on this host and
  scales them down with SIGTERM — riding the PR-8 graceful drain, so a
  scale-down drops zero admitted requests (the exit-accounting line is
  parsed and re-asserted per termination). This is what the fleet
  chaos CI phase drives (tools/ci/chaos_check.py --fleet).
- **k8s**: the same policy runs as an HPA on the custom metrics the
  chart already scrapes — ``--emit-hpa`` renders the committed
  ``tools/k8s/chart/templates/hpa.yaml`` manifest from values.yaml
  (the shipping path; this process is not needed in-cluster).

Warm replica hydration: every spawn carries ``--cache-dir`` on the
shared ``ExecutableStore`` volume plus ``--warmup``, so a scale-up
deserializes executables a sibling already compiled. The first ready
scrape of each new replica is audited
(:func:`~synapseml_tpu.runtime.autoscale.hydration_audit`): zero
post-warmup recompiles + zero store skew = ``warm``; counted in
``fleet_hydrations_total{outcome=}`` and recorded as a
``fleet_hydration`` flight event.

Distributed-trace stitching (round 16): ``GET /fleet/trace/<trace_id>``
answers the incident question PR 8's failover made unanswerable —
"where did THIS request spend its time, across which replicas" — by
fanning out to every live replica's ``/trace`` surface and merging the
legs with the shared trace archive's records
(:mod:`synapseml_tpu.runtime.tracearchive`; ``--dump-dir`` is the
shared directory), behind a bounded cache of recently stitched
traces. Archive merge is what keeps a SIGKILLed replica's legs
retrievable after the process is gone.

Fleet observability: the controller serves ``GET /fleet/status``
(JSON: per-replica state + samples, aggregates, the last decisions)
and ``GET /fleet/metrics`` (its own Prometheus registry —
``fleet_replicas{state=}``, ``fleet_scale_events_total{direction=,
reason=}``, per-replica ``fleet_replica_*`` series, and the
``process_*`` self-telemetry; ``/metrics`` is an alias). Every scale
action and replica death lands in the flight recorder AND the
structured log (``blackbox.record`` emits both), so
``grep '"event":"fleet_scale"'`` reconstructs a scaling incident end
to end (docs/deployment.md, "Fleet operations").

Usage (CI-shaped example; production knobs in docs/deployment.md)::

    python -m tools.fleet.controller \
        --model model.onnx --cache-dir /cache --warmup auto \
        --min 2 --max 4 --interval 2 \
        --duty-high 0.75 --duty-low 0.2 --burn-high 2
    python -m tools.fleet.controller --emit-hpa -   # k8s manifest
"""
from __future__ import annotations

import argparse
import http.server
import json
import os
import re
import signal
import subprocess
import sys
import threading
import time
import urllib.error
import urllib.request
from typing import Any, Callable, Dict, List, Optional

_ROOT = os.path.abspath(os.path.join(
    os.path.dirname(os.path.abspath(__file__)), os.pardir, os.pardir))
if _ROOT not in sys.path:
    sys.path.insert(0, _ROOT)

from synapseml_tpu.runtime import autoscale as _as  # noqa: E402
from synapseml_tpu.runtime.locksan import make_lock  # noqa: E402
from synapseml_tpu.runtime import blackbox as _bb  # noqa: E402
from synapseml_tpu.runtime import perfwatch as _pw  # noqa: E402
from synapseml_tpu.runtime import telemetry as _tm  # noqa: E402

_ANNOUNCE_RE = re.compile(r"serving \[.*\] on (http://\S+/)")
_ACCOUNTING_RE = re.compile(
    r"exit accounting: admitted=(\d+) replied=(\d+)")


def _http_get(url: str, timeout: float = 2.0) -> Optional[bytes]:
    try:
        with urllib.request.urlopen(
                urllib.request.Request(url), timeout=timeout) as r:
            return r.read()
    except Exception:  # noqa: BLE001 - poll failure IS the signal
        return None


def _http_status(url: str, timeout: float = 2.0) -> Optional[int]:
    try:
        with urllib.request.urlopen(
                urllib.request.Request(url), timeout=timeout) as r:
            return r.status
    except urllib.error.HTTPError as e:
        return e.code
    except Exception:  # noqa: BLE001
        return None


class LocalReplica:
    """One serving subprocess the local backend owns. Its stdout is
    drained continuously on a reader thread (a full pipe would wedge
    the child) into a bounded tail; the URL announce line and the exit
    accounting line are captured as they pass."""

    _MAX_LINES = 400

    def __init__(self, name: str, proc: subprocess.Popen):
        self.name = name
        self.proc = proc
        self.url: Optional[str] = None
        self.spawned_ts = time.monotonic()
        self.lines: List[str] = []
        self.accounting: Optional[Dict[str, int]] = None
        self._url_found = threading.Event()
        self._lock = make_lock("LocalReplica._lock")
        self._reader = threading.Thread(
            target=self._read_stdout, name=f"fleet-stdout-{name}",
            daemon=True)
        self._reader.start()

    def _read_stdout(self):
        for line in self.proc.stdout:
            with self._lock:
                self.lines.append(line)
                del self.lines[:-self._MAX_LINES]
            if not self._url_found.is_set():
                m = _ANNOUNCE_RE.search(line)
                if m:
                    self.url = m.group(1)
                    self._url_found.set()
            m = _ACCOUNTING_RE.search(line)
            if m:
                self.accounting = {"admitted": int(m.group(1)),
                                   "replied": int(m.group(2))}

    def wait_url(self, timeout: float) -> Optional[str]:
        self._url_found.wait(timeout)
        return self.url

    def tail(self, n: int = 40) -> List[str]:
        with self._lock:
            return self.lines[-n:]

    def alive(self) -> bool:
        return self.proc.poll() is None


class LocalProcessBackend:
    """Spawns/terminates real serving subprocesses on this host — the
    CI/laptop stand-in for a k8s Deployment, faithful where it counts:
    replicas are OS processes, scale-down is SIGTERM + graceful drain,
    and the zero-drop contract is read back from each child's exit
    accounting line."""

    def __init__(self, model: Optional[str] = None,
                 cache_dir: Optional[str] = None,
                 warmup: Optional[str] = None,
                 extra_args: Optional[List[str]] = None,
                 env: Optional[Dict[str, str]] = None,
                 announce_timeout_s: float = 120.0,
                 dump_dir: Optional[str] = None,
                 stderr_dir: Optional[str] = None):
        """``dump_dir``: forwarded to every replica as ``--dump-dir``,
        so flight dumps AND trace-archive files from the whole fleet
        land in ONE directory — which is what lets the controller's
        ``/fleet/trace`` stitch a SIGKILLed replica's archived legs
        after the process is gone. ``stderr_dir``: capture each
        replica's stderr (the structured log when ``SYNAPSEML_LOG`` is
        set in ``env``) to ``<stderr_dir>/<name>.stderr.log`` instead
        of devnull — a dead replica's log is forensics, not noise."""
        self.model = model
        self.cache_dir = cache_dir
        self.warmup = warmup
        self.extra_args = list(extra_args or [])
        self.env = env
        self.announce_timeout_s = announce_timeout_s
        self.dump_dir = dump_dir
        self.stderr_dir = stderr_dir
        self._seq = 0

    def _child_env(self) -> Dict[str, str]:
        env = dict(os.environ if self.env is None else self.env)
        # the replica must import the repo the controller runs from
        env["PYTHONPATH"] = _ROOT + (
            os.pathsep + env["PYTHONPATH"] if env.get("PYTHONPATH")
            else "")
        # fault specs are the chaos harness's business, never inherited
        # into fleet replicas by accident
        env.pop("SYNAPSEML_FAULTS", None)
        return env

    def spawn(self, name: Optional[str] = None) -> LocalReplica:
        """Start one replica (``--port 0``: the OS assigns, the
        announce line tells us) and block until it announces its URL —
        NOT until ready; warmup runs behind the readiness gate and the
        controller tracks the warming state."""
        self._seq += 1
        name = name or f"replica{self._seq}"
        argv = [sys.executable, "-m", "synapseml_tpu.io.serving",
                "--host", "127.0.0.1", "--port", "0", "--name", name]
        if self.model:
            argv += ["--model", self.model]
        if self.cache_dir:
            argv += ["--cache-dir", self.cache_dir]
        if self.warmup:
            argv += ["--warmup", self.warmup]
        if self.dump_dir:
            argv += ["--dump-dir", self.dump_dir]
        argv += self.extra_args
        stderr_file = subprocess.DEVNULL
        stderr_path = None
        if self.stderr_dir:
            os.makedirs(self.stderr_dir, exist_ok=True)
            stderr_path = os.path.join(self.stderr_dir,
                                       f"{name}.stderr.log")
            stderr_file = open(stderr_path, "a", encoding="utf-8")
        try:
            proc = subprocess.Popen(
                argv, stdout=subprocess.PIPE, stderr=stderr_file,
                text=True, env=self._child_env(), cwd=_ROOT)
        finally:
            if stderr_path is not None:
                stderr_file.close()  # the child holds its own fd
        replica = LocalReplica(name, proc)
        replica.stderr_path = stderr_path
        if replica.wait_url(self.announce_timeout_s) is None:
            proc.kill()
            proc.wait(timeout=10)
            raise RuntimeError(
                f"replica {name} never announced its URL "
                f"(tail: {replica.tail(10)})")
        return replica

    def terminate(self, replica: LocalReplica,
                  timeout_s: float = 30.0) -> Dict[str, Any]:
        """Graceful scale-down: SIGTERM rides the serving entry's drain
        path (new requests 503 + Retry-After, accepted ones finish to
        real replies). Returns the drain verdict, including the
        child's own exit-accounting proof that zero admitted requests
        were dropped."""
        if replica.alive():
            replica.proc.send_signal(signal.SIGTERM)
        try:
            code = replica.proc.wait(timeout=timeout_s)
        except subprocess.TimeoutExpired:
            replica.proc.kill()
            code = replica.proc.wait(timeout=10)
        replica._reader.join(timeout=5)
        acct = replica.accounting or {}
        admitted = acct.get("admitted")
        replied = acct.get("replied")
        return {
            "replica": replica.name,
            "exit_code": code,
            "admitted": admitted,
            "replied": replied,
            "zero_dropped": (admitted is not None
                             and admitted == replied),
        }


class FleetController:
    """The control loop: scrape -> aggregate -> decide -> act, once per
    ``interval_s``, against whatever backend owns the replicas. Also
    the fleet's observability surface (``serve()`` binds
    /fleet/status + /fleet/metrics).

    ``scrape_fn(replica) -> (metrics_text | None, ready)`` is
    injectable so the decision loop is testable without HTTP; the
    default polls the replica's real endpoints."""

    _TRACE_CACHE_MAX = 64
    _TRACE_CACHE_TTL_S = 2.0

    def __init__(self, backend: LocalProcessBackend,
                 policy: "_as.FleetPolicy",
                 interval_s: float = 2.0,
                 initial_replicas: Optional[int] = None,
                 scrape_timeout_s: float = 2.0,
                 scrape_fn: Optional[Callable[[Any], Any]] = None,
                 archive_dir: Optional[str] = None):
        """``archive_dir``: where the fleet's trace-archive JSONL files
        live (the backend's shared ``dump_dir``) — ``/fleet/trace``
        merges archived legs from here with live ``/trace`` fan-out,
        which is what makes a SIGKILLed replica's legs stitchable."""
        self.backend = backend
        self.policy = policy
        self.archive_dir = archive_dir
        # bounded cache of recently stitched traces: repeat reads of a
        # hot incident trace (dashboard link-outs, a runbook loop)
        # skip the fleet fan-out inside the TTL; insertion-ordered
        # dict, oldest evicted past the cap
        self._trace_cache: Dict[str, Any] = {}
        self.interval_s = float(interval_s)
        self.initial_replicas = min(policy.max_replicas, max(
            policy.min_replicas,
            policy.min_replicas if initial_replicas is None
            else int(initial_replicas)))
        self.scrape_timeout_s = scrape_timeout_s
        self.scrape_fn = scrape_fn or self._scrape_http
        self.replicas: List[Any] = []
        self.state = _as.FleetState()
        self._samples: Dict[str, "_as.ReplicaSample"] = {}
        self._prev_replies: Dict[str, Dict[str, float]] = {}
        self._ever_ready: set = set()
        self._hydrations: List[Dict[str, Any]] = []
        self._terminations: List[Dict[str, Any]] = []
        self._decisions: List[Dict[str, Any]] = []
        self._aggregates: Dict[str, Any] = {}
        self._lock = make_lock("FleetController._lock")
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._httpd: Optional[http.server.ThreadingHTTPServer] = None
        self.port: Optional[int] = None
        # controller self-telemetry: process gauges + fleet gauges on
        # the controller's OWN registry (it never imports jax)
        _pw.ensure_process_registered()
        _as.register_fleet_gauges(self.replica_state_counts,
                                  lambda: self.aggregates())

    # -- observability --------------------------------------------------

    def replica_state_counts(self) -> Dict[str, int]:
        counts = {"ready": 0, "warming": 0, "unreachable": 0}
        with self._lock:
            for r in list(self.replicas):
                s = self._samples.get(r.name)
                if s is None or not s.reachable:
                    counts["unreachable"] += 1
                elif s.ready:
                    counts["ready"] += 1
                else:
                    counts["warming"] += 1
        return counts

    def aggregates(self) -> Dict[str, Any]:
        with self._lock:
            return dict(self._aggregates)

    def status(self) -> Dict[str, Any]:
        """The /fleet/status payload: one JSON document an operator (or
        the chaos gate) reads the whole fleet from."""
        with self._lock:
            samples = dict(self._samples)
            replicas = [{
                "name": r.name,
                "url": getattr(r, "url", None),
                "alive": r.alive() if hasattr(r, "alive") else None,
                "state": ("unreachable"
                          if (samples.get(r.name) is None
                              or not samples[r.name].reachable)
                          else ("ready" if samples[r.name].ready
                                else "warming")),
                "duty": getattr(samples.get(r.name), "duty", 0.0),
                "burn": (samples[r.name].burn_max()
                         if samples.get(r.name) else 0.0),
                "recompiles": (samples[r.name].recompiles_total
                               if samples.get(r.name) else None),
            } for r in self.replicas]
            return {
                "replicas": replicas,
                "aggregates": dict(self._aggregates),
                "policy": {k: getattr(self.policy, k)
                           for k in self.policy.__slots__},
                "hydrations": list(self._hydrations[-8:]),
                "terminations": list(self._terminations[-8:]),
                "decisions": list(self._decisions[-8:]),
            }

    def stitch_trace(self, trace_id: str) -> Dict[str, Any]:
        """One distributed trace, fleet-wide: fan out to every live
        replica's ``GET /trace/<trace_id>`` and merge the legs with
        any records the shared trace archive holds (``archive_dir``) —
        live legs win on a shared span_id, archived legs are how a
        SIGKILLed replica still testifies. Legs come back
        wall-clock-ordered, each naming its replica; recently stitched
        traces are served from a bounded TTL cache."""
        now = time.monotonic()
        with self._lock:
            hit = self._trace_cache.get(trace_id)
            if hit is not None and now - hit[0] < self._TRACE_CACHE_TTL_S:
                return hit[1]
            replicas = list(self.replicas)
        legs: Dict[str, Dict[str, Any]] = {}
        queried = 0
        for r in replicas:
            url = getattr(r, "url", None)
            if not url:
                continue
            queried += 1
            raw = _http_get(url.rstrip("/") + f"/trace/{trace_id}",
                            self.scrape_timeout_s)
            if raw is None:
                continue  # dead/warming replica: the archive may testify
            try:
                payload = json.loads(raw)
            except ValueError:
                continue
            for leg in payload.get("legs", ()):
                leg = dict(leg)
                leg["source"] = "live"
                leg["replica"] = leg.get("origin") or r.name
                legs.setdefault(leg.get("span_id")
                                or f"live{len(legs)}", leg)
        archived = 0
        if self.archive_dir:
            from synapseml_tpu.runtime import tracearchive as _tarch

            for rec in _tarch.scan(trace_id, directory=self.archive_dir):
                key = rec.get("span_id") or f"arch{archived}"
                if key in legs:
                    continue  # the live span store is fresher
                leg = dict(rec)
                leg["source"] = "archive"
                leg["replica"] = leg.get("origin") or ""
                legs[key] = leg
                archived += 1
        merged = sorted(legs.values(),
                        key=lambda leg: leg.get("ts") or 0.0)
        payload = {"trace_id": trace_id, "legs": merged,
                   "replicas": sorted({leg["replica"] for leg in merged
                                       if leg.get("replica")}),
                   "replicas_queried": queried,
                   "archived_legs": archived,
                   "stitched_ts": round(time.time(), 6)}
        _as.trace_stitch_counter(
            "found" if merged else "not_found").inc()
        with self._lock:
            self._trace_cache[trace_id] = (now, payload)
            while len(self._trace_cache) > self._TRACE_CACHE_MAX:
                self._trace_cache.pop(next(iter(self._trace_cache)))
        return payload

    def serve(self, host: str = "127.0.0.1", port: int = 0) -> str:
        """Bind the controller's observability endpoints; returns the
        base URL."""
        controller = self

        class Handler(http.server.BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"

            def log_message(self, *a):  # quiet
                pass

            def _send(self, status: int, body: bytes,
                      ctype: str = "application/json"):
                self.send_response(status)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def do_GET(self):
                if self.path == "/fleet/status":
                    self._send(200, json.dumps(
                        controller.status(), default=repr).encode())
                elif self.path.startswith("/fleet/trace/"):
                    # the cross-replica trace view: merged live +
                    # archived legs for one trace id (404 = no replica
                    # and no archive file holds a leg)
                    tid = (self.path[len("/fleet/trace/"):]
                           .strip("/").lower())
                    if not re.fullmatch(r"[0-9a-f]{32}", tid):
                        self._send(400, b'{"error": "trace id must be '
                                        b'32 lowercase hex chars"}')
                        return
                    payload = controller.stitch_trace(tid)
                    self._send(200 if payload["legs"] else 404,
                               json.dumps(payload,
                                          default=repr).encode())
                elif self.path in ("/fleet/metrics", "/metrics"):
                    self._send(
                        200, _tm.prometheus_text().encode(),
                        "text/plain; version=0.0.4; charset=utf-8")
                elif self.path in ("/health", "/health/live",
                                   "/health/ready"):
                    self._send(200, b"ok", "text/plain")
                else:
                    self._send(404, b"not found", "text/plain")

        self._httpd = http.server.ThreadingHTTPServer((host, port),
                                                      Handler)
        self._httpd.daemon_threads = True
        self.port = self._httpd.server_address[1]
        threading.Thread(target=self._httpd.serve_forever,
                         name="fleet-http", daemon=True).start()
        return f"http://{host}:{self.port}"

    # -- scrape ---------------------------------------------------------

    def _scrape_http(self, replica) -> Any:
        url = getattr(replica, "url", None)
        if not url:
            return None, False
        text = _http_get(url.rstrip("/") + "/metrics",
                         self.scrape_timeout_s)
        if text is None:
            return None, False
        ready = _http_status(url.rstrip("/") + "/health/ready",
                             self.scrape_timeout_s) == 200
        return text.decode("utf-8", "replace"), ready

    def _sample(self, replica, now: float) -> "_as.ReplicaSample":
        text, ready = self.scrape_fn(replica)
        sample = _as.sample_from_scrape(replica.name,
                                        getattr(replica, "url", "")
                                        or "", now, text, ready)
        if not sample.reachable:
            _as.scrape_failure_counter().inc()
            return sample
        # burn over the controller's OWN window: reply-count deltas
        # between this scrape and the previous one (recovery decays
        # the signal; cumulative gauges never would). _prev_replies is
        # shared with the drain/reap paths (their threads pop
        # terminated names), so access stays under the lock.
        with self._lock:
            prev = self._prev_replies.get(replica.name)
            self._prev_replies[replica.name] = dict(
                sample.replies_by_code)
        if prev is not None:
            avail = _as.window_availability(prev, sample.replies_by_code)
            if avail is not None:
                sample.avail_burn = _slo_burn(avail)
        return sample

    def _audit_if_newly_ready(self, sample: "_as.ReplicaSample"):
        if not sample.ready:
            return
        with self._lock:
            if sample.name in self._ever_ready:
                return
            self._ever_ready.add(sample.name)
        audit = _as.hydration_audit(sample)
        _as.hydration_counter(audit["outcome"]).inc()
        with self._lock:
            self._hydrations.append(audit)
            del self._hydrations[:-64]  # bounded like _decisions
        _bb.record("fleet_hydration",
                   level="info" if audit["clean"] else "warn", **audit)

    # -- the loop -------------------------------------------------------

    def start(self, wait_ready_s: float = 300.0) -> "FleetController":
        """Sequential initial bring-up to ``initial_replicas`` (the
        FIRST replica seeds the shared ExecutableStore; waiting for
        its readiness before spawning siblings is what makes every
        later boot a warm one), then the control loop."""
        for _ in range(self.initial_replicas):
            self._spawn("initial")
            self.wait_all_ready(wait_ready_s)
        self.state.mark_scaled(time.monotonic(), "up")
        self._thread = threading.Thread(target=self._loop,
                                        name="fleet-controller",
                                        daemon=True)
        self._thread.start()
        return self

    def wait_all_ready(self, timeout_s: float) -> bool:
        deadline = time.monotonic() + timeout_s
        while time.monotonic() < deadline:
            now = time.monotonic()
            samples = [self._sample(r, now) for r in self.replicas]
            with self._lock:
                for s in samples:
                    self._samples[s.name] = s
            for s in samples:
                self._audit_if_newly_ready(s)
            if samples and all(s.ready for s in samples):
                return True
            time.sleep(0.2)
        return False

    def _spawn(self, reason: str):
        replica = self.backend.spawn()
        with self._lock:
            self.replicas.append(replica)
            n = len(self.replicas)
        _as.register_replica_gauges(
            replica.name,
            lambda name=replica.name: self._samples.get(
                name, _as.ReplicaSample(name)))
        _as.scale_event_counter("up", reason).inc()
        _bb.record("fleet_scale", direction="up", reason=reason,
                   replica=replica.name, replicas=n,
                   url=getattr(replica, "url", None))

    def _terminate(self, replica, reason: str):
        with self._lock:
            if replica in self.replicas:
                self.replicas.remove(replica)
            n = len(self.replicas)
            self._samples.pop(replica.name, None)
            self._prev_replies.pop(replica.name, None)
        _as.unregister_replica_gauges(replica.name)
        _as.scale_event_counter("down", reason).inc()
        _bb.record("fleet_scale", direction="down", reason=reason,
                   replica=replica.name, replicas=n)
        verdict = self.backend.terminate(replica)
        verdict["reason"] = reason
        with self._lock:
            self._terminations.append(verdict)
            del self._terminations[:-64]  # bounded like _decisions
            # the name never returns (spawn sequence numbers), so its
            # audit latch can go too — a crash-looping fleet must not
            # accumulate a set of dead names
            self._ever_ready.discard(replica.name)
        _bb.record("fleet_drain",
                   level="info" if verdict.get("zero_dropped")
                   else "warn", **verdict)

    def _reap(self):
        """Remove replicas whose PROCESS died under us (crash, OOM,
        chaos kill) — dead capacity must leave the decision's replica
        count, or a stale ghost would block scale-down forever and
        hide the shortfall scale-up needs to see."""
        for replica in list(self.replicas):
            if hasattr(replica, "alive") and not replica.alive():
                with self._lock:
                    self.replicas.remove(replica)
                    self._samples.pop(replica.name, None)
                    self._prev_replies.pop(replica.name, None)
                    self._ever_ready.discard(replica.name)
                _as.unregister_replica_gauges(replica.name)
                _bb.record("fleet_replica_died", level="error",
                           replica=replica.name,
                           exit_code=replica.proc.returncode
                           if hasattr(replica, "proc") else None)

    def tick(self) -> "_as.Decision":
        """One evaluation: reap -> enforce the min floor -> scrape ->
        decide -> act. Public so tests (and the chaos gate) can drive
        the loop deterministically."""
        self._reap()
        if len(self.replicas) < self.policy.min_replicas:
            # the min floor is not a *decision*, it is an invariant: a
            # died replica is replaced before any policy math runs
            self._spawn("min_floor")
        now = time.monotonic()
        samples = [self._sample(r, now) for r in self.replicas]
        with self._lock:
            self._samples = {s.name: s for s in samples}
        for s in samples:
            self._audit_if_newly_ready(s)
        decision = _as.decide(now, samples, self.state, self.policy)
        with self._lock:
            self._aggregates = decision.aggregates
            self._decisions.append(decision.as_dict())
            del self._decisions[:-64]
        if decision.direction == "up":
            # spawn FIRST: a failed spawn (announce timeout, bind
            # failure) raises into the loop's error handler with the
            # cooldown un-stamped, so the starved fleet retries on the
            # next breach instead of serving out a cooldown it never
            # bought capacity with (the decide() docstring contract)
            self._spawn(decision.reason)
            self.state.mark_scaled(time.monotonic(), "up")
        elif decision.direction == "down":
            victim = self._downscale_victim()
            if victim is not None:
                self.state.mark_scaled(now, "down")
                # drain in the background: a graceful drain takes
                # seconds and must not blind the control loop
                threading.Thread(
                    target=self._terminate,
                    args=(victim, decision.reason),
                    name=f"fleet-drain-{victim.name}",
                    daemon=True).start()
        return decision

    def _downscale_victim(self):
        """Newest ready replica first (LIFO): the oldest replicas carry
        the warmest caches and the longest uptime evidence."""
        with self._lock:
            candidates = [r for r in self.replicas
                          if self._samples.get(r.name) is not None
                          and self._samples[r.name].ready]
            return candidates[-1] if candidates else None

    def _loop(self):
        while not self._stop.wait(self.interval_s):
            try:
                self.tick()
            except Exception as e:  # noqa: BLE001 - the loop must survive
                _bb.record("fleet_tick_error", level="error",
                           error=repr(e)[:200])

    def stop(self, drain_replicas: bool = True):
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=max(5.0, 2 * self.interval_s))
        if self._httpd is not None:
            self._httpd.shutdown()
            self._httpd.server_close()
        if drain_replicas:
            for replica in list(self.replicas):
                self._terminate(replica, "shutdown")


def _slo_burn(availability: float) -> float:
    from synapseml_tpu.runtime import slo

    target = float(os.environ.get("SYNAPSEML_SLO_AVAILABILITY",
                                  str(slo.DEFAULT_AVAILABILITY_TARGET)))
    return slo.burn_rate(availability, target)


def emit_hpa(values_path: Optional[str] = None) -> str:
    """Render the chart's HPA-on-custom-metrics manifest (the k8s mode
    of this controller: the policy runs IN the cluster, scaling on the
    same duty-cycle/burn-rate series the chart's scrape annotations
    already export)."""
    from tools.k8s import render as _render

    k8s_dir = os.path.join(_ROOT, "tools", "k8s")
    with open(values_path
              or os.path.join(k8s_dir, "chart", "values.yaml")) as fh:
        values = _render.parse_simple_yaml(fh.read())
    with open(os.path.join(k8s_dir, "chart", "templates",
                           "hpa.yaml")) as fh:
        return _render.render(fh.read(), values)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--min", type=int, default=1)
    ap.add_argument("--max", type=int, default=4)
    ap.add_argument("--initial", type=int, default=None,
                    help="initial replica count (default: --min)")
    ap.add_argument("--interval", type=float, default=2.0,
                    help="seconds between control evaluations")
    ap.add_argument("--duty-high", type=float, default=0.75)
    ap.add_argument("--duty-low", type=float, default=0.20)
    ap.add_argument("--burn-high", type=float, default=2.0)
    ap.add_argument("--up-consecutive", type=int, default=2)
    ap.add_argument("--down-consecutive", type=int, default=4)
    ap.add_argument("--up-cooldown", type=float, default=15.0)
    ap.add_argument("--down-cooldown", type=float, default=60.0)
    ap.add_argument("--stale-after", type=float, default=10.0)
    ap.add_argument("--model", default=os.environ.get(
        "SYNAPSEML_MODEL_PATH") or None)
    ap.add_argument("--cache-dir", default=os.environ.get(
        "SYNAPSEML_COMPILE_CACHE") or None,
        help="shared ExecutableStore dir — what makes scale-up warm")
    ap.add_argument("--warmup", default=os.environ.get(
        "SYNAPSEML_WARMUP") or None)
    ap.add_argument("--replica-arg", action="append", default=[],
                    help="extra argv token passed to every replica "
                         "(repeatable)")
    ap.add_argument("--dump-dir", default=os.environ.get(
        "SYNAPSEML_DUMP_DIR") or None,
        help="shared forensics dir forwarded to every replica "
             "(--dump-dir): flight dumps + trace-archive JSONL land "
             "here, and /fleet/trace stitches archived legs from it — "
             "a SIGKILLed replica's legs stay retrievable")
    ap.add_argument("--stderr-dir", default=None,
                    help="capture each replica's stderr (its "
                         "structured log) to <dir>/<name>.stderr.log "
                         "instead of devnull")
    ap.add_argument("--port", type=int, default=8899,
                    help="controller HTTP port (/fleet/status, "
                         "/fleet/metrics); 0 = OS-assigned")
    ap.add_argument("--host", default="127.0.0.1")
    ap.add_argument("--emit-hpa", metavar="PATH", default=None,
                    help="render the k8s HPA manifest from the chart "
                         "values and write it to PATH ('-' = stdout), "
                         "then exit — the in-cluster deployment path")
    ap.add_argument("--values", default=None,
                    help="values.yaml override for --emit-hpa")
    args = ap.parse_args(argv)

    if args.emit_hpa is not None:
        text = emit_hpa(args.values)
        if args.emit_hpa == "-":
            sys.stdout.write(text)
        else:
            with open(args.emit_hpa, "w", encoding="utf-8") as fh:
                fh.write(text)
            print(f"wrote {args.emit_hpa}")
        return 0

    try:
        policy = _as.FleetPolicy(
            min_replicas=args.min, max_replicas=args.max,
            duty_high=args.duty_high, duty_low=args.duty_low,
            burn_high=args.burn_high,
            up_consecutive=args.up_consecutive,
            down_consecutive=args.down_consecutive,
            up_cooldown_s=args.up_cooldown,
            down_cooldown_s=args.down_cooldown,
            stale_after_s=args.stale_after)
    except ValueError as e:
        print(f"error: {e}", flush=True)
        return 2
    backend = LocalProcessBackend(
        model=args.model, cache_dir=args.cache_dir, warmup=args.warmup,
        extra_args=args.replica_arg, dump_dir=args.dump_dir,
        stderr_dir=args.stderr_dir)
    controller = FleetController(backend, policy,
                                 interval_s=args.interval,
                                 initial_replicas=args.initial,
                                 archive_dir=args.dump_dir)
    url = controller.serve(host=args.host, port=args.port)
    print(f"fleet controller on {url} (GET /fleet/status, "
          f"/fleet/metrics)", flush=True)
    controller.start()
    print(f"fleet up: {len(controller.replicas)} replicas "
          f"{[r.name for r in controller.replicas]}", flush=True)
    stop = threading.Event()
    for sig in (signal.SIGTERM, signal.SIGINT):
        signal.signal(sig, lambda *_: stop.set())
    stop.wait()
    print("fleet controller: draining fleet ...", flush=True)
    controller.stop(drain_replicas=True)
    print("fleet controller: stopped", flush=True)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
