"""Generate the serving Grafana dashboard FROM the metric catalog.

The catalog tables in docs/observability.md are the contract for every
observability consumer — the doc-drift gate
(tools/ci/metrics_doc_check.py) pins them to the code, and this
generator turns the same rows into a Grafana dashboard JSON, so a new
metric needs exactly one catalog row to reach both the gate and the
dashboards. Deterministic output (stable panel ids, doc ordering):
regeneration of an unchanged catalog is byte-identical, which is what
lets CI run ``--check`` against the committed file.

Panel mapping:

- ``serving_slo_*`` gauges -> a stat row at the top (the at-a-glance
  SLO view: availability, burn rates, latency good fraction);
- counters -> ``sum(rate(...[5m]))`` timeseries, grouped by the label
  the catalog row names (``{channel=}`` etc.);
- gauges   -> ``sum(...)`` timeseries (same grouping);
- histograms -> p50/p95/p99 ``histogram_quantile`` timeseries.

Usage::

    python tools/k8s/gen_dashboard.py            # rewrite the JSON
    python tools/k8s/gen_dashboard.py --check    # CI: fail on drift
"""
import argparse
import json
import os
import re
import sys

HERE = os.path.dirname(os.path.abspath(__file__))
ROOT = os.path.abspath(os.path.join(HERE, os.pardir, os.pardir))
DOC = os.path.join(ROOT, "docs", "observability.md")
OUT = os.path.join(HERE, "chart", "dashboards",
                   "serving-dashboard.json")

PREFIXES = ("serving_", "executor_", "faults_", "blackbox_", "device_",
            "fleet_", "process_", "trace_", "capture_", "gbdt_",
            "onnx_", "autotune_", "tp_", "kv_", "decode_", "locksan_")
_NAME = re.compile(r"([a-z][a-z0-9_]*)(\{([a-z_=,]*)\})?")


def catalog_rows(doc_path=DOC):
    """[(name, labels, kind, meaning)] in doc order, from every
    markdown table inside the '## Metric catalog' section."""
    with open(doc_path, encoding="utf-8") as fh:
        text = fh.read()
    m = re.search(r"^## Metric catalog$(.*?)(?=^## )", text,
                  re.M | re.S)
    if not m:
        raise SystemExit("docs/observability.md: no metric catalog")
    rows = []
    seen = set()
    for line in m.group(1).splitlines():
        if not line.startswith("|") or line.startswith("|-"):
            continue
        cells = [c.strip() for c in line.strip("|").split("|")]
        if len(cells) < 3 or cells[1].startswith("---"):
            continue
        kind = cells[1].strip()
        if kind not in ("counter", "gauge", "histogram"):
            continue
        meaning = cells[2].split(".")[0].strip()
        for token in re.findall(r"`([^`]+)`", cells[0]):
            nm = _NAME.match(token.strip())
            if not nm or not nm.group(1).startswith(PREFIXES):
                continue
            name = nm.group(1)
            if name in seen:
                continue
            seen.add(name)
            labels = [p.split("=")[0].strip() for p in
                      (nm.group(3) or "").split(",") if p.strip()]
            rows.append((name, labels, kind, meaning))
    return rows


def _grid(i, per_row=3, w=8, h=7, y0=0):
    return {"x": (i % per_row) * w, "y": y0 + (i // per_row) * h,
            "w": w, "h": h}


def _panel(pid, title, kind, targets, grid, description=""):
    return {"id": pid, "title": title, "type": kind,
            "datasource": {"type": "prometheus",
                           "uid": "${datasource}"},
            "description": description, "gridPos": grid,
            "targets": targets}


def build(rows):
    panels = []
    pid = 1
    slo = [(n, ls, k, mn) for n, ls, k, mn in rows
           if n.startswith("serving_slo_")]
    rest = [(n, ls, k, mn) for n, ls, k, mn in rows
            if not n.startswith("serving_slo_")]
    for i, (name, _labels, _kind, meaning) in enumerate(slo):
        panels.append(_panel(
            pid, name.replace("serving_slo_", "SLO "), "stat",
            [{"expr": f"avg(synapseml_{name})", "refId": "A"}],
            {"x": (i % 5) * 5, "y": (i // 5) * 4, "w": 5, "h": 4},
            meaning))
        pid += 1
    y0 = 4 * ((len(slo) + 4) // 5 or 1)
    for i, (name, labels, kind, meaning) in enumerate(rest):
        by = f" by ({', '.join(labels)})" if labels else ""
        if kind == "counter":
            targets = [{"expr": f"sum(rate(synapseml_{name}[5m]))"
                                f"{by}", "refId": "A"}]
        elif kind == "gauge":
            targets = [{"expr": f"sum(synapseml_{name}){by}",
                        "refId": "A"}]
        else:  # histogram
            targets = [
                {"expr": f"histogram_quantile({q}, sum(rate("
                         f"synapseml_{name}_bucket[5m])) by (le))",
                 "legendFormat": f"p{int(q * 100)}",
                 "refId": chr(ord("A") + j)}
                for j, q in enumerate((0.5, 0.95, 0.99))]
        panels.append(_panel(pid, name, "timeseries", targets,
                             _grid(i, y0=y0), meaning))
        pid += 1
    return {
        "title": "SynapseML TPU serving",
        "uid": "synapseml-serving",
        "tags": ["synapseml", "serving", "generated"],
        "schemaVersion": 39,
        "editable": True,
        "time": {"from": "now-1h", "to": "now"},
        "templating": {"list": [{"name": "datasource",
                                 "type": "datasource",
                                 "query": "prometheus"}]},
        "__generator": "tools/k8s/gen_dashboard.py — regenerate, "
                       "do not hand-edit (CI checks sync with the "
                       "docs/observability.md metric catalog)",
        "panels": panels,
    }


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--out", default=OUT)
    ap.add_argument("--check", action="store_true",
                    help="fail (exit 1) when the committed dashboard "
                         "differs from a fresh generation")
    args = ap.parse_args(argv)
    rows = catalog_rows()
    if not rows:
        print("no catalog rows parsed — is the doc table intact?")
        return 2
    text = json.dumps(build(rows), indent=2, sort_keys=False) + "\n"
    if args.check:
        try:
            with open(args.out, encoding="utf-8") as fh:
                committed = fh.read()
        except FileNotFoundError:
            print(f"{args.out} missing — run tools/k8s/gen_dashboard.py")
            return 1
        if committed != text:
            print(f"{os.path.relpath(args.out, ROOT)} is out of sync "
                  "with the metric catalog — regenerate with "
                  "python tools/k8s/gen_dashboard.py")
            return 1
        print(f"dashboard in sync ({len(rows)} catalog rows)")
        return 0
    os.makedirs(os.path.dirname(args.out), exist_ok=True)
    with open(args.out, "w", encoding="utf-8") as fh:
        fh.write(text)
    print(f"wrote {os.path.relpath(args.out, ROOT)} "
          f"({len(rows)} catalog rows)")
    return 0


if __name__ == "__main__":
    sys.exit(main())
