"""Dependency-free chart renderer: fills {{ dotted.path }} placeholders
in tools/k8s/chart/templates/*.yaml from values.yaml (or an overrides
file) — the helm-template equivalent for environments without helm
(ref: /root/reference/tools/helm; same values layout, so the templates
can migrate to helm unchanged).

    python tools/k8s/render.py [--values my-values.yaml] [--out DIR]
"""
import argparse
import os
import re
import sys

_HERE = os.path.dirname(os.path.abspath(__file__))


def parse_simple_yaml(text):
    """Minimal YAML subset: nested maps, scalars, comments. Enough for
    values files; no lists/anchors (use overrides for anything fancier)."""
    root = {}
    stack = [(-1, root)]
    for raw in text.splitlines():
        line = raw.split("#", 1)[0].rstrip()
        if not line.strip():
            continue
        indent = len(line) - len(line.lstrip())
        key, _, val = line.strip().partition(":")
        while stack and indent <= stack[-1][0]:
            stack.pop()
        parent = stack[-1][1]
        val = val.strip()
        if val == "":
            child = {}
            parent[key] = child
            stack.append((indent, child))
        else:
            parent[key] = val.strip("\"'")
    return root


def lookup(values, dotted):
    cur = values
    for part in dotted.split("."):
        if not isinstance(cur, dict) or part not in cur:
            raise KeyError(f"values has no key {dotted!r}")
        cur = cur[part]
    return cur


def render(template_text, values):
    def sub(m):
        return str(lookup(values, m.group(1).strip()))
    return re.sub(r"\{\{([^}]+)\}\}", sub, template_text)


def main(argv=None):
    ap = argparse.ArgumentParser()
    ap.add_argument("--values",
                    default=os.path.join(_HERE, "chart", "values.yaml"))
    ap.add_argument("--out", default=os.path.join(_HERE, "rendered"))
    args = ap.parse_args(argv)
    with open(args.values) as fh:
        values = parse_simple_yaml(fh.read())
    tdir = os.path.join(_HERE, "chart", "templates")
    os.makedirs(args.out, exist_ok=True)
    for name in sorted(os.listdir(tdir)):
        with open(os.path.join(tdir, name)) as fh:
            out = render(fh.read(), values)
        dest = os.path.join(args.out, name)
        with open(dest, "w") as fh:
            fh.write(out)
        print(f"rendered {dest}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
