"""Train and commit the bundled pretrained model artifact.

The reference ships a live model repo the ModelDownloader pulls from
(deep-learning/.../cntk/downloader/ModelDownloader.scala:112,233-260).
This environment has no egress, so the committed repo under
``models/repo`` carries a model **genuinely trained here**: a small CNN
fit on sklearn's digits (1797 8x8 grayscale images, 10 classes) to
>97% held-out accuracy, exported through torch.onnx (a real foreign
exporter) with its manifest + sha256. ImageFeaturizer's transfer-
learning tests then run on weights that encode actual learning, not a
random init.

Run from the repo root: ``python tools/make_pretrained.py``
"""
import json
import os

import numpy as np
import torch
import torch.nn as nn

# the TorchScript exporter serializes the full model itself; the onnx
# wheel is only imported to inject onnxscript functions (none used)
from torch.onnx._internal.torchscript_exporter import onnx_proto_utils

onnx_proto_utils._add_onnxscript_fn = lambda model_bytes, custom_opsets: \
    model_bytes

OUT = os.path.join(os.path.dirname(__file__), os.pardir, "models", "repo")


class DigitsCNN(nn.Module):
    """Conv backbone + linear head; the head is what transfer learning
    cuts off (ImageFeaturizer cut_output_layers)."""

    def __init__(self):
        super().__init__()
        self.features = nn.Sequential(
            nn.Conv2d(1, 16, 3, padding=1), nn.BatchNorm2d(16), nn.ReLU(),
            nn.Conv2d(16, 32, 3, padding=1), nn.ReLU(),
            nn.MaxPool2d(2),
            nn.Conv2d(32, 32, 3, padding=1), nn.ReLU(),
            nn.AdaptiveAvgPool2d(2),
        )
        self.head = nn.Sequential(nn.Flatten(), nn.Linear(32 * 4, 10))

    def forward(self, x):
        return self.head(self.features(x))


def main():
    from sklearn.datasets import load_digits
    from sklearn.model_selection import train_test_split

    X, y = load_digits(return_X_y=True)
    X = (X / 16.0).astype(np.float32).reshape(-1, 1, 8, 8)
    Xt, Xv, yt, yv = train_test_split(X, y, test_size=0.25, random_state=0)

    torch.manual_seed(0)
    model = DigitsCNN()
    opt = torch.optim.Adam(model.parameters(), lr=2e-3)
    loss_fn = nn.CrossEntropyLoss()
    xt = torch.from_numpy(Xt)
    tt = torch.from_numpy(yt)
    model.train()
    for epoch in range(60):
        perm = torch.randperm(len(xt))
        for i in range(0, len(xt), 128):
            idx = perm[i:i + 128]
            opt.zero_grad()
            loss = loss_fn(model(xt[idx]), tt[idx])
            loss.backward()
            opt.step()
    model.eval()
    with torch.no_grad():
        acc = (model(torch.from_numpy(Xv)).argmax(1).numpy() == yv).mean()
    print(f"held-out accuracy: {acc:.4f}")
    assert acc > 0.97, "refusing to commit an under-trained artifact"

    import io

    buf = io.BytesIO()
    torch.onnx.export(model, (torch.from_numpy(Xv[:2]),), buf,
                      opset_version=17, dynamo=False,
                      input_names=["input"], output_names=["logits"],
                      dynamic_axes={"input": {0: "batch"},
                                    "logits": {0: "batch"}})
    blob = buf.getvalue()

    from synapseml_tpu.dl.downloader import make_repo

    os.makedirs(OUT, exist_ok=True)
    make_repo(OUT, {"digits-cnn": blob}, schemas={
        "digits-cnn": {
            "task": "image classification (sklearn digits, 10 classes)",
            "input": "float32 [N,1,8,8], pixel range [0,1]",
            "heldout_accuracy": round(float(acc), 4),
            "exporter": "torch.onnx (TorchScript exporter, opset 17)",
            "trained_by": "tools/make_pretrained.py (seeded, reproducible)",
        }})
    # frozen eval set for the accuracy-gate test
    np.savez(os.path.join(OUT, "digits_eval.npz"),
             x=Xv[:200], y=yv[:200])
    print(f"wrote {OUT}: {len(blob)} bytes")


if __name__ == "__main__":
    main()
