#!/usr/bin/env bash
# Test runner (ref: tools/pytest/run_all_tests.py): package-sharded pytest,
# mirroring the reference CI's per-package UnitTests matrix.
set -euo pipefail
cd "$(dirname "$0")/.."
shards=(
  "tests/test_core.py tests/test_stages.py tests/test_featurize.py"
  "tests/test_gbdt.py tests/test_lgbm_format.py tests/test_gates.py tests/test_checkpoint.py"
  "tests/test_linear.py tests/test_knn_iforest.py tests/test_train_automl_rec.py"
  "tests/test_onnx.py tests/test_runtime_dl.py tests/test_image.py tests/test_downloader.py"
  "tests/test_parallel.py"
  "tests/test_io_http.py tests/test_serving.py tests/test_cognitive.py tests/test_cyber.py"
  "tests/test_fuzzing.py tests/test_explainers.py tests/test_native.py tests/test_codegen.py tests/test_fault.py"
)
for shard in "${shards[@]}"; do
  echo "=== $shard"
  python -m pytest $shard -q
done
