"""Generate committed foreign-exporter ONNX fixtures.

Every other ONNX graph in this repo's tests is emitted by the in-repo
``onnx/builder.py``; these fixtures instead come out of **torch.onnx** —
a real third-party exporter with its own serializer and idioms (dynamic
batch dims, Shape chains from Flatten, Identity/Dropout noise, traced
size arithmetic) — so the importer is certified against bytes it did not
write. The reference feeds arbitrary user .onnx files to onnxruntime
(deep-learning/.../onnx/ONNXModel.scala:173-193); committed fixtures are
the offline equivalent.

Run from the repo root (writes tests/fixtures/*.onnx + expected .npz):

    python tools/make_onnx_fixtures.py
"""
import os

import numpy as np
import torch
import torch.nn as nn

# The TorchScript exporter produces the complete model bytes with torch's
# own C++ protobuf serializer, then imports the `onnx` wheel only to
# re-inject onnxscript custom functions (none are used here). This image
# has no onnx wheel, so skip that no-op step and keep the raw bytes.
from torch.onnx._internal.torchscript_exporter import onnx_proto_utils

onnx_proto_utils._add_onnxscript_fn = lambda model_bytes, custom_opsets: \
    model_bytes

OUT = os.path.join(os.path.dirname(__file__), os.pardir, "tests", "fixtures")


class SmallCNN(nn.Module):
    """Conv/BN/pool classifier with the noise real exports carry:
    Dropout (folds to Identity in eval), Flatten (a Shape->Gather->
    Concat->Reshape chain under dynamic batch), and a log-softmax head.
    """

    def __init__(self):
        super().__init__()
        self.features = nn.Sequential(
            nn.Conv2d(1, 8, 3, padding=1), nn.BatchNorm2d(8), nn.ReLU(),
            nn.MaxPool2d(2),
            nn.Conv2d(8, 16, 3, padding=1), nn.ReLU(),
            nn.AdaptiveAvgPool2d(4),
        )
        self.drop = nn.Dropout(0.5)
        self.fc1 = nn.Linear(16 * 4 * 4, 32)
        self.fc2 = nn.Linear(32, 10)

    def forward(self, x):
        y = self.features(x)
        y = torch.flatten(y, 1)
        y = self.drop(torch.relu(self.fc1(y)))
        return torch.log_softmax(self.fc2(y), dim=1)


class GruSeq(nn.Module):
    """GRU sequence model: embedding gather + recurrent cell + per-step
    head — the RNN-era export shape (ONNX GRU op, Transpose layout
    shuffles, Gather on a traced index)."""

    def __init__(self):
        super().__init__()
        self.emb = nn.Embedding(50, 12)
        self.gru = nn.GRU(12, 16, batch_first=True, bidirectional=True)
        self.head = nn.Linear(32, 5)

    def forward(self, ids):
        x = self.emb(ids)
        y, _ = self.gru(x)
        # slice the final timestep through traced size arithmetic so the
        # exporter emits a Shape/Gather/Slice chain
        return self.head(y[:, y.shape[1] - 1, :])


class QuantCNN(nn.Module):
    """Statically-quantized conv net (torch.ao eager static quant,
    fbgemm): the exporter emits the QDQ idiom (QuantizeLinear/
    DequantizeLinear fencing int-weight convs) that onnxruntime's
    quantization tooling also produces — the importer must score it
    within integer-kernel rounding of torch's own quantized forward."""

    def __init__(self):
        super().__init__()
        self.quant = torch.ao.quantization.QuantStub()
        self.conv = nn.Conv2d(3, 8, 3, padding=1)
        self.relu = nn.ReLU()
        self.conv2 = nn.Conv2d(8, 4, 3, stride=2, padding=1)
        self.dequant = torch.ao.quantization.DeQuantStub()

    def forward(self, x):
        x = self.quant(x)
        x = self.relu(self.conv(x))
        x = self.conv2(x)
        return self.dequant(x)


class TinyKVDecoder(nn.Module):
    """Autoregressive decoder block with EXPLICIT KV-cache graph I/O:
    ``(ids, past_key, past_value) -> (logits, present_key, present_value)``
    — the ORT-GenAI / HF export shape where the cache is the caller's
    state, not hidden module state. GQA via repeat_interleave (4 query
    heads over 2 KV heads) and a past-offset causal mask built from
    traced ``arange`` arithmetic, so the exporter emits the
    Range/Less/Where idiom over DYNAMIC past length. The round-trip
    test proves KV concat is position-exact: feeding tokens one at a
    time through the cache must reproduce the full-sequence logits at
    every position."""

    def __init__(self, vocab=50, d=32, heads=4, kv_heads=2):
        super().__init__()
        self.h, self.kvh, self.hd = heads, kv_heads, d // heads
        self.emb = nn.Embedding(vocab, d)
        self.wq = nn.Linear(d, d)
        self.wk = nn.Linear(d, kv_heads * self.hd)
        self.wv = nn.Linear(d, kv_heads * self.hd)
        self.wo = nn.Linear(d, d)
        self.ln = nn.LayerNorm(d)
        self.head = nn.Linear(d, vocab)

    def forward(self, ids, past_key, past_value):
        b, s = ids.shape[0], ids.shape[1]
        p = past_key.shape[2]
        x = self.emb(ids)
        q = self.wq(x).view(b, s, self.h, self.hd).transpose(1, 2)
        k_new = self.wk(x).view(b, s, self.kvh, self.hd).transpose(1, 2)
        v_new = self.wv(x).view(b, s, self.kvh, self.hd).transpose(1, 2)
        k = torch.cat([past_key, k_new], dim=2)
        v = torch.cat([past_value, v_new], dim=2)
        kq = k.repeat_interleave(self.h // self.kvh, dim=1)
        vq = v.repeat_interleave(self.h // self.kvh, dim=1)
        att = (q @ kq.transpose(-1, -2)) / (self.hd ** 0.5)
        # past-offset causal mask over dynamic p: query i sits at
        # absolute position p+i and may attend k positions <= p+i
        kpos = torch.arange(p + s, device=ids.device)
        qpos = torch.arange(s, device=ids.device) + p
        att = att.masked_fill(kpos[None, None, None, :]
                              > qpos[None, None, :, None],
                              float("-inf"))
        out = (att.softmax(-1) @ vq).transpose(1, 2).reshape(b, s, -1)
        y = self.ln(x + self.wo(out))
        return self.head(y), k, v


def make_kv_decoder(name="torch_kv_decoder"):
    torch.manual_seed(42)
    m = TinyKVDecoder().eval()
    ids = torch.randint(0, 50, (2, 4))
    past_k = torch.randn(2, 2, 3, 8)
    past_v = torch.randn(2, 2, 3, 8)
    path = os.path.join(OUT, f"{name}.onnx")
    with torch.no_grad():
        logits, pk, pv = m(ids, past_k, past_v)
        # the npz also records a FULL-sequence run from an empty cache:
        # the round-trip test's from-scratch reference
        full_ids = torch.randint(0, 50, (1, 12))
        empty = torch.zeros(1, 2, 0, 8)
        full_logits, _, _ = m(full_ids, empty, empty)
    torch.onnx.export(
        m, (ids, past_k, past_v), path, opset_version=17, dynamo=False,
        input_names=["input_ids", "past_key", "past_value"],
        output_names=["logits", "present_key", "present_value"],
        dynamic_axes={"input_ids": {0: "batch", 1: "seq"},
                      "past_key": {0: "batch", 2: "past"},
                      "past_value": {0: "batch", 2: "past"},
                      "logits": {0: "batch", 1: "seq"},
                      "present_key": {0: "batch", 2: "total"},
                      "present_value": {0: "batch", 2: "total"}},
        do_constant_folding=True)
    np.savez(os.path.join(OUT, f"{name}_io.npz"),
             input_ids=ids.numpy(), past_key=past_k.numpy(),
             past_value=past_v.numpy(), logits=logits.numpy(),
             present_key=pk.numpy(), present_value=pv.numpy(),
             full_ids=full_ids.numpy(), full_logits=full_logits.numpy())
    print(f"{name}: {os.path.getsize(path)} bytes, "
          f"logits {tuple(logits.shape)}, present {tuple(pk.shape)}")


def make_quantized(name="torch_quant_cnn"):
    torch.backends.quantized.engine = "fbgemm"
    torch.manual_seed(7)
    m = QuantCNN().eval()
    m.qconfig = torch.ao.quantization.get_default_qconfig("fbgemm")
    torch.ao.quantization.fuse_modules(m, [["conv", "relu"]],
                                       inplace=True)
    torch.ao.quantization.prepare(m, inplace=True)
    for _ in range(8):  # calibration passes (seeded)
        m(torch.randn(2, 3, 16, 16))
    torch.ao.quantization.convert(m, inplace=True)
    x = torch.randn(2, 3, 16, 16)
    with torch.no_grad():
        expected = m(x).numpy()
    path = os.path.join(OUT, f"{name}.onnx")
    torch.onnx.export(m, (x,), path, opset_version=17, dynamo=False,
                      input_names=["input"], output_names=["output"])
    # record the model's OUTPUT dequant scale so the parity test can
    # gate in units of output quantization steps
    out_scale = float(m.conv2.scale) * 1.0
    np.savez(os.path.join(OUT, f"{name}_io.npz"),
             input=x.numpy(), expected=expected,
             out_scale=np.float32(out_scale))
    print(f"{name}: {os.path.getsize(path)} bytes, out {expected.shape}, "
          f"out_scale {out_scale:.5f}")


def export(model, args, name, dynamic_axes):
    model.eval()
    path = os.path.join(OUT, f"{name}.onnx")
    with torch.no_grad():
        expected = model(*args).numpy()
    torch.onnx.export(
        model, args, path, opset_version=17, dynamo=False,
        input_names=["input"], output_names=["output"],
        dynamic_axes=dynamic_axes, do_constant_folding=True)
    np.savez(os.path.join(OUT, f"{name}_io.npz"),
             input=args[0].numpy(), expected=expected)
    print(f"{name}: {os.path.getsize(path)} bytes, out {expected.shape}")


def main():
    os.makedirs(OUT, exist_ok=True)
    torch.manual_seed(1234)
    cnn = SmallCNN()
    # non-trivial BN running stats, as a trained checkpoint would have
    with torch.no_grad():
        cnn.features[1].running_mean.normal_(0, 0.5)
        cnn.features[1].running_var.uniform_(0.5, 2.0)
    x = torch.randn(3, 1, 16, 16)
    export(cnn, (x,), "torch_cnn",
           {"input": {0: "batch"}, "output": {0: "batch"}})

    gru = GruSeq()
    ids = torch.randint(0, 50, (4, 9))
    export(gru, (ids,), "torch_gru",
           {"input": {0: "batch", 1: "seq"}, "output": {0: "batch"}})

    # the transformer-era export: nn.MultiheadAttention lowers to the
    # densest shape-arithmetic idiom the exporter emits (Shape chains
    # through Mod/Gather/Concat feeding Reshape/Slice). The TorchScript
    # exporter constant-folds the SEQUENCE length inside attention, so
    # only the batch axis is dynamic in practice.
    txf = nn.TransformerEncoder(
        nn.TransformerEncoderLayer(d_model=32, nhead=4, dim_feedforward=64,
                                   batch_first=True, dropout=0.1),
        num_layers=2).eval()
    xt = torch.randn(3, 10, 32)
    export(txf, (xt,), "torch_transformer",
           {"input": {0: "batch"}, "output": {0: "batch"}})

    make_quantized()
    make_kv_decoder()


if __name__ == "__main__":
    import sys

    if len(sys.argv) > 1 and sys.argv[1] == "quantized":
        os.makedirs(OUT, exist_ok=True)
        make_quantized()  # additive: leaves the committed fixtures as-is
    elif len(sys.argv) > 1 and sys.argv[1] == "kv_decoder":
        os.makedirs(OUT, exist_ok=True)
        make_kv_decoder()  # additive: leaves the committed fixtures as-is
    else:
        main()
