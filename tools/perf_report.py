"""Automated bottleneck report: bench results x the roofline cost table.

Consumes ONE artifact — a ``bench.py --out`` JSON whose ``detail.cost``
embeds the roofline cost-table snapshot (runtime/costmodel.py) — and
emits a ranked markdown report answering, per bench group: what did it
achieve, what could its compiled program attain on this device's
roofline, which bound is it at, and what is the implied lever. This is
the machine-checked form of the "which signature is the bottleneck"
question the ROADMAP's next perf items (Pallas traversal kernel, int8
lane, TP serving) are judged against.

Attribution model (documented caveats, in the report itself):

- Each bench group's warmups run inside ``costmodel.tag_scope(group)``,
  so its compiled signatures carry the group name; the report joins on
  that tag. The *representative* signature is the tagged entry with the
  most flops (the big-bucket program dominates the group's wall time).
- Achieved FLOP/s = metric rate x flops-per-item, where flops-per-item
  is the representative entry's flops over its bucket (rows == items
  for every throughput metric we emit). Latency (ms) metrics convert
  through ``rate = 1000/value``; one-shot wall metrics (cold start)
  carry an achieved fraction of 0 by construction — their lever is the
  compile cache, not the roofline.
- XLA's cost model is a pre-fusion ESTIMATE (docs/perf.md "Roofline
  methodology"): the report ranks bottlenecks and classifies bounds;
  it does not replace a profiler trace. Pass ``--trace-dir`` to have
  the report inventory ``jax.profiler`` artifacts alongside.

Exit codes: **0** report written, **2** an attributed-kind group has no
captured cost signature (or ``--check`` schema violation), **1** usage/
unreadable input. Wired into CI as the ``perf-report`` smoke job
(``bench.py --fast --out`` -> ``perf_report.py --check``) and into
``bench.py --cost-report`` for one-command local runs.
"""
from __future__ import annotations

import argparse
import glob
import json
import os
import sys
from typing import Any, Dict, List, Optional, Tuple

# levers by bound class — the generic direction when no group-specific
# diagnosis applies
_BOUND_LEVERS = {
    "memory": ("memory-bound: raise arithmetic intensity — fuse the "
               "gather/elementwise chain (Pallas), grow the bucket, or "
               "shrink bytes (int8/bf16 operands)"),
    "compute": ("compute-bound: raise achieved FLOP/s — feed the MXU "
                "integer-native (int8 lane), improve occupancy with "
                "larger batches, or shard across chips (dp/tp)"),
    "unknown": ("unattributed program: XLA yielded no flops/bytes "
                "ledger — re-warm with a capturable executable or "
                "profile directly"),
    "host": ("host-bound: no device program in the loop — the lever is "
             "framework overhead (batching, linger, staging, reply "
             "path), not the roofline"),
}

# group-specific diagnoses — sharper than the bound-generic lever when
# we know what the group runs (kept in sync with bench.py's groups;
# unknown groups fall back to the bound lever alone)
_GROUP_LEVERS = {
    "gbdt_train": "histogram build routes via the measured prober — "
                  "next win is the fused Pallas traversal kernel for "
                  "predict (ROADMAP)",
    "onnx_lightgbm": "tree scoring is an XLA gather chain — the "
                     "Pallas fused traversal kernel is the named lever "
                     "(ROADMAP 'rawest speed lever left')",
    "gbdt_histogram": "already Pallas-routed where it wins; regression "
                      "here means the prober re-routed — check "
                      "auto_routed_to in the bench detail",
    "transformer": "occupancy-sensitive (docs/perf.md: bs=128 vs 32 "
                   "nearly 2x) — keep batches >=4k rows per matmul; "
                   "int8 QOperator lane is the next step",
    "resnet50": "conv stack near its measured MFU — compute dtype and "
                "hostfeed wire are autotuner-routed (see formulation "
                "column); next lever is the int8 lane or more chips "
                "(dp_scaling tracks that)",
    "resnet50_fast": "CI twin of resnet50: both lanes routed by "
                     "measured verdicts — a formulation flip here means "
                     "the autotuner re-decided, check the bench "
                     "detail.autotune snapshot",
    "dp_scaling": "speedup below ~0.9x/chip means dispatch or H2D "
                  "serialization — check executor_duty_cycle spread "
                  "across devices",
    "serving": "echo round trip: serving framework overhead only — "
               "batching/linger/reply-path tuning",
    "serving_scored": "per-request cost amortizes across the "
                      "micro-batch — deepen coalescing before touching "
                      "the model",
    "cold_start": "ruled by compile/deserialize wall, not FLOP/s — "
                  "lever is the executable store hit rate "
                  "(compile_cache_store_hits_total) and warm hydration",
    "onnx_tp_scaling": "weights tp-sharded at rest, gathered at entry "
                       "(the bit-identity contract) — speedup below "
                       "~1.0 is the all-gather price; to trade replay "
                       "equality for peak memory, route the megatron "
                       "preset (partition_rules) into sharded compute",
}

_REQUIRED_ROW_KEYS = (
    "group", "kind", "bound", "flops_per_item", "bytes_per_item",
    "achieved_flops_per_sec", "attainable_flops_per_sec",
    "roofline_fraction", "lever", "metric", "value", "unit",
    "formulation", "partition", "tokens_per_sec",
)


def _group_tokens_per_sec(metrics: List[Dict[str, Any]]) -> Optional[float]:
    """The group's token throughput, when it reports one (unit
    ``tokens/sec`` — the decode-serving groups); None otherwise. Kept
    as its own column because tokens/s is the serving-capacity number
    a roofline fraction cannot substitute for: a decode step is tiny
    and memory-bound by construction, so its fraction ranks it last
    while its tokens/s is the headline."""
    for m in metrics:
        v = m.get("value")
        if str(m.get("unit", "")) == "tokens/sec" and \
                isinstance(v, (int, float)):
            return float(v)
    return None


def _group_formulations(payload: Dict[str, Any],
                        group: str) -> List[str]:
    """``lane:choice`` strings for every autotune lane whose ``groups``
    tag includes this bench group (detail.autotune, the lane snapshot
    bench.py embeds). A lane with several routed keys lists each
    distinct choice once — the report answers WHICH formulation the
    run actually executed, per bottleneck."""
    lanes = ((payload.get("detail") or {}).get("autotune") or {}).get(
        "lanes") or {}
    out: List[str] = []
    for name in sorted(lanes):
        lane = lanes[name] or {}
        if group not in (lane.get("groups") or ()):
            continue
        choices = sorted(set((lane.get("decisions") or {}).values()))
        if not choices:
            choices = [f"{lane.get('reference', '?')} (unrouted)"]
        out.append(f"{name}:{'/'.join(choices)}")
    return out


def _group_partition(payload: Dict[str, Any], group: str) -> str:
    """The execution geometry a group ran under — the ``partition``
    string its bench detail reports (``dp1xtp8``-style, the executor's
    mesh label layout), or one synthesized from a plain ``devices``
    count (pure data parallelism). Groups that never leave one device
    show ``—``: the column answers "was this number measured sharded,
    and how" next to every roofline fraction."""
    for m in _group_metrics(payload, group):
        detail = m.get("detail") or {}
        part = detail.get("partition")
        if part:
            return str(part)
        ndev = detail.get("devices")
        if isinstance(ndev, int) and ndev > 1:
            return f"dp{ndev}"
    return "—"


def _fmt_eng(v: float, unit: str = "") -> str:
    """1.23e9 -> '1.23 G'; keeps tables scannable."""
    if v is None or v == 0:
        return "0" + (f" {unit}" if unit else "")
    for thresh, suffix in ((1e12, "T"), (1e9, "G"), (1e6, "M"),
                           (1e3, "k")):
        if abs(v) >= thresh:
            return f"{v / thresh:.2f} {suffix}{unit}"
    return f"{v:.3g}{(' ' + unit) if unit else ''}"


def _entries_for(cost: Dict[str, Any], group: str) -> List[Dict[str, Any]]:
    return [e for e in cost.get("entries", [])
            if e.get("tag") == group]


def _representative(entries: List[Dict[str, Any]]) -> Optional[Dict[str, Any]]:
    captured = [e for e in entries if e.get("captured")]
    if not captured:
        return None
    return max(captured, key=lambda e: e.get("flops", 0.0))


def _group_metrics(payload: Dict[str, Any],
                   group: str) -> List[Dict[str, Any]]:
    all_entries = [payload] + list(payload.get("secondary", []))
    return [e for e in all_entries if e.get("group") == group]


def _rate_per_sec(metric: Dict[str, Any]) -> Optional[float]:
    """items/sec implied by one bench metric: throughput units pass
    through, latency ms inverts, anything else (one-shot walls) is
    None — no rate, no achieved attribution."""
    value = metric.get("value")
    unit = str(metric.get("unit", ""))
    if not isinstance(value, (int, float)) or value <= 0:
        return None
    if "/sec" in unit:
        return float(value)
    if unit == "ms" and "cold_start" not in str(metric.get("metric", "")):
        return 1000.0 / float(value)
    return None


def attribute_group(group: str, meta: Dict[str, Any],
                    payload: Dict[str, Any],
                    cost: Dict[str, Any]) -> Dict[str, Any]:
    """One report row: join the group's headline metric with its
    representative cost signature and the roofline math already in the
    snapshot. Never raises — a group the table cannot attribute comes
    back with ``attributed=False`` (the --check failure)."""
    kind = meta.get("kind", "device")
    metrics = _group_metrics(payload, group)
    head = metrics[0] if metrics else {"metric": "?", "value": None,
                                       "unit": "?"}
    tagged = _entries_for(cost, group)
    rep = _representative(tagged)
    row: Dict[str, Any] = {
        "group": group,
        "kind": kind,
        "description": meta.get("description", ""),
        "metric": head.get("metric"),
        "value": head.get("value"),
        "unit": head.get("unit"),
        "n_signatures": len(tagged),
        "flops_per_item": 0.0,
        "bytes_per_item": 0.0,
        "bound": "host" if kind == "host" else "unknown",
        "achieved_flops_per_sec": 0.0,
        "attainable_flops_per_sec": 0.0,
        "roofline_fraction": 0.0,
        "attributed": kind == "host",  # host groups need no signature
        "signature": None,
        "device_kind": None,
        "tokens_per_sec": _group_tokens_per_sec(metrics),
    }
    if rep is not None:
        bucket = max(1, int(rep.get("bucket", 1)))
        flops_item = rep.get("flops", 0.0) / bucket
        bytes_item = rep.get("bytes_accessed", 0.0) / bucket
        row.update({
            "attributed": True,
            "signature": rep.get("signature"),
            "device_kind": rep.get("device_kind"),
            "bound": rep.get("bound", "unknown"),
            "flops_per_item": flops_item,
            "bytes_per_item": bytes_item,
            "arithmetic_intensity": rep.get("arithmetic_intensity", 0.0),
            "attainable_flops_per_sec": rep.get(
                "attainable_flops_per_sec", 0.0),
        })
        rate = _rate_per_sec(head)
        if rate is not None and flops_item > 0:
            ach = rate * flops_item
            row["achieved_flops_per_sec"] = ach
            if row["attainable_flops_per_sec"] > 0:
                row["roofline_fraction"] = round(
                    ach / row["attainable_flops_per_sec"], 6)
    lever = _BOUND_LEVERS.get(row["bound"], _BOUND_LEVERS["unknown"])
    extra = _GROUP_LEVERS.get(group)
    row["lever"] = f"{extra} — {lever}" if extra else lever
    forms = _group_formulations(payload, group)
    row["formulation"] = "; ".join(forms) if forms else "—"
    row["partition"] = _group_partition(payload, group)
    return row


def _trace_inventory(trace_dir: str) -> List[str]:
    """jax.profiler artifacts under a trace dir, for the report's
    ground-truth pointer (we inventory, we do not parse xplane)."""
    pats = ("**/*.xplane.pb", "**/*.trace.json.gz", "**/*.trace.json")
    out: List[str] = []
    for p in pats:
        out.extend(glob.glob(os.path.join(trace_dir, p), recursive=True))
    return sorted(out)


def build_report(payload: Dict[str, Any],
                 trace_dir: Optional[str] = None
                 ) -> Tuple[List[Dict[str, Any]], str, List[str]]:
    """``(rows, markdown, unattributed_groups)`` from one bench
    payload. Rows are ranked worst-first: device groups by ascending
    roofline fraction (the bottleneck order), host groups last."""
    detail = payload.get("detail", {}) or {}
    cost = detail.get("cost", {}) or {}
    groups_meta = detail.get("bench_groups", {}) or {}
    if not groups_meta:
        # tolerate a pre-cost artifact: derive groups from the entries
        groups_meta = {e.get("group"): {"kind": "device"}
                       for e in [payload] + list(payload.get(
                           "secondary", []))
                       if e.get("group")}
    rows = [attribute_group(g, meta, payload, cost)
            for g, meta in groups_meta.items()]
    rows.sort(key=lambda r: (r["kind"] == "host",
                             r["roofline_fraction"]
                             if r["attributed"] else -1.0,
                             r["group"]))
    unattributed = [r["group"] for r in rows if not r["attributed"]]

    lines: List[str] = []
    add = lines.append
    add("# Bench bottleneck report")
    add("")
    head_metric = payload.get("metric", "?")
    add(f"Headline: `{head_metric}` = {payload.get('value')} "
        f"{payload.get('unit', '')}")
    peaks = cost.get("peaks", {})
    if peaks:
        add("")
        add("| device kind | peak FLOP/s | peak HBM B/s | provenance |")
        add("|---|---|---|---|")
        for kind, p in sorted(peaks.items()):
            add(f"| {kind} | {_fmt_eng(p.get('flops_per_sec', 0))}F/s "
                f"| {_fmt_eng(p.get('bytes_per_sec', 0))}B/s "
                f"| {p.get('source', '?')} |")
    add("")
    add("## Ranked bottlenecks (worst roofline fraction first)")
    add("")
    add("| rank | group | bound | metric | tokens/s | flops/item | "
        "achieved FLOP/s | attainable | fraction | partition "
        "| formulation | lever |")
    add("|---|---|---|---|---|---|---|---|---|---|---|---|")
    for i, r in enumerate(rows, 1):
        frac = (f"{r['roofline_fraction']:.2%}"
                if r["attributed"] and r["kind"] != "host" else "—")
        tps = r.get("tokens_per_sec")
        tps_cell = f"{tps:,.0f}" if isinstance(tps, (int, float)) else "—"
        add(f"| {i} | {r['group']} | {r['bound']} "
            f"| `{r['metric']}` = {r['value']} {r['unit']} "
            f"| {tps_cell} "
            f"| {_fmt_eng(r['flops_per_item'])} "
            f"| {_fmt_eng(r['achieved_flops_per_sec'])} "
            f"| {_fmt_eng(r['attainable_flops_per_sec'])} "
            f"| {frac} | {r['partition']} | {r['formulation']} "
            f"| {r['lever']} |")
    add("")
    add("## Per-group signatures")
    for r in rows:
        add("")
        add(f"### {r['group']} ({r['kind']})")
        if r.get("description"):
            add(f"{r['description']}")
        lanes = ((payload.get("detail") or {}).get("autotune") or {}
                 ).get("lanes") or {}
        routed = [(n, lanes[n]) for n in sorted(lanes)
                  if r["group"] in (lanes[n].get("groups") or ())]
        if routed:
            add("")
            add("Autotuned formulations (runtime/autotune.py, verdicts "
                "in the shared route table):")
            for name, lane in routed:
                decided = lane.get("decisions") or {}
                probes = lane.get("probes", 0)
                if decided:
                    for key, choice in sorted(decided.items()):
                        add(f"- `{name}` -> **{choice}** "
                            f"(key `{key}`, {probes} probe(s) this "
                            f"run, reference {lane.get('reference')})")
                else:
                    add(f"- `{name}`: no keys routed this run "
                        f"(reference {lane.get('reference')})")
        tagged = _entries_for(cost, r["group"])
        if not tagged:
            add("no cost-table signatures recorded for this group"
                + (" (host-only: expected)" if r["kind"] == "host"
                   else " — **UNATTRIBUTED**"))
            continue
        add("")
        add("| signature | bucket | flops | bytes | AI | bound |")
        add("|---|---|---|---|---|---|")
        for e in sorted(tagged, key=lambda x: -x.get("flops", 0.0)):
            add(f"| `{e['signature']}` | {e['bucket']} "
                f"| {_fmt_eng(e.get('flops', 0))} "
                f"| {_fmt_eng(e.get('bytes_accessed', 0))} "
                f"| {e.get('arithmetic_intensity', 0)} "
                f"| {e.get('bound', '?')} |")
    if trace_dir:
        arts = _trace_inventory(trace_dir)
        add("")
        add("## Profiler artifacts")
        if arts:
            for a in arts[:20]:
                add(f"- `{a}`")
            if len(arts) > 20:
                add(f"- … {len(arts) - 20} more")
        else:
            add(f"- none under `{trace_dir}`")
    add("")
    add("---")
    add("*Attribution: "
        + str(cost.get("attribution", "bucket-proportional"))
        + "; flops/bytes are XLA's pre-fusion cost-model estimate, "
          "not hardware counters (docs/perf.md 'Roofline "
          "methodology').*")
    return rows, "\n".join(lines) + "\n", unattributed


def _check_schema(rows: List[Dict[str, Any]]) -> List[str]:
    """--check: every row must carry the full attribution schema."""
    problems = []
    for r in rows:
        missing = [k for k in _REQUIRED_ROW_KEYS if k not in r]
        if missing:
            problems.append(f"{r.get('group', '?')}: missing {missing}")
        if r.get("bound") not in ("compute", "memory", "host", "unknown"):
            problems.append(
                f"{r.get('group', '?')}: bad bound {r.get('bound')!r}")
    return problems


class _Parser(argparse.ArgumentParser):
    # the documented contract is 1 for usage errors (2 means an
    # unattributed group — a different failure an operator greps for)
    def error(self, message):
        self.print_usage(sys.stderr)
        self.exit(1, f"{self.prog}: error: {message}\n")


def main(argv=None) -> int:
    ap = _Parser(description=__doc__.splitlines()[0])
    ap.add_argument("bench_json",
                    help="bench.py --out artifact (detail.cost embedded)")
    ap.add_argument("--out", metavar="FILE",
                    help="write the markdown report here (default: "
                         "stdout)")
    ap.add_argument("--trace-dir", metavar="DIR",
                    help="inventory jax.profiler artifacts under DIR "
                         "into the report")
    ap.add_argument("--check", action="store_true",
                    help="CI gate: validate the report schema and that "
                         "every non-host bench group is attributed")
    args = ap.parse_args(argv)

    try:
        with open(args.bench_json, encoding="utf-8") as fh:
            payload = json.load(fh)
    except (OSError, ValueError) as e:
        print(f"cannot read bench artifact {args.bench_json}: {e}",
              file=sys.stderr)
        return 1
    if not isinstance(payload, dict) or "metric" not in payload:
        print(f"{args.bench_json} is not a bench.py --out payload",
              file=sys.stderr)
        return 1

    rows, md, unattributed = build_report(payload, args.trace_dir)
    if args.out:
        with open(args.out, "w", encoding="utf-8") as fh:
            fh.write(md)
        print(f"wrote {args.out} ({len(rows)} groups, "
              f"{len(unattributed)} unattributed)")
    else:
        sys.stdout.write(md)

    rc = 0
    if unattributed:
        print("unattributed bench groups (no captured cost signature): "
              + ", ".join(unattributed), file=sys.stderr)
        rc = 2
    if args.check:
        problems = _check_schema(rows)
        if problems:
            print("report schema violations:", *problems, sep="\n  ",
                  file=sys.stderr)
            rc = 2
        elif rc == 0:
            print(f"perf-report check ok: {len(rows)} groups "
                  "attributed, schema complete")
    return rc


if __name__ == "__main__":
    sys.exit(main())
