"""Generate real lib_lightgbm ground-truth fixtures (run OFFLINE).

This image does not ship the ``lightgbm`` wheel, so the fixtures cannot
be generated here — run this script in any environment with
``pip install lightgbm`` and commit the outputs to ``tests/fixtures/``:

    lightgbm_binary.txt / lightgbm_binary_pred.npz
    lightgbm_multiclass.txt / lightgbm_multiclass_pred.npz
    lightgbm_categorical.txt / lightgbm_categorical_pred.npz

Each ``.txt`` is the model string lib_lightgbm itself wrote
(``booster.model_to_string()``), each ``.npz`` holds the frozen input
matrix and lib_lightgbm's own predictions on it.
``tests/test_lightgbm_groundtruth.py`` then parity-tests
``Booster.load_string`` predictions against LightGBM's — replacing the
"sklearn agrees" cross-check with "LightGBM itself agrees" (the
reference gates against real LightGBM outputs:
lightgbm/src/test/resources/benchmarks/benchmarks_VerifyLightGBMClassifier.csv).

Data is generated from fixed seeds so fixture regeneration is
reproducible bit-for-bit given the same lightgbm version (record the
version in the commit message).
"""
import os
import sys

import numpy as np

HERE = os.path.dirname(os.path.abspath(__file__))
FIXTURES = os.path.join(os.path.dirname(HERE), "tests", "fixtures")


def _data(seed, n=800, d=8, n_classes=2, categorical=False):
    rng = np.random.default_rng(seed)
    x = rng.normal(size=(n, d))
    if categorical:
        x[:, 0] = rng.integers(0, 6, n)          # categorical slot
        x[rng.random(n) < 0.1, 3] = np.nan       # NaN missing
    logits = x[:, 1] + 0.5 * np.sin(2 * np.nan_to_num(x[:, 3])) * x[:, 2]
    if categorical:
        logits = logits + (np.nan_to_num(x[:, 0]) % 2) * 1.5
    if n_classes == 2:
        y = (logits + rng.normal(scale=0.3, size=n) > 0).astype(int)
    else:
        q = np.quantile(logits, np.linspace(0, 1, n_classes + 1)[1:-1])
        y = np.digitize(logits, q)
    return x, y


def main():
    import lightgbm as lgb

    os.makedirs(FIXTURES, exist_ok=True)
    cases = [
        ("binary", dict(objective="binary"), 2, False),
        ("multiclass", dict(objective="multiclass", num_class=3), 3, False),
        ("categorical", dict(objective="binary"), 2, True),
    ]
    for name, params, k, cat in cases:
        import zlib  # stable digest: hash() is salted per process
        x, y = _data(seed=zlib.crc32(name.encode()) % 2**31,
                     n_classes=k, categorical=cat)
        params = dict(params, num_leaves=15, learning_rate=0.1,
                      deterministic=True, force_row_wise=True, seed=7,
                      verbosity=-1)
        ds = lgb.Dataset(
            x, label=y,
            categorical_feature=[0] if cat else "auto",
            params={"verbosity": -1})
        booster = lgb.train(params, ds, num_boost_round=25)
        xq = _data(seed=12345, n=64, n_classes=k, categorical=cat)[0]
        pred = booster.predict(xq)
        raw = booster.predict(xq, raw_score=True)
        with open(os.path.join(FIXTURES, f"lightgbm_{name}.txt"),
                  "w") as fh:
            fh.write(booster.model_to_string())
        np.savez(os.path.join(FIXTURES, f"lightgbm_{name}_pred.npz"),
                 input=xq, pred=pred, raw=raw,
                 lgb_version=np.bytes_(lgb.__version__))
        print(f"wrote lightgbm_{name}.txt + pred.npz "
              f"(lightgbm {lgb.__version__})")


if __name__ == "__main__":
    sys.exit(main())
