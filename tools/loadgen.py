"""Open-loop Poisson load generator for the serving stack.

Open-loop means arrivals are scheduled by the CLOCK, not by completions:
a slow or shedding server does not slow the offered rate down, which is
the only way to observe real saturation behavior (a closed-loop client
self-throttles and hides it — the coordinated-omission trap). Arrival
gaps are exponential (Poisson process) and every request runs on its
own sender thread, so in-flight requests never gate the next arrival.

Mixed shapes: each request's payload feature vector length cycles
through ``shapes`` (weighted round-robin over the arrival sequence), so
the server's bucket ladder / padding paths are exercised the way mixed
production traffic would.

Reported: per-status counts, latency percentiles (p50/p95/p99) over
successful (200) replies and over ALL terminal replies, goodput
(200s/sec of wall time), offered vs achieved request rate. A request
that errors at the socket level (refused, reset, timed out) is counted
under ``"error"`` — the assertion surface for "zero hangs, zero silent
drops" is that every scheduled request reaches SOME terminal record.

Machine-readable results: ``--out results.json`` writes the full
summary dict (plus the SLO verdict, when asserted) to a file — the
surface CI consumes (tools/ci/chaos_check.py reads the file instead of
parsing stdout). SLO assertion mode: ``--slo-p99-ms`` and/or
``--slo-availability`` turn the run into a pass/fail gate
(:func:`evaluate_slo`) — p99 over successful replies must sit at or
under the threshold and the 200-fraction of every *scheduled* request
(socket errors count against — an unanswered request is an
availability loss) must meet the target; violation exits 2.

Multi-endpoint mode: ``--targets a,b,c`` round-robins ONE open-loop
arrival clock across several replicas (the fleet chaos phase's
load-balancer stand-in) with a per-target status/latency breakdown in
the summary; a request whose send dies at the socket level retries
once on the next target, the way an LB health-checks a member out.

Distributed tracing: every scheduled arrival carries a minted W3C
``traceparent`` header (seed-deterministic), REUSED on the failover
retry leg — one request, one trace, however many replicas it crossed.
The summary's ``slowest`` array (top-10 by latency: rid, trace_id,
status, target) links a bench/chaos report straight to
``GET /fleet/trace/<trace_id>`` on the fleet controller.

Usage (also importable: :func:`run_load` drives the chaos CI scenarios
in tools/ci/chaos_check.py)::

    python tools/loadgen.py --url http://127.0.0.1:8898/ \
        --rps 200 --duration 10 --shapes 2,8,32 [--deadline-ms 250] \
        [--seed 7] [--json] [--out results.json] \
        [--slo-p99-ms 250] [--slo-availability 0.999] \
        [--targets http://a/,http://b/] [--payload-key features] \
        [--replay capture.jsonl]

Replay verification mode: ``--replay capture.jsonl`` drives a capture
file's payloads (``runtime/capture.py``) in recorded order through the
same open-loop clock and verifies each reply's ``X-Output-Digest``
against the record — ``digest_mismatches`` in the summary/``--out``
JSON, nonzero exits 2 (the "did the rollout change scores?" gate).

Decode mode: ``--decode`` switches to open-loop Poisson *sequence*
arrivals against a decode-mode server's ``POST /generate``
(``io/serving.py --decode``): prompt/output lengths sampled from
``--prompt-lens`` / ``--output-lens``, a streamed-reply reader that
timestamps every token line, and TTFT / inter-token-latency
p50/p95/p99 plus tokens/s in the summary and ``--out`` JSON
(:func:`run_decode_load`).
"""
from __future__ import annotations

import argparse
import base64
import json
import random
import threading
import time
import urllib.error
import urllib.request
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

try:
    from synapseml_tpu.runtime.locksan import make_lock
except ImportError:  # standalone invocation without the repo on sys.path
    def make_lock(name):  # type: ignore[misc]
        return threading.Lock()


def _record_payload(rec: Dict[str, Any]) -> Optional[bytes]:
    """A capture record's request body back as bytes (inline utf-8 or
    base64) — duplicated from runtime/capture.py so this tool stays
    stdlib-only and runnable from an operator's laptop."""
    if "payload" in rec:
        return str(rec["payload"]).encode("utf-8")
    if "payload_b64" in rec:
        try:
            return base64.b64decode(rec["payload_b64"])
        except (ValueError, TypeError):
            return None
    return None


def load_capture_records(path: str) -> List[Dict[str, Any]]:
    """Parse a capture JSONL file (runtime/capture.py), skipping the
    one torn tail line a crash can leave."""
    out: List[Dict[str, Any]] = []
    with open(path, encoding="utf-8") as fh:
        for line in fh:
            try:
                rec = json.loads(line)
            except json.JSONDecodeError:
                continue
            if isinstance(rec, dict):
                out.append(rec)
    return out


def _default_payload(i: int, shape: int) -> Dict[str, Any]:
    """``{"x": [...]}`` of ``shape`` floats, deterministic in ``i`` —
    a scorer that computes a pure function of x lets the caller verify
    bit-identical replies (the failover acceptance check)."""
    return {"x": [float((i + k) % 7) for k in range(shape)]}


def percentile(sorted_vals: Sequence[float], q: float) -> float:
    """Nearest-rank percentile over an already-sorted sequence."""
    if not sorted_vals:
        return float("nan")
    k = max(0, min(len(sorted_vals) - 1,
                   int(round(q / 100.0 * (len(sorted_vals) - 1)))))
    return sorted_vals[k]


def _send(url: str, body: bytes, headers: Dict[str, str],
          timeout: float) -> Tuple[Any, Optional[str], Optional[str]]:
    """``(status, rid, output_digest)`` for one attempt — the rid comes
    back from the server's ``X-Request-Id`` reply header (every reply
    path echoes one), so a summary entry can link straight to
    ``/span/<rid>``; the ``X-Output-Digest`` header is what the
    ``--replay`` verification mode compares against the capture
    record's digest."""
    req = urllib.request.Request(url, data=body, method="POST",
                                 headers=headers)
    try:
        with urllib.request.urlopen(req, timeout=timeout) as r:
            r.read()
            return (r.status, r.headers.get("X-Request-Id"),
                    r.headers.get("X-Output-Digest"))
    except urllib.error.HTTPError as e:
        # explicit non-2xx IS a terminal reply (shed/drain/error paths);
        # read drains the connection so keep-alive sockets recycle
        try:
            e.read()
        except Exception:  # noqa: BLE001 - best-effort drain
            pass
        if e.headers is not None:
            return (e.code, e.headers.get("X-Request-Id"),
                    e.headers.get("X-Output-Digest"))
        return e.code, None, None
    except Exception:  # noqa: BLE001 - refused/reset/socket timeout
        return "error", None, None


def run_load(url: Optional[str], rps: float, duration_s: float,
             shapes: Sequence[int] = (2,),
             deadline_ms: Optional[float] = None,
             timeout: float = 30.0,
             seed: Optional[int] = None,
             payload_fn: Callable[[int, int], Any] = _default_payload,
             on_result: Optional[Callable[[int, Any, float], None]] = None,
             stop: Optional[threading.Event] = None,
             targets: Optional[Sequence[str]] = None,
             slowest_n: int = 10,
             replay_records: Optional[Sequence[Dict[str, Any]]] = None
             ) -> Dict[str, Any]:
    """Drive ``rps`` Poisson arrivals against ``url`` for ``duration_s``
    seconds; block until every sender reaches a terminal record; return
    the summary dict. ``seed`` makes the arrival schedule and shape
    sequence deterministic (the payloads already are). ``on_result(i,
    status, latency_s)`` observes each completion (chaos checks hook
    assertions here); ``stop`` aborts scheduling early (senders already
    launched still complete).

    ``targets`` (multi-endpoint mode, ``--targets``): ONE open-loop
    arrival clock round-robins requests across the given endpoints —
    the load-balancer stand-in the fleet chaos phase drives. A request
    whose send dies at the SOCKET level (refused/reset — a killed
    replica) retries once on the next target before recording, the
    way an LB health-checks a member out mid-flight; explicit HTTP
    replies (including sheds) never retry. The summary gains
    ``per_target`` (every attempt's status + ok-latency percentiles
    per endpoint) and ``failover_retries``; top-level ``by_status``
    stays final-outcome-per-request, so the SLO math is unchanged.

    Distributed tracing: every scheduled arrival mints one W3C
    ``traceparent`` (deterministic under ``seed``) and the failover
    retry leg REUSES it — a killed-replica request is therefore ONE
    trace with two sibling legs, stitchable fleet-wide via
    ``GET /fleet/trace/<trace_id>``. The summary's ``slowest`` array
    (top ``slowest_n`` by latency: rid, trace_id, latency_s, status,
    target) is the jump-off from a bench/chaos report to exactly that
    endpoint.

    Replay mode (``--replay``): ``replay_records`` is a sequence of
    capture records (runtime/capture.py JSONL dicts) driven in
    RECORDED order — same open-loop Poisson clock, but the bodies are
    the captured payload bytes and each record's ``trace_id`` rides
    the replayed request's traceparent, so the replay legs stitch
    next to the incident's own. Every reply to a record captured as
    200 has its ``X-Output-Digest`` header verified against the
    record's digest; the summary gains ``replayed`` /
    ``digest_checked`` / ``digest_mismatches`` (the "did the rollout
    change scores?" counter — the CLI exits 2 when it is nonzero)."""
    rng = random.Random(seed)
    headers = {"Content-Type": "application/json"}
    if deadline_ms is not None:
        headers["X-Deadline-Ms"] = str(deadline_ms)
    shapes = list(shapes) or [2]
    target_list = [u for u in (targets or ()) if u] or \
        ([url] if url else [])
    if not target_list:
        raise ValueError("run_load needs a url or a non-empty targets")

    results: List[Optional[Tuple[Any, float, Optional[str], str,
                                 str]]] = []
    senders: List[threading.Thread] = []
    lock = make_lock("loadgen:lock")
    per_target: Dict[str, Dict[str, Any]] = {
        t: {"by_status": {}, "ok_lat": []} for t in target_list}
    failovers = [0]
    digest_stats = {"checked": 0, "mismatches": 0, "unverified": 0}

    def _record_attempt(target: str, status: Any, dt: float):
        rec = per_target[target]
        key = str(status)
        rec["by_status"][key] = rec["by_status"].get(key, 0) + 1
        if status == 200:
            rec["ok_lat"].append(dt)

    def sender(i: int, body: bytes, trace_id: str, traceparent: str,
               expect_digest: Optional[str] = None):
        hdrs = dict(headers)
        hdrs["traceparent"] = traceparent
        target = target_list[i % len(target_list)]
        t0 = time.monotonic()
        status, rid, out_digest = _send(target, body, hdrs, timeout)
        with lock:
            _record_attempt(target, status, time.monotonic() - t0)
        if status == "error" and len(target_list) > 1:
            # LB-style one-shot failover on transport death only: the
            # request never reached an HTTP layer, so re-sending it to
            # a sibling cannot double-apply it any more than an LB
            # retry would. The SAME traceparent rides the retry leg,
            # so both attempts stitch into one trace.
            target = target_list[(i + 1) % len(target_list)]
            t1 = time.monotonic()
            status, rid, out_digest = _send(target, body, hdrs, timeout)
            with lock:
                failovers[0] += 1
                _record_attempt(target, status, time.monotonic() - t1)
        dt = time.monotonic() - t0
        with lock:
            results[i] = (status, dt, rid, trace_id, target)
            if expect_digest is not None:
                if expect_digest == "":
                    # the record itself carries no digest to compare:
                    # unverified, visibly
                    digest_stats["unverified"] += 1
                elif status == 200:
                    # the determinism check: a 200 whose digest header
                    # is absent or different means the server's output
                    # for this exact payload CHANGED since capture
                    digest_stats["checked"] += 1
                    if out_digest != expect_digest:
                        digest_stats["mismatches"] += 1
                elif status in (429, 503, 504, "error"):
                    # shed/transport: never reached the scoring path —
                    # unverified, not a verdict (counted so the gate
                    # is never silently partial)
                    digest_stats["unverified"] += 1
                else:
                    # 400/5xx to a payload that scored 200 at capture:
                    # the rollout now FAILS this request — that is a
                    # score change, not an environmental outcome
                    digest_stats["checked"] += 1
                    digest_stats["mismatches"] += 1
        if on_result is not None:
            on_result(i, status, dt)

    replay_list = (list(replay_records) if replay_records is not None
                   else None)
    replay_skipped = 0
    t_start = time.monotonic()
    t_end = t_start + duration_s
    next_arrival = t_start
    i = 0
    while stop is None or not stop.is_set():
        if replay_list is None:
            if next_arrival >= t_end:
                break
        elif i + replay_skipped >= len(replay_list):
            break
        delay = next_arrival - time.monotonic()
        if delay > 0:
            time.sleep(delay)
        expect_digest = None
        if replay_list is not None:
            # recorded order, same Poisson clock: the replay offers
            # the incident's payloads at a controlled rate, not the
            # incident's (possibly pathological) arrival pattern
            rec = replay_list[i + replay_skipped]
            body = _record_payload(rec)
            if body is None:
                replay_skipped += 1
                continue
            if rec.get("status_code") == 200:
                # "" = a 200 record with no recorded digest (trimmed /
                # older-format file): counted unverified in the
                # sender, never silently skipped
                expect_digest = rec.get("output_digest") or ""
            rtid = str(rec.get("trace_id") or "")
            trace_id = (rtid if len(rtid) == 32
                        and all(c in "0123456789abcdef" for c in rtid)
                        else "%032x" % (rng.getrandbits(128) or 1))
        else:
            body = json.dumps(
                payload_fn(i, shapes[i % len(shapes)])).encode()
            # one trace per scheduled arrival (or-1 guards the 2^-128
            # all-zero draw the W3C grammar forbids); deterministic
            # under --seed like the schedule itself
            trace_id = "%032x" % (rng.getrandbits(128) or 1)
        traceparent = "00-%s-%016x-01" % (trace_id,
                                          rng.getrandbits(64) or 1)
        with lock:
            results.append(None)
        t = threading.Thread(target=sender,
                             args=(i, body, trace_id, traceparent,
                                   expect_digest),
                             daemon=True)
        t.start()
        senders.append(t)
        i += 1
        # open loop: the NEXT arrival is clocked off the schedule, not
        # off this request's completion
        next_arrival += rng.expovariate(rps)
    # multi-target senders may legally spend a full socket timeout on
    # the first attempt (a killed replica that drops packets instead
    # of RSTing) and another on the failover retry — the join window
    # must cover both legs or a still-retrying request is miscounted
    # as the one forbidden outcome ("hung")
    join_wait = (2 * timeout if len(target_list) > 1 else timeout) + 10.0
    for t in senders:
        t.join(timeout=join_wait)
    wall = time.monotonic() - t_start

    by_status: Dict[str, int] = {}
    ok_lat: List[float] = []
    all_lat: List[float] = []
    terminal: List[Tuple[Any, float, Optional[str], str, str]] = []
    hung = 0
    with lock:
        snapshot = list(results)
    for rec in snapshot:
        if rec is None:
            hung += 1  # sender never recorded: the one forbidden outcome
            continue
        status, dt, _rid, _tid, _target = rec
        terminal.append(rec)
        by_status[str(status)] = by_status.get(str(status), 0) + 1
        all_lat.append(dt)
        if status == 200:
            ok_lat.append(dt)
    ok_lat.sort()
    all_lat.sort()
    # the operator's jump-off: top-N slowest terminal requests, each
    # with the keys that resolve it — /span/<rid> on the replica,
    # GET /fleet/trace/<trace_id> on the controller (chaos_check's
    # fleet phase consumes exactly this array)
    slowest = [
        {"rid": rid, "trace_id": tid, "latency_s": round(dt, 6),
         "status": str(status), "target": target}
        for status, dt, rid, tid, target in
        sorted(terminal, key=lambda r: r[1],
               reverse=True)[:max(0, slowest_n)]]
    summary = {
        "scheduled": i,
        "hung": hung,
        "by_status": by_status,
        "offered_rps": rps,
        "achieved_rps": i / wall if wall > 0 else 0.0,
        "goodput_rps": len(ok_lat) / wall if wall > 0 else 0.0,
        "wall_s": wall,
        "latency_ok_s": {q: percentile(ok_lat, q)
                         for q in (50.0, 95.0, 99.0)},
        "latency_all_s": {q: percentile(all_lat, q)
                          for q in (50.0, 95.0, 99.0)},
        "slowest": slowest,
    }
    if replay_list is not None:
        with lock:
            summary["replayed"] = i
            summary["replay_skipped"] = replay_skipped
            summary["digest_checked"] = digest_stats["checked"]
            summary["digest_mismatches"] = digest_stats["mismatches"]
            summary["digest_unverified"] = digest_stats["unverified"]
    if len(target_list) > 1 or targets:
        with lock:
            summary["failover_retries"] = failovers[0]
            summary["per_target"] = {
                t: {
                    "by_status": dict(rec["by_status"]),
                    "latency_ok_s": {
                        q: percentile(sorted(rec["ok_lat"]), q)
                        for q in (50.0, 95.0, 99.0)},
                } for t, rec in per_target.items()}
    return summary


def _decode_prompt(i: int, prompt_len: int) -> List[int]:
    """Deterministic token-id prompt for sequence ``i`` — like
    :func:`_default_payload`, pure in ``i`` so two runs against
    deterministic greedy decode can compare streams byte for byte."""
    return [(i * 7 + k * 3) % 50 + 1 for k in range(prompt_len)]


def run_decode_load(url: str, rps: float, duration_s: float,
                    prompt_lens: Sequence[int] = (4, 12, 24),
                    output_lens: Sequence[int] = (8, 16, 32),
                    deadline_ms: Optional[float] = None,
                    timeout: float = 60.0,
                    seed: Optional[int] = None,
                    stop: Optional[threading.Event] = None
                    ) -> Dict[str, Any]:
    """Open-loop Poisson *sequence* arrivals against a decode-mode
    server's ``POST /generate`` (``--decode``).

    Each arrival samples a prompt length and an output budget from the
    given mixes (cycled over the arrival sequence, deterministic under
    ``seed``) and opens a STREAMED request; the reader timestamps every
    NDJSON token line as it lands, so the summary reports what a decode
    deployment is actually judged on:

    - **TTFT** (time to first token): send -> first token line, p50/95/99
      — admission wait + prefill, the interactive-feel number;
    - **ITL** (inter-token latency): gaps between consecutive token
      lines, pooled across sequences, p50/95/99 — the steady-state
      decode step rate as one sequence experiences it under the
      continuous batch;
    - **tokens/s**: total streamed tokens over wall time — the
      throughput headline bench.py's ``decode_serving`` group A/Bs.

    The final stream line's ``digest`` (the canonical-reply sha256 the
    server also emits non-streamed) and ``finish_reason`` are recorded
    per sequence; open-loop semantics, join discipline, and the "every
    scheduled sequence reaches a terminal record" assertion surface
    match :func:`run_load`."""
    rng = random.Random(seed)
    prompt_lens = list(prompt_lens) or [4]
    output_lens = list(output_lens) or [16]
    results: List[Optional[Dict[str, Any]]] = []
    senders: List[threading.Thread] = []
    lock = make_lock("loadgen:lock")

    def sender(i: int, body: bytes, traceparent: str):
        hdrs = {"Content-Type": "application/json",
                "traceparent": traceparent}
        if deadline_ms is not None:
            hdrs["X-Deadline-Ms"] = str(deadline_ms)
        rec: Dict[str, Any] = {"status": "error", "tokens": 0,
                               "ttft_s": None, "itl_s": [],
                               "finish_reason": None, "digest": None,
                               "rid": None}
        t0 = time.monotonic()
        req = urllib.request.Request(url, data=body, method="POST",
                                     headers=hdrs)
        try:
            with urllib.request.urlopen(req, timeout=timeout) as r:
                rec["status"] = r.status
                rec["rid"] = r.headers.get("X-Request-Id")
                last = t0
                while True:
                    line = r.readline()
                    if not line:
                        break
                    now = time.monotonic()
                    try:
                        obj = json.loads(line)
                    except json.JSONDecodeError:
                        continue
                    if obj.get("done"):
                        rec["finish_reason"] = obj.get("finish_reason")
                        rec["digest"] = obj.get("digest")
                        break
                    if "t" in obj:
                        if rec["ttft_s"] is None:
                            rec["ttft_s"] = now - t0
                        else:
                            rec["itl_s"].append(now - last)
                        rec["tokens"] += 1
                        last = now
        except urllib.error.HTTPError as e:
            try:
                e.read()
            except Exception:  # noqa: BLE001 - best-effort drain
                pass
            rec["status"] = e.code
            if e.headers is not None:
                rec["rid"] = e.headers.get("X-Request-Id")
        except Exception:  # noqa: BLE001 - refused/reset/socket timeout
            pass
        rec["latency_s"] = time.monotonic() - t0
        with lock:
            results[i] = rec

    t_start = time.monotonic()
    t_end = t_start + duration_s
    next_arrival = t_start
    i = 0
    while (stop is None or not stop.is_set()) and next_arrival < t_end:
        delay = next_arrival - time.monotonic()
        if delay > 0:
            time.sleep(delay)
        p_len = prompt_lens[i % len(prompt_lens)]
        o_len = output_lens[i % len(output_lens)]
        body = json.dumps({"tokens": _decode_prompt(i, p_len),
                           "max_new_tokens": o_len,
                           "stream": True}).encode()
        traceparent = "00-%032x-%016x-01" % (rng.getrandbits(128) or 1,
                                             rng.getrandbits(64) or 1)
        with lock:
            results.append(None)
        t = threading.Thread(target=sender, args=(i, body, traceparent),
                             daemon=True)
        t.start()
        senders.append(t)
        i += 1
        next_arrival += rng.expovariate(rps)
    for t in senders:
        t.join(timeout=timeout + 10.0)
    wall = time.monotonic() - t_start

    by_status: Dict[str, int] = {}
    reasons: Dict[str, int] = {}
    ttfts: List[float] = []
    itls: List[float] = []
    total_tokens = 0
    hung = 0
    with lock:
        snapshot = list(results)
    for rec in snapshot:
        if rec is None:
            hung += 1
            continue
        by_status[str(rec["status"])] = \
            by_status.get(str(rec["status"]), 0) + 1
        if rec["finish_reason"]:
            reasons[rec["finish_reason"]] = \
                reasons.get(rec["finish_reason"], 0) + 1
        total_tokens += rec["tokens"]
        if rec["ttft_s"] is not None:
            ttfts.append(rec["ttft_s"])
        itls.extend(rec["itl_s"])
    ttfts.sort()
    itls.sort()
    return {
        "mode": "decode",
        "scheduled": i,
        "hung": hung,
        "by_status": by_status,
        "finish_reasons": reasons,
        "offered_rps": rps,
        "achieved_rps": i / wall if wall > 0 else 0.0,
        "wall_s": wall,
        "tokens": total_tokens,
        "tokens_per_s": total_tokens / wall if wall > 0 else 0.0,
        "ttft_s": {q: percentile(ttfts, q) for q in (50.0, 95.0, 99.0)},
        "itl_s": {q: percentile(itls, q) for q in (50.0, 95.0, 99.0)},
    }


def _json_finite(obj: Any) -> Any:
    """Replace non-finite floats with None so the results file is
    strict RFC-8259 JSON — ``json.dump`` would otherwise emit a bare
    ``NaN`` token (e.g. the p99 of a zero-success run), breaking every
    strict consumer exactly on the failure runs the file matters for."""
    if isinstance(obj, float):
        return obj if obj == obj and abs(obj) != float("inf") else None
    if isinstance(obj, dict):
        return {k: _json_finite(v) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        return [_json_finite(v) for v in obj]
    return obj


def evaluate_slo(summary: Dict[str, Any],
                 slo_p99_ms: Optional[float] = None,
                 slo_availability: Optional[float] = None
                 ) -> Optional[Dict[str, Any]]:
    """SLO verdict over a :func:`run_load` summary; None when no
    objective was given. Availability is strict: 200s over every
    SCHEDULED request, so socket errors and hung senders count against
    the target (an unanswered request is an availability loss whatever
    the transport did). p99 is over successful replies — shed replies
    are availability losses, not latency samples — and a run with zero
    successes fails a p99 objective outright (NaN must not pass)."""
    if slo_p99_ms is None and slo_availability is None:
        return None
    verdict: Dict[str, Any] = {"pass": True}
    if slo_p99_ms is not None:
        p99_ms = summary["latency_ok_s"][99.0] * 1e3
        ok = (summary["by_status"].get("200", 0) > 0
              and p99_ms == p99_ms and p99_ms <= slo_p99_ms)
        verdict["p99"] = {"target_ms": slo_p99_ms,
                          "observed_ms": (round(p99_ms, 3)
                                          if p99_ms == p99_ms else None),
                          "pass": ok}
        verdict["pass"] = verdict["pass"] and ok
    if slo_availability is not None:
        n = summary["scheduled"]
        avail = (summary["by_status"].get("200", 0) / n) if n else 1.0
        ok = avail >= slo_availability
        verdict["availability"] = {"target": slo_availability,
                                   "observed": round(avail, 6),
                                   "pass": ok}
        verdict["pass"] = verdict["pass"] and ok
    return verdict


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--url", default=None,
                    help="single endpoint (or use --targets)")
    ap.add_argument("--targets", default=None,
                    help="comma-separated endpoints: ONE open-loop "
                         "arrival clock round-robins across them with "
                         "per-target status/latency breakdown in the "
                         "summary — the fleet chaos phase's LB "
                         "stand-in (socket-dead sends retry once on "
                         "the next target)")
    ap.add_argument("--payload-key", default="x",
                    help="JSON field name the feature vector rides "
                         "under (the serving model pipeline expects "
                         "'features'; default 'x')")
    ap.add_argument("--replay", default=None, metavar="CAPTURE_JSONL",
                    help="drive the payloads of a capture file "
                         "(runtime/capture.py) in recorded order "
                         "through the open-loop clock and verify each "
                         "reply's X-Output-Digest against the record "
                         "(digest_mismatches in the summary; nonzero "
                         "exits 2)")
    ap.add_argument("--decode", action="store_true",
                    help="decode mode: open-loop Poisson SEQUENCE "
                         "arrivals against a decode-mode server's "
                         "POST /generate — streamed-reply reader, "
                         "TTFT / inter-token-latency p50/p95/p99 and "
                         "tokens/s in the summary (--url should point "
                         "at the /generate endpoint)")
    ap.add_argument("--prompt-lens", default="4,12,24",
                    help="decode mode: comma-separated prompt token "
                         "lengths the arrival sequence cycles through")
    ap.add_argument("--output-lens", default="8,16,32",
                    help="decode mode: comma-separated max_new_tokens "
                         "budgets the arrival sequence cycles through")
    ap.add_argument("--rps", type=float, default=50.0)
    ap.add_argument("--duration", type=float, default=10.0)
    ap.add_argument("--shapes", default="2",
                    help="comma-separated feature-vector lengths the "
                         "arrival sequence cycles through")
    ap.add_argument("--deadline-ms", type=float, default=None)
    ap.add_argument("--timeout", type=float, default=30.0)
    ap.add_argument("--seed", type=int, default=None)
    ap.add_argument("--json", action="store_true",
                    help="emit the raw summary dict as JSON")
    ap.add_argument("--out", default=None,
                    help="write the summary dict (plus any SLO "
                         "verdict) as JSON to this file — the "
                         "machine-readable surface CI consumes")
    ap.add_argument("--slo-p99-ms", type=float, default=None,
                    help="assert p99 latency over successful replies "
                         "is at or under this many ms (violation: "
                         "exit 2)")
    ap.add_argument("--slo-availability", type=float, default=None,
                    help="assert this fraction of SCHEDULED requests "
                         "replied 200 (socket errors count against; "
                         "violation: exit 2)")
    args = ap.parse_args(argv)
    targets = [t.strip() for t in (args.targets or "").split(",")
               if t.strip()] or None
    if not args.url and not targets:
        ap.error("one of --url / --targets is required")
    shapes = [int(s) for s in args.shapes.split(",") if s.strip()]
    key = args.payload_key

    def payload(i: int, shape: int) -> Dict[str, Any]:
        return {key: _default_payload(i, shape)["x"]}

    if args.decode:
        if not args.url:
            ap.error("--decode requires --url (the /generate endpoint)")
        summary = run_decode_load(
            args.url, args.rps, args.duration,
            prompt_lens=[int(s) for s in args.prompt_lens.split(",")
                         if s.strip()],
            output_lens=[int(s) for s in args.output_lens.split(",")
                         if s.strip()],
            deadline_ms=args.deadline_ms, timeout=args.timeout,
            seed=args.seed)
        if args.out:
            with open(args.out, "w", encoding="utf-8") as fh:
                json.dump(_json_finite(summary), fh, indent=2)
        if args.json:
            print(json.dumps(_json_finite(summary), indent=2))
        else:
            print(f"scheduled={summary['scheduled']} "
                  f"hung={summary['hung']} "
                  f"by_status={summary['by_status']} "
                  f"finish={summary['finish_reasons']}")
            print(f"offered={summary['offered_rps']:.1f}seq/s "
                  f"achieved={summary['achieved_rps']:.1f}seq/s "
                  f"tokens/s={summary['tokens_per_s']:.1f}")
            for label, key in (("ttft", "ttft_s"), ("itl", "itl_s")):
                vals = summary[key]
                print(f"{label}: " + "  ".join(
                    f"p{q:.0f}={vals[q] * 1e3:.2f}ms"
                    for q in (50.0, 95.0, 99.0)))
        return 1 if summary["hung"] else 0

    replay_records = None
    if args.replay:
        try:
            replay_records = load_capture_records(args.replay)
        except OSError as e:
            ap.error(f"--replay {args.replay}: {e}")
        if not replay_records:
            ap.error(f"--replay {args.replay}: no records")
    summary = run_load(args.url, args.rps, args.duration, shapes,
                       deadline_ms=args.deadline_ms,
                       timeout=args.timeout, seed=args.seed,
                       payload_fn=payload, targets=targets,
                       replay_records=replay_records)
    slo = evaluate_slo(summary, args.slo_p99_ms, args.slo_availability)
    if slo is not None:
        summary["slo"] = slo
    if args.out:
        with open(args.out, "w", encoding="utf-8") as fh:
            json.dump(_json_finite(summary), fh, indent=2)
    if args.json:
        print(json.dumps(_json_finite(summary), indent=2))
    else:
        lat = summary["latency_ok_s"]
        print(f"scheduled={summary['scheduled']} hung={summary['hung']} "
              f"by_status={summary['by_status']}")
        print(f"offered={summary['offered_rps']:.1f}rps "
              f"achieved={summary['achieved_rps']:.1f}rps "
              f"goodput={summary['goodput_rps']:.1f}rps")
        print("latency(200s): " + "  ".join(
            f"p{q:.0f}={lat[q] * 1e3:.2f}ms" for q in (50.0, 95.0, 99.0)))
        if replay_records is not None:
            print(f"replay: {summary['replayed']} records, "
                  f"digest_checked={summary['digest_checked']} "
                  f"digest_mismatches={summary['digest_mismatches']} "
                  f"digest_unverified={summary['digest_unverified']}")
        if slo is not None:
            print(f"slo: {'PASS' if slo['pass'] else 'FAIL'} {slo}")
    if summary["hung"]:
        return 1
    if replay_records is not None and not summary.get("digest_checked"):
        # zero verified digests = the gate compared NOTHING (endpoint
        # down, every reply shed, or a capture with no 200 records):
        # a vacuous pass must not read as "the rollout changed no
        # scores"
        print("replay verification vacuous: 0 digests checked "
              f"(by_status={summary['by_status']})")
        return 2
    if summary.get("digest_mismatches"):
        return 2
    return 0 if slo is None or slo["pass"] else 2


if __name__ == "__main__":
    raise SystemExit(main())
