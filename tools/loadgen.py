"""Open-loop Poisson load generator for the serving stack.

Open-loop means arrivals are scheduled by the CLOCK, not by completions:
a slow or shedding server does not slow the offered rate down, which is
the only way to observe real saturation behavior (a closed-loop client
self-throttles and hides it — the coordinated-omission trap). Arrival
gaps are exponential (Poisson process) and every request runs on its
own sender thread, so in-flight requests never gate the next arrival.

Mixed shapes: each request's payload feature vector length cycles
through ``shapes`` (weighted round-robin over the arrival sequence), so
the server's bucket ladder / padding paths are exercised the way mixed
production traffic would.

Reported: per-status counts, latency percentiles (p50/p95/p99) over
successful (200) replies and over ALL terminal replies, goodput
(200s/sec of wall time), offered vs achieved request rate. A request
that errors at the socket level (refused, reset, timed out) is counted
under ``"error"`` — the assertion surface for "zero hangs, zero silent
drops" is that every scheduled request reaches SOME terminal record.

Usage (also importable: :func:`run_load` drives the chaos CI scenarios
in tools/ci/chaos_check.py)::

    python tools/loadgen.py --url http://127.0.0.1:8898/ \
        --rps 200 --duration 10 --shapes 2,8,32 [--deadline-ms 250] \
        [--seed 7] [--json]
"""
from __future__ import annotations

import argparse
import json
import random
import threading
import time
import urllib.error
import urllib.request
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple


def _default_payload(i: int, shape: int) -> Dict[str, Any]:
    """``{"x": [...]}`` of ``shape`` floats, deterministic in ``i`` —
    a scorer that computes a pure function of x lets the caller verify
    bit-identical replies (the failover acceptance check)."""
    return {"x": [float((i + k) % 7) for k in range(shape)]}


def percentile(sorted_vals: Sequence[float], q: float) -> float:
    """Nearest-rank percentile over an already-sorted sequence."""
    if not sorted_vals:
        return float("nan")
    k = max(0, min(len(sorted_vals) - 1,
                   int(round(q / 100.0 * (len(sorted_vals) - 1)))))
    return sorted_vals[k]


def _send(url: str, body: bytes, headers: Dict[str, str],
          timeout: float) -> Tuple[Any, Optional[bytes]]:
    req = urllib.request.Request(url, data=body, method="POST",
                                 headers=headers)
    try:
        with urllib.request.urlopen(req, timeout=timeout) as r:
            return r.status, r.read()
    except urllib.error.HTTPError as e:
        # explicit non-2xx IS a terminal reply (shed/drain/error paths);
        # read drains the connection so keep-alive sockets recycle
        try:
            e.read()
        except Exception:  # noqa: BLE001 - best-effort drain
            pass
        return e.code, None
    except Exception:  # noqa: BLE001 - refused/reset/socket timeout
        return "error", None


def run_load(url: str, rps: float, duration_s: float,
             shapes: Sequence[int] = (2,),
             deadline_ms: Optional[float] = None,
             timeout: float = 30.0,
             seed: Optional[int] = None,
             payload_fn: Callable[[int, int], Any] = _default_payload,
             on_result: Optional[Callable[[int, Any, float], None]] = None,
             stop: Optional[threading.Event] = None) -> Dict[str, Any]:
    """Drive ``rps`` Poisson arrivals against ``url`` for ``duration_s``
    seconds; block until every sender reaches a terminal record; return
    the summary dict. ``seed`` makes the arrival schedule and shape
    sequence deterministic (the payloads already are). ``on_result(i,
    status, latency_s)`` observes each completion (chaos checks hook
    assertions here); ``stop`` aborts scheduling early (senders already
    launched still complete)."""
    rng = random.Random(seed)
    headers = {"Content-Type": "application/json"}
    if deadline_ms is not None:
        headers["X-Deadline-Ms"] = str(deadline_ms)
    shapes = list(shapes) or [2]

    results: List[Optional[Tuple[Any, float]]] = []
    senders: List[threading.Thread] = []
    lock = threading.Lock()

    def sender(i: int, body: bytes):
        t0 = time.monotonic()
        status, _ = _send(url, body, headers, timeout)
        dt = time.monotonic() - t0
        with lock:
            results[i] = (status, dt)
        if on_result is not None:
            on_result(i, status, dt)

    t_start = time.monotonic()
    t_end = t_start + duration_s
    next_arrival = t_start
    i = 0
    while next_arrival < t_end and (stop is None or not stop.is_set()):
        delay = next_arrival - time.monotonic()
        if delay > 0:
            time.sleep(delay)
        body = json.dumps(
            payload_fn(i, shapes[i % len(shapes)])).encode()
        with lock:
            results.append(None)
        t = threading.Thread(target=sender, args=(i, body), daemon=True)
        t.start()
        senders.append(t)
        i += 1
        # open loop: the NEXT arrival is clocked off the schedule, not
        # off this request's completion
        next_arrival += rng.expovariate(rps)
    for t in senders:
        t.join(timeout=timeout + 10.0)
    wall = time.monotonic() - t_start

    by_status: Dict[str, int] = {}
    ok_lat: List[float] = []
    all_lat: List[float] = []
    hung = 0
    with lock:
        snapshot = list(results)
    for rec in snapshot:
        if rec is None:
            hung += 1  # sender never recorded: the one forbidden outcome
            continue
        status, dt = rec
        by_status[str(status)] = by_status.get(str(status), 0) + 1
        all_lat.append(dt)
        if status == 200:
            ok_lat.append(dt)
    ok_lat.sort()
    all_lat.sort()
    return {
        "scheduled": i,
        "hung": hung,
        "by_status": by_status,
        "offered_rps": rps,
        "achieved_rps": i / wall if wall > 0 else 0.0,
        "goodput_rps": len(ok_lat) / wall if wall > 0 else 0.0,
        "wall_s": wall,
        "latency_ok_s": {q: percentile(ok_lat, q)
                         for q in (50.0, 95.0, 99.0)},
        "latency_all_s": {q: percentile(all_lat, q)
                          for q in (50.0, 95.0, 99.0)},
    }


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--url", required=True)
    ap.add_argument("--rps", type=float, default=50.0)
    ap.add_argument("--duration", type=float, default=10.0)
    ap.add_argument("--shapes", default="2",
                    help="comma-separated feature-vector lengths the "
                         "arrival sequence cycles through")
    ap.add_argument("--deadline-ms", type=float, default=None)
    ap.add_argument("--timeout", type=float, default=30.0)
    ap.add_argument("--seed", type=int, default=None)
    ap.add_argument("--json", action="store_true",
                    help="emit the raw summary dict as JSON")
    args = ap.parse_args(argv)
    shapes = [int(s) for s in args.shapes.split(",") if s.strip()]
    summary = run_load(args.url, args.rps, args.duration, shapes,
                       deadline_ms=args.deadline_ms,
                       timeout=args.timeout, seed=args.seed)
    if args.json:
        print(json.dumps(summary, indent=2))
    else:
        lat = summary["latency_ok_s"]
        print(f"scheduled={summary['scheduled']} hung={summary['hung']} "
              f"by_status={summary['by_status']}")
        print(f"offered={summary['offered_rps']:.1f}rps "
              f"achieved={summary['achieved_rps']:.1f}rps "
              f"goodput={summary['goodput_rps']:.1f}rps")
        print("latency(200s): " + "  ".join(
            f"p{q:.0f}={lat[q] * 1e3:.2f}ms" for q in (50.0, 95.0, 99.0)))
    return 1 if summary["hung"] else 0


if __name__ == "__main__":
    raise SystemExit(main())
