"""ai.onnx.ml domain: tree ensembles + classical-ML ops, and the
reference's flagship ONNX workload end-to-end (LightGBM -> ONNX ->
ONNXModel, ref: notebooks/ONNX - Inference on Spark.ipynb).
"""
import numpy as np
import pytest

from synapseml_tpu.data.table import Table
from synapseml_tpu.gbdt.estimators import (LightGBMClassifier,
                                           LightGBMRegressor)
from synapseml_tpu.onnx import (GraphBuilder, ONNXModel, convert_lightgbm,
                                import_model)


def _binary_data(n=500, d=6, seed=0):
    rng = np.random.default_rng(seed)
    x = rng.normal(size=(n, d)).astype(np.float32)
    y = (x[:, 0] - 0.5 * x[:, 1] + 0.2 * rng.normal(size=n) > 0).astype(
        np.float64)
    return x, y


# ---------------------------------------------------------------------------
# the notebook path: train -> convert -> import -> score
# ---------------------------------------------------------------------------

def test_lightgbm_binary_to_onnx_matches_booster():
    x, y = _binary_data()
    model = LightGBMClassifier(num_iterations=20, num_leaves=15).fit(
        Table({"features": x, "label": y}))
    blob = convert_lightgbm(model)
    g = import_model(blob)
    label, probs = g.apply(g.params, x)
    want = model.booster.predict(x)
    np.testing.assert_allclose(np.asarray(probs)[:, 1], want, atol=1e-5)
    np.testing.assert_array_equal(
        np.asarray(label), (want > 0.5).astype(np.int64))


def test_lightgbm_multiclass_to_onnx_matches_booster():
    rng = np.random.default_rng(3)
    x = rng.normal(size=(400, 5)).astype(np.float32)
    y = np.argmax(x[:, :3] + 0.1 * rng.normal(size=(400, 3)), axis=1).astype(
        np.float64)
    model = LightGBMClassifier(num_iterations=12, num_leaves=7,
                               objective="multiclass").fit(
        Table({"features": x, "label": y}))
    blob = convert_lightgbm(model)
    g = import_model(blob)
    label, probs = g.apply(g.params, x)
    want = model.booster.predict(x)
    np.testing.assert_allclose(np.asarray(probs), want, atol=1e-5)
    np.testing.assert_array_equal(np.asarray(label), want.argmax(-1))


def test_lightgbm_regressor_to_onnx_matches_booster():
    rng = np.random.default_rng(5)
    x = rng.normal(size=(300, 4)).astype(np.float32)
    y = (x[:, 0] * 2 - x[:, 2]).astype(np.float64)
    model = LightGBMRegressor(num_iterations=15, num_leaves=15).fit(
        Table({"features": x, "label": y}))
    blob = convert_lightgbm(model)
    g = import_model(blob)
    (pred,) = g.apply(g.params, x)
    np.testing.assert_allclose(np.asarray(pred)[:, 0],
                               model.booster.predict(x), atol=1e-4)


def test_goss_and_nan_features_roundtrip():
    """GOSS boosting + missing values: NaN takes the false branch in both
    engines (grower.predict_tree NaN-comparisons-False convention)."""
    x, y = _binary_data(seed=7)
    x[::17, 2] = np.nan
    model = LightGBMClassifier(num_iterations=15, num_leaves=7,
                               boosting_type="goss").fit(
        Table({"features": x, "label": y}))
    blob = convert_lightgbm(model)
    g = import_model(blob)
    _, probs = g.apply(g.params, x)
    np.testing.assert_allclose(np.asarray(probs)[:, 1],
                               model.booster.predict(x), atol=1e-5)


def test_onnx_model_transformer_notebook_flow():
    """The full ONNXModel path with feed/fetch-style columns
    (ref notebook: setFeedDict input->features, fetch probabilities)."""
    x, y = _binary_data(seed=11)
    est = LightGBMClassifier(num_iterations=10, num_leaves=7)
    model = est.fit(Table({"features": x, "label": y}))
    onnx_ml = ONNXModel(model_bytes=convert_lightgbm(model),
                        feed_dict={"input": "features"},
                        mini_batch_size=128)
    out = onnx_ml.transform(Table({"features": x}))
    probs = np.stack([np.asarray(v) for v in out["probabilities"]]) \
        if out["probabilities"].dtype == object \
        else np.asarray(out["probabilities"])
    np.testing.assert_allclose(probs[:, 1], model.booster.predict(x),
                               atol=1e-5)


# ---------------------------------------------------------------------------
# classical-ML op unit tests
# ---------------------------------------------------------------------------

def _ml_graph(op, in_shape, out_shape, out_dtype=np.float32, n_outputs=1,
              extra_inputs=(), **attrs):
    g = GraphBuilder(opset=17)
    x = g.add_input("x", np.float32, list(in_shape))
    ins = [x]
    for nm, arr in extra_inputs:
        ins.append(g.add_initializer(nm, arr))
    outs = [f"o{i}" for i in range(n_outputs)]
    g.add_node(op, ins, outputs=outs, domain="ai.onnx.ml", **attrs)
    for o in outs:
        g.add_output(o, out_dtype, list(out_shape))
    return import_model(g.to_bytes())


def test_scaler_normalizer_binarizer_imputer():
    x = np.array([[1.0, -2.0, np.nan], [4.0, 0.0, 2.0]], np.float32)

    g = _ml_graph("Scaler", ["N", 3], ["N", 3],
                  offset=[1.0, 0.0, 0.0], scale=[2.0, 1.0, 1.0])
    np.testing.assert_allclose(
        np.asarray(g.apply(g.params, np.nan_to_num(x)))[0][0],
        [(1 - 1) * 2, -2.0, 0.0])

    g = _ml_graph("Imputer", ["N", 3], ["N", 3],
                  imputed_value_floats=[9.0, 9.0, 9.0])
    out = np.asarray(g.apply(g.params, x)[0])
    assert out[0, 2] == 9.0 and out[1, 2] == 2.0

    g = _ml_graph("Binarizer", ["N", 3], ["N", 3], threshold=0.5)
    np.testing.assert_allclose(
        np.asarray(g.apply(g.params, np.nan_to_num(x))[0]),
        [[1, 0, 0], [1, 0, 1]])

    g = _ml_graph("Normalizer", ["N", 3], ["N", 3], norm="L2")
    out = np.asarray(g.apply(g.params, np.nan_to_num(x))[0])
    np.testing.assert_allclose(np.linalg.norm(out, axis=1), 1.0, rtol=1e-5)


def test_linear_classifier_and_regressor():
    x = np.array([[1.0, 0.0], [0.0, 2.0], [-1.0, -1.0]], np.float32)
    g = _ml_graph("LinearClassifier", ["N", 2], ["N", 2],
                  n_outputs=2, out_dtype=np.float32,
                  coefficients=[1.0, -1.0], intercepts=[0.1],
                  classlabels_int64s=[0, 1], post_transform="LOGISTIC")
    label, probs = g.apply(g.params, x)
    s = x @ np.array([1.0, -1.0], np.float32) + 0.1
    p = 1 / (1 + np.exp(-s))
    np.testing.assert_allclose(np.asarray(probs)[:, 1], p, rtol=1e-5)
    np.testing.assert_array_equal(np.asarray(label), (p > 0.5).astype(int))

    g = _ml_graph("LinearRegressor", ["N", 2], ["N", 1],
                  coefficients=[2.0, 0.5], intercepts=[1.0])
    out = np.asarray(g.apply(g.params, x)[0])
    np.testing.assert_allclose(out[:, 0], x @ [2.0, 0.5] + 1.0, rtol=1e-5)


def test_array_feature_extractor_and_vectorizer():
    x = np.arange(12, dtype=np.float32).reshape(3, 4)
    g = _ml_graph("ArrayFeatureExtractor", ["N", 4], ["N", 2],
                  extra_inputs=[("idx", np.array([2, 0], np.int64))])
    np.testing.assert_allclose(np.asarray(g.apply(g.params, x)[0]),
                               x[:, [2, 0]])

    gb = GraphBuilder(opset=17)
    a = gb.add_input("a", np.float32, ["N", 2])
    b = gb.add_input("b", np.float32, ["N", 1])
    out = gb.add_node("FeatureVectorizer", [a, b], domain="ai.onnx.ml",
                      inputdimensions=[2, 1])
    gb.add_output(out, np.float32, ["N", 3])
    g = import_model(gb.to_bytes())
    av = np.ones((2, 2), np.float32)
    bv = np.full((2, 1), 5.0, np.float32)
    np.testing.assert_allclose(np.asarray(g.apply(g.params, av, bv)[0]),
                               [[1, 1, 5]] * 2)


def test_label_encoder_and_onehot():
    g = _ml_graph("LabelEncoder", ["N"], ["N"], out_dtype=np.int64,
                  keys_int64s=[10, 20, 30], values_int64s=[0, 1, 2],
                  default_int64=-1)
    x = np.array([20, 10, 99], np.int64)
    np.testing.assert_array_equal(
        np.asarray(g.apply(g.params, x)[0]), [1, 0, -1])

    g = _ml_graph("OneHotEncoder", ["N"], ["N", 3],
                  cats_int64s=[3, 5, 7])
    x = np.array([5, 7, 4], np.int64)
    np.testing.assert_allclose(
        np.asarray(g.apply(g.params, x)[0]),
        [[0, 1, 0], [0, 0, 1], [0, 0, 0]])


def test_tree_modes_beyond_leq():
    """Hand-built ensemble exercising BRANCH_GT and missing-tracks-true."""
    g = _ml_graph(
        "TreeEnsembleRegressor", ["N", 1], ["N", 1],
        nodes_treeids=[0, 0, 0], nodes_nodeids=[0, 1, 2],
        nodes_featureids=[0, 0, 0], nodes_modes=["BRANCH_GT", "LEAF", "LEAF"],
        nodes_values=[1.5, 0.0, 0.0],
        nodes_truenodeids=[1, 1, 2], nodes_falsenodeids=[2, 1, 2],
        nodes_missing_value_tracks_true=[1, 0, 0],
        target_treeids=[0, 0], target_nodeids=[1, 2], target_ids=[0, 0],
        target_weights=[10.0, 20.0], n_targets=1)
    x = np.array([[2.0], [1.0], [np.nan]], np.float32)
    out = np.asarray(g.apply(g.params, x)[0])[:, 0]
    # x>1.5 -> true(10); else false(20); NaN tracks true -> 10
    np.testing.assert_allclose(out, [10.0, 20.0, 10.0])


def test_binary_single_score_on_class_id_one():
    """Spec-valid binary ensembles may scatter the single score into
    class_id 1 (review finding: the [:1] slice dropped it)."""
    g = _ml_graph(
        "TreeEnsembleClassifier", ["N", 1], ["N", 2], n_outputs=2,
        nodes_treeids=[0, 0, 0], nodes_nodeids=[0, 1, 2],
        nodes_featureids=[0, 0, 0],
        nodes_modes=["BRANCH_LEQ", "LEAF", "LEAF"],
        nodes_values=[0.0, 0.0, 0.0],
        nodes_truenodeids=[1, 1, 2], nodes_falsenodeids=[2, 1, 2],
        class_treeids=[0, 0], class_nodeids=[1, 2], class_ids=[1, 1],
        class_weights=[-2.0, 2.0], classlabels_int64s=[0, 1],
        post_transform="LOGISTIC")
    x = np.array([[-1.0], [1.0]], np.float32)
    _, probs = g.apply(g.params, x)
    sig = 1 / (1 + np.exp(-np.array([-2.0, 2.0])))
    np.testing.assert_allclose(np.asarray(probs)[:, 1], sig, rtol=1e-5)


def test_imputer_concrete_replaced_value_leaves_nan():
    g = _ml_graph("Imputer", ["N", 3], ["N", 3],
                  imputed_value_floats=[9.0, 9.0, 9.0],
                  replaced_value_float=-1.0)
    x = np.array([[np.nan, -1.0, 3.0]], np.float32)
    out = np.asarray(g.apply(g.params, x)[0])[0]
    assert np.isnan(out[0])           # NaN untouched
    assert out[1] == 9.0 and out[2] == 3.0


def test_multiclassova_conversion_raises():
    rng = np.random.default_rng(0)
    x = rng.normal(size=(120, 4)).astype(np.float32)
    y = rng.integers(0, 3, 120).astype(np.float64)
    model = LightGBMClassifier(num_iterations=4, num_leaves=5,
                               objective="multiclass").fit(
        Table({"features": x, "label": y}))
    import dataclasses
    model.booster.params = dataclasses.replace(
        model.booster.params, objective="multiclassova")
    with pytest.raises(NotImplementedError, match="multiclassova"):
        convert_lightgbm(model)


def test_rf_truncated_at_best_iteration_matches_booster():
    """rf margins average over the trees actually used; a converter that
    keeps 1/T_total weights after best_iteration truncation diverges
    from Booster.predict (advisor round-2 medium finding)."""
    x, y = _binary_data(seed=21)
    model = LightGBMClassifier(
        num_iterations=10, num_leaves=7, boosting_type="rf",
        bagging_fraction=0.8, bagging_freq=1).fit(
        Table({"features": x, "label": y}))
    model.booster.best_iteration = 3  # simulate early stopping at iter 3
    blob = convert_lightgbm(model)
    g = import_model(blob)
    _, probs = g.apply(g.params, x)
    np.testing.assert_allclose(np.asarray(probs)[:, 1],
                               model.booster.predict(x), atol=1e-5)


def test_converted_classifier_keeps_original_labels():
    """A model fit on non-dense labels {3, 7} exports ONNX whose 'label'
    output speaks the original labels, matching model.transform."""
    x, y01 = _binary_data(seed=31)
    y = np.where(y01 > 0.5, 7.0, 3.0)
    model = LightGBMClassifier(num_iterations=10, num_leaves=7).fit(
        Table({"features": x, "label": y}))
    blob = convert_lightgbm(model)
    g = import_model(blob)
    label, probs = g.apply(g.params, x)
    want = np.where(model.booster.predict(x) > 0.5, 7, 3)
    np.testing.assert_array_equal(np.asarray(label), want)


def test_tree_path_tensor_size_guard(monkeypatch):
    """The dense [T, M, n_leaves] path tensor must refuse (not silently
    allocate) gigabytes for very large ensembles."""
    from synapseml_tpu.onnx import ml_ops

    x, y = _binary_data(n=200, seed=41)
    model = LightGBMClassifier(num_iterations=4, num_leaves=7).fit(
        Table({"features": x, "label": y}))
    blob = convert_lightgbm(model)
    monkeypatch.setattr(ml_ops, "_PATH_WARN_BYTES", 16)
    with pytest.warns(RuntimeWarning, match="path tensor allocates"):
        g = import_model(blob)
        g.apply(g.params, x[:8])
    monkeypatch.setattr(ml_ops, "_PATH_GUARD_BYTES", 64)
    with pytest.raises(MemoryError, match="path tensor would allocate"):
        g = import_model(blob)
        g.apply(g.params, x[:8])


def test_tree_ensemble_v5_matches_old_style():
    """ai.onnx.ml opset-5 TreeEnsemble (compact leaf-array encoding)
    against the sklearn-verified old-style TreeEnsembleRegressor on the
    same two-tree ensemble, plus a hand-evaluated route check."""
    g = GraphBuilder(opset=17)
    x = g.add_input("x", np.float32, [None, 2])
    y = g.add_node(
        "TreeEnsemble", [x], domain="ai.onnx.ml",
        tree_roots=[0, 2],
        # tree0: n0(f0<=0.5) -> leaf0 | n1(f1>2) -> leaf1|leaf2
        # tree1: n2(f1<1.5) -> leaf3 | leaf4
        nodes_modes=np.asarray([0, 3, 1], np.uint8),
        nodes_featureids=[0, 1, 1],
        nodes_splits=np.asarray([0.5, 2.0, 1.5], np.float32),
        nodes_truenodeids=[0, 1, 3], nodes_trueleafs=[1, 1, 1],
        nodes_falsenodeids=[1, 2, 4], nodes_falseleafs=[0, 1, 1],
        leaf_targetids=[0, 0, 0, 0, 0],
        leaf_weights=np.asarray([1.5, -1.0, 3.0, 0.25, -0.25],
                                np.float32),
        n_targets=1, aggregate_function=1, post_transform=0)
    g.add_output(y, np.float32, None)
    m = import_model(g.to_bytes())
    xv = np.array([[0.3, 5.0], [0.9, 5.0], [0.9, 1.0]], np.float32)
    got = np.asarray(m.apply(m.params, xv)).reshape(-1)
    # routes: r0 leaf0+leaf4, r1 leaf1+leaf4, r2 leaf2+leaf3
    np.testing.assert_allclose(got, [1.25, -1.25, 3.25], atol=1e-6)

    g2 = GraphBuilder(opset=17)
    x2 = g2.add_input("x", np.float32, [None, 2])
    y2 = g2.add_node(
        "TreeEnsembleRegressor", [x2], domain="ai.onnx.ml",
        nodes_treeids=[0, 0, 0, 0, 0, 1, 1, 1],
        nodes_nodeids=[0, 1, 2, 3, 4, 0, 1, 2],
        nodes_modes=["BRANCH_LEQ", "LEAF", "BRANCH_GT", "LEAF", "LEAF",
                     "BRANCH_LT", "LEAF", "LEAF"],
        nodes_featureids=[0, 0, 1, 0, 0, 1, 0, 0],
        nodes_values=[0.5, 0., 2.0, 0., 0., 1.5, 0., 0.],
        nodes_truenodeids=[1, 0, 3, 0, 0, 1, 0, 0],
        nodes_falsenodeids=[2, 0, 4, 0, 0, 2, 0, 0],
        target_treeids=[0, 0, 0, 1, 1],
        target_nodeids=[1, 3, 4, 1, 2],
        target_ids=[0, 0, 0, 0, 0],
        target_weights=[1.5, -1.0, 3.0, 0.25, -0.25], n_targets=1)
    g2.add_output(y2, np.float32, None)
    m2 = import_model(g2.to_bytes())
    got2 = np.asarray(m2.apply(m2.params, xv)).reshape(-1)
    np.testing.assert_allclose(got, got2, atol=1e-6)

    # AVERAGE + LOGISTIC codes
    g3 = GraphBuilder(opset=17)
    x3 = g3.add_input("x", np.float32, [None, 2])
    y3 = g3.add_node(
        "TreeEnsemble", [x3], domain="ai.onnx.ml",
        tree_roots=[0, 2],
        nodes_modes=np.asarray([0, 3, 1], np.uint8),
        nodes_featureids=[0, 1, 1],
        nodes_splits=np.asarray([0.5, 2.0, 1.5], np.float32),
        nodes_truenodeids=[0, 1, 3], nodes_trueleafs=[1, 1, 1],
        nodes_falsenodeids=[1, 2, 4], nodes_falseleafs=[0, 1, 1],
        leaf_targetids=[0, 0, 0, 0, 0],
        leaf_weights=np.asarray([1.5, -1.0, 3.0, 0.25, -0.25],
                                np.float32),
        n_targets=1, aggregate_function=0, post_transform=2)
    g3.add_output(y3, np.float32, None)
    m3 = import_model(g3.to_bytes())
    got3 = np.asarray(m3.apply(m3.params, xv)).reshape(-1)
    np.testing.assert_allclose(
        got3, 1.0 / (1.0 + np.exp(-got / 2.0)), atol=1e-6)


def test_cast_map_dense_and_dict_forms():
    """CastMap behind ZipMap (the sklearn-converter tail) plus the
    genuine-map form with SPARSE densification."""
    g = GraphBuilder(opset=17)
    p = g.add_input("p", np.float32, [None, 3])
    z = g.add_node("ZipMap", [p], domain="ai.onnx.ml",
                   classlabels_int64s=[0, 1, 2])
    cm = g.add_node("CastMap", [z], domain="ai.onnx.ml",
                    cast_to="TO_FLOAT")
    g.add_output(cm, np.float32, None)
    m = import_model(g.to_bytes())
    pv = np.array([[0.1, 0.7, 0.2]], np.float32)
    np.testing.assert_allclose(
        np.asarray(m.apply(m.params, pv)[0]), pv)

    from synapseml_tpu.onnx.ml_ops import _cast_map

    class _Ctx:
        def __init__(self, **attrs):
            self.attrs = attrs

        def attr(self, k, d=None):
            return self.attrs.get(k, d)

    sparse = _cast_map(_Ctx(map_form="SPARSE", max_map=5,
                            cast_to="TO_FLOAT"), {1: 2.0, 3: 4.0, 9: 9.0})
    np.testing.assert_allclose(
        np.asarray(sparse), [[0.0, 2.0, 0.0, 4.0, 0.0]])
    dense = _cast_map(_Ctx(cast_to="TO_INT64"), {0: 7.0, 1: 8.0})
    assert np.asarray(dense).dtype == np.int64
    np.testing.assert_array_equal(np.asarray(dense), [[7, 8]])
