"""ONNX subsystem tests.

Strategy (the environment has no onnx/onnxruntime wheels — by design the
importer must not depend on them):
- wire-codec round-trips go through real serialized bytes;
- numerical correctness is checked against **torch** executing the *same
  weights* — an independent runtime, standing in for the reference's
  onnxruntime-vs-Spark comparisons
  (ref: deep-learning/src/test/scala/com/microsoft/ml/spark/onnx/ONNXModelSuite).
"""
import jax.numpy as jnp
import numpy as np
import pytest
import torch
import torch.nn as nn

from synapseml_tpu.data.table import Table
from synapseml_tpu.onnx import (GraphBuilder, ONNXModel, import_model, proto,
                                zoo)

torch.manual_seed(0)


# ---------------------------------------------------------------------------
# proto codec
# ---------------------------------------------------------------------------

def test_proto_roundtrip_tensor_dtypes():
    for arr in [
        np.arange(12, dtype=np.float32).reshape(3, 4),
        np.arange(6, dtype=np.int64) - 3,
        np.array([True, False, True]),
        np.arange(8, dtype=np.float64).reshape(2, 4),
        np.arange(4, dtype=np.uint8),
    ]:
        t = proto.numpy_to_tensor(arr, "x")
        blob = proto.encode(t)
        back = proto.tensor_to_numpy(proto.decode("TensorProto", blob))
        assert back.dtype == arr.dtype
        np.testing.assert_array_equal(back, arr)


def test_proto_typed_fields_decode():
    # models written by other emitters use typed repeated fields, not raw_data
    t = proto.Msg("TensorProto")
    t.dims = [2, 2]
    t.data_type = 1
    t.float_data = [1.0, 2.0, 3.0, 4.0]
    back = proto.tensor_to_numpy(proto.decode("TensorProto", proto.encode(t)))
    np.testing.assert_allclose(back, [[1, 2], [3, 4]])


def test_model_roundtrip_through_bytes(tmp_path):
    blob = zoo.mlp([8, 16], num_classes=3, seed=1)
    p = tmp_path / "m.onnx"
    p.write_bytes(blob)
    g = import_model(str(p))
    assert g.input_names == ["input"]
    assert len(g.output_names) == 1
    x = np.random.default_rng(0).normal(size=(5, 8)).astype(np.float32)
    (probs,) = g.apply(g.params, x)
    probs = np.asarray(probs)
    np.testing.assert_allclose(probs.sum(axis=-1), 1.0, atol=1e-5)


# ---------------------------------------------------------------------------
# numerical equivalence vs torch
# ---------------------------------------------------------------------------

def _torch_compare(builder_fn, torch_model, x, atol=2e-4, rtol=2e-4):
    blob = builder_fn()
    g = import_model(blob)
    got = np.asarray(g.apply(g.params, x)[0])
    with torch.no_grad():
        want = torch_model(torch.from_numpy(x)).numpy()
    np.testing.assert_allclose(got, want, atol=atol, rtol=rtol)


def test_conv_bn_pool_gemm_matches_torch():
    torch_m = nn.Sequential(
        nn.Conv2d(3, 8, 3, stride=2, padding=1),
        nn.BatchNorm2d(8),
        nn.ReLU(),
        nn.MaxPool2d(2, ceil_mode=True),
        nn.Flatten(),
        nn.Linear(8 * 4 * 4, 5),
    ).eval()
    # perturb BN running stats so the math is non-trivial
    with torch.no_grad():
        torch_m[1].running_mean.normal_(0, 0.5)
        torch_m[1].running_var.uniform_(0.5, 2.0)
        torch_m[1].weight.normal_(1, 0.2)
        torch_m[1].bias.normal_(0, 0.2)

    def build():
        g = GraphBuilder(opset=17)
        x = g.add_input("x", np.float32, ["N", 3, 16, 16])
        conv = torch_m[0]
        y = g.conv(x, conv.weight.detach().numpy(),
                   conv.bias.detach().numpy(), strides=(2, 2),
                   pads=(1, 1, 1, 1))
        bn = torch_m[1]
        y = g.batch_norm(y, bn.weight.detach().numpy(),
                         bn.bias.detach().numpy(),
                         bn.running_mean.numpy(), bn.running_var.numpy(),
                         epsilon=bn.eps)
        y = g.relu(y)
        y = g.add_node("MaxPool", [y], kernel_shape=[2, 2], strides=[2, 2],
                       ceil_mode=1)
        y = g.add_node("Flatten", [y], axis=1)
        fc = torch_m[5]
        y = g.gemm(y, fc.weight.detach().numpy(), fc.bias.detach().numpy())
        g.add_output(y, np.float32, ["N", 5])
        return g.to_bytes()

    x = np.random.default_rng(1).normal(size=(4, 3, 16, 16)).astype(np.float32)
    _torch_compare(build, torch_m, x)


def test_avgpool_grouped_conv_matches_torch():
    torch_m = nn.Sequential(
        nn.Conv2d(8, 8, 3, padding=2, groups=4, dilation=2),
        nn.SiLU(),
        nn.AvgPool2d(2),
        nn.Conv2d(8, 4, 1),
        nn.AdaptiveAvgPool2d(1),
        nn.Flatten(),
    ).eval()

    def build():
        g = GraphBuilder(opset=17)
        x = g.add_input("x", np.float32, ["N", 8, 12, 12])
        c0 = torch_m[0]
        y = g.conv(x, c0.weight.detach().numpy(), c0.bias.detach().numpy(),
                   pads=(2, 2, 2, 2), group=4, dilations=(2, 2))
        sig = g.add_node("Sigmoid", [y])
        y = g.add_node("Mul", [y, sig])  # SiLU = x*sigmoid(x)
        y = g.add_node("AveragePool", [y], kernel_shape=[2, 2], strides=[2, 2])
        c3 = torch_m[3]
        y = g.conv(y, c3.weight.detach().numpy(), c3.bias.detach().numpy())
        y = g.add_node("GlobalAveragePool", [y])
        y = g.add_node("Flatten", [y], axis=1)
        g.add_output(y, np.float32, ["N", 4])
        return g.to_bytes()

    x = np.random.default_rng(2).normal(size=(3, 8, 12, 12)).astype(np.float32)
    _torch_compare(build, torch_m, x)


def test_convtranspose_matches_torch():
    torch_m = nn.ConvTranspose2d(4, 6, 3, stride=2, padding=1,
                                 output_padding=1).eval()

    def build():
        g = GraphBuilder(opset=17)
        x = g.add_input("x", np.float32, ["N", 4, 7, 7])
        wn = g.add_initializer("w", torch_m.weight.detach().numpy())
        bn_ = g.add_initializer("b", torch_m.bias.detach().numpy())
        y = g.add_node("ConvTranspose", [x, wn, bn_], strides=[2, 2],
                       pads=[1, 1, 1, 1], output_padding=[1, 1])
        g.add_output(y, np.float32, ["N", 6, 14, 14])
        return g.to_bytes()

    x = np.random.default_rng(3).normal(size=(2, 4, 7, 7)).astype(np.float32)
    _torch_compare(build, torch_m, x)


def test_lstm_bidirectional_matches_torch():
    hidden, embed, seq, batch = 16, 8, 12, 3
    torch_lstm = nn.LSTM(embed, hidden, bidirectional=True).eval()

    def onnx_weights():
        # torch gate order i,f,g,o -> ONNX i,o,f,c
        def reorder(w):
            i, f, gg, o = np.split(w, 4, axis=0)
            return np.concatenate([i, o, f, gg], axis=0)
        ws, rs, bs = [], [], []
        for d, sfx in enumerate(["", "_reverse"]):
            w_ih = getattr(torch_lstm, f"weight_ih_l0{sfx}").detach().numpy()
            w_hh = getattr(torch_lstm, f"weight_hh_l0{sfx}").detach().numpy()
            b_ih = getattr(torch_lstm, f"bias_ih_l0{sfx}").detach().numpy()
            b_hh = getattr(torch_lstm, f"bias_hh_l0{sfx}").detach().numpy()
            ws.append(reorder(w_ih))
            rs.append(reorder(w_hh))
            bs.append(np.concatenate([reorder(b_ih), reorder(b_hh)]))
        return (np.stack(ws), np.stack(rs), np.stack(bs))

    w, r, b = onnx_weights()
    g = GraphBuilder(opset=17)
    xn = g.add_input("x", np.float32, [seq, "N", embed])
    wn = g.add_initializer("w", w)
    rn = g.add_initializer("r", r)
    bn_ = g.add_initializer("b", b)
    y = g.add_node("LSTM", [xn, wn, rn, bn_],
                   outputs=["y", "y_h", "y_c"],
                   hidden_size=hidden, direction="bidirectional")
    g.add_output("y", np.float32, [seq, 2, "N", hidden])
    gi = import_model(g.to_bytes())

    x = np.random.default_rng(4).normal(size=(seq, batch, embed)).astype(np.float32)
    got = np.asarray(gi.apply(gi.params, x)[0])  # (seq, dirs, batch, hidden)
    with torch.no_grad():
        want, _ = torch_lstm(torch.from_numpy(x))  # (seq, batch, 2*hidden)
    want = want.numpy().reshape(seq, batch, 2, hidden).transpose(0, 2, 1, 3)
    np.testing.assert_allclose(got, want, atol=1e-4, rtol=1e-4)


def test_shape_subgraph_folding():
    """Shape->Gather->Concat->Reshape chains (standard exporter output) must
    stay static under jit."""
    import jax
    g = GraphBuilder(opset=17)
    x = g.add_input("x", np.float32, ["N", 4, 6])
    shp = g.add_node("Shape", [x])
    n0 = g.add_node("Gather", [shp, g.add_initializer(
        "idx0", np.array(0, dtype=np.int64))], axis=0)
    n0u = g.add_node("Unsqueeze", [n0, g.add_initializer(
        "ax0", np.array([0], dtype=np.int64))])
    minus1 = g.add_initializer("m1", np.array([-1], dtype=np.int64))
    tgt = g.add_node("Concat", [n0u, minus1], axis=0)
    y = g.add_node("Reshape", [x, tgt])
    g.add_output(y, np.float32, ["N", 24])
    gi = import_model(g.to_bytes())
    fn = jax.jit(gi.bind())
    x_val = np.arange(48, dtype=np.float32).reshape(2, 4, 6)
    out = np.asarray(fn(x_val)[0])
    assert out.shape == (2, 24)
    np.testing.assert_array_equal(out, x_val.reshape(2, 24))


def test_opset_versioned_ops():
    # opset 9: Clip via attrs, Slice via attrs, Unsqueeze via attr
    g = GraphBuilder(opset=9)
    x = g.add_input("x", np.float32, ["N", 6])
    y = g.add_node("Clip", [x], min=-0.5, max=0.5)
    y = g.add_node("Slice", [y], starts=[0], ends=[4], axes=[1])
    y = g.add_node("Unsqueeze", [y], axes=[1])
    g.add_output(y, np.float32, ["N", 1, 4])
    gi = import_model(g.to_bytes())
    x_val = np.linspace(-1, 1, 12, dtype=np.float32).reshape(2, 6)
    out = np.asarray(gi.apply(gi.params, x_val)[0])
    assert out.shape == (2, 1, 4)
    np.testing.assert_allclose(out[:, 0, :], np.clip(x_val[:, :4], -0.5, 0.5))


def test_legacy_softmax_semantics():
    # opset < 13 softmax flattens trailing dims from axis
    g = GraphBuilder(opset=11)
    x = g.add_input("x", np.float32, [2, 3, 4])
    y = g.add_node("Softmax", [x], axis=1)
    g.add_output(y, np.float32, [2, 3, 4])
    gi = import_model(g.to_bytes())
    x_val = np.random.default_rng(5).normal(size=(2, 3, 4)).astype(np.float32)
    out = np.asarray(gi.apply(gi.params, x_val)[0])
    # flattened (2, 12) softmax
    flat = x_val.reshape(2, 12)
    e = np.exp(flat - flat.max(axis=1, keepdims=True))
    want = (e / e.sum(axis=1, keepdims=True)).reshape(2, 3, 4)
    np.testing.assert_allclose(out, want, atol=1e-5)


def test_tiny_resnet_imports_and_runs():
    blob = zoo.tiny_resnet(num_classes=7, image_size=32)
    g = import_model(blob)
    x = np.random.default_rng(6).normal(size=(2, 3, 32, 32)).astype(np.float32)
    (logits,) = g.apply(g.params, x)
    assert np.asarray(logits).shape == (2, 7)
    assert np.all(np.isfinite(np.asarray(logits)))


def test_bilstm_tagger_zoo():
    blob = zoo.bilstm_tagger(vocab=50, embed=8, hidden=12, n_tags=5, seq_len=10)
    g = import_model(blob)
    ids = np.random.default_rng(7).integers(0, 50, size=(3, 10))
    (logits,) = g.apply(g.params, ids)
    assert np.asarray(logits).shape == (3, 10, 5)


# ---------------------------------------------------------------------------
# ONNXModel transformer
# ---------------------------------------------------------------------------

def test_onnx_model_transformer_with_post_cols():
    blob = zoo.mlp([6, 12], num_classes=4, seed=3)
    m = ONNXModel(model_bytes=blob,
                  feed_dict={"input": "features"},
                  argmax_output_col="prediction")
    t = Table({"features": np.random.default_rng(8)
               .normal(size=(9, 6)).astype(np.float32)})
    out = m.transform(t)
    assert "prediction" in out
    assert out["prediction"].shape == (9,)
    # graph output column present under its graph name
    probs_col = [c for c in out.columns if c not in ("features", "prediction")]
    assert probs_col
    probs = out[probs_col[0]]
    np.testing.assert_allclose(probs.sum(axis=-1), 1.0, atol=1e-5)
    # batching must not change results
    m2 = m.copy(mini_batch_size=4)
    out2 = m2.transform(t)
    np.testing.assert_allclose(out2[probs_col[0]], probs, atol=1e-5)


def test_onnx_model_save_load(tmp_path):
    blob = zoo.mlp([5, 8], num_classes=3, seed=4)
    m = ONNXModel(model_bytes=blob, feed_dict={"input": "feat"},
                  argmax_output_col="pred")
    t = Table({"feat": np.random.default_rng(9).normal(size=(6, 5)).astype(np.float32)})
    want = m.transform(t)["pred"]
    path = str(tmp_path / "onnx_model")
    m.save(path)
    from synapseml_tpu.core.pipeline import PipelineStage
    m2 = PipelineStage.load(path)
    got = m2.transform(t)["pred"]
    np.testing.assert_array_equal(got, want)


def test_onnx_model_metadata():
    m = ONNXModel(model_bytes=zoo.tiny_resnet())
    meta = m.model_metadata()
    assert meta["inputs"]["data"][1][1:] == [3, 32, 32]
    assert meta["param_bytes"] > 0


def test_transformer_encoder_matches_torch():
    """BERT-era opset: the zoo transformer (Gather embeddings, multi-head
    MatMul/Softmax attention, LayerNormalization, Gelu FFN, Trilu causal
    mask) must match an independent torch implementation on the same
    weights."""
    vocab, d, heads, ffn, layers, S = 37, 16, 4, 40, 2, 10
    hd = d // heads
    blob = zoo.transformer_encoder(vocab, d, heads, ffn, layers,
                                   seq_len=S, causal=True, seed=5)
    g = import_model(blob)
    P = {k: torch.tensor(np.asarray(v)) for k, v in g.params.items()}

    ids = np.random.default_rng(1).integers(0, vocab, (3, S))
    (ours,) = g.apply(g.params, ids)

    def lin(x, name):
        return x @ P[f"{name}_w"] + P[f"{name}_b"]

    def ln(x, name):
        return torch.nn.functional.layer_norm(
            x, (d,), P[f"{name}_s"], P[f"{name}_b"], eps=1e-5)

    with torch.no_grad():
        x = P["tok_emb"][torch.tensor(ids)] + P["pos_emb"]
        mask = torch.triu(torch.ones(S, S), diagonal=1) * -1e9
        for li in range(layers):
            h1 = ln(x, f"l{li}_ln1")
            q = lin(h1, f"l{li}_q").view(3, S, heads, hd).transpose(1, 2)
            k = lin(h1, f"l{li}_k").view(3, S, heads, hd).transpose(1, 2)
            v = lin(h1, f"l{li}_v").view(3, S, heads, hd).transpose(1, 2)
            logits = q @ k.transpose(-1, -2) / np.sqrt(hd) + mask
            ctx = torch.softmax(logits, dim=-1) @ v
            ctx = ctx.transpose(1, 2).reshape(3, S, d)
            x = x + lin(ctx, f"l{li}_o")
            h2 = ln(x, f"l{li}_ln2")
            h2 = torch.nn.functional.gelu(lin(h2, f"l{li}_ff1"))
            x = x + lin(h2, f"l{li}_ff2")
        theirs = ln(x, "final_ln").numpy()

    np.testing.assert_allclose(np.asarray(ours), theirs, rtol=2e-4,
                               atol=2e-4)
    # causal: truncating future tokens must not change earlier positions
    ids2 = ids.copy()
    ids2[:, -1] = (ids2[:, -1] + 1) % vocab
    (ours2,) = g.apply(g.params, ids2)
    np.testing.assert_allclose(np.asarray(ours2)[:, :-1],
                               np.asarray(ours)[:, :-1], rtol=1e-4,
                               atol=1e-5)


def test_executor_path_keeps_shape_initializers_static():
    """Graphs whose Reshape/Slice targets are initializers must run through
    the BatchedExecutor (params ride as traced jit arguments; integer
    initializers stay static so shape ops keep concrete shapes)."""
    from synapseml_tpu.onnx.model import ONNXModel

    blob = zoo.transformer_encoder(30, 8, 2, 16, 1, seq_len=6, causal=True)
    m = ONNXModel(model_bytes=blob, feed_dict={"tokens": "toks"})
    ids = np.random.default_rng(2).integers(0, 30, (4, 6))
    out = m.transform(Table({"toks": ids}))
    enc = np.asarray(out[m.graph.output_names[0]])
    assert enc.shape == (4, 6, 8) and np.isfinite(enc).all()
    # executor result must equal the direct host apply
    g = m.graph
    (direct,) = g.apply(g.params, ids)
    np.testing.assert_allclose(enc, np.asarray(direct), rtol=1e-4,
                               atol=1e-5)
    # weights pytree carries only floats; shape tensors are static
    assert all(np.issubdtype(v.dtype, np.floating)
               for v in g.params.values())
    assert any(np.issubdtype(v.dtype, np.integer)
               for v in g.static_params.values())


def test_scatter_nd_set_and_add():
    g = GraphBuilder(opset=17)
    x = g.add_input("x", np.float32, [3, 4])
    idx = g.add_initializer("idx", np.array([[0], [2]], np.int64))
    upd = g.add_initializer("upd", np.full((2, 4), 9.0, np.float32))
    y = g.add_node("ScatterND", [x, idx, upd])
    g.add_output(y, np.float32, [3, 4])
    gi = import_model(g.to_bytes())
    xv = np.zeros((3, 4), np.float32)
    out = np.asarray(gi.apply(gi.params, xv)[0])
    np.testing.assert_allclose(out, [[9] * 4, [0] * 4, [9] * 4])

    g2 = GraphBuilder(opset=17)
    x = g2.add_input("x", np.float32, [3])
    idx = g2.add_initializer("idx", np.array([[1], [1]], np.int64))
    upd = g2.add_initializer("upd", np.array([2.0, 3.0], np.float32))
    y = g2.add_node("ScatterND", [x, idx, upd], reduction="add")
    g2.add_output(y, np.float32, [3])
    gi2 = import_model(g2.to_bytes())
    out = np.asarray(gi2.apply(gi2.params, np.ones(3, np.float32))[0])
    np.testing.assert_allclose(out, [1.0, 6.0, 1.0])  # duplicate adds


def test_grid_sample_matches_torch():
    th_x = torch.arange(16, dtype=torch.float32).reshape(1, 1, 4, 4)
    th_grid = (torch.rand(1, 3, 5, 2) * 2 - 1) * 0.9
    for mode in ("bilinear", "nearest"):
        for align in (True, False):
            want = torch.nn.functional.grid_sample(
                th_x, th_grid, mode=mode, padding_mode="zeros",
                align_corners=align).numpy()
            g = GraphBuilder(opset=17)
            x = g.add_input("x", np.float32, [1, 1, 4, 4])
            gr = g.add_initializer("grid", th_grid.numpy())
            y = g.add_node("GridSample", [x, gr], mode=mode,
                           padding_mode="zeros",
                           align_corners=1 if align else 0)
            g.add_output(y, np.float32, [1, 1, 3, 5])
            gi = import_model(g.to_bytes())
            got = np.asarray(gi.apply(gi.params, th_x.numpy())[0])
            np.testing.assert_allclose(got, want, atol=1e-5,
                                       err_msg=f"{mode} align={align}")


def _branch_graph(name, mult):
    from synapseml_tpu.onnx.proto import Msg, make_attr, numpy_to_tensor

    g = Msg("GraphProto")
    g.name = name
    node = Msg("NodeProto")
    node.op_type = "Mul"
    node.input = ["x", f"{name}_c"]
    node.output = [f"{name}_out"]
    node.name = f"{name}_mul"
    node.attribute = []
    init = numpy_to_tensor(np.float32(mult) * np.ones(1, np.float32),
                           f"{name}_c")
    g.initializer = [init]
    g.node = [node]
    out = Msg("ValueInfoProto")
    out.name = f"{name}_out"
    g.output = [out]
    g.input = []
    g.value_info = []
    return g


def test_if_subgraphs_capture_outer_scope():
    """If with then/else branches multiplying the OUTER graph's x."""
    g = GraphBuilder(opset=17)
    x = g.add_input("x", np.float32, ["N"])
    cond = g.add_input("cond", np.bool_, [])
    y = g.add_node("If", [cond], then_branch=_branch_graph("thenb", 2.0),
                   else_branch=_branch_graph("elseb", 10.0))
    g.add_output(y, np.float32, ["N"])
    gi = import_model(g.to_bytes())
    xv = np.array([1.0, 3.0], np.float32)
    # host-static condition: single branch executes
    np.testing.assert_allclose(
        np.asarray(gi.apply(gi.params, xv, np.bool_(True))[0]), [2, 6])
    np.testing.assert_allclose(
        np.asarray(gi.apply(gi.params, xv, np.bool_(False))[0]), [10, 30])
    # traced condition under jit: elementwise select of both branches
    import jax

    fn = jax.jit(lambda xv, c: gi.apply(gi.params, xv, c)[0])
    np.testing.assert_allclose(np.asarray(fn(xv, True)), [2, 6])
    np.testing.assert_allclose(np.asarray(fn(xv, False)), [10, 30])


def test_loop_static_trip_count():
    """Loop accumulating a carried sum and emitting scan outputs
    (the exported for-range pattern; body: acc += x)."""
    from synapseml_tpu.onnx.proto import Msg, numpy_to_tensor

    body = Msg("GraphProto")
    body.name = "body"
    for nm in ("iter", "cond_in", "acc"):
        vi = Msg("ValueInfoProto")
        vi.name = nm
        body.input.append(vi)
    add = Msg("NodeProto")
    add.op_type = "Add"
    add.input = ["acc", "x"]          # x captured from the outer scope
    add.output = ["acc_out"]
    add.name = "b_add"
    add.attribute = []
    ident = Msg("NodeProto")
    ident.op_type = "Identity"
    ident.input = ["cond_in"]
    ident.output = ["cond_out"]
    ident.name = "b_cond"
    ident.attribute = []
    body.node = [ident, add]
    for nm in ("cond_out", "acc_out", "acc_out"):
        vi = Msg("ValueInfoProto")
        vi.name = nm
        body.output.append(vi)

    g = GraphBuilder(opset=17)
    x = g.add_input("x", np.float32, [2])
    trip = g.add_initializer("M", np.int64(3))
    acc0 = g.add_initializer("acc0", np.zeros(2, np.float32))
    cond0 = g.add_initializer("cond0", np.array(True))
    outs = g.add_node("Loop", [trip, cond0, acc0],
                      outputs=["final", "scanned"], body=body)
    g.add_output("final", np.float32, [2])
    g.add_output("scanned", np.float32, [3, 2])
    gi = import_model(g.to_bytes())
    xv = np.array([1.0, 2.0], np.float32)
    final, scanned = gi.apply(gi.params, xv)
    np.testing.assert_allclose(np.asarray(final), [3.0, 6.0])
    np.testing.assert_allclose(np.asarray(scanned),
                               [[1, 2], [2, 4], [3, 6]])


def _while_body(with_scan=False):
    """Loop body: acc_out = acc + acc; cond_out = sum(acc_out) < limit
    (limit captured from the outer scope) — the scripted-while pattern."""
    from synapseml_tpu.onnx.proto import Msg

    body = Msg("GraphProto")
    body.name = "wbody"
    for nm in ("iter", "cond_in", "acc"):
        vi = Msg("ValueInfoProto")
        vi.name = nm
        body.input.append(vi)
    dbl = Msg("NodeProto")
    dbl.op_type = "Add"
    dbl.input = ["acc", "acc"]
    dbl.output = ["acc_out"]
    dbl.name = "w_dbl"
    dbl.attribute = []
    red = Msg("NodeProto")
    red.op_type = "ReduceSum"
    red.input = ["acc_out"]
    red.output = ["s"]
    red.name = "w_sum"
    red.attribute = []
    att = Msg("AttributeProto")
    att.name = "keepdims"
    att.type = 2  # INT
    att.i = 0
    red.attribute.append(att)
    less = Msg("NodeProto")
    less.op_type = "Less"
    less.input = ["s", "limit"]  # limit captured from the outer scope
    less.output = ["cond_out"]
    less.name = "w_less"
    less.attribute = []
    body.node = [dbl, red, less]
    outs = ["cond_out", "acc_out"] + (["acc_out"] if with_scan else [])
    for nm in outs:
        vi = Msg("ValueInfoProto")
        vi.name = nm
        body.output.append(vi)
    return body


def test_loop_traced_condition_lowers_to_while_loop():
    """A body-computed (device) termination condition runs as
    lax.while_loop — the pattern real exporters emit for scripted
    `while` loops (ref delegates to onnxruntime, ONNXModel.scala:173)."""
    import jax

    g = GraphBuilder(opset=17)
    acc0 = g.add_input("acc0", np.float32, [2])
    g.add_input("limit", np.float32, [])
    trip = g.add_initializer("M", np.int64(100))
    cond0 = g.add_initializer("cond0", np.array(True))
    g.add_node("Loop", [trip, cond0, acc0], outputs=["final"],
               body=_while_body())
    g.add_output("final", np.float32, [2])
    gi = import_model(g.to_bytes())
    # doubling [1,1] until sum >= 16 stops after [8,8]
    final, = gi.apply(gi.params, np.ones(2, np.float32),
                      np.float32(16.0))
    np.testing.assert_allclose(np.asarray(final), [8.0, 8.0])
    # and under jit, where everything is a tracer
    fn = jax.jit(lambda a, lim: gi.apply(gi.params, a, lim)[0])
    np.testing.assert_allclose(np.asarray(fn(np.ones(2, np.float32),
                                             np.float32(16.0))), [8, 8])
    np.testing.assert_allclose(np.asarray(fn(np.ones(2, np.float32),
                                             np.float32(100.0))), [64, 64])


def test_loop_traced_trip_count():
    """A data-dependent trip count (graph input M) bounds the while_loop;
    the smaller of M and the condition wins."""
    import jax

    g = GraphBuilder(opset=17)
    acc0 = g.add_input("acc0", np.float32, [2])
    g.add_input("limit", np.float32, [])
    m_in = g.add_input("M", np.int64, [])
    cond0 = g.add_initializer("cond0", np.array(True))
    g.add_node("Loop", [m_in, cond0, acc0], outputs=["final"],
               body=_while_body())
    g.add_output("final", np.float32, [2])
    gi = import_model(g.to_bytes())
    fn = jax.jit(lambda a, lim, m: gi.apply(gi.params, a, lim, m)[0])
    # trip bound cuts in first: 2 iterations only
    np.testing.assert_allclose(
        np.asarray(fn(np.ones(2, np.float32), np.float32(1e6),
                      np.int64(2))), [4, 4])
    # condition cuts in first
    np.testing.assert_allclose(
        np.asarray(fn(np.ones(2, np.float32), np.float32(16.0),
                      np.int64(50))), [8, 8])


def test_loop_int64_max_trip_count_means_unbounded():
    """torch exports scripted `while cond:` as Loop with M = INT64_MAX;
    with x64 disabled a naive cast canonicalizes that to int32 -1 and
    the loop would silently run ZERO iterations — it must be treated as
    unbounded instead (round-3 review finding)."""
    g = GraphBuilder(opset=17)
    acc0 = g.add_input("acc0", np.float32, [2])
    g.add_input("limit", np.float32, [])
    trip = g.add_initializer("M", np.int64(2**63 - 1))
    cond0 = g.add_initializer("cond0", np.array(True))
    g.add_node("Loop", [trip, cond0, acc0], outputs=["final"],
               body=_while_body())
    g.add_output("final", np.float32, [2])
    gi = import_model(g.to_bytes())
    final, = gi.apply(gi.params, np.ones(2, np.float32), np.float32(16.0))
    np.testing.assert_allclose(np.asarray(final), [8.0, 8.0])


def test_loop_traced_int64_max_trip_count_means_unbounded():
    """Same as above but M arrives as a *traced* graph input: jit's
    boundary canonicalization turns INT64_MAX into int32 -1 before the
    Loop op ever sees it, so the negative-means-unbounded clamp must
    live inside the lowering too (round-3 advisor finding)."""
    import jax

    g = GraphBuilder(opset=17)
    acc0 = g.add_input("acc0", np.float32, [2])
    g.add_input("limit", np.float32, [])
    m_in = g.add_input("M", np.int64, [])
    cond0 = g.add_initializer("cond0", np.array(True))
    g.add_node("Loop", [m_in, cond0, acc0], outputs=["final"],
               body=_while_body())
    g.add_output("final", np.float32, [2])
    gi = import_model(g.to_bytes())
    fn = jax.jit(lambda a, lim, m: gi.apply(gi.params, a, lim, m)[0])
    out = fn(np.ones(2, np.float32), np.float32(16.0), np.int64(2**63 - 1))
    np.testing.assert_allclose(np.asarray(out), [8.0, 8.0])


def test_loop_traced_cond_with_scan_outputs_rejected():
    """Scan outputs under a data-dependent trip count would have a
    data-dependent shape; XLA cannot express that — clear error."""
    import jax

    g = GraphBuilder(opset=17)
    acc0 = g.add_input("acc0", np.float32, [2])
    g.add_input("limit", np.float32, [])
    trip = g.add_initializer("M", np.int64(100))
    cond_in = g.add_input("c0", np.bool_, [])
    g.add_node("Loop", [trip, cond_in, acc0],
               outputs=["final", "scanned"], body=_while_body(True))
    g.add_output("final", np.float32, [2])
    g.add_output("scanned", np.float32, ["T", 2])
    gi = import_model(g.to_bytes())
    fn = jax.jit(lambda a, lim, c: gi.apply(gi.params, a, lim, c))
    with pytest.raises(NotImplementedError, match="data-dependent"):
        fn(np.ones(2, np.float32), np.float32(16.0), np.bool_(True))


def test_loop_zero_trips():
    from synapseml_tpu.onnx.proto import Msg

    body = Msg("GraphProto")
    body.name = "body0"
    for nm in ("iter", "cond_in", "acc"):
        vi = Msg("ValueInfoProto")
        vi.name = nm
        body.input.append(vi)
    ident = Msg("NodeProto")
    ident.op_type = "Identity"
    ident.input = ["cond_in"]
    ident.output = ["cond_out"]
    ident.name = "b_cond"
    ident.attribute = []
    add = Msg("NodeProto")
    add.op_type = "Add"
    add.input = ["acc", "x"]
    add.output = ["acc_out"]
    add.name = "b_add"
    add.attribute = []
    body.node = [ident, add]
    for nm in ("cond_out", "acc_out", "acc_out"):
        vi = Msg("ValueInfoProto")
        vi.name = nm
        body.output.append(vi)

    g = GraphBuilder(opset=17)
    g.add_input("x", np.float32, [2])
    trip = g.add_initializer("M", np.int64(0))
    acc0 = g.add_initializer("acc0", np.zeros(2, np.float32))
    cond0 = g.add_initializer("cond0", np.array(True))
    g.add_node("Loop", [trip, cond0, acc0],
               outputs=["final", "scanned"], body=body)
    g.add_output("final", np.float32, [2])
    g.add_output("scanned", np.float32, [0, 2])
    gi = import_model(g.to_bytes())
    final, scanned = gi.apply(gi.params, np.ones(2, np.float32))
    np.testing.assert_allclose(np.asarray(final), [0.0, 0.0])
    assert np.asarray(scanned).shape == (0, 2)  # empty scan output


def test_if_subgraph_unsupported_op_fails_at_import():
    """Unsupported ops inside branches must be rejected at import time,
    not on the first live request."""
    from synapseml_tpu.onnx.proto import Msg

    branch = Msg("GraphProto")
    branch.name = "bad"
    node = Msg("NodeProto")
    node.op_type = "TotallyUnknownOp"
    node.input = ["x"]
    node.output = ["y"]
    node.name = "bad_op"
    node.attribute = []
    branch.node = [node]
    vi = Msg("ValueInfoProto")
    vi.name = "y"
    branch.output = [vi]

    g = GraphBuilder(opset=17)
    g.add_input("x", np.float32, ["N"])
    cond = g.add_initializer("c", np.array(True))
    g.add_node("If", [cond], outputs=["out"], then_branch=branch,
               else_branch=branch)
    g.add_output("out", np.float32, ["N"])
    with pytest.raises(NotImplementedError, match="TotallyUnknownOp"):
        import_model(g.to_bytes())


def test_truncated_keeps_subgraph_captured_params():
    from synapseml_tpu.onnx.proto import Msg

    def branch(mult_name):
        b = Msg("GraphProto")
        b.name = f"br_{mult_name}"
        node = Msg("NodeProto")
        node.op_type = "Mul"
        node.input = ["x", mult_name]   # captures outer initializer
        node.output = [f"{mult_name}_o"]
        node.name = f"mul_{mult_name}"
        node.attribute = []
        b.node = [node]
        vi = Msg("ValueInfoProto")
        vi.name = f"{mult_name}_o"
        b.output = [vi]
        return b

    g = GraphBuilder(opset=17)
    x = g.add_input("x", np.float32, ["N"])
    w = g.add_initializer("W", np.array([2.0], np.float32))
    cond = g.add_input("cond", np.bool_, [])
    y = g.add_node("If", [cond], then_branch=branch("W"),
                   else_branch=branch("W"))
    z = g.add_node("Relu", [y])
    g.add_output(z, np.float32, ["N"])
    gi = import_model(g.to_bytes())
    t = gi.truncated(1)  # cut the Relu; the If + its captured W survive
    out = t.apply(t.params, np.array([3.0], np.float32), np.bool_(True))
    np.testing.assert_allclose(np.asarray(out[0]), [6.0])


def test_scan_cumsum_forward_and_reverse():
    """Scan as running sum over a sequence, forward and reverse
    directions (the pre-Loop RNN export pattern)."""
    from synapseml_tpu.onnx.proto import Msg

    body = Msg("GraphProto")
    body.name = "scan_body"
    for nm in ("s_in", "x_t"):
        vi = Msg("ValueInfoProto")
        vi.name = nm
        body.input.append(vi)
    add = Msg("NodeProto")
    add.op_type = "Add"
    add.input = ["s_in", "x_t"]
    add.output = ["s_out"]
    add.name = "sb_add"
    add.attribute = []
    body.node = [add]
    for nm in ("s_out", "s_out"):
        vi = Msg("ValueInfoProto")
        vi.name = nm
        body.output.append(vi)

    for reverse in (0, 1):
        g = GraphBuilder(opset=17)
        g.add_input("seq", np.float32, [4, 2])
        s0 = g.add_initializer("s0", np.zeros(2, np.float32))
        g.add_node("Scan", [s0, "seq"], outputs=["sfinal", "cums"],
                   body=body, num_scan_inputs=1,
                   scan_input_directions=[reverse])
        g.add_output("sfinal", np.float32, [2])
        g.add_output("cums", np.float32, [4, 2])
        gi = import_model(g.to_bytes())
        seq = np.arange(8, dtype=np.float32).reshape(4, 2)
        sfinal, cums = gi.apply(gi.params, seq)
        src = seq[::-1] if reverse else seq
        np.testing.assert_allclose(np.asarray(sfinal), seq.sum(0))
        np.testing.assert_allclose(np.asarray(cums), np.cumsum(src, 0))


def test_scan_long_sequence_uses_lax_scan():
    """Length > 16 lowers to one lax.scan body; results must match the
    unrolled semantics (cumsum check at length 64, reverse direction)."""
    from synapseml_tpu.onnx.proto import Msg

    body = Msg("GraphProto")
    body.name = "scan_body_long"
    for nm in ("s_in", "x_t"):
        vi = Msg("ValueInfoProto")
        vi.name = nm
        body.input.append(vi)
    add = Msg("NodeProto")
    add.op_type = "Add"
    add.input = ["s_in", "x_t"]
    add.output = ["s_out"]
    add.name = "sb_add"
    add.attribute = []
    body.node = [add]
    for nm in ("s_out", "s_out"):
        vi = Msg("ValueInfoProto")
        vi.name = nm
        body.output.append(vi)

    for reverse in (0, 1):
        g = GraphBuilder(opset=17)
        g.add_input("seq", np.float32, [64, 3])
        s0 = g.add_initializer("s0", np.zeros(3, np.float32))
        g.add_node("Scan", [s0, "seq"], outputs=["sfinal", "cums"],
                   body=body, num_scan_inputs=1,
                   scan_input_directions=[reverse])
        g.add_output("sfinal", np.float32, [3])
        g.add_output("cums", np.float32, [64, 3])
        gi = import_model(g.to_bytes())
        seq = np.random.default_rng(0).normal(size=(64, 3)).astype(np.float32)
        sfinal, cums = gi.apply(gi.params, seq)
        src = seq[::-1] if reverse else seq
        np.testing.assert_allclose(np.asarray(sfinal), seq.sum(0),
                                   rtol=1e-4, atol=1e-5)
        np.testing.assert_allclose(np.asarray(cums), np.cumsum(src, 0),
                                   rtol=1e-4, atol=1e-5)
        # jit the whole graph (the path real models take)
        import jax
        fn = jax.jit(gi.bind())
        np.testing.assert_allclose(np.asarray(fn(seq)[1]),
                                   np.cumsum(src, 0), rtol=1e-4, atol=1e-5)


# ---------------------------------------------------------------------------
# full-network parity: zoo.resnet50 vs a torch twin with identical weights
# ---------------------------------------------------------------------------

class _TorchResNet(nn.Module):
    """Twin of zoo.build_resnet: identical architecture, weights replayed
    from the same seeded generator, so the imported ONNX graph and this
    torch module compute the same function. Certifies the flagship bench
    graph end-to-end (the reference certifies via onnxruntime,
    deep-learning/.../ONNXModelSuite)."""

    def __init__(self, depths, bottleneck, num_classes, width, seed):
        super().__init__()
        from synapseml_tpu.onnx.zoo import _Rng
        r = _Rng(seed)

        def conv(in_c, out_c, k, stride=1, pad=0):
            m = nn.Conv2d(in_c, out_c, k, stride=stride, padding=pad,
                          bias=False)
            with torch.no_grad():
                m.weight.copy_(torch.from_numpy(r.conv_w(out_c, in_c, k, k)))
            return m

        def bn(c):
            m = nn.BatchNorm2d(c)
            s, b, mean, var = r.bn(c)
            with torch.no_grad():
                m.weight.copy_(torch.from_numpy(s))
                m.bias.copy_(torch.from_numpy(b))
                m.running_mean.copy_(torch.from_numpy(mean))
                m.running_var.copy_(torch.from_numpy(var))
            return m

        self.stem = nn.Sequential(conv(3, width, 7, stride=2, pad=3),
                                  bn(width), nn.ReLU(),
                                  nn.MaxPool2d(3, stride=2, padding=1))
        blocks = []
        in_c, chan = width, width
        for stage, n_blocks in enumerate(depths):
            for blk in range(n_blocks):
                stride = 2 if (stage > 0 and blk == 0) else 1
                if bottleneck:
                    mid, out_c = chan, chan * 4
                    main = nn.Sequential(
                        conv(in_c, mid, 1), bn(mid), nn.ReLU(),
                        conv(mid, mid, 3, stride=stride, pad=1), bn(mid),
                        nn.ReLU(),
                        conv(mid, out_c, 1), bn(out_c))
                else:
                    out_c = chan
                    main = nn.Sequential(
                        conv(in_c, out_c, 3, stride=stride, pad=1),
                        bn(out_c), nn.ReLU(),
                        conv(out_c, out_c, 3, pad=1), bn(out_c))
                if stride != 1 or in_c != out_c:
                    down = nn.Sequential(conv(in_c, out_c, 1, stride=stride),
                                         bn(out_c))
                else:
                    down = nn.Identity()
                blocks.append(nn.ModuleDict({"main": main, "down": down}))
                in_c = out_c
            chan *= 2
        self.blocks = nn.ModuleList(blocks)
        fcw, fcb = r.fc(num_classes, in_c)
        self.fc = nn.Linear(in_c, num_classes)
        with torch.no_grad():
            self.fc.weight.copy_(torch.from_numpy(fcw))
            self.fc.bias.copy_(torch.from_numpy(fcb))

    def forward(self, x):
        y = self.stem(x)
        for blk in self.blocks:
            y = torch.relu(blk["main"](y) + blk["down"](y))
        y = y.mean(dim=(2, 3))
        return self.fc(y)


def test_resnet50_full_network_parity_vs_torch():
    """The COMPLETE resnet50 graph ([3,4,6,3] bottlenecks, 1000 classes —
    the bench flagship) at reduced spatial size, against torch with the
    same weights: ~2.1e7 params through 53 convs + 53 BNs + fc."""
    blob = zoo.resnet50(image_size=32, seed=5)
    g = import_model(blob)
    x = np.random.default_rng(0).normal(
        size=(2, 3, 32, 32)).astype(np.float32)
    got = np.asarray(g.apply(g.params, x)[0])

    twin = _TorchResNet([3, 4, 6, 3], bottleneck=True, num_classes=1000,
                        width=64, seed=5).eval()
    with torch.no_grad():
        want = twin(torch.from_numpy(x)).numpy()
    assert got.shape == (2, 1000)
    np.testing.assert_allclose(got, want, rtol=1e-3, atol=1e-3)


def test_uint8_feed_device_cast_and_norm():
    """uint8 pixel feeds ride the wire as 1 byte/px: the cast to float
    (and optional (x-mean)*scale) happens ON DEVICE. Numerics must match
    host-side float conversion exactly for the plain cast, and the
    executor must see the uint8 array unwidened."""
    g = GraphBuilder(opset=17)
    x = g.add_input("data", np.float32, ["N", 4])
    y = g.add_node("Mul", [x, g.add_initializer(
        "w", np.array([1.0, 2.0, 3.0, 4.0], np.float32))])
    g.add_output(y, np.float32, ["N", 4])
    blob = g.to_bytes()
    pix = np.arange(32, dtype=np.uint8).reshape(8, 4)

    # default-on: integer feed to a float input casts device-side
    m = ONNXModel(model_bytes=blob)
    out = m._executor()(pix)[0]
    np.testing.assert_allclose(
        out, pix.astype(np.float32) * [1, 2, 3, 4], rtol=1e-6)

    # with input_norm: fused (x - mean) * scale on device
    m2 = ONNXModel(model_bytes=blob,
                   input_norm={"data": {"mean": 16.0, "scale": 0.25}})
    out2 = m2._executor()(pix)[0]
    want = (pix.astype(np.float32) - 16.0) * 0.25 * [1, 2, 3, 4]
    np.testing.assert_allclose(out2, want, rtol=1e-6)

    # the wire carried uint8: host coercion must not widen the feed
    from synapseml_tpu.runtime.executor import coerce_host_array
    assert coerce_host_array(pix, compute_dtype="bfloat16").dtype == np.uint8


def test_uint8_feed_integer_graph_input_not_cast():
    """Integer feeds to graph inputs that WANT integers (token ids) must
    stay integer — the device cast only fires for float-wanting inputs."""
    g = GraphBuilder(opset=17)
    x = g.add_input("ids", np.int32, ["N"])
    y = g.add_node("Add", [x, g.add_initializer(
        "one", np.array(1, np.int32))])
    g.add_output(y, np.int32, ["N"])
    m = ONNXModel(model_bytes=g.to_bytes(), compute_dtype="bfloat16")
    out = m._executor()(np.arange(6, dtype=np.int32))[0]
    assert out.dtype.kind == "i"
    np.testing.assert_array_equal(out, np.arange(6) + 1)


def test_stft_matches_torch():
    """ONNX STFT (opset 17) vs torch.stft with center=False — the audio
    front-end op, certified against a foreign implementation."""
    b, length, flen, step = 2, 400, 64, 32
    rng = np.random.default_rng(0)
    sig = rng.normal(size=(b, length)).astype(np.float32)
    win = np.hanning(flen).astype(np.float32)

    g = GraphBuilder(opset=17)
    s_in = g.add_input("signal", np.float32, [b, length])
    step_i = g.add_initializer("step", np.asarray(step, np.int64))
    win_i = g.add_initializer("win", win)
    y = g.add_node("STFT", [s_in, step_i, win_i], onesided=1)
    g.add_output(y, np.float32, None)
    gi = import_model(g.to_bytes())
    got = np.asarray(gi.apply(gi.params, sig)[0])

    want_c = torch.stft(torch.from_numpy(sig), n_fft=flen,
                        hop_length=step, win_length=flen,
                        window=torch.from_numpy(win), center=False,
                        onesided=True, return_complex=True).numpy()
    # torch layout [B, bins, frames]; ONNX [B, frames, bins, 2]
    want = np.stack([want_c.real, want_c.imag], axis=-1) \
        .transpose(0, 2, 1, 3)
    assert got.shape == want.shape
    np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-4)

    # no-window form: frame_length drives the geometry (rect window)
    g2 = GraphBuilder(opset=17)
    s2 = g2.add_input("signal", np.float32, [b, length])
    y2 = g2.add_node("STFT", [
        s2, g2.add_initializer("st", np.asarray(step, np.int64)),
        "", g2.add_initializer("fl", np.asarray(flen, np.int64))],
        onesided=1)
    g2.add_output(y2, np.float32, None)
    gi2 = import_model(g2.to_bytes())
    got2 = np.asarray(gi2.apply(gi2.params, sig)[0])
    want2_c = torch.stft(torch.from_numpy(sig), n_fft=flen,
                         hop_length=step, win_length=flen,
                         window=torch.ones(flen), center=False,
                         onesided=True, return_complex=True).numpy()
    want2 = np.stack([want2_c.real, want2_c.imag], axis=-1) \
        .transpose(0, 2, 1, 3)
    np.testing.assert_allclose(got2, want2, rtol=1e-4, atol=1e-4)


def test_random_family_ops():
    """RandomNormal/Uniform(/Like)/Bernoulli/Multinomial: deterministic
    per node (XLA cannot express ambient nondeterminism — the spec
    leaves unseeded behavior implementation-defined), statistically
    sane, distinct across nodes."""
    g = GraphBuilder(opset=17)
    n1 = g.add_node("RandomNormal", [], shape=[2000], scale=2.0, mean=1.0)
    n2 = g.add_node("RandomNormal", [], shape=[2000])
    u = g.add_node("RandomUniform", [], shape=[2000], low=-1.0, high=3.0)
    x_in = g.add_input("x", np.float32, [500])
    nl = g.add_node("RandomNormalLike", [x_in])
    bern = g.add_node("Bernoulli", [x_in])
    for nm in (n1, n2, u, nl, bern):
        g.add_output(nm, np.float32, None)
    gi = import_model(g.to_bytes())
    probs = np.full(500, 0.25, np.float32)
    a1, a2, au, anl, ab = [np.asarray(o) for o in gi.apply(gi.params, probs)]
    assert abs(a1.mean() - 1.0) < 0.2 and abs(a1.std() - 2.0) < 0.2
    assert abs(a2.mean()) < 0.2 and not np.allclose(a1, a2 * 2 + 1)
    # UNNAMED nodes (common in exporter output) must still draw
    # distinctly: fallback seeds derive from output names, not node.name
    stripped = proto.load_model(g.to_bytes())
    for nd in stripped.graph.node:
        nd.name = ""
    gs = import_model(proto.encode(stripped))
    s1, s2 = [np.asarray(o) for o in gs.apply(gs.params, probs)[:2]]
    assert not np.allclose((s1 - 1.0) / 2.0, s2)
    assert au.min() >= -1.0 and au.max() <= 3.0 and abs(au.mean() - 1.0) < 0.2
    assert anl.shape == (500,)
    assert set(np.unique(ab)) <= {0.0, 1.0}
    assert abs(ab.mean() - 0.25) < 0.1
    # deterministic across runs
    b1 = np.asarray(gi.apply(gi.params, probs)[0])
    np.testing.assert_array_equal(a1, b1)

    # Multinomial: draws follow the (log-prob) weights
    g2 = GraphBuilder(opset=17)
    lp = g2.add_input("logp", np.float32, [1, 3])
    m = g2.add_node("Multinomial", [lp], sample_size=2000, dtype=6)
    g2.add_output(m, np.int32, None)
    gi2 = import_model(g2.to_bytes())
    draws = np.asarray(gi2.apply(
        gi2.params, np.log(np.array([[0.7, 0.2, 0.1]], np.float32)))[0])
    assert draws.shape == (1, 2000)
    frac0 = (draws == 0).mean()
    assert 0.6 < frac0 < 0.8


def test_mel_weight_matrix_spec_properties():
    """MelWeightMatrix: triangular HTK-mel filters — peaks at the mel
    centers, zero outside [lower, upper], correct shape/dtype."""
    g = GraphBuilder(opset=17)
    y = g.add_node("MelWeightMatrix", [
        g.add_initializer("nmel", np.asarray(8, np.int64)),
        g.add_initializer("ndft", np.asarray(128, np.int64)),
        g.add_initializer("sr", np.asarray(8000, np.int64)),
        g.add_initializer("lo", np.asarray(100.0, np.float32)),
        g.add_initializer("hi", np.asarray(3800.0, np.float32))])
    g.add_output(y, np.float32, None)
    gi = import_model(g.to_bytes())
    w = np.asarray(gi.apply(gi.params)[0])
    assert w.shape == (65, 8)  # [dft//2+1, n_mel]
    assert (w >= 0).all() and w.max() <= 1.0 + 1e-6
    # spec quantizes edges to bins: every filter peaks at EXACTLY 1.0
    np.testing.assert_allclose(w.max(axis=0), 1.0)
    bin_hz = np.arange(65) * 8000 / 128
    # columns are triangles: each has one contiguous support inside
    # (100, 3800) and every filter has some energy
    assert (w.sum(axis=0) > 0).all()
    assert (w[bin_hz < 100] == 0).all()
    assert (w[bin_hz > 3800] == 0).all()
    # mel centers increase monotonically
    centers = w.argmax(axis=0)
    assert (np.diff(centers) > 0).all()


def test_external_data_save_load_roundtrip(tmp_path):
    """save_model(external_data_threshold=...) moves big initializers to
    a ``.data`` sidecar; import_model(path) resolves them transparently
    and the resolved graph matches the in-memory original."""
    g = GraphBuilder(opset=17)
    x = g.add_input("x", np.float32, ["N", 8])
    w = np.random.default_rng(0).normal(size=(8, 4)).astype(np.float32)
    b = np.array([1.0, -1.0, 0.5, 0.0], np.float32)
    y = g.add_node("MatMul", [x, g.add_initializer("w", w)])
    y = g.add_node("Add", [y, g.add_initializer("b", b)])
    g.add_output(y, np.float32, ["N", 4])
    blob = g.to_bytes()

    model = proto.load_model(blob)
    path = tmp_path / "m.onnx"
    proto.save_model(model, str(path), external_data_threshold=16)
    assert (tmp_path / "m.onnx.data").exists()
    # w (128 B) externalized, b (16 B) too; model file carries no payload
    reparsed = proto.load_model(path.read_bytes())
    assert all(not t.raw_data for t in reparsed.graph.initializer)

    gi = import_model(str(path))
    xv = np.random.default_rng(1).normal(size=(3, 8)).astype(np.float32)
    np.testing.assert_allclose(
        np.asarray(gi.apply(gi.params, xv)[0]), xv @ w + b, rtol=1e-5)

    # the caller's in-memory model is untouched by externalizing save
    assert all(t.raw_data and int(t.data_location or 0) == 0
               for t in model.graph.initializer)


def test_external_data_via_onnxmodel_path(tmp_path):
    """ONNXModel(model_path=...) must resolve sidecars against the model
    directory and produce a self-contained payload (survives save/load
    away from the sidecar)."""
    g = GraphBuilder(opset=17)
    x = g.add_input("x", np.float32, ["N", 4])
    w = np.random.default_rng(2).normal(size=(4, 3)).astype(np.float32)
    y = g.add_node("MatMul", [x, g.add_initializer("w", w)])
    g.add_output(y, np.float32, ["N", 3])
    model = proto.load_model(g.to_bytes())
    path = tmp_path / "m.onnx"
    proto.save_model(model, str(path), external_data_threshold=1)

    m = ONNXModel(model_path=str(path))
    xv = np.random.default_rng(3).normal(size=(2, 4)).astype(np.float32)
    np.testing.assert_allclose(
        m._executor()(xv)[0], xv @ w, rtol=1e-5)
    # payload is self-contained: no unresolved external references
    reparsed = proto.load_model(m.model_payload)
    assert all(int(t.data_location or 0) == 0
               for t in reparsed.graph.initializer)

    # a model with NO external data keeps its file bytes verbatim (no
    # lossy re-encode through the mini-schema)
    plain = tmp_path / "plain.onnx"
    plain.write_bytes(g.to_bytes())
    m_plain = ONNXModel(model_path=str(plain))
    assert bytes(m_plain.model_payload) == g.to_bytes()


def test_external_data_location_escape_rejected(tmp_path):
    """A location that walks out of the model directory must be refused
    (a hostile model file must not read arbitrary host paths)."""
    g = GraphBuilder(opset=17)
    x = g.add_input("x", np.float32, ["N", 2])
    y = g.add_node("Mul", [x, g.add_initializer(
        "s", np.array([2.0, 3.0], np.float32))])
    g.add_output(y, np.float32, ["N", 2])
    model = proto.load_model(g.to_bytes())
    t = model.graph.initializer[0]
    e = proto.Msg("StringStringEntryProto")
    e.key, e.value = "location", "../outside.bin"
    t.external_data = [e]
    t.data_location = 1
    t.raw_data = b""
    mdir = tmp_path / "mdl"
    mdir.mkdir()
    (tmp_path / "outside.bin").write_bytes(
        np.array([9.0, 9.0], np.float32).tobytes())
    path = mdir / "m.onnx"
    proto.save_model(model, str(path))
    with pytest.raises(ValueError, match="escapes"):
        import_model(str(path))


def test_external_data_symlink_escape_rejected(tmp_path):
    """A symlink inside the model dir must not smuggle reads outside it
    (realpath, not abspath, guards the boundary)."""
    import os

    g = GraphBuilder(opset=17)
    x = g.add_input("x", np.float32, ["N", 2])
    y = g.add_node("Mul", [x, g.add_initializer(
        "s", np.array([2.0, 3.0], np.float32))])
    g.add_output(y, np.float32, ["N", 2])
    model = proto.load_model(g.to_bytes())
    t = model.graph.initializer[0]
    e = proto.Msg("StringStringEntryProto")
    e.key, e.value = "location", "link/secret.bin"
    t.external_data = [e]
    t.data_location = 1
    t.raw_data = b""
    mdir = tmp_path / "mdl"
    mdir.mkdir()
    outside = tmp_path / "outside"
    outside.mkdir()
    (outside / "secret.bin").write_bytes(
        np.array([9.0, 9.0], np.float32).tobytes())
    os.symlink(outside, mdir / "link")
    path = mdir / "m.onnx"
    proto.save_model(model, str(path))
    with pytest.raises(ValueError, match="escapes"):
        import_model(str(path))


def test_input_norm_unknown_name_rejected():
    g = GraphBuilder(opset=17)
    x = g.add_input("data", np.float32, ["N", 2])
    y = g.add_node("Relu", [x])
    g.add_output(y, np.float32, ["N", 2])
    m = ONNXModel(model_bytes=g.to_bytes(),
                  input_norm={"Data": {"mean": 1.0}})  # typo'd case
    with pytest.raises(KeyError, match="Data"):
        m._executor()
    # typo'd spec key ('std' instead of 'scale') must not silently no-op
    m2 = ONNXModel(model_bytes=g.to_bytes(),
                   input_norm={"data": {"mean": 1.0, "std": 2.0}})
    with pytest.raises(KeyError, match="std"):
        m2._executor()
    # normalizing an integer-declared input is a misconfiguration
    gi = GraphBuilder(opset=17)
    x = gi.add_input("ids", np.int64, ["N"])
    y = gi.add_node("Identity", [x])
    gi.add_output(y, np.int64, ["N"])
    m3 = ONNXModel(model_bytes=gi.to_bytes(),
                   input_norm={"ids": {"mean": 0.5}})
    with pytest.raises(TypeError, match="integer"):
        m3._executor()


def test_resnet50_full_network_parity_vs_torch_224():
    """The bench flagship at BENCH RESOLUTION (224x224, bs=1): certifies
    the spatial-shape-dependent paths the 32px case can't — the 7x7/s2
    stem pad arithmetic, every stride-2 transition at full extent, and
    the final pool reduction window (round-3 review item)."""
    blob = zoo.resnet50(image_size=224, seed=5)
    g = import_model(blob)
    x = np.random.default_rng(3).normal(
        size=(1, 3, 224, 224)).astype(np.float32)
    got = np.asarray(g.apply(g.params, x)[0])
    twin = _TorchResNet([3, 4, 6, 3], bottleneck=True, num_classes=1000,
                        width=64, seed=5).eval()
    with torch.no_grad():
        want = twin(torch.from_numpy(x)).numpy()
    assert got.shape == (1, 1000)
    np.testing.assert_allclose(got, want, rtol=1e-3, atol=1e-3)


def test_resnet18_full_network_parity_vs_torch():
    """Basic-block variant through the same twin machinery."""
    blob = zoo.resnet18(image_size=32, seed=9)
    g = import_model(blob)
    x = np.random.default_rng(1).normal(
        size=(2, 3, 32, 32)).astype(np.float32)
    got = np.asarray(g.apply(g.params, x)[0])
    twin = _TorchResNet([2, 2, 2, 2], bottleneck=False, num_classes=1000,
                        width=64, seed=9).eval()
    with torch.no_grad():
        want = twin(torch.from_numpy(x)).numpy()
    np.testing.assert_allclose(got, want, rtol=1e-3, atol=1e-3)


# ---------------------------------------------------------------------------
# round-3 op additions (quantization, norm/pool families, misc)
# ---------------------------------------------------------------------------

def _unary_graph(op_name, shape, **attrs):
    g = GraphBuilder(opset=21)
    x = g.add_input("x", np.float32, list(shape))
    y = g.add_node(op_name, [x], **attrs)
    g.add_output(y, np.float32, list(shape))
    return import_model(g.to_bytes())


def test_celu_thresholded_relu_shrink_match_torch():
    x = np.random.default_rng(0).normal(size=(4, 6)).astype(np.float32) * 2
    xt = torch.from_numpy(x)
    cases = [
        ("Celu", dict(alpha=0.7), torch.celu(xt, alpha=0.7)),
        ("ThresholdedRelu", dict(alpha=0.9),
         torch.nn.functional.threshold(xt, 0.9, 0.0)),
        # Shrink has NO torch twin: softshrink hard-wires bias=lambd,
        # ONNX separates them — manual reference below
        ("Shrink", dict(lambd=0.5, bias=0.1), None),
    ]
    for op_name, attrs, want in cases:
        g = _unary_graph(op_name, (4, 6), **attrs)
        got = np.asarray(g.apply(g.params, x)[0])
        if want is None:  # Shrink: manual reference (torch softshrink
            # uses bias=lambd; ONNX separates them)
            want_np = np.where(x < -0.5, x + 0.1,
                               np.where(x > 0.5, x - 0.1, 0.0))
            np.testing.assert_allclose(got, want_np, atol=1e-6)
        else:
            np.testing.assert_allclose(got, want.numpy(), atol=1e-5)


def test_group_normalization_matches_torch():
    n, c, h, w = 2, 8, 5, 5
    x = np.random.default_rng(1).normal(size=(n, c, h, w)).astype(np.float32)
    gn = nn.GroupNorm(4, c).eval()
    with torch.no_grad():
        gn.weight.normal_(1, 0.2)
        gn.bias.normal_(0, 0.2)
    g = GraphBuilder(opset=21)
    xn = g.add_input("x", np.float32, ["N", c, h, w])
    s = g.add_initializer("s", gn.weight.detach().numpy())
    b = g.add_initializer("b", gn.bias.detach().numpy())
    y = g.add_node("GroupNormalization", [xn, s, b], num_groups=4,
                   epsilon=float(gn.eps))
    g.add_output(y, np.float32, ["N", c, h, w])
    gi = import_model(g.to_bytes())
    with torch.no_grad():
        want = gn(torch.from_numpy(x)).numpy()
    np.testing.assert_allclose(np.asarray(gi.apply(gi.params, x)[0]),
                               want, atol=1e-5, rtol=1e-5)


def test_quantize_dequantize_roundtrip_and_matmul_integer():
    rng = np.random.default_rng(2)
    x = rng.normal(size=(4, 8)).astype(np.float32)
    g = GraphBuilder(opset=21)
    xn = g.add_input("x", np.float32, ["N", 8])
    scale = g.add_initializer("sc", np.float32(0.05))
    zp = g.add_initializer("zp", np.uint8(128))
    q = g.add_node("QuantizeLinear", [xn, scale, zp])
    d = g.add_node("DequantizeLinear", [q, scale, zp])
    g.add_output(d, np.float32, ["N", 8])
    gi = import_model(g.to_bytes())
    got = np.asarray(gi.apply(gi.params, x)[0])
    # torch reference for the same affine quantization
    tq = torch.quantize_per_tensor(torch.from_numpy(x), 0.05, 128,
                                   torch.quint8).dequantize().numpy()
    np.testing.assert_allclose(got, tq, atol=1e-6)

    # int8 matmul accumulates in int32
    a = rng.integers(0, 255, (3, 4)).astype(np.uint8)
    b = rng.integers(-127, 127, (4, 5)).astype(np.int8)
    g2 = GraphBuilder(opset=21)
    an = g2.add_input("a", np.uint8, ["N", 4])
    bn_ = g2.add_initializer("b", b)
    azp = g2.add_initializer("azp", np.uint8(10))
    y = g2.add_node("MatMulInteger", [an, bn_, azp])
    g2.add_output(y, np.int32, ["N", 5])
    gi2 = import_model(g2.to_bytes())
    want = (a.astype(np.int32) - 10) @ b.astype(np.int32)
    np.testing.assert_array_equal(
        np.asarray(gi2.apply(gi2.params, a)[0]), want)


def test_lp_pool_and_normalization_families():
    x = np.random.default_rng(3).normal(size=(2, 3, 8, 8)).astype(
        np.float32)
    xt = torch.from_numpy(x)
    g = GraphBuilder(opset=21)
    xn = g.add_input("x", np.float32, ["N", 3, 8, 8])
    y = g.add_node("LpPool", [xn], kernel_shape=[2, 2], strides=[2, 2], p=2)
    g.add_output(y, np.float32, ["N", 3, 4, 4])
    gi = import_model(g.to_bytes())
    want = nn.LPPool2d(2, 2, stride=2)(xt).numpy()
    np.testing.assert_allclose(np.asarray(gi.apply(gi.params, x)[0]), want,
                               atol=1e-4, rtol=1e-4)

    g2 = GraphBuilder(opset=21)
    xn2 = g2.add_input("x", np.float32, ["N", 3, 8, 8])
    y2 = g2.add_node("GlobalLpPool", [xn2], p=2)
    g2.add_output(y2, np.float32, ["N", 3, 1, 1])
    gi2 = import_model(g2.to_bytes())
    want2 = np.sqrt((x ** 2).sum(axis=(2, 3), keepdims=True))
    np.testing.assert_allclose(np.asarray(gi2.apply(gi2.params, x)[0]),
                               want2, atol=1e-4, rtol=1e-4)

    v = np.random.default_rng(4).normal(size=(5, 7)).astype(np.float32)
    g3 = _unary_graph("LpNormalization", (5, 7), axis=-1, p=2)
    np.testing.assert_allclose(
        np.asarray(g3.apply(g3.params, v)[0]),
        torch.nn.functional.normalize(torch.from_numpy(v), dim=-1).numpy(),
        atol=1e-6)


def test_eyelike_reverse_sequence_nonzero():
    # EyeLike: host-static identity
    g = GraphBuilder(opset=21)
    xn = g.add_input("x", np.float32, [4, 5])
    y = g.add_node("EyeLike", [xn], k=1)
    g.add_output(y, np.float32, [4, 5])
    gi = import_model(g.to_bytes())
    np.testing.assert_array_equal(
        np.asarray(gi.apply(gi.params, np.zeros((4, 5), np.float32))[0]),
        np.eye(4, 5, k=1, dtype=np.float32))

    # ReverseSequence matches manual per-row reversal
    x = np.arange(24, dtype=np.float32).reshape(4, 3, 2)  # [T=4, B=3, 2]
    lens = np.array([4, 2, 1], np.int64)
    g2 = GraphBuilder(opset=21)
    xn2 = g2.add_input("x", np.float32, [4, 3, 2])
    ln = g2.add_initializer("lens", lens)
    y2 = g2.add_node("ReverseSequence", [xn2, ln], batch_axis=1,
                     time_axis=0)
    g2.add_output(y2, np.float32, [4, 3, 2])
    gi2 = import_model(g2.to_bytes())
    want = x.copy()
    for b, l in enumerate(lens):
        want[:l, b] = x[:l, b][::-1]
    np.testing.assert_array_equal(
        np.asarray(gi2.apply(gi2.params, x)[0]), want)

    # NonZero on a static initializer folds on host
    from synapseml_tpu.onnx.importer import _non_zero

    class _Ctx:
        def attr(self, *a):
            return a[1] if len(a) > 1 else None
    m = np.array([[1, 0], [0, 2]], np.float32)
    np.testing.assert_array_equal(_non_zero(_Ctx(), m),
                                  np.stack(np.nonzero(m)))


# ---------------------------------------------------------------------------
# Detection-era + statically-quantized export families
# (ref ONNXModel.scala:173-193 — the reference scores whatever ORT runs)
# ---------------------------------------------------------------------------

def test_dynamic_quantize_linear_spec_formula():
    """DynamicQuantizeLinear follows the ONNX spec exactly: range extended
    to include zero, scale over 255 steps, saturating uint8."""
    rng = np.random.default_rng(0)
    for x in [rng.normal(size=(3, 17)).astype(np.float32) * 4,
              np.abs(rng.normal(size=(5,)).astype(np.float32)),  # min>0
              -np.abs(rng.normal(size=(5,)).astype(np.float32)),  # max<0
              np.zeros((4,), np.float32)]:                        # degenerate
        g = GraphBuilder(opset=21)
        xn = g.add_input("x", np.float32, list(x.shape))
        y, ys, yzp = g.add_node("DynamicQuantizeLinear", [xn],
                                outputs=["y", "ys", "yzp"])
        g.add_output(y, np.uint8, list(x.shape))
        g.add_output(ys, np.float32, [])
        g.add_output(yzp, np.uint8, [])
        gi = import_model(g.to_bytes())
        qy, qs, qzp = [np.asarray(o) for o in gi.apply(gi.params, x)]
        mn, mx = min(x.min(), 0.0), max(x.max(), 0.0)
        scale = (mx - mn) / 255.0 or 1.0
        zp = np.clip(np.rint(-mn / scale), 0, 255)
        np.testing.assert_allclose(qs, scale, rtol=1e-6)
        assert qzp == zp and qzp.dtype == np.uint8
        want = np.clip(np.rint(x / scale) + zp, 0, 255).astype(np.uint8)
        np.testing.assert_array_equal(qy, want)


def _qlinear_conv_graph(x_shape, w, xs, xzp, ws, wzp, ys, yzp, b=None,
                        **conv_attrs):
    g = GraphBuilder(opset=21)
    xn = g.add_input("x", np.uint8, list(x_shape))
    ins = [xn,
           g.add_initializer("xs", np.float32(xs)),
           g.add_initializer("xzp", np.uint8(xzp)),
           g.add_initializer("w", w),
           g.add_initializer("ws", np.asarray(ws, np.float32)),
           g.add_initializer("wzp", np.asarray(wzp, np.int8)),
           g.add_initializer("ys", np.float32(ys)),
           g.add_initializer("yzp", np.uint8(yzp))]
    if b is not None:
        ins.append(g.add_initializer("b", np.asarray(b, np.int32)))
    y = g.add_node("QLinearConv", ins, **conv_attrs)
    g.add_output(y, np.uint8, None)
    return import_model(g.to_bytes())


def test_qlinear_conv_matches_torch_quantized():
    """Foreign ground truth: torch.ao.nn.quantized Conv2d (fbgemm) on the
    same scales/zero-points. Requantization rounding may differ by one
    ulp on ties, so the gate is <=1 LSB everywhere and overwhelmingly
    exact."""
    import torch.ao.nn.quantized as nnq
    if "fbgemm" not in torch.backends.quantized.supported_engines:
        pytest.skip("torch built without the fbgemm quantized engine")
    prev_engine = torch.backends.quantized.engine
    torch.backends.quantized.engine = "fbgemm"
    try:
        _check_qlinear_conv_against_torch(nnq)
    finally:
        torch.backends.quantized.engine = prev_engine


def _check_qlinear_conv_against_torch(nnq):
    rng = np.random.default_rng(1)
    cin, cout, k = 3, 8, 3
    xs, xzp, ys, yzp = 0.05, 128, 0.12, 100
    xq = rng.integers(0, 255, (2, cin, 10, 10)).astype(np.uint8)
    x_f = (xq.astype(np.float32) - xzp) * xs

    for per_channel in (False, True):
        wq = rng.integers(-100, 100, (cout, cin, k, k)).astype(np.int8)
        if per_channel:
            ws = (rng.random(cout) * 0.03 + 0.01).astype(np.float32)
            w_f = wq.astype(np.float32) * ws[:, None, None, None]
            qw = torch.quantize_per_channel(
                torch.from_numpy(w_f), torch.from_numpy(ws),
                torch.zeros(cout, dtype=torch.long), 0, torch.qint8)
            wzp = np.zeros(cout, np.int8)
        else:
            ws = np.float32(0.02)
            w_f = wq.astype(np.float32) * float(ws)
            qw = torch.quantize_per_tensor(torch.from_numpy(w_f),
                                           float(ws), 0, torch.qint8)
            wzp = np.int8(0)
        b_f = rng.normal(size=cout).astype(np.float32)
        # ONNX bias: int32 at scale xs*ws
        b_i32 = np.rint(b_f / (xs * np.asarray(ws))).astype(np.int32)
        b_used = b_i32.astype(np.float32) * (xs * np.asarray(ws))

        conv = nnq.Conv2d(cin, cout, k, stride=1, padding=1)
        conv.set_weight_bias(qw, torch.from_numpy(b_used))
        conv.scale, conv.zero_point = ys, yzp
        qx = torch.quantize_per_tensor(torch.from_numpy(x_f), xs, xzp,
                                       torch.quint8)
        want = conv(qx).int_repr().numpy()

        gi = _qlinear_conv_graph(xq.shape, wq, xs, xzp, ws, wzp, ys, yzp,
                                 b=b_i32, strides=[1, 1],
                                 pads=[1, 1, 1, 1])
        got = np.asarray(gi.apply(gi.params, xq)[0])
        assert got.dtype == np.uint8
        diff = np.abs(got.astype(np.int32) - want.astype(np.int32))
        assert diff.max() <= 1, (per_channel, diff.max())
        assert (diff == 0).mean() > 0.98, (per_channel, (diff == 0).mean())


def test_qlinear_matmul_and_conv_integer_exact_int_semantics():
    """QLinearMatMul against exact integer arithmetic + spec
    requantization; ConvInteger against a float64 conv over the
    zero-point-shifted operands (exact for int8 ranges)."""
    rng = np.random.default_rng(2)
    a = rng.integers(0, 255, (4, 6)).astype(np.uint8)
    b = rng.integers(-127, 127, (6, 5)).astype(np.int8)
    a_s, a_zp, b_s, b_zp, y_s, y_zp = 0.03, 120, 0.05, 3, 0.2, 64
    g = GraphBuilder(opset=21)
    an = g.add_input("a", np.uint8, [4, 6])
    ins = [an, g.add_initializer("as_", np.float32(a_s)),
           g.add_initializer("azp", np.uint8(a_zp)),
           g.add_initializer("b", b),
           g.add_initializer("bs", np.float32(b_s)),
           g.add_initializer("bzp", np.int8(b_zp)),
           g.add_initializer("ys", np.float32(y_s)),
           g.add_initializer("yzp", np.uint8(y_zp))]
    y = g.add_node("QLinearMatMul", ins)
    g.add_output(y, np.uint8, [4, 5])
    gi = import_model(g.to_bytes())
    got = np.asarray(gi.apply(gi.params, a)[0])
    acc = (a.astype(np.int64) - a_zp) @ (b.astype(np.int64) - b_zp)
    want = np.clip(
        np.rint(acc.astype(np.float32) * np.float32(a_s * b_s / y_s))
        + y_zp, 0, 255).astype(np.uint8)
    np.testing.assert_array_equal(got, want)

    # ConvInteger: raw int32 accumulator
    x = rng.integers(0, 255, (1, 2, 7, 7)).astype(np.uint8)
    w = rng.integers(-127, 127, (4, 2, 3, 3)).astype(np.int8)
    g2 = GraphBuilder(opset=21)
    xn = g2.add_input("x", np.uint8, [1, 2, 7, 7])
    ins2 = [xn, g2.add_initializer("w", w),
            g2.add_initializer("xzp", np.uint8(99))]
    y2 = g2.add_node("ConvInteger", ins2, pads=[1, 1, 1, 1])
    g2.add_output(y2, np.int32, None)
    gi2 = import_model(g2.to_bytes())
    got2 = np.asarray(gi2.apply(gi2.params, x)[0])
    want2 = torch.nn.functional.conv2d(
        torch.from_numpy(x.astype(np.float64) - 99.0),
        torch.from_numpy(w.astype(np.float64)), padding=1).numpy()
    np.testing.assert_array_equal(got2, want2.astype(np.int32))


def _nms_graph(n, max_out, iou, score_th=None, center=0, nb=1, nc=1):
    g = GraphBuilder(opset=21)
    bn = g.add_input("boxes", np.float32, [nb, n, 4])
    sn = g.add_input("scores", np.float32, [nb, nc, n])
    ins = [bn, sn, g.add_initializer("mo", np.int64(max_out)),
           g.add_initializer("iou", np.float32(iou))]
    if score_th is not None:
        ins.append(g.add_initializer("st", np.float32(score_th)))
    y = g.add_node("NonMaxSuppression", ins, center_point_box=center)
    g.add_output(y, np.int64, None)
    return import_model(g.to_bytes())


def test_nonmax_suppression_onnx_spec_case():
    """The canonical ONNX NMS example (suppress-by-IOU): host path gives
    the exact [num_selected, 3]; the traced (jit) path gives the same
    rows in fixed-capacity form with -1 padding."""
    boxes = np.array([[[0.0, 0.0, 1.0, 1.0], [0.0, 0.1, 1.0, 1.1],
                       [0.0, -0.1, 1.0, 0.9], [0.0, 10.0, 1.0, 11.0],
                       [0.0, 10.1, 1.0, 11.1], [0.0, 100.0, 1.0, 101.0]]],
                     np.float32)
    scores = np.array([[[0.9, 0.75, 0.6, 0.95, 0.5, 0.3]]], np.float32)
    want = np.array([[0, 0, 3], [0, 0, 0], [0, 0, 5]], np.int64)

    gi = _nms_graph(6, max_out=3, iou=0.5)
    host = np.asarray(gi.apply(gi.params, boxes, scores)[0])
    np.testing.assert_array_equal(host, want)

    import jax
    traced = np.asarray(jax.jit(gi.apply)(
        gi.params, jnp.asarray(boxes), jnp.asarray(scores))[0])
    assert traced.shape == (3, 3)  # 1 batch x 1 class x max_out
    np.testing.assert_array_equal(traced[traced[:, 2] >= 0], want)

    # score threshold + flipped-corner boxes + multi-class, traced vs host
    rng = np.random.default_rng(5)
    nb, nc, n = 2, 3, 40
    centers = rng.random((nb, n, 2)).astype(np.float32) * 4
    sizes = rng.random((nb, n, 2)).astype(np.float32) + 0.3
    b2 = np.concatenate([centers - sizes / 2, centers + sizes / 2],
                        axis=-1)[..., [1, 0, 3, 2]]  # y1 x1 y2 x2
    # randomly swap the diagonal (spec: order-free corners)
    swap = rng.random((nb, n)) < 0.5
    b2[swap] = b2[swap][:, [2, 3, 0, 1]]
    s2 = rng.random((nb, nc, n)).astype(np.float32)
    gi2 = _nms_graph(n, max_out=5, iou=0.45, score_th=0.2, nb=nb, nc=nc)
    host2 = np.asarray(gi2.apply(gi2.params, b2.astype(np.float32), s2)[0])
    traced2 = np.asarray(jax.jit(gi2.apply)(
        gi2.params, jnp.asarray(b2, jnp.float32), jnp.asarray(s2))[0])
    np.testing.assert_array_equal(traced2[traced2[:, 2] >= 0], host2)

    # center_point_box format agrees with the corner formulation
    bc = np.concatenate([centers, sizes], axis=-1).astype(np.float32)
    gi3 = _nms_graph(n, max_out=5, iou=0.45, score_th=0.2, nb=nb, nc=nc,
                     center=1)
    host3 = np.asarray(gi3.apply(gi3.params, bc, s2)[0])
    np.testing.assert_array_equal(host3, host2)


def _roi_align_ref(x, rois, bidx, oh, ow, sr, scale, mode, ctm):
    """Independent loop-based numpy implementation straight from the
    ONNX spec text (bilinear sampling with the -1/size outside rule)."""
    R = rois.shape[0]
    C, H, W = x.shape[1:]
    out = np.zeros((R, C, oh, ow), np.float32)
    off = 0.5 if ctm == "half_pixel" else 0.0
    for r in range(R):
        x1, y1, x2, y2 = rois[r] * scale - off
        rw, rh = x2 - x1, y2 - y1
        if ctm != "half_pixel":
            rw, rh = max(rw, 1.0), max(rh, 1.0)
        bw, bh = rw / ow, rh / oh
        fm = x[bidx[r]]
        for ph in range(oh):
            for pw in range(ow):
                vals = []
                for iy in range(sr):
                    for ix in range(sr):
                        yy = y1 + (ph + (iy + 0.5) / sr) * bh
                        xx = x1 + (pw + (ix + 0.5) / sr) * bw
                        if yy < -1.0 or yy > H or xx < -1.0 or xx > W:
                            vals.append(np.zeros(C, np.float32))
                            continue
                        yy, xx = min(max(yy, 0.0), H - 1), min(max(xx, 0.0), W - 1)
                        ylo, xlo = int(np.floor(yy)), int(np.floor(xx))
                        yhi, xhi = min(ylo + 1, H - 1), min(xlo + 1, W - 1)
                        fy, fx = yy - ylo, xx - xlo
                        v = (fm[:, ylo, xlo] * (1 - fy) * (1 - fx)
                             + fm[:, ylo, xhi] * (1 - fy) * fx
                             + fm[:, yhi, xlo] * fy * (1 - fx)
                             + fm[:, yhi, xhi] * fy * fx)
                        vals.append(v)
                stack = np.stack(vals)
                out[r, :, ph, pw] = (stack.max(0) if mode == "max"
                                     else stack.mean(0))
    return out


def test_roi_align_modes_and_transforms():
    rng = np.random.default_rng(6)
    x = rng.normal(size=(2, 3, 12, 12)).astype(np.float32)
    rois = np.array([[0.0, 0.0, 9.0, 9.0], [1.5, 2.0, 9.5, 11.0],
                     [4.0, 4.0, 6.0, 6.0], [-1.0, -1.0, 3.0, 3.0]],
                    np.float32)
    bidx = np.array([0, 1, 0, 1], np.int64)
    for mode in ("avg", "max"):
        for ctm, opset in (("output_half_pixel", 10), ("half_pixel", 16)):
            g = GraphBuilder(opset=max(opset, 16))
            xn = g.add_input("x", np.float32, [2, 3, 12, 12])
            rn = g.add_initializer("rois", rois)
            bn = g.add_initializer("bidx", bidx)
            y = g.add_node("RoiAlign", [xn, rn, bn], mode=mode,
                           output_height=4, output_width=3,
                           sampling_ratio=2, spatial_scale=0.5,
                           coordinate_transformation_mode=ctm)
            g.add_output(y, np.float32, [4, 3, 4, 3])
            gi = import_model(g.to_bytes())
            import jax
            got = np.asarray(jax.jit(gi.apply)(
                gi.params, jnp.asarray(x))[0])
            want = _roi_align_ref(x, rois, bidx, 4, 3, 2, 0.5, mode, ctm)
            np.testing.assert_allclose(got, want, atol=1e-5, rtol=1e-5,
                                       err_msg=f"{mode}/{ctm}")

    # sampling_ratio=0 is data-dependent under jit: explicit recipe error
    g = GraphBuilder(opset=16)
    xn = g.add_input("x", np.float32, [2, 3, 12, 12])
    rn = g.add_initializer("rois", rois)
    bn = g.add_initializer("bidx", bidx)
    y = g.add_node("RoiAlign", [xn, rn, bn], output_height=2,
                   output_width=2, sampling_ratio=0)
    g.add_output(y, np.float32, [4, 3, 2, 2])
    with pytest.raises(NotImplementedError, match="sampling_ratio"):
        gi = import_model(g.to_bytes())
        gi.apply(gi.params, x)


def test_detection_head_end_to_end():
    """Builder-composed detection head: conv backbone -> box-delta +
    score heads -> anchor decode (Mul/Add) -> NonMaxSuppression, traced
    through one jit. The selected indices must equal the host NMS run on
    the intermediate boxes/scores computed by a twin graph."""
    import jax

    rng = np.random.default_rng(8)
    n_anchors, img = 16, 8
    anchors = np.zeros((1, n_anchors, 4), np.float32)
    cy, cx = np.meshgrid(np.arange(4), np.arange(4), indexing="ij")
    anchors[0, :, 0] = cy.ravel() * 2
    anchors[0, :, 1] = cx.ravel() * 2
    anchors[0, :, 2] = cy.ravel() * 2 + 2.5
    anchors[0, :, 3] = cx.ravel() * 2 + 2.5

    def build(with_nms):
        g = GraphBuilder(opset=21)
        xn = g.add_input("x", np.float32, [1, 3, img, img])
        w1 = rng.normal(size=(8, 3, 3, 3)).astype(np.float32) * 0.4
        c1 = g.conv(xn, w1, pads=(1, 1, 1, 1))
        r1 = g.relu(c1)
        wb = rng.normal(size=(4, 8, 2, 2)).astype(np.float32) * 0.05
        box_map = g.conv(r1, wb, strides=(2, 2))          # [1,4,4,4]
        ws = rng.normal(size=(2, 8, 2, 2)).astype(np.float32) * 0.4
        sc_map = g.conv(r1, ws, strides=(2, 2))           # [1,2,4,4]
        # deltas [1,4,16] -> [1,16,4]; decode: anchors + 0.5*tanh(deltas)
        shp = g.add_initializer("shp", np.array([1, 4, 16], np.int64))
        box_r = g.add_node("Reshape", [box_map, shp])
        box_t = g.add_node("Transpose", [box_r], perm=[0, 2, 1])
        half = g.add_initializer("half", np.float32(0.5))
        delt = g.add_node("Mul", [g.add_node("Tanh", [box_t]), half])
        boxes = g.add_node("Add", [g.add_initializer("anchors", anchors),
                                   delt])
        shp2 = g.add_initializer("shp2", np.array([1, 2, 16], np.int64))
        sc_r = g.add_node("Reshape", [sc_map, shp2])
        scores = g.add_node("Sigmoid", [sc_r])            # [1,2,16]
        if not with_nms:
            g.add_output(boxes, np.float32, [1, n_anchors, 4])
            g.add_output(scores, np.float32, [1, 2, n_anchors])
            return g
        sel = g.add_node("NonMaxSuppression",
                         [boxes, scores,
                          g.add_initializer("mo", np.int64(4)),
                          g.add_initializer("iou", np.float32(0.5)),
                          g.add_initializer("st", np.float32(0.3))])
        g.add_output(sel, np.int64, None)
        return g

    rng_state = rng.bit_generator.state
    g_full = build(True)
    rng.bit_generator.state = rng_state     # identical weights
    g_mid = build(False)

    x = np.random.default_rng(9).normal(size=(1, 3, img, img)).astype(
        np.float32)
    gi = import_model(g_full.to_bytes())
    sel = np.asarray(jax.jit(gi.apply)(gi.params, jnp.asarray(x))[0])
    assert sel.shape == (1 * 2 * 4, 3)

    gm = import_model(g_mid.to_bytes())
    boxes_v, scores_v = [np.asarray(o) for o in gm.apply(gm.params, x)]
    from synapseml_tpu.onnx.importer import _nms_host
    want = _nms_host(boxes_v, scores_v, 4, 0.5, 0.3, 0)
    np.testing.assert_array_equal(sel[sel[:, 2] >= 0], want)


def test_qoperator_contrib_family():
    """The com.microsoft QOperator ops onnxruntime's static quantizer
    emits between QLinearConv/MatMul nodes: each against the exact
    dequant -> f32 op -> requant formula, plus a composed QOperator
    chain traced through one jit."""
    import jax

    rng = np.random.default_rng(3)

    def q(v, s, zp):
        return np.clip(np.rint(v / s) + zp, 0, 255).astype(np.uint8)

    def dq(x, s, zp):
        return (x.astype(np.float32) - zp) * s

    a = rng.integers(0, 255, (2, 3, 4, 4)).astype(np.uint8)
    b = rng.integers(0, 255, (2, 3, 4, 4)).astype(np.uint8)
    sa, za, sb, zb, sc, zc = 0.04, 120, 0.03, 110, 0.06, 128

    for op_name, fn in [("QLinearAdd", np.add),
                        ("QLinearMul", np.multiply)]:
        g = GraphBuilder(opset=21)
        an = g.add_input("a", np.uint8, list(a.shape))
        ins = [an, g.add_initializer("sa", np.float32(sa)),
               g.add_initializer("za", np.uint8(za)),
               g.add_initializer("b", b),
               g.add_initializer("sb", np.float32(sb)),
               g.add_initializer("zb", np.uint8(zb)),
               g.add_initializer("sc", np.float32(sc)),
               g.add_initializer("zc", np.uint8(zc))]
        y = g.add_node(op_name, ins, domain="com.microsoft")
        g.add_output(y, np.uint8, None)
        gi = import_model(g.to_bytes())
        got = np.asarray(jax.jit(gi.apply)(gi.params, jnp.asarray(a))[0])
        want = q(fn(dq(a, sa, za), dq(b, sb, zb)), sc, zc)
        # <=1 LSB, not bit-exact: under jit XLA rewrites the requant's
        # constant-divisor division (v / y_scale) into a multiply by
        # the reciprocal, which perturbs EXACT-TIE quotients (v/s =
        # n + 0.5 — e.g. 22.5, -12.5 in this fixture) by 1 ulp, so
        # round-half-to-even lands 1 LSB away from numpy's
        # true-division reference; unjitted jax matches numpy
        # element-exactly. ORT's own QLinear kernels promise the same
        # <=1 LSB (importer.py "matches ORT's lookup-table kernels"),
        # and the sigmoid/leakyrelu assertions below already use it —
        # assert that contract here too: never >1 off, ties rare.
        diff = np.abs(got.astype(int) - want.astype(int))
        assert diff.max() <= 1 and (diff == 0).mean() > 0.9, op_name

    # QLinearSigmoid + QLinearLeakyRelu + QLinearGlobalAveragePool
    x = rng.integers(0, 255, (2, 5, 6, 6)).astype(np.uint8)
    sx, zx, sy, zy = 0.02, 128, 1.0 / 256, 0
    for op_name, ref, attrs in [
        ("QLinearSigmoid",
         lambda v: 1 / (1 + np.exp(-v)), {}),
        ("QLinearLeakyRelu",
         lambda v: np.where(v >= 0, v, 0.1 * v), {"alpha": 0.1}),
    ]:
        g = GraphBuilder(opset=21)
        xn = g.add_input("x", np.uint8, list(x.shape))
        ins = [xn, g.add_initializer("sx", np.float32(sx)),
               g.add_initializer("zx", np.uint8(zx)),
               g.add_initializer("sy", np.float32(sy)),
               g.add_initializer("zy", np.uint8(zy))]
        y = g.add_node(op_name, ins, domain="com.microsoft", **attrs)
        g.add_output(y, np.uint8, None)
        gi = import_model(g.to_bytes())
        got = np.asarray(gi.apply(gi.params, x)[0])
        want = q(ref(dq(x, sx, zx)), sy, zy)
        diff = np.abs(got.astype(int) - want.astype(int))
        assert diff.max() <= 1 and (diff == 0).mean() > 0.99, op_name

    g = GraphBuilder(opset=21)
    xn = g.add_input("x", np.uint8, list(x.shape))
    ins = [xn, g.add_initializer("sx", np.float32(sx)),
           g.add_initializer("zx", np.uint8(zx)),
           g.add_initializer("sy", np.float32(0.015)),
           g.add_initializer("zy", np.uint8(100))]
    y = g.add_node("QLinearGlobalAveragePool", ins,
                   domain="com.microsoft")
    g.add_output(y, np.uint8, None)
    gi = import_model(g.to_bytes())
    got = np.asarray(gi.apply(gi.params, x)[0])
    want = q(dq(x, sx, zx).mean(axis=(2, 3), keepdims=True), 0.015, 100)
    diff = np.abs(got.astype(int) - want.astype(int))
    assert diff.max() <= 1, diff.max()

    # QLinearConcat: triplets after (Y_scale, Y_zp)
    g = GraphBuilder(opset=21)
    an = g.add_input("a", np.uint8, [2, 3])
    a2 = rng.integers(0, 255, (2, 3)).astype(np.uint8)
    b2 = rng.integers(0, 255, (2, 2)).astype(np.uint8)
    ins = [g.add_initializer("sy", np.float32(0.05)),
           g.add_initializer("zy", np.uint8(128)),
           an, g.add_initializer("s1", np.float32(0.04)),
           g.add_initializer("z1", np.uint8(100)),
           g.add_initializer("b2", b2),
           g.add_initializer("s2", np.float32(0.02)),
           g.add_initializer("z2", np.uint8(50))]
    y = g.add_node("QLinearConcat", ins, domain="com.microsoft", axis=1)
    g.add_output(y, np.uint8, [2, 5])
    gi = import_model(g.to_bytes())
    got = np.asarray(gi.apply(gi.params, a2)[0])
    want = q(np.concatenate([dq(a2, 0.04, 100), dq(b2, 0.02, 50)],
                            axis=1), 0.05, 128)
    np.testing.assert_array_equal(got, want)

    # QGemm: int accumulation, int32 bias, requantized AND float outputs
    A = rng.integers(0, 255, (3, 4)).astype(np.uint8)
    B = rng.integers(-127, 127, (4, 5)).astype(np.int8)
    bias = rng.integers(-500, 500, 5).astype(np.int32)
    for with_y in (True, False):
        g = GraphBuilder(opset=21)
        an = g.add_input("a", np.uint8, [3, 4])
        ins = [an, g.add_initializer("sa", np.float32(0.1)),
               g.add_initializer("za", np.uint8(10)),
               g.add_initializer("B", B),
               g.add_initializer("sb", np.float32(0.2)),
               g.add_initializer("zb", np.int8(3)),
               g.add_initializer("bias", bias)]
        if with_y:
            ins += [g.add_initializer("sy", np.float32(0.4)),
                    g.add_initializer("zy", np.uint8(64))]
        y = g.add_node("QGemm", ins, domain="com.microsoft", alpha=1.0)
        g.add_output(y, np.uint8 if with_y else np.float32, None)
        gi = import_model(g.to_bytes())
        got = np.asarray(gi.apply(gi.params, A)[0])
        acc = (A.astype(np.int64) - 10) @ (B.astype(np.int64) - 3) + bias
        if with_y:
            want = np.clip(np.rint(acc * np.float32(0.1 * 0.2 / 0.4))
                           + 64, 0, 255).astype(np.uint8)
            np.testing.assert_array_equal(got, want)
        else:
            np.testing.assert_allclose(
                got, acc * np.float32(0.1 * 0.2), rtol=1e-6)

    # composed QOperator chain through one jit: QLinearConv ->
    # QLinearSigmoid -> QLinearGlobalAveragePool
    w = rng.integers(-100, 100, (4, 5, 3, 3)).astype(np.int8)
    g = GraphBuilder(opset=21)
    xn = g.add_input("x", np.uint8, list(x.shape))
    conv = g.add_node("QLinearConv", [
        xn, g.add_initializer("cxs", np.float32(sx)),
        g.add_initializer("cxz", np.uint8(zx)),
        g.add_initializer("w", w),
        g.add_initializer("cws", np.float32(0.01)),
        g.add_initializer("cwz", np.int8(0)),
        g.add_initializer("cys", np.float32(0.05)),
        g.add_initializer("cyz", np.uint8(128))], pads=[1, 1, 1, 1])
    sig = g.add_node("QLinearSigmoid", [
        conv, g.add_initializer("ssx", np.float32(0.05)),
        g.add_initializer("ssz", np.uint8(128)),
        g.add_initializer("ssy", np.float32(1.0 / 256)),
        g.add_initializer("sszy", np.uint8(0))], domain="com.microsoft")
    pool = g.add_node("QLinearGlobalAveragePool", [
        sig, g.add_initializer("psx", np.float32(1.0 / 256)),
        g.add_initializer("psz", np.uint8(0)),
        g.add_initializer("psy", np.float32(1.0 / 256)),
        g.add_initializer("pszy", np.uint8(0))], domain="com.microsoft")
    g.add_output(pool, np.uint8, None)
    gi = import_model(g.to_bytes())
    out = np.asarray(jax.jit(gi.apply)(gi.params, jnp.asarray(x))[0])
    assert out.shape == (2, 4, 1, 1) and out.dtype == np.uint8
    assert out.min() >= 0 and int(out.max()) <= 255


def _one_op_graph(op_name, inputs, input_specs, out_dtype=np.float32,
                  opset=21, domain="", n_outputs=1, **attrs):
    """Single-node graph builder: ``inputs`` is an ordered list of
    (name, array_or_None) pairs — None marks a runtime input whose
    (dtype, shape) comes from ``input_specs``; arrays become
    initializers."""
    g = GraphBuilder(opset=opset)
    names = []
    for name, arr in inputs:
        if arr is None:
            dt, shp = input_specs[name]
            names.append(g.add_input(name, dt, shp))
        else:
            names.append(g.add_initializer(name, arr))
    outs = [f"out{i}" for i in range(n_outputs)]
    g.add_node(op_name, names, outputs=outs, domain=domain, **attrs)
    for o in outs:
        g.add_output(o, out_dtype, None)
    return import_model(g.to_bytes())


def test_bitwise_dft_centercroppad():
    rng = np.random.default_rng(0)
    a = rng.integers(0, 255, (3, 4)).astype(np.uint8)
    b = rng.integers(0, 255, (3, 4)).astype(np.uint8)
    for op_name, fn in [("BitwiseAnd", np.bitwise_and),
                        ("BitwiseOr", np.bitwise_or),
                        ("BitwiseXor", np.bitwise_xor)]:
        gi = _one_op_graph(op_name, [("a", None), ("b", b)],
                           {"a": (np.uint8, [3, 4])}, out_dtype=np.uint8)
        np.testing.assert_array_equal(
            np.asarray(gi.apply(gi.params, a)[0]), fn(a, b))
    gi = _one_op_graph("BitwiseNot", [("a", None)],
                       {"a": (np.uint8, [3, 4])}, out_dtype=np.uint8)
    np.testing.assert_array_equal(
        np.asarray(gi.apply(gi.params, a)[0]), np.invert(a))

    # DFT: real forward (onesided + full), complex inverse, negative axis
    sig = rng.normal(size=(2, 16, 1)).astype(np.float32)
    gi = _one_op_graph("DFT", [("x", None)],
                       {"x": (np.float32, [2, 16, 1])}, onesided=1)
    got = np.asarray(gi.apply(gi.params, sig)[0])
    spec = np.fft.rfft(sig[..., 0], axis=1)
    np.testing.assert_allclose(got[..., 0], spec.real, atol=2e-4)
    np.testing.assert_allclose(got[..., 1], spec.imag, atol=2e-4)

    # axis counts over the FULL rank incl. the trailing re/im dim, so
    # -2 (the opset-20 default, also valid explicitly) is the signal
    # axis of [2, 16, 1] — NOT the batch axis (round-5 review repro)
    gi = _one_op_graph("DFT", [("x", None),
                               ("dl", np.asarray(16, np.int64)),
                               ("ax", np.asarray(-2, np.int64))],
                       {"x": (np.float32, [2, 16, 1])}, onesided=1,
                       opset=21)
    got_neg = np.asarray(gi.apply(gi.params, sig)[0])
    np.testing.assert_allclose(got_neg, got, atol=1e-5)

    comp = rng.normal(size=(2, 8, 2)).astype(np.float32)
    gi = _one_op_graph("DFT", [("x", None)],
                       {"x": (np.float32, [2, 8, 2])}, inverse=1, axis=1)
    got = np.asarray(gi.apply(gi.params, comp)[0])
    want = np.fft.ifft(comp[..., 0] + 1j * comp[..., 1], axis=1)
    np.testing.assert_allclose(got[..., 0], want.real, atol=2e-5)
    np.testing.assert_allclose(got[..., 1], want.imag, atol=2e-5)

    # float16 in -> float16 out (same-T output constraint)
    gi = _one_op_graph("DFT", [("x", None)],
                       {"x": (np.float16, [2, 16, 1])}, onesided=1)
    assert np.asarray(
        gi.apply(gi.params, sig.astype(np.float16))[0]).dtype == np.float16

    # CenterCropPad: crop one axis, pad the other (ONNX center rules)
    x = np.arange(5 * 7, dtype=np.float32).reshape(5, 7)
    gi = _one_op_graph("CenterCropPad",
                       [("x", None),
                        ("shape", np.asarray([7, 3], np.int64))],
                       {"x": (np.float32, [5, 7])}, opset=21)
    got = np.asarray(gi.apply(gi.params, x)[0])
    assert got.shape == (7, 3)
    np.testing.assert_array_equal(got[1:6], x[:, 2:5])
    assert (got[0] == 0).all() and (got[6] == 0).all()


def test_col2im_and_affine_grid_match_torch():
    rng = np.random.default_rng(1)
    # Col2Im == torch.nn.functional.fold
    n, c, kh, kw = 2, 3, 2, 3
    oh, ow = 4, 5
    L = ((oh + 2 - 2 * (kh - 1) - 1) // 1 + 1) * \
        ((ow + 2 - 1 * (kw - 1) - 1) // 2 + 1)
    cols = rng.normal(size=(n, c * kh * kw, L)).astype(np.float32)
    gi = _one_op_graph(
        "Col2Im",
        [("x", None), ("img", np.asarray([oh, ow], np.int64)),
         ("blk", np.asarray([kh, kw], np.int64))],
        {"x": (np.float32, list(cols.shape))},
        dilations=[2, 1], pads=[1, 1, 1, 1], strides=[1, 2])
    got = np.asarray(gi.apply(gi.params, cols)[0])
    want = torch.nn.functional.fold(
        torch.from_numpy(cols), (oh, ow), (kh, kw), dilation=(2, 1),
        padding=(1, 1), stride=(1, 2)).numpy()
    np.testing.assert_allclose(got, want, atol=1e-5)

    # AffineGrid == torch.nn.functional.affine_grid
    theta = rng.normal(size=(2, 2, 3)).astype(np.float32)
    for align in (0, 1):
        gi = _one_op_graph(
            "AffineGrid",
            [("theta", None),
             ("size", np.asarray([2, 3, 4, 5], np.int64))],
            {"theta": (np.float32, [2, 2, 3])}, align_corners=align)
        got = np.asarray(gi.apply(gi.params, theta)[0])
        want = torch.nn.functional.affine_grid(
            torch.from_numpy(theta), (2, 3, 4, 5),
            align_corners=bool(align)).numpy()
        np.testing.assert_allclose(got, want, atol=1e-5,
                                   err_msg=f"align={align}")


def test_unique_compress_and_loss_ops():
    import jax

    # Unique: host path, sorted and first-appearance order
    x = np.asarray([2.0, 1.0, 1.0, 3.0, 4.0, 3.0], np.float32)
    for sorted_attr in (1, 0):
        gi = _one_op_graph("Unique", [("x", x)], {}, n_outputs=4,
                           sorted=sorted_attr)
        y, idx, inv, counts = [np.asarray(o) for o in gi.apply(gi.params)]
        if sorted_attr:
            np.testing.assert_array_equal(y, [1, 2, 3, 4])
        else:
            np.testing.assert_array_equal(y, [2, 1, 3, 4])
        np.testing.assert_array_equal(y[inv], x)
        np.testing.assert_array_equal(x[idx], y)
        assert counts.sum() == len(x)
        # host-only data rides static_params, so the same graph works
        # INSIDE jit too (round-5 review: Unique/Compress must not land
        # in the traced params pytree)
        y2 = np.asarray(jax.jit(gi.apply)(gi.params)[0])
        np.testing.assert_array_equal(y2, y)

    # a traced RUNTIME input -> explicit recipe error
    g = GraphBuilder(opset=21)
    xn = g.add_input("x", np.float32, [6])
    o = g.add_node("Unique", [xn])
    g.add_output(o, np.float32, None)
    gi2 = import_model(g.to_bytes())
    with pytest.raises(NotImplementedError, match="data-dependent"):
        jax.jit(gi2.apply)(gi2.params, jnp.asarray(x))

    gi = _one_op_graph(
        "Compress",
        [("x", np.arange(12, dtype=np.float32).reshape(3, 4)),
         ("cond", np.asarray([True, False, True]))], {}, axis=0)
    np.testing.assert_array_equal(
        np.asarray(gi.apply(gi.params)[0]),
        np.arange(12, dtype=np.float32).reshape(3, 4)[[0, 2]])

    # NLL / SoftmaxCrossEntropy vs torch (weights + ignore_index + all
    # reductions)
    rng = np.random.default_rng(2)
    scores = rng.normal(size=(6, 5)).astype(np.float32)
    target = rng.integers(0, 5, 6).astype(np.int64)
    target[2] = 3
    weight = (rng.random(5) + 0.5).astype(np.float32)
    for reduction in ("mean", "sum", "none"):
        for ignore in (None, 3):
            kw = dict(reduction=reduction)
            if ignore is not None:
                kw["ignore_index"] = ignore
            gi = _one_op_graph(
                "SoftmaxCrossEntropyLoss",
                [("s", None), ("t", target), ("w", weight)],
                {"s": (np.float32, [6, 5])}, **kw)
            got = np.asarray(gi.apply(gi.params, scores)[0])
            want = torch.nn.functional.cross_entropy(
                torch.from_numpy(scores), torch.from_numpy(target),
                weight=torch.from_numpy(weight), reduction=reduction,
                ignore_index=ignore if ignore is not None else -100
            ).numpy()
            np.testing.assert_allclose(got, want, rtol=2e-5, atol=2e-6,
                                       err_msg=f"{reduction}/{ignore}")

    logp = np.log(np.abs(scores) + 0.1).astype(np.float32)
    gi = _one_op_graph("NegativeLogLikelihoodLoss",
                       [("l", None), ("t", target)],
                       {"l": (np.float32, [6, 5])}, reduction="sum")
    got = np.asarray(gi.apply(gi.params, logp)[0])
    want = torch.nn.functional.nll_loss(
        torch.from_numpy(logp), torch.from_numpy(target),
        reduction="sum").numpy()
    np.testing.assert_allclose(got, want, rtol=1e-6)


def test_matmul_nbits_and_rotary_embedding():
    rng = np.random.default_rng(3)
    # MatMulNBits: pack a known int4 matrix blockwise, compare against
    # the float dequant reference
    N, K, block = 6, 32, 16
    n_blocks = K // block
    q = rng.integers(0, 16, (N, K)).astype(np.uint8)          # int4 vals
    scales = (rng.random((N, n_blocks)) * 0.2 + 0.05).astype(np.float32)
    packed = (q[:, 0::2] | (q[:, 1::2] << 4)).reshape(
        N, n_blocks, block // 2)
    a = rng.normal(size=(2, K)).astype(np.float32)
    w = ((q.astype(np.float32)
          - 8.0).reshape(N, n_blocks, block)
         * scales[..., None]).reshape(N, K)
    gi = _one_op_graph(
        "MatMulNBits",
        [("a", None), ("b", packed), ("sc", scales.reshape(-1))],
        {"a": (np.float32, [2, K])}, domain="com.microsoft",
        K=K, N=N, bits=4, block_size=block)
    # the packed weights are the model's dominant bytes: they must ride
    # the donated params pytree, not bake in as XLA constants
    assert "b" in gi.params and "b" not in gi.static_params
    got = np.asarray(gi.apply(gi.params, a)[0])
    np.testing.assert_allclose(got, a @ w.T, rtol=2e-5, atol=2e-5)

    # explicit packed 4-bit zero points
    zp_vals = rng.integers(0, 16, (N, n_blocks)).astype(np.uint8)
    zp_packed = (zp_vals[:, 0::2] | ((zp_vals[:, 1::2] << 4)
                 if n_blocks > 1 else 0)).astype(np.uint8)
    w2 = ((q.astype(np.float32) - zp_vals.repeat(block, 1))
          .reshape(N, n_blocks, block) * scales[..., None]).reshape(N, K)
    gi = _one_op_graph(
        "MatMulNBits",
        [("a", None), ("b", packed), ("sc", scales.reshape(-1)),
         ("zp", zp_packed.reshape(-1))],
        {"a": (np.float32, [2, K])}, domain="com.microsoft",
        K=K, N=N, bits=4, block_size=block)
    got = np.asarray(gi.apply(gi.params, a)[0])
    np.testing.assert_allclose(got, a @ w2.T, rtol=2e-5, atol=2e-5)

    # RotaryEmbedding: numpy reference, 4-D and 3-D, both layouts
    b, nh, s, hd = 2, 3, 5, 8
    cos = np.cos(rng.normal(size=(16, hd // 2))).astype(np.float32)
    sin = np.sin(rng.normal(size=(16, hd // 2))).astype(np.float32)
    pos = rng.integers(0, 16, (b, s)).astype(np.int64)
    x4 = rng.normal(size=(b, nh, s, hd)).astype(np.float32)

    def rot_ref(x, interleaved, pos_arr):
        cc = cos[pos_arr][:, None]
        ss = sin[pos_arr][:, None]
        if interleaved:
            x1, x2 = x[..., 0::2], x[..., 1::2]
        else:
            x1, x2 = x[..., :hd // 2], x[..., hd // 2:]
        o1 = x1 * cc - x2 * ss
        o2 = x2 * cc + x1 * ss
        if interleaved:
            return np.stack([o1, o2], -1).reshape(x.shape)
        return np.concatenate([o1, o2], -1)

    for inter in (0, 1):
        gi = _one_op_graph(
            "RotaryEmbedding",
            [("x", None), ("pos", pos), ("cos", cos), ("sin", sin)],
            {"x": (np.float32, list(x4.shape))}, domain="com.microsoft",
            interleaved=inter)
        got = np.asarray(gi.apply(gi.params, x4)[0])
        np.testing.assert_allclose(got, rot_ref(x4, inter, pos),
                                   atol=1e-5,
                                   err_msg=f"interleaved={inter}")

    # scalar position_ids = ORT's start-offset form: positions are
    # offset..offset+S-1, NOT one broadcast position (round-5 review)
    gi = _one_op_graph(
        "RotaryEmbedding",
        [("x", None), ("pos", np.asarray([4], np.int64)),
         ("cos", cos), ("sin", sin)],
        {"x": (np.float32, list(x4.shape))}, domain="com.microsoft")
    got = np.asarray(gi.apply(gi.params, x4)[0])
    pos_off = np.broadcast_to(np.arange(4, 4 + s), (b, s))
    np.testing.assert_allclose(got, rot_ref(x4, 0, pos_off), atol=1e-5)

    # 3-D input with num_heads splits/merges heads around the rotation
    x3 = x4.transpose(0, 2, 1, 3).reshape(b, s, nh * hd)
    gi = _one_op_graph(
        "RotaryEmbedding",
        [("x", None), ("pos", pos), ("cos", cos), ("sin", sin)],
        {"x": (np.float32, [b, s, nh * hd])}, domain="com.microsoft",
        num_heads=nh)
    got = np.asarray(gi.apply(gi.params, x3)[0])
    want = rot_ref(x4, 0, pos).transpose(0, 2, 1, 3).reshape(b, s, nh * hd)
    np.testing.assert_allclose(got, want, atol=1e-5)


def test_group_query_attention():
    """GQA vs a torch grouped causal-attention reference (prefill),
    packed-QKV parity with separate QKV, per-batch seqlens_k masking,
    and the KV-cache contract: two-step incremental decode must equal
    full-sequence attention on the concatenation."""
    import jax

    rng = np.random.default_rng(4)
    b, s, hq, hkv, d = 2, 6, 4, 2, 8

    def gqa_graph(with_past=False, past_t=0, n_outputs=1, packed=False,
                  seqlens=None, do_rotary=0, cos=None, sin=None):
        g = GraphBuilder(opset=21)
        if packed:
            qn = g.add_input("q", np.float32, [b, s, (hq + 2 * hkv) * d])
            ins = [qn, "", ""]
        else:
            qn = g.add_input("q", np.float32, [b, s, hq * d])
            kn = g.add_input("k", np.float32, [b, s, hkv * d])
            vn = g.add_input("v", np.float32, [b, s, hkv * d])
            ins = [qn, kn, vn]
        if with_past:
            ins += [g.add_input("pk", np.float32, [b, hkv, past_t, d]),
                    g.add_input("pv", np.float32, [b, hkv, past_t, d])]
        else:
            ins += ["", ""]
        if seqlens is not None:
            ins.append(g.add_initializer("sl", seqlens))
        elif do_rotary:
            ins.append("")
        if do_rotary:
            ins += ["", g.add_initializer("cos", cos),
                    g.add_initializer("sin", sin)]
        outs = ["y", "prk", "prv"][:n_outputs]
        g.add_node("GroupQueryAttention", ins, outputs=outs,
                   domain="com.microsoft", num_heads=hq,
                   kv_num_heads=hkv, do_rotary=do_rotary)
        for o in outs:
            g.add_output(o, np.float32, None)
        return import_model(g.to_bytes())

    def torch_ref(q, k, v, past_k=None, past_v=None, lims=None):
        tq = torch.from_numpy(q).reshape(b, -1, hq, d).transpose(1, 2)
        tk = torch.from_numpy(k).reshape(b, -1, hkv, d).transpose(1, 2)
        tv = torch.from_numpy(v).reshape(b, -1, hkv, d).transpose(1, 2)
        if past_k is not None:
            tk = torch.cat([torch.from_numpy(past_k), tk], dim=2)
            tv = torch.cat([torch.from_numpy(past_v), tv], dim=2)
        past_t = tk.shape[2] - tq.shape[2]
        tk = tk.repeat_interleave(hq // hkv, dim=1)
        tv = tv.repeat_interleave(hq // hkv, dim=1)
        sq, tt = tq.shape[2], tk.shape[2]
        mask = (torch.arange(tt)[None, :]
                <= past_t + torch.arange(sq)[:, None])
        mask = mask[None, None].expand(b, 1, sq, tt).clone()
        if lims is not None:
            mask &= (torch.arange(tt)[None, None, None, :]
                     < torch.as_tensor(lims)[:, None, None, None])
        att = (tq @ tk.transpose(-1, -2)) / np.sqrt(d)
        att = att.masked_fill(~mask, float("-inf")).softmax(-1)
        out = att @ tv
        return out.transpose(1, 2).reshape(b, sq, hq * d).numpy()

    q = rng.normal(size=(b, s, hq * d)).astype(np.float32)
    k = rng.normal(size=(b, s, hkv * d)).astype(np.float32)
    v = rng.normal(size=(b, s, hkv * d)).astype(np.float32)

    gi = gqa_graph()
    got = np.asarray(jax.jit(gi.apply)(
        gi.params, jnp.asarray(q), jnp.asarray(k), jnp.asarray(v))[0])
    np.testing.assert_allclose(got, torch_ref(q, k, v), atol=2e-5,
                               rtol=2e-5)

    # packed QKV == separate QKV
    gi_p = gqa_graph(packed=True)
    packed = np.concatenate([q, k, v], axis=-1)
    got_p = np.asarray(gi_p.apply(gi_p.params, packed)[0])
    np.testing.assert_allclose(got_p, got, atol=1e-6)

    # per-batch seqlens_k (ORT: valid keys - 1) bounds attention
    lims = np.asarray([4, 6], np.int32)
    gi_s = gqa_graph(seqlens=(lims - 1).astype(np.int32))
    got_s = np.asarray(gi_s.apply(gi_s.params, q, k, v)[0])
    np.testing.assert_allclose(got_s, torch_ref(q, k, v, lims=lims),
                               atol=2e-5, rtol=2e-5)

    # KV cache: prefill s tokens, then decode 2 more one-by-one ==
    # full attention over s+2 (causal => prefix outputs identical)
    s2 = 2
    q2 = rng.normal(size=(b, s2, hq * d)).astype(np.float32)
    k2 = rng.normal(size=(b, s2, hkv * d)).astype(np.float32)
    v2 = rng.normal(size=(b, s2, hkv * d)).astype(np.float32)
    gi_c = gqa_graph(n_outputs=3)
    _, pk, pv = gi_c.apply(gi_c.params, q, k, v)
    g_step = gqa_graph(with_past=True, past_t=s, n_outputs=3)
    out_step, pk2, pv2 = g_step.apply(
        g_step.params, q2, k2, v2, np.asarray(pk), np.asarray(pv))
    full = torch_ref(np.concatenate([q, q2], 1),
                     np.concatenate([k, k2], 1),
                     np.concatenate([v, v2], 1))
    np.testing.assert_allclose(np.asarray(out_step), full[:, s:],
                               atol=2e-5, rtol=2e-5)
    assert np.asarray(pk2).shape == (b, hkv, s + s2, d)

    # do_rotary: internal rope with position offset = past length must
    # equal applying RotaryEmbedding externally then GQA without it
    cos = np.cos(rng.normal(size=(32, d // 2))).astype(np.float32)
    sin = np.sin(rng.normal(size=(32, d // 2))).astype(np.float32)
    gi_r = gqa_graph(do_rotary=1, cos=cos, sin=sin)
    got_r = np.asarray(gi_r.apply(gi_r.params, q, k, v)[0])

    def rope_np(t, h):
        tt = t.reshape(b, s, h, d).transpose(0, 2, 1, 3)
        pos = np.arange(s)
        cc, ss = cos[pos][None, None], sin[pos][None, None]
        t1, t2 = tt[..., :d // 2], tt[..., d // 2:]
        out = np.concatenate([t1 * cc - t2 * ss, t2 * cc + t1 * ss], -1)
        return out.transpose(0, 2, 1, 3).reshape(b, s, h * d)

    want_r = torch_ref(rope_np(q, hq).astype(np.float32),
                       rope_np(k, hkv).astype(np.float32), v)
    np.testing.assert_allclose(got_r, want_r, atol=2e-5, rtol=2e-5)


def test_quantized_llm_decoder_block_end_to_end():
    """The ORT-GenAI decoder idiom composed from the triad: MatMulNBits
    int4 projections -> GroupQueryAttention (internal rotary, KV cache
    outputs) -> MatMulNBits out-projection + residual, traced through
    one jit. The packed weights must ride the donated params pytree."""
    import jax

    rng = np.random.default_rng(5)
    b, s, hq, hkv, d = 2, 4, 4, 2, 8
    H = hq * d

    def nbits_init(g, name, n_out, n_in, block=16):
        qw = rng.integers(0, 16, (n_out, n_in)).astype(np.uint8)
        nb = n_in // block
        sc = (rng.random((n_out, nb)) * 0.05 + 0.01).astype(np.float32)
        packed = (qw[:, 0::2] | (qw[:, 1::2] << 4)).reshape(
            n_out, nb, block // 2)
        g.add_initializer(f"{name}_w", packed)
        g.add_initializer(f"{name}_s", sc.reshape(-1))
        return [f"{name}_w", f"{name}_s"]

    g = GraphBuilder(opset=21)
    xn = g.add_input("x", np.float32, [b, s, H])
    cos = np.cos(rng.normal(size=(32, d // 2))).astype(np.float32)
    sin = np.sin(rng.normal(size=(32, d // 2))).astype(np.float32)

    def proj(name, n_out):
        return g.add_node(
            "MatMulNBits", [xn] + nbits_init(g, name, n_out, H),
            domain="com.microsoft", K=H, N=n_out, bits=4, block_size=16)

    qp, kp, vp = proj("q", hq * d), proj("k", hkv * d), proj("v", hkv * d)
    att = g.add_node(
        "GroupQueryAttention",
        [qp, kp, vp, "", "", "", "",
         g.add_initializer("cos", cos), g.add_initializer("sin", sin)],
        outputs=["att", "prk", "prv"], domain="com.microsoft",
        num_heads=hq, kv_num_heads=hkv, do_rotary=1)
    op_w = nbits_init(g, "o", H, H)
    out = g.add_node("MatMulNBits", [att[0]] + op_w,
                     domain="com.microsoft", K=H, N=H, bits=4,
                     block_size=16)
    y = g.add_node("Add", [xn, out])
    g.add_output(y, np.float32, [b, s, H])
    g.add_output("prk", np.float32, None)
    g.add_output("prv", np.float32, None)
    gi = import_model(g.to_bytes())

    # the int4 projection weights are in the donated pytree, not baked
    assert {"q_w", "k_w", "v_w", "o_w"} <= set(gi.params)
    x = rng.normal(size=(b, s, H)).astype(np.float32)
    yv, pk, pv = jax.jit(gi.apply)(gi.params, jnp.asarray(x))
    assert np.isfinite(np.asarray(yv)).all()
    assert np.asarray(yv).shape == (b, s, H)
    assert np.asarray(pk).shape == (b, hkv, s, d)
    # causality: recomputing with the LAST token's hidden state changed
    # must leave every earlier position's output untouched
    x2 = x.copy()
    x2[:, -1] += 1.0
    yv2 = np.asarray(jax.jit(gi.apply)(gi.params, jnp.asarray(x2))[0])
    np.testing.assert_allclose(np.asarray(yv)[:, :-1], yv2[:, :-1],
                               atol=1e-6)
    assert np.abs(np.asarray(yv)[:, -1] - yv2[:, -1]).max() > 1e-3


def test_sequence_ops():
    """Sequence family: list-of-tensors semantics with static lengths
    and positions; elements stay traced under jit (a list of tracers is
    a pytree). SplitToSequence/ConcatFromSequence round-trip, the
    scalar-split form, and a composed construct-insert-erase-at chain."""
    import jax

    rng = np.random.default_rng(6)
    x = rng.normal(size=(6, 4)).astype(np.float32)

    # split -> concat round trip (tensor split sizes)
    g = GraphBuilder(opset=21)
    xn = g.add_input("x", np.float32, [6, 4])
    seq = g.add_node("SplitToSequence",
                     [xn, g.add_initializer(
                         "sp", np.asarray([2, 3, 1], np.int64))], axis=0)
    y = g.add_node("ConcatFromSequence", [seq], axis=0)
    ln = g.add_node("SequenceLength", [seq])
    g.add_output(y, np.float32, [6, 4])
    g.add_output(ln, np.int64, [])
    gi = import_model(g.to_bytes())
    got, n = jax.jit(gi.apply)(gi.params, jnp.asarray(x))
    np.testing.assert_allclose(np.asarray(got), x, atol=1e-7)
    assert int(np.asarray(n)) == 3

    # scalar split size + keepdims=0 singleton split + new_axis stack
    g = GraphBuilder(opset=21)
    xn = g.add_input("x", np.float32, [6, 4])
    seq = g.add_node("SplitToSequence",
                     [xn, g.add_initializer("sp", np.asarray(2, np.int64))],
                     axis=0)
    stacked = g.add_node("ConcatFromSequence", [seq], axis=0, new_axis=1)
    g.add_output(stacked, np.float32, [3, 2, 4])
    gi = import_model(g.to_bytes())
    got = np.asarray(gi.apply(gi.params, x)[0])
    np.testing.assert_allclose(got, x.reshape(3, 2, 4), atol=1e-7)

    g = GraphBuilder(opset=21)
    xn = g.add_input("x", np.float32, [6, 4])
    seq = g.add_node("SplitToSequence", [xn], axis=0, keepdims=0)
    first = g.add_node("SequenceAt",
                       [seq, g.add_initializer("i0", np.asarray(0,
                                                                np.int64))])
    g.add_output(first, np.float32, [4])
    gi = import_model(g.to_bytes())
    np.testing.assert_allclose(np.asarray(gi.apply(gi.params, x)[0]),
                               x[0], atol=1e-7)

    # construct -> insert(front) -> erase(middle) -> at(-1)
    a = rng.normal(size=(2, 2)).astype(np.float32)
    b = rng.normal(size=(2, 2)).astype(np.float32)
    c = rng.normal(size=(2, 2)).astype(np.float32)
    g = GraphBuilder(opset=21)
    an = g.add_input("a", np.float32, [2, 2])
    bn = g.add_initializer("b", b)
    cn = g.add_initializer("c", c)
    seq = g.add_node("SequenceConstruct", [an, bn])
    seq = g.add_node("SequenceInsert",
                     [seq, cn, g.add_initializer("p0", np.asarray(
                         0, np.int64))])            # [c, a, b]
    seq = g.add_node("SequenceErase",
                     [seq, g.add_initializer("p1", np.asarray(
                         1, np.int64))])            # [c, b]
    last = g.add_node("SequenceAt",
                      [seq, g.add_initializer("m1", np.asarray(
                          -1, np.int64))])
    g.add_output(last, np.float32, [2, 2])
    gi = import_model(g.to_bytes())
    np.testing.assert_allclose(np.asarray(
        jax.jit(gi.apply)(gi.params, jnp.asarray(a))[0]), b, atol=1e-7)

    # negative axis + keepdims=0 (torch.unbind(dim=-1) export) and the
    # ONNX reference's negative-insert placement (insert(-1) = before
    # the last element) — round-5 review repros
    g = GraphBuilder(opset=21)
    xn = g.add_input("x", np.float32, [6, 4])
    seq = g.add_node("SplitToSequence", [xn], axis=-1, keepdims=0)
    el = g.add_node("SequenceAt",
                    [seq, g.add_initializer("i1", np.asarray(1, np.int64))])
    g.add_output(el, np.float32, [6])
    gi = import_model(g.to_bytes())
    np.testing.assert_allclose(np.asarray(gi.apply(gi.params, x)[0]),
                               x[:, 1], atol=1e-7)

    g = GraphBuilder(opset=21)
    an = g.add_input("a", np.float32, [2, 2])
    seq = g.add_node("SequenceConstruct",
                     [an, g.add_initializer("b2", b)])
    seq = g.add_node("SequenceInsert",
                     [seq, g.add_initializer("c2", c),
                      g.add_initializer("m1b", np.asarray(-1, np.int64))])
    mid = g.add_node("SequenceAt",
                     [seq, g.add_initializer("i1b", np.asarray(1,
                                                               np.int64))])
    g.add_output(mid, np.float32, [2, 2])
    gi = import_model(g.to_bytes())
    np.testing.assert_allclose(  # [a, c, b]: insert(-1) before last
        np.asarray(gi.apply(gi.params, a)[0]), c, atol=1e-7)

    # all-constant sequences stay host-side (foldable downstream)
    g = GraphBuilder(opset=21)
    g.add_input("a", np.float32, [2, 2])
    seq = g.add_node("SequenceConstruct",
                     [g.add_initializer("h1", np.asarray([2], np.int64)),
                      g.add_initializer("h2", np.asarray([3], np.int64))])
    shp = g.add_node("ConcatFromSequence", [seq], axis=0)
    y = g.add_node("Reshape", [g.add_node("ConstantOfShape", [shp]), shp])
    g.add_output(y, np.float32, [2, 3])
    gi = import_model(g.to_bytes())
    assert np.asarray(gi.apply(gi.params, a)[0]).shape == (2, 3)

    # out-of-range position: loud error, not a wrapped index
    g = GraphBuilder(opset=21)
    an = g.add_input("a", np.float32, [2, 2])
    seq = g.add_node("SequenceConstruct", [an])
    bad = g.add_node("SequenceAt",
                     [seq, g.add_initializer("p", np.asarray(3, np.int64))])
    g.add_output(bad, np.float32, [2, 2])
    gi = import_model(g.to_bytes())
    with pytest.raises(ValueError, match="out of range"):
        gi.apply(gi.params, a)


def test_optional_ops():
    """Optional wrappers ride the env's None/value distinction."""
    g = GraphBuilder(opset=21)
    xn = g.add_input("x", np.float32, [3])
    o = g.add_node("Optional", [xn])
    has = g.add_node("OptionalHasElement", [o])
    val = g.add_node("OptionalGetElement", [o])
    empty = g.add_node("Optional", [])
    has_not = g.add_node("OptionalHasElement", [empty])
    g.add_output(has, np.bool_, [])
    g.add_output(val, np.float32, [3])
    g.add_output(has_not, np.bool_, [])
    gi = import_model(g.to_bytes())
    x = np.asarray([1.0, 2.0, 3.0], np.float32)
    h, v, hn = gi.apply(gi.params, x)
    assert bool(h) is True and bool(hn) is False
    np.testing.assert_array_equal(np.asarray(v), x)

    g2 = GraphBuilder(opset=21)
    g2.add_input("x", np.float32, [3])
    e = g2.add_node("Optional", [])
    bad = g2.add_node("OptionalGetElement", [e])
    g2.add_output(bad, np.float32, [3])
    gi2 = import_model(g2.to_bytes())
    with pytest.raises(ValueError, match="empty optional"):
        gi2.apply(gi2.params, x)


def test_gather_nd_batch_dims():
    """GatherND with batch_dims (the detection heads' post-NMS gather
    idiom) vs a loop reference, batch_dims 1 and 2."""
    rng = np.random.default_rng(7)
    x = rng.normal(size=(2, 3, 4, 5)).astype(np.float32)

    def graph(idx, batch_dims):
        g = GraphBuilder(opset=21)
        xn = g.add_input("x", np.float32, list(x.shape))
        y = g.add_node("GatherND", [xn, g.add_initializer("i", idx)],
                       batch_dims=batch_dims)
        g.add_output(y, np.float32, None)
        return import_model(g.to_bytes())

    # batch_dims=1: per-batch [3,2] index tuples into [3,4,5]
    idx1 = np.stack([rng.integers(0, [3, 4], (6, 2)),
                     rng.integers(0, [3, 4], (6, 2))]).astype(np.int64)
    gi = graph(idx1, 1)
    got = np.asarray(gi.apply(gi.params, x)[0])
    want = np.stack([
        np.stack([x[b][tuple(idx1[b, j])] for j in range(6)])
        for b in range(2)])
    np.testing.assert_array_equal(got, want)

    # batch_dims=2: indices [2,3,2,1] into the length-4 axis
    idx2 = rng.integers(0, 4, (2, 3, 2, 1)).astype(np.int64)
    gi = graph(idx2, 2)
    got = np.asarray(gi.apply(gi.params, x)[0])
    want = np.stack([
        np.stack([x[b, c][idx2[b, c, :, 0]] for c in range(3)])
        for b in range(2)])
    np.testing.assert_array_equal(got, want)


def test_svm_family_matches_sklearn():
    """SVMClassifier/SVMRegressor against sklearn itself (the foreign
    oracle skl2onnx converts FROM): ovo decision values, vote labels,
    rbf/poly/sigmoid kernels, SVR, and the linear-weight modes."""
    from sklearn.svm import SVC, SVR, LinearSVC, LinearSVR

    rng = np.random.default_rng(8)
    x = rng.normal(size=(120, 5))
    y3 = np.digitize(x[:, 0] + 0.7 * x[:, 1], [-0.4, 0.4])
    xq = rng.normal(size=(40, 5)).astype(np.float32)

    for kernel, kind, params in [
        ("rbf", "RBF", dict(gamma=0.3)),
        ("poly", "POLY", dict(gamma=0.25, coef0=1.0, degree=3)),
        ("sigmoid", "SIGMOID", dict(gamma=0.05, coef0=0.2)),
        ("linear", "LINEAR", {}),
    ]:
        m = SVC(kernel=kernel, decision_function_shape="ovo",
                **params).fit(x, y3)
        sv = m.support_vectors_.astype(np.float32)
        g = GraphBuilder(opset=21)
        xn = g.add_input("x", np.float32, ["N", 5])
        lab, sc = g.add_node(
            "SVMClassifier", [xn], outputs=["lab", "sc"],
            domain="ai.onnx.ml",
            kernel_type=kind,
            kernel_params=[float(m._gamma),
                           float(params.get("coef0", 0.0)),
                           float(params.get("degree", 3))],
            support_vectors=sv.reshape(-1).tolist(),
            vectors_per_class=m.n_support_.tolist(),
            coefficients=m.dual_coef_.astype(
                np.float32).reshape(-1).tolist(),
            rho=m.intercept_.astype(np.float32).tolist(),
            classlabels_int64s=[int(c) for c in m.classes_])
        g.add_output(lab, np.int64, ["N"])
        g.add_output(sc, np.float32, None)
        gi = import_model(g.to_bytes())
        got_lab, got_sc = [np.asarray(o) for o in
                           gi.apply(gi.params, xq)]
        want_dec = m.decision_function(xq.astype(np.float64))
        np.testing.assert_allclose(got_sc, want_dec, rtol=2e-4,
                                   atol=2e-4, err_msg=kernel)
        want_lab = m.predict(xq.astype(np.float64))
        agree = (got_lab == want_lab).mean()
        assert agree > 0.97, (kernel, agree)  # vote ties may differ

    # SVR: kernel + rho
    mr = SVR(kernel="rbf", gamma=0.2, C=2.0).fit(x, x[:, 0] * 2 + x[:, 1])
    g = GraphBuilder(opset=21)
    xn = g.add_input("x", np.float32, ["N", 5])
    yr = g.add_node(
        "SVMRegressor", [xn], domain="ai.onnx.ml", kernel_type="RBF",
        kernel_params=[float(mr._gamma), 0.0, 3.0],
        support_vectors=mr.support_vectors_.astype(
            np.float32).reshape(-1).tolist(),
        n_supports=int(len(mr.support_vectors_)),
        coefficients=mr.dual_coef_.astype(np.float32).reshape(-1).tolist(),
        rho=mr.intercept_.astype(np.float32).tolist())
    g.add_output(yr, np.float32, ["N", 1])
    gi = import_model(g.to_bytes())
    got = np.asarray(gi.apply(gi.params, xq)[0])[:, 0]
    np.testing.assert_allclose(got, mr.predict(xq.astype(np.float64)),
                               rtol=2e-4, atol=2e-4)

    # BINARY SVC: libsvm/ORT sign convention is the NEGATION of
    # sklearn's binary decision_function; skl2onnx negates the dual
    # coefs + rho at export — mirror that and labels must match exactly
    yb = (y3 > 0).astype(int)
    mb = SVC(kernel="rbf", gamma=0.3).fit(x, yb)
    g = GraphBuilder(opset=21)
    xn = g.add_input("x", np.float32, ["N", 5])
    lab, sc = g.add_node(
        "SVMClassifier", [xn], outputs=["lab", "sc"],
        domain="ai.onnx.ml", kernel_type="RBF",
        kernel_params=[float(mb._gamma), 0.0, 3.0],
        support_vectors=mb.support_vectors_.astype(
            np.float32).reshape(-1).tolist(),
        vectors_per_class=mb.n_support_.tolist(),
        coefficients=(-mb.dual_coef_).astype(
            np.float32).reshape(-1).tolist(),
        rho=(-mb.intercept_).astype(np.float32).tolist(),
        classlabels_int64s=[int(c) for c in mb.classes_])
    g.add_output(lab, np.int64, ["N"])
    g.add_output(sc, np.float32, None)
    gi = import_model(g.to_bytes())
    got_lab, got_sc = [np.asarray(o) for o in gi.apply(gi.params, xq)]
    np.testing.assert_allclose(
        got_sc[:, 0], -mb.decision_function(xq.astype(np.float64)),
        rtol=2e-4, atol=2e-4)
    np.testing.assert_array_equal(got_lab,
                                  mb.predict(xq.astype(np.float64)))

    # OneClassSVM via SVMRegressor one_class=1: +/-1 == sklearn.predict
    from sklearn.svm import OneClassSVM
    mo = OneClassSVM(kernel="rbf", gamma=0.2, nu=0.3).fit(x)
    g = GraphBuilder(opset=21)
    xn = g.add_input("x", np.float32, ["N", 5])
    yo = g.add_node(
        "SVMRegressor", [xn], domain="ai.onnx.ml", kernel_type="RBF",
        kernel_params=[float(mo._gamma), 0.0, 3.0], one_class=1,
        support_vectors=mo.support_vectors_.astype(
            np.float32).reshape(-1).tolist(),
        n_supports=int(len(mo.support_vectors_)),
        coefficients=mo.dual_coef_.astype(np.float32).reshape(-1).tolist(),
        rho=mo.intercept_.astype(np.float32).tolist())
    g.add_output(yo, np.float32, ["N", 1])
    gi = import_model(g.to_bytes())
    got = np.asarray(gi.apply(gi.params, xq)[0])[:, 0]
    np.testing.assert_array_equal(
        got, mo.predict(xq.astype(np.float64)).astype(np.float32))

    # BINARY LinearSVC: one weight row, raw margin thresholds at 0
    # (round-5 review repro: the probability expansion misclassified)
    mlb = LinearSVC().fit(x, yb)
    g = GraphBuilder(opset=21)
    xn = g.add_input("x", np.float32, ["N", 5])
    lab, sc = g.add_node(
        "SVMClassifier", [xn], outputs=["lab", "sc"],
        domain="ai.onnx.ml", kernel_type="LINEAR",
        coefficients=mlb.coef_.astype(np.float32).reshape(-1).tolist(),
        rho=mlb.intercept_.astype(np.float32).tolist(),
        classlabels_int64s=[0, 1])
    g.add_output(lab, np.int64, ["N"])
    g.add_output(sc, np.float32, None)
    gi = import_model(g.to_bytes())
    got_lab = np.asarray(gi.apply(gi.params, xq)[0])
    np.testing.assert_array_equal(got_lab,
                                  mlb.predict(xq.astype(np.float64)))

    # linear-weight modes (LinearSVC/LinearSVR exports: no SVs)
    ml = LinearSVC().fit(x, y3)
    g = GraphBuilder(opset=21)
    xn = g.add_input("x", np.float32, ["N", 5])
    lab, sc = g.add_node(
        "SVMClassifier", [xn], outputs=["lab", "sc"],
        domain="ai.onnx.ml", kernel_type="LINEAR",
        coefficients=ml.coef_.astype(np.float32).reshape(-1).tolist(),
        rho=ml.intercept_.astype(np.float32).tolist(),
        classlabels_int64s=[int(c) for c in ml.classes_])
    g.add_output(lab, np.int64, ["N"])
    g.add_output(sc, np.float32, None)
    gi = import_model(g.to_bytes())
    got_lab, got_sc = [np.asarray(o) for o in gi.apply(gi.params, xq)]
    np.testing.assert_allclose(got_sc,
                               ml.decision_function(xq.astype(np.float64)),
                               rtol=2e-4, atol=2e-4)
    np.testing.assert_array_equal(got_lab,
                                  ml.predict(xq.astype(np.float64)))

    mlr = LinearSVR().fit(x, x[:, 0])
    g = GraphBuilder(opset=21)
    xn = g.add_input("x", np.float32, ["N", 5])
    yr = g.add_node(
        "SVMRegressor", [xn], domain="ai.onnx.ml", kernel_type="LINEAR",
        n_supports=0,
        coefficients=mlr.coef_.astype(np.float32).reshape(-1).tolist(),
        rho=[float(mlr.intercept_[0])])
    g.add_output(yr, np.float32, ["N", 1])
    gi = import_model(g.to_bytes())
    got = np.asarray(gi.apply(gi.params, xq)[0])[:, 0]
    np.testing.assert_allclose(got, mlr.predict(xq.astype(np.float64)),
                               rtol=2e-4, atol=2e-4)


def test_dict_vectorizer():
    g = GraphBuilder(opset=21)
    xn = g.add_input("x", np.float32, ["N"])  # dtype nominal: host objects
    y = g.add_node("DictVectorizer", [xn], domain="ai.onnx.ml",
                   string_vocabulary=["a", "b", "c"])
    g.add_output(y, np.float32, ["N", 3])
    gi = import_model(g.to_bytes())
    rows = np.empty(2, dtype=object)
    rows[0] = {"a": 1.0, "c": 2.0, "zzz": 9.0}  # unknown keys ignored
    rows[1] = {"b": -1.0}
    got = np.asarray(gi.apply(gi.params, rows)[0])
    np.testing.assert_array_equal(got, [[1, 0, 2], [0, -1, 0]])


def test_tfidf_vectorizer():
    """TfIdfVectorizer vs an independent loop reference (spec text) and
    sklearn CountVectorizer for the no-skip bigram case."""
    import itertools

    import jax

    def ref_counts(x, pool, counts_attr, indexes, min_n, max_n,
                   max_skip, n_out):
        out = np.zeros((x.shape[0], n_out), np.float64)
        bounds = list(counts_attr) + [len(pool)]
        cur = 0
        for level in range(len(counts_attr)):
            n = level + 1
            lo, hi = bounds[level], bounds[level + 1]
            grams = [tuple(pool[lo + i * n: lo + (i + 1) * n])
                     for i in range((hi - lo) // n)]
            cols = indexes[cur: cur + len(grams)]
            cur += len(grams)
            if not (min_n <= n <= max_n):
                continue
            for r in range(x.shape[0]):
                for s in (range(max_skip + 1) if n > 1 else [0]):
                    stride = s + 1
                    for start in range(x.shape[1]):
                        pos = [start + k * stride for k in range(n)]
                        if pos[-1] >= x.shape[1]:
                            break
                        g = tuple(x[r, p] for p in pos)
                        for gi_, gram in enumerate(grams):
                            if g == gram:
                                out[r, cols[gi_]] += 1
        return out

    rng = np.random.default_rng(9)
    x = rng.integers(0, 5, (3, 9)).astype(np.int64)
    # pool: 3 unigrams + 4 bigrams
    pool = [0, 2, 4, 0, 1, 2, 3, 1, 0, 4, 4]
    counts_attr = [0, 3]
    indexes = np.arange(7, dtype=np.int64)

    for min_n, max_n, skip in [(1, 2, 0), (2, 2, 2), (1, 1, 0),
                               (1, 2, 1)]:
        g = GraphBuilder(opset=21)
        xn = g.add_input("x", np.int64, list(x.shape))
        y = g.add_node("TfIdfVectorizer", [xn], mode="TF",
                       min_gram_length=min_n, max_gram_length=max_n,
                       max_skip_count=skip,
                       ngram_counts=counts_attr,
                       ngram_indexes=indexes.tolist(),
                       pool_int64s=pool)
        g.add_output(y, np.float32, None)
        gi = import_model(g.to_bytes())
        got = np.asarray(jax.jit(gi.apply)(gi.params, jnp.asarray(x))[0])
        want = ref_counts(np.asarray(x), pool, counts_attr, indexes,
                          min_n, max_n, skip, 7)
        np.testing.assert_array_equal(got, want,
                                      err_msg=f"{min_n},{max_n},{skip}")

    # TFIDF/IDF weighting: weights align with pool order via indexes
    wts = (rng.random(7) + 0.5).astype(np.float32)
    perm = rng.permutation(7).astype(np.int64)
    g = GraphBuilder(opset=21)
    xn = g.add_input("x", np.int64, list(x.shape))
    y = g.add_node("TfIdfVectorizer", [xn], mode="TFIDF",
                   min_gram_length=1, max_gram_length=2,
                   max_skip_count=0, ngram_counts=counts_attr,
                   ngram_indexes=perm.tolist(), pool_int64s=pool,
                   weights=wts.tolist())
    g.add_output(y, np.float32, None)
    gi = import_model(g.to_bytes())
    got = np.asarray(gi.apply(gi.params, x)[0])
    base = ref_counts(np.asarray(x), pool, counts_attr, perm, 1, 2, 0, 7)
    colw = np.ones(7, np.float32)
    colw[perm] = wts
    np.testing.assert_allclose(got, base * colw, rtol=1e-6)

    # sklearn CountVectorizer cross-check (no skips, unigram+bigram)
    from sklearn.feature_extraction.text import CountVectorizer
    docs = ["a b a c", "c c b a", "b b b c"]
    cv = CountVectorizer(ngram_range=(1, 2),
                         token_pattern=r"(?u)\b\w+\b").fit(docs)
    tok2id = {"a": 0, "b": 1, "c": 2}
    X = np.asarray([[tok2id[t] for t in d.split()] for d in docs],
                   np.int64)
    vocab = sorted(cv.vocabulary_, key=cv.vocabulary_.get)
    uni = [v for v in vocab if " " not in v]
    bi = [v for v in vocab if " " in v]
    pool2, cols2 = [], []
    for v in uni:
        pool2.append(tok2id[v])
        cols2.append(cv.vocabulary_[v])
    counts2 = [0, len(pool2)]
    for v in bi:
        a, bgram = v.split()
        pool2 += [tok2id[a], tok2id[bgram]]
        cols2.append(cv.vocabulary_[v])
    g = GraphBuilder(opset=21)
    xn = g.add_input("x", np.int64, list(X.shape))
    y = g.add_node("TfIdfVectorizer", [xn], mode="TF",
                   min_gram_length=1, max_gram_length=2,
                   max_skip_count=0, ngram_counts=counts2,
                   ngram_indexes=cols2, pool_int64s=pool2)
    g.add_output(y, np.float32, None)
    gi = import_model(g.to_bytes())
    got = np.asarray(gi.apply(gi.params, X)[0])
    want = cv.transform(docs).toarray()
    np.testing.assert_array_equal(got, want)

    # big pool exercises the lax.scan pool-chunking path (peak memory
    # bounded; round-5 review: text exports carry tens of thousands of
    # n-grams) — equal to a direct loop reference
    rng2 = np.random.default_rng(10)
    big_pool = rng2.integers(0, 50, 4000 * 2).tolist()
    Xb = rng2.integers(0, 50, (2, 600)).astype(np.int64)
    g = GraphBuilder(opset=21)
    xn = g.add_input("x", np.int64, list(Xb.shape))
    y = g.add_node("TfIdfVectorizer", [xn], mode="TF",
                   min_gram_length=2, max_gram_length=2,
                   max_skip_count=0, ngram_counts=[0, 0],
                   ngram_indexes=list(range(4000)),
                   pool_int64s=big_pool)
    g.add_output(y, np.float32, None)
    gi = import_model(g.to_bytes())
    got_b = np.asarray(gi.apply(gi.params, Xb)[0])
    grams_b = np.asarray(big_pool).reshape(4000, 2)
    want_b = np.zeros((2, 4000))
    for r in range(2):
        for i in range(Xb.shape[1] - 1):
            want_b[r] += (grams_b == Xb[r, i:i + 2]).all(1)
    np.testing.assert_array_equal(got_b, want_b)


def test_sklearn_text_pipeline_composed():
    """The sklearn text-classification export shape, composed in one
    graph: TfIdfVectorizer (bigram counts) -> SVMClassifier (linear),
    scored through ONNXModel.transform — predictions equal the sklearn
    Pipeline(CountVectorizer, LinearSVC) it mirrors."""
    from sklearn.feature_extraction.text import CountVectorizer
    from sklearn.svm import LinearSVC

    docs = ["good great fine", "bad awful bad", "great good good",
            "awful poor bad", "fine good fine", "poor awful poor",
            "good fine great", "bad poor awful"] * 4
    y = np.asarray([1, 0] * 16)
    cv = CountVectorizer(ngram_range=(1, 2),
                         token_pattern=r"(?u)\b\w+\b").fit(docs)
    Xc = cv.transform(docs).toarray().astype(np.float64)
    clf = LinearSVC().fit(Xc, y)

    tok2id = {t: i for i, t in enumerate(
        sorted({w for d in docs for w in d.split()}))}
    X = np.asarray([[tok2id[t] for t in d.split()] for d in docs],
                   np.int64)
    vocab = sorted(cv.vocabulary_, key=cv.vocabulary_.get)
    pool, cols = [], []
    uni = [v for v in vocab if " " not in v]
    for v in uni:
        pool.append(tok2id[v])
        cols.append(cv.vocabulary_[v])
    counts_attr = [0, len(pool)]
    for v in vocab:
        if " " in v:
            a, b = v.split()
            pool += [tok2id[a], tok2id[b]]
            cols.append(cv.vocabulary_[v])

    g = GraphBuilder(opset=21)
    xn = g.add_input("tokens", np.int64, ["N", 3])
    feats = g.add_node("TfIdfVectorizer", [xn], mode="TF",
                       min_gram_length=1, max_gram_length=2,
                       max_skip_count=0, ngram_counts=counts_attr,
                       ngram_indexes=cols, pool_int64s=pool)
    lab, sc = g.add_node(
        "SVMClassifier", [feats], outputs=["lab", "sc"],
        domain="ai.onnx.ml", kernel_type="LINEAR",
        coefficients=clf.coef_.astype(np.float32).reshape(-1).tolist(),
        rho=clf.intercept_.astype(np.float32).tolist(),
        classlabels_int64s=[0, 1])
    g.add_output(lab, np.int64, ["N"])
    g.add_output(sc, np.float32, None)

    from synapseml_tpu.onnx import ONNXModel
    model = ONNXModel(model_bytes=g.to_bytes(),
                      feed_dict={"tokens": "tokens"},
                      fetch_dict={"pred": "lab"})
    out = model.transform(Table({"tokens": X}))
    got = np.asarray(out["pred"], np.int64)
    np.testing.assert_array_equal(got, clf.predict(Xc))
    assert (got == y).all()  # the pipeline actually learned the task


def test_nms_through_onnx_model_requires_batch_alignment():
    """The fixed-capacity NMS output ([B*C*max_out, 3]) is not
    batch-aligned: scoring it through ONNXModel must fail LOUDLY with
    the reshape recipe (previously the executor silently sliced the
    first B rows — batch 0's 2nd pick landed on table row 1), and the
    recipe itself — an in-graph Reshape to [B, C*max_out, 3] — must
    yield correct per-row selections."""
    from synapseml_tpu.onnx import ONNXModel

    def build(aligned):
        g = GraphBuilder(opset=21)
        bn = g.add_input("boxes", np.float32, ["N", 6, 4])
        sn = g.add_input("scores", np.float32, ["N", 1, 6])
        ins = [bn, sn, g.add_initializer("mo", np.int64(3)),
               g.add_initializer("iou", np.float32(0.5))]
        y = g.add_node("NonMaxSuppression", ins)
        if aligned:
            shp = g.add_node("Shape", [bn])
            b0 = g.add_node("Gather", [shp, g.add_initializer(
                "z", np.asarray(0, np.int64))])
            tgt = g.add_node("Concat", [
                g.add_node("Unsqueeze", [b0, g.add_initializer(
                    "ax0", np.asarray([0], np.int64))]),
                g.add_initializer("rest", np.asarray([-1, 3], np.int64))],
                axis=0)
            y = g.add_node("Reshape", [y, tgt], outputs=["sel"])
        g.add_output(y, np.int64, None)
        return g.to_bytes(), y

    boxes = np.array([[[0, 0, 1, 1], [0, 0.1, 1, 1.1], [0, -0.1, 1, 0.9],
                       [0, 10, 1, 11], [0, 10.1, 1, 11.1],
                       [0, 100, 1, 101]]] * 2, np.float32)
    scores = np.array([[[0.9, 0.75, 0.6, 0.95, 0.5, 0.3]]] * 2,
                      np.float32)

    blob, out_name = build(False)
    m = ONNXModel(model_bytes=blob, feed_dict={"boxes": "b",
                                               "scores": "s"},
                  fetch_dict={"sel": out_name})
    with pytest.raises(ValueError, match="batch-aligned"):
        m.transform(Table({"b": boxes, "s": scores}))

    blob, out_name = build(True)
    m2 = ONNXModel(model_bytes=blob, feed_dict={"boxes": "b",
                                                "scores": "s"},
                   fetch_dict={"sel": out_name})
    out = m2.transform(Table({"b": boxes, "s": scores}))
    r0 = np.asarray(out["sel"][0])
    r1 = np.asarray(out["sel"][1])
    np.testing.assert_array_equal(r0, [[0, 0, 3], [0, 0, 0], [0, 0, 5]])
    np.testing.assert_array_equal(r1[:, 2], r0[:, 2])  # same picks
    assert (r1[:, 0] == 1).all()                       # its own batch


# ---------------------------------------------------------------------------
# Opset-completion batch 1: windows, MaxPool indices/MaxUnpool, MaxRoiPool,
# deprecated aliases, leftovers of the elementwise/reduce families
# ---------------------------------------------------------------------------

def _spec_cosine_window(name, n, periodic):
    big_n = n if periodic else n - 1
    k = 2 * np.pi * np.arange(n) / max(big_n, 1)
    if name == "HannWindow":
        return 0.5 - 0.5 * np.cos(k)
    if name == "HammingWindow":  # ONNX uses 25/46, NOT torch's 0.54
        return 25.0 / 46.0 - 21.0 / 46.0 * np.cos(k)
    return 0.42 - 0.5 * np.cos(k) + 0.08 * np.cos(2 * k)


@pytest.mark.parametrize("name", ["HannWindow", "HammingWindow",
                                  "BlackmanWindow"])
@pytest.mark.parametrize("periodic", [0, 1])
def test_cosine_windows_match_spec(name, periodic):
    g = GraphBuilder(opset=17)
    s = g.add_initializer("size", np.asarray(16, np.int64))
    out = g.add_node(name, [s], periodic=periodic)
    g.add_output(out, np.float32, [16])
    m = import_model(g.to_bytes())
    got = np.asarray(m.apply(m.params)[0]).reshape(-1)
    np.testing.assert_allclose(
        got, _spec_cosine_window(name, 16, periodic), atol=1e-6)
    # Hann cross-check against torch (whose hamming coefficients differ
    # from the ONNX spec, so only hann/blackman have a torch oracle)
    if name == "HannWindow":
        np.testing.assert_allclose(
            got, torch.hann_window(16, periodic=bool(periodic)).numpy(),
            atol=1e-6)


def test_hann_window_feeds_stft():
    """Window op composed into STFT — the exported torch.stft pattern
    (window built in-graph, not shipped as an initializer)."""
    sig = np.random.default_rng(5).normal(
        size=(1, 256)).astype(np.float32)
    g = GraphBuilder(opset=17)
    s_in = g.add_input("signal", np.float32, [1, 256])
    size_i = g.add_initializer("wsize", np.asarray(64, np.int64))
    win = g.add_node("HannWindow", [size_i])
    step_i = g.add_initializer("step", np.asarray(32, np.int64))
    y = g.add_node("STFT", [s_in, step_i, win], onesided=1)
    g.add_output(y, np.float32, None)
    m = import_model(g.to_bytes())
    got = np.asarray(m.apply(m.params, sig)[0])
    want_c = torch.stft(
        torch.from_numpy(sig), n_fft=64, hop_length=32, win_length=64,
        window=torch.hann_window(64), center=False, onesided=True,
        return_complex=True).numpy()
    want = np.stack([want_c.real, want_c.imag], -1).transpose(0, 2, 1, 3)
    np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-4)


def test_maxpool_indices_and_maxunpool_match_torch():
    """MaxPool's Indices output + MaxUnpool (the SegNet encoder/decoder
    pair) against torch's max_pool2d(return_indices)/max_unpool2d."""
    xs = np.random.default_rng(0).normal(
        size=(2, 3, 8, 10)).astype(np.float32)
    g = GraphBuilder(opset=17)
    x = g.add_input("x", np.float32, list(xs.shape))
    y, i = g.add_node("MaxPool", [x], outputs=["y", "i"],
                      kernel_shape=[2, 3], strides=[2, 2],
                      pads=[0, 1, 0, 1])
    oshape = g.add_initializer("oshape", np.array([2, 3, 8, 10], np.int64))
    u = g.add_node("MaxUnpool", [y, i, oshape], kernel_shape=[2, 3],
                   strides=[2, 2], pads=[0, 1, 0, 1])
    for nm in (y, i, u):
        g.add_output(nm, np.float32, None)
    m = import_model(g.to_bytes())
    gy, gi, gu = [np.asarray(v) for v in m.apply(m.params, xs)]
    ty, ti = torch.nn.functional.max_pool2d(
        torch.from_numpy(xs), (2, 3), (2, 2), (0, 1),
        return_indices=True)
    tu = torch.nn.functional.max_unpool2d(
        ty, ti, (2, 3), (2, 2), (0, 1), output_size=(8, 10))
    np.testing.assert_allclose(gy, ty.numpy())
    # torch flattens per-(N,C) plane; ONNX over the whole tensor
    nc_off = np.arange(2 * 3).reshape(2, 3, 1, 1) * (8 * 10)
    np.testing.assert_array_equal(gi, ti.numpy() + nc_off)
    np.testing.assert_allclose(gu, tu.numpy())


def test_maxpool_indices_degenerate_padding_clamped():
    """A pooling window that falls ENTIRELY inside the padding used to
    recover its argmax coordinate inside the pad region (e.g. -pads[0]),
    emitting a NEGATIVE flat index that MaxUnpool's scatter would wrap
    around to the tensor TAIL, corrupting a real cell. Degenerate
    windows now emit the dtype-max drop sentinel — non-negative and out
    of range for ANY unpool output shape (the spec allows output_shape
    LARGER than the pool input), so MaxUnpool's scatter drops them
    instead of colliding with a real window's cell."""
    xs = np.array([[[1.0, 2.0, 3.0, 4.0]]], np.float32)
    g = GraphBuilder(opset=17)
    x = g.add_input("x", np.float32, [1, 1, 4])
    y, i = g.add_node("MaxPool", [x], outputs=["y", "i"],
                      kernel_shape=[2], strides=[2], pads=[2, 2])
    u = g.add_node("MaxUnpool", [y, i], kernel_shape=[2], strides=[2],
                   pads=[2, 2])
    # spec-sanctioned ENLARGED output_shape: an input-sized sentinel
    # (4) would land INSIDE this 6-cell output and corrupt cell 4
    oshape = g.add_initializer("oshape", np.array([1, 1, 6], np.int64))
    u2 = g.add_node("MaxUnpool", [y, i, oshape], kernel_shape=[2],
                    strides=[2], pads=[2, 2])
    for nm in (y, i, u, u2):
        g.add_output(nm, np.float32, None)
    m = import_model(g.to_bytes())
    gy, gi, gu, gu2 = [np.asarray(v) for v in m.apply(m.params, xs)]
    # windows over the padded extent [-inf,-inf, 1,2,3,4, -inf,-inf]:
    # [-inf,-inf], [1,2], [3,4], [-inf,-inf] — first and last are
    # entirely padding (their pooled value is the -inf init)
    np.testing.assert_array_equal(gy[0, 0, 1:3], [2.0, 4.0])
    assert gy[0, 0, 0] == -np.inf and gy[0, 0, 3] == -np.inf
    # the regression: window 0 used to emit flat index -2 (wrapping to
    # cell 2 under MaxUnpool); real windows keep exact indices, the two
    # degenerate windows take the dtype-max drop sentinel
    assert (gi >= 0).all(), gi
    sentinel = np.iinfo(gi.dtype).max
    np.testing.assert_array_equal(gi[0, 0], [sentinel, 1, 3, sentinel])
    # MaxUnpool round trips: real maxima land on their cells, degenerate
    # windows' -inf is DROPPED — no wraparound, no collision, even when
    # the explicit output_shape is larger than the pool's input
    np.testing.assert_array_equal(gu[0, 0], [0.0, 2.0, 0.0, 4.0])
    np.testing.assert_array_equal(gu2[0, 0], [0.0, 2.0, 0.0, 4.0, 0.0, 0.0])


def test_maxunpool_inferred_shape_and_1d():
    xs = np.random.default_rng(3).normal(
        size=(2, 3, 8, 8)).astype(np.float32)
    g = GraphBuilder(opset=17)
    x = g.add_input("x", np.float32, [2, 3, 8, 8])
    y, i = g.add_node("MaxPool", [x], outputs=["y", "i"],
                      kernel_shape=[2, 2], strides=[2, 2])
    u = g.add_node("MaxUnpool", [y, i], kernel_shape=[2, 2],
                   strides=[2, 2])
    g.add_output(u, np.float32, None)
    m = import_model(g.to_bytes())
    gu = np.asarray(m.apply(m.params, xs)[0])
    ty, ti = torch.nn.functional.max_pool2d(
        torch.from_numpy(xs), 2, 2, return_indices=True)
    tu = torch.nn.functional.max_unpool2d(ty, ti, 2, 2)
    np.testing.assert_allclose(gu, tu.numpy())

    # 1-D: rank-generic path
    xs1 = np.random.default_rng(4).normal(size=(1, 2, 9)).astype(np.float32)
    g1 = GraphBuilder(opset=17)
    x1 = g1.add_input("x", np.float32, [1, 2, 9])
    y1, i1 = g1.add_node("MaxPool", [x1], outputs=["y1", "i1"],
                         kernel_shape=[3], strides=[3])
    u1 = g1.add_node("MaxUnpool", [y1, i1], kernel_shape=[3], strides=[3])
    g1.add_output(u1, np.float32, None)
    m1 = import_model(g1.to_bytes())
    gu1 = np.asarray(m1.apply(m1.params, xs1)[0])
    ty1, ti1 = torch.nn.functional.max_pool1d(
        torch.from_numpy(xs1), 3, 3, return_indices=True)
    tu1 = torch.nn.functional.max_unpool1d(ty1, ti1, 3, 3)
    np.testing.assert_allclose(gu1, tu1.numpy())


def test_max_roi_pool_matches_quantized_reference():
    """MaxRoiPool (Caffe ROIPooling quantization) against a literal
    per-bin numpy evaluation of the spec."""
    xs = np.random.default_rng(1).normal(
        size=(2, 3, 12, 14)).astype(np.float32)
    rois = np.array([[0, 0, 0, 11, 11], [1, 2, 2, 7, 9],
                     [0, 4, 1, 13, 10]], np.float32)
    g = GraphBuilder(opset=17)
    x = g.add_input("x", np.float32, list(xs.shape))
    r = g.add_input("r", np.float32, list(rois.shape))
    o = g.add_node("MaxRoiPool", [x, r], pooled_shape=[3, 4],
                   spatial_scale=0.5)
    g.add_output(o, np.float32, None)
    m = import_model(g.to_bytes())
    got = np.asarray(m.apply(m.params, xs, rois)[0])

    ph, pw, scale = 3, 4, 0.5
    want = np.zeros((len(rois), xs.shape[1], ph, pw), np.float32)
    height, width = xs.shape[2:]
    for ri, roi in enumerate(rois):
        b = int(round(roi[0]))
        x1, y1, x2, y2 = [int(round(v * scale)) for v in roi[1:]]
        rh, rw = max(y2 - y1 + 1, 1), max(x2 - x1 + 1, 1)
        for p in range(ph):
            hs = min(max(int(np.floor(p * rh / ph)) + y1, 0), height)
            he = min(max(int(np.ceil((p + 1) * rh / ph)) + y1, 0), height)
            for q in range(pw):
                ws = min(max(int(np.floor(q * rw / pw)) + x1, 0), width)
                we = min(max(int(np.ceil((q + 1) * rw / pw)) + x1, 0),
                         width)
                if he > hs and we > ws:
                    want[ri, :, p, q] = xs[b, :, hs:he, ws:we].max((1, 2))
    np.testing.assert_allclose(got, want)


def test_opset_leftovers_elementwise_and_aliases():
    """Asinh/Acosh/Atanh/Det/ReduceLogSum/Affine + the deprecated
    Scatter alias — the long tail that completes the default-domain
    opset table."""
    g = GraphBuilder(opset=17)
    x = g.add_input("x", np.float32, [2, 3, 3])
    outs = [g.add_node("Asinh", [x]), g.add_node("Acosh", [x]),
            g.add_node("Atanh", [x]),
            g.add_node("Det", [x]),
            g.add_node("ReduceLogSum", [x], axes=[1], keepdims=0),
            g.add_node("Affine", [x], alpha=2.0, beta=0.5)]
    for nm in outs:
        g.add_output(nm, np.float32, None)
    m = import_model(g.to_bytes())
    xv = (np.random.default_rng(2).random((2, 3, 3)) * 0.2
          + 1.2).astype(np.float32)  # >1 so acosh is defined
    asinh_v, acosh_v, atanh_v, det_v, rls_v, aff_v = [
        np.asarray(v) for v in m.apply(m.params, xv)]
    np.testing.assert_allclose(asinh_v, np.arcsinh(xv), atol=1e-5)
    np.testing.assert_allclose(acosh_v, np.arccosh(xv), atol=1e-5)
    # atanh needs |x|<1
    np.testing.assert_allclose(
        np.asarray(m.apply(m.params, xv - 1.0)[2]),
        np.arctanh(xv - 1.0), atol=1e-5)
    np.testing.assert_allclose(det_v, np.linalg.det(xv), atol=1e-4)
    np.testing.assert_allclose(rls_v, np.log(xv.sum(1)), atol=1e-5)
    np.testing.assert_allclose(aff_v, 2 * xv + 0.5, atol=1e-6)

    g2 = GraphBuilder(opset=9)
    x2 = g2.add_input("x", np.float32, [3, 3])
    ii = g2.add_initializer("ii", np.array([[0, 1, 2]], np.int64))
    uu = g2.add_initializer("uu", np.array([[9., 8., 7.]], np.float32))
    s = g2.add_node("Scatter", [x2, ii, uu], axis=0)
    g2.add_output(s, np.float32, None)
    m2 = import_model(g2.to_bytes())
    got = np.asarray(m2.apply(m2.params, np.zeros((3, 3), np.float32))[0])
    want = np.zeros((3, 3), np.float32)
    want[0, 0], want[1, 1], want[2, 2] = 9, 8, 7
    np.testing.assert_allclose(got, want)


# ---------------------------------------------------------------------------
# Opset-completion batch 2: string ops, SequenceMap, DeformConv, ImageDecoder
# ---------------------------------------------------------------------------

def test_string_ops_concat_split_normalize_regex():
    g = GraphBuilder(opset=20)
    a = g.add_initializer(
        "a", np.asarray(["foo", "bar baz qux", ""], object))
    b = g.add_initializer("b", np.asarray(["_x", "_y", "_z"], object))
    c = g.add_node("StringConcat", [a, b])
    s, n = g.add_node("StringSplit", [a], outputs=["s", "n"])
    norm = g.add_node("StringNormalizer", [a],
                      case_change_action="UPPER", stopwords=["foo"],
                      is_case_sensitive=1)
    rx = g.add_node("RegexFullMatch", [a], pattern=r"\w+")
    for nm in (c, s, n, norm, rx):
        g.add_output(nm, np.float32, None)
    m = import_model(g.to_bytes())
    cv, sv, nv, normv, rxv = m.apply(m.params)
    assert list(cv) == ["foo_x", "bar baz qux_y", "_z"]
    assert sv.shape == (3, 3)
    assert list(sv[1]) == ["bar", "baz", "qux"]
    assert list(sv[0]) == ["foo", "", ""]       # "" padding
    assert list(nv) == [1, 3, 0]                # whitespace-mode counts
    # "foo" is a stopword (elementwise match), remainder uppercased
    assert list(normv) == ["BAR BAZ QUX", ""]
    assert list(rxv) == [True, False, False]    # fullmatch, not search

    # delimiter + maxsplit form
    g2 = GraphBuilder(opset=20)
    a2 = g2.add_initializer("a", np.asarray(["a,b,c,d", "x,,y"], object))
    s2, n2 = g2.add_node("StringSplit", [a2], outputs=["s2", "n2"],
                         delimiter=",", maxsplit=2)
    g2.add_output(s2, np.float32, None)
    g2.add_output(n2, np.int64, None)
    m2 = import_model(g2.to_bytes())
    sv2, nv2 = m2.apply(m2.params)
    assert list(sv2[0]) == ["a", "b", "c,d"]
    assert list(sv2[1]) == ["x", "", "y"]       # empties kept with delim
    assert list(nv2) == [3, 3]

    # all-stopword input collapses to the spec's single empty string
    g3 = GraphBuilder(opset=20)
    a3 = g3.add_initializer("a", np.asarray([["Stop", "STOP"]], object))
    n3 = g3.add_node("StringNormalizer", [a3], stopwords=["stop"],
                     is_case_sensitive=0, case_change_action="LOWER")
    g3.add_output(n3, np.float32, None)
    m3 = import_model(g3.to_bytes())
    out3 = m3.apply(m3.params)[0]
    assert out3.shape == (1, 1) and out3[0, 0] == ""


def test_sequence_map_body_over_sequence():
    """SequenceMap: body runs per element; tensor extras broadcast,
    sequence extras zip."""
    body = GraphBuilder(name="body", opset=17, name_prefix="b_")
    e = body.add_input("e", None)
    t = body.add_input("t", None)
    o = body.add_node("Add", [e, t], outputs=["b_out"])
    body.add_output(o, np.float32, None)
    g = GraphBuilder(opset=17)
    x1 = g.add_initializer("x1", np.asarray([1., 2.], np.float32))
    x2 = g.add_initializer("x2", np.asarray([10., 20., 30.], np.float32))
    extra = g.add_initializer("extra", np.asarray([100.], np.float32))
    seq = g.add_node("SequenceConstruct", [x1, x2])
    mapped = g.add_node("SequenceMap", [seq, extra],
                        body=body.build().graph)
    cc = g.add_node("ConcatFromSequence", [mapped], axis=0)
    g.add_output(cc, np.float32, None)
    m = import_model(g.to_bytes())
    out = np.asarray(m.apply(m.params)[0])
    np.testing.assert_allclose(out, [101, 102, 110, 120, 130])


def test_deform_conv_matches_literal_reference():
    """DeformConv (opset 19) vs a literal per-pixel numpy evaluation of
    the torchvision-semantics spec: offsets, modulation mask, groups,
    offset_groups, strides/pads/dilations all exercised."""
    rng = np.random.default_rng(0)
    n, c, h, wd, oc, kh, kw = 2, 4, 7, 8, 6, 3, 2
    strides, pads, dil, group, og = [2, 1], [1, 0, 1, 0], [1, 1], 2, 2
    oh = (h + pads[0] + pads[2] - (dil[0] * (kh - 1) + 1)) // strides[0] + 1
    ow = (wd + pads[1] + pads[3] - (dil[1] * (kw - 1) + 1)) // strides[1] + 1
    x = rng.normal(size=(n, c, h, wd)).astype(np.float32)
    w = rng.normal(size=(oc, c // group, kh, kw)).astype(np.float32)
    off = (rng.normal(size=(n, og * 2 * kh * kw, oh, ow)) * 1.5
           ).astype(np.float32)
    msk = rng.random(size=(n, og * kh * kw, oh, ow)).astype(np.float32)
    bias = rng.normal(size=(oc,)).astype(np.float32)

    g = GraphBuilder(opset=19)
    xi = g.add_input("x", np.float32, list(x.shape))
    wi = g.add_initializer("w", w)
    oi = g.add_input("off", np.float32, list(off.shape))
    bi = g.add_initializer("b", bias)
    mi = g.add_input("m", np.float32, list(msk.shape))
    y = g.add_node("DeformConv", [xi, wi, oi, bi, mi], strides=strides,
                   pads=pads, dilations=dil, group=group, offset_group=og,
                   kernel_shape=[kh, kw])
    g.add_output(y, np.float32, None)
    m = import_model(g.to_bytes())
    got = np.asarray(m.apply(m.params, x, off, msk)[0])

    def bilinear(xc, py, px):
        y0, x0 = int(np.floor(py)), int(np.floor(px))
        fy, fx = py - y0, px - x0
        v = 0.0
        for yy, xx, wt in [(y0, x0, (1 - fy) * (1 - fx)),
                           (y0, x0 + 1, (1 - fy) * fx),
                           (y0 + 1, x0, fy * (1 - fx)),
                           (y0 + 1, x0 + 1, fy * fx)]:
            if 0 <= yy < h and 0 <= xx < wd:
                v += wt * xc[yy, xx]
        return v

    want = np.zeros((n, oc, oh, ow))
    cg = c // og
    for ni in range(n):
        for o in range(oc):
            gi_ = o // (oc // group)
            for ohh in range(oh):
                for oww in range(ow):
                    acc = 0.0
                    for ci in range(c // group):
                        cin = gi_ * (c // group) + ci
                        gg = cin // cg
                        for i in range(kh):
                            for j in range(kw):
                                kidx = i * kw + j
                                dy = off[ni, (gg * kh * kw + kidx) * 2,
                                         ohh, oww]
                                dx = off[ni, (gg * kh * kw + kidx) * 2 + 1,
                                         ohh, oww]
                                py = (ohh * strides[0] - pads[0]
                                      + i * dil[0] + dy)
                                px = (oww * strides[1] - pads[1]
                                      + j * dil[1] + dx)
                                v = bilinear(x[ni, cin], py, px)
                                v *= msk[ni, gg * kh * kw + kidx, ohh, oww]
                                acc += w[o, ci, i, j] * v
                    want[ni, o, ohh, oww] = acc + bias[o]
    np.testing.assert_allclose(got, want, atol=1e-4)


def test_image_decoder_png_and_formats():
    from PIL import Image
    import io as _io

    rng = np.random.default_rng(7)
    arr = rng.integers(0, 255, size=(9, 11, 3)).astype(np.uint8)
    buf = _io.BytesIO()
    Image.fromarray(arr).save(buf, "PNG")
    data = np.frombuffer(buf.getvalue(), np.uint8)
    for fmt, want in [("RGB", arr), ("BGR", arr[:, :, ::-1]),
                      ("Grayscale", None)]:
        g = GraphBuilder(opset=20)
        e = g.add_initializer("enc", data)
        d = g.add_node("ImageDecoder", [e], pixel_format=fmt)
        g.add_output(d, np.uint8, None)
        m = import_model(g.to_bytes())
        got = np.asarray(m.apply(m.params)[0])
        if fmt == "Grayscale":
            assert got.shape == (9, 11, 1)
            want = np.asarray(
                Image.fromarray(arr).convert("L"), np.uint8)[:, :, None]
        np.testing.assert_array_equal(got, want)


def test_maxpool_indices_ceil_dilation_tiebreak():
    """Indices path corners: ceil_mode, dilations, and the all-equal
    tie-break (row-major first occurrence, onnxruntime's rule)."""
    xs = np.random.default_rng(9).normal(
        size=(1, 2, 9, 9)).astype(np.float32)

    g = GraphBuilder(opset=17)
    x = g.add_input("x", np.float32, [1, 2, 9, 9])
    y, i = g.add_node("MaxPool", [x], outputs=["y", "i"],
                      kernel_shape=[2, 2], strides=[2, 2], ceil_mode=1)
    g.add_output(y, np.float32, None)
    g.add_output(i, np.int64, None)
    m = import_model(g.to_bytes())
    gy, gi = [np.asarray(v) for v in m.apply(m.params, xs)]
    ty, ti = torch.nn.functional.max_pool2d(
        torch.from_numpy(xs), 2, 2, ceil_mode=True, return_indices=True)
    np.testing.assert_allclose(gy, ty.numpy())
    nc_off = np.arange(2).reshape(1, 2, 1, 1) * 81
    np.testing.assert_array_equal(gi, ti.numpy() + nc_off)

    g2 = GraphBuilder(opset=17)
    x2 = g2.add_input("x", np.float32, [1, 2, 9, 9])
    y2, i2 = g2.add_node("MaxPool", [x2], outputs=["y2", "i2"],
                         kernel_shape=[2, 2], dilations=[2, 2])
    g2.add_output(y2, np.float32, None)
    g2.add_output(i2, np.int64, None)
    m2 = import_model(g2.to_bytes())
    gy2, gi2 = [np.asarray(v) for v in m2.apply(m2.params, xs)]
    ty2, ti2 = torch.nn.functional.max_pool2d(
        torch.from_numpy(xs), 2, 1, dilation=2, return_indices=True)
    np.testing.assert_allclose(gy2, ty2.numpy())
    np.testing.assert_array_equal(gi2, ti2.numpy() + nc_off)

    # all-equal window: the FIRST (row-major) position must win
    ones = np.ones((1, 1, 4, 4), np.float32)
    g3 = GraphBuilder(opset=17)
    x3 = g3.add_input("x", np.float32, [1, 1, 4, 4])
    y3, i3 = g3.add_node("MaxPool", [x3], outputs=["y3", "i3"],
                         kernel_shape=[2, 2], strides=[2, 2])
    g3.add_output(i3, np.int64, None)
    m3 = import_model(g3.to_bytes())
    gi3 = np.asarray(m3.apply(m3.params, ones)[0])
    np.testing.assert_array_equal(gi3[0, 0], [[0, 2], [8, 10]])


# ---------------------------------------------------------------------------
# com.microsoft transformer-fusion family (ORT transformer-optimizer output)
# ---------------------------------------------------------------------------

def _mk_attention_ref(x, w, bias, num_heads, lens=None, causal=False,
                      past=None):
    """Literal torch multi-head attention matching the contrib op."""
    import math as _math

    b, s, _ = x.shape
    hidden = w.shape[1] // 3
    d = hidden // num_heads
    qkv = torch.tensor(x) @ torch.tensor(w) + torch.tensor(bias)
    q, k, v = qkv.split(hidden, dim=-1)

    def hd(t):
        return t.reshape(b, s, num_heads, d).permute(0, 2, 1, 3)

    q, k, v = hd(q), hd(k), hd(v)
    past_len = 0
    if past is not None:
        pk, pv = torch.tensor(past[0]), torch.tensor(past[1])
        past_len = pk.shape[2]
        k = torch.cat([pk, k], dim=2)
        v = torch.cat([pv, v], dim=2)
    t_kv = k.shape[2]
    logits = (q @ k.transpose(-1, -2)) / _math.sqrt(d)
    if lens is not None:
        ok = torch.arange(t_kv)[None, :] < torch.tensor(lens)[:, None]
        logits = logits.masked_fill(~ok[:, None, None, :], -1e9)
    if causal:
        qp = past_len + torch.arange(s)[:, None]
        cm = torch.arange(t_kv)[None, :] <= qp
        logits = logits.masked_fill(~cm[None, None], -1e9)
    out = torch.softmax(logits, -1) @ v
    return out.permute(0, 2, 1, 3).reshape(b, s, hidden).numpy(), \
        torch.stack([k, v]).numpy()


def test_contrib_attention_masks_causal_and_past():
    rng = np.random.default_rng(0)
    b, s, h, n = 2, 5, 24, 3
    x = rng.normal(size=(b, s, h)).astype(np.float32)
    w = (rng.normal(size=(h, 3 * h)) * 0.3).astype(np.float32)
    bias = rng.normal(size=(3 * h,)).astype(np.float32)
    lens = np.array([5, 3], np.int32)

    # [B] length mask
    g = GraphBuilder(opset=17)
    xi = g.add_input("x", np.float32, [b, s, h])
    wi = g.add_initializer("w", w)
    bi = g.add_initializer("b", bias)
    mi = g.add_input("m", np.int32, [b])
    att = g.add_node("Attention", [xi, wi, bi, mi],
                     domain="com.microsoft", num_heads=n)
    g.add_output(att, np.float32, None)
    m = import_model(g.to_bytes())
    got = np.asarray(m.apply(m.params, x, lens)[0])
    want, _ = _mk_attention_ref(x, w, bias, n, lens=lens)
    np.testing.assert_allclose(got, want, atol=1e-4)

    # unidirectional + past KV cache, present output
    p = 3
    past = rng.normal(size=(2, b, n, p, h // n)).astype(np.float32)
    g2 = GraphBuilder(opset=17)
    xi2 = g2.add_input("x", np.float32, [b, s, h])
    wi2 = g2.add_initializer("w", w)
    bi2 = g2.add_initializer("b", bias)
    pi2 = g2.add_input("past", np.float32, list(past.shape))
    att2, pres = g2.add_node(
        "Attention", [xi2, wi2, bi2, "", pi2], outputs=["att2", "pres"],
        domain="com.microsoft", num_heads=n, unidirectional=1)
    g2.add_output(att2, np.float32, None)
    g2.add_output(pres, np.float32, None)
    m2 = import_model(g2.to_bytes())
    got2, pres2 = [np.asarray(v) for v in m2.apply(m2.params, x, past)]
    want2, want_pres = _mk_attention_ref(x, w, bias, n, causal=True,
                                         past=past)
    np.testing.assert_allclose(got2, want2, atol=1e-4)
    np.testing.assert_allclose(pres2, want_pres, atol=1e-5)

    # [B, T] 0/1 key mask == the length mask it encodes
    key_mask = (np.arange(s)[None] < lens[:, None]).astype(np.int32)
    g3 = GraphBuilder(opset=17)
    xi3 = g3.add_input("x", np.float32, [b, s, h])
    mi3 = g3.add_input("m", np.int32, [b, s])
    att3 = g3.add_node(
        "Attention",
        [xi3, g3.add_initializer("w", w), g3.add_initializer("b", bias),
         mi3], domain="com.microsoft", num_heads=n)
    g3.add_output(att3, np.float32, None)
    m3 = import_model(g3.to_bytes())
    got3 = np.asarray(m3.apply(m3.params, x, key_mask)[0])
    np.testing.assert_allclose(got3, want, atol=1e-4)


def test_fusion_family_matches_unfused_and_torch():
    """Each ORT fusion op == its unfused composition (and torch where a
    direct oracle exists), so optimizer-processed exports score
    identically to raw ones."""
    rng = np.random.default_rng(1)
    b, s, h = 2, 4, 16
    x = rng.normal(size=(b, s, h)).astype(np.float32)
    skip = rng.normal(size=(b, s, h)).astype(np.float32)
    gamma = rng.normal(size=(h,)).astype(np.float32)
    beta = rng.normal(size=(h,)).astype(np.float32)
    bias = rng.normal(size=(h,)).astype(np.float32)
    w = rng.normal(size=(h, h)).astype(np.float32)

    g = GraphBuilder(opset=17)
    xi = g.add_input("x", np.float32, [b, s, h])
    si = g.add_input("s", np.float32, [b, s, h])
    names = {k: g.add_initializer(k, v) for k, v in
             [("ga", gamma), ("be", beta), ("bi", bias), ("w", w)]}
    outs = [
        g.add_node("SkipLayerNormalization", [xi, si, names["ga"],
                   names["be"], names["bi"]], domain="com.microsoft"),
        g.add_node("SkipSimplifiedLayerNormalization",
                   [xi, si, names["ga"]], domain="com.microsoft"),
        g.add_node("BiasGelu", [xi, names["bi"]], domain="com.microsoft"),
        g.add_node("FastGelu", [xi, names["bi"]], domain="com.microsoft"),
        g.add_node("QuickGelu", [xi], domain="com.microsoft"),
        g.add_node("FusedMatMul", [xi, names["w"]],
                   domain="com.microsoft", alpha=0.5, transB=1),
        g.add_node("SimplifiedLayerNormalization", [xi, names["ga"]],
                   epsilon=1e-6),
    ]
    for nm in outs:
        g.add_output(nm, np.float32, None)
    m = import_model(g.to_bytes())
    (sln, ssln, bg, fg, qg, fmm, rms) = [
        np.asarray(v) for v in m.apply(m.params, x, skip)]

    hsum = x + skip + bias
    mu = hsum.mean(-1, keepdims=True)
    va = hsum.var(-1, keepdims=True)
    np.testing.assert_allclose(
        sln, (hsum - mu) / np.sqrt(va + 1e-5) * gamma + beta, atol=1e-4)
    h2 = x + skip
    np.testing.assert_allclose(
        ssln, h2 / np.sqrt((h2 ** 2).mean(-1, keepdims=True) + 1e-5)
        * gamma, atol=1e-4)
    np.testing.assert_allclose(
        bg, torch.nn.functional.gelu(torch.tensor(x + bias)).numpy(),
        atol=1e-4)
    np.testing.assert_allclose(
        fg, torch.nn.functional.gelu(torch.tensor(x + bias),
                                     approximate="tanh").numpy(),
        atol=1e-4)
    np.testing.assert_allclose(
        qg, (torch.tensor(x)
             * torch.sigmoid(1.702 * torch.tensor(x))).numpy(), atol=1e-4)
    np.testing.assert_allclose(fmm, 0.5 * (x @ w.T), atol=1e-4)
    np.testing.assert_allclose(
        rms, x / np.sqrt((x ** 2).mean(-1, keepdims=True) + 1e-6) * gamma,
        atol=1e-5)


def test_embed_layer_normalization_bert_frontend():
    rng = np.random.default_rng(2)
    b, s, h, v, p = 2, 6, 16, 40, 12
    word = rng.normal(size=(v, h)).astype(np.float32)
    pos = rng.normal(size=(p, h)).astype(np.float32)
    seg = rng.normal(size=(2, h)).astype(np.float32)
    gamma = rng.normal(size=(h,)).astype(np.float32)
    beta = rng.normal(size=(h,)).astype(np.float32)
    ids = rng.integers(0, v, (b, s)).astype(np.int32)
    sids = rng.integers(0, 2, (b, s)).astype(np.int32)
    lens = np.array([6, 4], np.int32)
    msk = (np.arange(s)[None] < lens[:, None]).astype(np.int32)

    g = GraphBuilder(opset=17)
    ii = g.add_input("ids", np.int32, [b, s])
    si = g.add_input("sids", np.int32, [b, s])
    mi = g.add_input("mask", np.int32, [b, s])
    names = [g.add_initializer(n_, a_) for n_, a_ in
             [("we", word), ("pe", pos), ("se", seg), ("ga", gamma),
              ("bt", beta)]]
    el, mx = g.add_node("EmbedLayerNormalization", [ii, si] + names + [mi],
                        outputs=["el", "mx"], domain="com.microsoft",
                        epsilon=1e-12)
    g.add_output(el, np.float32, None)
    g.add_output(mx, np.int32, None)
    m = import_model(g.to_bytes())
    gy, gm = [np.asarray(o) for o in m.apply(m.params, ids, sids, msk)]
    emb = word[ids] + pos[np.arange(s)][None] + seg[sids]
    mu = emb.mean(-1, keepdims=True)
    va = emb.var(-1, keepdims=True)
    np.testing.assert_allclose(
        gy, (emb - mu) / np.sqrt(va + 1e-12) * gamma + beta, atol=1e-4)
    np.testing.assert_array_equal(gm, lens)


def test_fused_encoder_layer_equals_unfused_composition():
    """A full ORT-optimizer-shaped encoder layer (Attention +
    SkipLayerNormalization + BiasGelu + FusedMatMul) against the same
    layer written as raw MatMul/Add/LayerNormalization/Gelu nodes with
    identical weights — the end-to-end form of the per-op fusion
    equalities, proving optimized and raw exports score identically."""
    rng = np.random.default_rng(3)
    b, s, h, n = 2, 6, 32, 4
    x = rng.normal(size=(b, s, h)).astype(np.float32)
    aw = (rng.normal(size=(h, 3 * h)) * 0.2).astype(np.float32)
    ab = rng.normal(size=(3 * h,)).astype(np.float32)
    g1 = rng.normal(size=(h,)).astype(np.float32)
    b1 = rng.normal(size=(h,)).astype(np.float32)
    fw = (rng.normal(size=(h, 4 * h)) * 0.2).astype(np.float32)
    fb = rng.normal(size=(4 * h,)).astype(np.float32)
    fw2 = (rng.normal(size=(4 * h, h)) * 0.2).astype(np.float32)
    g2 = rng.normal(size=(h,)).astype(np.float32)
    b2 = rng.normal(size=(h,)).astype(np.float32)
    lens = np.array([6, 4], np.int32)

    gf = GraphBuilder(opset=17)
    xi = gf.add_input("x", np.float32, [b, s, h])
    mi = gf.add_input("m", np.int32, [b])
    att = gf.add_node(
        "Attention", [xi, gf.add_initializer("aw", aw),
                      gf.add_initializer("ab", ab), mi],
        domain="com.microsoft", num_heads=n)
    s1 = gf.add_node(
        "SkipLayerNormalization", [att, xi, gf.add_initializer("g1", g1),
                                   gf.add_initializer("b1", b1)],
        domain="com.microsoft")
    ff = gf.add_node("FusedMatMul", [s1, gf.add_initializer("fw", fw)],
                     domain="com.microsoft")
    gl = gf.add_node("BiasGelu", [ff, gf.add_initializer("fb", fb)],
                     domain="com.microsoft")
    fo = gf.add_node("FusedMatMul", [gl, gf.add_initializer("fw2", fw2)],
                     domain="com.microsoft")
    s2 = gf.add_node(
        "SkipLayerNormalization", [fo, s1, gf.add_initializer("g2", g2),
                                   gf.add_initializer("b2", b2)],
        domain="com.microsoft")
    gf.add_output(s2, np.float32, None)
    mf = import_model(gf.to_bytes())
    fused = np.asarray(mf.apply(mf.params, x, lens)[0])

    # raw composition with the same weights
    want_att, _ = _mk_attention_ref(x, aw, ab, n, lens=lens)
    gr = GraphBuilder(opset=17)
    ai = gr.add_input("att", np.float32, [b, s, h])
    xi2 = gr.add_input("x", np.float32, [b, s, h])
    ad1 = gr.add_node("Add", [ai, xi2])
    ln1 = gr.add_node(
        "LayerNormalization", [ad1, gr.add_initializer("g1", g1),
                               gr.add_initializer("b1", b1)])
    mm1 = gr.add_node("MatMul", [ln1, gr.add_initializer("fw", fw)])
    ad2 = gr.add_node("Add", [mm1, gr.add_initializer("fb", fb)])
    ge = gr.add_node("Gelu", [ad2])
    mm2 = gr.add_node("MatMul", [ge, gr.add_initializer("fw2", fw2)])
    ad3 = gr.add_node("Add", [mm2, ln1])
    ln2 = gr.add_node(
        "LayerNormalization", [ad3, gr.add_initializer("g2", g2),
                               gr.add_initializer("b2", b2)])
    gr.add_output(ln2, np.float32, None)
    mr = import_model(gr.to_bytes())
    raw = np.asarray(mr.apply(mr.params, want_att, x)[0])
    np.testing.assert_allclose(fused, raw, atol=2e-4)


def test_standard_attention_opset23_matches_torch_sdpa():
    """Standard ai.onnx Attention (opset 23, what torch's newest
    exporter emits): 4-D GQA causal, 3-D with boolean mask, and
    scale+softcap+additive mask — all against torch SDPA / a literal
    reference."""
    rng = np.random.default_rng(0)
    b, nq, nk, s, t, d = 2, 4, 2, 5, 5, 8
    q = rng.normal(size=(b, nq, s, d)).astype(np.float32)
    k = rng.normal(size=(b, nk, t, d)).astype(np.float32)
    v = rng.normal(size=(b, nk, t, d)).astype(np.float32)

    g = GraphBuilder(opset=23)
    qi = g.add_input("q", np.float32, list(q.shape))
    ki = g.add_input("k", np.float32, list(k.shape))
    vi = g.add_input("v", np.float32, list(v.shape))
    g.add_output(g.add_node("Attention", [qi, ki, vi], is_causal=1),
                 np.float32, None)
    m = import_model(g.to_bytes())
    got = np.asarray(m.apply(m.params, q, k, v)[0])
    want = torch.nn.functional.scaled_dot_product_attention(
        torch.tensor(q), torch.tensor(k), torch.tensor(v),
        is_causal=True, enable_gqa=True).numpy()
    np.testing.assert_allclose(got, want, atol=1e-5)

    # cross-length causal: top-left (tril) alignment per spec — s != t
    # is where top-left and bottom-right diverge
    qs = q[:, :, :3]
    got_s = np.asarray(m.apply(m.params, qs, k, v)[0])
    want_s = torch.nn.functional.scaled_dot_product_attention(
        torch.tensor(qs), torch.tensor(k), torch.tensor(v),
        is_causal=True, enable_gqa=True).numpy()
    np.testing.assert_allclose(got_s, want_s, atol=1e-5)

    # 3-D layout + boolean mask (broadcast over heads)
    q3 = rng.normal(size=(b, s, nq * d)).astype(np.float32)
    k3 = rng.normal(size=(b, t, nk * d)).astype(np.float32)
    v3 = rng.normal(size=(b, t, nk * d)).astype(np.float32)
    mask = rng.random((b, 1, s, t)) > 0.3
    g2 = GraphBuilder(opset=23)
    qi2 = g2.add_input("q", np.float32, list(q3.shape))
    ki2 = g2.add_input("k", np.float32, list(k3.shape))
    vi2 = g2.add_input("v", np.float32, list(v3.shape))
    mi2 = g2.add_input("m", np.bool_, list(mask.shape))
    g2.add_output(
        g2.add_node("Attention", [qi2, ki2, vi2, mi2], q_num_heads=nq,
                    kv_num_heads=nk), np.float32, None)
    m2 = import_model(g2.to_bytes())
    got2 = np.asarray(m2.apply(m2.params, q3, k3, v3, mask)[0])

    def hd(x_, n):
        return torch.tensor(x_).reshape(b, -1, n, d).permute(0, 2, 1, 3)

    want2 = torch.nn.functional.scaled_dot_product_attention(
        hd(q3, nq), hd(k3, nk), hd(v3, nk),
        attn_mask=torch.tensor(mask), enable_gqa=True) \
        .permute(0, 2, 1, 3).reshape(b, s, nq * d).numpy()
    np.testing.assert_allclose(got2, np.nan_to_num(want2), atol=1e-5)

    # explicit scale + softcap (Gemma-style) + additive float mask
    addm = (rng.normal(size=(s, t)) * 2).astype(np.float32)
    g3 = GraphBuilder(opset=23)
    qi3 = g3.add_input("q", np.float32, list(q.shape))
    ki3 = g3.add_input("k", np.float32, list(k.shape))
    vi3 = g3.add_input("v", np.float32, list(v.shape))
    mi3 = g3.add_initializer("m", addm)
    g3.add_output(
        g3.add_node("Attention", [qi3, ki3, vi3, mi3], scale=0.25,
                    softcap=5.0), np.float32, None)
    m3 = import_model(g3.to_bytes())
    got3 = np.asarray(m3.apply(m3.params, q, k, v)[0])
    kr, vr = np.repeat(k, 2, 1), np.repeat(v, 2, 1)
    logits = torch.einsum("bnsd,bntd->bnst", torch.tensor(q),
                          torch.tensor(kr)) * 0.25
    # spec node order: Add(mask) BEFORE softcap
    logits = 5.0 * torch.tanh((logits + torch.tensor(addm)) / 5.0)
    want3 = torch.einsum("bnst,bntd->bnsd", torch.softmax(logits, -1),
                         torch.tensor(vr)).numpy()
    np.testing.assert_allclose(got3, want3, atol=1e-5)

    # V head size differing from QK head size (spec-legal)
    dv = 4
    v5 = rng.normal(size=(b, nk, t, dv)).astype(np.float32)
    g5 = GraphBuilder(opset=23)
    qi5 = g5.add_input("q", np.float32, list(q.shape))
    ki5 = g5.add_input("k", np.float32, list(k.shape))
    vi5 = g5.add_input("v", np.float32, list(v5.shape))
    g5.add_output(g5.add_node("Attention", [qi5, ki5, vi5]),
                  np.float32, None)
    m5_ = import_model(g5.to_bytes())
    got5 = np.asarray(m5_.apply(m5_.params, q, k, v5)[0])
    want5 = torch.nn.functional.scaled_dot_product_attention(
        torch.tensor(q), torch.tensor(k), torch.tensor(v5),
        enable_gqa=True).numpy()
    assert got5.shape == (b, nq, s, dv)
    np.testing.assert_allclose(got5, want5, atol=1e-5)

    # RMSNormalization (the opset-23 standard name) aliases the
    # spec-identical SimplifiedLayerNormalization lowering
    gamma = rng.normal(size=(nq * d,)).astype(np.float32)
    g4 = GraphBuilder(opset=23)
    xi4 = g4.add_input("x", np.float32, [b, s, nq * d])
    g4.add_output(
        g4.add_node("RMSNormalization",
                    [xi4, g4.add_initializer("sc", gamma)],
                    epsilon=1e-6), np.float32, None)
    m4 = import_model(g4.to_bytes())
    got4 = np.asarray(m4.apply(m4.params, q3)[0])
    want4 = q3 / np.sqrt((q3 ** 2).mean(-1, keepdims=True) + 1e-6) * gamma
    np.testing.assert_allclose(got4, want4, atol=1e-5)


def test_multi_head_attention_matches_torch():
    """com.microsoft MultiHeadAttention (post-projection fusion):
    cross-attention with a combined QKV bias and [B] key lengths, plus
    the causal self-attention + KV-cache decode step — against torch
    SDPA references."""
    rng = np.random.default_rng(4)
    b, n, s, t, d = 2, 3, 4, 6, 8
    h = n * d
    q = rng.normal(size=(b, s, h)).astype(np.float32)
    k = rng.normal(size=(b, t, h)).astype(np.float32)
    v = rng.normal(size=(b, t, h)).astype(np.float32)
    bias = rng.normal(size=(3 * h,)).astype(np.float32)
    lens = np.array([6, 3], np.int32)

    g = GraphBuilder(opset=17)
    qi = g.add_input("q", np.float32, [b, s, h])
    ki = g.add_input("k", np.float32, [b, t, h])
    vi = g.add_input("v", np.float32, [b, t, h])
    bi = g.add_initializer("b", bias)
    mi = g.add_input("m", np.int32, [b])
    att = g.add_node("MultiHeadAttention", [qi, ki, vi, bi, mi],
                     domain="com.microsoft", num_heads=n)
    g.add_output(att, np.float32, None)
    m = import_model(g.to_bytes())
    got = np.asarray(m.apply(m.params, q, k, v, lens)[0])

    def hd(x_, sl):
        return torch.tensor(x_).reshape(b, -1, n, d).permute(0, 2, 1, 3)

    bq, bk, bv = np.split(bias, 3)
    ok = torch.arange(t)[None, :] < torch.tensor(lens)[:, None]
    # ORT adds a finite mask floor (-1e4), not -inf
    addm = torch.where(ok, 0.0, -10000.0)[:, None, None, :]
    want = torch.nn.functional.scaled_dot_product_attention(
        hd(q + bq, s), hd(k + bk, t), hd(v + bv, t), attn_mask=addm) \
        .permute(0, 2, 1, 3).reshape(b, s, h).numpy()
    np.testing.assert_allclose(got, want, atol=1e-4)

    # causal decode step with a KV cache + present outputs
    p = 5
    q1 = rng.normal(size=(b, 1, h)).astype(np.float32)
    pk = rng.normal(size=(b, n, p, d)).astype(np.float32)
    pv = rng.normal(size=(b, n, p, d)).astype(np.float32)
    g2 = GraphBuilder(opset=17)
    qi2 = g2.add_input("q", np.float32, [b, 1, h])
    pki = g2.add_input("pk", np.float32, list(pk.shape))
    pvi = g2.add_input("pv", np.float32, list(pv.shape))
    o2, prk, prv = g2.add_node(
        "MultiHeadAttention",
        [qi2, qi2, qi2, "", "", "", pki, pvi],
        outputs=["o2", "prk", "prv"], domain="com.microsoft",
        num_heads=n, unidirectional=1)
    for nm in (o2, prk, prv):
        g2.add_output(nm, np.float32, None)
    m2 = import_model(g2.to_bytes())
    got2, gk, gv = [np.asarray(o) for o in m2.apply(m2.params, q1, pk, pv)]
    kc = torch.cat([torch.tensor(pk), hd(q1, 1)], dim=2)
    vc = torch.cat([torch.tensor(pv), hd(q1, 1)], dim=2)
    want2 = torch.nn.functional.scaled_dot_product_attention(
        hd(q1, 1), kc, vc).permute(0, 2, 1, 3).reshape(b, 1, h).numpy()
    np.testing.assert_allclose(got2, want2, atol=1e-4)
    assert gk.shape == (b, n, p + 1, d)
    np.testing.assert_allclose(gk[:, :, :p], pk, atol=1e-6)
