"""HTTP-on-tables layer tests (mirrors the reference's HTTPTransformer /
SimpleHTTPTransformer suites, ref: core/src/test/scala/.../io/split1/).

A stdlib mock server stands in for external services; the reference's
suites likewise start local servers and fire real HTTP
(SURVEY.md §4.4 — no mock/fake backend layer, real sockets).
"""
import http.server
import json
import threading

import numpy as np
import pytest

from synapseml_tpu.data.table import Table
from synapseml_tpu.io import (HTTPRequestData, HTTPTransformer,
                              JSONOutputParser, SimpleHTTPTransformer,
                              StringOutputParser)
from synapseml_tpu.io.http import HandlingUtils, SingleThreadedHTTPClient
from synapseml_tpu.io.serving import find_open_port


class _MockService(http.server.BaseHTTPRequestHandler):
    """Echo-uppercase service; /flaky fails twice then succeeds; /fail 500s."""
    protocol_version = "HTTP/1.1"
    flaky_counts = {}

    def log_message(self, *a):
        pass

    def do_POST(self):
        length = int(self.headers.get("Content-Length", 0))
        body = self.rfile.read(length)
        if self.path == "/fail":
            self._send(500, b'{"error": "boom"}')
            return
        if self.path.startswith("/flaky"):
            n = _MockService.flaky_counts.get(self.path, 0)
            _MockService.flaky_counts[self.path] = n + 1
            if n < 2:
                self._send(429, b"slow down")
                return
        data = json.loads(body)
        out = json.dumps({"echo": str(data.get("text", "")).upper()})
        self._send(200, out.encode())

    def _send(self, code, body):
        self.send_response(code)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)


@pytest.fixture(scope="module")
def mock_url():
    port = find_open_port(23400)
    httpd = http.server.ThreadingHTTPServer(("127.0.0.1", port), _MockService)
    httpd.daemon_threads = True
    t = threading.Thread(target=httpd.serve_forever, daemon=True)
    t.start()
    yield f"http://127.0.0.1:{port}"
    httpd.shutdown()
    httpd.server_close()


def _req(url, obj):
    return HTTPRequestData(url=url, method="POST",
                           headers={"Content-Type": "application/json"},
                           entity=json.dumps(obj).encode())


def test_http_transformer_ordered_concurrent(mock_url):
    n = 16
    reqs = np.empty(n, dtype=object)
    for i in range(n):
        reqs[i] = _req(mock_url, {"text": f"row{i}"})
    t = HTTPTransformer(input_col="req", output_col="resp",
                        concurrency=8).transform(Table({"req": reqs}))
    for i, r in enumerate(t["resp"]):
        assert r.status_code == 200
        assert r.json()["echo"] == f"ROW{i}"  # order preserved


def test_retry_ladder_recovers_from_429(mock_url):
    client = SingleThreadedHTTPClient(
        HandlingUtils.advanced(10, 10, 10), timeout=10)
    resp = client.send(_req(mock_url + "/flaky1", {"text": "x"}))
    assert resp.status_code == 200
    assert resp.json()["echo"] == "X"


def test_retry_gives_up_and_reports(mock_url):
    client = SingleThreadedHTTPClient(HandlingUtils.advanced(5), timeout=10)
    resp = client.send(_req(mock_url + "/fail", {"text": "x"}))
    assert resp.status_code == 500


def test_simple_http_transformer_with_error_col(mock_url):
    vals = np.empty(3, dtype=object)
    vals[:] = [{"text": "a"}, {"text": "b"}, {"text": "c"}]
    t = Table({"value": vals})
    st = SimpleHTTPTransformer(url=mock_url, input_col="value",
                               output_col="out", backoffs=())
    out = st.transform(t)
    assert [v["echo"] for v in out["out"]] == ["A", "B", "C"]
    assert all(e is None for e in out["errors"])

    st_fail = SimpleHTTPTransformer(url=mock_url + "/fail",
                                    input_col="value", output_col="out",
                                    backoffs=())
    out = st_fail.transform(t)
    assert all(v is None for v in out["out"])
    assert all(e["status_code"] == 500 for e in out["errors"])


def test_output_parsers(mock_url):
    reqs = np.empty(1, dtype=object)
    reqs[0] = _req(mock_url, {"text": "zz"})
    t = HTTPTransformer(input_col="req", output_col="resp").transform(
        Table({"req": reqs}))
    s = StringOutputParser(input_col="resp", output_col="s").transform(t)
    assert json.loads(s["s"][0])["echo"] == "ZZ"
    j = JSONOutputParser(input_col="resp", output_col="j").transform(t)
    assert j["j"][0]["echo"] == "ZZ"
    jp = JSONOutputParser(input_col="resp", output_col="j",
                          post_process=lambda d: d["echo"]).transform(t)
    assert jp["j"][0] == "ZZ"


def test_serde_roundtrip(tmp_path, mock_url):
    from synapseml_tpu.core.pipeline import PipelineStage

    st = SimpleHTTPTransformer(url=mock_url, input_col="value",
                               output_col="out", concurrency=3)
    p = str(tmp_path / "stage")
    st.save(p)
    st2 = PipelineStage.load(p)
    assert st2.url == mock_url
    assert st2.concurrency == 3
    vals = np.empty(1, dtype=object)
    vals[0] = {"text": "q"}
    out = st2.transform(Table({"value": vals}))
    assert out["out"][0]["echo"] == "Q"


def test_binary_file_reader(tmp_path):
    """Zip traversal + subsampling (ref: BinaryFileFormat.scala)."""
    import zipfile

    from synapseml_tpu.io.binary import read_binary_files

    (tmp_path / "a.bin").write_bytes(b"alpha")
    sub = tmp_path / "sub"
    sub.mkdir()
    (sub / "b.bin").write_bytes(b"beta")
    with zipfile.ZipFile(tmp_path / "c.zip", "w") as zf:
        zf.writestr("inner/x.txt", b"xx")
        zf.writestr("y.txt", b"yyy")

    t = read_binary_files(str(tmp_path))
    by_path = {p: b for p, b in zip(t["path"], t["bytes"])}
    assert by_path[str(tmp_path / "a.bin")] == b"alpha"
    assert by_path[str(sub / "b.bin")] == b"beta"
    assert by_path[str(tmp_path / "c.zip") + "/inner/x.txt"] == b"xx"
    assert by_path[str(tmp_path / "c.zip") + "/y.txt"] == b"yyy"
    assert int(t["length"][list(t["path"]).index(str(tmp_path / "a.bin"))]) == 5

    # non-recursive + pattern
    t2 = read_binary_files(str(tmp_path), recursive=False, pattern="*.bin")
    assert list(t2["path"]) == [str(tmp_path / "a.bin")]

    # subsampling is seeded and roughly proportional
    many = tmp_path / "many"
    many.mkdir()
    for i in range(200):
        (many / f"f{i:03d}.dat").write_bytes(bytes([i % 256]))
    t3 = read_binary_files(str(many), sample_ratio=0.25, seed=1)
    assert 20 <= t3.num_rows <= 80
    t4 = read_binary_files(str(many), sample_ratio=0.25, seed=1)
    assert list(t3["path"]) == list(t4["path"])


# ---------------------------------------------------------------------------
# PowerBI writer (round-2 weak #7: was the one untested component)
# ---------------------------------------------------------------------------

def _powerbi_mock():
    import http.server
    import threading

    class Handler(http.server.BaseHTTPRequestHandler):
        protocol_version = "HTTP/1.1"
        batches = []
        fail_next = [0]

        def log_message(self, *a):
            pass

        def do_POST(self):
            body = self.rfile.read(
                int(self.headers.get("Content-Length", 0)))
            if Handler.fail_next[0] > 0:
                Handler.fail_next[0] -= 1
                self.send_response(503)
                self.send_header("Content-Length", "0")
                self.end_headers()
                return
            Handler.batches.append(json.loads(body))
            self.send_response(200)
            self.send_header("Content-Length", "0")
            self.end_headers()

    httpd = http.server.ThreadingHTTPServer(("127.0.0.1", 0), Handler)
    httpd.daemon_threads = True
    threading.Thread(target=httpd.serve_forever, daemon=True).start()
    return httpd, Handler


def test_powerbi_writer_batches_and_serializes_numpy():
    from synapseml_tpu.io.powerbi import write_to_powerbi

    httpd, handler = _powerbi_mock()
    try:
        t = Table({"name": np.array(["a", "b", "c"], dtype=object),
                   "score": np.array([1.5, 2.5, np.nan], np.float64),
                   "count": np.arange(3, dtype=np.int64),
                   "flag": np.array([True, False, True])})
        statuses = write_to_powerbi(
            t, f"http://127.0.0.1:{httpd.server_address[1]}/push",
            batch_size=2)
        assert statuses == [200, 200]
        assert [len(b) for b in handler.batches] == [2, 1]
        row0 = handler.batches[0][0]
        # numpy scalars serialized as plain JSON types by _json_default
        assert row0 == {"name": "a", "score": 1.5, "count": 0,
                        "flag": True}
        assert handler.batches[1][0]["score"] is None or \
            handler.batches[1][0]["score"] != handler.batches[1][0]["score"]
    finally:
        httpd.shutdown()


def test_powerbi_writer_retries_then_raises():
    from synapseml_tpu.io.powerbi import write_to_powerbi

    httpd, handler = _powerbi_mock()
    try:
        t = Table({"x": np.arange(2, dtype=np.int64)})
        url = f"http://127.0.0.1:{httpd.server_address[1]}/push"
        # one 503 is absorbed by the retry ladder
        handler.fail_next[0] = 1
        assert write_to_powerbi(t, url, backoffs_ms=(10, 20)) == [200]
        # more failures than backoffs surface as an error
        handler.fail_next[0] = 10
        with pytest.raises(RuntimeError, match="PowerBI POST failed"):
            write_to_powerbi(t, url, backoffs_ms=(10,))
    finally:
        httpd.shutdown()
