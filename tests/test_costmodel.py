"""Roofline cost observatory (runtime/costmodel.py,
tools/perf_report.py; docs/perf.md "Roofline methodology",
docs/observability.md "Roofline cost observatory"): cost-table capture
at warmup on the forced-8-device platform, the pure roofline math
against hand-computed fixtures, bound-classification edge cases,
/debug/cost live + gated, gauge registration lifecycle, and the
perf_report CLI contract.

Discipline matches tests/test_perfwatch.py: the cost table is
process-global, so every test scopes its entries with a unique
``tag_scope`` and the autouse fixture resets the table — this file
runs inside tools/ci/smoke_pipeline.sh's wall clock.
"""
import json
import os
import sys
import urllib.error
import urllib.request

import numpy as np
import pytest

import jax

from synapseml_tpu.io.serving import WorkerServer
from synapseml_tpu.runtime import blackbox as bb
from synapseml_tpu.runtime import costmodel as cm
from synapseml_tpu.runtime import telemetry as tm
from synapseml_tpu.runtime.executor import BatchedExecutor

HARD = 30.0
ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _fresh_table():
    """The cost table is process-global; each test starts empty and
    leaves nothing registered (other suites' scrapes must not see this
    file's synthetic signatures)."""
    cm.reset()
    yield
    cm.reset()


def _get(url, timeout=HARD):
    try:
        with urllib.request.urlopen(
                urllib.request.Request(url), timeout=timeout) as r:
            return r.status, r.read()
    except urllib.error.HTTPError as e:
        return e.code, e.read()


# -- capture ----------------------------------------------------------------

def test_warmup_captures_cost_entries():
    with cm.tag_scope("t_capture"):
        ex = BatchedExecutor(lambda x: (x @ x.T,), min_bucket=8)
        rep = ex.warmup([((16,), np.float32)], buckets=[8, 16])
    assert rep.compiled == 2
    assert all(e.get("cost_captured") for e in rep.entries)
    mine = [e for e in cm.entries() if e["tag"] == "t_capture"]
    assert {e["bucket"] for e in mine} == {8, 16}
    for e in mine:
        # 2*N*N*16 madd flops for (N,16)@(16,N): the ledger is XLA's,
        # so only sanity-bound it — positive and scaling with N^2
        assert e["flops"] > 0 and e["bytes_accessed"] > 0
        assert e["arity"] == 1 and e["layout"] == "single"
        assert e["device_kind"] == "cpu"
        assert e["captured"] is True
        assert e["bound"] in ("compute", "memory")
        assert e["attainable_flops_per_sec"] > 0
    by_bucket = {e["bucket"]: e for e in mine}
    assert by_bucket[16]["flops"] > by_bucket[8]["flops"]


def test_warmup_capture_multidevice_shard_layout():
    # 8 virtual devices (conftest): a dp-shardable bucket compiles once
    # against the mesh and its cost entry carries the shard layout
    devs = jax.local_devices()
    assert len(devs) == 8, "forced-8-device platform required"
    with cm.tag_scope("t_shard"):
        ex = BatchedExecutor(lambda x: (x * 2.0,), min_bucket=8,
                             devices="all")
        ex.warmup([((4,), np.float32)], buckets=[16])
    mine = [e for e in cm.entries() if e["tag"] == "t_shard"]
    assert len(mine) == 1 and mine[0]["layout"] == "shard"


def test_record_dedupes_by_signature():
    with cm.tag_scope("t_dedupe"):
        ex = BatchedExecutor(lambda x: (x + 1.0,), min_bucket=8)
        ex.warmup([((4,), np.float32)], buckets=[8])
        before = [e for e in cm.entries() if e["tag"] == "t_dedupe"]
        ex.warmup([((4,), np.float32)], buckets=[8])  # warm -> no-op
        after = [e for e in cm.entries() if e["tag"] == "t_dedupe"]
    assert len(before) == len(after) == 1


def test_record_tolerates_broken_cost_analysis():
    class Refuses:
        def cost_analysis(self):
            raise RuntimeError("deserialized executable")

        def memory_analysis(self):
            raise RuntimeError("nope")

    e = cm.record(Refuses(), bucket=8, arity=1, layout="single",
                  device_kind="cpu", sig="s1", tag="t_broken")
    assert e is not None and e["captured"] is False
    assert e["bound"] == "unknown"
    assert e["flops"] == 0.0 and e["bytes_accessed"] == 0.0


def test_record_tolerates_missing_cost_keys():
    class Empty:
        def cost_analysis(self):
            return [{}]  # jax's list-of-dicts shape, no keys

        def memory_analysis(self):
            return object()  # no *_size_in_bytes attrs

    e = cm.record(Empty(), bucket=8, arity=1, layout="single",
                  device_kind="cpu", sig="s2", tag="t_missing")
    assert e is not None and e["captured"] is False
    assert e["bound"] == "unknown"


# -- pure roofline math -----------------------------------------------------

def test_roofline_math_hand_fixture():
    # flops=100, bytes=10 -> AI 10; peak (100 F/s, 5 B/s) -> ridge 20:
    # AI below the ridge is memory-bound, attainable = 10*5 = 50
    assert cm.arithmetic_intensity(100, 10) == 10.0
    assert cm.classify_bound(100, 10, 100, 5) == "memory"
    assert cm.attainable_flops(100, 10, 100, 5) == 50.0
    # AI 40 >= ridge 20 -> compute-bound, attainable clamps at peak
    assert cm.classify_bound(400, 10, 100, 5) == "compute"
    assert cm.attainable_flops(400, 10, 100, 5) == 100.0


def test_bound_classification_edge_cases():
    # pure flops (zero bytes) -> compute; pure movement -> memory;
    # neither -> unknown; broken peak -> unknown — never an exception
    assert cm.classify_bound(10, 0, 100, 5) == "compute"
    assert cm.classify_bound(0, 10, 100, 5) == "memory"
    assert cm.classify_bound(0, 0, 100, 5) == "unknown"
    assert cm.classify_bound(10, 10, 0, 5) == "unknown"
    assert cm.arithmetic_intensity(0, 10) == 0.0
    assert cm.arithmetic_intensity(10, 0) == 0.0
    # no byte ledger: the flat compute roof is all we know
    assert cm.attainable_flops(10, 0, 100, 5) == 100.0
    assert cm.attainable_flops(0, 0, 0, 0) == 0.0


def test_parse_cost_analysis_shapes():
    good = [{"flops": 8.0, "bytes accessed": 4.0, "transcendentals": 1.0,
             "bytes accessedout{}": 2.0}]
    got = cm.parse_cost_analysis(good)
    assert got == {"flops": 8.0, "bytes_accessed": 4.0,
                   "transcendentals": 1.0, "output_bytes": 2.0}
    # dict (newer jax), junk values, junk shapes: zeros, no raise
    assert cm.parse_cost_analysis({"flops": 8.0})["flops"] == 8.0
    assert cm.parse_cost_analysis({"flops": "x"})["flops"] == 0.0
    assert cm.parse_cost_analysis(None)["flops"] == 0.0
    assert cm.parse_cost_analysis(["junk", 3])["flops"] == 0.0


def test_peak_table_and_env_overrides(monkeypatch):
    monkeypatch.delenv("SYNAPSEML_PEAK_FLOPS", raising=False)
    monkeypatch.delenv("SYNAPSEML_PEAK_BW", raising=False)
    v5e = cm.peak_for("TPU v5 lite")
    assert v5e["flops_per_sec"] == 197e12 and v5e["source"] == "table"
    assert cm.peak_for("TPU v4")["flops_per_sec"] == 275e12
    assert cm.peak_for("never-heard-of-it")["source"] == "default"
    monkeypatch.setenv("SYNAPSEML_PEAK_FLOPS", "123e9")
    got = cm.peak_for("TPU v5 lite")
    assert got["flops_per_sec"] == 123e9 and got["source"] == "env"
    assert got["bytes_per_sec"] == 8.19e11  # only the set axis moves
    monkeypatch.setenv("SYNAPSEML_PEAK_FLOPS", "garbage")
    assert cm.peak_for("TPU v5 lite")["source"] == "table"  # ignored


# -- achieved attribution ---------------------------------------------------

def test_achieved_attribution_pure_window_math():
    table = [{"signature": "sA", "bucket": 8, "flops": 100.0,
              "bytes_accessed": 50.0, "device_kind": "cpu",
              "attainable_flops_per_sec": 1000.0},
             {"signature": "sB", "bucket": 8, "flops": 300.0,
              "bytes_accessed": 50.0, "device_kind": "cpu",
              "attainable_flops_per_sec": 1000.0}]
    prev = {"t": 0.0, "counts": {"8": 0.0}}
    cur = {"t": 2.0, "counts": {"8": 8.0}}  # 8 dispatches over 2s
    out = cm._attribute(prev, cur, table)
    # bucket-proportional even split: 4 dispatches each over 2s = 2/s
    a = out["per_entry"]["sA"]
    assert a["dispatch_rate_per_sec"] == 2.0
    assert a["achieved_flops_per_sec"] == 200.0
    assert a["achieved_fraction"] == 0.2
    # per-kind sums both entries: 200 + 600 = 800 F/s
    assert out["per_kind"]["cpu"]["achieved_flops_per_sec"] == 800.0
    assert out["window_seconds"] == 2.0


def test_achieved_moves_with_real_dispatches():
    with cm.tag_scope("t_ach"):
        ex = BatchedExecutor(lambda x: (x @ x.T,), min_bucket=8)
        ex.warmup([((16,), np.float32)], buckets=[8])
        cm.achieved(force=True)  # pin the window start
        ex(np.ones((8, 16), np.float32))
        got = cm.achieved(force=True)
    assert got.get("cpu", {}).get("achieved_flops_per_sec", 0.0) > 0


# -- read surfaces ----------------------------------------------------------

def test_gauges_register_on_warmup_and_unregister_on_reset():
    with cm.tag_scope("t_gauges"):
        ex = BatchedExecutor(lambda x: (x * 3.0,), min_bucket=8)
        ex.warmup([((4,), np.float32)], buckets=[8])
    text = tm.prometheus_text()
    assert "synapseml_executor_signature_flops{signature=\"t_gauges/" \
        in text
    assert "synapseml_executor_signature_bytes{signature=\"t_gauges/" \
        in text
    assert 'synapseml_executor_achieved_flops_per_sec{device="cpu"}' \
        in text
    assert 'synapseml_executor_roofline_fraction{device="cpu"}' in text
    dropped = cm.reset()
    assert dropped >= 1
    text = tm.prometheus_text()
    assert "executor_signature_flops" not in text
    assert "executor_roofline_fraction" not in text


def test_snapshot_shape_and_flight_recorder_fold():
    with cm.tag_scope("t_snap"):
        ex = BatchedExecutor(lambda x: (x + 1.0,), min_bucket=8)
        ex.warmup([((4,), np.float32)], buckets=[8])
    snap = cm.snapshot(force=True)
    assert snap["attribution"] == "bucket-proportional"
    assert "cpu" in snap["peaks"]
    mine = [e for e in snap["entries"] if e["tag"] == "t_snap"]
    assert len(mine) == 1
    assert {"achieved_fraction", "dispatch_rate_per_sec",
            "bound"} <= set(mine[0])
    # flight-recorder dumps carry the table (docs/observability.md)
    flight = bb.snapshot(stacks=False)
    assert "cost" in flight and "entries" in flight["cost"]


def test_debug_cost_endpoint_live_and_gated(monkeypatch):
    with cm.tag_scope("t_endpoint"):
        ex = BatchedExecutor(lambda x: (x * 2.0,), min_bucket=8)
        ex.warmup([((4,), np.float32)], buckets=[8])
    srv = WorkerServer("cost_dbg")
    try:
        base = f"http://{srv.host}:{srv.port}"
        st, body = _get(f"{base}/debug/cost")
        assert st == 200
        snap = json.loads(body)
        assert any(e["tag"] == "t_endpoint" for e in snap["entries"])
        assert {"peaks", "attribution", "per_kind"} <= set(snap)
        monkeypatch.setenv("SYNAPSEML_DEBUG_ENDPOINTS", "0")
        st, _body = _get(f"{base}/debug/cost")
        assert st == 403
    finally:
        srv.stop()


def test_overflow_cap_never_grows_unbounded(monkeypatch):
    monkeypatch.setattr(cm, "MAX_ENTRIES", 2)

    class Fake:
        def cost_analysis(self):
            return [{"flops": 1.0, "bytes accessed": 1.0}]

        def memory_analysis(self):
            raise RuntimeError

    for i in range(4):
        cm.record(Fake(), bucket=8, arity=1, layout="single",
                  device_kind="cpu", sig=f"s{i}", tag="t_cap")
    snap = cm.snapshot(force=True)
    assert len(snap["entries"]) == 2
    assert snap["overflow_dropped"] == 2


# -- perf_report CLI --------------------------------------------------------

def _payload(with_cost=True, group_kind="device"):
    cost = {"entries": [], "peaks": {}, "attribution":
            "bucket-proportional"}
    if with_cost:
        cost["entries"] = [{
            "signature": "g/b8-a1-single-abc123", "tag": "g",
            "bucket": 8, "arity": 1, "layout": "single",
            "device_kind": "cpu", "captured": True, "flops": 800.0,
            "bytes_accessed": 80.0, "transcendentals": 0.0,
            "argument_bytes": 32.0, "output_bytes": 32.0,
            "temp_bytes": 0.0, "arithmetic_intensity": 10.0,
            "bound": "memory", "attainable_flops_per_sec": 1e6,
            "achieved_fraction": 0.0, "dispatch_rate_per_sec": 0.0,
            "achieved_flops_per_sec": 0.0}]
        cost["peaks"] = {"cpu": {"flops_per_sec": 1e11,
                                 "bytes_per_sec": 5e10,
                                 "source": "default"}}
    return {
        "metric": "g_rows_per_sec", "value": 100.0, "unit": "rows/sec",
        "group": "g", "secondary": [],
        "detail": {"cost": cost,
                   "bench_groups": {"g": {"kind": group_kind,
                                          "description": "test group"}}},
    }


def _run_report(tmp_path, payload, *extra):
    from tools import perf_report

    src = tmp_path / "bench.json"
    src.write_text(json.dumps(payload))
    out = tmp_path / "report.md"
    rc = perf_report.main([str(src), "--out", str(out), *extra])
    return rc, out


def test_perf_report_exit_0_and_report_content(tmp_path):
    rc, out = _run_report(tmp_path, _payload(), "--check")
    assert rc == 0
    md = out.read_text()
    assert "# Bench bottleneck report" in md
    assert "| 1 | g | memory |" in md
    # achieved = 100 rows/s * 100 flops/row = 1e4; frac = 1e4/1e6
    assert "1.00%" in md
    assert "g/b8-a1-single-abc123" in md


def test_perf_report_exit_2_on_unattributed_group(tmp_path):
    rc, out = _run_report(tmp_path, _payload(with_cost=False))
    assert rc == 2
    assert "UNATTRIBUTED" in out.read_text()


def test_perf_report_host_group_needs_no_signature(tmp_path):
    rc, _out = _run_report(
        tmp_path, _payload(with_cost=False, group_kind="host"),
        "--check")
    assert rc == 0


def test_perf_report_exit_1_on_usage():
    from tools import perf_report

    assert perf_report.main(["/nonexistent/bench.json"]) == 1
    with pytest.raises(SystemExit) as exc:
        perf_report.main([])  # missing positional -> usage error
    assert exc.value.code == 1


def test_perf_report_exit_1_on_non_bench_payload(tmp_path):
    src = tmp_path / "junk.json"
    src.write_text(json.dumps({"not": "a bench payload"}))
    from tools import perf_report

    assert perf_report.main([str(src)]) == 1


def test_bench_list_prints_descriptions_and_metrics(capsys):
    sys.path.insert(0, ROOT)
    try:
        import bench
    finally:
        sys.path.remove(ROOT)
    assert bench.main(["--list"]) == 0
    out = capsys.readouterr().out
    assert "serving_roundtrip_p50_ms" in out  # measured metric names
    assert "echo round trip" in out           # one-line description
    assert "[host]" in out and "[device]" in out
