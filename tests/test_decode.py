"""Decode serving tests (round 19): the paged KV cache allocator, the
continuous-batching scheduler end to end on the ``tiny_decoder`` zoo
graph, and the ``/generate`` handler wired into a real ``WorkerServer``.

The expensive pieces (scheduler warmups) are module-scoped fixtures:
one big-capacity scheduler shared by the e2e / determinism / serving
tests, one 4-page scheduler shared by the eviction-recompute and
kv_capacity tests.
"""
import hashlib
import http.client
import json
import re

import numpy as np
import pytest

from synapseml_tpu.onnx import zoo
from synapseml_tpu.onnx.importer import import_model
from synapseml_tpu.runtime import kvcache
from synapseml_tpu.runtime import telemetry as _tm
from synapseml_tpu.runtime.decode import DecodeScheduler
from synapseml_tpu.runtime.kvcache import PagedKVCache


# -- PagedKVCache unit tests (no device work) ---------------------------

def _cache(pages=4, page_size=8, bpt=16, name="t_kvunit"):
    return PagedKVCache(page_size, bpt, capacity_bytes=pages * page_size * bpt,
                        name=name)


def test_kv_pages_for_ceil_div():
    kv = _cache(name="t_kv_pages")
    assert kv.pages_for(1) == 1
    assert kv.pages_for(8) == 1
    assert kv.pages_for(9) == 2
    assert kv.pages_for(0) == 1  # a sequence always holds >= 1 page


def test_kv_validation():
    with pytest.raises(ValueError):
        PagedKVCache(0, 16)
    with pytest.raises(ValueError):
        PagedKVCache(8, 0)


def test_kv_acquire_release_accounting():
    kv = _cache(name="t_kv_acct")
    assert kv.capacity_pages == 4
    assert kv.acquire("a", 8) == []          # 1 page
    assert kv.acquire("b", 17) == []         # 3 pages
    assert kv.pages_in_use() == 4
    assert kv.resident("a") and kv.resident("b")
    assert not kv.fits(1)                    # full
    kv.release("b")
    assert kv.pages_in_use() == 1
    assert kv.fits(24) and not kv.fits(25)


def test_kv_grow_in_place_excludes_held_pages():
    kv = _cache(name="t_kv_grow")
    kv.acquire("a", 8)
    kv.acquire("b", 8)
    # growing a from 1 -> 3 pages fits (2 free) without evicting b
    assert kv.acquire("a", 24) == []
    assert kv.pages_in_use() == 4
    assert kv.resident("b")


def test_kv_acquire_evicts_lru_order():
    kv = _cache(name="t_kv_lru")
    kv.acquire("a", 8)
    kv.acquire("b", 8)
    kv.acquire("c", 8)
    kv.touch("a")  # b is now least-recently-used
    evicted = kv.acquire("d", 17)  # needs 3 pages, 1 free -> evict 2
    assert evicted == ["b", "c"]
    assert kv.resident("a") and kv.resident("d")
    assert not kv.resident("b") and not kv.resident("c")


def test_kv_acquire_impossible_returns_none():
    kv = _cache(name="t_kv_toolarge")
    # more pages than the whole cache: never admissible
    assert kv.acquire("a", 4 * 8 + 1) is None
    # growth past capacity is equally refused, holder intact
    kv.acquire("a", 8)
    assert kv.acquire("a", 4 * 8 + 1) is None
    assert kv.resident("a")


def test_kv_evict_lru_exclude():
    kv = _cache(name="t_kv_excl")
    kv.acquire("a", 8)
    kv.acquire("b", 8)
    assert kv.evict_lru(exclude="a") == "b"
    assert kv.evict_lru(exclude="a") is None  # only a left
    assert kv.resident("a")


def test_kv_capacity_bytes_env(monkeypatch):
    monkeypatch.setenv("SYNAPSEML_KV_CAPACITY_BYTES", "123456")
    assert kvcache.kv_capacity_bytes() == 123456
    # empty string is "unset", falls through to the HBM-fraction path
    monkeypatch.setenv("SYNAPSEML_KV_CAPACITY_BYTES", "")
    assert kvcache.kv_capacity_bytes() > 0


# -- scheduler fixtures -------------------------------------------------

@pytest.fixture(scope="module")
def sched():
    """Big-capacity warmed scheduler: no evictions, pure scheduling."""
    g = import_model(zoo.tiny_decoder())
    s = DecodeScheduler(g, name="t_dec", max_batch=4, prefill_chunk=8,
                        page_size=8, max_seq=64, capacity_bytes=10 ** 9)
    s.warmup()
    s.start()
    yield s
    s.close()


@pytest.fixture(scope="module")
def tiny_sched():
    """4-page scheduler: concurrent sequences MUST evict each other."""
    g = import_model(zoo.tiny_decoder())
    s = DecodeScheduler(g, name="t_dec_tiny", max_batch=4, prefill_chunk=8,
                        page_size=8, max_seq=64, capacity_bytes=1)
    # rebuild the cache at exactly 4 pages of the scheduler's own
    # bytes-per-token so the test geometry is independent of the zoo
    # graph's layer/head counts
    bpt = s.kv.bytes_per_token
    s.kv = PagedKVCache(8, bpt, capacity_bytes=4 * 8 * bpt,
                        name="t_dec_tiny_kv")
    s.warmup()
    s.start()
    yield s
    s.close()


def _prompts(lens, seed=0):
    rng = np.random.default_rng(seed)
    return [list(rng.integers(1, 50, size=n)) for n in lens]


def _recompiles():
    text = _tm.prometheus_text()
    return sum(int(v) for v in
               re.findall(r'executor_recompiles_total\{[^}]*\} (\d+)', text))


# -- scheduler end to end -----------------------------------------------

def test_mixed_prompts_complete_with_zero_recompiles(sched):
    before = _recompiles()
    handles = [sched.submit(p, max_new_tokens=12)
               for p in _prompts((3, 11, 20, 5, 17, 9))]
    results = [h.result(timeout=120) for h in handles]
    assert all(reason == "completed" for _, reason in results)
    assert all(len(toks) == 12 for toks, _ in results)
    # every (phase, T) signature was warmed: the steady-state loop must
    # never lazily compile (the PR-10 sentinel)
    assert _recompiles() == before


def test_repeat_submission_is_deterministic(sched):
    prompt = _prompts((13,), seed=7)[0]
    a, ra = sched.submit(prompt, max_new_tokens=10).result(timeout=120)
    b, rb = sched.submit(prompt, max_new_tokens=10).result(timeout=120)
    assert (a, ra) == (b, rb)


def test_streaming_iteration_matches_result(sched):
    prompt = _prompts((9,), seed=3)[0]
    ref, _ = sched.submit(prompt, max_new_tokens=8).result(timeout=120)
    h = sched.submit(prompt, max_new_tokens=8)
    streamed = list(h)
    assert streamed == ref
    assert h.finish_reason == "completed"


def test_deadline_expiry_is_partial_not_error(sched):
    h = sched.submit([1, 2, 3], max_new_tokens=50, deadline_s=1e-6)
    toks, reason = h.result(timeout=120)
    assert reason == "deadline"
    assert len(toks) < 50


def test_submit_validation(sched):
    with pytest.raises(ValueError):
        sched.submit([], max_new_tokens=4)
    with pytest.raises(ValueError):
        sched.submit([1] * 60, max_new_tokens=10)  # 70 > max_seq=64
    with pytest.raises(ValueError):
        sched.submit([1, 2], max_new_tokens=0)


def test_admission_queue_full_raises(sched):
    # shrink the waiting-room bound briefly; scheduler reads it per submit
    old = sched.max_waiting
    sched.max_waiting = 0
    try:
        with pytest.raises(RuntimeError):
            sched.submit([1, 2, 3], max_new_tokens=4)
    finally:
        sched.max_waiting = old


def test_stats_shape(sched):
    st = sched.stats()
    assert st["warmed"] is True
    assert st["capacity_pages"] >= 1
    assert "waiting" in st and "active" in st and "t_bucket" in st


# -- eviction / recompute bit-identity ----------------------------------

def test_eviction_recompute_is_bit_identical(tiny_sched):
    # each sequence fits alone (<= 32 tokens = 4 pages) but the three
    # together need 10 pages: concurrency forces evict-then-recompute
    prompts = _prompts((6, 10, 14), seed=1)
    ref = [tiny_sched.submit(p, max_new_tokens=12).result(timeout=120)[0]
           for p in prompts]  # solo: no concurrent evictor

    handles = [tiny_sched.submit(p, max_new_tokens=12) for p in prompts]
    got = [h.result(timeout=240)[0] for h in handles]
    assert got == ref  # recompute restored the exact prefix state

    text = _tm.prometheus_text()
    ev = re.findall(
        r'kv_evictions_total\{cache="t_dec_tiny_kv"[^}]*\} (\d+)', text)
    rec = re.findall(
        r'kv_recomputes_total\{cache="t_dec_tiny_kv"\} (\d+)', text)
    assert sum(int(x) for x in ev) >= 1
    assert sum(int(x) for x in rec) >= 1


def test_unfittable_prompt_finishes_kv_capacity(tiny_sched):
    # 40 tokens need 6 pages against a 4-page cache: admissible by the
    # compile geometry (40 + 8 <= max_seq) but never by capacity — the
    # scheduler must retire it with reason kv_capacity, not hang
    toks, reason = tiny_sched.submit(
        list(range(1, 41)), max_new_tokens=8).result(timeout=120)
    assert reason == "kv_capacity"
    assert toks == []


# -- /generate over HTTP ------------------------------------------------

@pytest.fixture(scope="module")
def decode_server(sched):
    from synapseml_tpu.io.serving import ContinuousServer

    def _noop(table):
        return table

    cs = ContinuousServer("t_dec_http", _noop, port=0, ready=False)
    cs.server.decode = sched
    cs.server.set_ready(True)
    yield cs
    cs.stop()


def _generate(cs, payload, headers=()):
    host, port = cs.url.split("//")[1].rstrip("/").split(":")
    c = http.client.HTTPConnection(host, int(port), timeout=60)
    hdrs = {"Content-Type": "application/json"}
    hdrs.update(dict(headers))
    c.request("POST", "/generate", body=json.dumps(payload).encode(),
              headers=hdrs)
    r = c.getresponse()
    return r, r.read()


def test_generate_nonstream_digest_and_provenance(decode_server):
    r, body = _generate(decode_server,
                        {"tokens": [5, 9, 13, 2], "max_new_tokens": 8},
                        headers={"X-Request-Id": "rid-dec-1"})
    assert r.status == 200
    assert r.getheader("X-Request-Id") == "rid-dec-1"
    assert r.getheader("traceparent")
    assert r.getheader("X-Output-Digest") == \
        hashlib.sha256(body).hexdigest()
    obj = json.loads(body)
    assert obj["prompt_len"] == 4
    assert len(obj["tokens"]) == 8
    assert obj["finish_reason"] == "completed"


def test_generate_stream_matches_nonstream_digest(decode_server):
    ref_r, ref_body = _generate(
        decode_server, {"tokens": [5, 9, 13, 2], "max_new_tokens": 8})
    ref_digest = ref_r.getheader("X-Output-Digest")
    ref_tokens = json.loads(ref_body)["tokens"]

    r, body = _generate(decode_server,
                        {"tokens": [5, 9, 13, 2], "max_new_tokens": 8,
                         "stream": True},
                        headers={"X-Request-Id": "rid-dec-s"})
    assert r.status == 200
    assert r.getheader("X-Request-Id") == "rid-dec-s"
    assert r.getheader("traceparent")
    assert r.getheader("Content-Type") == "application/x-ndjson"
    lines = body.decode().strip().split("\n")
    toks = [json.loads(ln)["t"] for ln in lines[:-1]]
    final = json.loads(lines[-1])
    assert toks == ref_tokens
    assert final["done"] and final["finish_reason"] == "completed"
    # the streamed fingerprint is the CANONICAL body digest: a streamed
    # client verifies the same sha a replay of the non-streamed form
    # recomputes
    assert final["digest"] == ref_digest


def test_generate_bad_request_and_too_long(decode_server):
    r, _ = _generate(decode_server, {"max_new_tokens": 8})  # no tokens
    assert r.status == 400
    r, _ = _generate(decode_server,
                     {"tokens": [1] * 60, "max_new_tokens": 10})
    assert r.status == 400


def test_generate_deadline_header(decode_server):
    r, body = _generate(decode_server,
                        {"tokens": [1, 2, 3], "max_new_tokens": 50},
                        headers={"X-Deadline-Ms": "0.001"})
    assert r.status == 200
    assert json.loads(body)["finish_reason"] == "deadline"
