"""Image subsystem tests: op pipeline parity, stages, IO, transfer learning
(ref suites: opencv/src/test/.../ImageTransformerSuite.scala,
core/.../image/UnrollImageSuite, deep-learning ImageFeaturizerSuite —
the flower-photos transfer-learning config is BASELINE config #3).
"""
import io as _io
import zipfile

import numpy as np
import pytest

from synapseml_tpu.data.table import Table
from synapseml_tpu.image import (ImageFeaturizer, ImageSetAugmenter,
                                 ImageTransformer, ResizeImageTransformer,
                                 UnrollBinaryImage, UnrollImage, decode_image,
                                 from_spark_layout, ops, read_image_files,
                                 to_spark_layout)

RNG = np.random.default_rng(0)


def _img(h=24, w=32, c=3, seed=0):
    return np.random.default_rng(seed).integers(
        0, 256, (h, w, c)).astype(np.uint8)


def _obj_col(imgs):
    col = np.empty(len(imgs), dtype=object)
    col[:] = imgs
    return col


# ---------------------------------------------------------------------------
# ops
# ---------------------------------------------------------------------------

def test_resize_matches_pil_bilinear():
    from PIL import Image

    img = _img(40, 60)
    ours = np.asarray(ops.resize(img.astype(np.float32), height=20, width=30))
    theirs = np.asarray(
        Image.fromarray(img).resize((30, 20), Image.BILINEAR), np.float32)
    # different half-pixel conventions; interior pixels agree closely
    diff = np.abs(ours[2:-2, 2:-2] - theirs[2:-2, 2:-2])
    assert np.median(diff) < 6.0


def test_crop_center_crop_flip_threshold_exact():
    img = _img(10, 12).astype(np.float32)
    np.testing.assert_array_equal(
        np.asarray(ops.crop(img, x=2, y=3, height=4, width=5)),
        img[3:7, 2:7])
    cc = np.asarray(ops.center_crop(img, 6, 6))
    assert cc.shape == (6, 6, 3)
    np.testing.assert_array_equal(np.asarray(ops.flip(img, 1)), img[:, ::-1])
    th = np.asarray(ops.threshold(img, 128.0, 255.0, ops.THRESH_BINARY))
    np.testing.assert_array_equal(th, np.where(img > 128, 255.0, 0.0))


def test_gray_conversion_bt601():
    img = _img(6, 6).astype(np.float32)
    gray = np.asarray(ops.color_format(img, ops.COLOR_RGB2GRAY))
    want = img[..., 0] * 0.299 + img[..., 1] * 0.587 + img[..., 2] * 0.114
    np.testing.assert_allclose(gray[..., 0], want, rtol=1e-5)


# ---------------------------------------------------------------------------
# stages
# ---------------------------------------------------------------------------

def test_image_transformer_param_map_and_fluent():
    imgs = _obj_col([_img(seed=i) for i in range(4)])
    t = Table({"image": imgs})
    # reference-style stage dicts
    it = ImageTransformer(input_col="image", output_col="out", stages=(
        {"action": "resize", "height": 16, "width": 16},
        {"action": "colorformat", "format": ops.COLOR_RGB2GRAY},
    ))
    out = it.transform(t)
    assert out["out"][0].shape == (16, 16, 1)
    # fluent builder
    it2 = ImageTransformer(input_col="image", output_col="out") \
        .resize(height=16, width=16).flip(ops.FLIP_LEFT_RIGHT)
    out2 = it2.transform(t)
    assert out2["out"][0].shape == (16, 16, 3)


def test_image_transformer_mixed_shapes():
    imgs = _obj_col([_img(20, 20, seed=1), _img(30, 40, seed=2), None])
    it = ImageTransformer(input_col="image", output_col="out") \
        .resize(height=8, width=8)
    out = it.transform(Table({"image": imgs}))
    assert out["out"][0].shape == (8, 8, 3)
    assert out["out"][1].shape == (8, 8, 3)
    assert out["out"][2] is None


def test_resize_image_transformer_keep_aspect():
    imgs = _obj_col([_img(40, 80)])
    r = ResizeImageTransformer(input_col="image", output_col="out", size=20,
                               keep_aspect_ratio=True)
    out = r.transform(Table({"image": imgs}))
    assert out["out"][0].shape == (20, 40, 3)  # shorter side -> 20


def test_unroll_image_layout():
    img = _img(5, 7)
    out = UnrollImage(input_col="image", output_col="v").transform(
        Table({"image": _obj_col([img])}))
    vec = out["v"][0] if out["v"].dtype == object else out["v"][0, :]
    want = np.transpose(img.astype(np.float64), (2, 0, 1)).reshape(-1)
    np.testing.assert_array_equal(np.asarray(vec), want)


def test_image_set_augmenter_adds_flips():
    imgs = _obj_col([_img(seed=3), _img(seed=4)])
    t = Table({"image": imgs, "label": np.array([0, 1])})
    aug = ImageSetAugmenter(input_col="image", output_col="image_aug",
                            flip_left_right=True, flip_up_down=True)
    out = aug.transform(t)
    assert out.num_rows == 6
    assert list(out["label"]) == [0, 1, 0, 1, 0, 1]
    np.testing.assert_array_equal(
        np.asarray(out["image_aug"][2]), np.asarray(imgs[0])[:, ::-1])
    np.testing.assert_array_equal(
        np.asarray(out["image_aug"][4]), np.asarray(imgs[0])[::-1])


# ---------------------------------------------------------------------------
# IO
# ---------------------------------------------------------------------------

def _png_bytes(img):
    from PIL import Image

    buf = _io.BytesIO()
    Image.fromarray(img).save(buf, format="PNG")
    return buf.getvalue()


def test_decode_png_and_ppm():
    img = _img(9, 11)
    np.testing.assert_array_equal(decode_image(_png_bytes(img)), img)
    ppm = b"P6\n11 9\n255\n" + img.tobytes()
    np.testing.assert_array_equal(decode_image(ppm), img)
    assert decode_image(b"not an image") is None


def test_read_image_files_with_zip(tmp_path):
    a, b = _img(seed=5), _img(seed=6)
    (tmp_path / "a.png").write_bytes(_png_bytes(a))
    with zipfile.ZipFile(tmp_path / "batch.zip", "w") as zf:
        zf.writestr("b.png", _png_bytes(b))
        zf.writestr("notes.txt", b"skip me")
    (tmp_path / "broken.png").write_bytes(b"corrupt")
    t = read_image_files(str(tmp_path))
    assert t.num_rows == 2
    by_path = {p: im for p, im in zip(t["path"], t["image"])}
    np.testing.assert_array_equal(by_path[str(tmp_path / "a.png")], a)
    np.testing.assert_array_equal(
        by_path[str(tmp_path / "batch.zip") + "/b.png"], b)


def test_spark_layout_roundtrip():
    img = _img(4, 6)
    data = to_spark_layout(img)
    back = from_spark_layout(data, 4, 6, 3)
    np.testing.assert_array_equal(back, img)


# ---------------------------------------------------------------------------
# ImageFeaturizer — transfer learning gate (BASELINE config #3 analogue)
# ---------------------------------------------------------------------------

def _striped_dataset(n_per_class=40, size=32, seed=0):
    """Two texture classes: vertical vs horizontal stripes + noise."""
    rng = np.random.default_rng(seed)
    imgs, labels = [], []
    for cls in (0, 1):
        for _ in range(n_per_class):
            freq = rng.integers(2, 5)
            ramp = np.arange(size) * freq * 2 * np.pi / size
            wave = (np.sin(ramp) * 100 + 128)
            img = np.tile(wave[None, :] if cls == 0 else wave[:, None],
                          (size, 1) if cls == 0 else (1, size))
            img = img[..., None].repeat(3, -1)
            img = img + rng.normal(0, 20, img.shape)
            imgs.append(np.clip(img, 0, 255).astype(np.uint8))
            labels.append(cls)
    idx = rng.permutation(len(imgs))
    return ([imgs[i] for i in idx],
            np.array([labels[i] for i in idx]))


def test_image_featurizer_transfer_learning_gate():
    from sklearn.linear_model import LogisticRegression

    from synapseml_tpu.onnx import zoo

    imgs, labels = _striped_dataset()
    feat = ImageFeaturizer(model_bytes=zoo.tiny_resnet(image_size=32),
                           cut_output_layers=1, image_size=32,
                           input_col="image")
    out = feat.transform(Table({"image": _obj_col(imgs)}))
    feats = np.asarray(out[feat.output_col])
    assert feats.ndim == 2 and feats.shape[0] == len(imgs)
    n_train = 60
    clf = LogisticRegression(max_iter=2000).fit(
        feats[:n_train], labels[:n_train])
    acc = clf.score(feats[n_train:], labels[n_train:])
    # committed gate: random-init backbone features must separate the two
    # texture classes (reference gates flower-photos accuracy similarly)
    assert acc >= 0.85, f"transfer accuracy {acc}"


def test_image_featurizer_full_predictions_and_binary_input():
    from synapseml_tpu.onnx import zoo

    imgs, _ = _striped_dataset(n_per_class=3)
    blob = zoo.tiny_resnet(image_size=32, num_classes=10)
    # cut=0: full model output
    feat0 = ImageFeaturizer(model_bytes=blob, cut_output_layers=0,
                            image_size=32, input_col="image",
                            output_col="probs")
    out0 = feat0.transform(Table({"image": _obj_col(imgs)}))
    assert np.asarray(out0["probs"]).shape == (6, 10)
    # binary (encoded bytes) input column
    blobs = _obj_col([_png_bytes(im) for im in imgs])
    featb = ImageFeaturizer(model_bytes=blob, cut_output_layers=1,
                            image_size=32, input_col="bytes",
                            output_col="features")
    outb = featb.transform(Table({"bytes": blobs}))
    feats_b = np.asarray(outb["features"])
    # same as decoding first
    feati = ImageFeaturizer(model_bytes=blob, cut_output_layers=1,
                            image_size=32, input_col="image",
                            output_col="features")
    outi = feati.transform(Table({"image": _obj_col(imgs)}))
    np.testing.assert_allclose(feats_b, np.asarray(outi["features"]),
                               rtol=1e-4, atol=1e-5)


def test_featurizer_serde_roundtrip(tmp_path):
    from synapseml_tpu.core.pipeline import PipelineStage
    from synapseml_tpu.onnx import zoo

    imgs, _ = _striped_dataset(n_per_class=2)
    feat = ImageFeaturizer(model_bytes=zoo.tiny_resnet(image_size=32),
                           cut_output_layers=1, image_size=32,
                           input_col="image")
    p = str(tmp_path / "feat")
    feat.save(p)
    feat2 = PipelineStage.load(p)
    t = Table({"image": _obj_col(imgs)})
    np.testing.assert_allclose(
        np.asarray(feat2.transform(t)[feat2.output_col]),
        np.asarray(feat.transform(t)[feat.output_col]), rtol=1e-5)
