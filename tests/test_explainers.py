import numpy as np
import pytest

from synapseml_tpu.core.param import Param
from synapseml_tpu.core.pipeline import Transformer
from synapseml_tpu.data.table import Table
from synapseml_tpu.explainers import (
    ImageLIME,
    ImageSHAP,
    TabularSHAP,
    TextLIME,
    VectorLIME,
    VectorSHAP,
    superpixels,
)

W = np.array([2.0, -1.0, 0.5], np.float32)


class LinearModel(Transformer):
    """probability = W . features (deterministic, vector input)."""

    input_col = Param("features col", default="features")

    def _transform(self, table):
        x = np.asarray(table[self.input_col], np.float32)
        p = x @ W
        return table.with_column("probability", np.column_stack([p]))


class TabularLinear(Transformer):
    def _transform(self, table):
        p = (2.0 * np.asarray(table["a"], np.float32)
             - 1.0 * np.asarray(table["b"], np.float32))
        return table.with_column("probability", np.column_stack([p]))


class TokenCounter(Transformer):
    """Score = 1 if 'good' present else 0."""

    def _transform(self, table):
        p = np.array([1.0 if "good" in str(t).split() else 0.0
                      for t in table["text"]], np.float32)
        return table.with_column("probability", np.column_stack([p]))


class BrightnessModel(Transformer):
    """Score = mean pixel intensity of the image."""

    def _transform(self, table):
        p = np.array([float(np.mean(img)) for img in table["image"]], np.float32)
        return table.with_column("probability", np.column_stack([p]))


@pytest.fixture
def vec_table():
    rng = np.random.default_rng(1)
    return Table({"features": rng.normal(size=(4, 3)).astype(np.float32)})


def test_vector_shap_matches_linear(vec_table):
    shap = VectorSHAP(model=LinearModel(), input_col="features",
                      target_col="probability", target_classes=(0,),
                      num_samples=128, seed=3)
    out = shap.transform(vec_table)
    phis = out["explanation" if "explanation" in out else "output"]
    x = np.asarray(vec_table["features"])
    bg = x.mean(axis=0)
    expected = W * (x - bg)  # linear-model shapley values
    got = np.asarray(phis)[:, 0, 1:]
    np.testing.assert_allclose(got, expected, atol=0.08)
    # phi0 == f(background)
    np.testing.assert_allclose(np.asarray(phis)[:, 0, 0], np.full(4, W @ bg),
                               atol=0.05)
    # efficiency: phis sum to f(x) - f(bg)
    np.testing.assert_allclose(got.sum(1), x @ W - W @ bg, atol=0.02)


def test_vector_lime_signs(vec_table):
    lime = VectorLIME(model=LinearModel(), input_col="features",
                      target_col="probability", target_classes=(0,),
                      num_samples=200, seed=0, regularization=0.001)
    out = lime.transform(vec_table)
    coefs = np.asarray(out["output"])[:, 0, :]
    x = np.asarray(vec_table["features"])
    bg = x.mean(axis=0)
    # LIME coefs on on/off states approximate w_i * (x_i - bg_i)
    expected = W * (x - bg)
    assert np.corrcoef(coefs.ravel(), expected.ravel())[0, 1] > 0.9


def test_tabular_shap():
    t = Table({"a": np.array([1.0, 2.0, 0.0]),
               "b": np.array([0.0, 1.0, 2.0]),
               "id": [10, 11, 12]})
    shap = TabularSHAP(model=TabularLinear(), input_cols=["a", "b"],
                       target_col="probability", target_classes=(0,),
                       num_samples=32, seed=0)
    out = shap.transform(t)
    phis = np.asarray(out["output"])
    a, b = t["a"], t["b"]
    expected_a = 2.0 * (a - a.mean())
    np.testing.assert_allclose(phis[:, 0, 1], expected_a, atol=0.05)
    assert "id" in out  # pass-through columns preserved


def test_text_lime():
    t = Table({"text": ["good movie plot", "bad movie plot"]})
    lime = TextLIME(model=TokenCounter(), input_col="text",
                    target_col="probability", target_classes=(0,),
                    num_samples=64, seed=0)
    out = lime.transform(t)
    coefs = np.asarray(out["output"])
    toks0 = out["tokens"][0]
    # 'good' token should carry the largest positive weight in row 0
    assert toks0[int(np.argmax(coefs[0, 0, :len(toks0)]))] == "good"
    # row 1 has no signal: coefficients near zero
    assert np.abs(coefs[1, 0]).max() < 0.2


def test_superpixels():
    img = np.zeros((24, 24, 3), np.float32)
    img[:, 12:] = 1.0
    sp = superpixels(img, cell_size=8.0)
    assert sp.assignment.shape == (24, 24)
    assert 2 <= sp.num_clusters <= 16
    # left/right halves should not share a cluster
    left = set(sp.assignment[:, :10].ravel())
    right = set(sp.assignment[:, 14:].ravel())
    assert not left & right


def test_image_lime_and_shap():
    rng = np.random.default_rng(0)
    img = rng.random((16, 16, 3)).astype(np.float32) * 0.2
    img[4:12, 4:12] = 0.9  # bright patch drives the score
    t = Table({"image": [img], "rowid": [1]})
    for cls in (ImageLIME, ImageSHAP):
        ex = cls(model=BrightnessModel(), input_col="image",
                 target_col="probability", target_classes=(0,),
                 num_samples=40, seed=0, cell_size=8.0)
        out = ex.transform(t)
        coefs = np.asarray(out["output"])[0, 0]
        sp = out["superpixels"][0]
        # the superpixel covering the bright center should rank highest
        center_cluster = sp[8, 8]
        vals = coefs[1:] if cls is ImageSHAP else coefs
        assert int(np.argmax(vals[:sp.max() + 1])) == int(center_cluster)


def test_tabular_shap_over_onnx_scorer():
    """The north-star explainer config: KernelSHAP attributing a REAL
    imported-ONNX scorer (LightGBM -> convert -> ONNXModel), not a toy
    python function (BASELINE config #4 'explainers over TPU scorer')."""
    from synapseml_tpu.core.pipeline import Transformer
    from synapseml_tpu.gbdt.estimators import LightGBMClassifier
    from synapseml_tpu.onnx import ONNXModel, convert_lightgbm

    rng = np.random.default_rng(2)
    n = 300
    x = rng.normal(size=(n, 3)).astype(np.float32)
    # only feature 0 matters: attributions must concentrate there
    y = (x[:, 0] > 0).astype(np.float64)
    lgbm = LightGBMClassifier(num_iterations=15, num_leaves=7).fit(
        Table({"features": x, "label": y}))
    scorer = ONNXModel(model_bytes=convert_lightgbm(lgbm),
                       feed_dict={"input": "features"})

    class OnnxScorer(Transformer):
        """Adapter: assemble feature cols -> ONNX scorer -> probability."""

        def _transform(self, table):
            feats = np.column_stack([
                np.asarray(table[c], np.float32) for c in ("f0", "f1", "f2")])
            scored = scorer.transform(Table({"features": feats}))
            return table.with_column(
                "probability", np.asarray(scored["probabilities"]))

        def transform(self, table):  # bypass telemetry wrapper simplicity
            return self._transform(table)

    t = Table({"f0": x[:24, 0].astype(np.float64),
               "f1": x[:24, 1].astype(np.float64),
               "f2": x[:24, 2].astype(np.float64)})
    shap = TabularSHAP(model=OnnxScorer(), input_cols=["f0", "f1", "f2"],
                       target_col="probability", target_classes=(1,),
                       num_samples=32, seed=0)
    phis = np.asarray(shap.transform(t)["output"])  # [N, 1, D+1]
    # mean |phi| of the informative feature dominates the noise features
    mag = np.abs(phis[:, 0, 1:]).mean(axis=0)
    assert mag[0] > 3 * max(mag[1], mag[2]), mag
