"""Codegen tests (ref: CodeGen.scala:22-199 reflection-driven wrapper
emission; the generated tier stands in for the reference's PyTestFuzzing
generated-test artifacts)."""
import os

import pytest

from synapseml_tpu import codegen


def test_public_stage_discovery_covers_all_modules():
    stages = codegen.public_stages()
    mods = {q.rsplit(".", 2)[0] for q in stages}
    # every major layer contributes stages
    for want in ["synapseml_tpu.gbdt", "synapseml_tpu.linear",
                 "synapseml_tpu.onnx", "synapseml_tpu.image",
                 "synapseml_tpu.io", "synapseml_tpu.cognitive",
                 "synapseml_tpu.cyber", "synapseml_tpu.stages",
                 "synapseml_tpu.featurize", "synapseml_tpu.explainers"]:
        assert any(m.startswith(want) for m in mods), want
    assert len(stages) > 100


def test_r_wrapper_content(tmp_path):
    files = codegen.generate_r(str(tmp_path))
    assert len(files) > 100
    path = os.path.join(str(tmp_path), "smt_light_gbm_classifier.R")
    src = open(path).read()
    assert "smt_light_gbm_classifier <- function(" in src
    assert 'reticulate::import("synapseml_tpu.gbdt.estimators")' in src
    assert "num_iterations = 100" in src       # defaults preserved
    assert "#' @param num_leaves" in src       # roxygen docs
    assert "#' @export" in src
    # acronym-aware naming
    assert os.path.exists(os.path.join(str(tmp_path), "smt_ocr.R"))
    assert os.path.exists(os.path.join(str(tmp_path), "smt_sar.R"))


def test_api_reference(tmp_path):
    out = str(tmp_path / "api.md")
    content = codegen.generate_api_reference(out)
    assert os.path.exists(out)
    assert "### LightGBMClassifier (Estimator)" in content
    assert "### ONNXModel (Transformer)" in content
    assert "| `num_leaves` |" in content


def test_committed_artifacts_in_sync():
    """generated/ is committed; regeneration must be a no-op so the
    artifacts never drift from the code (the reference regenerates wrappers
    every build)."""
    root = os.path.join(os.path.dirname(__file__), "..", "generated")
    if not os.path.isdir(root):
        pytest.skip("no committed generated/ dir")
    import tempfile

    with tempfile.TemporaryDirectory() as d:
        codegen.generate_r(os.path.join(d, "R"))
        codegen.generate_api_reference(os.path.join(d, "api.md"))
        committed = sorted(os.listdir(os.path.join(root, "R")))
        fresh = sorted(os.listdir(os.path.join(d, "R")))
        assert committed == fresh
        for name in ("R/smt_light_gbm_classifier.R", "api.md"):
            with open(os.path.join(root, name)) as a, \
                    open(os.path.join(d, name)) as b:
                assert a.read() == b.read(), f"{name} drifted: re-run " \
                    "python -m synapseml_tpu.codegen"


def test_generated_r_wrapper_executes_under_r():
    """Execute one generated wrapper in a real R session (reticulate).
    The CI image ships no R runtime, so this skips there — with the
    reason stated explicitly rather than silently passing on unparsed
    code (round-2 weak #8). The content assertions above still guard
    wrapper structure on every run."""
    import shutil
    import subprocess

    rscript = shutil.which("Rscript")
    if rscript is None:
        pytest.skip("Rscript is not installed in this image; generated R "
                    "is structure-checked only (content assertions above)")
    import tempfile

    with tempfile.TemporaryDirectory() as d:
        codegen.generate_r(os.path.join(d, "R"))
        wrapper = os.path.join(d, "R", "smt_light_gbm_classifier.R")
        probe = os.path.join(d, "probe.R")
        with open(probe, "w") as fh:
            fh.write(f'source("{wrapper}"); '
                     f'stopifnot(is.function(smt_light_gbm_classifier))\n')
        r = subprocess.run([rscript, probe], capture_output=True, text=True,
                           timeout=120)
        assert r.returncode == 0, r.stderr
