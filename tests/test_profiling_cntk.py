"""Profiling utilities + CNTKModel tests (SURVEY.md §5 tracing; §2.6
CNTKModel feed/fetch by name or index)."""
import os

import numpy as np
import pytest

from synapseml_tpu.data.table import Table
from synapseml_tpu.dl.cntk import CNTKModel
from synapseml_tpu.onnx import zoo
from synapseml_tpu.utils.profiling import StopWatch, stage_stats, trace


def test_stopwatch_accumulates():
    sw = StopWatch()
    with sw.measure():
        sum(range(10000))
    first = sw.elapsed
    assert first > 0
    with sw.measure():
        sum(range(10000))
    assert sw.elapsed > first


def test_stage_stats_pipeline():
    from synapseml_tpu.stages.transformers import DropColumns, RenameColumn
    from synapseml_tpu.gbdt.estimators import LightGBMClassifier

    rng = np.random.default_rng(0)
    t = Table({"features": rng.normal(size=(80, 4)).astype(np.float32),
               "label": (rng.random(80) > 0.5).astype(np.float64),
               "junk": np.arange(80)})
    out, stats = stage_stats([
        DropColumns(cols=["junk"]),
        LightGBMClassifier(num_iterations=3, num_leaves=3),
        RenameColumn(input_col="prediction", output_col="pred"),
    ], t)
    assert "pred" in out.columns and "junk" not in out.columns
    assert list(stats["stage"]) == ["DropColumns", "LightGBMClassifier",
                                    "RenameColumn"]
    assert list(stats["kind"]) == ["transformer", "estimator", "transformer"]
    assert stats["pct"].sum() == pytest.approx(100.0)


def test_trace_writes_profile(tmp_path):
    import jax
    import jax.numpy as jnp

    d = str(tmp_path / "prof")
    with trace(d):
        jnp.arange(1000).sum().block_until_ready()
    # a trace dir appears where the profiler is supported; either way the
    # context must not raise
    if os.path.isdir(d):
        assert any(os.scandir(d))


def test_cntk_model_onnx_path_and_port_selection():
    blob = zoo.mlp([6, 12], num_classes=4, seed=1)
    m = CNTKModel(model_bytes=blob)
    m.set_input_node(0, column="feats").set_output_node(0, column="probs")
    x = np.random.default_rng(0).normal(size=(5, 6)).astype(np.float32)
    out = m.transform(Table({"feats": x}))
    assert np.asarray(out["probs"]).shape == (5, 4)
    # name-based selection agrees with index-based
    in_name = m.graph.input_names[0]
    m2 = CNTKModel(model_bytes=blob).set_input_node(in_name, column="feats")
    m2.set_output_node(m.graph.output_names[0], column="probs")
    np.testing.assert_allclose(np.asarray(m2.transform(
        Table({"feats": x}))["probs"]), np.asarray(out["probs"]), rtol=1e-6)
    with pytest.raises(KeyError):
        m.set_output_node("nonexistent")


def test_cntk_native_model_rejected_with_recipe():
    fake_cntk = "BCNTK".encode("utf-16-le") + b"\x00" * 64
    with pytest.raises(ValueError, match="ONNX export with the CNTK python package"):
        CNTKModel(model_bytes=fake_cntk)


def test_cntk_cut_output_layers_headless():
    blob = zoo.tiny_resnet(image_size=24)
    m = CNTKModel(model_bytes=blob, feed_dict={"data": "img"},
                  fetch_dict=None)
    m.cut_output_layers(1)  # drop the Gemm head
    x = np.random.default_rng(0).normal(size=(2, 3, 24, 24)).astype(
        np.float32)
    out = m.transform(Table({"img": x}))
    feats = np.asarray(out[m.graph.output_names[0]])
    assert feats.ndim == 2 and feats.shape[0] == 2 and feats.shape[1] > 4


def test_cntk_truncation_survives_serde(tmp_path):
    from synapseml_tpu.core.pipeline import PipelineStage

    blob = zoo.tiny_resnet(image_size=24)
    m = CNTKModel(model_bytes=blob, feed_dict={"data": "img"},
                  fetch_dict=None).cut_output_layers(1)
    x = np.random.default_rng(0).normal(size=(2, 3, 24, 24)).astype(
        np.float32)
    feats = np.asarray(m.transform(Table({"img": x}))[m.graph.output_names[0]])
    p = str(tmp_path / "cntk")
    m.save(p)
    m2 = PipelineStage.load(p)
    assert m2.cut_layers == 1
    feats2 = np.asarray(
        m2.transform(Table({"img": x}))[m2.graph.output_names[0]])
    np.testing.assert_allclose(feats2, feats, rtol=1e-5)
    # copies stay headless too
    m3 = m.copy()
    np.testing.assert_allclose(
        np.asarray(m3.transform(Table({"img": x}))[m3.graph.output_names[0]]),
        feats, rtol=1e-5)


def test_cntk_multi_input_feed_merge():
    from synapseml_tpu.onnx import GraphBuilder

    g = GraphBuilder(name="two_in", opset=17)
    a = g.add_input("a", np.float32, ["N", 3])
    b = g.add_input("b", np.float32, ["N", 3])
    s = g.add_node("Add", [a, b])
    g.add_output(s, np.float32, ["N", 3])
    m = CNTKModel(model_bytes=g.to_bytes())
    m.set_input_node(0, column="left").set_input_node(1, column="right")
    m.set_output_node(0, column="sum")
    x = np.ones((2, 3), np.float32)
    out = m.transform(Table({"left": x, "right": x * 2}))
    np.testing.assert_allclose(np.asarray(out["sum"]), x * 3)


def test_cntk_cut_via_param_api_refreshes_executor():
    """Setting cut_layers through the public param surface must not reuse
    a stale full-graph executor."""
    blob = zoo.tiny_resnet(image_size=24)
    m = CNTKModel(model_bytes=blob, feed_dict={"data": "img"},
                  fetch_dict=None)
    x = np.random.default_rng(0).normal(size=(2, 3, 24, 24)).astype(
        np.float32)
    full = np.asarray(m.transform(Table({"img": x}))[m.graph.output_names[0]])
    m.set(cut_layers=1)  # plain param write, no helper
    feats = np.asarray(
        m.transform(Table({"img": x}))[m.graph.output_names[0]])
    assert feats.shape != full.shape  # truncated output, not head logits


def test_cntk_payload_param_path_also_rejected():
    fake = "BCNTK".encode("utf-16-le") + b"\x00" * 64
    m = CNTKModel()
    m.set(model_payload=fake)  # the generated-wrapper path
    with pytest.raises(ValueError, match="ONNX export with the CNTK python package"):
        _ = m.graph


def test_payload_swap_refreshes_graph():
    """set(model_payload=...) after a transform must re-import, not serve
    the stale cached graph."""
    blob2 = zoo.mlp([6, 12], num_classes=2, seed=9)
    blob4 = zoo.mlp([6, 12], num_classes=4, seed=9)
    m = CNTKModel(model_bytes=blob2)
    x = np.random.default_rng(0).normal(size=(3, 6)).astype(np.float32)
    out2 = np.asarray(m.transform(Table({"input": x}))[
        m.graph.output_names[0]])
    assert out2.shape == (3, 2)
    m.set(model_payload=blob4)
    out4 = np.asarray(m.transform(Table({"input": x}))[
        m.graph.output_names[0]])
    assert out4.shape == (3, 4)
    # native payload swapped in via set() is rejected at next use
    m.set(model_payload="BCNTK".encode("utf-16-le") + b"\x00" * 64)
    with pytest.raises(ValueError, match="ONNX export with the CNTK python package"):
        _ = m.graph
