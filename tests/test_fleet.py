"""Fleet autoscaling (runtime/autoscale.py + tools/fleet/controller.py,
docs/deployment.md "Fleet operations") and its satellites: the pure
decision math (hysteresis, cooldowns, clamps, the never-scale-on-
blindness rails), scrape parsing, the warm-boot hydration audit, the
controller loop against a fake backend AND a real echo subprocess,
/debug/build, the process self-telemetry gauges, the bench-history
rotation cap, and loadgen's multi-target LB stand-in mode.

Discipline matches tests/test_blackbox.py: every blocking wait rides a
HARD timeout so a regression fails fast instead of wedging the suite
(this file runs inside tools/ci/smoke_pipeline.sh's wall clock).
"""
import json
import os
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

from synapseml_tpu.io.serving import (ContinuousServer, WorkerServer,
                                      make_reply)
from synapseml_tpu.runtime import autoscale as aut
from synapseml_tpu.runtime import blackbox as bb
from synapseml_tpu.runtime import perfwatch as pw
from synapseml_tpu.runtime import telemetry as tm

HARD = 30.0  # hard wall for any blocking wait: hang -> fast red X
ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _policy(**kw):
    base = dict(min_replicas=1, max_replicas=4, duty_high=0.7,
                duty_low=0.2, burn_high=2.0, up_consecutive=2,
                down_consecutive=2, up_cooldown_s=0.0,
                down_cooldown_s=0.0, stale_after_s=10.0)
    base.update(kw)
    return aut.FleetPolicy(**base)


def _sample(name="r1", *, ts=100.0, reachable=True, ready=True,
            duty=0.0, avail_burn=None, **kw):
    return aut.ReplicaSample(name, ts=ts, reachable=reachable,
                             ready=ready, duty=duty,
                             avail_burn=avail_burn, **kw)


def _decide_n(policy, state, samples, now=100.0, n=1):
    last = None
    for _ in range(n):
        last = aut.decide(now, samples, state, policy)
    return last


# -- decision math ----------------------------------------------------------

def test_policy_validation():
    with pytest.raises(ValueError):
        aut.FleetPolicy(min_replicas=0)
    with pytest.raises(ValueError):
        aut.FleetPolicy(min_replicas=3, max_replicas=2)
    with pytest.raises(ValueError):
        aut.FleetPolicy(duty_high=0.2, duty_low=0.5)


def test_scale_up_needs_consecutive_breaches():
    policy = _policy(up_consecutive=3)
    state = aut.FleetState()
    hot = [_sample(duty=0.9)]
    assert aut.decide(100.0, hot, state, policy).direction == "hold"
    assert aut.decide(100.5, hot, state, policy).direction == "hold"
    d = aut.decide(101.0, hot, state, policy)
    assert (d.direction, d.reason, d.target) == ("up", "duty_cycle", 2)


def test_scale_up_on_burn_rate_even_at_low_duty():
    policy = _policy()
    state = aut.FleetState()
    burning = [_sample(duty=0.05, avail_burn=5.0)]
    d = _decide_n(policy, state, burning, n=2)
    assert (d.direction, d.reason) == ("up", "burn_rate")


def test_up_cooldown_blocks_flapping():
    policy = _policy(up_cooldown_s=30.0)
    state = aut.FleetState()
    state.mark_scaled(95.0, "up")  # scaled 5s ago, 30s cooldown
    d = _decide_n(policy, state, [_sample(duty=0.9)], n=2)
    assert (d.direction, d.reason) == ("hold", "cooldown")
    # once the window passes, the (still breaching) streak scales
    d = aut.decide(130.0, [_sample(ts=130.0, duty=0.9)], state, policy)
    assert d.direction == "up"


def test_up_clamped_at_max():
    policy = _policy(max_replicas=2)
    state = aut.FleetState()
    fleet = [_sample("r1", duty=0.9), _sample("r2", duty=0.9)]
    d = _decide_n(policy, state, fleet, n=2)
    assert (d.direction, d.reason) == ("hold", "at_max")


def test_scale_down_after_streak():
    policy = _policy(down_consecutive=3)
    state = aut.FleetState()
    idle = [_sample("r1", duty=0.01), _sample("r2", duty=0.01)]
    assert _decide_n(policy, state, idle, n=2).direction == "hold"
    d = aut.decide(100.0, idle, state, policy)
    assert (d.direction, d.target, d.reason) == ("down", 1,
                                                 "duty_cycle")


def test_down_clamped_at_min():
    policy = _policy(min_replicas=2)
    state = aut.FleetState()
    idle = [_sample("r1", duty=0.0), _sample("r2", duty=0.0)]
    d = _decide_n(policy, state, idle, n=3)
    assert (d.direction, d.reason) == ("hold", "at_min")


def test_scrape_failure_never_scales_down():
    """THE safety rail: an unreachable replica removes evidence, not
    capacity — down is forbidden while any live replica lacks a fresh
    sample, and total blindness holds outright."""
    policy = _policy()
    state = aut.FleetState()
    mixed = [_sample("r1", duty=0.0),
             _sample("r2", reachable=False)]
    d = _decide_n(policy, state, mixed, n=4)
    assert (d.direction, d.reason) == ("hold", "stale_telemetry")
    # every scrape failing: hold with streaks reset, never scale-to-min
    blind = [_sample("r1", reachable=False),
             _sample("r2", reachable=False)]
    d = _decide_n(policy, state, blind, n=6)
    assert (d.direction, d.reason) == ("hold", "no_fresh_telemetry")
    assert state.down_streak == 0 and state.up_streak == 0


def test_stale_sample_counts_as_unreachable():
    policy = _policy(stale_after_s=5.0)
    state = aut.FleetState()
    # r2 answered long ago: fresh at t=100 it is not
    mixed = [_sample("r1", ts=100.0, duty=0.0),
             _sample("r2", ts=80.0, duty=0.0)]
    d = _decide_n(policy, state, mixed, now=100.0, n=4)
    assert (d.direction, d.reason) == ("hold", "stale_telemetry")
    assert d.aggregates["stale"] == 1


def test_down_blocked_while_replica_warming():
    policy = _policy()
    state = aut.FleetState()
    fleet = [_sample("r1", duty=0.0),
             _sample("r2", ready=False)]  # hydrating: capacity in flight
    d = _decide_n(policy, state, fleet, n=4)
    assert (d.direction, d.reason) == ("hold", "replicas_warming")


def test_streaks_reset_on_opposite_signal():
    policy = _policy(up_consecutive=2, down_consecutive=2)
    state = aut.FleetState()
    aut.decide(100.0, [_sample(duty=0.9)], state, policy)
    assert state.up_streak == 1
    aut.decide(100.5, [_sample(duty=0.5)], state, policy)  # mid-band
    assert state.up_streak == 0 and state.down_streak == 0
    aut.decide(101.0, [_sample(duty=0.01)], state, policy)
    assert state.down_streak == 1
    aut.decide(101.5, [_sample(duty=0.9)], state, policy)
    assert state.down_streak == 0 and state.up_streak == 1


# -- scrape parsing + windows -----------------------------------------------

METRICS_TEXT = """# TYPE synapseml_executor_duty_cycle gauge
synapseml_executor_duty_cycle{device="0"} 0.25
synapseml_executor_duty_cycle{device="dp8"} 0.65
synapseml_executor_recompiles_total{reason="shape_drift"} 2
synapseml_executor_recompiles_total{reason="cache_skew"} 0
synapseml_serving_replies_total{code="200",server="a"} 10
synapseml_serving_replies_total{code="200",server="b"} 5
synapseml_serving_replies_total{code="503",server="a"} 1
synapseml_compile_cache_store_hits_total 7
synapseml_compile_cache_store_skew_total 0
garbage line that must not parse
"""


def test_parse_prometheus():
    m = aut.parse_prometheus(METRICS_TEXT)
    assert m["synapseml_executor_duty_cycle"] == [
        ({"device": "0"}, 0.25), ({"device": "dp8"}, 0.65)]
    assert ({"code": "200", "server": "a"}, 10.0) in \
        m["synapseml_serving_replies_total"]
    assert "garbage" not in " ".join(m)


def test_sample_from_scrape():
    s = aut.sample_from_scrape("r1", "http://x/", 50.0, METRICS_TEXT,
                               ready=True)
    assert s.reachable and s.ready and s.ts == 50.0
    assert s.duty == 0.65  # busiest dispatch target
    assert s.recompiles == {"shape_drift": 2.0}  # zero series dropped
    assert s.recompiles_total == 2.0
    assert s.replies_by_code == {"200": 15.0, "503": 1.0}
    assert s.store_hits == 7.0 and s.store_skew == 0.0


def test_sample_unreachable_scrape():
    s = aut.sample_from_scrape("r1", "http://x/", 50.0, None,
                               ready=False)
    assert not s.reachable and s.duty == 0.0
    assert aut.aggregate([s], 50.0, _policy())["fresh"] == 0


def test_window_availability():
    prev = {"200": 100.0, "503": 2.0}
    assert aut.window_availability(prev, prev) is None  # idle window
    cur = {"200": 190.0, "503": 2.0, "500": 10.0}
    # window: 90 good, 10 bad
    assert aut.window_availability(prev, cur) == pytest.approx(0.9)


def test_hydration_audit_outcomes():
    """The warm-boot no-recompile assertion, unit-level: zero sentinel
    counts + zero store skew + store hits = warm; any post-warmup
    recompile (cache_skew included) = dirty."""
    warm = aut.hydration_audit(_sample(store_hits=5.0))
    assert warm["outcome"] == "warm" and warm["clean"]
    seed = aut.hydration_audit(_sample(store_hits=0.0))
    assert seed["outcome"] == "clean_cold" and seed["clean"]
    dirty = aut.hydration_audit(
        _sample(recompiles={"cache_skew": 1.0}, store_hits=5.0))
    assert dirty["outcome"] == "dirty" and not dirty["clean"]
    skewed = aut.hydration_audit(_sample(store_skew=2.0,
                                         store_hits=5.0))
    assert skewed["outcome"] == "dirty"


def test_fleet_series_register_and_unregister():
    c0 = aut.scale_event_counter("up", "unit_test").value
    aut.scale_event_counter("up", "unit_test").inc()
    assert aut.scale_event_counter("up", "unit_test").value == c0 + 1
    box = {"s": _sample("ghost", duty=0.5)}
    aut.register_replica_gauges("ghost", lambda: box["s"])
    assert ('synapseml_fleet_replica_duty_cycle{replica="ghost"} 0.5'
            in tm.prometheus_text())
    aut.unregister_replica_gauges("ghost")
    assert 'replica="ghost"' not in tm.prometheus_text()


# -- controller loop (fake backend: pure loop logic) ------------------------

class FakeReplica:
    def __init__(self, name):
        self.name = name
        self.url = f"http://fake/{name}"
        self.dead = False

    def alive(self):
        return not self.dead


class FakeBackend:
    def __init__(self):
        self.seq = 0
        self.spawned = []
        self.terminated = []

    def spawn(self, name=None):
        self.seq += 1
        r = FakeReplica(name or f"fake{self.seq}")
        self.spawned.append(r)
        return r

    def terminate(self, replica, timeout_s=30.0):
        self.terminated.append(replica.name)
        return {"replica": replica.name, "exit_code": 0,
                "admitted": 3, "replied": 3, "zero_dropped": True}


def _fake_controller(duty_box, policy=None, **kw):
    from tools.fleet.controller import FleetController

    backend = FakeBackend()
    c = FleetController(
        backend, policy or _policy(),
        scrape_fn=lambda replica: (
            f'synapseml_executor_duty_cycle{{device="0"}} '
            f'{duty_box["duty"]}\n', True),
        **kw)
    return backend, c


def _wait(cond, timeout=HARD):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if cond():
            return True
        time.sleep(0.02)
    return False


def test_controller_scales_up_then_down():
    duty = {"duty": 0.9}
    backend, c = _fake_controller(duty)
    c._spawn("initial")
    assert [r.name for r in c.replicas] == ["fake1"]
    assert c.tick().direction == "hold"  # streak 1 of 2
    d = c.tick()
    assert d.direction == "up" and len(c.replicas) == 2
    # scale events recorded in ring + counters
    evs = [e for e in bb.snapshot(stacks=False)["events"]
           if e["event"] == "fleet_scale"]
    assert any(e.get("direction") == "up"
               and e.get("reason") == "duty_cycle" for e in evs)
    duty["duty"] = 0.01
    c.tick()
    assert c.tick().direction == "down"
    assert _wait(lambda: backend.terminated == ["fake2"])  # LIFO victim
    assert len(c.replicas) == 1
    assert _wait(lambda: any(t.get("zero_dropped")
                             for t in c._terminations))


def test_controller_min_floor_replaces_dead_replica():
    duty = {"duty": 0.5}  # mid-band: no policy scaling in play
    backend, c = _fake_controller(duty)
    c._spawn("initial")
    c.replicas[0].dead = True  # SIGKILL chaos, OOM, crash
    c.tick()
    names = [r.name for r in c.replicas]
    assert names == ["fake2"]  # corpse reaped, floor restored
    died = [e for e in bb.snapshot(stacks=False)["events"]
            if e["event"] == "fleet_replica_died"]
    assert died and died[-1]["replica"] == "fake1"


def test_controller_status_and_metrics_http():
    duty = {"duty": 0.4}
    backend, c = _fake_controller(duty)
    c._spawn("initial")
    base = c.serve(port=0)
    try:
        c.tick()
        with urllib.request.urlopen(base + "/fleet/status",
                                    timeout=HARD) as r:
            status = json.loads(r.read())
        assert [x["state"] for x in status["replicas"]] == ["ready"]
        assert status["replicas"][0]["duty"] == 0.4
        assert status["aggregates"]["fresh"] == 1
        assert status["decisions"][-1]["direction"] == "hold"
        with urllib.request.urlopen(base + "/fleet/metrics",
                                    timeout=HARD) as r:
            text = r.read().decode()
        assert 'synapseml_fleet_replicas{state="ready"} 1' in text
        assert "synapseml_process_rss_bytes" in text
        with urllib.request.urlopen(base + "/metrics",
                                    timeout=HARD) as r:
            assert r.status == 200  # scrape-compatible alias
    finally:
        c._httpd.shutdown()
        c._httpd.server_close()


def test_local_backend_echo_replica_round_trip():
    """A REAL serving subprocess: spawn (echo pipeline — no model, no
    jax warmup), score one request through it, then SIGTERM and read
    the zero-drop exit accounting back."""
    from tools.fleet.controller import LocalProcessBackend

    backend = LocalProcessBackend(announce_timeout_s=120.0)
    replica = backend.spawn("fleet_echo_test")
    try:
        req = urllib.request.Request(
            replica.url, data=json.dumps({"ping": 1}).encode(),
            method="POST", headers={"Content-Type": "application/json"})
        with urllib.request.urlopen(req, timeout=HARD) as r:
            assert r.status == 200 and json.loads(r.read()) == {"ping": 1}
    finally:
        verdict = backend.terminate(replica, timeout_s=HARD)
    assert verdict["exit_code"] == 0
    assert verdict["admitted"] >= 1
    assert verdict["zero_dropped"], verdict


# -- satellites -------------------------------------------------------------

def test_debug_build_endpoint():
    srv = WorkerServer("buildinfo_test")
    try:
        with urllib.request.urlopen(
                srv.url.rstrip("/") + "/debug/build",
                timeout=HARD) as r:
            info = json.loads(r.read())
        assert info["server"] == "buildinfo_test"
        assert info["ready"] is True and info["draining"] is False
        assert info["python"] and info["pid"] == os.getpid()
        # jax/jaxlib versions come from dist metadata, never an import
        assert "jax" in info and "backend" in info
        srv.set_ready(False)
        with urllib.request.urlopen(
                srv.url.rstrip("/") + "/debug/build",
                timeout=HARD) as r:
            assert json.loads(r.read())["ready"] is False
    finally:
        srv.stop()


def test_debug_build_behind_debug_gate(monkeypatch):
    monkeypatch.setenv("SYNAPSEML_DEBUG_ENDPOINTS", "0")
    srv = WorkerServer("buildinfo_gated")
    try:
        with pytest.raises(urllib.error.HTTPError) as ei:
            urllib.request.urlopen(
                srv.url.rstrip("/") + "/debug/build", timeout=HARD)
        assert ei.value.code == 403
    finally:
        srv.stop()


def test_process_self_telemetry_gauges():
    assert pw.ensure_process_registered()
    stats = pw.process_stats()
    assert stats["rss_bytes"] > 0
    assert stats["open_fds"] > 0
    assert stats["thread_count"] >= 1
    assert stats["uptime_seconds"] > 0
    text = tm.prometheus_text()
    for series in ("synapseml_process_rss_bytes",
                   "synapseml_process_open_fds",
                   "synapseml_process_thread_count",
                   "synapseml_process_uptime_seconds"):
        line = next(ln for ln in text.splitlines()
                    if ln.startswith(series + " "))
        assert float(line.split()[1]) > 0


def test_bench_history_rotation_cap(tmp_path):
    from tools.ci.bench_check import append_history, load_history

    path = str(tmp_path / "hist.jsonl")
    for i in range(10):
        append_history(path, [{"metric": "m", "value": float(i),
                               "unit": "ms"}], max_lines=4)
    lines = open(path).read().splitlines()
    assert len(lines) == 4  # capped at the newest K
    runs = load_history(path, 99)
    assert [r["value"] for r in runs] == [6.0, 7.0, 8.0, 9.0]
    # torn tail (killed writer): rotation neither crashes nor keeps it
    with open(path, "a") as fh:
        fh.write('{"ts": 1, "run": {"metric": "torn"')
    append_history(path, [{"metric": "m", "value": 10.0,
                           "unit": "ms"}], max_lines=4)
    assert len(open(path).read().splitlines()) == 4
    assert load_history(path, 99)[-1]["value"] == 10.0
    # max_lines=0 disables rotation
    for i in range(8):
        append_history(path, [{"metric": "m", "value": 0.0,
                               "unit": "ms"}], max_lines=0)
    assert len(open(path).read().splitlines()) == 12


def _echo_pipeline(table):
    replies = np.empty(table.num_rows, dtype=object)
    for i, v in enumerate(table["value"]):
        replies[i] = make_reply(v)
    return table.with_column("reply", replies)


def test_loadgen_multi_target_round_robin():
    from tools.loadgen import run_load

    a = ContinuousServer("fleet_lg_a", _echo_pipeline,
                         max_batch=16).start()
    b = ContinuousServer("fleet_lg_b", _echo_pipeline,
                         max_batch=16).start()
    try:
        s = run_load(None, rps=150, duration_s=0.6, shapes=[2],
                     seed=3, timeout=HARD, targets=[a.url, b.url])
        assert s["hung"] == 0
        assert s["by_status"].get("200", 0) == s["scheduled"]
        assert set(s["per_target"]) == {a.url, b.url}
        hits = [t["by_status"].get("200", 0)
                for t in s["per_target"].values()]
        assert all(h > 0 for h in hits)  # both endpoints carried load
        assert sum(hits) == s["scheduled"]
        assert s["failover_retries"] == 0
    finally:
        a.stop()
        b.stop()


def test_loadgen_multi_target_failover_on_dead_target():
    """The LB stand-in behavior the fleet chaos phase leans on: a
    socket-dead target's requests retry once on the next target, so a
    killed replica costs retries, not availability."""
    from tools.loadgen import evaluate_slo, run_load

    a = ContinuousServer("fleet_lg_c", _echo_pipeline,
                         max_batch=16).start()
    dead = "http://127.0.0.1:1/"  # connection refused, instantly
    try:
        s = run_load(None, rps=120, duration_s=0.6, shapes=[2],
                     seed=4, timeout=HARD, targets=[a.url, dead])
        assert s["hung"] == 0
        assert s["by_status"].get("200", 0) == s["scheduled"]
        assert s["failover_retries"] > 0
        assert s["per_target"][dead]["by_status"].get("error", 0) > 0
        slo = evaluate_slo(s, slo_availability=0.99)
        assert slo["pass"], slo
    finally:
        a.stop()


def test_loadgen_cli_targets_and_payload_key(tmp_path):
    import subprocess
    import sys

    a = ContinuousServer("fleet_lg_cli", _echo_pipeline,
                         max_batch=16).start()
    out = str(tmp_path / "lg.json")
    try:
        r = subprocess.run(
            [sys.executable, os.path.join(ROOT, "tools", "loadgen.py"),
             "--targets", f"{a.url},{a.url}", "--payload-key",
             "features", "--rps", "60", "--duration", "0.4",
             "--seed", "6", "--timeout", "20", "--out", out,
             "--slo-availability", "0.99"],
            capture_output=True, text=True, timeout=HARD * 4,
            cwd=ROOT)
        assert r.returncode == 0, r.stdout + r.stderr
        summary = json.load(open(out))
        assert summary["per_target"]
        assert summary["slo"]["pass"]
    finally:
        a.stop()
    # neither --url nor --targets is a usage error
    from tools.loadgen import main as lg_main

    with pytest.raises(SystemExit) as ei:
        lg_main(["--rps", "1", "--duration", "0.1"])
    assert ei.value.code == 2


# -- decode starvation signal (round 19) ------------------------------------

def test_scale_up_on_decode_starvation_even_at_low_duty():
    policy = _policy()
    state = aut.FleetState()
    starved = [_sample(duty=0.05, decode_wait_burn=1.4)]
    d = _decide_n(policy, state, starved, n=2)
    assert (d.direction, d.reason) == ("up", "decode_starvation")


def test_decode_burn_below_threshold_does_not_scale():
    policy = _policy()
    state = aut.FleetState()
    warm = [_sample(duty=0.3, decode_wait_burn=0.6)]
    d = _decide_n(policy, state, warm, n=3)
    assert d.direction == "hold"


def test_decode_burn_blocks_scale_down():
    # duty says idle, but admission waits are burning the wait SLO:
    # shrinking the fleet would starve the decode queue further
    policy = _policy(down_consecutive=2)
    state = aut.FleetState()
    idle_but_starved = [_sample("r1", duty=0.01, decode_wait_burn=1.2),
                        _sample("r2", duty=0.01)]
    d = _decide_n(policy, state, idle_but_starved, n=3)
    assert d.direction != "down"
    # same fleet with the burn cooled drains normally
    state2 = aut.FleetState()
    cooled = [_sample("r1", duty=0.01, decode_wait_burn=0.1),
              _sample("r2", duty=0.01)]
    d2 = _decide_n(policy, state2, cooled, n=3)
    assert d2.direction == "down"


def test_aggregate_decode_burn_max():
    agg = aut.aggregate(
        [_sample("r1", decode_wait_burn=0.4),
         _sample("r2", decode_wait_burn=1.1),
         _sample("r3")], 100.0, _policy())
    assert agg["decode_burn_max"] == 1.1


def test_sample_from_scrape_decode_burn():
    text = METRICS_TEXT + (
        'synapseml_decode_queue_wait_burn{server="a"} 0.3\n'
        'synapseml_decode_queue_wait_burn{server="b"} 1.7\n')
    s = aut.sample_from_scrape("r1", "http://x/", 50.0, text,
                               ready=True)
    assert s.decode_wait_burn == 1.7  # max across a replica's servers


def test_sample_from_scrape_decode_burn_absent_is_none():
    # a scoring-only replica exports no decode series: the sample must
    # say "no signal" (None), not a 0.0 that reads as measured-cold
    s = aut.sample_from_scrape("r1", "http://x/", 50.0, METRICS_TEXT,
                               ready=True)
    assert s.decode_wait_burn is None
    agg = aut.aggregate([s], 50.0, _policy())
    assert agg["decode_burn_max"] == 0.0
