"""Cyber AccessAnomaly tests — anomaly separation on synthetic access data
(ref: core/src/main/python/mmlspark/cyber/anomaly/collaborative_filtering.py
test strategy: departments of users accessing disjoint resource sets;
cross-department access must score anomalous)."""
import numpy as np
import pytest

from synapseml_tpu.core.pipeline import PipelineStage
from synapseml_tpu.cyber import (AccessAnomaly, AccessAnomalyModel,
                                 ComplementAccessTransformer)
from synapseml_tpu.data.table import Table


def _department_data(n_tenants=1, users_per_dept=12, res_per_dept=8,
                     seed=0):
    """Two departments per tenant with disjoint resource sets."""
    rng = np.random.default_rng(seed)
    rows = {"tenant": [], "user": [], "res": [], "likelihood": []}
    for t in range(n_tenants):
        for dept in (0, 1):
            for u in range(users_per_dept):
                uid = f"t{t}_d{dept}_u{u}"
                for _ in range(10):
                    r = rng.integers(0, res_per_dept)
                    rows["tenant"].append(t)
                    rows["user"].append(uid)
                    rows["res"].append(f"t{t}_d{dept}_r{r}")
                    rows["likelihood"].append(float(rng.integers(1, 5)))
    return Table({k: np.asarray(v) for k, v in rows.items()})


def test_anomaly_separation_and_normalization():
    t = _department_data()
    est = AccessAnomaly(likelihood_col="likelihood", rank_param=8,
                        max_iter=15, seed=1)
    model = est.fit(t)
    scored = model.transform(t)
    train_scores = np.asarray(scored["anomaly_score"], np.float64)
    # normalized on training accesses: mean ~0, std ~1
    assert abs(train_scores.mean()) < 0.15
    assert 0.7 < train_scores.std() < 1.3

    # cross-department accesses must be substantially more anomalous
    cross = Table({
        "tenant": np.zeros(12, np.int64),
        "user": np.asarray([f"t0_d0_u{u}" for u in range(12)]),
        "res": np.asarray([f"t0_d1_r{r % 8}" for r in range(12)]),
    })
    cross_scores = np.asarray(model.transform(cross)["anomaly_score"])
    assert np.isfinite(cross_scores).all()
    assert cross_scores.mean() > train_scores.mean() + 1.5


def test_multi_tenant_isolation():
    """Tenants are fitted independently; same ids in another tenant don't
    leak (reference: tenant partitions are completely isolated)."""
    t = _department_data(n_tenants=2)
    model = AccessAnomaly(likelihood_col="likelihood", rank_param=6,
                          max_iter=10, seed=2).fit(t)
    assert len(model.mappings) == 2
    scored = model.transform(t)
    s = np.asarray(scored["anomaly_score"])
    assert np.isfinite(s).all()


def test_unseen_entities_yield_null_scores():
    t = _department_data()
    model = AccessAnomaly(likelihood_col="likelihood", rank_param=4,
                          max_iter=5).fit(t)
    unknown = Table({
        "tenant": np.zeros(2, np.int64),
        "user": np.asarray(["nobody", "t0_d0_u0"]),
        "res": np.asarray(["t0_d0_r0", "never_seen"]),
    })
    s = np.asarray(model.transform(unknown)["anomaly_score"])
    assert np.isnan(s).all()


def test_model_serde(tmp_path):
    t = _department_data(users_per_dept=6, res_per_dept=5)
    model = AccessAnomaly(likelihood_col="likelihood", rank_param=4,
                          max_iter=5).fit(t)
    p = str(tmp_path / "aa")
    model.save(p)
    model2 = PipelineStage.load(p)
    np.testing.assert_allclose(
        np.asarray(model2.transform(t)["anomaly_score"], np.float64),
        np.asarray(model.transform(t)["anomaly_score"], np.float64),
        rtol=1e-6)


def test_complement_access_transformer():
    t = _department_data(users_per_dept=5, res_per_dept=4)
    comp = ComplementAccessTransformer(
        partition_key="tenant", indexed_col_names=("user", "res"),
        complementset_factor=1, seed=3)
    out = comp.transform(t)
    assert out.num_rows > 0
    seen = set(zip(np.asarray(t["user"]).tolist(),
                   np.asarray(t["res"]).tolist()))
    for u, r in zip(out["user"], out["res"]):
        assert (u, r) not in seen  # strictly from the complement set
    # entities come from the observed vocabulary
    assert set(np.asarray(out["user"])) <= set(np.asarray(t["user"]))


# ---------------------------------------------------------------------------
# feature module: indexers + per-partition scalers
# ---------------------------------------------------------------------------

def _access_log():
    return Table({
        "tenant": np.array(["t1", "t1", "t1", "t2", "t2"], dtype=object),
        "user": np.array(["alice", "bob", "alice", "bob", "carol"],
                         dtype=object),
        "score": np.array([1.0, 3.0, 5.0, 10.0, 30.0]),
    })


def test_id_indexer_reset_per_partition():
    from synapseml_tpu.cyber import IdIndexer

    t = _access_log()
    model = IdIndexer(input_col="user", output_col="user_idx",
                      partition_key="tenant",
                      reset_per_partition=True).fit(t)
    out = model.transform(t)
    assert "user" not in out.columns  # raw value column is dropped
    idx = np.asarray(out["user_idx"])
    # per-tenant 1-based: t1 has {alice:1, bob:2}; t2 restarts {bob:1, carol:2}
    assert idx.tolist() == [1, 2, 1, 1, 2]

    # global numbering when reset_per_partition=False
    g = IdIndexer(input_col="user", output_col="user_idx",
                  partition_key="tenant",
                  reset_per_partition=False).fit(t)
    gi = np.asarray(g.transform(t)["user_idx"])
    assert sorted(set(gi.tolist())) == [1, 2, 3, 4]

    # unseen values map to 0
    unseen = Table({"tenant": np.array(["t1"], dtype=object),
                    "user": np.array(["mallory"], dtype=object)})
    assert np.asarray(model.transform(unseen)["user_idx"]).tolist() == [0]

    # undo_transform restores the original values by (tenant, id)
    restored = model.undo_transform(out)
    assert np.asarray(restored["user"]).tolist() == [
        "alice", "bob", "alice", "bob", "carol"]


def test_multi_indexer_and_serde(tmp_path):
    from synapseml_tpu.core.pipeline import PipelineStage
    from synapseml_tpu.cyber import IdIndexer, MultiIndexer

    t = Table({
        "tenant": np.array(["t1", "t1", "t2"], dtype=object),
        "user": np.array(["u1", "u2", "u1"], dtype=object),
        "res": np.array(["r1", "r1", "r2"], dtype=object),
    })
    mi = MultiIndexer(indexers=[
        IdIndexer(input_col="user", output_col="uidx",
                  partition_key="tenant"),
        IdIndexer(input_col="res", output_col="ridx",
                  partition_key="tenant"),
    ])
    model = mi.fit(t)
    out = model.transform(t)
    assert set(out.columns) == {"tenant", "uidx", "ridx"}
    assert model.get_model_by_input_col("user").output_col == "uidx"
    assert model.get_model_by_output_col("ridx").input_col == "res"

    p = str(tmp_path / "mi")
    model.save(p)
    model2 = PipelineStage.load(p)
    out2 = model2.transform(t)
    assert np.asarray(out2["uidx"]).tolist() == \
        np.asarray(out["uidx"]).tolist()


def test_standard_scaler_per_partition():
    from synapseml_tpu.cyber import StandardScalarScaler

    t = _access_log()
    model = StandardScalarScaler(input_col="score", output_col="z",
                                 partition_key="tenant").fit(t)
    z = np.asarray(model.transform(t)["z"])
    # each tenant normalized with ITS OWN mean/std_pop
    t1 = np.array([1.0, 3.0, 5.0])
    t2 = np.array([10.0, 30.0])
    np.testing.assert_allclose(z[:3], (t1 - t1.mean()) / t1.std())
    np.testing.assert_allclose(z[3:], (t2 - t2.mean()) / t2.std())

    # unseen partition -> NaN (the reference's left-join null)
    unk = Table({"tenant": np.array(["t9"], dtype=object),
                 "score": np.array([1.0])})
    assert np.isnan(np.asarray(model.transform(unk)["z"])).all()

    # degenerate std falls back to centering
    const = Table({"tenant": np.array(["c", "c"], dtype=object),
                   "score": np.array([7.0, 7.0])})
    m2 = StandardScalarScaler(input_col="score", output_col="z",
                              partition_key="tenant").fit(const)
    np.testing.assert_allclose(
        np.asarray(m2.transform(const)["z"]), [0.0, 0.0])


def test_linear_scaler_per_partition():
    from synapseml_tpu.cyber import LinearScalarScaler

    t = _access_log()
    model = LinearScalarScaler(input_col="score", output_col="s",
                               partition_key="tenant",
                               min_required_value=0.0,
                               max_required_value=1.0).fit(t)
    s = np.asarray(model.transform(t)["s"])
    np.testing.assert_allclose(s[:3], [0.0, 0.5, 1.0])  # t1: [1,5] -> [0,1]
    np.testing.assert_allclose(s[3:], [0.0, 1.0])       # t2: [10,30] -> [0,1]

    # degenerate range maps to the midpoint
    const = Table({"tenant": np.array(["c"], dtype=object),
                   "score": np.array([7.0])})
    m2 = LinearScalarScaler(input_col="score", output_col="s",
                            partition_key="tenant", min_required_value=2.0,
                            max_required_value=4.0).fit(const)
    np.testing.assert_allclose(np.asarray(m2.transform(const)["s"]), [3.0])

    # unpartitioned mode: one global group
    g = LinearScalarScaler(input_col="score", output_col="s").fit(t)
    gs = np.asarray(g.transform(t)["s"])
    np.testing.assert_allclose(gs, (np.asarray(t["score"]) - 1.0) / 29.0,
                               atol=1e-12)
