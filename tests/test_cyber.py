"""Cyber AccessAnomaly tests — anomaly separation on synthetic access data
(ref: core/src/main/python/mmlspark/cyber/anomaly/collaborative_filtering.py
test strategy: departments of users accessing disjoint resource sets;
cross-department access must score anomalous)."""
import numpy as np
import pytest

from synapseml_tpu.core.pipeline import PipelineStage
from synapseml_tpu.cyber import (AccessAnomaly, AccessAnomalyModel,
                                 ComplementAccessTransformer)
from synapseml_tpu.data.table import Table


def _department_data(n_tenants=1, users_per_dept=12, res_per_dept=8,
                     seed=0):
    """Two departments per tenant with disjoint resource sets."""
    rng = np.random.default_rng(seed)
    rows = {"tenant": [], "user": [], "res": [], "likelihood": []}
    for t in range(n_tenants):
        for dept in (0, 1):
            for u in range(users_per_dept):
                uid = f"t{t}_d{dept}_u{u}"
                for _ in range(10):
                    r = rng.integers(0, res_per_dept)
                    rows["tenant"].append(t)
                    rows["user"].append(uid)
                    rows["res"].append(f"t{t}_d{dept}_r{r}")
                    rows["likelihood"].append(float(rng.integers(1, 5)))
    return Table({k: np.asarray(v) for k, v in rows.items()})


def test_anomaly_separation_and_normalization():
    t = _department_data()
    est = AccessAnomaly(likelihood_col="likelihood", rank_param=8,
                        max_iter=15, seed=1)
    model = est.fit(t)
    scored = model.transform(t)
    train_scores = np.asarray(scored["anomaly_score"], np.float64)
    # normalized on training accesses: mean ~0, std ~1
    assert abs(train_scores.mean()) < 0.15
    assert 0.7 < train_scores.std() < 1.3

    # cross-department accesses must be substantially more anomalous
    cross = Table({
        "tenant": np.zeros(12, np.int64),
        "user": np.asarray([f"t0_d0_u{u}" for u in range(12)]),
        "res": np.asarray([f"t0_d1_r{r % 8}" for r in range(12)]),
    })
    cross_scores = np.asarray(model.transform(cross)["anomaly_score"])
    assert np.isfinite(cross_scores).all()
    assert cross_scores.mean() > train_scores.mean() + 1.5


def test_multi_tenant_isolation():
    """Tenants are fitted independently; same ids in another tenant don't
    leak (reference: tenant partitions are completely isolated)."""
    t = _department_data(n_tenants=2)
    model = AccessAnomaly(likelihood_col="likelihood", rank_param=6,
                          max_iter=10, seed=2).fit(t)
    assert len(model.mappings) == 2
    scored = model.transform(t)
    s = np.asarray(scored["anomaly_score"])
    assert np.isfinite(s).all()


def test_unseen_entities_yield_null_scores():
    t = _department_data()
    model = AccessAnomaly(likelihood_col="likelihood", rank_param=4,
                          max_iter=5).fit(t)
    unknown = Table({
        "tenant": np.zeros(2, np.int64),
        "user": np.asarray(["nobody", "t0_d0_u0"]),
        "res": np.asarray(["t0_d0_r0", "never_seen"]),
    })
    s = np.asarray(model.transform(unknown)["anomaly_score"])
    assert np.isnan(s).all()


def test_model_serde(tmp_path):
    t = _department_data(users_per_dept=6, res_per_dept=5)
    model = AccessAnomaly(likelihood_col="likelihood", rank_param=4,
                          max_iter=5).fit(t)
    p = str(tmp_path / "aa")
    model.save(p)
    model2 = PipelineStage.load(p)
    np.testing.assert_allclose(
        np.asarray(model2.transform(t)["anomaly_score"], np.float64),
        np.asarray(model.transform(t)["anomaly_score"], np.float64),
        rtol=1e-6)


def test_complement_access_transformer():
    t = _department_data(users_per_dept=5, res_per_dept=4)
    comp = ComplementAccessTransformer(
        partition_key="tenant", indexed_col_names=("user", "res"),
        complementset_factor=1, seed=3)
    out = comp.transform(t)
    assert out.num_rows > 0
    seen = set(zip(np.asarray(t["user"]).tolist(),
                   np.asarray(t["res"]).tolist()))
    for u, r in zip(out["user"], out["res"]):
        assert (u, r) not in seen  # strictly from the complement set
    # entities come from the observed vocabulary
    assert set(np.asarray(out["user"])) <= set(np.asarray(t["user"]))
