"""Channel-level fault tolerance (docs/robustness.md, "channel failure
domains"): depth-aware placement, circuit breakers + half-open probes,
one-shot failover dispatch, and the graceful-drain lifecycle.

The contract: the CHANNEL — not the thread — is the unit of failure a
DistributedServer plans for. A channel whose scoring path breaks trips
its breaker (quarantine + redisperse), in-hand work fails over ONCE to
a healthy sibling bit-identically, a background probe re-admits the
channel when it heals, and a SIGTERM-style drain gets every accepted
request a real reply while new arrivals see 503 + Retry-After. Every
blocking wait rides a hard timeout (the smoke_pipeline.sh discipline).
"""
import json
import random
import threading
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

from synapseml_tpu.data.table import Table
from synapseml_tpu.io.http import HTTPRequestData
from synapseml_tpu.io.serving import (BREAKER_CLOSED, BREAKER_HALF_OPEN,
                                      BREAKER_OPEN, CachedRequest,
                                      ContinuousServer, DistributedServer,
                                      MultiChannelMap, WorkerServer,
                                      _retry_rng, make_reply)
from synapseml_tpu.runtime import faults as flt
from synapseml_tpu.runtime import telemetry as tm

HARD = 30.0  # hard wall for any blocking wait: hang -> fast red X


@pytest.fixture(autouse=True)
def _clean_faults():
    flt.deactivate()
    yield
    flt.deactivate()


def _ctr(name, **labels):
    """Sum one counter family, optionally filtered by exact labels."""
    total = 0.0
    for k, v in tm.snapshot()["counters"].items():
        if not k.startswith("synapseml_" + name):
            continue
        if all(f'{lk}="{lv}"' in k for lk, lv in labels.items()):
            total += v
    return total


def _post(url, obj, timeout=HARD, headers=None):
    hdrs = {"Content-Type": "application/json"}
    hdrs.update(headers or {})
    req = urllib.request.Request(url, data=json.dumps(obj).encode(),
                                 method="POST", headers=hdrs)
    with urllib.request.urlopen(req, timeout=timeout) as r:
        return r.status, json.loads(r.read().decode()), dict(r.headers)


def _get(url, timeout=HARD):
    try:
        with urllib.request.urlopen(url, timeout=timeout) as r:
            return r.status, dict(r.headers)
    except urllib.error.HTTPError as e:
        e.read()
        return e.code, dict(e.headers)


def _cr(rid):
    return CachedRequest(rid, HTTPRequestData(
        url="/", method="POST", headers={}, entity=b"{}"))


def _linear_pipeline(table: Table) -> Table:
    replies = np.empty(table.num_rows, dtype=object)
    for i, v in enumerate(table["value"]):
        replies[i] = make_reply({"y": [x * 3.0 + 1.0 for x in v["x"]]})
    return table.with_column("reply", replies)


# ---------------------------------------------------------------------------
# MultiChannelMap: depth-aware, quarantine-aware placement
# ---------------------------------------------------------------------------

def test_depth_aware_placement_prefers_least_loaded():
    """A backed-up channel sheds NEW load to its siblings instead of
    accumulating it; with uniform depths placement stays round-robin."""
    m = MultiChannelMap(3)
    # uniform depths -> exact round-robin (the PR-2 behavior preserved)
    for i in range(6):
        m.add(_cr(f"rr{i}"))
    assert m.depths() == [2, 2, 2]
    # channel 0 backs up (its consumer stalled): new adds avoid it
    for i in range(4):
        m.channel(0).put(_cr(f"deep{i}"))
    assert m.depths() == [6, 2, 2]
    for i in range(4):
        m.add(_cr(f"new{i}"))
    assert m.depths()[0] == 6  # nothing new landed on the deep channel
    assert sum(m.depths()) == 14


def test_placement_never_picks_quarantined_channel():
    m = MultiChannelMap(3)
    m.set_channel_enabled(0, False)
    assert m.enabled_channels() == [1, 2]
    for i in range(8):
        m.add(_cr(f"q{i}"))
    assert m.depths()[0] == 0
    assert sum(m.depths()) == 8
    # ALL channels quarantined: availability over purity — placement
    # degrades to least-loaded over everything rather than dropping
    m.set_channel_enabled(1, False)
    m.set_channel_enabled(2, False)
    m.add(_cr("last"))
    assert sum(m.depths()) == 9


def test_quarantine_redisperses_parked_requests():
    """A request must never sit on a queue no healthy consumer drains:
    tripping a channel moves its parked work onto enabled siblings."""
    m = MultiChannelMap(3)
    for i in range(5):
        m.channel(0).put(_cr(f"p{i}"))
    moved = m.set_channel_enabled(0, False)
    assert moved == 5
    d = m.depths()
    assert d[0] == 0 and d[1] + d[2] == 5
    # re-admitting moves nothing back (placement just may pick it again)
    assert m.set_channel_enabled(0, True) == 0
    assert m.enabled_channels() == [0, 1, 2]


def test_multichannelmap_concurrent_add_resize_quarantine():
    """No request lost or duplicated under concurrent add() +
    update_n_channels() + breaker-style quarantine/re-admit churn."""
    m = MultiChannelMap(3)
    N = 300
    stop = threading.Event()

    def adder():
        for i in range(N):
            m.add(_cr(f"r{i}"))

    def resizer():
        rng = random.Random(7)
        while not stop.is_set():
            m.update_n_channels(rng.randint(1, 4))
            time.sleep(0.001)

    def quarantiner():
        rng = random.Random(11)
        while not stop.is_set():
            ch = rng.randint(0, 3)
            m.set_channel_enabled(ch, False)
            time.sleep(0.001)
            m.set_channel_enabled(ch, True)

    threads = [threading.Thread(target=f)
               for f in (adder, resizer, quarantiner)]
    for t in threads:
        t.start()
    threads[0].join(timeout=HARD)
    assert not threads[0].is_alive(), "adder wedged"
    stop.set()
    for t in threads[1:]:
        t.join(timeout=HARD)
        assert not t.is_alive()
    m.update_n_channels(3)
    # re-enable everything, then drain all queues: exactly N unique rids
    for ch in range(3):
        m.set_channel_enabled(ch, True)
    seen = []
    for ch in range(3):
        q = m.channel(ch)
        while True:
            try:
                seen.append(q.get_nowait().rid)
            except Exception:  # noqa: BLE001 - queue.Empty
                break
    assert len(seen) == N, f"lost/duplicated: {len(seen)} != {N}"
    assert len(set(seen)) == N


# ---------------------------------------------------------------------------
# circuit breakers + failover dispatch
# ---------------------------------------------------------------------------

def test_breaker_trips_quarantines_and_probe_readmits():
    """threshold consecutive failures trip OPEN (quarantine + placement
    avoidance); disarming the fault lets the half-open probe re-admit
    the channel CLOSED — the full state round trip, counted."""
    ds = DistributedServer("t_cf_brk", n_channels=2, breaker_threshold=2,
                           probe_interval=0.05)
    try:
        flt.activate("compute.channel0", prob=1.0)
        for _ in range(2):
            # failover: the same fn lands on channel 1 and succeeds
            assert ds.score_on_channel(0, lambda: 42) == 42
        # the trip wakes the probe, which may already be mid-pass
        # (HALF_OPEN) when we look: quarantined means NOT CLOSED
        assert ds.channel_state(0) != BREAKER_CLOSED
        assert ds.channels.enabled_channels() == [1]
        assert _ctr("serving_failover_total", server="t_cf_brk") >= 2
        assert _ctr("serving_channel_trips_total", server="t_cf_brk") >= 1

        flt.deactivate("compute.channel0")
        deadline = time.monotonic() + HARD
        while time.monotonic() < deadline and \
                ds.channel_state(0) != BREAKER_CLOSED:
            time.sleep(0.01)
        assert ds.channel_state(0) == BREAKER_CLOSED
        assert ds.channels.enabled_channels() == [0, 1]
        # the probe's bounce is faster than a scrape: transitions are
        # COUNTED per state, so the round trip is provable after the fact
        for state in ("open", "half_open", "closed"):
            assert _ctr("serving_breaker_transitions_total",
                        server="t_cf_brk", channel="0",
                        state=state) >= 1, state
    finally:
        ds.stop()


def test_breaker_trip_redisperses_parked_requests():
    """Requests parked on the tripping channel move to healthy siblings
    at trip time (counted in serving_redispersed_total)."""
    ds = DistributedServer("t_cf_redis", n_channels=2,
                           breaker_threshold=1, probe_interval=30.0)
    try:
        for i in range(4):
            ds.channels.channel(0).put(_cr(f"park{i}"))
        flt.activate("compute.channel0", prob=1.0)
        assert ds.score_on_channel(0, lambda: 1) == 1  # fails over
        # trip-woken probe may be mid-pass (HALF_OPEN); the armed fault
        # fails its canary, so the channel never returns to CLOSED
        assert ds.channel_state(0) != BREAKER_CLOSED
        assert ds.channels.depths()[0] == 0
        assert ds.channels.depths()[1] == 4
        assert _ctr("serving_redispersed_total", server="t_cf_redis") >= 4
    finally:
        ds.stop()


def test_no_healthy_sibling_raises_to_caller():
    """Failover needs a healthy target: with every other channel OPEN
    the original error propagates (the caller's explicit-error path —
    never a hang, never a silent drop)."""
    ds = DistributedServer("t_cf_alone", n_channels=2,
                           breaker_threshold=1, probe_interval=30.0)
    try:
        flt.activate("compute.channel0", prob=1.0)
        flt.activate("compute.channel1", prob=1.0)
        # channel0 fails -> trips OPEN -> fails over to channel1, whose
        # own fault fails the retry too: the error surfaces (explicitly)
        with pytest.raises(flt.FaultInjected):
            ds.score_on_channel(0, lambda: 1)
        # probe passes (woken at trip) fail on the armed faults: both
        # channels stay quarantined (OPEN, transiently HALF_OPEN)
        assert ds.channel_state(0) != BREAKER_CLOSED
        assert ds.channel_state(1) != BREAKER_CLOSED
        # both quarantined: no failover target exists, the error propagates
        with pytest.raises(flt.FaultInjected):
            ds.score_on_channel(1, lambda: 1)
    finally:
        ds.stop()


def test_stall_counts_as_breaker_failure():
    """A score stalled past stall_timeout counts against the channel
    even though its result still returns (the slow-channel trip)."""
    ds = DistributedServer("t_cf_stall", n_channels=2,
                           breaker_threshold=1, probe_interval=30.0,
                           stall_timeout=0.005)
    try:
        flt.activate("latency.channel_stall", prob=1.0, latency_ms=25.0)
        assert ds.score_on_channel(0, lambda: 7) == 7
        # the result returned, but the stall tripped the breaker
        assert _ctr("serving_channel_trips_total",
                    server="t_cf_stall") >= 1
    finally:
        ds.stop()


def test_probe_canary_stall_does_not_readmit():
    """A channel tripped for slowness must not be re-admitted by a
    canary that itself stalled — that would flap trip->re-admit->trip
    with a redisperse every cycle. The probe times its canary against
    stall_timeout like any real score."""
    ds = DistributedServer("t_cf_probestall", n_channels=2,
                           breaker_threshold=1, probe_interval=0.03,
                           stall_timeout=0.005)
    try:
        flt.activate("latency.channel_stall", prob=1.0, latency_ms=25.0)
        assert ds.score_on_channel(0, lambda: 3) == 3  # stall trips it
        deadline = time.monotonic() + HARD
        while time.monotonic() < deadline and _ctr(
                "serving_channel_probe_total", server="t_cf_probestall",
                outcome="fail") < 2:
            time.sleep(0.01)
        # probes ran and FAILED on the still-stalling canary; the
        # channel was never re-admitted
        assert _ctr("serving_channel_probe_total",
                    server="t_cf_probestall", outcome="fail") >= 2
        assert ds.channel_state(0) != BREAKER_CLOSED
        # disarm: the next canary is fast -> re-admitted CLOSED
        flt.deactivate("latency.channel_stall")
        deadline = time.monotonic() + HARD
        while time.monotonic() < deadline and \
                ds.channel_state(0) != BREAKER_CLOSED:
            time.sleep(0.01)
        assert ds.channel_state(0) == BREAKER_CLOSED
    finally:
        ds.stop()


def test_stall_on_failover_attempt_counts_against_target():
    """The failover attempt gets the same stall accounting as a direct
    score: a degraded channel every failover lands on must accrue
    breaker failures, not be recorded as an unconditional success."""
    ds = DistributedServer("t_cf_fostall", n_channels=2,
                           breaker_threshold=1, probe_interval=30.0,
                           stall_timeout=0.005)
    try:
        flt.activate("compute.channel0", prob=1.0)

        def slow():
            time.sleep(0.02)
            return 9

        # channel0 fails -> trips; failover to channel1 returns 9 but
        # stalls past stall_timeout -> channel1 trips too. Assert via
        # the monotonic trips counter: channel1's healthy canary may
        # legitimately re-admit it before state is observed (channel0
        # stays OPEN — its armed fault fails every probe)
        assert ds.score_on_channel(0, slow) == 9
        assert ds.channel_state(0) != BREAKER_CLOSED
        assert _ctr("serving_channel_trips_total",
                    server="t_cf_fostall") >= 2
    finally:
        ds.stop()


def test_serve_failover_bit_identical_e2e():
    """End to end over HTTP: with channel0's compute fault armed at
    prob 1.0, every request still gets 200 with the SAME numbers a
    healthy channel computes — failover is invisible to clients."""
    ds = DistributedServer("t_cf_e2e", n_channels=2, breaker_threshold=2,
                           probe_interval=0.05)
    ds.serve(_linear_pipeline, max_batch=8, linger=0.002)
    try:
        flt.activate("compute.channel0", prob=1.0)
        for k in range(8):
            st, body, _ = _post(ds.url, {"x": [float(k), 2.0]})
            assert st == 200
            assert body["y"] == [k * 3.0 + 1.0, 7.0]
        assert ds.channel_state(0) != BREAKER_CLOSED
        flt.deactivate("compute.channel0")
        deadline = time.monotonic() + HARD
        while time.monotonic() < deadline and \
                ds.channel_state(0) != BREAKER_CLOSED:
            time.sleep(0.01)
        assert ds.channel_state(0) == BREAKER_CLOSED
        st, body, _ = _post(ds.url, {"x": [1.0, 1.0]})
        assert (st, body["y"]) == (200, [4.0, 4.0])
    finally:
        ds.stop()


def test_default_canary_scores_real_pipeline_no_flap():
    """serve() wires a REAL-pipeline canary by default: a channel broken
    by a genuine (non-injected) fault is NOT re-admitted while the fault
    persists — a no-op canary would flap it OPEN->CLOSED->OPEN with a
    redisperse every probe cycle — and re-admission happens once the
    pipeline actually scores again."""
    broken = threading.Event()

    def pipeline(table: Table) -> Table:
        if broken.is_set():
            raise RuntimeError("device wedged")
        return _linear_pipeline(table)

    ds = DistributedServer("t_cf_canary", n_channels=2,
                           breaker_threshold=1, probe_interval=0.05)
    ds.serve(pipeline, max_batch=4, linger=0.002)
    try:
        # first success captures the known-good canary row
        st, body, _ = _post(ds.url, {"x": [2.0]})
        assert (st, body["y"]) == (200, [7.0])
        deadline = time.monotonic() + HARD
        while time.monotonic() < deadline and ds._canary_table is None:
            time.sleep(0.01)
        assert ds._canary_table is not None
        assert ds.canary_fn is not None

        # a genuine fault (invisible to fault points) trips both
        # channels: the original score fails, so does the failover
        broken.set()
        try:
            _post(ds.url, {"x": [1.0]})
            raise AssertionError("expected 500")
        except urllib.error.HTTPError as e:
            assert e.code == 500
            e.read()
        deadline = time.monotonic() + HARD
        while time.monotonic() < deadline and not all(
                ds.channel_state(c) != BREAKER_CLOSED for c in (0, 1)):
            time.sleep(0.01)
        # the probe re-scores the canary through the REAL pipeline,
        # which still fails: channels must STAY quarantined (>= several
        # probe intervals — a no-op canary re-admits within one;
        # HALF_OPEN mid-probe still counts as quarantined)
        time.sleep(ds.probe_interval * 6)
        assert ds.channel_state(0) != BREAKER_CLOSED
        assert ds.channel_state(1) != BREAKER_CLOSED
        probe_fails = _ctr("serving_channel_probe_total",
                           server="t_cf_canary", outcome="fail")
        assert probe_fails >= 1

        # heal: the canary scores for real and re-admits both channels
        broken.clear()
        deadline = time.monotonic() + HARD
        while time.monotonic() < deadline and not all(
                ds.channel_state(c) == BREAKER_CLOSED for c in (0, 1)):
            time.sleep(0.01)
        assert ds.channel_state(0) == BREAKER_CLOSED
        assert ds.channel_state(1) == BREAKER_CLOSED
        st, body, _ = _post(ds.url, {"x": [3.0]})
        assert (st, body["y"]) == (200, [10.0])
    finally:
        ds.stop()


def test_resize_while_serving_is_refused():
    """serve() snapshots the channel count: a live resize would route
    depth-aware placement onto queues no scorer drains (clients park
    until reply_timeout) — it must raise, not silently strand."""
    ds = DistributedServer("t_cf_resize", n_channels=2)
    ds.serve(_linear_pipeline, max_batch=4)
    try:
        with pytest.raises(ValueError, match="resize while serving"):
            ds.update_n_channels(4)
        assert ds.channels.n_channels == 2
    finally:
        ds.stop()
    # stopped: resize is supported again (stop, resize, re-serve)
    assert ds.channels.n_channels == 2


def test_distributed_stop_fails_parked_channel_requests():
    """stop() with requests still parked on channel queues replies an
    explicit 503 + Retry-After — clients never wait out reply_timeout."""
    ds = DistributedServer("t_cf_stop", n_channels=2, reply_timeout=HARD)
    results = {}

    def client():
        try:
            results["r"] = _post(ds.url, {"v": 1}, timeout=HARD)
        except urllib.error.HTTPError as e:
            results["r"] = (e.code, None, dict(e.headers))

    th = threading.Thread(target=client)
    th.start()
    # wait for the distributor to fan the request onto a channel
    deadline = time.monotonic() + HARD
    while time.monotonic() < deadline and sum(ds.channels.depths()) < 1:
        time.sleep(0.01)
    assert sum(ds.channels.depths()) == 1
    ds.stop()
    th.join(timeout=HARD)
    assert not th.is_alive(), "client hung through stop()"
    st, _, hdrs = results["r"]
    assert st == 503
    assert hdrs.get("Retry-After") == "1"


# ---------------------------------------------------------------------------
# graceful drain + split health surface
# ---------------------------------------------------------------------------

def test_health_split_live_vs_ready():
    """/health/live is process-up (200 through warmup AND drain);
    /health(/ready) is traffic-worthiness (503 in both states)."""
    srv = WorkerServer("t_cf_health", ready=False)
    try:
        base = f"http://{srv.host}:{srv.port}"
        assert _get(f"{base}/health/live")[0] == 200
        assert _get(f"{base}/health/ready")[0] == 503  # warming
        assert _get(f"{base}/health")[0] == 503        # alias of ready
        srv.set_ready(True)
        assert _get(f"{base}/health/ready")[0] == 200
        assert _get(f"{base}/health")[0] == 200
        srv.begin_drain()
        assert _get(f"{base}/health/live")[0] == 200   # still alive
        st, hdrs = _get(f"{base}/health/ready")
        assert st == 503
        assert hdrs.get("Retry-After") == "1"
    finally:
        srv.stop()


def test_drain_gate_refuses_new_sheds_queued_finishes_accepted():
    """begin_drain: new enqueues 503 + Retry-After; wait_drained holds
    until accepted requests reply; stop() 503s what never got consumed."""
    srv = WorkerServer("t_cf_drain", reply_timeout=HARD)
    try:
        results = {}

        def client():
            results["r"] = _post(srv.url, {"x": 1}, timeout=HARD)

        th = threading.Thread(target=client)
        th.start()
        batch = srv.get_batch(max_rows=4, timeout=5.0)
        assert len(batch) == 1
        srv.begin_drain()
        # accepted request still in flight: not drained yet
        assert srv.wait_drained(0.05) is False
        # new arrival during drain: refused with explicit 503
        with pytest.raises(urllib.error.HTTPError) as ei:
            _post(srv.url, {"x": 2}, timeout=HARD)
        assert ei.value.code == 503
        assert ei.value.headers.get("Retry-After") == "1"
        ei.value.read()
        # the accepted request finishes to a real reply -> drained
        srv.reply_to(batch[0].rid, make_reply({"ok": True}))
        assert srv.wait_drained(HARD) is True
        th.join(timeout=HARD)
        assert results["r"][0] == 200 and results["r"][1] == {"ok": True}
        assert _ctr("serving_drain_shed_total", server="t_cf_drain") >= 1
    finally:
        srv.stop()


def test_continuous_server_drain_then_stop():
    """ContinuousServer.drain: traffic in flight completes, the drain
    histogram records, and post-drain arrivals shed 503."""
    cs = ContinuousServer("t_cf_csdrain", _linear_pipeline,
                          max_batch=8, batch_linger=0.002).start()
    try:
        st, body, _ = _post(cs.url, {"x": [1.0]})
        assert (st, body["y"]) == (200, [4.0])
        assert cs.drain(timeout_ms=5000) is True
        with pytest.raises(urllib.error.HTTPError) as ei:
            _post(cs.url, {"x": [2.0]})
        assert ei.value.code == 503
        ei.value.read()
        snap = tm.snapshot()["histograms"]
        key = [k for k in snap
               if k.startswith("synapseml_serving_drain_seconds")
               and 't_cf_csdrain' in k]
        assert key and snap[key[0]]["count"] >= 1
    finally:
        cs.stop()


def test_worker_stop_fails_queued_with_503():
    """stop() with unconsumed queued requests: explicit 503 +
    Retry-After, counted — never a silent reply_timeout."""
    srv = WorkerServer("t_cf_stopq", reply_timeout=HARD)
    results = {}

    def client():
        try:
            results["r"] = _post(srv.url, {"x": 1}, timeout=HARD)
        except urllib.error.HTTPError as e:
            results["r"] = (e.code, None, dict(e.headers))

    th = threading.Thread(target=client)
    th.start()
    deadline = time.monotonic() + HARD
    while time.monotonic() < deadline and srv.requests.qsize() < 1:
        time.sleep(0.01)
    before = _ctr("serving_drain_shed_total", server="t_cf_stopq")
    srv.stop()
    th.join(timeout=HARD)
    assert not th.is_alive(), "client hung through stop()"
    st, _, hdrs = results["r"]
    assert st == 503
    assert hdrs.get("Retry-After") == "1"
    assert _ctr("serving_drain_shed_total",
                server="t_cf_stopq") >= before + 1


def test_stop_sets_drain_gate_before_shedding():
    """stop() gates new enqueues BEFORE shedding the queue: a handler
    racing the shed must see the drain gate and 503 instead of
    re-parking on the just-emptied queue with no consumer left."""
    srv = WorkerServer("t_cf_stopgate", reply_timeout=HARD)
    assert not srv.draining
    srv.stop()
    assert srv.draining


def test_concurrent_trips_spawn_single_probe_thread():
    """_ensure_probe_thread under a thundering herd: N channels tripping
    in the same instant start exactly ONE probe loop (a second loop
    would double-probe quarantined devices and escape stop()'s join)."""
    ds = DistributedServer("t_cf_oneprobe", n_channels=2,
                           breaker_threshold=1, probe_interval=30.0)
    try:
        barrier = threading.Barrier(8)

        def racer():
            barrier.wait()
            ds._ensure_probe_thread()

        threads = [threading.Thread(target=racer) for _ in range(8)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=HARD)
        alive = [t for t in threading.enumerate()
                 if t.name == "breaker-probe-t_cf_oneprobe" and t.is_alive()]
        assert len(alive) == 1, f"{len(alive)} probe loops running"
        assert ds._probe_thread in alive
    finally:
        ds.stop()


# ---------------------------------------------------------------------------
# Retry-After on the existing shed paths + seedable retry jitter
# ---------------------------------------------------------------------------

def test_retry_after_on_429_queue_shed():
    # scorer deliberately NOT started: with max_queue=0 admission sheds
    # every arrival at enqueue, before any pipeline exists to run
    cs = ContinuousServer("t_cf_429", _linear_pipeline, max_queue=0)
    try:
        with pytest.raises(urllib.error.HTTPError) as ei:
            _post(cs.url, {"x": [1.0]})
        assert ei.value.code == 429
        assert ei.value.headers.get("Retry-After") == "1"
        ei.value.read()
    finally:
        cs.stop()


def test_retry_after_on_504_deadline_shed():
    cs = ContinuousServer("t_cf_504", _linear_pipeline,
                          max_batch=8).start()
    try:
        with pytest.raises(urllib.error.HTTPError) as ei:
            _post(cs.url, {"x": [1.0]},
                  headers={"X-Deadline-Ms": "0.01"})
        assert ei.value.code == 504
        assert ei.value.headers.get("Retry-After") == "1"
        ei.value.read()
    finally:
        cs.stop()


def test_retry_rng_seedable_and_injectable(monkeypatch):
    """SYNAPSEML_RETRY_SEED makes the transient-retry jitter stream
    deterministic; an injected RNG wins over everything; a malformed
    seed degrades to the shared module PRNG."""
    inj = random.Random(5)
    assert _retry_rng(inj) is inj
    monkeypatch.setenv("SYNAPSEML_RETRY_SEED", "123")
    rng_a, rng_b = _retry_rng(), _retry_rng()
    # two independently constructed streams off the same seed draw the
    # same sequence — retry-timing assertions stop depending on luck
    assert rng_a is not rng_b
    want = random.Random(123)
    draws = [want.random() for _ in range(4)]
    assert [rng_a.random() for _ in range(4)] == draws
    assert [rng_b.random() for _ in range(4)] == draws
    monkeypatch.setenv("SYNAPSEML_RETRY_SEED", "not-a-seed")
    assert _retry_rng() is random
    monkeypatch.delenv("SYNAPSEML_RETRY_SEED")
    assert _retry_rng() is random
    # the server ctor threads it through
    cs = ContinuousServer("t_cf_rng", _linear_pipeline, retry_rng=inj)
    try:
        assert cs._retry_rng is inj
    finally:
        cs.stop()
