"""mmlspark.plot analogue: confusion matrix + ROC data computed
in-repo (no sklearn), matplotlib rendering exercised headless
(ref core/src/main/python/mmlspark/plot/plot.py:17-60)."""
import matplotlib

matplotlib.use("Agg")  # headless backend before pyplot import

import numpy as np

from synapseml_tpu.data.table import Table
from synapseml_tpu.utils.plot import confusion_matrix, roc


def test_confusion_matrix_counts_and_render(tmp_path):
    t = Table({"y": np.asarray([0, 0, 1, 1, 2, 2, 2]),
               "pred": np.asarray([0, 1, 1, 1, 2, 0, 2])})
    cm = confusion_matrix(t, "y", "pred", labels=[0, 1, 2], render=False)
    np.testing.assert_array_equal(
        cm, [[1, 1, 0], [0, 2, 0], [1, 0, 2]])
    cmn = confusion_matrix(t, "y", "pred", normalize=True, render=False)
    np.testing.assert_allclose(cmn.sum(axis=1), 1.0)

    import matplotlib.pyplot as plt
    fig, ax = plt.subplots()
    confusion_matrix(t, "y", "pred", ax=ax)
    fig.savefig(tmp_path / "cm.png")  # rendering path actually draws
    plt.close(fig)
    assert (tmp_path / "cm.png").stat().st_size > 0


def test_roc_matches_sklearn_semantics():
    rng = np.random.default_rng(0)
    y = rng.integers(0, 2, 200)
    s = np.clip(y * 0.4 + rng.normal(0.3, 0.25, 200), 0, 1)
    t = Table({"y": y.astype(np.float64), "score": s})
    fpr, tpr, auc = roc(t, "y", "score", render=False)
    assert fpr[0] == 0 and tpr[-1] == 1 and fpr[-1] == 1
    assert np.all(np.diff(fpr) >= 0) and np.all(np.diff(tpr) >= 0)
    # cross-check AUC against the rank-statistic formulation
    pos, neg = s[y == 1], s[y == 0]
    wins = (pos[:, None] > neg[None, :]).sum() \
        + 0.5 * (pos[:, None] == neg[None, :]).sum()
    np.testing.assert_allclose(auc, wins / (len(pos) * len(neg)),
                               atol=1e-9)

    # perfect separation -> AUC 1; reversed -> 0
    t2 = Table({"y": np.asarray([0, 0, 1, 1], np.float64),
                "score": np.asarray([0.1, 0.2, 0.8, 0.9])})
    assert roc(t2, "y", "score", render=False)[2] == 1.0
    t3 = Table({"y": np.asarray([1, 1, 0, 0], np.float64),
                "score": np.asarray([0.1, 0.2, 0.8, 0.9])})
    assert roc(t3, "y", "score", render=False)[2] == 0.0


def test_plot_edge_cases():
    import pytest

    # explicit labels omit a present class: those rows are IGNORED
    # (sklearn semantics), not a KeyError
    t = Table({"y": np.asarray([0, 0, 1, 2]),
               "pred": np.asarray([0, 2, 1, 2])})
    cm = confusion_matrix(t, "y", "pred", labels=[0, 1], render=False)
    np.testing.assert_array_equal(cm, [[1, 0], [0, 1]])

    # single-class labels: ROC is undefined -> loud error, not 0.0
    t2 = Table({"y": np.ones(5, np.float64),
                "score": np.linspace(0, 1, 5)})
    with pytest.raises(ValueError, match="undefined"):
        roc(t2, "y", "score", render=False)
