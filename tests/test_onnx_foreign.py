"""Foreign-exporter ONNX certification.

The committed ``tests/fixtures/*.onnx`` bytes were produced by
**torch.onnx** (see ``tools/make_onnx_fixtures.py``) — a third-party
exporter with its own protobuf serializer and graph idioms: dynamic
batch dims, Shape->Gather->Concat->Reshape chains from ``flatten``,
eval-mode Dropout folded to Identity, traced size arithmetic. The
importer must consume bytes it did not write, the way the reference
hands arbitrary user files to onnxruntime
(ref: deep-learning/src/main/scala/com/microsoft/ml/spark/onnx/ONNXModel.scala:173-193).

Expected outputs in the ``*_io.npz`` files were recorded from the torch
modules at export time, so parity here is against a frozen foreign
runtime, not this repo's own code.
"""
import os

import numpy as np
import pytest

from synapseml_tpu.onnx import ONNXModel, import_model
from synapseml_tpu.data.table import Table

FIXTURES = os.path.join(os.path.dirname(__file__), "fixtures")


def _load(name):
    g = import_model(os.path.join(FIXTURES, f"{name}.onnx"))
    io = np.load(os.path.join(FIXTURES, f"{name}_io.npz"))
    return g, io


def test_torch_cnn_fixture_parity():
    """Conv/BN/pool/dropout/flatten/log-softmax graph exported with a
    dynamic batch axis: committed bytes -> imported -> bitwise-close to
    the torch outputs recorded at export time."""
    g, io = _load("torch_cnn")
    got = np.asarray(g.apply(g.params, io["input"])[0])
    np.testing.assert_allclose(got, io["expected"], atol=1e-5, rtol=1e-5)


def test_torch_cnn_fixture_dynamic_batch():
    """The exported batch dim is symbolic ('batch'); the imported graph
    must run at batch sizes never seen at export (the Shape-chain
    Reshape resolves per trace)."""
    g, io = _load("torch_cnn")
    x = io["input"]
    x5 = np.concatenate([x, x[:2]], axis=0)          # batch 5
    got5 = np.asarray(g.apply(g.params, x5)[0])
    np.testing.assert_allclose(got5[:3], io["expected"], atol=1e-5,
                               rtol=1e-5)
    got1 = np.asarray(g.apply(g.params, x[:1])[0])   # batch 1
    np.testing.assert_allclose(got1, io["expected"][:1], atol=1e-5,
                               rtol=1e-5)


def test_torch_gru_fixture_parity():
    """Bidirectional-GRU sequence model (Embedding Gather + ONNX GRU +
    Shape/Gather/Slice final-step indexing)."""
    g, io = _load("torch_gru")
    got = np.asarray(g.apply(g.params, io["input"])[0])
    np.testing.assert_allclose(got, io["expected"], atol=1e-5, rtol=1e-5)


def test_torch_fixture_through_onnx_model_transformer():
    """The user path: ONNXModel scoring a foreign file end-to-end over a
    Table, argmax post-column included."""
    path = os.path.join(FIXTURES, "torch_cnn.onnx")
    io = np.load(os.path.join(FIXTURES, "torch_cnn_io.npz"))
    m = ONNXModel(model_path=path, feed_dict={"input": "images"},
                  argmax_output_col="prediction")
    out = m.transform(Table({"images": io["input"]}))
    want = io["expected"].argmax(-1)
    np.testing.assert_array_equal(np.asarray(out["prediction"]), want)


def test_fixture_bytes_are_foreign():
    """Guard the provenance claim: the committed files carry torch's
    producer tag, not this repo's builder."""
    from synapseml_tpu.onnx import proto

    for name in ("torch_cnn", "torch_gru", "torch_transformer",
                 "torch_quant_cnn"):
        with open(os.path.join(FIXTURES, f"{name}.onnx"), "rb") as fh:
            m = proto.decode("ModelProto", fh.read())
        assert m.producer_name == "pytorch", m.producer_name


def test_torch_transformer_fixture_parity():
    """nn.TransformerEncoder export: MultiheadAttention's packed-QKV
    slicing rides the densest shape-arithmetic idiom torch emits
    (Shape -> Mod/Gather/Concat -> Reshape/Slice). Parity against the
    frozen torch outputs, eagerly and under jit."""
    import jax

    g, io = _load("torch_transformer")
    got = np.asarray(g.apply(g.params, io["input"])[0])
    np.testing.assert_allclose(got, io["expected"], atol=1e-5, rtol=1e-5)
    fn = jax.jit(lambda x: g.apply(g.params, x)[0])
    np.testing.assert_allclose(np.asarray(fn(io["input"])),
                               io["expected"], atol=1e-5, rtol=1e-5)
    # batch axis is dynamic (seq is constant-folded by the exporter)
    x2 = np.concatenate([io["input"]] * 2, axis=0)
    got2 = np.asarray(g.apply(g.params, x2)[0])
    np.testing.assert_allclose(got2[:3], io["expected"], atol=1e-5,
                               rtol=1e-5)


def test_torch_quantized_cnn_fixture_parity():
    """Committed statically-quantized torch export (QDQ idiom, fbgemm
    calibration): the importer's integer/QDQ lowering must reproduce
    torch's own quantized forward within 2 output quantization steps —
    the headroom between fbgemm's int kernels and float-simulated QDQ
    (ref ONNXModel.scala:173-193: the reference scores whatever ORT
    runs, statically-quantized exports included)."""
    gi, io = _load("torch_quant_cnn")
    got = np.asarray(gi.apply(gi.params, io["input"])[0])
    want = io["expected"]
    assert got.shape == want.shape
    tol = 2.0 * float(io["out_scale"])
    assert np.abs(got - want).max() <= tol + 1e-7, (
        np.abs(got - want).max(), tol)
    # overwhelmingly exact at the quantization grid: >95% of outputs
    # within one step
    assert (np.abs(got - want) <= float(io["out_scale"]) + 1e-7).mean() \
        > 0.95


def test_torch_kv_decoder_fixture_parity():
    """Committed torch export of a decoder with EXPLICIT KV-cache I/O
    (ids, past_key, past_value) -> (logits, present_key, present_value):
    the ORT-GenAI / HF shape where the cache crosses the graph boundary.
    Exercises Concat on a dynamic past axis, GQA repeat_interleave, and
    the Range/Less/Where causal-mask idiom over a traced past offset."""
    gi, io = _load("torch_kv_decoder")
    logits, pk, pv = gi.apply(gi.params, io["input_ids"], io["past_key"],
                              io["past_value"])
    np.testing.assert_allclose(np.asarray(logits), io["logits"],
                               atol=1e-5, rtol=1e-5)
    np.testing.assert_allclose(np.asarray(pk), io["present_key"],
                               atol=1e-5, rtol=1e-5)
    np.testing.assert_allclose(np.asarray(pv), io["present_value"],
                               atol=1e-5, rtol=1e-5)


def test_torch_kv_decoder_incremental_round_trip():
    """KV concat must be position-exact: feeding the prompt one token at
    a time (each step consuming the previous step's present_* as past_*)
    has to reproduce the from-scratch full-sequence logits at EVERY
    position — the correctness contract autoregressive decode rests on.
    Also runs a mixed-chunk schedule (3+1+5+3) to cover multi-token
    chunked prefill against the same reference."""
    gi, io = _load("torch_kv_decoder")
    full_ids = io["full_ids"]
    want = io["full_logits"]
    L = int(full_ids.shape[1])
    empty = np.zeros((1, 2, 0, 8), np.float32)

    # from-scratch full-sequence run matches the torch reference
    fl, _, _ = gi.apply(gi.params, full_ids, empty, empty)
    np.testing.assert_allclose(np.asarray(fl), want, atol=1e-5, rtol=1e-5)

    # single-token incremental decode
    k, v = empty, empty
    rows = []
    for t in range(L):
        lo, k, v = gi.apply(gi.params, full_ids[:, t:t + 1],
                            np.asarray(k), np.asarray(v))
        rows.append(np.asarray(lo)[:, 0])
        assert np.asarray(k).shape[2] == t + 1
    np.testing.assert_allclose(np.stack(rows, axis=1), want,
                               atol=1e-4, rtol=1e-4)

    # mixed chunk sizes (chunked prefill): same positions, same logits
    k, v = empty, empty
    chunks, t = [3, 1, 5, 3], 0
    rows = []
    for n in chunks:
        lo, k, v = gi.apply(gi.params, full_ids[:, t:t + n],
                            np.asarray(k), np.asarray(v))
        rows.append(np.asarray(lo))
        t += n
    np.testing.assert_allclose(np.concatenate(rows, axis=1), want,
                               atol=1e-4, rtol=1e-4)
