"""Tests for the synlint analyzer itself (tools/analysis).

Corpus layout: tests/fixtures/analysis/{bad,good}/<rule>.py — every bad
fixture must trip its rule (CLI exit 1), every good twin must be clean
(exit 0). Plus: baseline round-trip, suppression-comment handling,
fingerprint stability, and the repo-level gate the CI job enforces.
"""
import json
import os
import subprocess
import sys

import pytest

from tools.analysis.engine import analyze_paths
from tools.analysis.findings import (Finding, load_baseline, split_new,
                                     write_baseline)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
FIXTURES = os.path.join(REPO, "tests", "fixtures", "analysis")

RULE_FIXTURES = ["jh001", "jh002", "jh003", "jh004", "jh005",
                 "cc001", "cc002", "cc003",
                 "rl001", "rl002", "rl003", "eh001", "eh002",
                 "ev001", "ev003", "pl001", "ds001"]


def _cli(*args):
    return subprocess.run(
        [sys.executable, "-m", "tools.analysis", *args],
        capture_output=True, text=True, cwd=REPO)


def _analyze(path):
    return analyze_paths([path], root=REPO)


# -- fixture corpus: one good/bad pair per rule -------------------------

@pytest.mark.parametrize("name", RULE_FIXTURES)
def test_bad_fixture_trips_its_rule(name):
    findings = _analyze(os.path.join(FIXTURES, "bad", f"{name}.py"))
    rules = {f.rule for f in findings}
    assert name.upper() in rules, (name, findings)


@pytest.mark.parametrize("name", RULE_FIXTURES)
def test_good_fixture_is_clean(name):
    findings = _analyze(os.path.join(FIXTURES, "good", f"{name}.py"))
    assert findings == [], [f.render() for f in findings]


@pytest.mark.parametrize("name", RULE_FIXTURES[:2] + ["cc001"])
def test_cli_exit_codes_per_fixture(name):
    assert _cli(os.path.join("tests", "fixtures", "analysis", "bad",
                             f"{name}.py")).returncode == 1
    assert _cli(os.path.join("tests", "fixtures", "analysis", "good",
                             f"{name}.py")).returncode == 0


# -- suppression syntax -------------------------------------------------

def test_suppression_same_line_and_previous_line():
    findings = _analyze(os.path.join(FIXTURES, "bad", "suppressed.py"))
    assert len(findings) == 1  # 3 violations, 2 suppressed
    assert findings[0].rule == "JH001" and findings[0].line == 8


def test_suppression_wrong_rule_id_does_not_suppress(tmp_path):
    src = ("def _dispatch(self, out):\n"
           "    out.block_until_ready()  # synlint: disable=CC001\n")
    p = tmp_path / "wrong_id.py"
    p.write_text(src)
    findings = analyze_paths([str(p)], root=str(tmp_path))
    assert [f.rule for f in findings] == ["JH001"]


def test_directive_inside_string_literal_does_not_suppress(tmp_path):
    src = ('def _dispatch(self, out):\n'
           '    hint = "# synlint: disable"; out.block_until_ready()\n')
    p = tmp_path / "strlit.py"
    p.write_text(src)
    findings = analyze_paths([str(p)], root=str(tmp_path))
    assert [f.rule for f in findings] == ["JH001"]


def test_missing_path_raises_instead_of_clean_scan(tmp_path):
    with pytest.raises(FileNotFoundError):
        analyze_paths([str(tmp_path / "nope")], root=str(tmp_path))


def test_blanket_disable_suppresses_all(tmp_path):
    src = ("def _dispatch(self, out):\n"
           "    out.block_until_ready()  # synlint: disable\n")
    p = tmp_path / "blanket.py"
    p.write_text(src)
    assert analyze_paths([str(p)], root=str(tmp_path)) == []


# -- baseline round-trip ------------------------------------------------

def test_baseline_round_trip(tmp_path):
    findings = _analyze(os.path.join(FIXTURES, "bad", "cc003.py"))
    assert findings
    bl = tmp_path / "baseline.json"
    write_baseline(str(bl), findings)
    new, matched = split_new(findings, load_baseline(str(bl)))
    assert new == [] and matched == len(findings)


def test_baseline_covers_counts_not_extras(tmp_path):
    f = Finding("CC001", "m.py", 3, 0, "C.m", "msg")
    bl = tmp_path / "baseline.json"
    write_baseline(str(bl), [f, f])  # two identical findings baselined
    three = [Finding("CC001", "m.py", 3, 0, "C.m", "msg")] * 3
    new, matched = split_new(three, load_baseline(str(bl)))
    assert matched == 2 and len(new) == 1  # the third is NEW


def test_fingerprint_survives_line_shifts():
    a = Finding("JH001", "m.py", 10, 4, "C.m", "msg")
    b = Finding("JH001", "m.py", 99, 0, "C.m", "msg")
    assert a.fingerprint() == b.fingerprint()
    assert a.fingerprint() != Finding("JH002", "m.py", 10, 4,
                                      "C.m", "msg").fingerprint()


def test_cli_fail_on_new_with_baseline(tmp_path):
    target = os.path.join("tests", "fixtures", "analysis", "bad",
                          "cc002.py")
    bl = tmp_path / "bl.json"
    assert _cli(target, "--baseline", str(bl),
                "--write-baseline").returncode == 0
    assert _cli(target, "--baseline", str(bl),
                "--fail-on-new").returncode == 0
    # without --fail-on-new the baselined findings still gate nothing new
    res = _cli(target, "--baseline", str(bl), "--fail-on-new", "--json")
    payload = json.loads(res.stdout)
    assert payload["findings_new"] == 0 and payload["findings_total"] > 0


def test_unparseable_file_reports_syn000(tmp_path):
    p = tmp_path / "broken.py"
    p.write_text("def broken(:\n")
    findings = analyze_paths([str(p)], root=str(tmp_path))
    assert [f.rule for f in findings] == ["SYN000"]


# -- the repo gate CI enforces ------------------------------------------

def test_repo_is_clean_under_committed_baseline():
    res = _cli("synapseml_tpu", "tools", "bench.py", "--fail-on-new")
    assert res.returncode == 0, res.stdout + res.stderr


def test_stale_baseline_entry_fails_fail_on_new(tmp_path):
    """A baseline entry nothing produces anymore is rot: the gate must
    demand --prune-baseline instead of silently carrying it."""
    target = os.path.join("tests", "fixtures", "analysis", "bad",
                          "jh001.py")
    bl = tmp_path / "bl.json"
    assert _cli(target, "--baseline", str(bl),
                "--write-baseline").returncode == 0
    payload = json.loads(bl.read_text())
    payload["findings"].append({
        "fingerprint": "00000000deadbeef", "rule": "JH001",
        "path": target.replace(os.sep, "/"), "context": "gone",
        "message": "rotted", "count": 1})
    bl.write_text(json.dumps(payload))
    res = _cli(target, "--baseline", str(bl), "--fail-on-new")
    assert res.returncode == 1 and "stale baseline entry" in res.stderr


def test_prune_baseline_drops_only_rot(tmp_path):
    target = os.path.join("tests", "fixtures", "analysis", "bad",
                          "cc002.py")
    bl = tmp_path / "bl.json"
    _cli(target, "--baseline", str(bl), "--write-baseline")
    payload = json.loads(bl.read_text())
    live = len(payload["findings"])
    payload["findings"].append({
        "fingerprint": "00000000deadbeef", "rule": "CC001",
        "path": "synapseml_tpu/gone.py", "context": "gone",
        "message": "rotted", "count": 1})
    bl.write_text(json.dumps(payload))
    res = _cli(target, "--baseline", str(bl), "--prune-baseline")
    assert res.returncode == 0 and "pruned 1 stale" in res.stdout
    kept = json.loads(bl.read_text())["findings"]
    assert len(kept) == live
    assert _cli(target, "--baseline", str(bl),
                "--fail-on-new").returncode == 0


# -- v2: whole-program analysis -----------------------------------------

def test_crossmod_lock_cycle_needs_whole_program():
    """The two-file lock-order cycle: each half is clean alone (one
    lock per function; the second acquisition hides behind a call into
    the other module) — only the cross-module pass flags it."""
    a = os.path.join(FIXTURES, "bad", "crossmod_a.py")
    b = os.path.join(FIXTURES, "bad", "crossmod_b.py")
    assert [f.rule for f in _analyze(a)] == []
    assert [f.rule for f in _analyze(b)] == []
    both = analyze_paths([a, b], root=REPO)
    assert "CC002" in {f.rule for f in both}, [f.render() for f in both]
    rendered = " ".join(f.render() for f in both)
    assert "crossmod_a:LOCK_A" in rendered and \
        "crossmod_b:LOCK_B" in rendered


def test_crossmod_good_twins_are_clean():
    both = analyze_paths(
        [os.path.join(FIXTURES, "good", "crossmod_a.py"),
         os.path.join(FIXTURES, "good", "crossmod_b.py")], root=REPO)
    assert both == [], [f.render() for f in both]


def test_pl002_kernel_without_parity_test(tmp_path):
    """PL002 is repo-relative (it walks tests/), so exercise it in a
    scratch repo: an undocumented kernel trips, one named next to
    'interpret' in a test file is clean."""
    kern = ("_VMEM_BUDGET_BYTES = 1 << 24\n"
            "def warp_rows(x):\n"
            "    from jax.experimental import pallas as pl\n"
            "    if x.size > _VMEM_BUDGET_BYTES:\n"
            "        raise ValueError('budget')\n"
            "    return pl.pallas_call(lambda i, o: None,\n"
            "                          out_shape=None)(x)\n")
    (tmp_path / "kernels.py").write_text(kern)
    os.makedirs(tmp_path / "tests")
    (tmp_path / "tests" / "test_k.py").write_text("")
    findings = analyze_paths([str(tmp_path / "kernels.py")],
                             root=str(tmp_path))
    assert [f.rule for f in findings] == ["PL002"]
    (tmp_path / "tests" / "test_k.py").write_text(
        "def test_parity():\n"
        "    assert warp_rows is not None  # interpret=True parity\n")
    findings = analyze_paths([str(tmp_path / "kernels.py")],
                             root=str(tmp_path))
    assert findings == [], [f.render() for f in findings]


# -- v2: suppression attachment -----------------------------------------

def test_suppression_on_decorated_def(tmp_path):
    """A directive on the decorator line must cover findings anchored
    at the ``def`` line — decorators and def are ONE statement. PL002
    anchors at the def line, so a decorated kernel is the regression:
    v1 attached the directive to the decorator line only and the
    suppression silently failed."""
    kern = ("import functools\n"
            "_VMEM_BUDGET_BYTES = 1 << 24\n"
            "@functools.lru_cache()  # synlint: disable=PL002\n"
            "def warp_rows(x):\n"
            "    from jax.experimental import pallas as pl\n"
            "    assert x.size < _VMEM_BUDGET_BYTES\n"
            "    return pl.pallas_call(lambda i, o: None,\n"
            "                          out_shape=None)(x)\n")
    p = tmp_path / "kernels.py"
    p.write_text(kern)
    os.makedirs(tmp_path / "tests")
    (tmp_path / "tests" / "test_k.py").write_text("")
    assert analyze_paths([str(p)], root=str(tmp_path)) == []
    # same module without the directive proves the rule does fire
    p.write_text(kern.replace("  # synlint: disable=PL002", ""))
    assert [f.rule for f in analyze_paths([str(p)],
                                          root=str(tmp_path))] == ["PL002"]


def test_suppression_comment_block(tmp_path):
    """A directive opening a multi-line comment block attaches through
    the block to the first code line below it."""
    src = ("def _dispatch(self, out):\n"
           "    # synlint: disable=JH001 - deliberate sync point,\n"
           "    # rationale continues on a second comment line\n"
           "    return out.block_until_ready()\n")
    p = tmp_path / "block.py"
    p.write_text(src)
    assert analyze_paths([str(p)], root=str(tmp_path)) == []


# -- v2: result cache ---------------------------------------------------

def test_cache_second_run_hits(tmp_path):
    target = os.path.join("tests", "fixtures", "analysis", "bad",
                          "jh001.py")
    cache = tmp_path / "cache.json"
    cold = json.loads(_cli(target, "--no-baseline", "--cache",
                           str(cache), "--json").stdout)
    warm = json.loads(_cli(target, "--no-baseline", "--cache",
                           str(cache), "--json").stdout)
    assert cold["cache"]["cache_hits"] == 0
    assert warm["cache"]["cache_hits"] == warm["cache"]["files"] > 0
    assert cold["findings_total"] == warm["findings_total"] > 0
    # cached and fresh runs must render identical findings
    assert cold["findings"] == warm["findings"]


def test_cache_invalidated_by_content_change(tmp_path):
    src = tmp_path / "m.py"
    src.write_text("def _dispatch(self, out):\n"
                   "    out.block_until_ready()\n")
    cache = tmp_path / "cache.json"
    from tools.analysis.cache import ResultCache
    from tools.analysis.engine import analyze_program

    _f1, _p, s1 = analyze_program([str(src)], root=str(tmp_path),
                                  cache=ResultCache(str(cache)))
    src.write_text("def fetch(self, out):\n"
                   "    return out\n")
    c2 = ResultCache(str(cache))
    f2, _p, s2 = analyze_program([str(src)], root=str(tmp_path),
                                 cache=c2)
    assert s1["cache_misses"] == 1 and s2["cache_misses"] == 1
    assert f2 == []


# -- v2: --changed-only -------------------------------------------------

def test_changed_only_reports_only_diffed_files(tmp_path):
    bad = ("def _dispatch(self, out):\n"
           "    out.block_until_ready()\n")
    (tmp_path / "a.py").write_text(bad)
    (tmp_path / "b.py").write_text(bad)

    def git(*args):
        subprocess.run(["git", "-c", "user.email=t@t", "-c",
                        "user.name=t", *args], cwd=tmp_path, check=True,
                       capture_output=True)

    git("init", "-q")
    git("add", ".")
    git("commit", "-qm", "seed")
    (tmp_path / "b.py").write_text(bad + "\n# touched\n")
    env = dict(os.environ, PYTHONPATH=REPO)
    res = subprocess.run(
        [sys.executable, "-m", "tools.analysis", "a.py", "b.py",
         "--no-baseline", "--changed-only", "--json"],
        capture_output=True, text=True, cwd=tmp_path, env=env)
    payload = json.loads(res.stdout)
    assert payload["findings_total"] == 2  # both analyzed...
    assert {f["path"] for f in payload["findings"]} == {"b.py"}  # one shown


# -- v2: SARIF ----------------------------------------------------------

def test_sarif_output(tmp_path):
    target = os.path.join("tests", "fixtures", "analysis", "bad",
                          "cc003.py")
    out = tmp_path / "synlint.sarif"
    res = _cli(target, "--no-baseline", "--sarif", str(out))
    assert res.returncode == 1
    sarif = json.loads(out.read_text())
    assert sarif["version"] == "2.1.0"
    results = sarif["runs"][0]["results"]
    assert results and all(r["ruleId"].startswith("CC")
                           for r in results)
    loc = results[0]["locations"][0]["physicalLocation"]
    assert loc["artifactLocation"]["uri"].endswith("cc003.py")
    assert results[0]["partialFingerprints"]["synlint/v1"]
    rule_ids = {r["id"] for r in
                sarif["runs"][0]["tool"]["driver"]["rules"]}
    assert {r["ruleId"] for r in results} <= rule_ids


# -- v2: knob table -----------------------------------------------------

def test_knob_table_preserves_descriptions(tmp_path):
    from tools.analysis.engine import analyze_program
    from tools.analysis.rules_env import render_knob_table

    src = tmp_path / "knobby.py"
    src.write_text("import os\n"
                   "X = os.environ.get('SYNAPSEML_FIXTURE_KNOB', '1')\n")
    _f, prog, _s = analyze_program([str(src)], root=str(tmp_path))
    first = render_knob_table(prog)
    assert "SYNAPSEML_FIXTURE_KNOB" in first and "'1'" in first
    edited = first.replace(
        "| `synapseml_tpu", "| `synapseml_tpu")  # no-op, keep layout
    edited = "\n".join(
        line.rstrip()[:-1] + "hand-written words |"
        if "SYNAPSEML_FIXTURE_KNOB" in line else line
        for line in edited.splitlines())
    again = render_knob_table(prog, existing_text=edited)
    assert "hand-written words" in again


def test_repo_knob_table_is_current():
    """docs/knobs.md must match what --write-knob-table would emit —
    the EV-pack side of the one drift gate."""
    from tools.analysis.engine import analyze_program
    from tools.analysis.rules_env import render_knob_table

    doc = os.path.join(REPO, "docs", "knobs.md")
    with open(doc, encoding="utf-8") as fh:
        committed = fh.read()
    _f, prog, _s = analyze_program(
        [os.path.join(REPO, p) for p in
         ("synapseml_tpu", "tools", "bench.py")], root=REPO)
    assert render_knob_table(prog, existing_text=committed) == committed


def test_executor_serving_fixed_violations_not_baselined():
    """The PR-5 fixes must be real fixes: runtime/ and io/ produce no
    CC001 findings for the fields the analyzer surfaced (they are
    guarded now, not baselined away)."""
    baseline = load_baseline(os.path.join(REPO, "tools", "analysis",
                                          "baseline.json"))
    findings = analyze_paths(
        [os.path.join(REPO, "synapseml_tpu", "runtime"),
         os.path.join(REPO, "synapseml_tpu", "io")], root=REPO)
    fixed_fields = ("_jits", "_donate_masks", "_bound_rr", "_rr_next",
                    "_aot", "_cache", "errors", "_dist_owner")
    for f in findings:
        assert not any(field in f.message for field in fixed_fields), \
            f.render()
