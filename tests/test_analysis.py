"""Tests for the synlint analyzer itself (tools/analysis).

Corpus layout: tests/fixtures/analysis/{bad,good}/<rule>.py — every bad
fixture must trip its rule (CLI exit 1), every good twin must be clean
(exit 0). Plus: baseline round-trip, suppression-comment handling,
fingerprint stability, and the repo-level gate the CI job enforces.
"""
import json
import os
import subprocess
import sys

import pytest

from tools.analysis.engine import analyze_paths
from tools.analysis.findings import (Finding, load_baseline, split_new,
                                     write_baseline)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
FIXTURES = os.path.join(REPO, "tests", "fixtures", "analysis")

RULE_FIXTURES = ["jh001", "jh002", "jh003", "jh004", "jh005",
                 "cc001", "cc002", "cc003"]


def _cli(*args):
    return subprocess.run(
        [sys.executable, "-m", "tools.analysis", *args],
        capture_output=True, text=True, cwd=REPO)


def _analyze(path):
    return analyze_paths([path], root=REPO)


# -- fixture corpus: one good/bad pair per rule -------------------------

@pytest.mark.parametrize("name", RULE_FIXTURES)
def test_bad_fixture_trips_its_rule(name):
    findings = _analyze(os.path.join(FIXTURES, "bad", f"{name}.py"))
    rules = {f.rule for f in findings}
    assert name.upper() in rules, (name, findings)


@pytest.mark.parametrize("name", RULE_FIXTURES)
def test_good_fixture_is_clean(name):
    findings = _analyze(os.path.join(FIXTURES, "good", f"{name}.py"))
    assert findings == [], [f.render() for f in findings]


@pytest.mark.parametrize("name", RULE_FIXTURES[:2] + ["cc001"])
def test_cli_exit_codes_per_fixture(name):
    assert _cli(os.path.join("tests", "fixtures", "analysis", "bad",
                             f"{name}.py")).returncode == 1
    assert _cli(os.path.join("tests", "fixtures", "analysis", "good",
                             f"{name}.py")).returncode == 0


# -- suppression syntax -------------------------------------------------

def test_suppression_same_line_and_previous_line():
    findings = _analyze(os.path.join(FIXTURES, "bad", "suppressed.py"))
    assert len(findings) == 1  # 3 violations, 2 suppressed
    assert findings[0].rule == "JH001" and findings[0].line == 8


def test_suppression_wrong_rule_id_does_not_suppress(tmp_path):
    src = ("def _dispatch(self, out):\n"
           "    out.block_until_ready()  # synlint: disable=CC001\n")
    p = tmp_path / "wrong_id.py"
    p.write_text(src)
    findings = analyze_paths([str(p)], root=str(tmp_path))
    assert [f.rule for f in findings] == ["JH001"]


def test_directive_inside_string_literal_does_not_suppress(tmp_path):
    src = ('def _dispatch(self, out):\n'
           '    hint = "# synlint: disable"; out.block_until_ready()\n')
    p = tmp_path / "strlit.py"
    p.write_text(src)
    findings = analyze_paths([str(p)], root=str(tmp_path))
    assert [f.rule for f in findings] == ["JH001"]


def test_missing_path_raises_instead_of_clean_scan(tmp_path):
    with pytest.raises(FileNotFoundError):
        analyze_paths([str(tmp_path / "nope")], root=str(tmp_path))


def test_blanket_disable_suppresses_all(tmp_path):
    src = ("def _dispatch(self, out):\n"
           "    out.block_until_ready()  # synlint: disable\n")
    p = tmp_path / "blanket.py"
    p.write_text(src)
    assert analyze_paths([str(p)], root=str(tmp_path)) == []


# -- baseline round-trip ------------------------------------------------

def test_baseline_round_trip(tmp_path):
    findings = _analyze(os.path.join(FIXTURES, "bad", "cc003.py"))
    assert findings
    bl = tmp_path / "baseline.json"
    write_baseline(str(bl), findings)
    new, matched = split_new(findings, load_baseline(str(bl)))
    assert new == [] and matched == len(findings)


def test_baseline_covers_counts_not_extras(tmp_path):
    f = Finding("CC001", "m.py", 3, 0, "C.m", "msg")
    bl = tmp_path / "baseline.json"
    write_baseline(str(bl), [f, f])  # two identical findings baselined
    three = [Finding("CC001", "m.py", 3, 0, "C.m", "msg")] * 3
    new, matched = split_new(three, load_baseline(str(bl)))
    assert matched == 2 and len(new) == 1  # the third is NEW


def test_fingerprint_survives_line_shifts():
    a = Finding("JH001", "m.py", 10, 4, "C.m", "msg")
    b = Finding("JH001", "m.py", 99, 0, "C.m", "msg")
    assert a.fingerprint() == b.fingerprint()
    assert a.fingerprint() != Finding("JH002", "m.py", 10, 4,
                                      "C.m", "msg").fingerprint()


def test_cli_fail_on_new_with_baseline(tmp_path):
    target = os.path.join("tests", "fixtures", "analysis", "bad",
                          "cc002.py")
    bl = tmp_path / "bl.json"
    assert _cli(target, "--baseline", str(bl),
                "--write-baseline").returncode == 0
    assert _cli(target, "--baseline", str(bl),
                "--fail-on-new").returncode == 0
    # without --fail-on-new the baselined findings still gate nothing new
    res = _cli(target, "--baseline", str(bl), "--fail-on-new", "--json")
    payload = json.loads(res.stdout)
    assert payload["findings_new"] == 0 and payload["findings_total"] > 0


def test_unparseable_file_reports_syn000(tmp_path):
    p = tmp_path / "broken.py"
    p.write_text("def broken(:\n")
    findings = analyze_paths([str(p)], root=str(tmp_path))
    assert [f.rule for f in findings] == ["SYN000"]


# -- the repo gate CI enforces ------------------------------------------

def test_repo_is_clean_under_committed_baseline():
    res = _cli("synapseml_tpu", "tools", "bench.py", "--fail-on-new")
    assert res.returncode == 0, res.stdout + res.stderr


def test_executor_serving_fixed_violations_not_baselined():
    """The PR-5 fixes must be real fixes: runtime/ and io/ produce no
    CC001 findings for the fields the analyzer surfaced (they are
    guarded now, not baselined away)."""
    baseline = load_baseline(os.path.join(REPO, "tools", "analysis",
                                          "baseline.json"))
    findings = analyze_paths(
        [os.path.join(REPO, "synapseml_tpu", "runtime"),
         os.path.join(REPO, "synapseml_tpu", "io")], root=REPO)
    fixed_fields = ("_jits", "_donate_masks", "_bound_rr", "_rr_next",
                    "_aot", "_cache", "errors", "_dist_owner")
    for f in findings:
        assert not any(field in f.message for field in fixed_fields), \
            f.render()
