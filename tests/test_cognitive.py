"""Cognitive services tests against a local Azure-shaped mock service.

(ref suites: cognitive/src/test/scala/.../split1..split3 — the reference
hits live services with vault keys; this environment has no egress, so a
mock speaking the same REST shapes stands in.)
"""
import json
import http.server
import threading

import numpy as np
import pytest

from synapseml_tpu.cognitive import (AnalyzeImage, AzureSearchWriter,
                                     BingImageSearch, DetectEntireSeries,
                                     DetectLastAnomaly, KeyPhraseExtractor,
                                     LanguageDetector, NER, OCR,
                                     SpeechToText, TextSentiment, Translate)
from synapseml_tpu.core.pipeline import PipelineStage
from synapseml_tpu.data.table import Table


class _AzureMock(http.server.BaseHTTPRequestHandler):
    protocol_version = "HTTP/1.1"
    seen = []

    def log_message(self, *a):
        pass

    def _reply(self, code, obj):
        body = json.dumps(obj).encode()
        self.send_response(code)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def do_GET(self):
        if self.path.startswith("/bing/images/search"):
            self._reply(200, {"value": [{"name": "img1"}, {"name": "img2"}]})
        else:
            self._reply(404, {})

    def do_POST(self):
        body = self.rfile.read(int(self.headers.get("Content-Length", 0)))
        key = self.headers.get("Ocp-Apim-Subscription-Key")
        _AzureMock.seen.append((self.path, key,
                                self.headers.get("Content-Type")))
        if key == "bad-key":
            self._reply(401, {"error": {"code": "401",
                                        "message": "Access denied"}})
            return
        path = self.path
        if path.startswith("/text/analytics/v3.1/sentiment"):
            docs = json.loads(body)["documents"]
            self._reply(200, {"documents": [
                {"id": d["id"],
                 "sentiment": "positive" if "good" in d["text"] else "negative",
                 "confidenceScores": {"positive": 0.9, "negative": 0.1}}
                for d in docs], "errors": []})
        elif path.startswith("/text/analytics/v3.1/entities"):
            docs = json.loads(body)["documents"]
            self._reply(200, {"documents": [
                {"id": d["id"], "entities": [
                    {"text": w, "category": "Noun"}
                    for w in d["text"].split() if w.istitle()]}
                for d in docs], "errors": []})
        elif path.startswith("/text/analytics/v3.1/keyPhrases"):
            docs = json.loads(body)["documents"]
            self._reply(200, {"documents": [
                {"id": d["id"], "keyPhrases": d["text"].split()[:2]}
                for d in docs], "errors": []})
        elif path.startswith("/text/analytics/v3.1/languages"):
            docs = json.loads(body)["documents"]
            self._reply(200, {"documents": [
                {"id": d["id"], "detectedLanguage": {
                    "name": "English", "iso6391Name": "en",
                    "confidenceScore": 0.99}}
                for d in docs], "errors": []})
        elif path.startswith("/anomalydetector/v1.0/timeseries/last/detect"):
            series = json.loads(body)["series"]
            last = series[-1]["value"]
            self._reply(200, {"isAnomaly": last > 100,
                              "expectedValue": 10.0,
                              "upperMargin": 5.0, "lowerMargin": 5.0})
        elif path.startswith("/anomalydetector/v1.0/timeseries/entire/detect"):
            series = json.loads(body)["series"]
            self._reply(200, {
                "isAnomaly": [pt["value"] > 100 for pt in series],
                "expectedValues": [10.0] * len(series),
                "upperMargins": [5.0] * len(series),
                "lowerMargins": [5.0] * len(series)})
        elif path.startswith("/vision/v3.2/analyze"):
            self._reply(200, {"categories": [{"name": "outdoor"}],
                              "tags": [{"name": "grass"}],
                              "description": {"captions": [
                                  {"text": "a field"}]}})
        elif path.startswith("/vision/v3.2/ocr"):
            self._reply(200, {"regions": [{"lines": [{"words": [
                {"text": "HELLO"}, {"text": "WORLD"}]}]}]})
        elif path.startswith("/translator/translate"):
            texts = json.loads(body)
            self._reply(200, [
                {"translations": [{"text": t["text"][::-1], "to": "fr"}]}
                for t in texts])
        elif path.startswith("/speech"):
            self._reply(200, {"RecognitionStatus": "Success",
                              "DisplayText": f"heard {len(body)} bytes"})
        elif path.startswith("/search/indexes"):
            docs = json.loads(body)["value"]
            self._reply(200, {"value": [
                {"key": str(i), "status": True, "statusCode": 201}
                for i in range(len(docs))]})
        else:
            self._reply(404, {"error": "no such endpoint"})


@pytest.fixture(scope="module")
def mock():
    httpd = http.server.ThreadingHTTPServer(("127.0.0.1", 0), _AzureMock)
    httpd.daemon_threads = True
    threading.Thread(target=httpd.serve_forever, daemon=True).start()
    yield f"http://127.0.0.1:{httpd.server_address[1]}"
    httpd.shutdown()
    httpd.server_close()


def _texts():
    return Table({"text": np.array(
        ["good day Alice", "bad turn Bob", "good good"], dtype=object)})


def test_sentiment_batched_and_keyed(mock):
    s = TextSentiment(url=f"{mock}/text/analytics/v3.1/sentiment",
                      batch_size=2, output_col="sentiment")
    s.set_service_value("subscription_key", "k123")
    s.set_service_col("text", "text")
    out = s.transform(_texts())
    sents = [v["sentiment"] for v in out["sentiment"]]
    assert sents == ["positive", "negative", "positive"]
    assert all(e is None for e in out["errors"])
    # the key rode the header; 2 batches for 3 docs at batch_size=2
    keys = {k for _, k, _ in _AzureMock.seen if k}
    assert "k123" in keys


def test_ner_and_keyphrases_and_language(mock):
    t = _texts()
    ner = NER(url=f"{mock}/text/analytics/v3.1/entities", output_col="ents")
    ner.set_service_col("text", "text")
    out = ner.transform(t)
    assert out["ents"][0][0]["text"] == "Alice"

    kp = KeyPhraseExtractor(url=f"{mock}/text/analytics/v3.1/keyPhrases",
                            output_col="kp")
    kp.set_service_col("text", "text")
    assert list(kp.transform(t)["kp"][0]) == ["good", "day"]

    ld = LanguageDetector(url=f"{mock}/text/analytics/v3.1/languages",
                          output_col="lang")
    ld.set_service_col("text", "text")
    assert ld.transform(t)["lang"][0]["iso6391Name"] == "en"


def test_anomaly_detector(mock):
    series = np.empty(2, dtype=object)
    series[0] = [("2024-01-0%d" % (i + 1), float(i)) for i in range(5)]
    series[1] = [("2024-01-0%d" % (i + 1), 5000.0 if i == 4 else float(i))
                 for i in range(5)]
    t = Table({"series": series})
    last = DetectLastAnomaly(
        url=f"{mock}/anomalydetector/v1.0/timeseries/last/detect",
        output_col="anom")
    last.set_service_col("series", "series")
    out = last.transform(t)
    assert out["anom"][0]["isAnomaly"] is False
    assert out["anom"][1]["isAnomaly"] is True

    entire = DetectEntireSeries(
        url=f"{mock}/anomalydetector/v1.0/timeseries/entire/detect",
        output_col="anom")
    entire.set_service_col("series", "series")
    out = entire.transform(t)
    assert out["anom"][1]["isAnomaly"][4] is True


def test_vision_and_ocr_bytes_and_url(mock):
    t = Table({"img": np.array([b"\x89PNGfakebytes"], dtype=object),
               "url": np.array(["http://x/img.png"], dtype=object)})
    an = AnalyzeImage(url=f"{mock}/vision/v3.2/analyze", output_col="a")
    an.set_service_col("image_bytes", "img")
    out = an.transform(t)
    assert out["a"][0]["categories"][0]["name"] == "outdoor"
    # bytes ride as octet-stream
    assert any(ct == "application/octet-stream"
               for _, _, ct in _AzureMock.seen)

    an2 = AnalyzeImage(url=f"{mock}/vision/v3.2/analyze", output_col="a")
    an2.set_service_col("image_url", "url")
    assert an2.transform(t)["a"][0]["tags"][0]["name"] == "grass"

    ocr = OCR(url=f"{mock}/vision/v3.2/ocr", output_col="o")
    ocr.set_service_col("image_bytes", "img")
    assert ocr.transform(t)["o"][0]["text"] == "HELLO WORLD"


def test_translate_and_bing_and_speech(mock):
    t = Table({"text": np.array(["bonjour"], dtype=object)})
    tr = Translate(url=f"{mock}/translator/translate", output_col="tr")
    tr.set_service_col("text", "text")
    tr.set_service_value("to_language", ["fr"])
    out = tr.transform(t)
    assert out["tr"][0][0]["text"] == "ruojnob"

    b = BingImageSearch(url=f"{mock}/bing/images/search", output_col="imgs")
    b.set_service_value("query", "cats")
    out = b.transform(Table({"x": np.array([1])}))
    assert [v["name"] for v in out["imgs"][0]] == ["img1", "img2"]

    stt = SpeechToText(url=f"{mock}/speech/recognition", output_col="sp")
    stt.set_service_col("audio_bytes", "audio")
    out = stt.transform(Table({"audio": np.array([b"RIFFwavdata"],
                                                 dtype=object)}))
    assert out["sp"][0]["RecognitionStatus"] == "Success"


def test_error_col_keeps_rows_flowing(mock):
    s = TextSentiment(url=f"{mock}/text/analytics/v3.1/sentiment",
                      output_col="sentiment", backoffs=())
    s.set_service_value("subscription_key", "bad-key")
    s.set_service_col("text", "text")
    out = s.transform(_texts())
    assert all(v is None for v in out["sentiment"])
    assert all(e["status_code"] == 401 for e in out["errors"])


def test_key_per_row_column(mock):
    """value-or-column duality: the subscription key can come per row."""
    t = _texts().with_column(
        "key", np.array(["k-a", "k-b", "k-c"], dtype=object))
    s = TextSentiment(url=f"{mock}/text/analytics/v3.1/sentiment",
                      batch_size=1, output_col="sentiment")
    s.set_service_col("subscription_key", "key")
    s.set_service_col("text", "text")
    s.transform(t)
    # concurrent batches may arrive in any order
    keys = {k for _, k, _ in _AzureMock.seen[-3:]}
    assert keys == {"k-a", "k-b", "k-c"}


def test_service_serde_roundtrip(tmp_path, mock):
    s = TextSentiment(url=f"{mock}/text/analytics/v3.1/sentiment",
                      batch_size=2, output_col="sentiment")
    s.set_service_value("subscription_key", "k123")
    s.set_service_col("text", "text")
    p = str(tmp_path / "svc")
    s.save(p)
    s2 = PipelineStage.load(p)
    assert s2.batch_size == 2
    out = s2.transform(_texts())
    assert out["sentiment"][0]["sentiment"] == "positive"


def test_azure_search_writer(mock):
    w = AzureSearchWriter(
        url=f"{mock}/search/indexes/myidx/docs/index",
        subscription_key="sk", batch_size=2)
    t = Table({"id": np.array(["1", "2", "3"], dtype=object),
               "score": np.array([0.5, 0.7, 0.9])})
    statuses = w.write(t)
    assert statuses == [200, 200]
