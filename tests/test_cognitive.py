"""Cognitive services tests against a local Azure-shaped mock service.

(ref suites: cognitive/src/test/scala/.../split1..split3 — the reference
hits live services with vault keys; this environment has no egress, so a
mock speaking the same REST shapes stands in.)
"""
import json
import http.server
import threading

import numpy as np
import pytest

from synapseml_tpu.cognitive import (AnalyzeImage, AnalyzeLayout,
                                     AnalyzeReceipts, AzureSearchWriter,
                                     BingImageSearch, BreakSentence,
                                     Detect, DetectEntireSeries,
                                     DetectLastAnomaly, DictionaryExamples,
                                     DictionaryLookup, FindSimilarFace,
                                     GenerateThumbnails, GetCustomModel,
                                     GroupFaces, IdentifyFaces,
                                     KeyPhraseExtractor, LanguageDetector,
                                     ListCustomModels, NER, OCR, ReadImage,
                                     RecognizeDomainSpecificContent,
                                     RecognizeText, SpeechToText, TagImage,
                                     TextSentiment, Translate, Transliterate,
                                     VerifyFaces, flatten_read_results)
from synapseml_tpu.core.pipeline import PipelineStage
from synapseml_tpu.data.table import Table


class _AzureMock(http.server.BaseHTTPRequestHandler):
    protocol_version = "HTTP/1.1"
    seen = []
    # operation id -> {"polls_left": n, "result": payload}
    operations = {}
    op_counter = [0]

    def log_message(self, *a):
        pass

    def _reply(self, code, obj, headers=None):
        body = json.dumps(obj).encode()
        self.send_response(code)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(body)))
        for k, v in (headers or {}).items():
            self.send_header(k, v)
        self.end_headers()
        self.wfile.write(body)

    def _reply_bytes(self, code, body, content_type="image/jpeg"):
        self.send_response(code)
        self.send_header("Content-Type", content_type)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def _start_operation(self, result, polls=1):
        """202 + Operation-Location; the op returns running `polls` times."""
        _AzureMock.op_counter[0] += 1
        op = str(_AzureMock.op_counter[0])
        _AzureMock.operations[op] = {"polls_left": polls, "result": result}
        host = self.headers.get("Host")
        self._reply(202, {}, headers={
            "Operation-Location": f"http://{host}/operations/{op}"})

    def do_GET(self):
        if self.path.startswith("/bing/images/search"):
            self._reply(200, {"value": [{"name": "img1"}, {"name": "img2"}]})
        elif self.path.startswith("/operations/"):
            op = self.path.rsplit("/", 1)[1]
            state = _AzureMock.operations.get(op)
            if state is None:
                self._reply(404, {})
            elif state["polls_left"] > 0:
                state["polls_left"] -= 1
                self._reply(200, {"status": "running"})
            elif state["result"] is None:
                self._reply(200, {"status": "failed",
                                  "error": {"code": "InternalServerError"}})
            else:
                self._reply(200, {"status": "succeeded", **state["result"]})
        elif self.path.startswith("/formrecognizer/custom/models/"):
            model = self.path.split("/models/", 1)[1].split("?")[0]
            self._reply(200, {"modelInfo": {"modelId": model,
                                            "status": "ready"}})
        elif self.path.startswith("/formrecognizer/custom/models"):
            self._reply(200, {"modelList": [
                {"modelId": "m1", "status": "ready"},
                {"modelId": "m2", "status": "creating"}]})
        else:
            self._reply(404, {})

    def do_POST(self):
        body = self.rfile.read(int(self.headers.get("Content-Length", 0)))
        key = self.headers.get("Ocp-Apim-Subscription-Key")
        _AzureMock.seen.append((self.path, key,
                                self.headers.get("Content-Type")))
        if key == "bad-key":
            self._reply(401, {"error": {"code": "401",
                                        "message": "Access denied"}})
            return
        path = self.path
        if path.startswith("/text/analytics/v3.1/sentiment"):
            docs = json.loads(body)["documents"]
            self._reply(200, {"documents": [
                {"id": d["id"],
                 "sentiment": "positive" if "good" in d["text"] else "negative",
                 "confidenceScores": {"positive": 0.9, "negative": 0.1}}
                for d in docs], "errors": []})
        elif path.startswith("/text/analytics/v3.1/entities"):
            docs = json.loads(body)["documents"]
            self._reply(200, {"documents": [
                {"id": d["id"], "entities": [
                    {"text": w, "category": "Noun"}
                    for w in d["text"].split() if w.istitle()]}
                for d in docs], "errors": []})
        elif path.startswith("/text/analytics/v3.1/keyPhrases"):
            docs = json.loads(body)["documents"]
            self._reply(200, {"documents": [
                {"id": d["id"], "keyPhrases": d["text"].split()[:2]}
                for d in docs], "errors": []})
        elif path.startswith("/text/analytics/v3.1/languages"):
            docs = json.loads(body)["documents"]
            self._reply(200, {"documents": [
                {"id": d["id"], "detectedLanguage": {
                    "name": "English", "iso6391Name": "en",
                    "confidenceScore": 0.99}}
                for d in docs], "errors": []})
        elif path.startswith("/anomalydetector/v1.0/timeseries/last/detect"):
            series = json.loads(body)["series"]
            last = series[-1]["value"]
            self._reply(200, {"isAnomaly": last > 100,
                              "expectedValue": 10.0,
                              "upperMargin": 5.0, "lowerMargin": 5.0})
        elif path.startswith("/anomalydetector/v1.0/timeseries/entire/detect"):
            series = json.loads(body)["series"]
            self._reply(200, {
                "isAnomaly": [pt["value"] > 100 for pt in series],
                "expectedValues": [10.0] * len(series),
                "upperMargins": [5.0] * len(series),
                "lowerMargins": [5.0] * len(series)})
        elif path.startswith("/vision/v3.2/analyze"):
            self._reply(200, {"categories": [{"name": "outdoor"}],
                              "tags": [{"name": "grass"}],
                              "description": {"captions": [
                                  {"text": "a field"}]}})
        elif path.startswith("/vision/v3.2/ocr"):
            self._reply(200, {"regions": [{"lines": [{"words": [
                {"text": "HELLO"}, {"text": "WORLD"}]}]}]})
        elif path.startswith("/vision/v3.2/tag"):
            self._reply(200, {"tags": [{"name": "cat", "confidence": 0.98}]})
        elif path.startswith("/vision/v3.2/generateThumbnail"):
            self._reply_bytes(200, b"\xff\xd8JPEGTHUMB")
        elif path.startswith("/vision/v3.2/models/"):
            model = path.split("/models/", 1)[1].split("/")[0]
            self._reply(200, {"result": {model: [{"name": "Satya"}]}})
        elif path.startswith("/vision/v3.2/failingRead"):
            self._start_operation(None)
        elif path.startswith("/vision/v3.2/recognizeText"):
            self._start_operation({"recognitionResult": {"lines": [
                {"text": "ASYNC"}, {"text": "TEXT"}]}})
        elif path.startswith("/vision/v3.2/read/analyze"):
            self._start_operation({"analyzeResult": {"readResults": [
                {"lines": [{"text": "READ"}, {"text": "RESULT"}]}]}})
        elif path.startswith("/face/v1.0/findsimilars"):
            req = json.loads(body)
            assert "faceId" in req
            self._reply(200, [{"faceId": "f2", "confidence": 0.92}])
        elif path.startswith("/face/v1.0/group"):
            req = json.loads(body)
            ids = req["faceIds"]
            self._reply(200, {"groups": [ids[:2]], "messyGroup": ids[2:]})
        elif path.startswith("/face/v1.0/identify"):
            req = json.loads(body)
            self._reply(200, [
                {"faceId": fid, "candidates": [
                    {"personId": "p1", "confidence": 0.9}]}
                for fid in req["faceIds"]])
        elif path.startswith("/face/v1.0/verify"):
            req = json.loads(body)
            same = (req.get("faceId1") == req.get("faceId2")
                    or "personId" in req)
            self._reply(200, {"isIdentical": same,
                              "confidence": 0.95 if same else 0.1})
        elif path.startswith("/formrecognizer/"):
            # layout/receipt/custom analyses all reply via the LRO
            self._start_operation({"analyzeResult": {
                "readResults": [{"lines": [{"text": "INVOICE"},
                                           {"text": "TOTAL 42"}]}],
                "documentResults": [{"fields": {
                    "Total": {"type": "number", "valueNumber": 42}}}],
            }})
        elif path.startswith("/translator/transliterate"):
            texts = json.loads(body)
            self._reply(200, [
                {"text": t["text"].upper(), "script": "Latn"}
                for t in texts])
        elif path.startswith("/translator/detect"):
            self._reply(200, [
                {"language": "fr", "score": 0.97}
                for _ in json.loads(body)])
        elif path.startswith("/translator/breaksentence"):
            self._reply(200, [
                {"sentLen": [len(t["text"])]} for t in json.loads(body)])
        elif path.startswith("/translator/dictionary/lookup"):
            self._reply(200, [
                {"translations": [{"normalizedTarget": t["text"] + "_fr"}]}
                for t in json.loads(body)])
        elif path.startswith("/translator/dictionary/examples"):
            self._reply(200, [
                {"examples": [{"sourcePrefix": t["text"],
                               "targetPrefix": t["translation"]}]}
                for t in json.loads(body)])
        elif path.startswith("/translator/translate"):
            texts = json.loads(body)
            self._reply(200, [
                {"translations": [{"text": t["text"][::-1], "to": "fr"}]}
                for t in texts])
        elif path.startswith("/speech"):
            self._reply(200, {"RecognitionStatus": "Success",
                              "DisplayText": f"heard {len(body)} bytes"})
        elif path.startswith("/search/indexes"):
            docs = json.loads(body)["value"]
            self._reply(200, {"value": [
                {"key": str(i), "status": True, "statusCode": 201}
                for i in range(len(docs))]})
        else:
            self._reply(404, {"error": "no such endpoint"})


@pytest.fixture(scope="module")
def mock():
    httpd = http.server.ThreadingHTTPServer(("127.0.0.1", 0), _AzureMock)
    httpd.daemon_threads = True
    threading.Thread(target=httpd.serve_forever, daemon=True).start()
    yield f"http://127.0.0.1:{httpd.server_address[1]}"
    httpd.shutdown()
    httpd.server_close()


def _texts():
    return Table({"text": np.array(
        ["good day Alice", "bad turn Bob", "good good"], dtype=object)})


def test_sentiment_batched_and_keyed(mock):
    s = TextSentiment(url=f"{mock}/text/analytics/v3.1/sentiment",
                      batch_size=2, output_col="sentiment")
    s.set_service_value("subscription_key", "k123")
    s.set_service_col("text", "text")
    out = s.transform(_texts())
    sents = [v["sentiment"] for v in out["sentiment"]]
    assert sents == ["positive", "negative", "positive"]
    assert all(e is None for e in out["errors"])
    # the key rode the header; 2 batches for 3 docs at batch_size=2
    keys = {k for _, k, _ in _AzureMock.seen if k}
    assert "k123" in keys


def test_ner_and_keyphrases_and_language(mock):
    t = _texts()
    ner = NER(url=f"{mock}/text/analytics/v3.1/entities", output_col="ents")
    ner.set_service_col("text", "text")
    out = ner.transform(t)
    assert out["ents"][0][0]["text"] == "Alice"

    kp = KeyPhraseExtractor(url=f"{mock}/text/analytics/v3.1/keyPhrases",
                            output_col="kp")
    kp.set_service_col("text", "text")
    assert list(kp.transform(t)["kp"][0]) == ["good", "day"]

    ld = LanguageDetector(url=f"{mock}/text/analytics/v3.1/languages",
                          output_col="lang")
    ld.set_service_col("text", "text")
    assert ld.transform(t)["lang"][0]["iso6391Name"] == "en"


def test_anomaly_detector(mock):
    series = np.empty(2, dtype=object)
    series[0] = [("2024-01-0%d" % (i + 1), float(i)) for i in range(5)]
    series[1] = [("2024-01-0%d" % (i + 1), 5000.0 if i == 4 else float(i))
                 for i in range(5)]
    t = Table({"series": series})
    last = DetectLastAnomaly(
        url=f"{mock}/anomalydetector/v1.0/timeseries/last/detect",
        output_col="anom")
    last.set_service_col("series", "series")
    out = last.transform(t)
    assert out["anom"][0]["isAnomaly"] is False
    assert out["anom"][1]["isAnomaly"] is True

    entire = DetectEntireSeries(
        url=f"{mock}/anomalydetector/v1.0/timeseries/entire/detect",
        output_col="anom")
    entire.set_service_col("series", "series")
    out = entire.transform(t)
    assert out["anom"][1]["isAnomaly"][4] is True


def test_vision_and_ocr_bytes_and_url(mock):
    t = Table({"img": np.array([b"\x89PNGfakebytes"], dtype=object),
               "url": np.array(["http://x/img.png"], dtype=object)})
    an = AnalyzeImage(url=f"{mock}/vision/v3.2/analyze", output_col="a")
    an.set_service_col("image_bytes", "img")
    out = an.transform(t)
    assert out["a"][0]["categories"][0]["name"] == "outdoor"
    # bytes ride as octet-stream
    assert any(ct == "application/octet-stream"
               for _, _, ct in _AzureMock.seen)

    an2 = AnalyzeImage(url=f"{mock}/vision/v3.2/analyze", output_col="a")
    an2.set_service_col("image_url", "url")
    assert an2.transform(t)["a"][0]["tags"][0]["name"] == "grass"

    ocr = OCR(url=f"{mock}/vision/v3.2/ocr", output_col="o")
    ocr.set_service_col("image_bytes", "img")
    assert ocr.transform(t)["o"][0]["text"] == "HELLO WORLD"


def test_translate_and_bing_and_speech(mock):
    t = Table({"text": np.array(["bonjour"], dtype=object)})
    tr = Translate(url=f"{mock}/translator/translate", output_col="tr")
    tr.set_service_col("text", "text")
    tr.set_service_value("to_language", ["fr"])
    out = tr.transform(t)
    assert out["tr"][0][0]["text"] == "ruojnob"

    b = BingImageSearch(url=f"{mock}/bing/images/search", output_col="imgs")
    b.set_service_value("query", "cats")
    out = b.transform(Table({"x": np.array([1])}))
    assert [v["name"] for v in out["imgs"][0]] == ["img1", "img2"]

    stt = SpeechToText(url=f"{mock}/speech/recognition", output_col="sp")
    stt.set_service_col("audio_bytes", "audio")
    out = stt.transform(Table({"audio": np.array([b"RIFFwavdata"],
                                                 dtype=object)}))
    assert out["sp"][0]["RecognitionStatus"] == "Success"


def test_error_col_keeps_rows_flowing(mock):
    s = TextSentiment(url=f"{mock}/text/analytics/v3.1/sentiment",
                      output_col="sentiment", backoffs=())
    s.set_service_value("subscription_key", "bad-key")
    s.set_service_col("text", "text")
    out = s.transform(_texts())
    assert all(v is None for v in out["sentiment"])
    assert all(e["status_code"] == 401 for e in out["errors"])


def test_key_per_row_column(mock):
    """value-or-column duality: the subscription key can come per row."""
    t = _texts().with_column(
        "key", np.array(["k-a", "k-b", "k-c"], dtype=object))
    s = TextSentiment(url=f"{mock}/text/analytics/v3.1/sentiment",
                      batch_size=1, output_col="sentiment")
    s.set_service_col("subscription_key", "key")
    s.set_service_col("text", "text")
    s.transform(t)
    # concurrent batches may arrive in any order
    keys = {k for _, k, _ in _AzureMock.seen[-3:]}
    assert keys == {"k-a", "k-b", "k-c"}


def test_service_serde_roundtrip(tmp_path, mock):
    s = TextSentiment(url=f"{mock}/text/analytics/v3.1/sentiment",
                      batch_size=2, output_col="sentiment")
    s.set_service_value("subscription_key", "k123")
    s.set_service_col("text", "text")
    p = str(tmp_path / "svc")
    s.save(p)
    s2 = PipelineStage.load(p)
    assert s2.batch_size == 2
    out = s2.transform(_texts())
    assert out["sentiment"][0]["sentiment"] == "positive"


def _img_table():
    return Table({"img": np.array([b"\x89PNGfakebytes"], dtype=object)})


def test_vision_extras_tag_thumbnail_domain(mock):
    t = _img_table()
    tag = TagImage(url=f"{mock}/vision/v3.2/tag", output_col="tags")
    tag.set_service_col("image_bytes", "img")
    assert tag.transform(t)["tags"][0][0]["name"] == "cat"

    th = GenerateThumbnails(url=f"{mock}/vision/v3.2/generateThumbnail",
                            width=32, height=32, output_col="thumb")
    th.set_service_col("image_bytes", "img")
    out = th.transform(t)
    assert out["thumb"][0].startswith(b"\xff\xd8")
    assert out["errors"][0] is None

    dom = RecognizeDomainSpecificContent(
        url=f"{mock}/vision/v3.2/models", model="celebrities",
        output_col="celebs")
    dom.set_service_col("image_bytes", "img")
    assert dom.transform(t)["celebs"][0]["celebrities"][0]["name"] == "Satya"


def test_async_reply_recognize_text_and_read(mock):
    """202 + Operation-Location is polled through running -> succeeded
    (ref: ComputerVision.scala BasicAsyncReply:211-257)."""
    t = _img_table()
    rt = RecognizeText(url=f"{mock}/vision/v3.2/recognizeText",
                       output_col="rt", polling_delay_ms=10)
    rt.set_service_col("image_bytes", "img")
    out = rt.transform(t)
    assert out["rt"][0]["text"] == "ASYNC TEXT"
    assert out["errors"][0] is None

    rd = ReadImage(url=f"{mock}/vision/v3.2/read/analyze",
                   output_col="rd", polling_delay_ms=10)
    rd.set_service_col("image_bytes", "img")
    assert rd.transform(t)["rd"][0]["text"] == "READ RESULT"


def test_async_failed_operation_lands_in_error_col(mock):
    """A terminal failed/cancelled LRO must not masquerade as an empty
    success — it becomes a non-2xx error row."""
    rd = ReadImage(url=f"{mock}/vision/v3.2/failingRead",
                   output_col="rd", polling_delay_ms=10)
    rd.set_service_col("image_bytes", "img")
    out = rd.transform(_img_table())
    assert out["rd"][0] is None
    err = out["errors"][0]
    assert err is not None and err["status_code"] == 502
    assert "failed" in err["reason"]


def test_row_bound_query_params_are_url_encoded(mock):
    """Reserved characters in a column value must not inject query params
    (review finding: raw f-string splicing)."""
    t = Table({"text": np.array(["salut"], dtype=object)})
    tr = Transliterate(url=f"{mock}/translator/transliterate",
                       output_col="o")
    tr.set_service_col("text", "text")
    tr.set_service_value("language", "fr&toScript=Cyrl")
    tr.set_service_value("from_script", "Latn")
    tr.set_service_value("to_script", "Latn")
    out = tr.transform(t)
    # the encoded value rides as ONE parameter; the mock still answers
    assert out["o"][0]["text"] == "SALUT"
    path = [p for p, _, _ in _AzureMock.seen
            if p.startswith("/translator/transliterate")][-1]
    assert "fr%26toScript%3DCyrl" in path


def test_face_services(mock):
    t = Table({"fid": np.array(["f1"], dtype=object),
               "fids": np.empty(1, dtype=object)})
    t["fids"][0] = ["f1", "f2", "f3"]

    fs = FindSimilarFace(url=f"{mock}/face/v1.0/findsimilars",
                         output_col="sim")
    fs.set_service_col("face_id", "fid")
    fs.set_service_col("face_ids", "fids")
    assert fs.transform(t)["sim"][0][0]["confidence"] == 0.92

    g = GroupFaces(url=f"{mock}/face/v1.0/group", output_col="groups")
    g.set_service_col("face_ids", "fids")
    out = g.transform(t)
    assert out["groups"][0]["groups"] == [["f1", "f2"]]
    assert out["groups"][0]["messyGroup"] == ["f3"]

    idf = IdentifyFaces(url=f"{mock}/face/v1.0/identify", output_col="id")
    idf.set_service_col("face_ids", "fids")
    idf.set_service_value("person_group_id", "pg1")
    res = idf.transform(t)["id"][0]
    assert res[0]["candidates"][0]["personId"] == "p1"

    v = VerifyFaces(url=f"{mock}/face/v1.0/verify", output_col="ver")
    v.set_service_value("face_id1", "f1")
    v.set_service_value("face_id2", "f1")
    assert v.transform(Table({"x": np.array([1])}))["ver"][0][
        "isIdentical"] is True

    # missing both faceId1 and faceId -> null row, no crash
    v2 = VerifyFaces(url=f"{mock}/face/v1.0/verify", output_col="ver")
    out = v2.transform(Table({"x": np.array([1])}))
    assert out["ver"][0] is None


def test_form_recognizer_async_and_flatteners(mock):
    t = _img_table()
    lay = AnalyzeLayout(url=f"{mock}/formrecognizer/v2.1/layout/analyze",
                        output_col="layout", polling_delay_ms=10)
    lay.set_service_col("image_bytes", "img")
    out = lay.transform(t)
    assert flatten_read_results(out["layout"][0]) == "INVOICE TOTAL 42"

    rec = AnalyzeReceipts(
        url=f"{mock}/formrecognizer/v2.1/prebuilt/receipt/analyze",
        output_col="rec", polling_delay_ms=10)
    rec.set_service_col("image_bytes", "img")
    rec.set_service_value("include_text_details", True)
    out = rec.transform(t)
    fields = out["rec"][0]["analyzeResult"]["documentResults"][0]["fields"]
    assert fields["Total"]["valueNumber"] == 42

    lst = ListCustomModels(url=f"{mock}/formrecognizer/custom/models",
                           output_col="models")
    lst.set_service_value("op", "full")
    models = lst.transform(Table({"x": np.array([1])}))["models"][0]
    assert [m["modelId"] for m in models["modelList"]] == ["m1", "m2"]

    getm = GetCustomModel(url=f"{mock}/formrecognizer/custom/models",
                          output_col="m")
    getm.set_service_value("model_id", "m1")
    getm.set_service_value("include_keys", True)
    out = getm.transform(Table({"x": np.array([1])}))
    assert out["m"][0]["modelInfo"]["modelId"] == "m1"


def test_translator_family(mock):
    t = Table({"text": np.array(["salut"], dtype=object)})

    tr = Transliterate(url=f"{mock}/translator/transliterate",
                       output_col="o")
    tr.set_service_col("text", "text")
    tr.set_service_value("language", "fr")
    tr.set_service_value("from_script", "Latn")
    tr.set_service_value("to_script", "Latn")
    assert tr.transform(t)["o"][0]["text"] == "SALUT"

    d = Detect(url=f"{mock}/translator/detect", output_col="o")
    d.set_service_col("text", "text")
    assert d.transform(t)["o"][0]["language"] == "fr"

    bs = BreakSentence(url=f"{mock}/translator/breaksentence",
                       output_col="o")
    bs.set_service_col("text", "text")
    assert bs.transform(t)["o"][0]["sentLen"] == [5]

    dl = DictionaryLookup(url=f"{mock}/translator/dictionary/lookup",
                          output_col="o")
    dl.set_service_col("text", "text")
    dl.set_service_value("from_language", "fr")
    dl.set_service_value("to_language", "en")
    out = dl.transform(t)
    assert out["o"][0]["translations"][0]["normalizedTarget"] == "salut_fr"

    de = DictionaryExamples(url=f"{mock}/translator/dictionary/examples",
                            output_col="o")
    de.set_service_col("text", "text")
    de.set_service_value("translation", "hi")
    de.set_service_value("from_language", "fr")
    de.set_service_value("to_language", "en")
    out = de.transform(t)
    assert out["o"][0]["examples"][0]["targetPrefix"] == "hi"


def test_azure_search_writer(mock):
    w = AzureSearchWriter(
        url=f"{mock}/search/indexes/myidx/docs/index",
        subscription_key="sk", batch_size=2)
    t = Table({"id": np.array(["1", "2", "3"], dtype=object),
               "score": np.array([0.5, 0.7, 0.9])})
    statuses = w.write(t)
    assert statuses == [200, 200]


# ---------------------------------------------------------------------------
# streaming speech (SpeechToTextSDK analogue)
# ---------------------------------------------------------------------------

def _multi_utterance_wav(n_utt=3, sr=16000, utt_ms=400, gap_ms=500):
    """tone / silence / tone ... — n_utt bursts separated by gaps."""
    from synapseml_tpu.cognitive import pcm_to_wav

    t = np.arange(sr * utt_ms // 1000)
    tone = (0.3 * np.sin(2 * np.pi * 440 * t / sr) * 32767).astype(np.int16)
    gap = np.zeros(sr * gap_ms // 1000, np.int16)
    parts = [gap]
    for _ in range(n_utt):
        parts += [tone, gap]
    return pcm_to_wav(np.concatenate(parts), sr)


def test_wav_stream_parses_and_asserts_format():
    from synapseml_tpu.cognitive import WavStream, pcm_to_wav

    wav = _multi_utterance_wav(1)
    ws = WavStream(wav)
    assert (ws.sample_rate, ws.channels, ws.bits_per_sample) == (16000, 1, 16)
    assert len(ws.pcm) > 0
    # the SDK pull loop: chunked reads cover the whole stream
    total = sum(len(c) for c in ws.chunks(100))
    assert total == len(ws.pcm)
    # reference asserts (AudioStreams.scala:64-66)
    import struct as _s

    bad = bytearray(pcm_to_wav(np.zeros(100, np.int16), 8000))
    with pytest.raises(ValueError, match="16000"):
        WavStream(bytes(bad))
    with pytest.raises(ValueError, match="RIFF"):
        WavStream(b"nonsense")


def test_segment_utterances_finds_bursts():
    from synapseml_tpu.cognitive import WavStream, segment_utterances

    ws = WavStream(_multi_utterance_wav(3))
    segs = segment_utterances(ws.pcm, ws.sample_rate)
    assert len(segs) == 3
    # segments ordered, non-overlapping, each covering ~400ms of tone
    for (s, e), nxt in zip(segs, segs[1:] + [(len(ws.pcm), 0)]):
        assert e > s
        assert e <= nxt[0]
        assert 0.3 < (e - s) / ws.sample_rate < 0.7
    assert segment_utterances(np.zeros(16000, np.int16), 16000) == []


def test_speech_sdk_streams_per_utterance_rows(mock):
    from synapseml_tpu.cognitive import SpeechToTextSDK

    sdk = SpeechToTextSDK(url=f"{mock}/speech/recognition",
                          output_col="utt").set_service_value(
        "subscription_key", "k").set_service_col("audio_bytes", "audio")
    t = Table({"audio": np.array([_multi_utterance_wav(3),
                                  _multi_utterance_wav(2)], dtype=object),
               "doc": np.array(["a", "b"], dtype=object)})
    out = sdk.transform(t)
    # flatMap semantics: 3 + 2 utterance rows, input columns repeated
    assert out.num_rows == 5
    assert list(out["doc"]) == ["a", "a", "a", "b", "b"]
    utts = list(out["utt"])
    assert all(u["RecognitionStatus"] == "Success" for u in utts)
    # offsets are 100-ns ticks, strictly increasing within a document
    offs = [u["Offset"] for u in utts[:3]]
    assert offs == sorted(offs) and offs[0] > 0
    assert all(u["Duration"] > 3_000_000 for u in utts)  # >300ms


def test_speech_sdk_array_mode_and_empty_audio(mock):
    from synapseml_tpu.cognitive import SpeechToTextSDK, pcm_to_wav

    sdk = SpeechToTextSDK(url=f"{mock}/speech/recognition",
                          output_col="utt",
                          stream_intermediate_results=False)
    sdk.set_service_value("subscription_key", "k")
    sdk.set_service_col("audio_bytes", "audio")
    silent = pcm_to_wav(np.zeros(16000, np.int16))
    t = Table({"audio": np.array([_multi_utterance_wav(2), silent],
                                 dtype=object)})
    out = sdk.transform(t)
    assert out.num_rows == 2
    assert len(out["utt"][0]) == 2
    assert out["utt"][1] == []  # no utterances in silence


def test_audio_featurizer_log_mel():
    """On-device log-mel features: STFT certified against torch
    elsewhere; here the full transformer path — ragged clips, WAV-bytes
    input, frame-count bookkeeping — against a torch.stft-based
    reference."""
    import torch

    from synapseml_tpu.cognitive.speech import AudioFeaturizer, pcm_to_wav

    sr, flen, step, n_mel = 16000, 400, 160, 24
    rng = np.random.default_rng(0)
    t1 = np.sin(2 * np.pi * 440 * np.arange(8000) / sr).astype(np.float32)
    t2 = (0.5 * np.sin(2 * np.pi * 1200 * np.arange(5000) / sr)
          + 0.01 * rng.normal(size=5000)).astype(np.float32)

    feat = AudioFeaturizer(frame_length=flen, frame_step=step,
                           num_mel_bins=n_mel, sample_rate=sr)
    out = feat.transform(Table({"audio": np.array([t1, t2], dtype=object)}))
    f1, f2 = out["features"]
    assert f1.shape == (1 + (8000 - flen) // step, n_mel)
    assert f2.shape == (1 + (5000 - flen) // step, n_mel)

    # torch-based reference for clip 1 (same hann window, center=False)
    win = torch.hann_window(flen, periodic=False)
    spec = torch.stft(torch.from_numpy(t1), n_fft=flen, hop_length=step,
                      win_length=flen, window=win, center=False,
                      onesided=True, return_complex=True)
    power = (spec.real ** 2 + spec.imag ** 2).numpy().T  # [frames, bins]
    # the featurizer's own mel matrix (already spec-property-tested)
    from synapseml_tpu.onnx import import_model
    from synapseml_tpu.onnx.builder import GraphBuilder
    g = GraphBuilder(opset=17)
    m = g.add_node("MelWeightMatrix", [
        g.add_initializer("a", np.asarray(n_mel, np.int64)),
        g.add_initializer("b", np.asarray(flen, np.int64)),
        g.add_initializer("c", np.asarray(sr, np.int64)),
        g.add_initializer("d", np.asarray(125.0, np.float32)),
        g.add_initializer("e", np.asarray(7600.0, np.float32))])
    g.add_output(m, np.float32, None)
    gm = import_model(g.to_bytes())
    mel = np.asarray(gm.apply(gm.params)[0])
    want1 = np.log(power @ mel + 1e-6)
    np.testing.assert_allclose(f1, want1, rtol=1e-3, atol=1e-3)
    # the 440 Hz tone's energy concentrates in one low mel band
    assert f1.mean(axis=0).argmax() < n_mel // 3

    # WAV-bytes input path (16k mono PCM16 canonical asserts); int16
    # quantization perturbs bins near the log floor, so compare where
    # there is actual energy
    wav = pcm_to_wav((t1 * 32767).astype(np.int16))
    out_w = feat.transform(Table({"audio": np.array([wav], dtype=object)}))
    fw = out_w["features"][0]
    m = f1 > np.log(1e-4)
    np.testing.assert_allclose(fw[m], f1[m], rtol=5e-2, atol=5e-2)
