import numpy as np
import pytest

from sklearn.datasets import load_breast_cancer, load_diabetes, load_iris
from sklearn.metrics import r2_score, roc_auc_score
from sklearn.model_selection import train_test_split

from synapseml_tpu.core.pipeline import PipelineStage
from synapseml_tpu.data.table import Table
from synapseml_tpu.gbdt.boosting import Booster, BoostParams, train
from synapseml_tpu.gbdt.estimators import (
    LightGBMClassifier, LightGBMRanker, LightGBMRegressor)


@pytest.fixture(scope="module")
def cancer():
    X, y = load_breast_cancer(return_X_y=True)
    return train_test_split(X, y, test_size=0.3, random_state=0)


def test_binary_auc_beats_reference_gate(cancer):
    # reference gate: breast-cancer gbdt AUC 0.9920 +- 0.1
    # (BASELINE.md, lightgbm benchmarks CSV row 22)
    Xt, Xv, yt, yv = cancer
    b = train(BoostParams(objective="binary", num_iterations=100), Xt, yt)
    auc = roc_auc_score(yv, b.predict(Xv))
    assert auc > 0.99


def test_classifier_estimator_table_api(cancer, tmp_path):
    Xt, Xv, yt, yv = cancer
    t = Table({"features": Xt, "label": yt})
    model = LightGBMClassifier(num_iterations=30).fit(t)
    out = model.transform(Table({"features": Xv, "label": yv}))
    assert set(["rawPrediction", "probability", "prediction"]) <= set(out.columns)
    auc = roc_auc_score(yv, out["probability"][:, 1])
    assert auc > 0.98
    # serde roundtrip (SerializationFuzzing analogue, SURVEY.md 4.2)
    p = str(tmp_path / "m")
    model.save(p)
    model2 = PipelineStage.load(p)
    out2 = model2.transform(Table({"features": Xv, "label": yv}))
    # booster now round-trips through the native LightGBM text format, which
    # folds init_score into tree-0 leaves: one f32 rounding step (~1e-7)
    np.testing.assert_allclose(out2["probability"], out["probability"],
                               rtol=1e-5, atol=1e-7)


def test_multiclass(cancer):
    X, y = load_iris(return_X_y=True)
    t = Table({"features": X, "label": y.astype(float)})
    model = LightGBMClassifier(objective="multiclass", num_iterations=40,
                               num_leaves=15, min_data_in_leaf=5).fit(t)
    out = model.transform(t)
    acc = (out["prediction"] == y).mean()
    assert acc > 0.95
    assert out["probability"].shape == (len(y), 3)
    np.testing.assert_allclose(out["probability"].sum(-1), 1.0, atol=1e-5)


def test_regressor_matches_sklearn_ballpark():
    X, y = load_diabetes(return_X_y=True)
    Xt, Xv, yt, yv = train_test_split(X, y, test_size=0.3, random_state=0)
    t = Table({"features": Xt, "label": yt})
    model = LightGBMRegressor(num_iterations=200, learning_rate=0.05).fit(t)
    pred = model.transform(Table({"features": Xv}))["prediction"]
    from sklearn.ensemble import HistGradientBoostingRegressor
    ref = HistGradientBoostingRegressor(
        max_iter=200, learning_rate=0.05, max_leaf_nodes=31,
        min_samples_leaf=20, early_stopping=False).fit(Xt, yt)
    ours, theirs = r2_score(yv, pred), r2_score(yv, ref.predict(Xv))
    assert ours > theirs - 0.05


def test_feature_cols_api(cancer):
    Xt, Xv, yt, yv = cancer
    cols = {f"f{i}": Xt[:, i] for i in range(5)}
    cols["label"] = yt
    t = Table(cols)
    m = LightGBMClassifier(features_col=None,
                           feature_cols=[f"f{i}" for i in range(5)],
                           num_iterations=20).fit(t)
    out = m.transform(t)
    assert roc_auc_score(yt, out["probability"][:, 1]) > 0.9


def test_early_stopping_and_validation_col(cancer):
    Xt, Xv, yt, yv = cancer
    X = np.vstack([Xt, Xv])
    y = np.concatenate([yt, yv])
    is_val = np.zeros(len(y), bool)
    is_val[len(yt):] = True
    t = Table({"features": X, "label": y, "isVal": is_val})
    m = LightGBMClassifier(num_iterations=500, validation_indicator_col="isVal",
                           early_stopping_round=10).fit(t)
    assert m.booster.num_trees < 500  # stopped early
    assert m.booster.best_iteration >= 0


def test_weight_column_changes_model(cancer):
    Xt, Xv, yt, yv = cancer
    w = np.where(yt == 1, 10.0, 1.0)
    t_w = Table({"features": Xt, "label": yt, "w": w})
    m0 = LightGBMClassifier(num_iterations=10).fit(t_w)
    m1 = LightGBMClassifier(num_iterations=10, weight_col="w").fit(t_w)
    p0 = m0.transform(t_w)["probability"][:, 1]
    p1 = m1.transform(t_w)["probability"][:, 1]
    assert not np.allclose(p0, p1)
    # upweighting positives shifts predictions up on average
    assert p1.mean() > p0.mean()


def test_goss_and_rf_and_bagging(cancer):
    Xt, Xv, yt, yv = cancer
    for bt, kw in [("goss", {}), ("rf", dict(bagging_fraction=0.8,
                                             bagging_freq=1)),
                   ("gbdt", dict(bagging_fraction=0.7, bagging_freq=1,
                                 feature_fraction=0.8))]:
        b = train(BoostParams(objective="binary", boosting_type=bt,
                              num_iterations=40, **kw), Xt, yt)
        auc = roc_auc_score(yv, b.predict(Xv))
        assert auc > 0.95, (bt, auc)


def test_ranker_orders_by_relevance():
    rng = np.random.default_rng(0)
    n_q, per_q = 40, 10
    n = n_q * per_q
    x = rng.standard_normal((n, 5))
    rel = (x[:, 0] + 0.3 * rng.standard_normal(n) > 0.5).astype(float) * 2
    q = np.repeat(np.arange(n_q), per_q)
    t = Table({"features": x, "label": rel, "query": q})
    m = LightGBMRanker(num_iterations=30, num_leaves=7,
                       min_data_in_leaf=5).fit(t)
    pred = m.transform(t)["prediction"]
    # predictions should correlate with relevance
    assert np.corrcoef(pred, rel)[0, 1] > 0.5


def test_shap_additivity(cancer):
    Xt, Xv, yt, yv = cancer
    b = train(BoostParams(objective="binary", num_iterations=10,
                          num_leaves=7), Xt, yt)
    xs = Xv[:16]
    contrib = b.predict_raw(xs)
    from synapseml_tpu.gbdt.shap import tree_shap
    phi = tree_shap(b, xs)
    np.testing.assert_allclose(phi.sum(axis=1), contrib, rtol=1e-4, atol=1e-4)


def test_feature_importances(cancer):
    Xt, Xv, yt, yv = cancer
    t = Table({"features": Xt, "label": yt})
    m = LightGBMClassifier(num_iterations=20).fit(t)
    split_imp = m.get_feature_importances("split")
    gain_imp = m.get_feature_importances("gain")
    assert len(split_imp) == Xt.shape[1]
    assert sum(split_imp) > 0 and sum(gain_imp) > 0


def test_missing_values_handled(cancer):
    Xt, Xv, yt, yv = cancer
    Xt = Xt.copy()
    rng = np.random.default_rng(0)
    Xt[rng.random(Xt.shape) < 0.1] = np.nan
    b = train(BoostParams(objective="binary", num_iterations=30), Xt, yt)
    pred = b.predict(np.where(np.isnan(Xv), np.nan, Xv))
    assert np.isfinite(pred).all()
    assert roc_auc_score(yv, pred) > 0.95


def test_booster_string_roundtrip(cancer):
    Xt, Xv, yt, yv = cancer
    b = train(BoostParams(objective="binary", num_iterations=5), Xt, yt)
    b2 = Booster.load_string(b.save_string())
    np.testing.assert_allclose(b2.predict(Xv), b.predict(Xv), rtol=1e-6)


def test_predict_leaf_shape(cancer):
    Xt, Xv, yt, yv = cancer
    b = train(BoostParams(objective="binary", num_iterations=5), Xt, yt)
    leaves = b.predict_leaf(Xv[:10])
    assert leaves.shape == (10, 5)
    assert (leaves >= 0).all()


def test_distributed_dp_matches_single_device(cancer):
    import jax
    from jax.sharding import Mesh
    Xt, Xv, yt, yv = cancer
    p = BoostParams(objective="binary", num_iterations=10)
    b_single = train(p, Xt, yt)
    mesh = Mesh(np.array(jax.devices()), ("dp",))
    b_dist = train(p, Xt, yt, mesh=mesh)
    np.testing.assert_allclose(
        b_dist.predict(Xv), b_single.predict(Xv), rtol=1e-4, atol=1e-5)


def test_distributed_multiclass_runs():
    import jax
    from jax.sharding import Mesh
    X, y = load_iris(return_X_y=True)
    mesh = Mesh(np.array(jax.devices()), ("dp",))
    b = train(BoostParams(objective="multiclass", num_class=3,
                          num_iterations=10, num_leaves=7,
                          min_data_in_leaf=5), X, y.astype(float), mesh=mesh)
    acc = (b.predict(X).argmax(-1) == y).mean()
    assert acc > 0.9


def test_dart_boosting(cancer):
    Xt, Xv, yt, yv = cancer
    b = train(BoostParams(objective="binary", boosting_type="dart",
                          num_iterations=40, drop_rate=0.2), Xt, yt)
    auc = roc_auc_score(yv, b.predict(Xv))
    assert auc > 0.95
    # dart reweights dropped trees below the base learning rate
    assert (b.tree_weights <= 0.1 + 1e-6).all()
    assert (b.tree_weights > 0).all()


def test_model_save_with_estimator_params(cancer, tmp_path):
    Xt, Xv, yt, yv = cancer
    t = Table({"features": Xt, "label": yt})
    m = LightGBMClassifier(objective="binary", num_iterations=5,
                           learning_rate=0.2).fit(t)
    p = str(tmp_path / "m2")
    m.save(p)  # regression: estimator-only params used to break model save
    m2 = PipelineStage.load(p)
    np.testing.assert_allclose(
        m2.transform(Table({"features": Xv}))["probability"],
        m.transform(Table({"features": Xv}))["probability"], rtol=1e-6)


def test_nonzero_based_labels_remap(cancer):
    # labels {1,2} must train as well as {0,1} (review finding: label remap)
    x, _, y, _ = cancer
    from synapseml_tpu.data.table import Table
    from synapseml_tpu.gbdt.estimators import LightGBMClassifier
    t = Table({"features": x, "label": y + 1.0})
    model = LightGBMClassifier(num_iterations=20).fit(t)
    out = model.transform(t)
    acc = float((out["prediction"] == y + 1.0).mean())
    assert acc > 0.9
    assert set(np.unique(out["prediction"])) <= {1.0, 2.0}


def test_sparse_multiclass_labels():
    # labels {0,2,5} -> dense remap, predictions in original label space
    rng = np.random.default_rng(0)
    x = rng.normal(size=(300, 4))
    y = np.choose((x[:, 0] > 0).astype(int) + (x[:, 1] > 0).astype(int),
                  [0.0, 2.0, 5.0])
    from synapseml_tpu.data.table import Table
    from synapseml_tpu.gbdt.estimators import LightGBMClassifier
    t = Table({"features": x, "label": y})
    out = LightGBMClassifier(num_iterations=20).fit(t).transform(t)
    assert set(np.unique(out["prediction"])) <= {0.0, 2.0, 5.0}
    assert float((out["prediction"] == y).mean()) > 0.85


def test_valid_set_without_early_stopping_keeps_all_trees(cancer):
    # review finding: best_iteration must not truncate predictions unless
    # early stopping is enabled
    x, _, y, _ = cancer
    from synapseml_tpu.gbdt.boosting import BoostParams, train
    split = int(0.8 * len(y))
    b_plain = train(BoostParams(objective="binary", num_iterations=15),
                    x[:split], y[:split])
    b_valid = train(BoostParams(objective="binary", num_iterations=15),
                    x[:split], y[:split],
                    valid_sets=[(x[split:], y[split:])])
    assert b_valid.best_iteration == -1
    np.testing.assert_allclose(b_plain.predict(x[split:]),
                               b_valid.predict(x[split:]), rtol=1e-5)


def test_ranker_ndcg_early_stopping():
    rng = np.random.default_rng(1)
    n = 400
    x = rng.normal(size=(n, 5))
    rel = (x[:, 0] + 0.1 * rng.normal(size=n) > 0.5).astype(np.float64)
    group = np.repeat(np.arange(n // 8), 8)
    from synapseml_tpu.gbdt.boosting import BoostParams, train
    b = train(BoostParams(objective="lambdarank", num_iterations=30,
                          early_stopping_round=5),
              x[:320], rel[:320], group=group[:320],
              valid_sets=[(x[320:], rel[320:], group[320:] - group[320])])
    assert "ndcg" in b.eval_history
    assert len(b.eval_history["ndcg"]) > 0
    assert max(b.eval_history["ndcg"]) > 0.5


def test_rf_valid_metric_uses_averaged_scores(cancer):
    x, _, y, _ = cancer
    from synapseml_tpu.gbdt.boosting import BoostParams, train
    split = int(0.8 * len(y))
    b = train(BoostParams(objective="binary", boosting_type="rf",
                          bagging_fraction=0.8, bagging_freq=1,
                          num_iterations=12),
              x[:split], y[:split], valid_sets=[(x[split:], y[split:])])
    h = b.eval_history["binary_logloss"]
    # averaged margins keep logloss bounded; summed margins would diverge
    assert h[-1] < 1.0


def test_distributed_goss_dart_rank(cancer):
    """The previously-unsupported distributed modes run on the mesh and
    produce sane models (goss: global psum'd top-rate threshold; dart:
    precomputed drop schedule; lambdarank: group-aligned sharding)."""
    import jax
    from jax.sharding import Mesh

    Xt, Xv, yt, yv = cancer
    mesh = Mesh(np.array(jax.devices()), ("dp",))

    b_goss = train(BoostParams(objective="binary", boosting_type="goss",
                               num_iterations=10), Xt, yt, mesh=mesh)
    assert roc_auc_score(yv, b_goss.predict(Xv)) > 0.95

    b_dart = train(BoostParams(objective="binary", boosting_type="dart",
                               num_iterations=10, drop_rate=0.3),
                   Xt, yt, mesh=mesh)
    assert roc_auc_score(yv, b_dart.predict(Xv)) > 0.95
    # dart weights come from the precomputed schedule, not all-ones
    assert not np.allclose(b_dart.tree_weights, 1.0)

    # lambdarank: synthetic queries, relevance correlated with feature 0
    rng = np.random.default_rng(0)
    nq, per = 24, 12
    X = rng.normal(size=(nq * per, 5))
    gid = np.repeat(np.arange(nq), per)
    rel = np.clip((X[:, 0] + rng.normal(scale=0.3, size=nq * per)) * 2,
                  0, 4).astype(np.float64)
    b_rank = train(BoostParams(objective="lambdarank", num_iterations=15,
                               num_leaves=7, min_data_in_leaf=3),
                   X, rel, group=gid, mesh=mesh)
    scores = b_rank.predict(X)
    # ranking quality: within-query score order correlates with relevance
    from scipy.stats import spearmanr
    cors = [spearmanr(scores[gid == q], rel[gid == q]).statistic
            for q in range(nq)]
    assert np.nanmean(cors) > 0.5


def test_distributed_dart_matches_single_device_schedule(cancer):
    """Same seed => identical drop schedule; mesh dart must track the
    single-device dart closely (same trees up to psum'd float noise)."""
    import jax
    from jax.sharding import Mesh

    Xt, Xv, yt, yv = cancer
    p = BoostParams(objective="binary", boosting_type="dart",
                    num_iterations=6, drop_rate=0.5, skip_drop=0.0)
    b1 = train(p, Xt, yt)
    mesh = Mesh(np.array(jax.devices()), ("dp",))
    b2 = train(p, Xt, yt, mesh=mesh)
    np.testing.assert_allclose(b2.tree_weights, b1.tree_weights, rtol=1e-6)
    np.testing.assert_allclose(b2.predict(Xv), b1.predict(Xv),
                               rtol=5e-3, atol=5e-3)


def test_distributed_early_stopping_on_device_eval(cancer):
    import jax
    from jax.sharding import Mesh

    Xt, Xv, yt, yv = cancer
    mesh = Mesh(np.array(jax.devices()), ("dp",))
    p = BoostParams(objective="binary", num_iterations=400,
                    early_stopping_round=5, num_leaves=5)
    b = train(p, Xt, yt, valid_sets=[(Xv, yv)], mesh=mesh)
    assert b.best_iteration >= 0
    assert len(b.eval_history["binary_logloss"]) < 400  # stopped early
    b_single = train(p, Xt, yt, valid_sets=[(Xv, yv)])
    # padding perturbs histograms slightly; stop points should be close
    assert abs(b.best_iteration - b_single.best_iteration) <= 25


def test_distributed_l1_renewal_matches_single_device():
    """L1 leaf renewal uses global quantiles on the mesh (all_gather), so
    mesh and single-device L1 models must agree."""
    import jax
    from jax.sharding import Mesh

    rng = np.random.default_rng(3)
    X = rng.normal(size=(400, 6))
    y = X[:, 0] * 2 + np.abs(rng.standard_cauchy(400))  # heavy-tailed noise
    p = BoostParams(objective="regression_l1", num_iterations=8, num_leaves=7)
    b1 = train(p, X, y)
    mesh = Mesh(np.array(jax.devices()), ("dp",))
    b2 = train(p, X, y, mesh=mesh)
    np.testing.assert_allclose(b2.predict(X), b1.predict(X),
                               rtol=1e-3, atol=1e-3)


def test_lambdarank_blocked_matches_dense():
    """Block-diagonal lambdarank gradients must equal the dense pair
    formulation (same math, O(N*G) instead of O(N^2))."""
    from synapseml_tpu.gbdt import objectives as obj

    rng = np.random.default_rng(4)
    sizes = [5, 9, 3, 12, 7]
    gid = np.concatenate([np.full(s, i) for i, s in enumerate(sizes)])
    perm = rng.permutation(len(gid))
    gid = gid[perm]
    n = len(gid)
    preds = rng.normal(size=n).astype(np.float32)
    labels = rng.integers(0, 4, n).astype(np.float32)
    g_dense, h_dense = obj.lambdarank_grad(preds, labels, gid)
    qidx, qmask, qinv = obj.build_query_blocks(gid)
    g_blk, h_blk = obj.lambdarank_grad_blocked(preds, labels, qidx, qmask,
                                               qinv)
    np.testing.assert_allclose(np.asarray(g_blk), np.asarray(g_dense),
                               rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(np.asarray(h_blk), np.asarray(h_dense),
                               rtol=1e-5, atol=1e-6)


def test_ndcg_vectorized_matches_reference_loop():
    from synapseml_tpu.gbdt.boosting import _ndcg_score

    rng = np.random.default_rng(8)
    sizes = [1, 4, 9, 2, 15, 7, 3]
    gid = np.concatenate([np.full(s, i * 10) for i, s in enumerate(sizes)])
    perm = rng.permutation(len(gid))
    gid = gid[perm]
    scores = rng.normal(size=len(gid))
    labels = rng.integers(0, 4, len(gid)).astype(float)

    def loop_ndcg(scores, labels, group_ids, at):
        total, count = 0.0, 0
        for g in np.unique(group_ids):
            sel = group_ids == g
            rel = labels[sel]
            order = np.argsort(-scores[sel], kind="stable")[:at]
            discounts = 1.0 / np.log2(np.arange(2, len(order) + 2))
            dcg = float(np.sum((2.0 ** rel[order] - 1.0) * discounts))
            ideal = np.sort(rel)[::-1][:at]
            idcg = float(np.sum((2.0 ** ideal - 1.0)
                                / np.log2(np.arange(2, len(ideal) + 2))))
            if idcg > 0:
                total += dcg / idcg
                count += 1
        return total / max(count, 1)

    for at in (1, 3, 10, 30):
        assert _ndcg_score(scores, labels, gid, at) == pytest.approx(
            loop_ndcg(scores, labels, gid, at), rel=1e-9)
    # all-zero relevance: no valid queries
    assert _ndcg_score(scores, np.zeros(len(gid)), gid, 10) == 0.0


def test_ndcg_skewed_groups_fallback():
    from synapseml_tpu.gbdt.boosting import _ndcg_score, _ndcg_score_loop

    rng = np.random.default_rng(9)
    # one 400-doc query among 200 singletons: blocked layout would pad
    # 201x400; the skew guard must route to the loop with equal results
    gid = np.concatenate([np.zeros(400), np.arange(1, 201)])
    scores = rng.normal(size=len(gid))
    labels = rng.integers(0, 3, len(gid)).astype(float)
    got = _ndcg_score(scores, labels, gid, 10)
    want = _ndcg_score_loop(scores, labels, gid, 10)
    assert got == pytest.approx(want, rel=1e-9)


# ---------------------------------------------------------------------------
# batch training + delegate hooks (ref: LightGBMBase.scala train:46-61,
# LightGBMDelegate.scala:12-62)
# ---------------------------------------------------------------------------

def _batch_table(n=400, d=5, seed=7):
    rng = np.random.default_rng(seed)
    x = rng.normal(size=(n, d)).astype(np.float32)
    y = (x[:, 0] + 0.5 * x[:, 1] > 0).astype(np.float64)
    return Table({"features": x, "label": y})


def test_num_batches_threads_booster():
    from synapseml_tpu.gbdt import LightGBMClassifier, LightGBMDelegate

    calls = []

    class Spy(LightGBMDelegate):
        def before_train_batch(self, bi, table, prev_model):
            calls.append(("before", bi, prev_model is not None))

        def after_train_batch(self, bi, table, model):
            calls.append(("after", bi, model.booster.num_trees))

    t = _batch_table()
    est = LightGBMClassifier(num_iterations=8, num_leaves=7,
                             num_batches=2, delegate=Spy())
    model = est.fit(t)
    # batch 2 continues from batch 1's booster: 8 + 8 trees total
    assert model.booster.num_trees == 16
    assert calls[0] == ("before", 0, False)
    assert calls[1][0] == "after" and calls[1][1] == 0
    assert calls[2] == ("before", 1, True)
    assert calls[3][2] == 16
    # the combined model still separates the classes
    probs = np.asarray(model.transform(t)["probability"])[:, 1]
    y = np.asarray(t["label"])
    assert probs[y == 1].mean() > probs[y == 0].mean() + 0.2


def test_delegate_constant_lr_schedule_matches_static():
    """A delegate returning a constant rate must train the same model as
    the plain learning_rate param (schedule rides as data)."""
    from synapseml_tpu.gbdt import LightGBMRegressor, LightGBMDelegate

    class ConstLR(LightGBMDelegate):
        def get_learning_rate(self, bi, it, prev):
            return 0.05

    t = _batch_table(seed=3)
    t = Table({"features": t["features"],
               "label": np.asarray(t["features"])[:, 0].astype(np.float64)})
    base = LightGBMRegressor(num_iterations=10, num_leaves=7,
                             learning_rate=0.05).fit(t)
    sched = LightGBMRegressor(num_iterations=10, num_leaves=7,
                              learning_rate=0.9,  # overridden by delegate
                              delegate=ConstLR()).fit(t)
    np.testing.assert_allclose(
        np.asarray(sched.transform(t)["prediction"]),
        np.asarray(base.transform(t)["prediction"]), rtol=1e-5)


def test_delegate_decaying_lr_and_iteration_hook():
    from synapseml_tpu.gbdt import LightGBMRegressor, LightGBMDelegate

    iters_seen = []

    class Decay(LightGBMDelegate):
        def get_learning_rate(self, bi, it, prev):
            return 0.2 / (1 + it)

        def after_train_iteration(self, bi, iters_done):
            iters_seen.append(iters_done)

    t = _batch_table(seed=5)
    model = LightGBMRegressor(num_iterations=6, num_leaves=7,
                              delegate=Decay()).fit(t)
    assert model.booster.num_trees == 6
    # hook fired with monotonically increasing completed-iteration counts,
    # ending at the full run
    assert iters_seen and iters_seen[-1] == 6
    assert all(b > a for a, b in zip(iters_seen, iters_seen[1:]))


def test_iteration_hook_on_early_stop_and_unpicklable_delegate(tmp_path):
    """The hook must report kept iterations even when early stopping cuts
    the run, and a locally-defined (unpicklable) delegate must never leak
    into the saved model artifact."""
    from synapseml_tpu.core.pipeline import PipelineStage
    from synapseml_tpu.gbdt import LightGBMRegressor, LightGBMDelegate

    seen = []

    class LocalSpy(LightGBMDelegate):  # local class: pickle would fail
        def after_train_iteration(self, bi, iters):
            seen.append(iters)

    rng = np.random.default_rng(11)
    x = rng.normal(size=(300, 4)).astype(np.float32)
    y = rng.normal(size=300)  # pure noise -> valid metric plateaus fast
    val = np.zeros(300, bool)
    val[200:] = True
    t = Table({"features": x, "label": y, "val": val})
    est = LightGBMRegressor(num_iterations=60, num_leaves=7,
                            early_stopping_round=3,
                            validation_indicator_col="val",
                            delegate=LocalSpy())
    model = est.fit(t)
    kept = model.booster.num_trees
    assert seen and seen[-1] == kept

    p = str(tmp_path / "m")
    model.save(p)  # would raise if the delegate were copied to the model
    m2 = PipelineStage.load(p)
    np.testing.assert_allclose(
        np.asarray(m2.transform(t)["prediction"]),
        np.asarray(model.transform(t)["prediction"]), rtol=1e-6)


def test_dart_multiclass():
    """DART with k class trees per iteration (drops at iteration
    granularity, one shared weight per iteration's tree group)."""
    from synapseml_tpu.gbdt.boosting import BoostParams, train

    rng = np.random.default_rng(9)
    n, d, k = 400, 5, 3
    x = rng.normal(size=(n, d))
    y = np.argmax(x[:, :k] + 0.2 * rng.normal(size=(n, k)),
                  axis=1).astype(np.float64)
    p = BoostParams(objective="multiclass", num_class=k,
                    boosting_type="dart", num_iterations=15, num_leaves=7,
                    drop_rate=0.3, seed=0)
    b = train(p, x, y)
    assert b.num_trees == 15 * k
    # iteration's k trees share one dart weight
    tw = b.tree_weights.reshape(15, k)
    assert np.allclose(tw, tw[:, :1])
    probs = b.predict(x)
    assert probs.shape == (n, k)
    np.testing.assert_allclose(probs.sum(-1), 1.0, atol=1e-5)
    acc = (probs.argmax(-1) == y).mean()
    assert acc > 0.85, acc


def test_distributed_dart_multiclass_matches_single_device():
    """Mesh dart multiclass: iteration-granular drops, per-class score
    reconstruction; same seed must track the single-device ensemble."""
    import jax
    from jax.sharding import Mesh

    rng = np.random.default_rng(13)
    n, d, k = 320, 5, 3
    x = rng.normal(size=(n, d))
    y = np.argmax(x[:, :k] + 0.2 * rng.normal(size=(n, k)),
                  axis=1).astype(np.float64)
    p = BoostParams(objective="multiclass", num_class=k,
                    boosting_type="dart", num_iterations=6, num_leaves=7,
                    drop_rate=0.4, skip_drop=0.0, seed=0)
    b1 = train(p, x, y)
    mesh = Mesh(np.array(jax.devices()), ("dp",))
    b2 = train(p, x, y, mesh=mesh)
    assert b2.num_trees == 6 * k
    # iteration's k trees share one weight, matching single-device
    np.testing.assert_allclose(b2.tree_weights, b1.tree_weights, rtol=1e-6)
    tw = b2.tree_weights.reshape(6, k)
    assert np.allclose(tw, tw[:, :1])
    p1 = b1.predict(x)
    p2 = b2.predict(x)
    np.testing.assert_allclose(p2, p1, rtol=5e-3, atol=5e-3)
    assert (p2.argmax(-1) == y).mean() > 0.8


def test_hist_backend_routing(cancer, tmp_path, monkeypatch):
    """hist_backend threads estimator -> BoostParams -> GrowerParams;
    'auto' resolves via the measured router (trivially 'xla' off-TPU),
    forced backends train identically on CPU (the backend only selects
    a TPU formulation), and the route cache persists to disk."""
    from synapseml_tpu.gbdt.grower import (_HIST_ROUTE_CACHE,
                                           resolve_hist_backend)

    Xt, Xv, yt, yv = cancer
    p_forced = BoostParams(objective="binary", num_iterations=5,
                           hist_backend="xla")
    assert p_forced.grower().hist_backend == "xla"
    b1 = train(p_forced, Xt, yt)
    b2 = train(BoostParams(objective="binary", num_iterations=5), Xt, yt)
    np.testing.assert_allclose(b1.predict(Xv), b2.predict(Xv), rtol=1e-6)

    # off-TPU the router always answers xla (scatter path ignores it)
    monkeypatch.setenv("SYNAPSEML_TPU_CACHE_DIR", str(tmp_path))
    _HIST_ROUTE_CACHE.clear()
    assert resolve_hist_backend(4096, 10, 256) == "xla"

    est = LightGBMClassifier(num_iterations=3, hist_backend="pallas")
    assert est._boost_params("binary").hist_backend == "pallas"
    with pytest.raises(TypeError):
        LightGBMClassifier(hist_backend="cuda")


def test_hist_route_probe_and_disk_cache(tmp_path, monkeypatch):
    """The probe + persistence path, exercised off-TPU by stubbing the
    backend checks: a measured verdict is written to the cache file; a
    fresh process (cleared in-process cache) reads it back WITHOUT
    re-probing; a probe failure falls back to xla and is not persisted."""
    import json

    import jax.numpy as jnp

    from synapseml_tpu.gbdt import grower, pallas_kernels

    monkeypatch.setenv("SYNAPSEML_TPU_CACHE_DIR", str(tmp_path))
    monkeypatch.setattr(grower.jax, "default_backend", lambda: "tpu")
    monkeypatch.setattr(pallas_kernels, "available", lambda: True)
    calls = []

    def fake_hist(binned, grad, hess, mask, n_bins, axis_name=None,
                  backend="auto"):
        calls.append(backend)
        f = binned.shape[1]
        return jnp.zeros((f, n_bins, 3), jnp.float32)

    monkeypatch.setattr(grower, "histogram", fake_hist)
    grower._HIST_ROUTE_CACHE.clear()
    got = grower.resolve_hist_backend(4096, 6, 64, iters=8)
    assert got in ("pallas", "xla")
    assert "pallas" in calls and "xla" in calls  # both legs timed
    cache_file = tmp_path / "hist_routing.json"
    disk = json.loads(cache_file.read_text())
    assert list(disk.values()) == [got]

    # fresh "process": disk answers, no probe runs
    grower._HIST_ROUTE_CACHE.clear()
    calls.clear()
    assert grower.resolve_hist_backend(4096, 6, 64, iters=8) == got
    assert calls == []

    # probe failure: xla fallback, nothing new persisted
    def boom(*a, **k):
        raise RuntimeError("mosaic lowering failed")

    monkeypatch.setattr(grower, "histogram", boom)
    grower._HIST_ROUTE_CACHE.clear()
    cache_file.unlink()
    assert grower.resolve_hist_backend(4096, 6, 64, iters=8) == "xla"
    assert not cache_file.exists()
    grower._HIST_ROUTE_CACHE.clear()


def test_hist_probe_scaled_to_fit_size(monkeypatch):
    """The probe is skipped entirely for fits too small to amortize it
    (structural guarantee that a first small fit pays <1 s of routing
    overhead, not the 10-17 s full probe), runs for big fits, and caps
    its per-call budget at ~1/8 of a mid-size fit's estimated work —
    never below the floor that keeps it measuring compute, not RTT."""
    from synapseml_tpu.gbdt import grower

    def forbid(*a, **k):
        raise AssertionError("probe must not run for a small fit")

    monkeypatch.setattr(grower, "_resolve_hist_backend_local", forbid)
    small = grower._PROBE_MIN_FIT_ROW_VISITS - 1
    assert grower.resolve_hist_backend(
        4096, 10, 64, fit_row_visits=small) == "xla"

    seen = {}

    def record(n, f, n_bins, iters=None, fit_row_visits=None):
        seen["fit_row_visits"] = fit_row_visits
        return "pallas"

    monkeypatch.setattr(grower, "_resolve_hist_backend_local", record)
    assert grower.resolve_hist_backend(
        100_000, 10, 64, fit_row_visits=10**9) == "pallas"
    assert seen["fit_row_visits"] == 10**9

    # budget arithmetic: full for big fits, capped for mid, floored
    full, floor = grower._PROBE_FULL_BUDGET, grower._PROBE_FLOOR_BUDGET
    cap = lambda v: min(full, max(floor, v // 8))  # noqa: E731
    assert cap(10**10) == full
    assert cap(100 * 10**6) == 100 * 10**6 // 8
    assert cap(grower._PROBE_MIN_FIT_ROW_VISITS) == floor

    # train() threads the hint: a tiny fit routes to xla without probing
    monkeypatch.setattr(grower, "_resolve_hist_backend_local", forbid)
    x = np.random.default_rng(0).normal(size=(200, 4))
    y = (x[:, 0] > 0).astype(np.float64)
    b = train(BoostParams(objective="binary", num_iterations=3,
                          num_leaves=7), x, y)
    assert b.num_trees == 3


def test_voting_parallel_tree_learner():
    """parallelism=voting_parallel (PV-tree, the reference's second
    tree_learner): with top_k >= F the election is exhaustive and the
    booster is BIT-IDENTICAL to data_parallel; with a small top_k the
    restricted search still learns (accuracy within a few points) and
    is deterministic; invalid learners fail loudly."""
    import dataclasses

    import jax
    from jax.sharding import Mesh

    rng = np.random.default_rng(11)
    x = rng.normal(size=(600, 8))
    y = (x[:, 0] + 0.8 * x[:, 3] - 0.5 * x[:, 6] > 0).astype(np.float64)
    mesh = Mesh(np.asarray(jax.devices()), ("dp",))

    base = BoostParams(objective="binary", num_iterations=8, num_leaves=7)
    want = train(base, x, y, mesh=mesh)

    exhaustive = dataclasses.replace(base, tree_learner="voting_parallel",
                                     voting_top_k=8)
    got = train(exhaustive, x, y, mesh=mesh)
    # identical trees; leaf values differ only by the psum association
    # order of the totals (psum-of-sum vs sum-of-psum, last-ulp)
    np.testing.assert_array_equal(got.trees_feature, want.trees_feature)
    np.testing.assert_array_equal(got.trees_left, want.trees_left)
    np.testing.assert_allclose(got.predict(x), want.predict(x),
                               rtol=1e-5, atol=1e-6)

    small = dataclasses.replace(base, tree_learner="voting_parallel",
                                voting_top_k=2)
    b1 = train(small, x, y, mesh=mesh)
    b2 = train(small, x, y, mesh=mesh)
    np.testing.assert_array_equal(b1.predict(x), b2.predict(x))
    acc_full = ((want.predict(x) > 0.5) == y).mean()
    acc_vote = ((b1.predict(x) > 0.5) == y).mean()
    assert acc_vote > acc_full - 0.05, (acc_vote, acc_full)
    if int(mesh.shape["dp"]) > 1:
        # the restricted election actually bit (on a 1-device mesh the
        # local vote IS the global argmax, so trees coincide)
        assert not np.array_equal(b1.trees_feature, want.trees_feature)

    # deep-leaf regime: per-shard leaf rows drop below min_data_in_leaf
    # while global counts pass — the unconstrained-vote fallback must
    # keep the election informative (review repro: all--inf local gains
    # voted features 0..k-1)
    deep = dataclasses.replace(base, tree_learner="voting_parallel",
                               voting_top_k=2, num_leaves=31,
                               min_data_in_leaf=20, num_iterations=4)
    bd = train(deep, x, y, mesh=mesh)
    deep_full = dataclasses.replace(base, num_leaves=31,
                                    min_data_in_leaf=20, num_iterations=4)
    bf = train(deep_full, x, y, mesh=mesh)
    acc_d = ((bd.predict(x) > 0.5) == y).mean()
    acc_f = ((bf.predict(x) > 0.5) == y).mean()
    assert acc_d > acc_f - 0.05, (acc_d, acc_f)

    with pytest.raises(ValueError, match="tree_learner"):
        train(dataclasses.replace(base, tree_learner="feature_parallel"),
              x, y, mesh=mesh)

    # estimator surface: param accepted + threaded
    est = LightGBMClassifier(num_iterations=3,
                             parallelism="voting_parallel", top_k=4)
    assert est._boost_params("binary").voting_top_k == 4
    with pytest.raises(TypeError):
        LightGBMClassifier(parallelism="feature_parallel")


# ---------------------------------------------------------------------------
# Round-5 param-surface completion: DART modes, stratified bagging,
# bagging seed, improvement tolerance
# ---------------------------------------------------------------------------

def test_dart_select_and_normalize_semantics():
    """Unit semantics of the shared DART helpers (lib_lightgbm dart.hpp
    rules): weighted vs uniform selection, max_drop cap, xgboost vs
    classic normalization."""
    from synapseml_tpu.gbdt.boosting import _dart_normalize, _dart_select

    p = BoostParams(boosting_type="dart", learning_rate=0.5,
                    drop_rate=1.0, skip_drop=0.0, max_drop=2)
    rng = np.random.default_rng(0)
    # drop_rate=1 drops every tree, capped by max_drop
    dropped = _dart_select(rng, 5, np.ones(5), p)
    assert len(dropped) == 2

    # weighted mode: a zero-weight tree is never dropped when others
    # carry all the weight (probability proportional to |w|)
    pw = BoostParams(boosting_type="dart", drop_rate=0.5, skip_drop=0.0,
                     max_drop=0, uniform_drop=False)
    w = np.array([0.0, 1.0, 1.0, 1.0, 1.0, 1.0])
    hits = set()
    for s in range(50):
        hits.update(_dart_select(np.random.default_rng(s), 6, w, pw)
                    .tolist())
    assert 0 not in hits and len(hits) > 0

    # classic vs xgboost normalization
    p0 = BoostParams(learning_rate=0.3)
    assert _dart_normalize(p0, 0) == (0.3, 1.0)
    nw, sc = _dart_normalize(p0, 2)
    assert abs(nw - 0.1) < 1e-12 and abs(sc - 2 / 3) < 1e-12
    px = BoostParams(learning_rate=0.3, xgboost_dart_mode=True)
    nw, sc = _dart_normalize(px, 2)
    assert abs(nw - 0.3 / 2.3) < 1e-12 and abs(sc - 2 / 2.3) < 1e-12


def test_dart_mode_params_change_the_ensemble():
    """uniform_drop / xgboost_dart_mode must actually reach the trainer:
    toggling them changes predictions; same settings reproduce."""
    rng = np.random.default_rng(5)
    x = rng.normal(size=(400, 6))
    y = (x[:, 0] + 0.5 * x[:, 1] + rng.normal(0, 0.2, 400) > 0) \
        .astype(np.float64)
    t = Table({"features": x, "label": y})

    def fit(**kw):
        m = LightGBMClassifier(boosting_type="dart", num_iterations=30,
                               drop_rate=0.4, skip_drop=0.0, seed=7,
                               **kw).fit(t)
        return np.asarray(m.transform(t)["probability"])

    base = fit()
    again = fit()
    np.testing.assert_allclose(base, again)     # deterministic
    assert not np.allclose(base, fit(uniform_drop=True))
    assert not np.allclose(base, fit(xgboost_dart_mode=True))


def test_stratified_bagging_binary_only_and_effective():
    rng = np.random.default_rng(11)
    x = rng.normal(size=(600, 5))
    y = (x[:, 0] > 0.8).astype(np.float64)      # imbalanced positives
    t = Table({"features": x, "label": y})

    def fit(**kw):
        m = LightGBMClassifier(num_iterations=25, bagging_freq=1, seed=3,
                               **kw).fit(t)
        return np.asarray(m.transform(t)["probability"])

    base = fit()
    strat = fit(neg_bagging_fraction=0.3)       # downsample negatives
    assert not np.allclose(base, strat)
    np.testing.assert_allclose(strat, fit(neg_bagging_fraction=0.3))

    with pytest.raises(ValueError, match="binary"):
        train(BoostParams(objective="regression",
                          pos_bagging_fraction=0.5, bagging_freq=1),
              x, y)


def test_bagging_seed_independent_stream():
    rng = np.random.default_rng(13)
    x = rng.normal(size=(500, 5))
    y = (x[:, 0] + rng.normal(0, 0.3, 500) > 0).astype(np.float64)
    t = Table({"features": x, "label": y})

    def fit(**kw):
        m = LightGBMClassifier(num_iterations=20, bagging_freq=1,
                               bagging_fraction=0.5, seed=3, **kw).fit(t)
        return np.asarray(m.transform(t)["probability"])

    base = fit()                    # bagging_seed=None: derived stream
    np.testing.assert_allclose(base, fit())
    seeded = fit(bagging_seed=42)
    assert not np.allclose(base, seeded)
    np.testing.assert_allclose(seeded, fit(bagging_seed=42))


def test_improvement_tolerance_early_stopping():
    """Reference TrainUtils.scala:129-141 semantics: an improvement
    below tolerance does not reset patience (larger-better)."""
    from synapseml_tpu.gbdt.boosting import BoostParams as BP

    class _Tracker:
        # minimal record() host: mirror the ValidTracker fields it reads
        def __init__(self, p):
            self.p = p
            self.history = {"auc": []}
            self.metric_name = "auc"
            self.larger_better = True
            self.best_score = -np.inf
            self.best_iter = -1
        from synapseml_tpu.gbdt.boosting import _ValidTracker
        record = _ValidTracker.record

    p = BP(early_stopping_round=2, improvement_tolerance=0.05)
    tr = _Tracker(p)
    assert tr.record(0.70, 0) is False          # first: improved
    assert tr.record(0.72, 1) is False          # +0.02 < tol: no reset
    assert tr.record(0.73, 2) is True           # patience exhausted
    assert tr.best_iter == 0

    p0 = BP(early_stopping_round=2, improvement_tolerance=0.0)
    tr0 = _Tracker(p0)
    assert tr0.record(0.70, 0) is False
    assert tr0.record(0.72, 1) is False         # resets with tol=0
    assert tr0.record(0.73, 2) is False
    assert tr0.best_iter == 2


def test_predict_start_iteration_window():
    """start_iteration/num_iteration select an iteration range, and the
    windows compose additively (lib_lightgbm's predict window; the
    reference's startIteration model param)."""
    rng = np.random.default_rng(17)
    x = rng.normal(size=(300, 5))
    y = x[:, 0] * 2 + x[:, 1] + rng.normal(0, 0.1, 300)
    b = train(BoostParams(objective="regression", num_iterations=20,
                          boost_from_average=True), x, y)
    full = b.predict_raw(x)
    head = b.predict_raw(x, num_iteration=8)
    tail = b.predict_raw(x, start_iteration=8)
    # init score attaches once (to the window starting at 0), so the
    # two windows sum exactly to the full prediction
    np.testing.assert_allclose(head + tail, full, rtol=1e-5, atol=1e-5)
    mid = b.predict_raw(x, num_iteration=4, start_iteration=8)
    win = b.predict_raw(x, num_iteration=12) - head
    np.testing.assert_allclose(mid, win, rtol=1e-4, atol=1e-5)

    # early-stopped model: whole-model predict truncates at best_iter,
    # but an explicit start window means "all remaining trees"
    # (lib_lightgbm sets num_iteration=-1 whenever start_iteration > 0)
    b2 = train(BoostParams(objective="regression", num_iterations=20),
               x, y)
    import dataclasses
    b2 = dataclasses.replace(b2, best_iteration=4)
    np.testing.assert_allclose(
        b2.predict_raw(x, start_iteration=2),
        b2.predict_raw(x, num_iteration=18, start_iteration=2),
        rtol=1e-6)
    assert not np.allclose(b2.predict_raw(x),
                           b2.predict_raw(x, start_iteration=0,
                                          num_iteration=20))



def test_model_introspection_getters():
    """Reference model-methods surface
    (LightGBMModelMethods.scala:27-96): single-row SHAP and the booster
    introspection getters."""
    rng = np.random.default_rng(19)
    x = rng.normal(size=(200, 6))
    y = (x[:, 0] > 0).astype(np.float64)
    m = LightGBMClassifier(num_iterations=8).fit(
        Table({"features": x, "label": y}))
    assert m.get_booster_num_features() == 6
    assert m.get_booster_num_classes() == 1      # binary: one score
    assert m.get_booster_num_total_iterations() == 8
    assert m.get_booster_num_total_model() == 8
    assert m.get_booster_best_iteration() == -1  # no early stopping ran
    shaps = m.get_feature_shaps(x[0])
    assert len(shaps) == 7                       # 6 features + expected
    np.testing.assert_allclose(
        sum(shaps), m.booster.predict_raw(x[:1])[0], atol=1e-4)


def test_feature_shaps_multiclass_flat_contract():
    """Multiclass get_feature_shaps flattens to K*(F+1) floats (the
    reference's flat-array contract); wrong row width raises clearly."""
    rng = np.random.default_rng(23)
    x = rng.normal(size=(150, 4))
    y = rng.integers(0, 3, 150).astype(np.float64)
    m = LightGBMClassifier(objective="multiclass", num_iterations=4).fit(
        Table({"features": x, "label": y}))  # 3 classes inferred
    shaps = m.get_feature_shaps(x[0])
    assert len(shaps) == 3 * (4 + 1)
    assert all(isinstance(v, float) for v in shaps)
    with pytest.raises(ValueError, match="feature width"):
        m.get_feature_shaps(x[0][:2])
