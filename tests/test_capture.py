"""Incident capture & deterministic replay (docs/observability.md,
"Incident capture & replay"): the tail-based payload capture sink
(runtime/capture.py), the X-Output-Digest reply header, /debug/capture,
the offline replay harness (tools/replay.py), and loadgen --replay."""
import hashlib
import json
import os
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

from synapseml_tpu.data.table import Table
from synapseml_tpu.io.serving import ContinuousServer, make_reply
from synapseml_tpu.runtime import capture as cap
from synapseml_tpu.runtime import compile_cache as cc


class _Req:
    """Minimal HTTPRequestData stand-in for unit tests."""

    def __init__(self, entity=b"{}", headers=None, method="POST",
                 url="/"):
        self.entity = entity
        self.headers = headers or {"Content-Type": "application/json"}
        self.method = method
        self.url = url


@pytest.fixture
def sink(tmp_path):
    """Capture sink pointed at a private dir, healthy sampling off,
    every knob restored after — tier-1 runs everything in one
    process."""
    prev_enabled = cap.set_enabled(True)
    prev_hash = cap.set_model_hash(None)
    cap.configure(directory=str(tmp_path), head_every=0,
                  max_bytes=cap.DEFAULT_MAX_BYTES,
                  reply_cap=cap.DEFAULT_REPLY_BYTES,
                  payload_cap=cap.DEFAULT_PAYLOAD_BYTES)
    yield str(tmp_path)
    cap.reset()
    cap._S.dir = None
    cap.configure(head_every=0, max_bytes=cap.DEFAULT_MAX_BYTES,
                  reply_cap=cap.DEFAULT_REPLY_BYTES,
                  payload_cap=cap.DEFAULT_PAYLOAD_BYTES)
    cap.set_model_hash(prev_hash)
    cap.set_enabled(prev_enabled)


# -- retention policy -------------------------------------------------------

@pytest.mark.parametrize("status,latency,expect", [
    (200, 0.001, None),                 # healthy: the drop path
    (204, 0.001, None),
    (404, 0.001, None),                 # deliberate 4xx answers drop
    (500, 0.001, cap.REASON_5XX),
    (502, 0.001, cap.REASON_5XX),
    (429, 0.001, cap.REASON_SHED),      # admission shed
    (503, 0.001, cap.REASON_SHED),      # drain shed
    (504, 0.001, cap.REASON_DEADLINE),  # deadline before it is a 5xx
    (400, 0.001, cap.REASON_POISON),    # the bisection verdict
    (200, 10.0, cap.REASON_LATENCY),    # healthy status, breached SLO
])
def test_classify_matrix(status, latency, expect):
    assert cap.classify(status, latency, threshold_s=0.25) == expect


def test_head_sample_stride_and_drop_counter(sink):
    cap.configure(head_every=3)
    from synapseml_tpu.runtime import telemetry as tm

    dropped = tm.counter("capture_dropped_total")
    before = dropped.value
    kept = sum(1 for _ in range(9)
               if cap.maybe_capture(_Req(), 200, 0.001, rid="h",
                                    threshold_s=1.0))
    assert kept == 3
    assert dropped.value - before == 6
    recs = cap.scan()
    assert len(recs) == 3
    assert all(r["reason"] == cap.REASON_HEAD for r in recs)


def test_kill_switch(sink):
    cap.configure(head_every=1)
    cap.set_enabled(False)
    assert cap.maybe_capture(_Req(), 500, 0.01, rid="off") is None
    assert cap.scan() == []
    cap.set_enabled(True)
    assert cap.maybe_capture(_Req(), 500, 0.01, rid="on") \
        == cap.REASON_5XX


def test_record_is_self_contained(sink):
    cap.set_model_hash("m" * 64)
    payload = json.dumps({"features": [1.0, 2.0, 3.0],
                          "meta": "x"}).encode()
    reason = cap.maybe_capture(
        _Req(entity=payload), 500, 0.123, rid="rid-1",
        trace_id="t" * 32, span_id="s" * 16, origin="srv",
        digest="d" * 64, reply_entity=b'{"output": [0.5]}')
    assert reason == cap.REASON_5XX
    (rec,) = cap.scan()
    assert rec["rid"] == "rid-1" and rec["trace_id"] == "t" * 32
    assert rec["span_id"] == "s" * 16 and rec["origin"] == "srv"
    assert rec["status_code"] == 500 and rec["reason"] == cap.REASON_5XX
    assert rec["model_hash"] == "m" * 64
    assert rec["output_digest"] == "d" * 64
    assert rec["latency_s"] == pytest.approx(0.123)
    assert rec["method"] == "POST" and rec["path"] == "/"
    assert rec["content_type"] == "application/json"
    # the replay inputs: payload bytes + best-effort shapes/dtypes
    assert cap.payload_bytes(rec) == payload
    assert rec["payload_shapes"] == {"features": [3]}
    assert rec["payload_dtypes"] == {"features": "float"}
    assert cap.reply_bytes(rec) == b'{"output": [0.5]}'
    assert rec["pid"] == os.getpid()


def test_binary_payload_base64_roundtrip(sink):
    blob = bytes(range(256))
    cap.maybe_capture(_Req(entity=blob), 500, 0.01, rid="bin")
    (rec,) = cap.scan()
    assert "payload" not in rec
    assert cap.payload_bytes(rec) == blob


def test_reply_retention_cap(sink):
    cap.configure(reply_cap=32)
    cap.maybe_capture(_Req(), 500, 0.01, rid="small",
                      reply_entity=b"x" * 16)
    cap.maybe_capture(_Req(), 500, 0.01, rid="big",
                      reply_entity=b"y" * 64)
    small, big = cap.scan()
    assert cap.reply_bytes(small) == b"x" * 16
    # an oversized reply is NOTED, never stored truncated (a truncated
    # body would be a lying diff input)
    assert cap.reply_bytes(big) is None
    assert big["reply_truncated"] == 64
    # reply_cap=0 disables retention entirely
    cap.configure(reply_cap=0)
    cap.maybe_capture(_Req(), 500, 0.01, rid="none",
                      reply_entity=b"z")
    assert cap.reply_bytes(cap.scan()[-1]) is None


def test_payload_cap_notes_never_truncates(sink):
    cap.configure(payload_cap=1024)
    big = b'{"features": [' + b"1.0," * 1024 + b"1.0]}"
    cap.maybe_capture(_Req(entity=big), 500, 0.01, rid="huge")
    (rec,) = cap.scan()
    # noted, never stored truncated: a half payload would replay to a
    # meaningless divergence
    assert rec["payload_truncated"] == len(big)
    assert cap.payload_bytes(rec) is None
    # and replay skips a record with no payload instead of erroring
    from tools.replay import main as replay_main

    assert replay_main([cap.capture_path()]) == 1  # nothing replayable


def test_rotation_and_torn_tail(sink):
    cap.configure(max_bytes=4096)
    for i in range(64):
        assert cap.maybe_capture(_Req(entity=b'{"x": [1.0]}'), 500,
                                 0.01, rid=f"rot-{i}")
    live = cap.capture_path()
    assert os.path.exists(live) and os.path.exists(live + ".1")
    assert os.path.getsize(live) <= 4096 + 1024
    # a crash can tear at most the tail line: scan shrugs at it
    with open(live, "a", encoding="utf-8") as fh:
        fh.write('{"torn')
    recs = cap.scan()
    assert recs and all(r["rid"].startswith("rot-") for r in recs)
    # tail_summaries reads the same tail, bodies elided
    tail = cap.tail_summaries(8)
    assert 0 < len(tail) <= 8
    assert "payload" not in tail[-1] and "rid" in tail[-1]


def test_scan_missing_file_is_empty(sink):
    assert cap.scan(os.path.join(sink, "nope.jsonl")) == []


# -- serving end to end -----------------------------------------------------

def _echo_pipeline(table: Table) -> Table:
    replies = np.empty(table.num_rows, dtype=object)
    for i, v in enumerate(table["value"]):
        replies[i] = make_reply(v)
    return table.with_column("reply", replies)


def _post(url, obj, headers=None, timeout=30):
    hdrs = {"Content-Type": "application/json"}
    hdrs.update(headers or {})
    req = urllib.request.Request(url, data=json.dumps(obj).encode(),
                                 method="POST", headers=hdrs)
    try:
        with urllib.request.urlopen(req, timeout=timeout) as r:
            return r.status, dict(r.headers), r.read()
    except urllib.error.HTTPError as e:
        body = e.read()
        return e.code, dict(e.headers or {}), body


def _get_json(url, timeout=30):
    with urllib.request.urlopen(urllib.request.Request(url),
                                timeout=timeout) as r:
        return r.status, json.loads(r.read())


def _wait_records(pred, n=1, timeout=5.0):
    """Poll the capture file until at least ``n`` records match
    ``pred``: the reply deliberately flushes to the client BEFORE the
    capture record is appended (a reply must never wait on the dump
    volume), so a test that scans right after its HTTP reply races
    the handler thread."""
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        out = [r for r in cap.scan() if pred(r)]
        if len(out) >= n:
            return out
        time.sleep(0.02)
    return [r for r in cap.scan() if pred(r)]


@pytest.fixture
def server(sink):
    cs = ContinuousServer("cap_e2e", _echo_pipeline, max_batch=8).start()
    yield cs
    cs.stop()


def test_digest_header_matches_reply_and_span(server):
    st, hdrs, body = _post(server.url, {"x": [1.0, 2.0]})
    assert st == 200
    digest = hdrs.get("X-Output-Digest")
    assert digest == hashlib.sha256(body).hexdigest()
    host = server.url.split("//")[1].rstrip("/")
    st, span = _get_json(f"http://{host}/span/{hdrs['X-Request-Id']}")
    assert st == 200 and span["output_digest"] == digest


def test_deadline_shed_is_captured(server):
    st, hdrs, _ = _post(server.url, {"x": [9.0]},
                        headers={"X-Deadline-Ms": "0.001"})
    assert st == 504
    recs = _wait_records(lambda r: r["rid"] == hdrs["X-Request-Id"])
    assert recs and recs[0]["reason"] == cap.REASON_DEADLINE
    assert recs[0]["status_code"] == 504
    assert cap.payload_bytes(recs[0]) == json.dumps({"x": [9.0]}).encode()
    assert recs[0]["origin"] == "cap_e2e"


def test_drain_shed_is_captured(server):
    server.server.begin_drain()
    try:
        st, hdrs, _ = _post(server.url, {"x": [7.0]})
        assert st == 503
        recs = _wait_records(lambda r: r["rid"] == hdrs["X-Request-Id"])
        assert recs and recs[0]["reason"] == cap.REASON_SHED
    finally:
        server.server._draining.clear()


def test_healthy_head_sample_rides_with_digest(server):
    cap.configure(head_every=1)
    st, hdrs, body = _post(server.url, {"x": [5.0]})
    assert st == 200
    recs = _wait_records(lambda r: r["rid"] == hdrs["X-Request-Id"])
    assert recs and recs[0]["reason"] == cap.REASON_HEAD
    assert recs[0]["output_digest"] == hashlib.sha256(body).hexdigest()
    assert cap.reply_bytes(recs[0]) == body


def test_debug_capture_endpoint_and_gate(server, monkeypatch):
    cap.configure(head_every=1)
    _post(server.url, {"x": [6.0]})
    assert _wait_records(lambda r: True)  # record on disk before GET
    host = server.url.split("//")[1].rstrip("/")
    st, dbg = _get_json(f"http://{host}/debug/capture?n=4")
    assert st == 200
    assert dbg["enabled"] is True
    assert dbg["path"] == cap.capture_path()
    assert dbg["size_bytes"] > 0
    assert dbg["records"] and "rid" in dbg["records"][-1]
    # the whole /debug surface gate covers the new endpoint
    monkeypatch.setenv("SYNAPSEML_DEBUG_ENDPOINTS", "0")
    with pytest.raises(urllib.error.HTTPError) as ei:
        _get_json(f"http://{host}/debug/capture")
    assert ei.value.code == 403


# -- digest determinism across fresh pipelines ------------------------------

@pytest.fixture(scope="module")
def mlp_model(tmp_path_factory):
    from synapseml_tpu.onnx import zoo

    work = tmp_path_factory.mktemp("cap_mlp")
    path = os.path.join(str(work), "model.onnx")
    with open(path, "wb") as fh:
        fh.write(zoo.mlp([4, 8], num_classes=3, seed=0))
    return path, os.path.join(str(work), "cache")


def _score_payloads(model_path, cache_dir, payloads):
    """Fresh pipeline, one reply digest per payload — scored one
    batch so the per-row digests are what serving would have sent."""
    from synapseml_tpu.io.http import HTTPRequestData
    from synapseml_tpu.io.serving import (ID_COL, REQUEST_COL,
                                          _model_pipeline, parse_request)

    pipeline, _model = _model_pipeline(model_path, cache_dir=cache_dir)
    ids = np.array([f"r{i}" for i in range(len(payloads))], dtype=object)
    reqs = np.empty(len(payloads), dtype=object)
    reqs[:] = [HTTPRequestData(url="/", method="POST", headers={},
                               entity=p) for p in payloads]
    out = pipeline(parse_request(Table({ID_COL: ids,
                                        REQUEST_COL: reqs})))
    return [hashlib.sha256(r.entity or b"").hexdigest()
            for r in out["reply"]]


def test_digest_stable_across_fresh_pipelines(mlp_model):
    model_path, cache_dir = mlp_model
    p1 = json.dumps({"features": [0.1, 0.2, 0.3, 0.4]}).encode()
    p2 = json.dumps({"features": [1.0, -1.0, 2.0, 0.0]}).encode()
    a = _score_payloads(model_path, cache_dir, [p1, p2])
    # a brand-new pipeline (fresh ONNXModel, fresh executor), scored
    # in a DIFFERENT batch composition, must reproduce every per-row
    # digest bit-identically — the property replay depends on
    b = _score_payloads(model_path, cache_dir, [p1])
    c = _score_payloads(model_path, cache_dir, [p2, p1])
    assert a[0] == b[0] == c[1]
    assert a[1] == c[0]
    assert a[0] != a[1]


# -- offline replay harness -------------------------------------------------

def _write_records(path, records):
    with open(path, "w", encoding="utf-8") as fh:
        for r in records:
            fh.write(json.dumps(r) + "\n")


def test_replay_offline_echo_roundtrip(server, sink):
    from tools.replay import main as replay_main

    cap.configure(head_every=1)
    for k in range(4):
        _post(server.url, {"x": [float(k)]})
    assert len(_wait_records(lambda r: True, n=4)) >= 4
    out = os.path.join(sink, "report.json")
    rc = replay_main([cap.capture_path(), "--out", out])
    assert rc == 0
    report = json.load(open(out))
    assert report["matched"] == 4 and report["diverged"] == []
    assert report["mode"] == "offline"


def test_replay_divergence_exits_2_with_report(server, sink, capsys):
    from tools.replay import main as replay_main

    cap.configure(head_every=1)
    _post(server.url, {"x": [1.0]})
    _post(server.url, {"x": [2.0]})
    assert len(_wait_records(lambda r: True, n=2)) >= 2
    recs = cap.scan()
    recs[0]["output_digest"] = "0" * 64
    perturbed = os.path.join(sink, "perturbed.jsonl")
    _write_records(perturbed, recs)
    out = os.path.join(sink, "report.json")
    rc = replay_main([perturbed, "--keep-outputs", "--out", out])
    assert rc == 2
    report = json.load(open(out))
    assert len(report["diverged"]) == 1
    d = report["diverged"][0]
    assert d["rid"] == recs[0]["rid"]
    assert d["trace_id"] == recs[0]["trace_id"]
    assert d["captured_digest"] == "0" * 64
    assert d["replayed_digest"] != "0" * 64
    # values identical (only the recorded digest was flipped): the
    # max-abs-diff says "digest lies, numbers agree"
    assert d["max_abs_diff"] == 0.0
    assert "DIVERGED" in capsys.readouterr().out


def test_replay_skips_environmental_statuses(server, sink):
    from tools.replay import main as replay_main

    cap.configure(head_every=1)
    _post(server.url, {"x": [1.0]})
    # a deadline shed is an environmental outcome, not a payload
    # property: replay must not "diverge" on it
    _post(server.url, {"x": [2.0]}, headers={"X-Deadline-Ms": "0.001"})
    assert len(_wait_records(lambda r: True, n=2)) >= 2
    out = os.path.join(sink, "report.json")
    rc = replay_main([cap.capture_path(), "--out", out])
    assert rc == 0
    report = json.load(open(out))
    assert report["matched"] == 1 and report["skipped"] == 1


def test_replay_undecodable_payloads_inconclusive(sink):
    """A file whose every replayable record has a corrupt payload must
    end inconclusive (exit 1), never 'ok: 0 bit-identical'."""
    from tools.replay import main as replay_main

    rec = {"rid": "r1", "trace_id": "t" * 32, "status_code": 200,
           "reason": "head_sample", "output_digest": "d" * 64,
           "payload_b64": "!!!corrupt!!!"}
    f = os.path.join(sink, "undecodable.jsonl")
    _write_records(f, [rec])
    out = os.path.join(sink, "report.json")
    assert replay_main([f, "--out", out]) == 1
    assert json.load(open(out))["undecodable"] == 1


def test_replay_empty_capture_exits_1(sink):
    from tools.replay import main as replay_main

    empty = os.path.join(sink, "empty.jsonl")
    open(empty, "w").close()
    assert replay_main([empty]) == 1


def test_replay_model_hash_guard(mlp_model, sink):
    from tools.replay import main as replay_main

    model_path, _cache = mlp_model
    rec = {"rid": "r1", "trace_id": "t" * 32, "status_code": 200,
           "reason": "head_sample", "output_digest": "d" * 64,
           "payload": json.dumps({"features": [0.0] * 4}),
           "model_hash": "not-the-real-hash"}
    f = os.path.join(sink, "hash.jsonl")
    _write_records(f, [rec])
    # records carry a model hash: --model is mandatory ...
    assert replay_main([f]) == 1
    # ... and a mismatching model file is refused before any scoring
    assert replay_main([f, "--model", model_path]) == 1


def test_replay_poison_reproduces_400(mlp_model, sink):
    from tools.replay import main as replay_main

    model_path, cache_dir = mlp_model
    with open(model_path, "rb") as fh:
        model_hash = cc.content_hash(fh.read())
    healthy_payload = json.dumps({"features": [0.5, 1.5, -0.5, 2.0]}
                                 ).encode()
    (healthy_digest,) = _score_payloads(model_path, cache_dir,
                                        [healthy_payload])
    records = [
        {"rid": "ok-1", "trace_id": "a" * 32, "status_code": 200,
         "reason": "head_sample", "output_digest": healthy_digest,
         "payload": healthy_payload.decode(), "model_hash": model_hash},
        # the poison contract: a non-numeric feature raised at capture
        # time (bisection -> 400) and must STILL raise on replay
        {"rid": "poison-1", "trace_id": "b" * 32, "status_code": 400,
         "reason": "poison", "output_digest": "",
         "payload": json.dumps({"features": ["boom", 1.0, 1.0, 1.0]}),
         "model_hash": model_hash},
    ]
    f = os.path.join(sink, "poison.jsonl")
    _write_records(f, records)
    out = os.path.join(sink, "report.json")
    rc = replay_main([f, "--model", model_path,
                      "--cache-dir", cache_dir, "--out", out])
    assert rc == 0
    report = json.load(open(out))
    assert report["matched"] == 1
    assert report["reproduced_errors"] == 1
    assert report["model_hash"] == model_hash
    # a poison that suddenly scores clean IS a divergence
    records[1]["payload"] = healthy_payload.decode()
    _write_records(f, records)
    rc = replay_main([f, "--model", model_path,
                      "--cache-dir", cache_dir, "--out", out])
    assert rc == 2
    report = json.load(open(out))
    assert report["diverged"][0]["rid"] == "poison-1"


def test_replay_poison_only_file_is_inconclusive(mlp_model, sink):
    """A capture whose every replayable record errors on replay must
    NOT exit 0: an all-error run is indistinguishable from a broken
    replay environment, and crediting it as 'reproduced' would
    false-pass the determinism gate."""
    from tools.replay import main as replay_main

    model_path, cache_dir = mlp_model
    with open(model_path, "rb") as fh:
        model_hash = cc.content_hash(fh.read())
    rec = {"rid": "poison-solo", "trace_id": "c" * 32,
           "status_code": 400, "reason": "poison", "output_digest": "",
           "payload": json.dumps({"features": ["boom", 1.0, 1.0, 1.0]}),
           "model_hash": model_hash}
    f = os.path.join(sink, "poison_only.jsonl")
    _write_records(f, [rec])
    assert replay_main([f, "--model", model_path,
                        "--cache-dir", cache_dir]) == 1


def test_replay_serve_mode(server, sink):
    from tools.replay import main as replay_main

    cap.configure(head_every=1)
    for k in range(3):
        _post(server.url, {"x": [float(k), 1.0]})
    assert len(_wait_records(lambda r: True, n=3)) >= 3
    rc = replay_main([cap.capture_path(), "--serve", server.url])
    assert rc == 0
    # perturbed: the live endpoint's digest header must expose it
    recs = cap.scan()
    recs[1]["output_digest"] = "f" * 64
    perturbed = os.path.join(sink, "serve_perturbed.jsonl")
    _write_records(perturbed, recs)
    rc = replay_main([perturbed, "--serve", server.url])
    assert rc == 2


def test_serve_poison_singleton_500_reproduces(mlp_model, sink):
    """--serve replays sequentially, so a poison arrives as a SINGLETON
    batch and serving legally replies 500 (bisection isolates to 400
    only at n>1) — that still reproduces the captured 400, never a
    divergence."""
    from synapseml_tpu.io.serving import _model_pipeline
    from tools.replay import main as replay_main

    model_path, cache_dir = mlp_model
    pipeline, _model = _model_pipeline(model_path, cache_dir=cache_dir)
    cs = ContinuousServer("cap_poison_srv", pipeline,
                          max_batch=8).start()
    try:
        healthy = {"features": [0.5, 1.5, -0.5, 2.0]}
        st, hdrs, _ = _post(cs.url, healthy)
        assert st == 200
        st, _, _ = _post(cs.url, {"features": ["boom", 1.0, 1.0, 1.0]})
        assert st == 500  # singleton: no batch-mates to bisect from
        records = [
            {"rid": "ok", "trace_id": "a" * 32, "status_code": 200,
             "reason": "head_sample",
             "output_digest": hdrs["X-Output-Digest"],
             "payload": json.dumps(healthy)},
            {"rid": "poison", "trace_id": "b" * 32, "status_code": 400,
             "reason": "poison", "output_digest": "",
             "payload": json.dumps({"features":
                                    ["boom", 1.0, 1.0, 1.0]})},
        ]
        f = os.path.join(sink, "serve_poison.jsonl")
        _write_records(f, records)
        assert replay_main([f, "--serve", cs.url]) == 0
    finally:
        cs.stop()


def test_disabled_telemetry_never_stamps_the_noop_span(server):
    """With telemetry off every request shares the _NOOP_SPAN
    singleton: the digest stamp must skip it (a raw attribute write
    would smear one request's digest across all handlers)."""
    from synapseml_tpu.runtime import telemetry as tm

    prev = tm.set_enabled(False)
    try:
        st, hdrs, body = _post(server.url, {"x": [1.0]})
        assert st == 200
        # the header is still served (sha of the bytes in hand) ...
        assert hdrs.get("X-Output-Digest") == \
            hashlib.sha256(body).hexdigest()
        # ... but the shared no-op span stays unstamped
        assert tm._NOOP_SPAN.output_digest == ""
    finally:
        tm.set_enabled(prev)


def test_replay_serve_unreachable_is_inconclusive(server, sink):
    """--serve against a dead endpoint must exit 1 (environment), not
    2 (divergence) and never 0: no request was scored, so nothing was
    verified either way."""
    from tools.replay import main as replay_main

    cap.configure(head_every=1)
    _post(server.url, {"x": [1.0]})
    assert _wait_records(lambda r: True)
    rc = replay_main([cap.capture_path(),
                      "--serve", "http://127.0.0.1:9/",
                      "--timeout", "2"])
    assert rc == 1


# -- loadgen --replay -------------------------------------------------------

def test_loadgen_replay_roundtrip(server, sink):
    from tools.loadgen import load_capture_records, run_load

    cap.configure(head_every=1)
    for k in range(5):
        _post(server.url, {"x": [float(k), 2.0]})
    assert len(_wait_records(lambda r: True, n=5)) >= 5
    records = load_capture_records(cap.capture_path())
    assert len(records) == 5
    s = run_load(server.url, rps=200.0, duration_s=10.0, seed=3,
                 replay_records=records)
    assert s["hung"] == 0
    assert s["replayed"] == 5
    assert s["digest_checked"] == 5
    assert s["digest_mismatches"] == 0
    # recorded trace ids ride the replay legs (the replays stitch
    # next to the incident's own legs): every slowest[] entry's trace
    # id is one the capture file named
    tids = {r["trace_id"] for r in records}
    assert {e["trace_id"] for e in s["slowest"]} <= tids
    # a flipped digest is reported as a mismatch
    records[2]["output_digest"] = "0" * 64
    s = run_load(server.url, rps=200.0, duration_s=10.0, seed=3,
                 replay_records=records)
    assert s["digest_mismatches"] == 1


def test_loadgen_replay_cli(server, sink, tmp_path):
    from tools.loadgen import main as lg_main

    cap.configure(head_every=1)
    for k in range(3):
        _post(server.url, {"x": [float(k), 3.0]})
    assert len(_wait_records(lambda r: True, n=3)) >= 3
    out = str(tmp_path / "replay_out.json")
    rc = lg_main(["--url", server.url, "--replay", cap.capture_path(),
                  "--rps", "200", "--out", out])
    assert rc == 0
    summary = json.load(open(out))
    assert summary["digest_mismatches"] == 0
    assert summary["digest_checked"] >= 3
    # nonzero mismatches exit 2
    recs = cap.scan()
    recs[0]["output_digest"] = "0" * 64
    perturbed = str(tmp_path / "perturbed.jsonl")
    _write_records(perturbed, recs)
    rc = lg_main(["--url", server.url, "--replay", perturbed,
                  "--rps", "200"])
    assert rc == 2
    # a dead endpoint verifies NOTHING: digest_checked == 0 must be a
    # loud exit 2, never a vacuous pass of the determinism gate
    rc = lg_main(["--url", "http://127.0.0.1:9/", "--replay",
                  cap.capture_path(), "--rps", "200",
                  "--timeout", "2"])
    assert rc == 2
