import numpy as np
import pytest

from synapseml_tpu.data.table import Table
from synapseml_tpu.stages import (
    ClassBalancer,
    DropColumns,
    EnsembleByKey,
    Explode,
    Lambda,
    MultiColumnAdapter,
    PartitionConsolidator,
    RenameColumn,
    Repartition,
    SelectColumns,
    StratifiedRepartition,
    SummarizeData,
    TextPreprocessor,
    Timer,
    UDFTransformer,
    UnicodeNormalize,
)


@pytest.fixture
def table():
    return Table({
        "a": np.arange(6, dtype=np.float64),
        "b": ["x", "y", "x", "y", "x", "y"],
        "label": [0, 0, 0, 0, 1, 1],
    })


def test_drop_select_rename(table):
    assert DropColumns(["a"]).transform(table).columns == ["b", "label"]
    assert SelectColumns(["a"]).transform(table).columns == ["a"]
    out = RenameColumn(input_col="a", output_col="z").transform(table)
    assert "z" in out and "a" not in out


def test_repartition_shards(table):
    shards = Repartition(n=3).shards(table)
    assert len(shards) == 3
    assert sum(s.num_rows for s in shards) == 6


def test_stratified_repartition(table):
    out = StratifiedRepartition(label_col="label", n=2).transform(table)
    assert out.num_rows == 6
    # first half should contain both labels after interleave
    first = out.slice(0, 3)["label"]
    assert set(first) == {0, 1}


def test_ensemble_by_key():
    t = Table({
        "k": ["u", "u", "v"],
        "score": np.array([[1.0, 3.0], [3.0, 5.0], [2.0, 2.0]]),
    })
    out = EnsembleByKey(keys=["k"], cols=["score"]).transform(t)
    assert out.num_rows == 2
    got = {out["k"][i]: out["mean(score)"][i] for i in range(2)}
    np.testing.assert_allclose(got["u"], [2.0, 4.0])


def test_explode():
    t = Table({"id": [1, 2], "words": [["a", "b"], ["c"]]})
    out = Explode(input_col="words", output_col="word").transform(t)
    assert out.num_rows == 3
    assert list(out["word"]) == ["a", "b", "c"]
    assert list(out["id"]) == [1, 1, 2]


def test_lambda_and_udf(table):
    out = Lambda(lambda t: t.with_column("c", t["a"] * 2)).transform(table)
    np.testing.assert_allclose(out["c"], table["a"] * 2)
    udf = UDFTransformer(lambda v: v + 1.0, input_col="a", output_col="a1")
    np.testing.assert_allclose(udf.transform(table)["a1"], table["a"] + 1)
    vec = UDFTransformer(lambda v: v * 3, input_col="a", output_col="a3",
                         vectorized=True)
    np.testing.assert_allclose(vec.transform(table)["a3"], table["a"] * 3)


def test_multi_column_adapter(table):
    base = UDFTransformer(lambda v: str(v).upper(), input_col="x", output_col="y")
    mca = MultiColumnAdapter(base, input_cols=["b"], output_cols=["B"])
    out = mca.transform(table)
    assert list(out["B"]) == ["X", "Y", "X", "Y", "X", "Y"]


def test_text_preprocessor():
    t = Table({"text": ["the cat sat", "catalog"]})
    tp = TextPreprocessor({"cat": "dog", "catalog": "book"},
                          input_col="text", output_col="out")
    out = tp.transform(t)
    # longest match wins: "catalog" -> "book", not "dogalog"
    assert list(out["out"]) == ["the dog sat", "book"]


def test_unicode_normalize():
    t = Table({"text": ["Café"]})
    out = UnicodeNormalize(input_col="text", output_col="out", form="NFKD").transform(t)
    assert out["out"][0].startswith("cafe")


def test_class_balancer(table):
    model = ClassBalancer(input_col="label", output_col="w").fit(table)
    out = model.transform(table)
    # minority class (1, count 2) gets weight 2x majority (0, count 4)
    w0 = out["w"][0]
    w1 = out["w"][5]
    assert w1 == pytest.approx(2 * w0)


def test_timer(table):
    inner = UDFTransformer(lambda v: v, input_col="a", output_col="a2")
    model = Timer(inner).fit(table)
    assert "a2" in model.transform(table)


def test_summarize(table):
    out = SummarizeData().transform(table)
    stats = {out["Feature"][i]: out["Mean"][i] for i in range(out.num_rows)}
    assert stats["a"] == pytest.approx(2.5)


def test_partition_consolidator(table):
    shards = Repartition(n=3).shards(table)
    merged = PartitionConsolidator().consolidate(shards)
    assert merged[0].num_rows == 6
    assert all(m.num_rows == 0 for m in merged[1:])


def test_stage_serde(table, tmp_path):
    from synapseml_tpu.core.pipeline import PipelineStage
    tp = TextPreprocessor({"cat": "dog"}, input_col="b", output_col="b2")
    tp.save(str(tmp_path / "tp"))
    loaded = PipelineStage.load(str(tmp_path / "tp"))
    assert loaded.map == {"cat": "dog"}


def test_cacher_survives_copy_and_load(tmp_path, table):
    from synapseml_tpu.core.pipeline import PipelineStage
    from synapseml_tpu.stages import Cacher

    c = Cacher()
    c.copy().transform(table)  # round-1 defect: AttributeError on copies
    c.save(str(tmp_path / "cacher"))
    loaded = PipelineStage.load(str(tmp_path / "cacher"))
    out = loaded.transform(table)
    assert out.num_rows == table.num_rows
    assert loaded.device_column("a") is not None


def test_partition_consolidator_funnels_shards(table):
    import threading

    pc = PartitionConsolidator()
    shards = Repartition(3).shards(table)
    # concurrent shard workers: exactly one (the elected owner) emits rows
    outs = [None] * 3
    barrier = threading.Barrier(3)

    def worker(i):
        barrier.wait()
        outs[i] = pc.transform(shards[i])

    threads = [threading.Thread(target=worker, args=(i,)) for i in range(3)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    emitted = sorted(o.num_rows for o in outs)
    assert emitted[:2] == [0, 0]
    # owner may have raced ahead of other feeds; remaining rows stay buffered
    assert emitted[2] >= shards[0].num_rows - 1
    pc.reset()
    outs = pc.consolidate(shards)
    assert outs[0].num_rows == table.num_rows
    assert all(o.num_rows == 0 for o in outs[1:])


def test_dynamic_minibatch_is_real_class(table):
    from synapseml_tpu import stages
    from synapseml_tpu.data import batching

    t = stages.DynamicMiniBatchTransformer()
    assert isinstance(t, stages.DynamicMiniBatchTransformer)
    assert stages.DynamicMiniBatchTransformer is batching.DynamicMiniBatchTransformer
