"""runtime/autotune.py — the shared verify-then-time prober registry.

Pins the routing loop's whole failure contract (verify mismatch ->
reference PERSISTED, timing regression -> reference persisted, probe
crash -> in-process memo only, kill switch -> zero probes and zero
table I/O), the fleet-sharing path (a sibling process's persisted
verdict is adopted with zero probes), the two refactored PR-15 routers
as lane *callers*, and the round-16 proberoute fixes: no D2H in
``best_of``'s timed region, selective negative retirement in
``RouteTable.record``, single-flight disk reads in ``lookup``.
"""
import json
import os
import subprocess
import sys
import threading
import time

import numpy as np
import pytest

from synapseml_tpu.runtime import autotune
from synapseml_tpu.runtime import proberoute as pr


@pytest.fixture
def at_env(tmp_path, monkeypatch):
    monkeypatch.setenv("SYNAPSEML_TPU_CACHE_DIR", str(tmp_path))
    monkeypatch.delenv("SYNAPSEML_AUTOTUNE", raising=False)
    yield tmp_path


def _lane(name, candidates, reference, verify_fn=None, time_fn=None,
          reps=2):
    """A host-only decomposed lane over 1-D float arrays; candidates
    map choice -> make(rargs, args) like the real registrations."""
    return autotune.register_lane(
        name,
        key_fn=lambda n: f"t|{n}",
        candidates=candidates,
        verify_fn=verify_fn,
        reference=reference,
        args_fn=lambda n: (np.arange(n, dtype=np.float64),),
        time_fn=time_fn,
        reps=reps,
    )


def _mk(fn):
    return lambda rargs, args: fn


def test_registry_round_trip_probes_once_and_persists(at_env):
    calls = {"ref": 0, "cand": 0}

    def ref(x):
        calls["ref"] += 1
        return x * 2.0

    def cand(x):
        calls["cand"] += 1
        return x + x

    ln = _lane("t_round_trip", {"ref": _mk(ref), "cand": _mk(cand)},
               "ref")
    ln.time_fn = lambda fn, a, r: 1.0 if fn is cand else 2.0
    assert ln.route(8) == "cand"
    assert ln.probes == 1
    # memoized: no second probe, same verdict
    assert ln.route(8) == "cand"
    assert ln.probes == 1
    # persisted for the fleet
    path = os.path.join(str(at_env), "autotune_t_round_trip.json")
    with open(path) as fh:
        assert json.load(fh) == {"t|8": "cand"}
    # a fresh table (new process stand-in) adopts it with zero probes
    ln2 = _lane("t_round_trip", {"ref": _mk(ref), "cand": _mk(cand)},
                "ref")
    assert ln2.route(8) == "cand"
    assert ln2.probes == 0
    assert autotune.cached("t_round_trip", 8) == "cand"


def test_verify_failure_falls_back_and_persists_reference(at_env):
    def ref(x):
        return x * 2.0

    def wrong(x):
        return x * 3.0  # mismatches the reference output

    ln = _lane("t_mismatch", {"ref": _mk(ref), "wrong": _mk(wrong)},
               "ref", time_fn=lambda fn, a, r: 0.0)
    assert ln.route(8) == "ref"
    assert ln.probes == 1
    # the reference verdict IS persisted: a deterministic mismatch
    # must not re-pay the probe after restart
    with open(os.path.join(str(at_env),
                           "autotune_t_mismatch.json")) as fh:
        assert json.load(fh) == {"t|8": "ref"}
    ln2 = _lane("t_mismatch", {"ref": _mk(ref), "wrong": _mk(wrong)},
                "ref")
    assert ln2.route(8) == "ref"
    assert ln2.probes == 0


def test_timing_regression_keeps_reference(at_env):
    def ref(x):
        return x * 2.0

    def slow(x):
        return x + x  # verifies clean, times slower

    ln = _lane("t_slow", {"ref": _mk(ref), "slow": _mk(slow)}, "ref")
    ln.time_fn = lambda fn, a, r: 5.0 if fn is slow else 1.0
    assert ln.route(8) == "ref"
    with open(os.path.join(str(at_env), "autotune_t_slow.json")) as fh:
        assert json.load(fh) == {"t|8": "ref"}


def test_probe_crash_memoized_in_process_only(at_env):
    calls = {"n": 0}

    def boom(rargs, args):
        calls["n"] += 1
        raise RuntimeError("compile exploded")

    def ref(x):
        return x

    # the REFERENCE build crashing is the probe crashing
    ln = _lane("t_crash", {"ref": boom, "cand": _mk(ref)}, "ref")
    assert ln.route(8) == "ref"
    assert ln.probes == 1
    # in-process memo: no second probe for the same key ...
    assert ln.route(8) == "ref"
    assert calls["n"] == 1
    # ... but NOTHING persisted — a transient crash must not be
    # remembered fleet-wide
    assert not os.path.exists(
        os.path.join(str(at_env), "autotune_t_crash.json"))


def test_kill_switch_zero_probes_zero_io(at_env, monkeypatch):
    monkeypatch.setenv("SYNAPSEML_AUTOTUNE", "0")

    def forbid(*a, **k):
        raise AssertionError("probe ran under the kill switch")

    ln = _lane("t_kill", {"ref": forbid, "cand": forbid}, "ref")
    ln.probe = forbid
    assert ln.route(8) == "ref"
    assert ln.cached(8) is None
    assert ln.probes == 0
    assert not os.listdir(str(at_env))
    assert autotune.snapshot()["enabled"] is False


def test_route_never_raises(at_env):
    ln = _lane("t_neverraise", {"ref": _mk(lambda x: x)}, "ref")
    ln.key_fn = lambda *a: (_ for _ in ()).throw(RuntimeError("key"))
    assert ln.route(8) == "ref"
    assert ln.cached(8) is None


def test_cross_process_sharing(at_env):
    """Process A probes and persists; process B (fresh interpreter,
    same SYNAPSEML_TPU_CACHE_DIR) serves the verdict with ZERO
    probes — the fleet-shared half of the contract, for real."""
    prog = r"""
import json, sys
import numpy as np
from synapseml_tpu.runtime import autotune

ln = autotune.register_lane(
    "t_fleet",
    key_fn=lambda n: f"t|{n}",
    candidates={"ref": lambda r, a: (lambda x: x * 2.0),
                "cand": lambda r, a: (lambda x: x + x)},
    reference="ref",
    args_fn=lambda n: (np.arange(n, dtype=np.float64),),
    time_fn=lambda fn, a, r: 1.0 if fn(np.ones(1))[0] == 2.0 else 9.0,
)
# both legs compute x*2 so time_fn cannot tell them apart by value;
# force a deterministic winner instead: candidate wins ties
print(json.dumps({"choice": ln.route(64), "probes": ln.probes}))
"""
    env = dict(os.environ, SYNAPSEML_TPU_CACHE_DIR=str(at_env),
               JAX_PLATFORMS="cpu")
    out_a = subprocess.run([sys.executable, "-c", prog], env=env,
                           capture_output=True, text=True, check=True)
    got_a = json.loads(out_a.stdout.strip().splitlines()[-1])
    assert got_a["probes"] == 1
    out_b = subprocess.run([sys.executable, "-c", prog], env=env,
                           capture_output=True, text=True, check=True)
    got_b = json.loads(out_b.stdout.strip().splitlines()[-1])
    assert got_b["probes"] == 0
    assert got_b["choice"] == got_a["choice"]


def test_poison_persists_demotion(at_env):
    ln = _lane("t_poison", {"ref": _mk(lambda x: x * 2.0),
                            "cand": _mk(lambda x: x + x)}, "ref",
               time_fn=lambda fn, a, r: 0.0)
    ln.poison(8)
    with open(os.path.join(str(at_env),
                           "autotune_t_poison.json")) as fh:
        assert json.load(fh) == {"t|8": "ref"}
    # a later route serves the demotion without probing
    assert ln.route(8) == "ref"
    assert ln.probes == 0


def test_verify_then_time_candidate_wins_ties(at_env):
    def ref(x):
        return x * 2.0

    def cand(x):
        return x + x

    got = autotune.verify_then_time(
        {"ref": ref, "cand": cand}, (np.arange(4.0),), "ref",
        time_fn=lambda fn, a, r: 1.0)
    assert got == "cand"


def test_snapshot_shape(at_env):
    ln = _lane("t_snap", {"ref": _mk(lambda x: x)}, "ref")
    ln.groups = ("some_group",)
    ln.route(4)
    snap = autotune.snapshot()
    rec = snap["lanes"]["t_snap"]
    assert rec["reference"] == "ref"
    assert rec["groups"] == ["some_group"]
    assert rec["decisions"] == {"t|4": "ref"}
    assert rec["table"] == "autotune_t_snap.json"


# -- the refactored PR-15 routers as lane callers -------------------


def test_predict_route_is_an_autotune_lane(at_env, monkeypatch):
    from synapseml_tpu.gbdt import predict_route

    predict_route.clear_cache()
    ln = autotune.lane("gbdt_predict")
    assert ln is not None and ln is predict_route._LANE
    assert ln.reference == "xla"
    assert set(ln.candidates) == {"xla", "pallas"}
    monkeypatch.setattr(predict_route.jax, "default_backend",
                        lambda: "tpu")
    monkeypatch.setattr(predict_route, "_probe",
                        lambda *a: "pallas")
    got = predict_route.route_predict(1024, 64, 512, 32, 6)
    assert got == "pallas"
    assert ln.probes == 1
    # the verdict went through the lane's shared table
    assert predict_route.cached_route(1024, 64, 512, 32, 6) == "pallas"
    predict_route.clear_cache()


def test_quant_route_is_an_autotune_lane(at_env, monkeypatch):
    from synapseml_tpu.onnx import quant_route

    quant_route.clear_cache()
    mm = autotune.lane("onnx_int8_matmul")
    cv = autotune.lane("onnx_int8_conv")
    assert mm is quant_route._MM_LANE and cv is quant_route._CONV_LANE
    assert mm.reference == "dequant" and cv.reference == "dequant"
    monkeypatch.setattr(quant_route.jax, "default_backend",
                        lambda: "tpu")
    monkeypatch.setattr(quant_route, "_probe_matmul",
                        lambda *a: "int8")
    a = np.zeros((256, 256), np.uint8)
    b = np.zeros((256, 256), np.int8)
    got = quant_route.route_matmul(a, b, np.uint8(3), np.int8(0))
    assert got == "int8"
    assert mm.probes == 1
    # same args, no second probe
    assert quant_route.route_matmul(a, b, np.uint8(3),
                                    np.int8(0)) == "int8"
    assert mm.probes == 1
    quant_route.clear_cache()


# -- round-16 proberoute fixes --------------------------------------


class _LazyFetch:
    """Device-array stand-in: completion is cheap, the value fetch is
    expensive — exactly the asymmetry the old np.asarray-based timing
    loop mis-measured."""

    D2H_SLEEP = 0.25

    def block_until_ready(self):
        return self

    def __array__(self, dtype=None, copy=None):
        time.sleep(self.D2H_SLEEP)
        return np.zeros(1)


def test_best_of_no_d2h_in_timed_region():
    t = pr.best_of(lambda: _LazyFetch(), (), reps=2)
    # jax.block_until_ready on a non-jax object must not fall back to
    # the expensive __array__ fetch; the timed region stays ~free
    assert t < _LazyFetch.D2H_SLEEP / 2


def test_record_retires_only_satisfied_negatives(tmp_path, monkeypatch):
    monkeypatch.setenv("SYNAPSEML_TPU_CACHE_DIR", str(tmp_path))
    t = pr.RouteTable("t_selective.json")
    assert t.lookup("k1") is None  # negatives armed for k1
    assert t.lookup("k2") is None  # ... and k2
    reads = {"n": 0}
    orig = pr.RouteTable._load_disk

    def counting(self):
        reads["n"] += 1
        return orig(self)

    monkeypatch.setattr(pr.RouteTable, "_load_disk", counting)
    t.record("k1", "v1")  # persists; must NOT blanket-clear k2's neg
    assert "k2" in t._neg
    before = reads["n"]
    assert t.lookup("k2") is None  # fresh negative: no disk re-read
    assert reads["n"] == before


def test_record_merge_adopts_sibling_and_retires_its_negative(
        tmp_path, monkeypatch):
    monkeypatch.setenv("SYNAPSEML_TPU_CACHE_DIR", str(tmp_path))
    t = pr.RouteTable("t_sibling.json")
    assert t.lookup("k2") is None  # negative armed
    # a sibling worker lands k2 on the shared volume
    sib = pr.RouteTable("t_sibling.json")
    sib.record("k2", "v2")
    # our own record's pre-write merge surfaces it: memo adopted, k2's
    # negative retired, visible immediately despite the TTL
    t.record("k1", "v1")
    assert "k2" not in t._neg
    assert t.lookup("k2") == "v2"


def test_lookup_single_flight(tmp_path, monkeypatch):
    monkeypatch.setenv("SYNAPSEML_TPU_CACHE_DIR", str(tmp_path))
    t = pr.RouteTable("t_flight.json")
    os.makedirs(str(tmp_path), exist_ok=True)
    with open(t.path(), "w") as fh:
        json.dump({"k": "v"}, fh)
    n = 4
    gate = threading.Barrier(n)
    reads = {"n": 0}
    orig = pr.RouteTable._load_disk

    def slow_read(self):
        reads["n"] += 1
        time.sleep(0.05)  # hold the read open so the others pile up
        return orig(self)

    monkeypatch.setattr(pr.RouteTable, "_load_disk", slow_read)
    got = [None] * n

    def worker(i):
        gate.wait()
        got[i] = t.lookup("k")

    threads = [threading.Thread(target=worker, args=(i,))
               for i in range(n)]
    for th in threads:
        th.start()
    for th in threads:
        th.join()
    assert got == ["v"] * n
    assert reads["n"] == 1  # one disk read served all concurrent missers
