import numpy as np
import pytest

from synapseml_tpu.automl import (
    DiscreteHyperParam,
    FindBestModel,
    GridSpace,
    HyperparamBuilder,
    MetricEvaluator,
    ParamSpace,
    RangeHyperParam,
    TuneHyperparameters,
)
from synapseml_tpu.data.table import Table
from synapseml_tpu.recommendation import (
    RankingAdapter,
    RankingEvaluator,
    RankingTrainValidationSplit,
    RecommendationIndexer,
    SAR,
)
from synapseml_tpu.train import (
    ComputeModelStatistics,
    ComputePerInstanceStatistics,
    TrainClassifier,
    TrainRegressor,
)


def _tabular(n=400, seed=0):
    rng = np.random.default_rng(seed)
    x1 = rng.normal(size=n)
    x2 = rng.normal(size=n)
    cat = rng.choice(["a", "b", "c"], size=n)
    y = ((x1 + (cat == "a") * 2 - x2) > 0).astype(np.float64)
    return Table({"x1": x1, "x2": x2, "cat": cat, "label": y})


def test_train_classifier():
    t = _tabular()
    model = TrainClassifier(label_col="label").fit(t)
    out = model.transform(t)
    acc = (out["prediction"] == t["label"]).mean()
    assert acc > 0.9
    stats = ComputeModelStatistics(label_col="label").transform(out)
    assert stats["accuracy"][0] == pytest.approx(acc)
    assert 0.9 < stats["AUC"][0] <= 1.0


def test_train_regressor():
    rng = np.random.default_rng(1)
    x = rng.normal(size=300)
    y = 3 * x + rng.normal(size=300) * 0.1
    t = Table({"x": x, "label": y})
    model = TrainRegressor(label_col="label").fit(t)
    out = model.transform(t)
    stats = ComputeModelStatistics(label_col="label",
                                   evaluation_metric="regression").transform(out)
    assert stats["R^2"][0] > 0.8


def test_per_instance_stats():
    t = Table({
        "label": [0.0, 1.0],
        "prediction": [0.0, 0.0],
        "probability": np.array([[0.9, 0.1], [0.6, 0.4]]),
    })
    out = ComputePerInstanceStatistics(label_col="label").transform(t)
    assert out["log_loss"][0] == pytest.approx(-np.log(0.9))
    assert out["correct"][1] == 0.0


def test_tune_hyperparameters():
    from synapseml_tpu.gbdt import LightGBMClassifier
    t = _tabular(300)
    from synapseml_tpu.featurize import Featurize
    ft = Featurize(input_cols=["x1", "x2", "cat"],
                   output_col="features").fit(t).transform(t)
    space = ParamSpace(
        HyperparamBuilder()
        .add_hyperparam("num_leaves", DiscreteHyperParam([4, 8]))
        .add_hyperparam("num_iterations", DiscreteHyperParam([10, 20]))
        .build(), seed=1)
    tuned = TuneHyperparameters(
        models=[LightGBMClassifier(features_col="features")],
        evaluator=MetricEvaluator(metric="accuracy"),
        param_space=space, number_of_runs=3, number_of_folds=2,
        parallelism=2).fit(ft)
    assert tuned.best_metric > 0.8
    assert "num_leaves" in tuned.best_params
    out = tuned.transform(ft)
    assert "prediction" in out


def test_grid_space_and_find_best():
    from synapseml_tpu.gbdt import LightGBMClassifier
    t = _tabular(250)
    from synapseml_tpu.featurize import Featurize
    ft = Featurize(input_cols=["x1", "x2", "cat"],
                   output_col="features").fit(t).transform(t)
    grid = GridSpace({"num_iterations": DiscreteHyperParam([5, 15])})
    assert len(grid.param_maps()) == 2
    fb = FindBestModel(
        models=[LightGBMClassifier(features_col="features", num_iterations=5),
                LightGBMClassifier(features_col="features", num_iterations=25)],
        evaluator=MetricEvaluator(metric="accuracy")).fit(ft)
    assert fb.best_metric >= 0.8


def _interactions(n_users=30, n_items=20, seed=0):
    """Block structure: even users like even items, odd users odd items."""
    rng = np.random.default_rng(seed)
    rows = []
    for u in range(n_users):
        liked = [i for i in range(n_items) if i % 2 == u % 2]
        for i in rng.choice(liked, size=6, replace=False):
            rows.append((f"u{u}", f"i{i}", 1.0, 1_600_000_000 + u))
    return Table({
        "user": [r[0] for r in rows],
        "item": [r[1] for r in rows],
        "rating": [r[2] for r in rows],
        "ts": [float(r[3]) for r in rows],
    })


def test_sar_recommendations():
    t = _interactions()
    indexer = RecommendationIndexer().fit(t)
    it = indexer.transform(t)
    sar = SAR(support_threshold=1, similarity_function="jaccard")
    model = sar.fit(it)
    recs = model.recommend_for_all_users(5)
    items = it["itemIdx"]
    users = it["userIdx"]
    # users should be recommended unseen items of their own parity block
    item_levels = indexer.item_indexer.levels
    for row in range(min(10, recs.num_rows)):
        uidx = recs["userIdx"][row]
        urows = np.flatnonzero(users == uidx)
        u_parity = int(item_levels[items[urows[0]]][1:]) % 2
        rec_parities = [int(item_levels[j][1:]) % 2
                        for j in recs["recommendations"][row]]
        assert np.mean([p == u_parity for p in rec_parities]) > 0.7


def test_sar_transform_scores():
    t = _interactions()
    it = RecommendationIndexer().fit(t).transform(t)
    model = SAR(support_threshold=1).fit(it)
    out = model.transform(it)
    assert (out["prediction"] >= 0).all()


def test_ranking_eval_and_split():
    ev = RankingEvaluator(k=3, metric_name="ndcgAt")
    t = Table({
        "recommendations": [[1, 2, 3], [4, 5, 6]],
        "label": [[1, 2, 3], [9, 9, 9]],
    })
    m = ev.evaluate(t)
    assert 0.4 < m < 0.6  # perfect row + zero row averages to 0.5

    inter = _interactions()
    it = RecommendationIndexer().fit(inter).transform(inter)
    tv = RankingTrainValidationSplit(
        estimator=RankingAdapter(
            recommender=SAR(support_threshold=1), k=5),
        evaluator=RankingEvaluator(k=5, metric_name="recallAtK"),
        train_ratio=0.7, seed=2).fit(it)
    assert tv.validation_metric is not None
    assert tv.validation_metric > 0.1


def test_per_instance_stats_label_mapping():
    import numpy as np
    import pytest
    from synapseml_tpu.data.table import Table
    from synapseml_tpu.train import ComputePerInstanceStatistics

    probs = np.array([[0.9, 0.1], [0.2, 0.8], [0.7, 0.3]])
    t = Table({
        "label": np.array([-1.0, 1.0, -1.0]),
        "prediction": np.array([-1.0, 1.0, 1.0]),
        "probability": probs,
    })
    # {-1,1} labels without a mapping must raise, not silently misread columns
    with pytest.raises(ValueError):
        ComputePerInstanceStatistics(label_col="label").transform(t)
    out = ComputePerInstanceStatistics(
        label_col="label", label_values=[-1.0, 1.0]).transform(t)
    np.testing.assert_allclose(
        out["log_loss"], -np.log([0.9, 0.8, 0.7]), rtol=1e-12)
