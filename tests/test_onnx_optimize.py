"""Proto-level graph optimizer (onnx/optimize.py): parallel-MatMul/QKV
packing. Mathematically exact, but XLA may accumulate the packed shape
in a different order, so parity asserts float32 tightness. Ships off by
default — docs/perf.md records the on-chip A/B that put it there."""
import numpy as np

from synapseml_tpu.onnx import import_model, proto, zoo
from synapseml_tpu.onnx.optimize import pack_parallel_matmuls


def _load_graph(blob):
    return proto.load_model(blob).graph


def test_qkv_packing_fires_and_is_exact():
    blob = zoo.transformer_encoder(100, 64, 4, 128, 2, seq_len=16, seed=0)
    g_ref = import_model(blob)                  # default: no rewrites
    g_opt = import_model(blob, optimize=True)
    # 2 layers x (3 MatMuls -> packed MatMul + Split): one node saved per
    # layer and the packed weight replaces three
    assert len(g_opt._nodes) == len(g_ref._nodes) - 2
    ids = np.random.default_rng(0).integers(0, 100, (3, 16))
    a = np.asarray(g_ref.apply(g_ref.params, ids)[0])
    b = np.asarray(g_opt.apply(g_opt.params, ids)[0])
    np.testing.assert_allclose(a, b, rtol=1e-6, atol=1e-6)


def test_packing_respects_graph_outputs_and_shared_weights():
    from synapseml_tpu.onnx import GraphBuilder

    # two parallel MatMuls, but one output IS a graph output: must not pack
    g = GraphBuilder(opset=17)
    x = g.add_input("x", np.float32, ["N", 4])
    w1 = g.add_initializer("w1", np.ones((4, 3), np.float32))
    w2 = g.add_initializer("w2", np.full((4, 5), 2.0, np.float32))
    y1 = g.add_node("MatMul", [x, w1])
    y2 = g.add_node("MatMul", [x, w2])
    g.add_output(y1, np.float32, ["N", 3])
    g.add_output(y2, np.float32, ["N", 5])
    graph = _load_graph(g.to_bytes())
    assert pack_parallel_matmuls(graph, opset=17) == 0

    # a weight consumed twice must not be folded into a pack
    g2 = GraphBuilder(opset=17)
    x = g2.add_input("x", np.float32, ["N", 4])
    w1 = g2.add_initializer("w1", np.ones((4, 3), np.float32))
    w2 = g2.add_initializer("w2", np.full((4, 3), 2.0, np.float32))
    a1 = g2.add_node("MatMul", [x, w1])
    a2 = g2.add_node("MatMul", [x, w2])
    extra = g2.add_node("MatMul", [a1, w2])  # second use of w2
    s = g2.add_node("Add", [a2, extra])
    g2.add_output(s, np.float32, ["N", 3])
    graph2 = _load_graph(g2.to_bytes())
    assert pack_parallel_matmuls(graph2, opset=17) == 0


def test_packing_pre13_split_attribute_form():
    from synapseml_tpu.onnx import GraphBuilder

    g = GraphBuilder(opset=11)
    x = g.add_input("x", np.float32, ["N", 4])
    w1 = g.add_initializer("w1", np.arange(12, dtype=np.float32).reshape(4, 3))
    w2 = g.add_initializer("w2", np.arange(20, dtype=np.float32).reshape(4, 5))
    y1 = g.add_node("MatMul", [x, w1])
    y2 = g.add_node("MatMul", [x, w2])
    out = g.add_node("Concat", [y1, y2], axis=-1)
    g.add_output(out, np.float32, ["N", 8])
    blob = g.to_bytes()
    ref = import_model(blob)
    graph = _load_graph(blob)
    assert pack_parallel_matmuls(graph, opset=11) == 1
    from synapseml_tpu.onnx.importer import ImportedGraph

    opt = ImportedGraph(graph, 11)
    xv = np.random.default_rng(1).normal(size=(6, 4)).astype(np.float32)
    np.testing.assert_allclose(
        np.asarray(ref.apply(ref.params, xv)[0]),
        np.asarray(opt.apply(opt.params, xv)[0]), rtol=1e-6, atol=1e-6)
