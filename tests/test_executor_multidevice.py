"""Multi-device data-parallel BatchedExecutor tests.

The suite runs under the conftest-forced 8-device virtual CPU platform
(XLA_FLAGS=--xla_force_host_platform_device_count=8), the same stand-in
a TPU slice gets in CI. Guarantees pinned here:

- dp-sharded buckets produce BIT-IDENTICAL outputs to the single-device
  path, including ragged final buckets and the n=1 degenerate batch;
- stream() preserves submission order across mixed bucket sizes;
- odd topologies (device counts that don't divide the pow2 buckets)
  fall back to round-robin whole-bucket dispatch, same outputs;
- the donation mask only annotates inputs an output can actually alias
  (the "Some donated buffers were not usable" fix).
"""
import threading

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from synapseml_tpu.runtime.executor import BatchedExecutor, resolve_devices

needs8 = pytest.mark.skipif(len(jax.devices()) < 8,
                            reason="needs the 8-device virtual platform")


def _mlp_fn():
    w = np.random.default_rng(0).standard_normal((6, 4)).astype(np.float32)
    # per-row program with a real contraction (not just elementwise):
    # the shape class every scoring workload is
    return (lambda p, x: (jnp.tanh(x @ p), x * 2.0 + 1.0)), w


def test_resolve_devices_specs():
    assert resolve_devices(None) is None
    assert resolve_devices("all") == tuple(jax.local_devices())
    assert resolve_devices(2) == tuple(jax.local_devices()[:2])
    two = jax.local_devices()[:2]
    assert resolve_devices(two) == tuple(two)
    with pytest.raises(ValueError):
        resolve_devices("everything")
    with pytest.raises(ValueError):
        resolve_devices(0)
    with pytest.raises(ValueError):
        resolve_devices(len(jax.local_devices()) + 1)
    with pytest.raises(ValueError):
        resolve_devices([])


@needs8
def test_sharded_bit_identical_to_single_device():
    """Bucket sizes that divide over 8 devices shard; outputs must be
    bit-identical to the single-device executor, padding and all —
    ragged final buckets (37 -> 32+8-bucket tail, 100 -> 3x32+8) and the
    n=1 and n=0 degenerate batches included."""
    fn, w = _mlp_fn()
    single = BatchedExecutor(fn, bound_args=(w,), max_bucket=32)
    multi = BatchedExecutor(fn, devices="all", bound_args=(w,),
                            max_bucket=32)
    assert multi.n_devices == 8
    for n in (0, 1, 3, 8, 32, 37, 100):
        x = np.random.default_rng(n).standard_normal(
            (n, 6)).astype(np.float32)
        got = multi(x)
        want = single(x)
        assert len(got) == len(want) == 2
        for g, s in zip(got, want):
            np.testing.assert_array_equal(np.asarray(g), np.asarray(s))


@needs8
def test_stream_order_preserved_multidevice():
    fn, w = _mlp_fn()
    ex = BatchedExecutor(fn, devices="all", bound_args=(w,), max_bucket=32)
    sizes = [3, 17, 1, 32, 9, 4, 27, 2]
    items = [np.full((s, 6), float(i), np.float32)
             for i, s in enumerate(sizes)]
    outs = list(ex.stream((a,) for a in items))
    assert len(outs) == len(items)
    for i, (x, (_, doubled)) in enumerate(zip(items, outs)):
        assert len(doubled) == sizes[i]
        np.testing.assert_array_equal(doubled, x * 2.0 + 1.0)


def test_single_entry_devices_degenerates_to_pinned_device():
    """devices=[d] must take the plain single-device path (no mesh, no
    sharding machinery) pinned to that device."""
    fn, w = _mlp_fn()
    dev = jax.local_devices()[0]
    ex = BatchedExecutor(fn, devices=[dev], bound_args=(w,))
    assert ex.devices is None and ex.n_devices == 1
    assert ex._device == dev
    ref = BatchedExecutor(fn, bound_args=(w,))
    x = np.random.default_rng(1).standard_normal((5, 6)).astype(np.float32)
    for g, s in zip(ex(x), ref(x)):
        np.testing.assert_array_equal(np.asarray(g), np.asarray(s))


def test_device_and_devices_are_mutually_exclusive():
    fn, w = _mlp_fn()
    with pytest.raises(ValueError):
        BatchedExecutor(fn, device=jax.local_devices()[0], devices="all",
                        bound_args=(w,))


@needs8
@pytest.mark.parametrize("ndev", [3, 5, 7])
def test_round_robin_fallback_odd_topologies(ndev):
    """Non-pow2 device counts never divide the pow2 buckets: every
    bucket must fall back to whole-bucket round-robin dispatch and still
    reproduce the single-device results exactly."""
    fn, w = _mlp_fn()
    devs = jax.local_devices()[:ndev]
    ex = BatchedExecutor(fn, devices=devs, bound_args=(w,), max_bucket=32)
    assert ex._layout(8) == "rr" and ex._layout(32) == "rr"
    single = BatchedExecutor(fn, bound_args=(w,), max_bucket=32)
    for n in (1, 3, 37, 100):
        x = np.random.default_rng(n).standard_normal(
            (n, 6)).astype(np.float32)
        for g, s in zip(ex(x), single(x)):
            np.testing.assert_array_equal(np.asarray(g), np.asarray(s))
    # successive buckets actually rotated over the devices
    assert ex._rr_next > len(devs)


@needs8
def test_shard_vs_rr_layout_selection():
    fn, w = _mlp_fn()
    ex8 = BatchedExecutor(fn, devices=8, bound_args=(w,))
    assert ex8._layout(8) == "shard" and ex8._layout(64) == "shard"
    ex4 = BatchedExecutor(fn, devices=4, bound_args=(w,))
    assert ex4._layout(8) == "shard"
    ex5 = BatchedExecutor(fn, devices=5, bound_args=(w,))
    assert ex5._layout(8) == "rr"


@needs8
def test_concurrent_submit_multidevice():
    """The dp fan-out sits UNDER the shared submit/drain pipeline:
    concurrent callers must still each get exactly their own answer."""
    fn, w = _mlp_fn()
    ex = BatchedExecutor(fn, devices="all", bound_args=(w,), max_bucket=16)
    results = {}
    lock = threading.Lock()

    def worker(t):
        mine = []
        for k in range(4):
            x = (np.random.default_rng(100 * t + k)
                 .standard_normal((3 + (t + k) % 9, 6)).astype(np.float32))
            _, doubled = ex.submit(x).result()
            mine.append((x, doubled))
        with lock:
            results[t] = mine

    threads = [threading.Thread(target=worker, args=(t,)) for t in range(6)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=60)
    assert len(results) == 6
    for mine in results.values():
        for x, doubled in mine:
            np.testing.assert_array_equal(doubled, x * 2.0 + 1.0)


def test_donate_mask_only_aliasable_inputs():
    """Donation annotations must match real buffer layouts: an input no
    output matches in (shape, dtype) is NOT donated — that annotation
    was the source of the per-compile 'Some donated buffers were not
    usable' warnings in bench runs."""
    # output (n, 1) never matches input (n, 6): nothing to donate
    ex = BatchedExecutor(lambda x: (x.sum(axis=1, keepdims=True),),
                         donate=True)
    assert ex._donate_mask_for([np.zeros((8, 6), np.float32)]) == (False,)
    # same shape+dtype out: donable
    ex2 = BatchedExecutor(lambda x: (x * 2.0,), donate=True)
    assert ex2._donate_mask_for([np.zeros((8, 6), np.float32)]) == (True,)
    # dtype mismatch blocks aliasing even at equal shape
    ex3 = BatchedExecutor(lambda x: (x.astype(jnp.bfloat16),), donate=True)
    assert ex3._donate_mask_for([np.zeros((8, 6), np.float32)]) == (False,)
    # two inputs, one matching output: exactly one donated (multiset)
    ex4 = BatchedExecutor(lambda a, b: (a + b,), donate=True)
    assert ex4._donate_mask_for(
        [np.zeros((8, 4), np.float32), np.zeros((8, 4), np.float32)]) \
        == (True, False)
    # donate=False masks everything off
    ex5 = BatchedExecutor(lambda x: (x * 2.0,), donate=False)
    assert ex5._donate_mask_for([np.zeros((8, 6), np.float32)]) == (False,)


def test_no_unusable_donation_warning():
    """With the mask, a donation-hostile program (no aliasable output)
    compiles without the 'donated buffers were not usable' warning even
    when donation is forced on."""
    import warnings

    ex = BatchedExecutor(lambda x: (x.sum(axis=1, keepdims=True),),
                         donate=True, min_bucket=8)
    x = np.random.default_rng(0).standard_normal((8, 6)).astype(np.float32)
    with warnings.catch_warnings(record=True) as rec:
        warnings.simplefilter("always")
        (out,) = ex(x)
    np.testing.assert_allclose(out, x.sum(axis=1, keepdims=True),
                               rtol=1e-6)
    bad = [w for w in rec if "donated buffers" in str(w.message).lower()]
    assert not bad, [str(w.message) for w in bad]


@needs8
def test_onnxmodel_devices_param_bit_identical():
    from synapseml_tpu.data.table import Table
    from synapseml_tpu.onnx import ONNXModel, zoo

    blob = zoo.mlp([16, 32], num_classes=4, seed=0)
    feats = np.random.default_rng(0).standard_normal(
        (37, 16)).astype(np.float32)
    base = ONNXModel(model_bytes=blob).transform(Table({"input": feats}))
    multi_model = ONNXModel(model_bytes=blob)
    multi_model.set(devices="all")
    assert multi_model._executor().n_devices == 8
    multi = multi_model.transform(Table({"input": feats}))
    for col in base.columns:
        np.testing.assert_array_equal(np.asarray(base[col]),
                                      np.asarray(multi[col]))


@needs8
def test_image_featurizer_devices_param_bit_identical():
    from synapseml_tpu.data.table import Table
    from synapseml_tpu.image.featurizer import ImageFeaturizer
    from synapseml_tpu.onnx import zoo

    rng = np.random.default_rng(0)
    imgs = np.empty(5, dtype=object)
    imgs[:] = [rng.integers(0, 255, (32, 32, 3)).astype(np.float32)
               for _ in range(5)]
    table = Table({"image": imgs})
    kw = dict(model_bytes=zoo.tiny_resnet(image_size=32),
              cut_output_layers=1, image_size=32,
              input_col="image", output_col="feats")
    base = ImageFeaturizer(**kw).transform(table)
    multi = ImageFeaturizer(devices="all", **kw).transform(table)
    np.testing.assert_array_equal(np.stack(list(base["feats"])),
                                  np.stack(list(multi["feats"])))


def test_device_for_channel_round_robin():
    from synapseml_tpu.io.serving import device_for_channel

    devs = jax.local_devices()
    for i in range(2 * len(devs)):
        assert device_for_channel(i) == devs[i % len(devs)]
    assert device_for_channel(3, devices=devs[:2]) == devs[1]


# ---------------------------------------------------------------------------
# tensor parallelism: the dp×tp mesh lane (parallel/partition_rules.py)


def _tp_transformer():
    from synapseml_tpu.onnx import import_model, zoo

    g = import_model(zoo.transformer_encoder(
        100, 64, 4, 128, 2, seq_len=16, seed=3))
    return g, (lambda p, x: g.apply(p, x))


def _tp_specs(g, dp, tp):
    from jax.sharding import Mesh

    from synapseml_tpu.parallel.partition_rules import match_partition_rules

    mesh = Mesh(np.array(jax.devices()[:dp * tp]).reshape(dp, tp),
                ("dp", "tp"))
    specs, report = match_partition_rules(g.params, mesh)
    return specs, report


@needs8
@pytest.mark.parametrize("tp", [2, 4])
def test_executor_tp_bit_identical_and_sharded_at_rest(tp):
    """The tentpole contract end to end: params live tp-sharded at rest
    (max per-device bytes == sharded/tp + replicated remainder), yet
    every reply is BITWISE equal to the single-device executor — the
    gather formulation all-gathers weights at entry, so no float ever
    reassociates. Covers the dp-divisible shard layout AND the
    indivisible tp_rep layout (n=5)."""
    from synapseml_tpu.parallel.onnx_tp import param_bytes_per_device

    g, fn = _tp_transformer()
    specs, report = _tp_specs(g, 8 // tp, tp)
    single = BatchedExecutor(fn, bound_args=(g.params,), max_bucket=8)
    tpex = BatchedExecutor(fn, bound_args=(g.params,), max_bucket=8,
                           devices="all", tensor_parallel=tp,
                           bound_specs=(specs,))
    try:
        assert tpex._mesh_shape() == (8 // tp, tp, "gather")
        # at-rest placement: the registry's sharded set really splits
        per_dev = param_bytes_per_device(tpex._bound)
        total = sum(v.nbytes for v in g.params.values())
        sharded = sum(g.params[c.param].nbytes for c in report.sharded())
        assert len(per_dev) == 8
        assert max(per_dev.values()) == sharded // tp + (total - sharded)
        for n in (8, 5, 1):  # shard, tp_rep, tp_rep layouts
            ids = np.random.default_rng(n).integers(0, 100, (n, 16))
            want = [np.asarray(a) for a in single.submit(ids).result()]
            got = [np.asarray(a) for a in tpex.submit(ids).result()]
            for w, t in zip(want, got):
                assert w.dtype == t.dtype
                assert np.array_equal(
                    w.view(np.uint32), t.view(np.uint32)), (n, tp)
    finally:
        single.close()
        tpex.close()


@needs8
def test_executor_tp_param_bytes_gauges_live_and_clear():
    """tp_param_bytes{device=} gauges register at executor build with
    one nonzero entry per mesh device, surface through memory_snapshot
    (the /debug/memory payload), and clear on close()."""
    import gc

    from synapseml_tpu.runtime import perfwatch as pw

    # the gauges sum over ALL live multi-device executors (earlier
    # tests' model-cached ones included) — assert this executor's
    # DELTA, after flushing any pending finalizers
    gc.collect()
    before = pw.tp_param_bytes()
    g, fn = _tp_transformer()
    specs, _ = _tp_specs(g, 2, 4)
    ex = BatchedExecutor(fn, bound_args=(g.params,), max_bucket=8,
                         devices="all", tensor_parallel=4,
                         bound_specs=(specs,))
    try:
        tpb = pw.tp_param_bytes()
        delta = {d: tpb.get(d, 0) - before.get(d, 0) for d in tpb}
        assert len(delta) == 8 and all(v > 0 for v in delta.values())
        snap = pw.memory_snapshot(force=True)
        by_dev = {d["device"]: d for d in snap["devices"]}
        for dev, n in tpb.items():
            assert by_dev[dev]["tp_param_bytes"] == n
        assert snap["totals"]["tp_param_bytes"] == sum(tpb.values())
    finally:
        ex.close()
    assert pw.tp_param_bytes() == before


@needs8
def test_executor_tp_validation():
    g, fn = _tp_transformer()
    with pytest.raises(ValueError, match="requires devices"):
        BatchedExecutor(fn, bound_args=(g.params,), tensor_parallel=2)
    with pytest.raises(ValueError, match="does not divide"):
        BatchedExecutor(fn, bound_args=(g.params,), devices="all",
                        tensor_parallel=3)
    with pytest.raises(ValueError, match="tp_compute"):
        BatchedExecutor(fn, bound_args=(g.params,), devices="all",
                        tensor_parallel=2, tp_compute="magic")


@needs8
def test_executor_tp_no_recompiles_after_warmup():
    """The recompile sentinel must stay silent under tp: every layout
    (shard + tp_rep) AOT-warms, and serving-shaped traffic afterwards
    never lands on a dispatch-path compile."""
    from synapseml_tpu.runtime import telemetry as tm

    def recompiles():
        return sum(
            float(ln.rsplit(" ", 1)[1])
            for ln in tm.prometheus_text().splitlines()
            if ln.startswith("synapseml_executor_recompiles_total"))

    g, fn = _tp_transformer()
    specs, _ = _tp_specs(g, 2, 4)
    ex = BatchedExecutor(fn, bound_args=(g.params,), max_bucket=8,
                         devices="all", tensor_parallel=4,
                         bound_specs=(specs,))
    try:
        ex.warmup([((16,), np.int64)])
        before = recompiles()
        for n in (8, 5, 3, 1):
            ids = np.random.default_rng(n).integers(0, 100, (n, 16))
            ex.submit(ids).result()
        assert recompiles() == before
    finally:
        ex.close()


@needs8
def test_onnxmodel_tensor_parallel_bit_identical():
    """ONNXModel wiring: tensor_parallel=N scores byte-identically to
    the default single-device path, and the coverage report names the
    rule that claimed each param."""
    from synapseml_tpu.data.table import Table
    from synapseml_tpu.onnx import ONNXModel, zoo

    payload = zoo.transformer_encoder(100, 64, 4, 128, 2,
                                      seq_len=16, seed=3)
    tok = np.random.default_rng(0).integers(
        0, 100, size=(8, 16)).astype(np.int32)
    table = Table({"tokens": tok})
    kw = dict(model_payload=payload, mini_batch_size=8,
              feed_dict={"tokens": "tokens"})

    def out(t):
        return np.stack([np.asarray(x, np.float32)
                         for x in t[t.columns[-1]]])

    base = ONNXModel().set(**kw)
    want = out(base.transform(table))
    m = ONNXModel().set(devices="all", tensor_parallel=4, **kw)
    got = out(m.transform(table))
    try:
        assert np.array_equal(want.view(np.uint32), got.view(np.uint32))
        cov = m.partition_coverage()
        assert cov["summary"]["params"] == 37
        assert cov["summary"]["sharded"] >= 16
        assert base.partition_coverage() is None
    finally:
        m._executor().close()


@needs8
def test_onnxmodel_tensor_parallel_validation():
    from synapseml_tpu.onnx import ONNXModel, zoo

    payload = zoo.mlp([16, 32], num_classes=4, seed=0)
    with pytest.raises(ValueError, match="requires"):
        ONNXModel().set(model_payload=payload,
                        tensor_parallel=2)._executor()
    with pytest.raises(ValueError, match="divide"):
        ONNXModel().set(model_payload=payload, devices="all",
                        tensor_parallel=3)._executor()
