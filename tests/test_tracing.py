"""Distributed tracing (docs/observability.md, "Distributed tracing"):
W3C traceparent accept/echo, trace-labeled spans, the configurable
completed-span ring, OpenMetrics histogram exemplars, the TraceArchive
tail-retention sink, and fleet-wide trace stitching."""
import json
import os
import urllib.error
import urllib.request

import numpy as np
import pytest

from synapseml_tpu.data.table import Table
from synapseml_tpu.io.serving import ContinuousServer, make_reply
from synapseml_tpu.runtime import telemetry as tm
from synapseml_tpu.runtime import tracearchive as ta

TID = "ab" * 16
SID = "cd" * 8


# -- traceparent grammar ----------------------------------------------------

def test_parse_traceparent_valid():
    assert tm.parse_traceparent(f"00-{TID}-{SID}-01") == (TID, SID)
    # unknown-but-parseable version: accepted (W3C forward compat),
    # including trailing "-suffixed" data a future version may append
    assert tm.parse_traceparent(f"42-{TID}-{SID}-00") == (TID, SID)
    assert tm.parse_traceparent(
        f"cc-{TID}-{SID}-01-futuredata") == (TID, SID)
    # surrounding whitespace tolerated
    assert tm.parse_traceparent(f"  00-{TID}-{SID}-01 ") == (TID, SID)


@pytest.mark.parametrize("header", [
    None, "", "garbage",
    f"ff-{TID}-{SID}-01",             # version ff forbidden
    f"00-{'0' * 32}-{SID}-01",        # all-zero trace id
    f"00-{TID}-{'0' * 16}-01",        # all-zero parent id
    f"00-{TID.upper()}-{SID}-01",     # uppercase hex is invalid
    f"00-{TID[:-2]}-{SID}-01",        # short trace id
    f"00-{TID}-{SID}",                # missing flags
    f"00-{TID}-{SID}-01-extra",       # version 00 is EXACTLY 4 fields
])
def test_parse_traceparent_rejects(header):
    assert tm.parse_traceparent(header) is None


def test_format_traceparent_round_trips():
    tp = tm.format_traceparent(TID, SID)
    assert tm.parse_traceparent(tp) == (TID, SID)
    assert tp.endswith("-01")
    assert tm.format_traceparent(TID, SID, sampled=False).endswith("-00")


def test_minted_ids_are_well_formed():
    tid, sid = tm.mint_trace_id(), tm.mint_span_id()
    assert tm.parse_traceparent(f"00-{tid}-{sid}-01") == (tid, sid)


# -- spans carry trace context ----------------------------------------------

def test_span_adopts_and_mints_trace_context():
    span = tm.start_span("rid-t1", trace_id=TID, parent_span_id=SID,
                         origin="srv")
    try:
        assert (span.trace_id, span.parent_span_id) == (TID, SID)
        assert span.origin == "srv"
        bd = span.breakdown()
        assert bd["trace_id"] == TID and bd["parent_span_id"] == SID
        assert bd["origin"] == "srv" and bd["span_id"] == span.span_id
        minted = tm.start_span("rid-t2")
        assert len(minted.trace_id) == 32 and len(minted.span_id) == 16
        assert minted.parent_span_id == ""
        minted.finish()
    finally:
        span.finish()


def test_trace_spans_collects_every_leg():
    a = tm.start_span("rid-l1", trace_id=TID, origin="s1")
    b = tm.start_span("rid-l2", trace_id=TID, origin="s2")
    other = tm.start_span("rid-l3")
    a.finish()
    legs = tm.trace_spans(TID)
    try:
        rids = [leg["rid"] for leg in legs]
        assert "rid-l1" in rids and "rid-l2" in rids  # done AND active
        assert "rid-l3" not in rids
        assert tm.trace_spans(tm.mint_trace_id()) == []
    finally:
        b.finish()
        other.finish()


# -- the completed-span ring knob (SYNAPSEML_SPAN_RING) ---------------------

def test_span_ring_depth_regression():
    """A deep ring retains, a shallow ring evicts — the operator knob
    the 1024 hardcode became."""
    prev = tm.span_ring_depth()
    try:
        tm.configure_span_ring(4)
        for i in range(8):
            tm.start_span(f"ring-{i}").finish()
        held = {s["rid"] for s in tm.completed_spans(limit=64)
                if s["rid"].startswith("ring-")}
        assert held == {f"ring-{i}" for i in range(4, 8)}
        tm.configure_span_ring(64)  # deep again: everything survives
        for i in range(8, 16):
            tm.start_span(f"ring-{i}").finish()
        held = {s["rid"] for s in tm.completed_spans(limit=64)
                if s["rid"].startswith("ring-")}
        # the resize kept the newest 4 and the 8 new ones
        assert held >= {f"ring-{i}" for i in range(4, 16)}
    finally:
        tm.configure_span_ring(prev)


def test_span_ring_env_validation(monkeypatch):
    prev = tm.span_ring_depth()
    try:
        monkeypatch.setenv("SYNAPSEML_SPAN_RING", "2048")
        assert tm.configure_span_ring() == 2048
        # 0, negative, and garbage all degrade to the default
        for bad in ("0", "-5", "not-a-number"):
            monkeypatch.setenv("SYNAPSEML_SPAN_RING", bad)
            assert tm.configure_span_ring() == tm.DEFAULT_SPAN_RING
        monkeypatch.delenv("SYNAPSEML_SPAN_RING")
        assert tm.configure_span_ring() == tm.DEFAULT_SPAN_RING
        with pytest.raises(ValueError):
            tm.configure_span_ring(0)  # explicit bad arg raises
    finally:
        tm.configure_span_ring(prev)


# -- exemplars --------------------------------------------------------------

def test_histogram_exemplar_last_write_wins_per_bucket():
    h = tm.histogram("serving_request_seconds", server="trace_unit")
    h.observe(0.0003, exemplar="t" * 32)
    h.observe(0.0004, exemplar=TID)   # same bucket: last write wins
    h.observe(2.0)                    # no exemplar on this bucket
    om = tm.prometheus_text(openmetrics=True)
    line = next(ln for ln in om.splitlines()
                if 'server="trace_unit"' in ln and f'"{TID}"' in ln)
    assert f'# {{trace_id="{TID}"}} 0.0004' in line
    assert ("t" * 32) not in om
    assert om.rstrip().endswith("# EOF")
    # the default exposition never carries exemplars
    plain = tm.prometheus_text()
    assert "trace_id=" not in plain and "# EOF" not in plain


# -- TraceArchive -----------------------------------------------------------

@pytest.fixture
def archive(tmp_path):
    prev_enabled = ta.set_enabled(True)
    ta.configure(directory=str(tmp_path), head_every=0,
                 max_bytes=ta.DEFAULT_MAX_BYTES)
    yield str(tmp_path)
    ta.reset()
    ta.configure(directory=None, head_every=0)
    ta._S.dir = None
    ta.set_enabled(prev_enabled)


def _finished_span(rid, trace_id, status="ok"):
    span = tm.start_span(rid, trace_id=trace_id, origin="arch")
    span.finish(status)
    return span


def test_archive_keeps_breaches_drops_healthy(archive):
    kept = _finished_span("arch-bad", TID, status="error")
    assert ta.maybe_archive(kept, 500, 0.01) == ta.CLASS_BREACH
    # healthy + under threshold + head sampling off: dropped
    healthy = _finished_span("arch-ok", tm.mint_trace_id())
    assert ta.maybe_archive(healthy, 200, 0.01) is None
    # latency breach archives even a 200
    slow = _finished_span("arch-slow", tm.mint_trace_id())
    assert ta.maybe_archive(slow, 200, 10.0,
                            threshold_s=0.25) == ta.CLASS_BREACH
    recs = ta.scan(TID, directory=archive)
    assert len(recs) == 1 and recs[0]["rid"] == "arch-bad"
    assert recs[0]["retention"] == ta.CLASS_BREACH
    assert recs[0]["status_code"] == 500
    assert ta.scan(healthy.trace_id, directory=archive) == []


def test_archive_head_samples_healthy(archive):
    ta.configure(head_every=2)  # every 2nd healthy reply
    kept = 0
    for i in range(6):
        span = _finished_span(f"head-{i}", tm.mint_trace_id())
        if ta.maybe_archive(span, 200, 0.001, threshold_s=1.0):
            kept += 1
    assert kept == 3


def test_archive_rotation_is_atomic_and_bounded(archive):
    ta.configure(max_bytes=4096)
    for i in range(64):  # each record is a few hundred bytes
        span = _finished_span(f"rot-{i}", tm.mint_trace_id(),
                              status="error")
        assert ta.maybe_archive(span, 500, 0.01)
    live = ta.archive_path()
    assert os.path.exists(live) and os.path.exists(live + ".1")
    assert os.path.getsize(live) <= 4096 + 1024
    # rotated records still scannable, torn tail lines tolerated
    with open(live, "a", encoding="utf-8") as fh:
        fh.write('{"torn')
    some = ta.scan(_finished_span("rot-last", TID).trace_id,
                   directory=archive)
    assert some == []  # unarchived span: scan just returns nothing


def test_archive_disabled_is_a_noop(archive):
    ta.set_enabled(False)
    span = _finished_span("off", TID, status="error")
    assert ta.maybe_archive(span, 500, 0.01) is None
    assert ta.scan(TID, directory=archive) == []


# -- serving end to end -----------------------------------------------------

def _echo_pipeline(table: Table) -> Table:
    replies = np.empty(table.num_rows, dtype=object)
    for i, v in enumerate(table["value"]):
        replies[i] = make_reply({"echo": v})
    return table.with_column("reply", replies)


def _post(url, obj, headers=None, timeout=30):
    hdrs = {"Content-Type": "application/json"}
    hdrs.update(headers or {})
    req = urllib.request.Request(url, data=json.dumps(obj).encode(),
                                 method="POST", headers=hdrs)
    try:
        with urllib.request.urlopen(req, timeout=timeout) as r:
            return r.status, dict(r.headers), r.read()
    except urllib.error.HTTPError as e:
        body = e.read()
        return e.code, dict(e.headers or {}), body


def _get_json(url, timeout=30):
    with urllib.request.urlopen(urllib.request.Request(url),
                                timeout=timeout) as r:
        return r.status, json.loads(r.read())


@pytest.fixture
def server(tmp_path):
    ta.configure(directory=str(tmp_path), head_every=0)
    cs = ContinuousServer("trace_e2e", _echo_pipeline,
                          max_batch=8).start()
    yield cs
    cs.stop()
    ta.reset()
    ta._S.dir = None


def test_serving_traceparent_round_trip(server):
    st, hdrs, _body = _post(server.url, {"x": [1.0]},
                            headers={"traceparent":
                                     f"00-{TID}-{SID}-01"})
    assert st == 200
    echo = hdrs.get("traceparent", "")
    parsed = tm.parse_traceparent(echo)
    assert parsed is not None and parsed[0] == TID
    assert parsed[1] != SID  # OUR span id, not an echo of the caller's
    rid = hdrs["X-Request-Id"]
    host = server.url.split("//")[1].rstrip("/")
    st, span = _get_json(f"http://{host}/span/{rid}")
    assert st == 200
    assert span["trace_id"] == TID
    assert span["parent_span_id"] == SID
    assert span["span_id"] == parsed[1]  # header names the server leg
    assert span["origin"] == "trace_e2e"


def test_serving_mints_when_header_absent_or_bad(server):
    for headers in ({}, {"traceparent": "not-a-traceparent"}):
        st, hdrs, _body = _post(server.url, {"x": [2.0]},
                                headers=headers)
        assert st == 200
        parsed = tm.parse_traceparent(hdrs.get("traceparent", ""))
        assert parsed is not None  # minted, well-formed, echoed


def test_serving_trace_endpoint(server):
    tid = tm.mint_trace_id()
    _post(server.url, {"x": [3.0]},
          headers={"traceparent": f"00-{tid}-{SID}-01"})
    host = server.url.split("//")[1].rstrip("/")
    st, trace = _get_json(f"http://{host}/trace/{tid}")
    assert st == 200
    assert trace["trace_id"] == tid
    assert len(trace["legs"]) == 1
    assert trace["legs"][0]["origin"] == "trace_e2e"
    # unknown trace: 404; malformed id: 400
    with pytest.raises(urllib.error.HTTPError) as ei:
        _get_json(f"http://{host}/trace/{tm.mint_trace_id()}")
    assert ei.value.code == 404
    with pytest.raises(urllib.error.HTTPError) as ei:
        _get_json(f"http://{host}/trace/NOT-HEX")
    assert ei.value.code == 400


def test_serving_shed_paths_echo_traceparent(server):
    server.server.begin_drain()
    try:
        st, hdrs, _body = _post(server.url, {"x": [4.0]},
                                headers={"traceparent":
                                         f"00-{TID}-{SID}-01"})
        assert st == 503
        parsed = tm.parse_traceparent(hdrs.get("traceparent", ""))
        assert parsed is not None and parsed[0] == TID
    finally:
        server.server._draining.clear()


def test_serving_breach_lands_in_archive(server, tmp_path):
    tid = tm.mint_trace_id()
    st, _hdrs, _body = _post(server.url, {"x": [5.0]},
                             headers={"traceparent":
                                      f"00-{tid}-{SID}-01",
                                      "X-Deadline-Ms": "0.01"})
    assert st == 504  # pre-expired deadline: shed before scoring
    # the reply flushes to the client BEFORE the archive append (round
    # 17: a reply must never wait on the dump volume), so poll briefly
    import time as _time

    deadline = _time.monotonic() + 5.0
    recs = ta.scan(tid, directory=str(tmp_path))
    while not recs and _time.monotonic() < deadline:
        _time.sleep(0.02)
        recs = ta.scan(tid, directory=str(tmp_path))
    assert recs, "the 504 shed never reached the archive"
    assert recs[0]["retention"] == ta.CLASS_BREACH
    assert recs[0]["status_code"] == 504
    assert recs[0]["origin"] == "trace_e2e"


def test_serving_openmetrics_negotiation(server):
    tid = tm.mint_trace_id()
    _post(server.url, {"x": [6.0]},
          headers={"traceparent": f"00-{tid}-{SID}-01"})
    host = server.url.split("//")[1].rstrip("/")
    req = urllib.request.Request(
        f"http://{host}/metrics",
        headers={"Accept": "application/openmetrics-text"})
    with urllib.request.urlopen(req, timeout=30) as r:
        assert r.headers["Content-Type"].startswith(
            "application/openmetrics-text")
        om = r.read().decode()
    assert f'trace_id="{tid}"' in om
    assert om.rstrip().endswith("# EOF")
    with urllib.request.urlopen(f"http://{host}/metrics",
                                timeout=30) as r:
        assert r.headers["Content-Type"].startswith("text/plain")
        assert "trace_id=" not in r.read().decode()


# -- loadgen + fleet stitching ----------------------------------------------

def test_loadgen_mints_traces_and_reports_slowest(server):
    from tools.loadgen import run_load

    s = run_load(server.url, rps=60, duration_s=0.4, shapes=[2],
                 seed=9)
    assert s["hung"] == 0
    assert s["slowest"]
    top = s["slowest"][0]
    assert set(top) == {"rid", "trace_id", "latency_s", "status",
                        "target"}
    # the minted trace resolved server-side: its leg is in the store
    legs = tm.trace_spans(top["trace_id"])
    assert any(leg["rid"] == top["rid"] for leg in legs)
    # seed-determinism: the same seed mints the same trace ids
    s2 = run_load(server.url, rps=60, duration_s=0.4, shapes=[2],
                  seed=9)
    n = min(s["scheduled"], s2["scheduled"], 3)
    assert n > 0


def test_fleet_trace_stitching(tmp_path):
    """The controller's /fleet/trace merges live legs from two
    'replicas' (two in-process servers — distinct origins) with an
    archived leg from a dead one, dedups shared span_ids, and caches
    the stitched result."""
    from synapseml_tpu.runtime.autoscale import FleetPolicy
    from tools.fleet.controller import (FleetController,
                                        LocalProcessBackend)

    ta.configure(directory=str(tmp_path), head_every=0)
    tid = tm.mint_trace_id()
    a = ContinuousServer("fleet_tr_a", _echo_pipeline,
                         max_batch=4).start()
    b = ContinuousServer("fleet_tr_b", _echo_pipeline,
                         max_batch=4).start()
    controller = None
    try:
        tp = f"00-{tid}-{SID}-01"
        st, _h, _ = _post(a.url, {"x": [1.0]},
                          headers={"traceparent": tp})
        assert st == 200
        st, _h, _ = _post(b.url, {"x": [1.0]},
                          headers={"traceparent": tp})
        assert st == 200
        # a third, "dead" replica testifies only through the archive
        dead = tm.Span("dead-rid", trace_id=tid, origin="fleet_tr_dead")
        dead.status = "error"
        assert ta.maybe_archive(dead, 500, 0.02) == ta.CLASS_BREACH

        class FakeReplica:
            def __init__(self, name, url):
                self.name, self.url = name, url

            def alive(self):
                return True

        policy = FleetPolicy(min_replicas=1, max_replicas=2)
        controller = FleetController(LocalProcessBackend(), policy,
                                     archive_dir=str(tmp_path))
        controller.replicas = [FakeReplica("fleet_tr_a", a.url),
                               FakeReplica("fleet_tr_b", b.url)]
        base = controller.serve()
        st, stitched = _get_json(base + f"/fleet/trace/{tid}")
        assert st == 200
        legs = stitched["legs"]
        origins = {leg["replica"] for leg in legs}
        # both servers share one process-wide span store, so each
        # fan-out returns BOTH live legs — dedup must leave exactly
        # two live legs plus the archived one
        assert {"fleet_tr_a", "fleet_tr_b",
                "fleet_tr_dead"} <= origins
        assert len(legs) == 3
        assert all(leg["trace_id"] == tid for leg in legs)
        archived = [leg for leg in legs if leg["source"] == "archive"]
        assert len(archived) == 1
        assert archived[0]["replica"] == "fleet_tr_dead"
        # cached: a repeat inside the TTL returns the same payload
        assert controller.stitch_trace(tid) is not None
        assert tid in controller._trace_cache
        # unknown trace: 404 from the endpoint
        with pytest.raises(urllib.error.HTTPError) as ei:
            _get_json(base + f"/fleet/trace/{tm.mint_trace_id()}")
        assert ei.value.code == 404
        with pytest.raises(urllib.error.HTTPError) as ei:
            _get_json(base + "/fleet/trace/zz")
        assert ei.value.code == 400
    finally:
        if controller is not None:
            controller._stop.set()
            if controller._httpd is not None:
                controller._httpd.shutdown()
                controller._httpd.server_close()
        a.stop()
        b.stop()
        ta.reset()
        ta._S.dir = None


def test_flight_snapshot_embeds_completed_spans():
    from synapseml_tpu.runtime import blackbox as bb

    span = tm.start_span("flight-span", trace_id=TID)
    span.finish()
    snap = bb.snapshot(stacks=False)
    assert "spans" in snap
    assert any(s["rid"] == "flight-span" and s["trace_id"] == TID
               for s in snap["spans"])
