import numpy as np
import pytest

from synapseml_tpu.data.table import Table
from synapseml_tpu.isolationforest import IsolationForest
from synapseml_tpu.knn import BallTree, ConditionalKNN, KNN


@pytest.fixture
def points():
    rng = np.random.default_rng(0)
    return rng.normal(size=(200, 8)).astype(np.float32)


def test_knn_exact(points):
    t = Table({"features": points, "id": np.arange(200)})
    model = KNN(input_col="features", output_col="matches",
                values_col="id", k=4).fit(t)
    q = Table({"features": points[:5]})
    out = model.transform(q)
    for i in range(5):
        matches = out["matches"][i]
        assert matches[0]["value"] == i  # nearest neighbour of a point is itself
        assert matches[0]["distance"] == pytest.approx(0.0, abs=1e-3)
        dists = [m["distance"] for m in matches]
        assert dists == sorted(dists)


def test_knn_matches_balltree(points):
    t = Table({"features": points})
    model = KNN(input_col="features", output_col="matches", k=6).fit(t)
    out = model.transform(Table({"features": points[10:13]}))
    tree = BallTree(points)
    for i, row in enumerate(out["matches"]):
        expected = tree.query(points[10 + i], k=6)
        assert {m["index"] for m in row} == {m["index"] for m in expected}


def test_conditional_knn(points):
    labels = ["a" if i % 2 == 0 else "b" for i in range(200)]
    t = Table({"features": points, "labels": labels})
    model = ConditionalKNN(input_col="features", output_col="matches",
                           label_col="labels", k=3).fit(t)
    q = Table({"features": points[:4],
               "conditioner": [["b"]] * 4})
    out = model.transform(q)
    for row in out["matches"]:
        assert all(m["label"] == "b" for m in row)


def test_isolation_forest():
    rng = np.random.default_rng(1)
    normal = rng.normal(size=(300, 4)).astype(np.float32)
    outliers = rng.normal(size=(6, 4)).astype(np.float32) * 8 + 12
    x = np.concatenate([normal, outliers])
    t = Table({"features": x})
    model = IsolationForest(num_estimators=50, max_samples=128,
                            contamination=0.02, random_seed=3).fit(t)
    out = model.transform(t)
    scores = out["outlierScore"]
    # outliers should score above the typical inlier
    assert scores[300:].mean() > scores[:300].mean() + 0.1
    # contamination threshold flags mostly the planted outliers
    flagged = np.flatnonzero(out["prediction"])
    assert len(set(flagged) & set(range(300, 306))) >= 4


def test_knn_serde(points, tmp_path):
    from synapseml_tpu.core.pipeline import PipelineStage
    t = Table({"features": points})
    model = KNN(input_col="features", output_col="m", k=2).fit(t)
    model.save(str(tmp_path / "knn"))
    loaded = PipelineStage.load(str(tmp_path / "knn"))
    out = loaded.transform(Table({"features": points[:2]}))
    assert out["m"][0][0]["index"] == 0
