"""locksan (runtime/locksan.py) + the rules_dynsan cross-check.

Pinned here:

- the three detectors on deliberately-broken fixtures: a lock-order
  inversion, a blocking call under a lock, and a real ABBA deadlock the
  watchdog must report (the ABBA legs self-unwedge via acquire
  timeouts, and every wait carries a hard wall so a regression fails
  fast instead of hanging CI);
- the disabled hot path is ONE attribute test ahead of the raw op —
  the zero-overhead contract the knob table promises;
- lock identity: migrated sites carry their static CC002 labels, so
  the observed graph and the static model share a vocabulary (the
  whole point of the factory migration);
- the static<->dynamic diff: an observed edge the static model cannot
  reach is a DS001 model-gap finding, a modeled edge is not, and a
  ``# synlint: disable=DS001`` at the acquire site suppresses it;
- editing rules_dynsan.py invalidates cached analysis summaries (the
  analyzer-version hash covers the new pack).
"""
import json
import os
import threading
import time

import pytest

from synapseml_tpu.runtime import locksan

HARD = 30  # wall-clock ceiling for any single wait in this file


@pytest.fixture
def sanitizer():
    """Enabled sanitizer with a fast watchdog, always torn down."""
    locksan.disable()
    locksan.enable(watchdog_s=0.3)
    locksan.reset()
    yield locksan
    locksan.disable()


def _join(threads):
    for t in threads:
        t.join(timeout=HARD)
        assert not t.is_alive(), f"{t.name} wedged past the {HARD}s wall"


# -- detectors ----------------------------------------------------------


def test_inversion_detected(sanitizer):
    a = locksan.make_lock("t:_A")
    b = locksan.make_lock("t:_B")
    with a:
        with b:
            pass
    with b:
        with a:  # closes the cycle: B -> A after A -> B
            pass
    kinds = [f["kind"] for f in locksan.findings()]
    assert kinds == ["inversion"]
    f = locksan.findings()[0]
    assert {"t:_A", "t:_B"} == {f["outer"], f["inner"]}
    assert "t:_A" in f["detail"] and "t:_B" in f["detail"]


def test_consistent_order_is_clean(sanitizer):
    a = locksan.make_lock("t:_A")
    b = locksan.make_lock("t:_B")
    for _ in range(3):
        with a:
            with b:
                pass
    assert locksan.findings() == []
    assert [(e["outer"], e["inner"], e["count"])
            for e in locksan.edges()] == [("t:_A", "t:_B", 3)]


def test_blocking_under_lock_detected(sanitizer):
    lk = locksan.make_lock("t:_HELD")
    with lk:
        time.sleep(0.01)
    fs = locksan.findings()
    assert [f["kind"] for f in fs] == ["blocking"]
    assert fs[0]["what"] == "time.sleep"
    assert fs[0]["lock"] == "t:_HELD"


def test_nonblocking_get_is_not_blocking(sanitizer):
    import queue
    q = queue.Queue()
    q.put(1)
    lk = locksan.make_lock("t:_HELD")
    with lk:
        assert q.get_nowait() == 1  # routes through get(block=False)
    with lk:
        with pytest.raises(queue.Empty):
            q.get(block=False)
    assert locksan.findings() == []


def test_blocking_get_under_lock_detected(sanitizer):
    import queue
    q = queue.Queue()
    q.put(1)
    lk = locksan.make_lock("t:_HELD")
    with lk:
        q.get(timeout=1)
    assert [f["kind"] for f in locksan.findings()] == ["blocking"]


def test_sleep_without_lock_is_clean(sanitizer):
    time.sleep(0.01)
    assert locksan.findings() == []


def test_blocking_site_skips_subprocess_internals(sanitizer):
    # subprocess.run(..., timeout=) parks in a poll loop that calls
    # time.sleep from subprocess.py; the finding must point at the
    # application frame that launched the child, not the stdlib.
    import subprocess
    import sys
    lk = locksan.make_lock("t:_HELD")
    with lk:
        subprocess.run(
            [sys.executable, "-c", "import time; time.sleep(0.2)"],
            timeout=HARD, capture_output=True)
    fs = [f for f in locksan.findings() if f["kind"] == "blocking"]
    assert fs and fs[0]["what"] == "time.sleep"
    assert "test_locksan.py" in fs[0]["site"]
    assert "subprocess.py" not in fs[0]["site"]


def test_build_static_does_not_block_under_lock(sanitizer):
    # Regression for a real bring-up finding: _build_static() used to
    # run ``git rev-parse`` (and its sleeping wait loop) while holding
    # _BUILD_LOCK. The resolve now happens outside the lock.
    from synapseml_tpu.io import serving
    old = serving._BUILD_STATIC
    serving._BUILD_STATIC = None
    try:
        info = serving._build_static()
    finally:
        serving._BUILD_STATIC = old
    assert info["python"] and info["pid"] == os.getpid()
    assert [f for f in locksan.findings()
            if f["kind"] == "blocking"] == []


def test_deadlock_watchdog_fires(sanitizer):
    """Real ABBA: both threads park on the other's lock. The acquire
    timeouts (< HARD) self-unwedge the test; the watchdog (0.3s) must
    report the window first."""
    a = locksan.make_lock("t:_DL_A")
    b = locksan.make_lock("t:_DL_B")
    mid = threading.Barrier(2)

    def leg(first, second):
        first.acquire()
        try:
            mid.wait(timeout=HARD)
            if second.acquire(timeout=5):  # parks; watchdog fires
                second.release()
        except threading.BrokenBarrierError:
            pass
        finally:
            first.release()

    t1 = threading.Thread(target=leg, args=(a, b), name="leg-ab")
    t2 = threading.Thread(target=leg, args=(b, a), name="leg-ba")
    t1.start()
    t2.start()
    _join([t1, t2])
    kinds = {f["kind"] for f in locksan.findings()}
    assert "deadlock" in kinds
    dl = next(f for f in locksan.findings() if f["kind"] == "deadlock")
    assert {dl["lock"], dl["holder_waits_on"]} == {"t:_DL_A", "t:_DL_B"}
    assert "leg-" in dl["waiter"] and "leg-" in dl["holder"]
    assert dl["waiter_stack"] and dl["holder_stack"]


def test_slow_holder_is_not_deadlock(sanitizer):
    """A parked thread whose holder is RUNNING (slow, not parked) must
    not trip the watchdog."""
    lk = locksan.make_lock("t:_SLOW")
    entered = threading.Event()

    def holder():
        with lk:
            entered.set()
            t0 = time.monotonic()
            while time.monotonic() - t0 < 0.8:  # busy, never parked
                pass

    t = threading.Thread(target=holder, name="slow-holder")
    t.start()
    assert entered.wait(timeout=HARD)
    assert lk.acquire(timeout=HARD)
    lk.release()
    _join([t])
    assert [f for f in locksan.findings()
            if f["kind"] == "deadlock"] == []


# -- zero-overhead contract + lifecycle ---------------------------------


def test_disabled_path_is_one_attribute_test():
    locksan.disable()
    lk = locksan.make_lock("t:_OFF")
    assert locksan._STATE.tracer is None  # the single attribute read
    with lk:
        assert lk.locked()
    assert not lk.locked()
    assert locksan.findings() == [] and locksan.edges() == []
    # and nothing was patched while off
    assert not locksan._PATCHES


def test_disable_restores_patches(sanitizer):
    assert locksan._PATCHES
    assert hasattr(time.sleep, "_locksan_orig")
    locksan.disable()
    assert not locksan._PATCHES
    assert not hasattr(time.sleep, "_locksan_orig")


def test_rlock_reentry_records_no_edge(sanitizer):
    rl = locksan.make_rlock("t:_RL")
    with rl:
        with rl:  # owner re-entry: RLock semantics, no self-edge
            pass
    assert locksan.findings() == []
    assert locksan.edges() == []


def test_condition_wait_releases_held_set(sanitizer):
    cv = locksan.make_condition("t:_CV")
    woke = threading.Event()

    def waiter():
        with cv:
            cv.wait(timeout=HARD)
            woke.set()

    t = threading.Thread(target=waiter, name="cv-waiter")
    t.start()
    time.sleep(0.1)
    with cv:  # acquirable: wait() released through the SanLock
        cv.notify_all()
    _join([t])
    assert woke.is_set()
    # the wait must not read as blocking-under-lock
    assert [f for f in locksan.findings()
            if f["kind"] == "blocking"] == []


def test_snapshot_and_dump_roundtrip(sanitizer, tmp_path):
    a = locksan.make_lock("t:_A")
    b = locksan.make_lock("t:_B")
    with a:
        with b:
            pass
    path = locksan.dump(str(tmp_path / "locksan-test.json"))
    art = json.loads(open(path).read())
    assert art["tool"] == "locksan" and art["enabled"]
    assert [(e["outer"], e["inner"]) for e in art["edges"]] == \
        [("t:_A", "t:_B")]
    assert art["locks"]["t:_A"] == 1 and art["events_total"] >= 4


# -- identity vocabulary (satellite: migration stability) ---------------


def test_migrated_sites_carry_cc002_identity():
    from synapseml_tpu.runtime import telemetry
    assert telemetry._REG_LOCK.name == "telemetry:_REG_LOCK"
    from synapseml_tpu.runtime.kvcache import PagedKVCache
    from synapseml_tpu.runtime import blackbox
    assert blackbox._S.lock.name == "_State.lock"
    from synapseml_tpu.runtime.decode import DecodeScheduler  # noqa: F401


def test_observed_vocabulary_matches_static_model(sanitizer):
    """The telemetry registry lock under observation carries exactly
    the identity the static CC002 summary uses — the shared-vocabulary
    contract the cross-check depends on."""
    from synapseml_tpu.runtime import telemetry
    outer = locksan.make_lock("t:_OUTER")
    with outer:
        telemetry.counter("locksan_vocab_test_total")
    names = {e["inner"] for e in locksan.edges()}
    assert "telemetry:_REG_LOCK" in names


# -- static<->dynamic cross-check (rules_dynsan) ------------------------

_MODULE = '''\
from synapseml_tpu.runtime.locksan import make_lock

_A = make_lock("mod:_A")
_B = make_lock("mod:_B")


def ordered():
    with _A:
        with _B:
            pass
'''


def _observed(path, outer, inner, site):
    return {"version": 1, "tool": "locksan", "pid": 0, "enabled": True,
            "edges": [{"outer": outer, "inner": inner, "count": 1,
                       "site": site}],
            "locks": {outer: 1, inner: 1}, "findings": [],
            "events_total": 4, "threads": 1}


def _cross(tmp_path, source, observed):
    from tools.analysis.engine import analyze_program
    from tools.analysis.rules_dynsan import cross_check
    mod = tmp_path / "mod.py"
    mod.write_text(source)
    _, prog, _ = analyze_program([str(mod)], root=str(tmp_path))
    return cross_check(prog, [observed]), prog


def test_cross_check_modeled_edge_is_clean(tmp_path):
    (findings, coverage), _ = _cross(
        tmp_path, _MODULE,
        _observed("mod.py", "mod:_A", "mod:_B", "mod.py:9"))
    assert findings == []
    assert coverage == []  # the one static edge was observed


def test_cross_check_model_gap_is_ds001(tmp_path):
    (findings, _), _ = _cross(
        tmp_path, _MODULE,
        _observed("mod.py", "mod:_B", "mod:_A", "mod.py:9"))
    assert [f.rule for f in findings] == ["DS001"]
    assert findings[0].context == "mod:_B -> mod:_A"


def test_cross_check_coverage_note_for_unobserved_edge(tmp_path):
    from tools.analysis.engine import analyze_program
    from tools.analysis.rules_dynsan import cross_check
    mod = tmp_path / "mod.py"
    mod.write_text(_MODULE)
    _, prog, _ = analyze_program([str(mod)], root=str(tmp_path))
    findings, coverage = cross_check(
        prog, [{"version": 1, "tool": "locksan", "pid": 0,
                "enabled": True, "edges": [], "locks": {},
                "findings": [], "events_total": 0, "threads": 0}])
    assert findings == []
    assert [c.rule for c in coverage] == ["DS900"]
    assert "mod:_A -> mod:_B" in coverage[0].message


def test_cross_check_runtime_finding_becomes_ds_rule(tmp_path):
    from tools.analysis.engine import analyze_program
    from tools.analysis.rules_dynsan import cross_check
    mod = tmp_path / "mod.py"
    mod.write_text(_MODULE)
    _, prog, _ = analyze_program([str(mod)], root=str(tmp_path))
    art = _observed("mod.py", "mod:_A", "mod:_B", "mod.py:9")
    art["findings"] = [{"kind": "blocking", "what": "time.sleep",
                        "lock": "mod:_A", "site": "mod.py:9",
                        "detail": "blocking call time.sleep while "
                                  "holding mod:_A"}]
    findings, _ = cross_check(prog, [art])
    assert [f.rule for f in findings] == ["DS003"]
    assert "time.sleep" in findings[0].message


def test_ds001_suppressed_at_acquire_site(tmp_path):
    from tools.analysis.engine import analyze_paths
    mod = tmp_path / "mod.py"
    mod.write_text(
        'from synapseml_tpu.runtime.locksan import make_lock\n'
        '\n'
        '_A = make_lock("mod:_A")\n'
        '_B = make_lock("mod:_B")\n'
        '\n'
        '\n'
        'def leaf():\n'
        '    # synlint: disable=DS001 - _B is a leaf lock\n'
        '    with _B:\n'
        '        pass\n')
    (tmp_path / "mod.observed.json").write_text(json.dumps(
        _observed("mod.py", "mod:_A", "mod:_B", "mod.py:9")))
    findings = analyze_paths([str(mod)], root=str(tmp_path))
    assert [f.rule for f in findings if f.rule == "DS001"] == []


def test_sidecar_fixture_without_suppression_trips(tmp_path):
    from tools.analysis.engine import analyze_paths
    mod = tmp_path / "mod.py"
    mod.write_text(
        'from synapseml_tpu.runtime.locksan import make_lock\n'
        '\n'
        '_A = make_lock("mod:_A")\n'
        '_B = make_lock("mod:_B")\n'
        '\n'
        '\n'
        'def leaf():\n'
        '    with _B:\n'
        '        pass\n')
    (tmp_path / "mod.observed.json").write_text(json.dumps(
        _observed("mod.py", "mod:_A", "mod:_B", "mod.py:8")))
    findings = analyze_paths([str(mod)], root=str(tmp_path))
    assert [f.rule for f in findings] == ["DS001"]


def test_load_artifacts_rejects_junk(tmp_path):
    from tools.analysis.rules_dynsan import load_artifacts
    with pytest.raises(ValueError):
        load_artifacts(str(tmp_path))  # empty dir
    bad = tmp_path / "locksan-1.json"
    bad.write_text('{"tool": "other"}')
    with pytest.raises(ValueError):
        load_artifacts(str(bad))


def test_analyzer_version_covers_dynsan_pack():
    """Editing rules_dynsan.py must invalidate cached summaries."""
    import tools.analysis.cache as cache
    import inspect
    src = inspect.getsource(cache)
    assert "analyzer_version" in src
    v = cache.analyzer_version()
    import tools.analysis.rules_dynsan as rd
    path = rd.__file__
    orig = open(path, encoding="utf-8").read()
    try:
        with open(path, "a", encoding="utf-8") as fh:
            fh.write("\n# cache-buster\n")
        assert cache.analyzer_version() != v
    finally:
        with open(path, "w", encoding="utf-8") as fh:
            fh.write(orig)
