"""Prediction-level parity against real lib_lightgbm outputs.

The fixtures (model strings lib_lightgbm itself wrote + its own
predictions) are generated OFFLINE by ``tools/make_lightgbm_fixtures.py``
— the ``lightgbm`` wheel is not in this image, so when the fixtures are
absent these tests skip with that reason rather than pretending the gate
ran. When present, they replace the sklearn independent-implementation
cross-check (tests/test_external_equivalence.py) with "LightGBM itself
agrees" — the reference's own gating style
(lightgbm/src/test/resources/benchmarks/benchmarks_VerifyLightGBMClassifier.csv).
"""
import os

import numpy as np
import pytest

from synapseml_tpu.gbdt.boosting import Booster

FIXTURES = os.path.join(os.path.dirname(__file__), "fixtures")
CASES = ["binary", "multiclass", "categorical"]


def _fixture(name):
    txt = os.path.join(FIXTURES, f"lightgbm_{name}.txt")
    npz = os.path.join(FIXTURES, f"lightgbm_{name}_pred.npz")
    if not (os.path.exists(txt) and os.path.exists(npz)):
        pytest.skip(
            f"lightgbm ground-truth fixture {name!r} absent: the "
            "lightgbm wheel is not in this image; generate offline with "
            "tools/make_lightgbm_fixtures.py and commit the outputs")
    with open(txt) as fh:
        return fh.read(), np.load(npz)


@pytest.mark.parametrize("name", CASES)
def test_native_string_predictions_match_lightgbm(name):
    model_txt, io = _fixture(name)
    b = Booster.load_string(model_txt)
    got = b.predict(io["input"])
    want = io["pred"]
    np.testing.assert_allclose(
        np.asarray(got).reshape(want.shape), want, rtol=1e-5, atol=1e-7)


@pytest.mark.parametrize("name", CASES)
def test_native_string_raw_scores_match_lightgbm(name):
    model_txt, io = _fixture(name)
    b = Booster.load_string(model_txt)
    got = b.predict_raw(io["input"])
    want = io["raw"]
    np.testing.assert_allclose(
        np.asarray(got).reshape(want.shape), want, rtol=1e-5, atol=1e-7)


def test_fixture_generator_schema(tmp_path, monkeypatch):
    """The CI lightgbm-groundtruth job (tools/ci/pipeline.yaml) runs
    tools/make_lightgbm_fixtures.py with the real wheel. This in-image
    test drives the SAME generator against a faked lightgbm module so
    schema drift (renamed npz keys, changed file names, dropped cases)
    is caught here, where the wheel cannot be installed — the npz keys
    below are exactly what _fixture()/the gate tests consume."""
    import sys
    import types

    sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..",
                                    "tools"))
    try:
        import make_lightgbm_fixtures as gen
    finally:
        sys.path.pop(0)

    class _FakeBooster:
        def model_to_string(self):
            return "tree\nversion=v4\nobjective=binary\n"

        def predict(self, x, raw_score=False):
            return np.zeros(len(x) if np.ndim(x) else 1)

    fake = types.ModuleType("lightgbm")
    fake.__version__ = "0.0-fake"
    fake.Dataset = lambda *a, **k: None
    fake.train = lambda *a, **k: _FakeBooster()
    monkeypatch.setitem(sys.modules, "lightgbm", fake)
    monkeypatch.setattr(gen, "FIXTURES", str(tmp_path))
    gen.main()

    for name in CASES:
        txt = tmp_path / f"lightgbm_{name}.txt"
        npz = tmp_path / f"lightgbm_{name}_pred.npz"
        assert txt.exists() and npz.exists(), name
        data = np.load(npz)
        # the exact keys the gate tests read — drift fails HERE
        assert {"input", "pred", "raw", "lgb_version"} <= set(data.files)
        assert data["input"].ndim == 2 and len(data["input"]) == 64

    # the generator's data is deterministic: fixture regeneration with
    # the same lightgbm version must be reproducible
    x1, y1 = gen._data(seed=7, n_classes=3)
    x2, y2 = gen._data(seed=7, n_classes=3)
    np.testing.assert_array_equal(x1, x2)
    np.testing.assert_array_equal(y1, y2)

    # and the CI pipeline actually carries the job
    ci = os.path.join(os.path.dirname(__file__), "..", "tools", "ci",
                      "pipeline.yaml")
    with open(ci) as fh:
        text = fh.read()
    assert "lightgbm-groundtruth" in text
    assert "make_lightgbm_fixtures.py" in text
    assert "test_lightgbm_groundtruth.py" in text
