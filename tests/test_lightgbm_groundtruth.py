"""Prediction-level parity against real lib_lightgbm outputs.

The fixtures (model strings lib_lightgbm itself wrote + its own
predictions) are generated OFFLINE by ``tools/make_lightgbm_fixtures.py``
— the ``lightgbm`` wheel is not in this image, so when the fixtures are
absent these tests skip with that reason rather than pretending the gate
ran. When present, they replace the sklearn independent-implementation
cross-check (tests/test_external_equivalence.py) with "LightGBM itself
agrees" — the reference's own gating style
(lightgbm/src/test/resources/benchmarks/benchmarks_VerifyLightGBMClassifier.csv).
"""
import os

import numpy as np
import pytest

from synapseml_tpu.gbdt.boosting import Booster

FIXTURES = os.path.join(os.path.dirname(__file__), "fixtures")
CASES = ["binary", "multiclass", "categorical"]


def _fixture(name):
    txt = os.path.join(FIXTURES, f"lightgbm_{name}.txt")
    npz = os.path.join(FIXTURES, f"lightgbm_{name}_pred.npz")
    if not (os.path.exists(txt) and os.path.exists(npz)):
        pytest.skip(
            f"lightgbm ground-truth fixture {name!r} absent: the "
            "lightgbm wheel is not in this image; generate offline with "
            "tools/make_lightgbm_fixtures.py and commit the outputs")
    with open(txt) as fh:
        return fh.read(), np.load(npz)


@pytest.mark.parametrize("name", CASES)
def test_native_string_predictions_match_lightgbm(name):
    model_txt, io = _fixture(name)
    b = Booster.load_string(model_txt)
    got = b.predict(io["input"])
    want = io["pred"]
    np.testing.assert_allclose(
        np.asarray(got).reshape(want.shape), want, rtol=1e-5, atol=1e-7)


@pytest.mark.parametrize("name", CASES)
def test_native_string_raw_scores_match_lightgbm(name):
    model_txt, io = _fixture(name)
    b = Booster.load_string(model_txt)
    got = b.predict_raw(io["input"])
    want = io["raw"]
    np.testing.assert_allclose(
        np.asarray(got).reshape(want.shape), want, rtol=1e-5, atol=1e-7)
