import json

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from synapseml_tpu.parallel.mesh import AXES, build_mesh, factor_axes
from synapseml_tpu.parallel.ring_attention import (
    dense_attention, make_ring_attention, make_ulysses_attention)


def test_factor_axes_covers_devices():
    for n in (1, 2, 4, 8):
        sizes = factor_axes(n)
        assert int(np.prod(list(sizes.values()))) == n
    sizes = factor_axes(8, {"pp": 2})
    assert sizes["pp"] == 2 and int(np.prod(list(sizes.values()))) == 8


def test_build_mesh_axes():
    mesh = build_mesh()
    assert tuple(mesh.axis_names) == AXES
    assert int(np.prod([mesh.shape[a] for a in AXES])) == len(jax.devices())


@pytest.mark.parametrize("causal", [False, True])
def test_ring_attention_matches_dense(causal):
    mesh = build_mesh(want={"sp": 4, "dp": 2})
    rng = np.random.default_rng(0)
    b, s, h, d = 2, 16, 4, 8
    q = rng.standard_normal((b, s, h, d)).astype(np.float32)
    k = rng.standard_normal((b, s, h, d)).astype(np.float32)
    v = rng.standard_normal((b, s, h, d)).astype(np.float32)

    ring = make_ring_attention(mesh, causal=causal)
    got = jax.jit(ring)(q, k, v)
    want = dense_attention(jnp.asarray(q), jnp.asarray(k), jnp.asarray(v),
                           causal=causal)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-4, atol=2e-4)


def test_ring_attention_grads_flow():
    mesh = build_mesh(want={"sp": 4, "dp": 2})
    rng = np.random.default_rng(1)
    b, s, h, d = 2, 8, 2, 4
    q = rng.standard_normal((b, s, h, d)).astype(np.float32)
    k = rng.standard_normal((b, s, h, d)).astype(np.float32)
    v = rng.standard_normal((b, s, h, d)).astype(np.float32)
    ring = make_ring_attention(mesh)

    def loss_ring(q, k, v):
        return (ring(q, k, v) ** 2).sum()

    def loss_dense(q, k, v):
        return (dense_attention(q, k, v) ** 2).sum()

    g_ring = jax.jit(jax.grad(loss_ring, argnums=(0, 1, 2)))(q, k, v)
    g_dense = jax.grad(loss_dense, argnums=(0, 1, 2))(
        jnp.asarray(q), jnp.asarray(k), jnp.asarray(v))
    for a, b_ in zip(g_ring, g_dense):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b_),
                                   rtol=1e-3, atol=1e-3)


def test_ulysses_attention_matches_dense():
    mesh = build_mesh(want={"sp": 4, "dp": 2})
    rng = np.random.default_rng(2)
    b, s, h, d = 2, 16, 4, 8
    q = rng.standard_normal((b, s, h, d)).astype(np.float32)
    k = rng.standard_normal((b, s, h, d)).astype(np.float32)
    v = rng.standard_normal((b, s, h, d)).astype(np.float32)
    uly = make_ulysses_attention(mesh)
    got = jax.jit(uly)(q, k, v)
    want = dense_attention(jnp.asarray(q), jnp.asarray(k), jnp.asarray(v))
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-4, atol=2e-4)


def test_tagger_train_step_full_mesh():
    from synapseml_tpu.dl.tagger import TaggerConfig, make_train_step

    mesh = build_mesh()  # all 8 devices across dp/pp/sp/tp/ep
    cfg = TaggerConfig.for_mesh(
        mesh, vocab_size=128, num_tags=8, d_model=32, head_dim=8,
        ffn_dim=64, max_seq_len=32)
    step, init_state, batch_shard = make_train_step(cfg, mesh)
    params, opt_state = init_state()

    rng = np.random.default_rng(3)
    b, s = 8, 32
    tokens = rng.integers(0, cfg.vocab_size, (b, s)).astype(np.int32)
    labels = rng.integers(0, cfg.num_tags, (b, s)).astype(np.int32)
    mask = np.ones((b, s), np.bool_)
    tokens = jax.device_put(tokens, batch_shard)
    labels = jax.device_put(labels, batch_shard)
    mask = jax.device_put(mask, batch_shard)

    losses = []
    for _ in range(3):
        params, opt_state, loss = step(params, opt_state, tokens, labels, mask)
        losses.append(float(loss))
    assert np.isfinite(losses).all()
    assert losses[-1] < losses[0]  # learns on a fixed batch


def test_rendezvous_roster_and_ranks():
    """Driver rendezvous collects workers and assigns deterministic ranks
    (ref: LightGBMBase.createDriverNodesThread:394-432,
    TrainUtils.getNetworkInitNodes:236-277)."""
    import threading

    from synapseml_tpu.parallel.distributed import (DriverRendezvous,
                                                    WorkerInfo, announce)

    drv = DriverRendezvous(num_workers=3, host="127.0.0.1").start()
    replies = {}
    lock = threading.Lock()

    def worker(name, hint):
        r = announce("127.0.0.1", drv.port, WorkerInfo(host=name,
                                                       rank_hint=hint))
        with lock:
            replies[name] = r

    ts = [threading.Thread(target=worker, args=(f"host{i}", 2 - i))
          for i in range(3)]
    for t in ts:
        t.start()
    for t in ts:
        t.join(timeout=20)
    drv.wait()
    assert len(replies) == 3
    # rank order follows rank_hint: host2 (hint 0) -> 0, host1 -> 1, host0 -> 2
    assert replies["host2"]["process_id"] == 0
    assert replies["host0"]["process_id"] == 2
    rosters = {json.dumps(r["roster"]) for r in replies.values()}
    assert len(rosters) == 1  # everyone sees the identical roster
    assert [w["host"] for w in replies["host0"]["roster"]] == [
        "host2", "host1", "host0"]


def test_worker_announce_retries_until_driver_up():
    """Workers may start before the driver: announce retries with backoff
    (ref: TrainUtils.networkInit:279-295)."""
    import threading
    import time

    from synapseml_tpu.parallel.distributed import (DriverRendezvous,
                                                    WorkerInfo, announce)
    from synapseml_tpu.io.serving import find_open_port

    port = find_open_port(24500)
    result = {}

    def worker():
        result["r"] = announce("127.0.0.1", port, WorkerInfo(host="w0"))

    t = threading.Thread(target=worker)
    t.start()
    time.sleep(0.4)  # let the first connection attempt fail
    drv = DriverRendezvous(num_workers=1, host="127.0.0.1", port=port).start()
    t.join(timeout=30)
    drv.wait()
    assert result["r"]["process_id"] == 0


def test_initialize_noop_single_process():
    from synapseml_tpu.parallel import distributed

    assert distributed.initialize() is False  # 1 process -> no-op


def test_distributed_initialize_subprocess():
    """jax.distributed.initialize in a clean subprocess: 1-process job with
    an explicit coordinator — the full code path the multi-host deployment
    takes, minus the extra hosts."""
    import subprocess
    import sys

    from synapseml_tpu.io.serving import find_open_port

    port = find_open_port(25500)
    code = f"""
import os
os.environ.setdefault("JAX_PLATFORMS", "cpu")
from synapseml_tpu.parallel.distributed import initialize, global_mesh
ok = initialize(coordinator_address="127.0.0.1:{port}", num_processes=1,
                process_id=0)
assert ok, "explicit coordinator must initialize"
import jax
assert jax.process_count() == 1
mesh = global_mesh()
print("subprocess ok", dict(mesh.shape))
"""
    env = dict(**__import__("os").environ)
    env.pop("PALLAS_AXON_POOL_IPS", None)
    env["JAX_PLATFORMS"] = "cpu"
    env["PYTHONPATH"] = "."
    out = subprocess.run([sys.executable, "-c", code], env=env,
                         capture_output=True, text=True, timeout=120)
    assert out.returncode == 0, out.stderr[-2000:]
    assert "subprocess ok" in out.stdout


def test_two_process_distributed_mesh():
    """Full multi-host bootstrap, for real: two OS processes rendezvous,
    join jax.distributed, build a global mesh spanning both, and psum over
    DCN — the driver-rendezvous -> NetworkInit -> collectives path
    (SURVEY.md §2.10) with actual process isolation."""
    import os
    import subprocess
    import sys

    from synapseml_tpu.io.serving import find_open_port

    rdv_port = find_open_port(26500)
    coord_port = find_open_port(26600)
    worker_code = """
import os, sys
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=2"
rank_hint = int(sys.argv[1])
from synapseml_tpu.parallel.distributed import (DriverRendezvous,
                                                rendezvous_and_initialize)
if rank_hint == 0:
    drv = DriverRendezvous(num_workers=2, host="127.0.0.1",
                           port={rdv_port}).start()
reply = rendezvous_and_initialize("127.0.0.1", {rdv_port},
                                  my_host="127.0.0.1", rank_hint=rank_hint,
                                  coordinator_port={coord_port})
import jax
import jax.numpy as jnp
import numpy as np
assert jax.process_count() == 2, jax.process_count()
assert len(jax.devices()) == 4  # 2 local per process
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
from synapseml_tpu.parallel.distributed import shard_map
mesh = Mesh(np.array(jax.devices()), ("dp",))
out = jax.jit(shard_map(
    lambda x: jax.lax.psum(x, "dp"), mesh=mesh,
    in_specs=P("dp"), out_specs=P("dp"), check_vma=False),
    out_shardings=NamedSharding(mesh, P("dp")))(
        jnp.arange(8, dtype=jnp.float32))
local = np.asarray(
    [s.data for s in out.addressable_shards][0]).reshape(-1)
print("RANK", reply["process_id"], "PSUM", float(local[0]), flush=True)
""".replace("{rdv_port}", str(rdv_port)).replace("{coord_port}",
                                                 str(coord_port))
    env = dict(os.environ)
    env.pop("PALLAS_AXON_POOL_IPS", None)
    env["PYTHONPATH"] = "."
    procs = [
        subprocess.Popen([sys.executable, "-c", worker_code, str(i)],
                         env=env, stdout=subprocess.PIPE,
                         stderr=subprocess.PIPE, text=True)
        for i in range(2)
    ]
    outs = []
    for p in procs:
        try:
            out, err = p.communicate(timeout=180)
        except subprocess.TimeoutExpired:
            for q in procs:
                q.kill()
            pytest.fail("two-process distributed run hung")
        outs.append((p.returncode, out, err))
    if any("Multiprocess computations aren't implemented" in err
           for _, _, err in outs):
        # the pinned jaxlib's CPU backend has no cross-process
        # collectives: rendezvous + jax.distributed init (what this
        # module provides) succeeded, the psum data plane cannot run
        pytest.skip("CPU backend lacks multiprocess collectives")
    for rc, out, err in outs:
        assert rc == 0, err[-3000:]
    ranks = sorted(line.split()[1] for rc, out, _ in outs
                   for line in out.splitlines() if line.startswith("RANK"))
    assert ranks == ["0", "1"]
    # psum over the global 4-device mesh of arange(8) sharded by dp:
    # every shard's first element sums the 4 shard leads 0+2+4+6 = 12
    for rc, out, _ in outs:
        for line in out.splitlines():
            if line.startswith("RANK"):
                assert line.split()[3] == "12.0", line


def test_two_level_all_reduce_equals_flat_psum():
    """DCN-aware schedule (reduce-scatter ICI -> psum DCN -> all-gather
    ICI) must equal a flat psum over both axes."""
    import jax
    from jax import lax
    from jax.sharding import Mesh, PartitionSpec as P
    from synapseml_tpu.parallel.distributed import shard_map

    from synapseml_tpu.parallel.collectives import two_level_all_reduce

    devs = np.array(jax.devices()[:8]).reshape(2, 4)
    mesh = Mesh(devs, ("outer", "inner"))
    x = np.arange(8 * 16, dtype=np.float32).reshape(8, 16)

    def flat(xl):
        return lax.psum(xl, ("outer", "inner"))

    def tiered(xl):
        return two_level_all_reduce(xl, "inner", "outer", scatter_axis=1)

    spec = P(("outer", "inner"), None)
    args = dict(mesh=mesh, in_specs=spec, out_specs=spec)
    a = np.asarray(jax.jit(shard_map(flat, **args))(x))
    b = np.asarray(jax.jit(shard_map(tiered, **args))(x))
    np.testing.assert_allclose(a, b, rtol=1e-6)


def test_ring_all_reduce_equals_psum():
    import jax
    from jax import lax
    from jax.sharding import Mesh, PartitionSpec as P
    from synapseml_tpu.parallel.distributed import shard_map

    from synapseml_tpu.parallel.collectives import ring_all_reduce

    devs = np.array(jax.devices()[:4])
    mesh = Mesh(devs, ("r",))
    x = np.random.default_rng(0).normal(size=(4, 8, 6)).astype(np.float32)

    spec = P("r", None, None)
    args = dict(mesh=mesh, in_specs=spec, out_specs=spec)
    a = np.asarray(jax.jit(shard_map(
        lambda xl: lax.psum(xl, "r"), **args))(x))
    b = np.asarray(jax.jit(shard_map(
        lambda xl: ring_all_reduce(xl, "r", chunk_axis=1), **args))(x))
    np.testing.assert_allclose(a, b, rtol=1e-5, atol=1e-5)


def test_imported_onnx_graph_runs_tensor_parallel():
    """An imported ONNX transformer runs tensor-parallel over the tp
    axis: 2-D weights column-sharded, GSPMD propagates the layouts and
    inserts collectives, outputs match single-device exactly. (The
    reference's ORT sessions are single-device-per-partition —
    ONNXModel.scala:497; model parallelism is TPU-native new ground.)"""
    import jax
    from jax.sharding import Mesh

    from synapseml_tpu.onnx import import_model, zoo
    from synapseml_tpu.parallel.onnx_tp import tp_jit

    from synapseml_tpu.parallel.partition_rules import megatron_rules

    g = import_model(zoo.transformer_encoder(
        100, 64, 4, 128, 2, seq_len=16, seed=3))
    mesh = Mesh(np.array(jax.devices()[:4]), ("tp",))
    # the full Megatron preset: every 2-D weight shards (maximum memory
    # savings; the reduction-free default preset is covered by the
    # partition-rule tests, which additionally assert bit-identity)
    params, run = tp_jit(g, mesh, rules=megatron_rules())
    # every 2-D weight actually sharded over tp (64 and 128 divide by 4)
    sharded = [k for k, v in params.items()
               if getattr(v.sharding, "spec", None) is not None
               and v.sharding.spec == jax.sharding.PartitionSpec(None, "tp")]
    assert len(sharded) >= 12, sharded  # q/k/v/o + ffn per layer + embeddings
    ids = np.random.default_rng(0).integers(0, 100, (3, 16))
    want = np.asarray(g.apply(g.params, ids)[0])
    got = np.asarray(run(params, ids)[0])
    np.testing.assert_allclose(got, want, rtol=2e-5, atol=2e-5)

    # the memory claim is an invariant, not prose: per-device parameter
    # bytes must be ~total/n (exactly: sharded/n + replicated remainder).
    # The sharded set now includes paired biases (P('tp')), so compute it
    # from actual placements rather than the 2-D weight list above.
    from synapseml_tpu.parallel.onnx_tp import param_bytes_per_device
    total = sum(v.nbytes for v in g.params.values())
    sharded_total = sum(
        g.params[k].nbytes for k, v in params.items()
        if tuple(v.sharding.spec) != ())
    expected = sharded_total // 4 + (total - sharded_total)
    per_dev = param_bytes_per_device(params)
    assert len(per_dev) == 4
    assert max(per_dev.values()) == expected, (per_dev, expected)
    # the dominant weights really shard: per-device ≲ 40% of the model
    assert expected < 0.4 * total, (expected, total)

    # batch-sharded activations: outputs stay sharded over the axis, and
    # numerics still match (batch 4 divides the 4-device axis)
    params_b, run_b = tp_jit(g, mesh, batch_axis="tp")
    ids4 = np.random.default_rng(1).integers(0, 100, (4, 16))
    want4 = np.asarray(g.apply(g.params, ids4)[0])
    out_b = run_b(params_b, ids4)[0]
    assert out_b.sharding.spec == jax.sharding.PartitionSpec("tp")
    # each device holds 1/4 of the output batch, not the full tensor
    assert out_b.addressable_shards[0].data.shape[0] == 1
    np.testing.assert_allclose(np.asarray(out_b), want4,
                               rtol=2e-5, atol=2e-5)
    with pytest.raises(ValueError, match="divide"):
        run_b(params_b, np.random.default_rng(2).integers(0, 100, (3, 16)))

    # a graph with a batchless (reduced) output gets a clear error, not
    # an opaque GSPMD compile failure
    from synapseml_tpu.onnx.builder import GraphBuilder
    gb = GraphBuilder(opset=17)
    xin = gb.add_input("x", np.float32, [4, 8])
    red = gb.add_node("ReduceSum", [xin, gb.add_initializer(
        "axes", np.array([0, 1], np.int64))], keepdims=0)
    gb.add_output(red, np.float32, [])
    g3 = import_model(gb.to_bytes())
    params3, run3 = tp_jit(g3, mesh, batch_axis="tp")
    with pytest.raises(ValueError, match="batchless|cannot shard"):
        run3(params3, np.zeros((4, 8), np.float32))

    # the foreign torch-exported CNN fixture rides the same machinery
    import os

    fx = os.path.join(os.path.dirname(__file__), "fixtures",
                      "torch_cnn.onnx")
    g2 = import_model(fx)
    params2, run2 = tp_jit(g2, mesh)
    io = np.load(fx.replace(".onnx", "_io.npz"))
    got2 = np.asarray(run2(params2, io["input"])[0])
    np.testing.assert_allclose(got2, io["expected"], atol=1e-5, rtol=1e-5)


# ---------------------------------------------------------------------------
# partition-rule registry (parallel/partition_rules.py)


def _registry_mesh(dp=2, tp=4):
    from jax.sharding import Mesh
    devs = jax.devices()
    assert len(devs) >= dp * tp
    return Mesh(np.array(devs[:dp * tp]).reshape(dp, tp), ("dp", "tp"))


def test_partition_rules_first_match_wins():
    from jax.sharding import PartitionSpec as P

    from synapseml_tpu.parallel.partition_rules import match_partition_rules

    mesh = _registry_mesh()
    params = {"l0_q_w": np.zeros((8, 8), np.float32)}
    # two rules both match; the FIRST claims the param
    rules = [(r"q_w$", P("tp", None)), (r"_w$", P(None, "tp"))]
    specs, report = match_partition_rules(params, mesh, rules=rules)
    assert specs["l0_q_w"] == P("tp", None)
    assert report.rule_for("l0_q_w") == r"q_w$"
    # swapped order, the other rule wins
    specs2, report2 = match_partition_rules(
        params, mesh, rules=list(reversed(rules)))
    assert specs2["l0_q_w"] == P(None, "tp")
    assert report2.rule_for("l0_q_w") == r"_w$"


def test_partition_rules_overrides_precede_defaults():
    from jax.sharding import PartitionSpec as P

    from synapseml_tpu.parallel.partition_rules import match_partition_rules

    mesh = _registry_mesh()
    params = {"l0_q_w": np.zeros((8, 8), np.float32),
              "l0_ff2_w": np.zeros((8, 8), np.float32)}
    # defaults would column-shard q_w; an override pins it replicated
    specs, report = match_partition_rules(
        params, mesh, overrides=[(r"q_w$", P())])
    assert specs["l0_q_w"] == P()
    assert report.rule_for("l0_q_w") == r"q_w$"
    # non-overridden params still flow to the default rules
    assert specs["l0_ff2_w"] == P()  # row half replicates under defaults


def test_partition_rules_miss_hits_divisibility_fallback():
    from jax.sharding import PartitionSpec as P

    from synapseml_tpu.parallel.partition_rules import match_partition_rules

    mesh = _registry_mesh()  # tp axis size 4
    params = {
        "mystery_matrix": np.zeros((6, 8), np.float32),   # 8 % 4 == 0
        "odd_matrix": np.zeros((6, 7), np.float32),       # 7 % 4 != 0
        "int_table": np.zeros((6, 8), np.int32),          # non-float
        "vector": np.zeros((8,), np.float32),             # not 2-D
    }
    specs, report = match_partition_rules(params, mesh)
    # no rule names these: 2-D float with a divisible last dim still
    # column-shards (the old ndim==2 heuristic, demoted to fallback)
    assert specs["mystery_matrix"] == P(None, "tp")
    assert report.claims_by_name()["mystery_matrix"].reason == "fallback"
    for k in ("odd_matrix", "int_table", "vector"):
        assert specs[k] == P(), k
        assert report.claims_by_name()[k].reason == "fallback_replicate", k


def test_partition_rules_indivisible_degrades_with_warning(caplog):
    import logging

    from jax.sharding import PartitionSpec as P

    from synapseml_tpu.parallel.partition_rules import match_partition_rules

    mesh = _registry_mesh()  # tp axis size 4
    params = {"l0_q_w": np.zeros((8, 6), np.float32)}  # 6 % 4 != 0
    with caplog.at_level(logging.WARNING,
                         logger="synapseml_tpu.parallel.partition_rules"):
        specs, report = match_partition_rules(params, mesh)
    # the default column rule CLAIMS it, but the dim does not divide the
    # axis: degrade to replicate — never a GSPMD shape error — and say so
    assert specs["l0_q_w"] == P()
    assert report.claims_by_name()["l0_q_w"].reason == "degraded"
    assert any("l0_q_w" in r.message and "degrad" in r.message
               for r in caplog.records)


def test_partition_rules_bias_pairs_with_column_sharded_weight():
    from jax.sharding import PartitionSpec as P

    from synapseml_tpu.parallel.partition_rules import match_partition_rules

    mesh = _registry_mesh()
    params = {
        "l0_q_w": np.zeros((8, 8), np.float32),   # column-sharded
        "l0_q_b": np.zeros((8,), np.float32),     # pairs with q_w
        "l0_ln1_b": np.zeros((8,), np.float32),   # layernorm: no weight pair
        "l0_ff2_w": np.zeros((8, 8), np.float32),  # row half: replicated
        "l0_ff2_b": np.zeros((8,), np.float32),   # pair NOT column-sharded
    }
    specs, report = match_partition_rules(params, mesh)
    by = report.claims_by_name()
    # the satellite fix: a bias whose weight pair is column-sharded rides
    # the same axis instead of replicating
    assert specs["l0_q_w"] == P(None, "tp")
    assert specs["l0_q_b"] == P("tp")
    assert by["l0_q_b"].reason == "bias_pair"
    # a bias with no column-sharded pair must stay replicated
    assert specs["l0_ln1_b"] == P()
    assert by["l0_ln1_b"].reason == "unpaired_bias"
    assert specs["l0_ff2_b"] == P()
    assert by["l0_ff2_b"].reason == "unpaired_bias"


def test_partition_rules_coverage_report_accounts_every_param():
    from synapseml_tpu.onnx import import_model, zoo
    from synapseml_tpu.parallel.partition_rules import match_partition_rules

    g = import_model(zoo.transformer_encoder(
        100, 64, 4, 128, 2, seq_len=16, seed=3))
    mesh = _registry_mesh()
    specs, report = match_partition_rules(g.params, mesh)
    assert set(specs) == set(g.params)
    assert {c.param for c in report.claims} == set(g.params)
    summary = report.summary()
    assert summary["params"] == len(g.params)
    assert summary["sharded"] == len(report.sharded())
    # round-trips to JSON for /debug + logs
    json.dumps(report.as_dict())


def test_tp_jit_default_rules_bit_identical_on_tp_dp_mesh():
    """The digest contract behind capture/replay: under the DEFAULT
    (reduction-free) rules every cross-device edge is an all-gather —
    a concatenation, not a reduction — so a tp×dp-sharded forward is
    BITWISE equal to the single-device graph, not merely allclose."""
    from synapseml_tpu.onnx import import_model, zoo
    from synapseml_tpu.parallel.onnx_tp import tp_jit

    g = import_model(zoo.transformer_encoder(
        100, 64, 4, 128, 2, seq_len=16, seed=3))
    mesh = _registry_mesh(dp=2, tp=4)
    params, run, report = tp_jit(g, mesh, with_report=True)
    assert len(report.sharded()) >= 12
    ids = np.random.default_rng(0).integers(0, 100, (6, 16))
    want = np.asarray(g.apply(g.params, ids)[0])
    got = np.asarray(run(params, ids)[0])
    assert want.dtype == got.dtype
    assert np.array_equal(
        got.view(np.uint32), want.view(np.uint32)), (
        np.abs(got - want).max())


def test_serving_ring_attention_rides_dp_tp_mesh():
    from jax.sharding import Mesh

    from synapseml_tpu.parallel.ring_attention import (
        dense_attention, make_serving_ring_attention)

    mesh = _registry_mesh(dp=2, tp=4)
    rng = np.random.default_rng(0)
    q, k, v = (jnp.asarray(rng.standard_normal((2, 16, 4, 8)).astype(
        np.float32)) for _ in range(3))
    fn = make_serving_ring_attention(mesh, causal=True)
    with mesh:
        got = jax.jit(fn)(q, k, v)
    want = dense_attention(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-5, atol=2e-5)
    with pytest.raises(ValueError, match="dp×tp|dp.tp"):
        make_serving_ring_attention(Mesh(np.array(jax.devices()[:4]),
                                         ("sp",)))
