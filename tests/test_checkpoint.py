"""Step-level checkpoint / resume tests (VERDICT missing #10; SURVEY.md §5
checkpoint/resume — the reference only threads whole batch models via
setModelString, ref: LightGBMBase.scala:49-61)."""
import dataclasses
import json
import os
import signal
import subprocess
import sys
import time

import numpy as np
import pytest

from synapseml_tpu.data.table import Table
from synapseml_tpu.gbdt.boosting import (Booster, BoostParams,
                                         load_checkpoint, train)

RNG = np.random.default_rng(5)
X = RNG.normal(size=(500, 6))
Y = (X[:, 0] + X[:, 1] * X[:, 2] > 0).astype(np.float64)


def test_init_model_continuation_equals_uninterrupted():
    """Training 8 then 12-more iterations from init_model must equal one
    uninterrupted 20-iteration run (deterministic gbdt)."""
    p20 = BoostParams(objective="binary", num_iterations=20, num_leaves=7)
    full = train(p20, X, Y)
    first = train(dataclasses.replace(p20, num_iterations=8), X, Y)
    resumed = train(dataclasses.replace(p20, num_iterations=12), X, Y,
                    init_model=first)
    assert resumed.num_trees == 20
    np.testing.assert_allclose(resumed.predict(X), full.predict(X),
                               rtol=1e-4, atol=1e-5)


def test_checkpoint_files_written_and_loadable(tmp_path):
    ckpt = str(tmp_path / "ck")
    p = BoostParams(objective="binary", num_iterations=12, num_leaves=7)
    train(p, X, Y, checkpoint_dir=ckpt, checkpoint_every=4)
    b, meta = load_checkpoint(ckpt)
    assert meta["total_iterations"] == 12
    assert meta["iterations_done"] in (4, 8, 12)
    assert b.num_trees == meta["iterations_done"]


def test_kill_mid_fit_and_resume_to_equivalent_model(tmp_path):
    """The VERDICT's done-when: kill a fit mid-run, resume to an
    equivalent model. The child trains 400 slow iterations with
    checkpoints every 3; the parent SIGKILLs it once a checkpoint lands,
    then resumes the remaining iterations of a 20-iteration target."""
    ckpt = str(tmp_path / "ck")
    data = str(tmp_path / "data.npz")
    np.savez(data, x=X, y=Y)
    code = f"""
import numpy as np
from synapseml_tpu.gbdt.boosting import BoostParams, train
d = np.load({data!r})
p = BoostParams(objective="binary", num_iterations=400, num_leaves=7)
train(p, d["x"], d["y"], checkpoint_dir={ckpt!r}, checkpoint_every=3)
"""
    env = dict(os.environ)
    env.pop("PALLAS_AXON_POOL_IPS", None)
    env["JAX_PLATFORMS"] = "cpu"
    env["PYTHONPATH"] = "."
    child = subprocess.Popen([sys.executable, "-c", code], env=env,
                             stdout=subprocess.DEVNULL,
                             stderr=subprocess.DEVNULL)
    try:
        deadline = time.monotonic() + 240
        meta_path = os.path.join(ckpt, "checkpoint.json")
        while time.monotonic() < deadline:
            if os.path.exists(meta_path):
                with open(meta_path) as fh:
                    if json.load(fh)["iterations_done"] >= 3:
                        break
            if child.poll() is not None:
                pytest.fail("child exited before being killed")
            time.sleep(0.2)
        else:
            pytest.fail("no checkpoint appeared in time")
        child.send_signal(signal.SIGKILL)
        child.wait(timeout=30)
    finally:
        if child.poll() is None:
            child.kill()

    booster, meta = load_checkpoint(ckpt)
    done = meta["iterations_done"]
    assert booster.num_trees == done
    assert done < 400  # genuinely killed mid-run

    # resume to a target past the kill point; must equal an uninterrupted
    # run of the same total length (deterministic gbdt)
    target = done + 10
    resumed = train(
        BoostParams(objective="binary", num_iterations=target - done,
                    num_leaves=7), X, Y, init_model=booster)
    full = train(BoostParams(objective="binary", num_iterations=target,
                             num_leaves=7), X, Y)
    assert resumed.num_trees == target
    np.testing.assert_allclose(resumed.predict(X), full.predict(X),
                               rtol=1e-4, atol=1e-5)


def test_resume_with_early_stopping_offsets_best_iteration():
    Xv = RNG.normal(size=(150, 6))
    yv = (Xv[:, 0] + Xv[:, 1] * Xv[:, 2] > 0).astype(np.float64)
    first = train(BoostParams(objective="binary", num_iterations=5,
                              num_leaves=5), X, Y)
    resumed = train(
        BoostParams(objective="binary", num_iterations=300, num_leaves=5,
                    early_stopping_round=5), X, Y,
        valid_sets=[(Xv, yv)], init_model=first)
    assert resumed.best_iteration >= 5  # offset past the init trees
    # truncated predict uses combined-stack indices and stays sane
    from sklearn.metrics import roc_auc_score
    assert roc_auc_score(yv, resumed.predict(Xv)) > 0.9


def test_checkpoint_under_early_stopping_keeps_full_stack(tmp_path):
    """Early stopping must not truncate checkpointed trees: the stored
    model carries the full stack, best_iteration rides metadata."""
    Xv = RNG.normal(size=(120, 6))
    yv = (Xv[:, 0] + Xv[:, 1] * Xv[:, 2] > 0).astype(np.float64)
    ckpt = str(tmp_path / "ck")
    p = BoostParams(objective="binary", num_iterations=30, num_leaves=5,
                    early_stopping_round=50)
    b = train(p, X, Y, valid_sets=[(Xv, yv)], checkpoint_dir=ckpt,
              checkpoint_every=5)
    loaded, meta = load_checkpoint(ckpt)
    assert loaded.num_trees == meta["iterations_done"]
    assert loaded.best_iteration == meta["best_iteration"]
    np.testing.assert_allclose(loaded.predict(X), b.predict(X),
                               rtol=1e-4, atol=1e-5)


def test_mesh_init_model_continuation_and_checkpoint(tmp_path):
    """Distributed resume: init_model continuation and step checkpoints
    on the dp mesh must track the single-device behavior."""
    import jax
    from jax.sharding import Mesh

    mesh = Mesh(np.array(jax.devices()), ("dp",))
    p20 = BoostParams(objective="binary", num_iterations=20, num_leaves=7)
    full = train(p20, X, Y, mesh=mesh)
    first = train(dataclasses.replace(p20, num_iterations=8), X, Y,
                  mesh=mesh)
    resumed = train(dataclasses.replace(p20, num_iterations=12), X, Y,
                    mesh=mesh, init_model=first)
    assert resumed.num_trees == 20
    np.testing.assert_allclose(resumed.predict(X), full.predict(X),
                               rtol=1e-3, atol=1e-4)

    ckpt = str(tmp_path / "ck_mesh")
    train(dataclasses.replace(p20, num_iterations=12), X, Y, mesh=mesh,
          checkpoint_dir=ckpt, checkpoint_every=4)
    b, meta = load_checkpoint(ckpt)
    assert meta["iterations_done"] in (4, 8, 12)
    assert b.num_trees == meta["iterations_done"]
    # a checkpointed partial resumes on the mesh to the full ensemble
    remaining = p20.num_iterations - meta["iterations_done"]
    if remaining > 0:
        resumed2 = train(
            dataclasses.replace(p20, num_iterations=remaining), X, Y,
            mesh=mesh, init_model=b)
        assert resumed2.num_trees == 20


def test_mesh_multiclass_init_model_continuation():
    import jax
    from jax.sharding import Mesh

    rng = np.random.default_rng(3)
    x = rng.normal(size=(400, 5))
    y = np.argmax(x[:, :3], axis=1).astype(np.float64)
    mesh = Mesh(np.array(jax.devices()), ("dp",))
    p = BoostParams(objective="multiclass", num_class=3,
                    num_iterations=10, num_leaves=7)
    full = train(p, x, y, mesh=mesh)
    first = train(dataclasses.replace(p, num_iterations=4), x, y, mesh=mesh)
    resumed = train(dataclasses.replace(p, num_iterations=6), x, y,
                    mesh=mesh, init_model=first)
    assert resumed.num_trees == 30
    np.testing.assert_allclose(resumed.predict(x), full.predict(x),
                               rtol=1e-3, atol=1e-4)


def test_mesh_iteration_hook():
    import jax
    from jax.sharding import Mesh

    mesh = Mesh(np.array(jax.devices()), ("dp",))
    seen = []
    p = BoostParams(objective="binary", num_iterations=6, num_leaves=7)
    train(p, X, Y, mesh=mesh, iteration_hook=lambda it: seen.append(it))
    assert seen and seen[-1] == 6


def test_categorical_init_model_continuation():
    """Continuing from a native categorical model: old nodes keep their
    split sets and pool, new trees are numeric; the combined booster
    predicts init margins + new-tree contributions and round-trips
    through the native format (lib_lightgbm continues from categorical
    models transparently — round-2 guard removed)."""
    import sys
    sys.path.insert(0, os.path.dirname(__file__))
    from test_lgbm_format import _cat_model_string

    cat_b = Booster.load_string(_cat_model_string())
    rng2 = np.random.default_rng(7)
    x2 = np.column_stack([rng2.integers(0, 50, 300).astype(np.float64),
                          rng2.uniform(0, 10, 300)])
    y2 = (np.where(np.isin(x2[:, 0], [1, 3, 40]), 1.0, -3.0)
          + 0.3 * x2[:, 1])
    p = BoostParams(objective="regression", num_iterations=6, num_leaves=5)
    resumed = train(p, x2, y2, init_model=cat_b)
    assert resumed.num_trees == cat_b.num_trees + 6
    assert resumed.trees_cat is not None
    # old tree kept its categorical routing (set {1,3,40} on feature 0)
    assert (resumed.trees_cat[0] >= 0).any()
    assert (resumed.trees_cat[1:] == -1).all()

    # combined = init margins + the new numeric trees' contribution
    tail = Booster(
        trees_feature=resumed.trees_feature[1:],
        trees_threshold=resumed.trees_threshold[1:],
        trees_left=resumed.trees_left[1:],
        trees_right=resumed.trees_right[1:],
        trees_value=resumed.trees_value[1:],
        trees_cover=resumed.trees_cover[1:],
        trees_gain=resumed.trees_gain[1:],
        tree_weights=resumed.tree_weights[1:],
        params=p, init_score=0.0, num_class=1, num_features=2)
    want = cat_b.predict(x2) + tail.predict_raw(x2)
    np.testing.assert_allclose(resumed.predict(x2), want,
                               rtol=1e-5, atol=1e-5)
    # native-format round trip of the combined model
    back = Booster.load_string(resumed.save_string())
    np.testing.assert_allclose(back.predict(x2), resumed.predict(x2),
                               rtol=1e-5, atol=1e-5)
    # NaN in the categorical feature still routes right (warned semantics)
    xnan = x2.copy()
    xnan[:5, 0] = np.nan
    np.testing.assert_allclose(resumed.predict(xnan)[:5],
                               cat_b.predict(xnan)[:5]
                               + tail.predict_raw(xnan)[:5],
                               rtol=1e-5, atol=1e-5)


def test_learning_rate_schedule_on_mesh_matches_single_device():
    """Per-iteration LR schedules run on the dp mesh (round-2 guard
    removed): mesh == single-device for a decaying schedule, and a
    constant schedule equals the static-LR path."""
    import jax
    from jax.sharding import Mesh

    mesh = Mesh(np.array(jax.devices()), ("dp",))
    p = BoostParams(objective="binary", num_iterations=10, num_leaves=7)
    lrs = np.linspace(0.2, 0.05, 10).astype(np.float32)
    single = train(p, X, Y, learning_rates=lrs)
    meshed = train(p, X, Y, mesh=mesh, learning_rates=lrs)
    assert meshed.num_trees == 10
    np.testing.assert_allclose(meshed.predict(X), single.predict(X),
                               rtol=1e-3, atol=1e-4)
    const = train(p, X, Y, mesh=mesh,
                  learning_rates=np.full(10, 0.1, np.float32))
    base = train(p, X, Y, mesh=mesh)
    np.testing.assert_allclose(const.predict(X), base.predict(X),
                               rtol=1e-4, atol=1e-5)
    # schedule-vs-boosting-type guards hold on the mesh too
    with pytest.raises(NotImplementedError, match="rf"):
        train(dataclasses.replace(p, boosting_type="rf",
                                  bagging_fraction=0.8, bagging_freq=1),
              X, Y, mesh=mesh, learning_rates=lrs)
