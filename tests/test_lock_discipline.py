"""Regression tests for the concurrency violations synlint surfaced
(tools/analysis — PR 5): each exercises the exact race the fix guards,
so a future refactor that drops the lock fails here, not in prod.
"""
import threading

import numpy as np
import pytest

import jax

from synapseml_tpu.io.serving import (ContinuousServer, DistributedServer,
                                      HTTPSourceStateHolder)
from synapseml_tpu.runtime.executor import BatchedExecutor, JitCache


def _hammer(fn, n_threads=8, iters=25):
    """Run fn concurrently; return every result produced."""
    results, errors = [], []
    start = threading.Barrier(n_threads)

    def worker():
        try:
            start.wait(timeout=10)
            for _ in range(iters):
                results.append(fn())
        except Exception as e:  # noqa: BLE001 - surfaced via assertion
            errors.append(e)

    threads = [threading.Thread(target=worker) for _ in range(n_threads)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=30)
    assert not errors, errors
    return results


def test_jit_for_concurrent_callers_share_one_wrapper():
    ex = BatchedExecutor(lambda x: (x * 2,), donate=False)
    got = _hammer(lambda: ex._jit_for(1, (False,)))
    assert len({id(g) for g in got}) == 1
    assert len(ex._jits) == 1


def test_donate_mask_concurrent_resolution_is_consistent():
    ex = BatchedExecutor(lambda x: (x * 2,), donate=True)
    sig = (((8, 4), "float32"),)
    got = _hammer(lambda: ex._donate_mask_for_sig(sig))
    assert len(set(got)) == 1
    assert len(ex._donate_masks) == 1


def test_jitcache_concurrent_get_returns_single_winner():
    cache = JitCache()
    built = []

    def build():
        built.append(1)  # may run more than once; winner must be unique
        return object()

    got = _hammer(lambda: cache.get("k", build))
    assert len({id(g) for g in got}) == 1


def test_continuous_server_concurrent_errors_all_recorded():
    def bad_pipeline(table):
        raise RuntimeError("boom")

    cs = ContinuousServer("lockdisc-errors", bad_pipeline)
    try:
        n = len(_hammer(lambda: cs._score_only([]), n_threads=6, iters=10))
        assert len(cs.errors) == n == 60
    finally:
        HTTPSourceStateHolder.remove("lockdisc-errors")


def test_distributed_server_attach_race_single_owner():
    winners, losers = [], []
    start = threading.Barrier(4)

    def attach():
        start.wait(timeout=10)
        try:
            winners.append(DistributedServer("lockdisc-owner", 2))
        except ValueError:
            losers.append(1)

    threads = [threading.Thread(target=attach) for _ in range(4)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=30)
    try:
        assert len(winners) == 1 and len(losers) == 3
    finally:
        for w in winners:
            w.stop()


def test_bound_for_device_concurrent_single_replica():
    dev = jax.devices()[0]
    ex = BatchedExecutor(lambda w, x: (x + w,),
                         bound_args=(np.float32(1.0),), donate=False)
    got = _hammer(lambda: ex._bound_for_device(dev), n_threads=6, iters=5)
    assert len({id(g) for g in got}) == 1
    assert set(ex._bound_rr) == {dev.id}
