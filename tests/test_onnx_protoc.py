"""Cross-check the hand-rolled ONNX wire codec against protoc.

The in-tree codec (synapseml_tpu/onnx/proto.py) is self-contained; its
round-trip tests alone would not catch a systematic wire-format
misunderstanding shared by both directions. ``protoc`` (real protobuf)
acts as the foreign producer/consumer here: models *encoded by protoc*
must import and execute, and models *encoded by the codec* must decode
cleanly with protoc. (No ``onnx``/``onnxruntime``/``onnxscript`` in
this environment, so protoc is the only independent implementation
available — SURVEY.md §2.6 north-star path.)
"""
import shutil
import subprocess

import numpy as np
import pytest

from synapseml_tpu.onnx import import_model
from synapseml_tpu.onnx.builder import GraphBuilder

protoc = shutil.which("protoc")
pytestmark = pytest.mark.skipif(protoc is None, reason="protoc not installed")

# The public onnx.proto subset the codec implements. Field numbers are
# frozen forever by protobuf compatibility rules.
ONNX_PROTO = """
syntax = "proto3";
package onnx;

message AttributeProto {
  string name = 1;
  float f = 2;
  int64 i = 3;
  bytes s = 4;
  TensorProto t = 5;
  GraphProto g = 6;
  repeated float floats = 7;
  repeated int64 ints = 8;
  repeated bytes strings = 9;
  repeated TensorProto tensors = 10;
  repeated GraphProto graphs = 11;
  int32 type = 20;
}

message ValueInfoProto {
  string name = 1;
  TypeProto type = 2;
  string doc_string = 3;
}

message NodeProto {
  repeated string input = 1;
  repeated string output = 2;
  string name = 3;
  string op_type = 4;
  repeated AttributeProto attribute = 5;
  string doc_string = 6;
  string domain = 7;
}

message ModelProto {
  int64 ir_version = 1;
  string producer_name = 2;
  string producer_version = 3;
  string domain = 4;
  int64 model_version = 5;
  string doc_string = 6;
  GraphProto graph = 7;
  repeated OperatorSetIdProto opset_import = 8;
}

message GraphProto {
  repeated NodeProto node = 1;
  string name = 2;
  repeated TensorProto initializer = 5;
  string doc_string = 10;
  repeated ValueInfoProto input = 11;
  repeated ValueInfoProto output = 12;
  repeated ValueInfoProto value_info = 13;
}

message StringStringEntryProto {
  string key = 1;
  string value = 2;
}

message TensorProto {
  enum DataLocation { DEFAULT = 0; EXTERNAL = 1; }
  repeated int64 dims = 1;
  int32 data_type = 2;
  repeated float float_data = 4;
  repeated int32 int32_data = 5;
  repeated bytes string_data = 6;
  repeated int64 int64_data = 7;
  string name = 8;
  bytes raw_data = 9;
  repeated double double_data = 10;
  repeated uint64 uint64_data = 11;
  string doc_string = 12;
  repeated StringStringEntryProto external_data = 13;
  DataLocation data_location = 14;
}

message TensorShapeProto {
  message Dimension {
    oneof value {
      int64 dim_value = 1;
      string dim_param = 2;
    }
  }
  repeated Dimension dim = 1;
}

message TypeProto {
  message Tensor {
    int32 elem_type = 1;
    TensorShapeProto shape = 2;
  }
  Tensor tensor_type = 1;
}

message OperatorSetIdProto {
  string domain = 1;
  int64 version = 2;
}
"""


@pytest.fixture(scope="module")
def proto_file(tmp_path_factory):
    d = tmp_path_factory.mktemp("protoc")
    p = d / "onnx_subset.proto"
    p.write_text(ONNX_PROTO)
    return p


def _protoc(proto_file, args, data: bytes) -> bytes:
    r = subprocess.run(
        [protoc, f"--proto_path={proto_file.parent}", proto_file.name, *args],
        input=data, stdout=subprocess.PIPE, stderr=subprocess.PIPE,
        cwd=proto_file.parent)
    assert r.returncode == 0, r.stderr.decode()
    return r.stdout


def test_builder_bytes_decode_with_protoc(proto_file):
    """Every byte our encoder emits must be canonical protobuf."""
    g = GraphBuilder(opset=17)
    x = g.add_input("x", np.float32, ["N", 4])
    w = np.arange(12, dtype=np.float32).reshape(3, 4)
    y = g.gemm(x, w, np.zeros(3, np.float32))
    y = g.add_node("Softmax", [y], axis=-1)  # axis=-1: negative int varint
    g.add_output(y, np.float32, ["N", 3])
    blob = g.to_bytes()

    text = _protoc(proto_file, ["--decode=onnx.ModelProto"], blob).decode()
    assert 'op_type: "Gemm"' in text
    assert 'op_type: "Softmax"' in text
    # negative attribute ints survive two's-complement varint encoding
    assert "i: -1" in text
    assert "dim_param" in text  # symbolic batch dim


def test_protoc_encoded_model_imports_and_runs(proto_file):
    """A model serialized by protoc (typed float_data fields, the
    encoding layout other emitters use) imports and computes correctly."""
    textproto = """
ir_version: 8
producer_name: "protoc-fixture"
opset_import { domain: "" version: 17 }
graph {
  name: "affine_relu"
  input {
    name: "x"
    type { tensor_type { elem_type: 1 shape {
      dim { dim_param: "N" } dim { dim_value: 2 } } } }
  }
  output {
    name: "y"
    type { tensor_type { elem_type: 1 shape {
      dim { dim_param: "N" } dim { dim_value: 2 } } } }
  }
  initializer {
    dims: 2 dims: 2 data_type: 1 name: "w"
    float_data: 1.0 float_data: -1.0 float_data: 2.0 float_data: 0.5
  }
  initializer {
    dims: 2 data_type: 1 name: "b"
    float_data: 0.25 float_data: -0.75
  }
  node { input: "x" input: "w" output: "mm" op_type: "MatMul" }
  node { input: "mm" input: "b" output: "s" op_type: "Add" }
  node { input: "s" output: "y" op_type: "Relu" }
}
"""
    blob = _protoc(proto_file, ["--encode=onnx.ModelProto"],
                   textproto.encode())
    g = import_model(blob)
    x = np.array([[1.0, 2.0], [-3.0, 0.5]], np.float32)
    (got,) = g.apply(g.params, x)
    want = np.maximum(
        x @ np.array([[1.0, -1.0], [2.0, 0.5]], np.float32)
        + np.array([0.25, -0.75], np.float32), 0.0)
    np.testing.assert_allclose(np.asarray(got), want, rtol=1e-6)


def test_protoc_negative_axis_and_int64_raw_data(proto_file):
    """Negative ints in typed int64 fields plus raw_data initializers as
    protoc escapes them."""
    # int64 initializer via typed int64_data with a negative value
    textproto = """
ir_version: 8
opset_import { domain: "" version: 17 }
graph {
  name: "neg"
  input {
    name: "x"
    type { tensor_type { elem_type: 1 shape {
      dim { dim_value: 3 } dim { dim_value: 2 } } } }
  }
  output {
    name: "y"
    type { tensor_type { elem_type: 1 shape { dim { dim_value: 3 } } } }
  }
  initializer { dims: 1 data_type: 7 name: "axes" int64_data: -1 }
  node {
    input: "x" input: "axes" output: "y" op_type: "ReduceSum"
    attribute { name: "keepdims" i: 0 type: 2 }
  }
}
"""
    blob = _protoc(proto_file, ["--encode=onnx.ModelProto"],
                   textproto.encode())
    g = import_model(blob)
    x = np.arange(6, dtype=np.float32).reshape(3, 2)
    (got,) = g.apply(g.params, x)
    np.testing.assert_allclose(np.asarray(got), x.sum(-1), rtol=1e-6)


def test_protoc_external_data_model_imports(proto_file, tmp_path):
    """A model whose weights live in a sidecar file (``data_location:
    EXTERNAL`` with location/offset/length entries — the standard
    ``save_as_external_data`` layout for >2GB exports), with the model
    bytes encoded by protoc as the foreign producer. Offsets are
    deliberately non-contiguous to prove they are honored."""
    w = np.array([[1.0, -1.0], [2.0, 0.5]], np.float32)
    b = np.array([0.25, -0.75], np.float32)
    # b first at offset 64, w at offset 128: order != graph order
    sidecar = bytearray(128 + w.nbytes)
    sidecar[64:64 + b.nbytes] = b.tobytes()
    sidecar[128:] = w.tobytes()
    (tmp_path / "weights.bin").write_bytes(bytes(sidecar))

    textproto = """
ir_version: 8
opset_import { domain: "" version: 17 }
graph {
  name: "ext"
  input {
    name: "x"
    type { tensor_type { elem_type: 1 shape {
      dim { dim_param: "N" } dim { dim_value: 2 } } } }
  }
  output {
    name: "y"
    type { tensor_type { elem_type: 1 shape {
      dim { dim_param: "N" } dim { dim_value: 2 } } } }
  }
  initializer {
    dims: 2 dims: 2 data_type: 1 name: "w" data_location: EXTERNAL
    external_data { key: "location" value: "weights.bin" }
    external_data { key: "offset" value: "128" }
    external_data { key: "length" value: "16" }
  }
  initializer {
    dims: 2 data_type: 1 name: "b" data_location: EXTERNAL
    external_data { key: "location" value: "weights.bin" }
    external_data { key: "offset" value: "64" }
    external_data { key: "length" value: "8" }
  }
  node { input: "x" input: "w" output: "mm" op_type: "MatMul" }
  node { input: "mm" input: "b" output: "y" op_type: "Add" }
}
"""
    blob = _protoc(proto_file, ["--encode=onnx.ModelProto"],
                   textproto.encode())
    model_path = tmp_path / "ext.onnx"
    model_path.write_bytes(blob)
    g = import_model(str(model_path))
    x = np.array([[1.0, 2.0], [-3.0, 0.5]], np.float32)
    (got,) = g.apply(g.params, x)
    np.testing.assert_allclose(np.asarray(got), x @ w + b, rtol=1e-6)

    # raw bytes with no base_dir cannot resolve the sidecar: clear error
    with pytest.raises(ValueError, match="external"):
        import_model(blob)
    # ... but bytes + explicit base_dir works
    g2 = import_model(blob, base_dir=str(tmp_path))
    np.testing.assert_allclose(
        np.asarray(g2.apply(g2.params, x)[0]), x @ w + b, rtol=1e-6)


def test_roundtrip_identity_through_protoc(proto_file):
    """codec encode -> protoc decode -> protoc encode -> codec decode
    reproduces the same executable graph."""
    g = GraphBuilder(opset=17)
    x = g.add_input("x", np.float32, ["N", 3])
    y = g.add_node("Mul", [x, g.add_initializer(
        "scale", np.array([2.0, 3.0, 4.0], np.float32))])
    g.add_output(y, np.float32, ["N", 3])
    blob = g.to_bytes()

    text = _protoc(proto_file, ["--decode=onnx.ModelProto"], blob)
    blob2 = _protoc(proto_file, ["--encode=onnx.ModelProto"], text)
    gi = import_model(blob2)
    xv = np.ones((2, 3), np.float32)
    np.testing.assert_allclose(
        np.asarray(gi.apply(gi.params, xv)[0]), [[2, 3, 4]] * 2, rtol=1e-6)
