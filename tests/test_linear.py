import numpy as np
import pytest

from synapseml_tpu.data.table import Table
from synapseml_tpu.linear import (
    VowpalWabbitClassifier,
    VowpalWabbitContextualBandit,
    VowpalWabbitFeaturizer,
    VowpalWabbitInteractions,
    VowpalWabbitRegressor,
)


def _classification_table(n=800, seed=0):
    rng = np.random.default_rng(seed)
    x = rng.normal(size=(n, 6)).astype(np.float32)
    w = np.array([2.0, -1.5, 1.0, 0.0, 0.5, -2.0])
    y = (x @ w + 0.2 * rng.normal(size=n) > 0).astype(np.float64)
    return Table({"vec": x, "label": y})


def test_featurizer_shapes():
    t = Table({
        "age": np.array([25.0, 0.0, 40.0]),
        "city": ["nyc", "sf", "nyc"],
        "words": [["a", "b"], ["c"], []],
    })
    out = VowpalWabbitFeaturizer(
        input_cols=["age", "city", "words"], output_col="f",
        num_bits=12).transform(t)
    idx, val = out["f_idx"], out["f_val"]
    assert idx.shape == val.shape
    assert idx.max() < 4096
    # row 0: age + city + 2 words = 4 features
    assert (val[0] != 0).sum() == 4
    # row 1: age==0 dropped, city + 1 word = 2
    assert (val[1] != 0).sum() == 2


def test_classifier_learns():
    t = _classification_table()
    feat = VowpalWabbitFeaturizer(input_cols=["vec"], output_col="features",
                                  num_bits=12)
    ft = feat.transform(t)
    clf = VowpalWabbitClassifier(num_bits=12, num_passes=6, learning_rate=0.5,
                                 batch_size=64)
    model = clf.fit(ft)
    out = model.transform(ft)
    acc = (out["prediction"] == ft["label"]).mean()
    assert acc > 0.9
    stats = model.get_performance_statistics()
    assert stats["rows"] == 800


def test_regressor_learns():
    rng = np.random.default_rng(1)
    x = rng.normal(size=(600, 4)).astype(np.float32)
    y = (x @ np.array([1.0, 2.0, -1.0, 0.5]) + 0.05 * rng.normal(size=600))
    t = Table({"vec": x, "label": y})
    ft = VowpalWabbitFeaturizer(input_cols=["vec"], output_col="features",
                                num_bits=12).transform(t)
    model = VowpalWabbitRegressor(num_bits=12, num_passes=10,
                                  learning_rate=0.8, batch_size=32).fit(ft)
    pred = model.transform(ft)["prediction"]
    mse = float(np.mean((pred - y) ** 2))
    assert mse < 0.5


def test_ftrl_sparsifies():
    t = _classification_table()
    ft = VowpalWabbitFeaturizer(input_cols=["vec"], output_col="features",
                                num_bits=12).transform(t)
    model = VowpalWabbitClassifier(num_bits=12, num_passes=4,
                                   optimizer="ftrl", l1=0.01, batch_size=64).fit(ft)
    w = np.asarray(model.state.w)
    acc = (model.transform(ft)["prediction"] == t["label"]).mean()
    assert acc > 0.85
    # l1 keeps almost all of the 4096 hash slots exactly zero
    assert (w != 0).sum() < 100


def test_interactions():
    t = Table({"a": ["x", "y"], "b": ["u", "v"]})
    fa = VowpalWabbitFeaturizer(input_cols=["a"], output_col="fa", num_bits=10)
    fb = VowpalWabbitFeaturizer(input_cols=["b"], output_col="fb", num_bits=10)
    out = fb.transform(fa.transform(t))
    out = VowpalWabbitInteractions(left_col="fa", right_col="fb",
                                   output_col="q", num_bits=10).transform(out)
    assert out["q_idx"].shape[1] == out["fa_idx"].shape[1] + \
        out["fa_idx"].shape[1] * out["fb_idx"].shape[1]
    # interaction of different pairs hashes differently
    assert out["q_idx"][0, -1] != out["q_idx"][1, -1]


def test_contextual_bandit():
    rng = np.random.default_rng(2)
    n, n_actions = 400, 3
    ctx = rng.integers(0, 2, size=n)  # context bit determines the best action
    shared_t = Table({"c": [f"ctx{c}" for c in ctx]})
    sh = VowpalWabbitFeaturizer(input_cols=["c"], output_col="shared",
                                num_bits=10).transform(shared_t)
    # action features conditioned on context (the -q ctx:action analogue —
    # a purely additive shared+action model cannot express cost = f(ctx, a))
    af = VowpalWabbitFeaturizer(input_cols=["aid"], output_col="af",
                                num_bits=10)
    cache = {}
    for c in (0, 1):
        for a in range(n_actions):
            fa = af.transform(Table({"aid": [f"ctx{c}|a{a}"]}))
            cache[(c, a)] = (fa["af_idx"][0], fa["af_val"][0])
    actions = np.empty(n, dtype=object)
    for i in range(n):
        actions[i] = [cache[(int(ctx[i]), a)] for a in range(n_actions)]
    chosen = rng.integers(1, n_actions + 1, size=n)
    # cost 0 if chosen matches best action for context else 1
    best_action = np.where(ctx == 0, 1, 2)
    cost = (chosen != best_action).astype(np.float64)
    t = Table({
        "shared_idx": sh["shared_idx"], "shared_val": sh["shared_val"],
        "action_features": actions,
        "chosenAction": chosen.astype(np.float64),
        "cost": cost,
        "probability": np.full(n, 1.0 / n_actions),
    })
    cb = VowpalWabbitContextualBandit(num_bits=10, num_passes=8,
                                      learning_rate=0.5, batch_size=32)
    model = cb.fit(t)
    out = model.transform(t)
    picked = np.asarray(out["prediction"], int)
    agree = (picked == best_action).mean()
    assert agree > 0.9


def test_vw_serde(tmp_path):
    from synapseml_tpu.core.pipeline import PipelineStage
    t = _classification_table(200)
    ft = VowpalWabbitFeaturizer(input_cols=["vec"], output_col="features",
                                num_bits=10).transform(t)
    model = VowpalWabbitClassifier(num_bits=10, num_passes=2).fit(ft)
    model.save(str(tmp_path / "vw"))
    loaded = PipelineStage.load(str(tmp_path / "vw"))
    np.testing.assert_allclose(
        loaded.transform(ft)["probability"],
        model.transform(ft)["probability"], rtol=1e-5)


def test_vw_trains_tail_rows():
    # round-1 defect: range(0, n - bs + 1, bs) dropped the tail batch
    import numpy as np
    from synapseml_tpu.linear.learner import VWParams, train

    rng = np.random.default_rng(0)
    n, k = 300, 4  # bs=256 -> tail of 44 rows must still train
    idx = rng.integers(0, 1 << 10, (n, k))
    val = rng.standard_normal((n, k)).astype(np.float32)
    y = np.where(val.sum(1) > 0, 1.0, -1.0).astype(np.float32)
    p = VWParams(num_bits=10, num_passes=1, batch_size=256)
    state, losses = train(p, idx, val, y)
    assert len(losses) == 2  # full batch + padded tail batch
    # n < bs entirely: must still run one (padded) step, not zero
    p2 = VWParams(num_bits=10, num_passes=1, batch_size=512)
    state2, losses2 = train(p2, idx, val, y)
    assert len(losses2) == 1 and float(np.abs(np.asarray(state2.w)).sum()) > 0


def test_iforest_max_features():
    import numpy as np
    from synapseml_tpu.data.table import Table
    from synapseml_tpu.isolationforest.iforest import IsolationForest

    rng = np.random.default_rng(0)
    x = rng.standard_normal((200, 10)).astype(np.float32)
    t = Table({"features": x})
    m = IsolationForest(num_estimators=10, max_features=0.3).fit(t)
    feat = m.trees[0]
    used = set(int(f) for f in feat.ravel() if f >= 0)
    assert len(used) <= 10  # sanity
    per_tree = [set(int(f) for f in row if f >= 0) for row in feat]
    assert all(len(s) <= 3 for s in per_tree)
    # different trees should sample different subsets (overwhelmingly likely)
    assert len(set(frozenset(s) for s in per_tree if s)) > 1


def test_featurizer_string_split_and_prefix_modes():
    """stringSplitInputCols + prefixStringsWithColumnName parity
    (ref: vw/.../VowpalWabbitFeaturizer.scala param surface). The
    string-split path ALWAYS hashes the bare token (reference
    StringSplitFeaturizer semantics); the prefix flag only governs the
    input_cols string/token paths."""
    from synapseml_tpu.linear.featurizer import VowpalWabbitFeaturizer

    t = Table({"txt": np.asarray(["red blue", "blue"], object),
               "tok": np.asarray([["blue"], ["red"]], object)})
    f = VowpalWabbitFeaturizer(string_split_input_cols=["txt"],
                               output_col="features")
    out = f.transform(t)
    # row 0 splits into two tokens, row 1 into one (padded)
    assert (out["features_val"][0] != 0).sum() == 2
    assert (out["features_val"][1] != 0).sum() == 1

    # prefix=False hashes tok's entries bare too: 'txt' split token
    # "blue" collides (shares a weight slot) with 'tok' token "blue"
    f2 = VowpalWabbitFeaturizer(string_split_input_cols=["txt"],
                                input_cols=["tok"],
                                prefix_strings_with_column_name=False,
                                output_col="features")
    o2 = f2.transform(t)
    r0 = set(np.asarray(o2["features_idx"][0])[
        np.asarray(o2["features_val"][0]) != 0])
    assert len(r0) == 2  # {blue(tok), red, blue(txt)} -> blue collides

    f3 = VowpalWabbitFeaturizer(string_split_input_cols=["txt"],
                                input_cols=["tok"],
                                output_col="features")
    o3 = f3.transform(t)
    r0p = set(np.asarray(o3["features_idx"][0])[
        np.asarray(o3["features_val"][0]) != 0])
    assert len(r0p) == 3  # bare split 'blue' != prefixed 'tok=blue'


def test_featurizer_string_split_matches_reference_tokenizer():
    """Reference parity for the string-split path
    (ref: vw/.../featurizer/StringSplitFeaturizer.scala): tokens come
    from the unicode word regex (?U)\\w+ — punctuation stripped — and
    the BARE token is hashed regardless of
    prefix_strings_with_column_name."""
    from synapseml_tpu.linear.featurizer import VowpalWabbitFeaturizer

    t = Table({"txt": np.asarray(["foo, foo! bar", "naïve café"],
                                 object)})
    f = VowpalWabbitFeaturizer(string_split_input_cols=["txt"],
                               output_col="features")
    out = f.transform(t)
    # 'foo,' and 'foo!' both tokenize to 'foo' -> ONE slot summed to 2.0
    # (whitespace splitting would emit three distinct hashes)
    row0 = np.asarray(out["features_val"][0])
    assert sorted(row0[row0 != 0].tolist()) == [1.0, 2.0]
    # unicode \\w keeps accented words as single tokens
    assert (np.asarray(out["features_val"][1]) != 0).sum() == 2

    # the prefix flag does not perturb string-split slots
    f_bare = VowpalWabbitFeaturizer(string_split_input_cols=["txt"],
                                    prefix_strings_with_column_name=False,
                                    output_col="features")
    o_bare = f_bare.transform(t)
    np.testing.assert_array_equal(np.asarray(out["features_idx"]),
                                  np.asarray(o_bare["features_idx"]))
    np.testing.assert_array_equal(np.asarray(out["features_val"]),
                                  np.asarray(o_bare["features_val"]))


def test_contextual_bandit_exploration_pmf():
    from synapseml_tpu.linear.estimators import VowpalWabbitContextualBandit

    rng = np.random.default_rng(0)
    n, k, d = 60, 3, 8
    bits = 10
    sh_idx = rng.integers(0, 2 ** bits, (n, d)).astype(np.int32)
    sh_val = rng.normal(size=(n, d)).astype(np.float32)
    actions = np.empty(n, object)
    for i in range(n):
        actions[i] = [(rng.integers(0, 2 ** bits, d).astype(np.int32),
                       rng.normal(size=d).astype(np.float32))
                      for _ in range(k)]
    t = Table({"shared_idx": sh_idx, "shared_val": sh_val,
               "action_features": actions,
               "chosenAction": rng.integers(1, k + 1, n).astype(np.int64),
               "cost": rng.random(n).astype(np.float32),
               "probability": np.full(n, 0.5, np.float32)})
    m = VowpalWabbitContextualBandit(
        num_bits=bits, num_passes=2, epsilon=0.3).fit(t)
    out = m.transform(t)
    for i in range(5):
        pmf = np.asarray(out["probabilities"][i])
        assert pmf.shape == (k,)
        np.testing.assert_allclose(pmf.sum(), 1.0, atol=1e-6)
        best = int(out["prediction"][i]) - 1
        np.testing.assert_allclose(pmf[best], 1 - 0.3 + 0.3 / k,
                                   atol=1e-6)
        others = [pmf[j] for j in range(k) if j != best]
        np.testing.assert_allclose(others, 0.3 / k, atol=1e-6)
