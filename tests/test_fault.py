"""Fault-tolerance utility tests (ref: FaultToleranceUtils.scala:1-33,
TrainUtils.scala:279-295 backoff retries)."""
import time

import pytest

from synapseml_tpu.utils.fault import retry_with_backoff, retry_with_timeout


def test_retry_with_timeout_succeeds_after_failures():
    calls = {"n": 0}

    def fn():
        calls["n"] += 1
        if calls["n"] < 3:
            raise RuntimeError("boom")
        return "ok"

    assert retry_with_timeout(fn, timeout_s=5, max_retries=3) == "ok"
    assert calls["n"] == 3


def test_retry_with_timeout_abandons_hung_attempts():
    t0 = time.monotonic()
    with pytest.raises(TimeoutError):
        retry_with_timeout(lambda: time.sleep(30), timeout_s=0.2,
                           max_retries=2)
    # the hung attempts were abandoned, not joined
    assert time.monotonic() - t0 < 5


def test_retry_with_timeout_raises_last_error():
    with pytest.raises(ValueError, match="always"):
        retry_with_timeout(lambda: (_ for _ in ()).throw(ValueError("always")),
                           timeout_s=1, max_retries=2)


def test_retry_with_backoff():
    calls = {"n": 0}

    def fn():
        calls["n"] += 1
        if calls["n"] < 3:
            raise ConnectionError("transient")
        return 42

    assert retry_with_backoff(fn, backoffs_ms=(1, 1, 1)) == 42

    with pytest.raises(ConnectionError):
        retry_with_backoff(lambda: (_ for _ in ()).throw(ConnectionError("x")),
                           backoffs_ms=(1,))

    # non-retryable types propagate immediately
    calls["n"] = 0

    def typed():
        calls["n"] += 1
        raise KeyError("nope")

    with pytest.raises(KeyError):
        retry_with_backoff(typed, backoffs_ms=(1, 1),
                           retryable=(ConnectionError,))
    assert calls["n"] == 1
