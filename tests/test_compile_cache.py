"""Persistent compile cache + AOT warmup tests (runtime/compile_cache.py).

Runs under the conftest-forced 8-device virtual CPU platform (the same
stand-in tests/test_executor_multidevice.py uses). Guarantees pinned:

- warmup() precompiles every (bucket, arity, donation-mask, layout)
  signature and _dispatch serves from the AOT table (no lazy jit);
- serialized executables round-trip across executor instances (the
  restarted-replica path) with bit-identical outputs;
- cache-KEY invalidation: changed model bytes, changed device count /
  mesh shape, and a changed jax version string all MISS — fresh compile,
  identical outputs, never a stale hit;
- cache-ENTRY corruption (truncated file) degrades to a fresh compile,
  never an error;
- JitCache.clear() invalidates open store handles so cleared tests
  cannot read back memoized stale executables;
- the serving readiness gate holds /health at 503 until warmup is done.
"""
import os

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from synapseml_tpu.runtime import compile_cache as cc
from synapseml_tpu.runtime.executor import (GLOBAL_JIT_CACHE,
                                            BatchedExecutor)

needs8 = pytest.mark.skipif(len(jax.devices()) < 8,
                            reason="needs the 8-device virtual platform")


@pytest.fixture(autouse=True)
def _restore_global_cache_config():
    """enable_persistent_cache wires PROCESS-GLOBAL jax config at tmp
    paths pytest deletes afterward — restore it so the rest of the suite
    never writes XLA cache entries into a dead directory."""
    prev = jax.config.jax_compilation_cache_dir
    prev_wired = cc._PERSISTENT_WIRED
    yield
    jax.config.update("jax_compilation_cache_dir", prev)
    cc._PERSISTENT_WIRED = prev_wired


def _mlp_fn():
    w = np.random.default_rng(0).standard_normal((6, 4)).astype(np.float32)
    return (lambda p, x: (jnp.tanh(x @ p), x * 2.0 + 1.0)), w


def _x(n=20, seed=1):
    return np.random.default_rng(seed).standard_normal(
        (n, 6)).astype(np.float32)


def _assert_same(got, want):
    assert len(got) == len(want)
    for g, s in zip(got, want):
        np.testing.assert_array_equal(np.asarray(g), np.asarray(s))


# -- warmup mechanics ----------------------------------------------------

def test_warmup_precompiles_full_ladder_and_dispatch_uses_aot():
    fn, w = _mlp_fn()
    ex = BatchedExecutor(fn, bound_args=(w,), max_bucket=32)
    rep = ex.warmup([((6,), np.float32)])
    assert [e["bucket"] for e in rep.entries] == [8, 16, 32]
    assert rep.compiled == 3 and rep.loaded == 0 and not rep.errors
    ref = BatchedExecutor(fn, bound_args=(w,), max_bucket=32)
    for n in (1, 9, 20, 32):
        _assert_same(ex(_x(n, seed=n)), ref(_x(n, seed=n)))
    # every call above hit a warmed executable, no lazy jit compile
    assert ex._aot_hits == 4
    # a second warmup is a no-op ("warm"), not a recompile
    rep2 = ex.warmup([((6,), np.float32)])
    assert all(e["status"] == "warm" for e in rep2.entries)


def test_warmup_from_example_arrays_matches_staged_signature():
    """Example arrays with a batch dim (and a dtype staging coerces,
    f64->f32) must produce the signature the pipeline dispatches."""
    fn, w = _mlp_fn()
    ex = BatchedExecutor(fn, bound_args=(w,), max_bucket=16)
    rep = ex.warmup([np.zeros((5, 6), np.float64)])
    assert rep.compiled == len(rep.entries) > 0
    ex(np.zeros((5, 6), np.float64))
    assert ex._aot_hits == 1


def test_warmup_unbounded_executor_requires_buckets():
    fn, w = _mlp_fn()
    ex = BatchedExecutor(fn, bound_args=(w,))
    with pytest.raises(ValueError):
        ex.warmup([((6,), np.float32)])
    rep = ex.warmup([((6,), np.float32)], buckets=[8])
    assert rep.compiled == 1


def test_warmup_error_degrades_to_lazy_jit():
    """A signature that fails to AOT-compile must be reported, not
    raised — and the executor still serves it through the lazy path."""
    calls = {"n": 0}

    def flaky(x):
        calls["n"] += 1
        return (x * 2.0,)

    ex = BatchedExecutor(flaky, max_bucket=8)
    orig = ex._jit_for

    def broken_jit_for(*a, **k):
        raise RuntimeError("synthetic AOT failure")

    ex._jit_for = broken_jit_for
    rep = ex.warmup([((3,), np.float32)])
    assert rep.entries[0]["status"] == "error" and rep.errors
    ex._jit_for = orig
    (out,) = ex(np.ones((4, 3), np.float32))
    np.testing.assert_array_equal(out, np.full((4, 3), 2.0, np.float32))


# -- persistence (the restarted-replica path) ---------------------------

def test_persist_roundtrip_new_executor_loads_and_matches(tmp_path):
    fn, w = _mlp_fn()
    kw = dict(bound_args=(w,), max_bucket=32, cache_key="model-v1",
              cache_dir=str(tmp_path))
    exA = BatchedExecutor(fn, **kw)
    repA = exA.warmup([((6,), np.float32)])
    assert repA.compiled == 3
    assert all(e.get("persisted") for e in repA.entries)
    wantA = exA(_x())

    exB = BatchedExecutor(fn, **kw)  # "restarted process"
    repB = exB.warmup([((6,), np.float32)])
    assert repB.loaded == 3 and repB.compiled == 0
    _assert_same(exB(_x()), wantA)
    assert exB._aot_hits == 1


def test_invalidation_changed_model_bytes(tmp_path):
    """A different cache_key (= changed graph/weights content hash) must
    MISS and fresh-compile — with outputs matching ITS OWN model."""
    fn, w = _mlp_fn()
    exA = BatchedExecutor(fn, bound_args=(w,), max_bucket=8,
                          cache_key="model-v1", cache_dir=str(tmp_path))
    assert exA.warmup([((6,), np.float32)]).compiled == 1

    w2 = w * 3.0

    exB = BatchedExecutor(fn, bound_args=(w2,), max_bucket=8,
                          cache_key="model-v2", cache_dir=str(tmp_path))
    repB = exB.warmup([((6,), np.float32)])
    assert repB.loaded == 0 and repB.compiled == 1
    ref = BatchedExecutor(fn, bound_args=(w2,), max_bucket=8)
    _assert_same(exB(_x()), ref(_x()))


@needs8
def test_invalidation_changed_device_count_and_mesh(tmp_path):
    """The same cache_key on a different topology (4-chip vs 8-chip mesh,
    and multi- vs single-device) must miss: mesh shape is part of the
    executable key. Outputs stay bit-identical to single-device."""
    fn, w = _mlp_fn()
    ref = BatchedExecutor(fn, bound_args=(w,), max_bucket=32)
    ex8 = BatchedExecutor(fn, devices=8, bound_args=(w,), max_bucket=32,
                          cache_key="m", cache_dir=str(tmp_path))
    rep8 = ex8.warmup([((6,), np.float32)])
    assert rep8.compiled == len(rep8.entries) > 0

    ex4 = BatchedExecutor(fn, devices=4, bound_args=(w,), max_bucket=32,
                          cache_key="m", cache_dir=str(tmp_path))
    rep4 = ex4.warmup([((6,), np.float32)])
    assert rep4.loaded == 0 and rep4.compiled == len(rep4.entries)

    ex1 = BatchedExecutor(fn, bound_args=(w,), max_bucket=32,
                          cache_key="m", cache_dir=str(tmp_path))
    rep1 = ex1.warmup([((6,), np.float32)])
    assert rep1.loaded == 0 and rep1.compiled == len(rep1.entries)

    for ex in (ex8, ex4, ex1):
        _assert_same(ex(_x(37)), ref(_x(37)))


def test_invalidation_changed_jax_version_string(tmp_path, monkeypatch):
    """An entry written by a different jax/jaxlib/backend fingerprint
    must be rejected at LOAD time (not just keyed apart): a cache volume
    surviving an image upgrade deserializing a stale executable would be
    undefined behavior."""
    fn, w = _mlp_fn()
    kw = dict(bound_args=(w,), max_bucket=8, cache_key="m",
              cache_dir=str(tmp_path))
    exA = BatchedExecutor(fn, **kw)
    assert exA.warmup([((6,), np.float32)]).compiled == 1

    real = cc.env_fingerprint()
    monkeypatch.setattr(cc, "env_fingerprint",
                        lambda: real + "|jax=99.99.99")
    exB = BatchedExecutor(fn, **kw)
    repB = exB.warmup([((6,), np.float32)])
    # key differs -> miss -> fresh compile; and even a key COLLISION
    # would be caught by the header check (exercised below)
    assert repB.loaded == 0 and repB.compiled == 1
    # header check: same key, skewed env at load time only
    store = exA._store
    skey = cc.executable_key("m", bucket=8,
                             sig=((("8", "x"),),), layout="single",
                             mesh_shape=(1,), device_kind="cpu",
                             fingerprint=real)
    monkeypatch.setattr(cc, "env_fingerprint", lambda: real)
    assert store.load(skey) is None  # missing entry: still just a miss
    ref = BatchedExecutor(fn, bound_args=(w,), max_bucket=8)
    _assert_same(exB(_x()), ref(_x()))


def test_corrupt_cache_entry_falls_back_to_fresh_compile(tmp_path):
    fn, w = _mlp_fn()
    kw = dict(bound_args=(w,), max_bucket=8, cache_key="m",
              cache_dir=str(tmp_path))
    exA = BatchedExecutor(fn, **kw)
    assert exA.warmup([((6,), np.float32)]).compiled == 1
    exdir = os.path.join(str(tmp_path), "executables")
    entries = [f for f in os.listdir(exdir) if f.endswith(".xc")]
    assert len(entries) == 1
    path = os.path.join(exdir, entries[0])
    with open(path, "rb") as fh:
        raw = fh.read()
    # deliberate truncation mid-payload
    with open(path, "wb") as fh:
        fh.write(raw[:len(raw) // 2])

    exB = BatchedExecutor(fn, **kw)
    repB = exB.warmup([((6,), np.float32)])
    assert repB.loaded == 0 and repB.compiled == 1 and not repB.errors
    ref = BatchedExecutor(fn, bound_args=(w,), max_bucket=8)
    _assert_same(exB(_x()), ref(_x()))
    # garbage that isn't even our container format: also just a miss
    with open(path, "wb") as fh:
        fh.write(b"\x00not an executable\xff" * 10)
    exC = BatchedExecutor(fn, **kw)
    assert exC.warmup([((6,), np.float32)]).compiled == 1


def test_jitcache_clear_invalidates_store_memos(tmp_path):
    """After GLOBAL_JIT_CACHE.clear(), a store must re-read DISK: an
    entry rewritten after the clear is observed (the memoized stale
    executable would otherwise win)."""
    fn, w = _mlp_fn()
    kw = dict(bound_args=(w,), max_bucket=8, cache_key="m",
              cache_dir=str(tmp_path))
    exA = BatchedExecutor(fn, **kw)
    exA.warmup([((6,), np.float32)])
    store = exA._store
    exdir = os.path.join(str(tmp_path), "executables")
    key = os.listdir(exdir)[0][:-len(".xc")]
    assert store.load(key) is not None
    assert store._memo  # memoized
    GLOBAL_JIT_CACHE.clear()
    assert not store._memo
    os.unlink(os.path.join(exdir, key + ".xc"))
    assert store.load(key) is None  # deleted entry actually observed


def test_env_knob_default_cache_dir(monkeypatch, tmp_path):
    monkeypatch.delenv("SYNAPSEML_COMPILE_CACHE", raising=False)
    assert cc.default_cache_dir() is None
    monkeypatch.setenv("SYNAPSEML_COMPILE_CACHE", str(tmp_path))
    assert cc.default_cache_dir() == str(tmp_path)
    fn, w = _mlp_fn()
    ex = BatchedExecutor(fn, bound_args=(w,), max_bucket=8, cache_key="m")
    rep = ex.warmup([((6,), np.float32)])
    assert all(e.get("persisted") for e in rep.entries)
    assert os.listdir(os.path.join(str(tmp_path), "executables"))


# -- donation-mask fallback (the residual-warning satellite) ------------

def test_donate_mask_eval_shape_failure_donates_nothing(monkeypatch):
    """When eval_shape cannot verify aliasability the mask must donate
    NOTHING (the old donate-all fallback produced the per-compile 'Some
    donated buffers were not usable' warnings in the bench tails)."""
    ex = BatchedExecutor(lambda x: (x * 2.0,), donate=True)

    def boom(*a, **k):
        raise RuntimeError("platform plugin tantrum")

    monkeypatch.setattr(jax, "eval_shape", boom)
    assert ex._donate_mask_for([np.zeros((8, 6), np.float32)]) == (False,)


def test_submit_precomputes_donate_mask_on_caller_thread():
    """submit() resolves the donate mask eagerly (caller's thread), so
    the dispatch thread only reads the cache."""
    ex = BatchedExecutor(lambda x: (x * 2.0,), donate=True, min_bucket=8)
    x = np.zeros((5, 6), np.float32)
    sig = ex._staged_sig([x], 8)
    assert sig == (((8, 6), "float32"),)
    ex(x)
    assert ex._donate_masks.get(sig) == (True,)


# -- model-layer wiring -------------------------------------------------

def test_onnxmodel_warmup_persist_and_restart(tmp_path):
    from synapseml_tpu.data.table import Table
    from synapseml_tpu.onnx import ONNXModel, zoo

    blob = zoo.mlp([16, 32], num_classes=4, seed=0)
    feats = np.random.default_rng(0).standard_normal(
        (20, 16)).astype(np.float32)

    mA = ONNXModel(model_bytes=blob)
    mA.set(compile_cache_dir=str(tmp_path), mini_batch_size=32)
    repA = mA.warmup()
    assert repA.compiled == len(repA.entries) == 3
    outA = mA.transform(Table({"input": feats}))

    mB = ONNXModel(model_bytes=blob)
    mB.set(compile_cache_dir=str(tmp_path), mini_batch_size=32)
    repB = mB.warmup()
    assert repB.loaded == 3 and repB.compiled == 0
    outB = mB.transform(Table({"input": feats}))
    col = mA.graph.output_names[0]
    np.testing.assert_array_equal(np.asarray(outA[col]),
                                  np.asarray(outB[col]))
    # changed model bytes -> different content hash -> cold again
    mC = ONNXModel(model_bytes=zoo.mlp([16, 32], num_classes=4, seed=7))
    mC.set(compile_cache_dir=str(tmp_path), mini_batch_size=32)
    repC = mC.warmup()
    assert repC.loaded == 0 and repC.compiled == 3


def test_onnxmodel_warmup_example_feeds_override_dtype(tmp_path):
    """The uint8-pixel wire (input_norm) serves a different staged dtype
    than the graph declares — example_feeds pins the real signature."""
    from synapseml_tpu.data.table import Table
    from synapseml_tpu.onnx import ONNXModel, zoo

    m = ONNXModel(model_bytes=zoo.mlp([16, 32], num_classes=4, seed=0))
    m.set(mini_batch_size=8,
          input_norm={"input": {"mean": 127.5, "scale": 1 / 58.0}})
    rep = m.warmup(example_feeds={
        "input": np.zeros((1, 16), np.uint8)})
    assert rep.compiled == 1 and not rep.errors
    ex = m._executor()
    m.transform(Table({"input": np.zeros((5, 16), np.uint8)}))
    assert ex._aot_hits == 1


def test_image_featurizer_warmup(tmp_path):
    from synapseml_tpu.data.table import Table
    from synapseml_tpu.image.featurizer import ImageFeaturizer
    from synapseml_tpu.onnx import zoo

    kw = dict(model_bytes=zoo.tiny_resnet(image_size=32),
              cut_output_layers=1, image_size=32, mini_batch_size=8,
              input_col="image", output_col="feats",
              compile_cache_dir=str(tmp_path))
    fA = ImageFeaturizer(**kw)
    repA = fA.warmup()
    assert repA.compiled == len(repA.entries) == 1
    imgs = np.empty(3, dtype=object)
    imgs[:] = [np.random.default_rng(i).integers(
        0, 255, (32, 32, 3)).astype(np.float32) for i in range(3)]
    outA = fA.transform(Table({"image": imgs}))
    assert fA._pieces()._aot_hits == 1

    fB = ImageFeaturizer(**kw)
    repB = fB.warmup()
    assert repB.loaded == 1 and repB.compiled == 0
    outB = fB.transform(Table({"image": imgs}))
    np.testing.assert_array_equal(np.stack(list(outA["feats"])),
                                  np.stack(list(outB["feats"])))


@needs8
def test_multidevice_warmup_restart_bit_identical(tmp_path):
    """The dp-sharded layout round-trips through the store too: a
    restarted 8-chip replica loads the mesh executables and reproduces
    the single-device outputs exactly."""
    fn, w = _mlp_fn()
    kw = dict(devices="all", bound_args=(w,), max_bucket=32,
              cache_key="mesh-model", cache_dir=str(tmp_path))
    exA = BatchedExecutor(fn, **kw)
    repA = exA.warmup([((6,), np.float32)])
    assert repA.compiled == len(repA.entries)
    exB = BatchedExecutor(fn, **kw)
    repB = exB.warmup([((6,), np.float32)])
    assert repB.loaded == len(repB.entries) and repB.compiled == 0
    single = BatchedExecutor(fn, bound_args=(w,), max_bucket=32)
    for n in (1, 8, 37):
        _assert_same(exB(_x(n, seed=n)), single(_x(n, seed=n)))


# -- serving readiness gate ---------------------------------------------

def test_serving_readiness_gate_health_503_until_ready():
    import urllib.error
    import urllib.request

    from synapseml_tpu.io.serving import ContinuousServer, make_reply

    def pipe(t):
        r = np.empty(t.num_rows, dtype=object)
        for i, v in enumerate(t["value"]):
            r[i] = make_reply(v)
        return t.with_column("reply", r)

    cs = ContinuousServer("readiness_gate_test", pipe, ready=False)
    try:
        health = cs.url.rstrip("/") + "/health"
        with pytest.raises(urllib.error.HTTPError) as ei:
            urllib.request.urlopen(health, timeout=5)
        assert ei.value.code == 503
        assert not cs.server.ready
        cs.server.set_ready(True)
        with urllib.request.urlopen(health, timeout=5) as r:
            assert r.status == 200 and r.read() == b"ok"
        cs.start()
        req = urllib.request.Request(
            cs.url, b'{"a": 1}', {"Content-Type": "application/json"})
        with urllib.request.urlopen(req, timeout=10) as r:
            assert r.read() == b'{"a": 1}'
    finally:
        cs.stop()
