"""Performance observatory (docs/observability.md "Performance
observatory", docs/perf.md "Regression gate"): the recompile sentinel,
device-memory telemetry + /debug/memory, utilization attribution, the
bench regression gate, and the donation-warning-zero regression guard.

Discipline matches tests/test_blackbox.py: every blocking wait rides a
HARD timeout so a regression fails fast instead of wedging the suite
(this file runs inside tools/ci/smoke_pipeline.sh's wall clock).
"""
import io
import json
import os
import subprocess
import sys
import urllib.error
import urllib.request
import warnings

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from synapseml_tpu.io.serving import ContinuousServer, make_reply
from synapseml_tpu.runtime import blackbox as bb
from synapseml_tpu.runtime import executor as E
from synapseml_tpu.runtime import perfwatch as pw
from synapseml_tpu.runtime import structlog as slog
from synapseml_tpu.runtime import telemetry as tm
from synapseml_tpu.runtime.executor import BatchedExecutor

HARD = 30.0  # hard wall for any blocking wait: hang -> fast red X
ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _clean_observability(tmp_path):
    """Fresh recorder + silent logs per test; dumps land in tmp."""
    prev_mode = slog.set_mode("")
    bb.set_dump_dir(str(tmp_path / "flight"))
    bb.reset()
    yield
    slog.set_mode(prev_mode[0], level=prev_mode[1])
    bb.set_dump_dir(None)
    bb.reset()


def _recompiles():
    return {r: c.value for r, c in E._M_RECOMPILE.items()}


def _ring(event):
    return [e for e in bb.snapshot(stacks=False)["events"]
            if e["event"] == event]


def _get(url, timeout=HARD):
    try:
        with urllib.request.urlopen(
                urllib.request.Request(url), timeout=timeout) as r:
            return r.status, r.read()
    except urllib.error.HTTPError as e:
        return e.code, e.read()


def _post(url, obj, timeout=HARD):
    req = urllib.request.Request(
        url, data=json.dumps(obj).encode(), method="POST",
        headers={"Content-Type": "application/json"})
    with urllib.request.urlopen(req, timeout=timeout) as r:
        return r.status, r.read()


# -- recompile sentinel -----------------------------------------------------

def test_post_warmup_shape_drift_counts_rings_and_logs():
    """The acceptance loop in one process: a deliberately shape-drifted
    call after warmup() increments the reason-labeled counter, lands a
    `recompile` event (with the offending signature) in the ring, and
    emits the matching structlog line."""
    buf = io.StringIO()
    slog.set_mode("json", level="info", stream=buf)
    ex = BatchedExecutor(lambda x: (x * 2.0,), min_bucket=8,
                         max_bucket=8)
    try:
        ex.warmup([((3,), np.float32)])
        before = _recompiles()
        ex(np.ones((5, 3), np.float32))  # warmed: AOT, no recompile
        mid = _recompiles()
        assert mid == before
        ex(np.ones((5, 7), np.float32))  # drifted: 7 features vs 3
        after = _recompiles()
        assert after["shape_drift"] == before["shape_drift"] + 1
        evs = _ring("recompile")
        assert len(evs) == 1
        assert evs[0]["reason"] == "shape_drift"
        assert "(8, 7)" in evs[0]["signature"]
        assert evs[0]["seconds"] > 0
        lines = [json.loads(ln) for ln in
                 buf.getvalue().splitlines() if ln]
        rec = [ln for ln in lines if ln["event"] == "recompile"]
        assert len(rec) == 1 and rec[0]["reason"] == "shape_drift"
    finally:
        ex.close()


def test_unwarmed_executor_never_counts_recompiles():
    disp = tm.histogram("executor_compile_seconds", phase="dispatch")
    before, n0 = _recompiles(), disp.count
    ex = BatchedExecutor(lambda x: (x + 1.0,), min_bucket=8)
    try:
        ex(np.ones((4, 3), np.float32))
        assert _recompiles() == before  # not warmed: not an incident
        assert disp.count == n0 + 1  # ...but the compile IS timed
        ex(np.ones((4, 3), np.float32))
        assert disp.count == n0 + 1  # second call: no compile observed
    finally:
        ex.close()


def test_retired_aot_entry_counts_cache_skew():
    ex = BatchedExecutor(lambda x: (x * 3.0,), min_bucket=8,
                         max_bucket=8)
    try:
        ex.warmup([((2,), np.float32)])
        before = _recompiles()
        # poison every warmed executable: the AOT call fails, the entry
        # retires, and the lazy fallback is a cache_skew recompile (the
        # shared-cache-volume / foreign-host failure mode)
        def _broken(*a, **k):
            raise RuntimeError("deserialized executable won't run here")
        with ex._tables_lock:
            for key in list(ex._aot):
                ex._aot[key] = _broken
        (out,) = ex(np.ones((5, 2), np.float32))  # degrades, no error
        np.testing.assert_allclose(out, np.ones((5, 2)) * 3.0)
        after = _recompiles()
        assert after["cache_skew"] == before["cache_skew"] + 1
        assert _ring("recompile")[0]["reason"] == "cache_skew"
    finally:
        ex.close()


def test_arity_drift_reason():
    ex = BatchedExecutor(lambda *xs: (sum(x.sum(axis=1) for x in xs)
                                      + xs[0][:, 0],),
                         min_bucket=8, max_bucket=8)
    try:
        ex.warmup([((2,), np.float32)])
        before = _recompiles()
        a = np.ones((4, 2), np.float32)
        ex(a, a)  # two args; warmup only ever saw one
        after = _recompiles()
        assert after["arity"] == before["arity"] + 1
    finally:
        ex.close()


def test_failed_first_attempt_still_counts_on_retry():
    """A first lazy-compile attempt that RAISES must not permanently
    blind the sentinel: the retry's real compile is still counted,
    timed, and ring-recorded."""
    ex = BatchedExecutor(lambda x: (x + 2.0,), min_bucket=8,
                         max_bucket=8)
    try:
        ex.warmup([((3,), np.float32)])
        real = ex._jit_for
        calls = {"n": 0}

        def flaky(n_args, mask=()):
            f = real(n_args, mask)

            def wrapped(*a):
                calls["n"] += 1
                if calls["n"] == 1:
                    raise RuntimeError("transient backend error")
                return f(*a)

            return wrapped

        ex._jit_for = flaky
        before = _recompiles()
        drifted = np.ones((4, 9), np.float32)
        with pytest.raises(RuntimeError, match="transient"):
            ex(drifted)  # first attempt dies mid-compile
        assert _recompiles() == before  # nothing compiled: not counted
        (out,) = ex(drifted)  # the retry performs the real compile
        np.testing.assert_allclose(out, drifted + 2.0)
        after = _recompiles()
        assert after["shape_drift"] == before["shape_drift"] + 1
        assert len(_ring("recompile")) == 1
    finally:
        ex.close()


def test_classify_donation_mask_reason():
    ex = BatchedExecutor(lambda x: (x * 2.0,), min_bucket=8)
    try:
        sig = (((8, 2), "float32"),)
        ex._note_warm_sig(sig, (True,))
        assert ex._classify_recompile(sig, (False,), False) \
            == "donation_mask"
        assert ex._classify_recompile(sig, (True,), False) \
            == "shape_drift"  # same sig+mask: outside-warmed-set bucket
        assert ex._classify_recompile(sig, (True,), True) == "cache_skew"
        assert ex._classify_recompile(sig * 2, (True,) * 2, False) \
            == "arity"
    finally:
        ex.close()


def test_compile_seconds_phases_on_scrape():
    ex = BatchedExecutor(lambda x: (x - 1.0,), min_bucket=8,
                         max_bucket=8)
    try:
        ex.warmup([((4,), np.float32)])
        ex(np.ones((3, 9), np.float32))  # drift -> dispatch-phase compile
        text = tm.prometheus_text()
        warm = [ln for ln in text.splitlines()
                if ln.startswith("synapseml_executor_compile_seconds_"
                                 "count") and 'phase="warmup"' in ln]
        disp = [ln for ln in text.splitlines()
                if ln.startswith("synapseml_executor_compile_seconds_"
                                 "count") and 'phase="dispatch"' in ln]
        assert warm and int(warm[0].rsplit(" ", 1)[1]) >= 1
        assert disp and int(disp[0].rsplit(" ", 1)[1]) >= 1
    finally:
        ex.close()


# -- device-memory telemetry ------------------------------------------------

def test_memory_gauges_present_per_forced_device():
    assert pw.ensure_registered()
    text = tm.prometheus_text()
    n_dev = len(jax.local_devices())
    assert n_dev == 8  # conftest forces the 8-device CPU platform
    for d in range(n_dev):
        assert f'synapseml_device_hbm_bytes_in_use{{device="{d}"}}' \
            in text
        assert f'synapseml_device_live_buffer_count{{device="{d}"}}' \
            in text
    assert "synapseml_device_hbm_peak_bytes" in text
    assert "synapseml_device_hbm_bytes_limit" in text


def test_memory_snapshot_counts_live_arrays_and_peaks():
    dev0 = jax.local_devices()[0]
    big = jax.device_put(jnp.zeros((256, 1024), jnp.float32), dev0)
    big.block_until_ready()
    snap = pw.memory_snapshot(force=True)
    assert len(snap["devices"]) == 8
    rec0 = [d for d in snap["devices"] if d["device"] == "0"][0]
    assert rec0["source"] == "live_arrays"  # CPU: no allocator stats
    assert rec0["bytes_in_use"] >= big.nbytes
    assert rec0["live_buffers"] >= 1
    assert rec0["process_peak_bytes"] >= rec0["bytes_in_use"]
    assert snap["totals"]["bytes_in_use"] >= big.nbytes
    # peak is a process high-water mark: dropping the array cannot
    # lower it
    peak = rec0["process_peak_bytes"]
    del big
    snap2 = pw.memory_snapshot(force=True)
    rec0b = [d for d in snap2["devices"] if d["device"] == "0"][0]
    assert rec0b["process_peak_bytes"] >= peak


def test_replicated_array_counts_full_bytes_per_device():
    """A weights-replicated array (the executor's bound-arg layout)
    holds a FULL copy on every device — the live_arrays fallback must
    count it per device from addressable_shards, not split one nbytes
    across the mesh (which would read 8x low here)."""
    from jax.sharding import Mesh, NamedSharding, PartitionSpec

    devs = jax.local_devices()
    mesh = Mesh(np.asarray(devs), ("dp",))
    repl = jax.device_put(jnp.zeros((128, 1024), jnp.float32),
                          NamedSharding(mesh, PartitionSpec()))
    repl.block_until_ready()
    try:
        snap = pw.memory_snapshot(force=True)
        per_copy = 128 * 1024 * 4
        for rec in snap["devices"]:
            assert rec["bytes_in_use"] >= per_copy, rec
        assert snap["totals"]["bytes_in_use"] >= per_copy * len(devs)
    finally:
        del repl


def test_high_water_event_latches_and_rearms():
    def rec(used):
        return [{"device": "hw-test-dev", "platform": "test",
                 "bytes_in_use": used, "bytes_limit": 1000,
                 "peak_bytes_in_use": 0, "live_buffers": 1}]

    assert pw.check_high_water(rec(950), fraction=0.9) \
        == ["hw-test-dev"]
    assert len(_ring("hbm_high_water")) == 1
    ev = _ring("hbm_high_water")[0]
    assert ev["bytes_in_use"] == 950 and ev["fraction"] == 0.95
    # hovering above the line: latched, no second event
    assert pw.check_high_water(rec(940), fraction=0.9) == []
    # dipping just under the line does NOT re-arm (hysteresis)...
    assert pw.check_high_water(rec(880), fraction=0.9) == []
    assert pw.check_high_water(rec(950), fraction=0.9) == []
    # ...falling 15% below it does
    assert pw.check_high_water(rec(500), fraction=0.9) == []
    assert pw.check_high_water(rec(999), fraction=0.9) \
        == ["hw-test-dev"]
    assert len(_ring("hbm_high_water")) == 2
    # no-limit records (the live_arrays fallback) never fire
    nolimit = [{"device": "hw-test-dev2", "platform": "t",
                "bytes_in_use": 10**12, "bytes_limit": 0,
                "peak_bytes_in_use": 0, "live_buffers": 1}]
    assert pw.check_high_water(nolimit, fraction=0.9) == []


# -- utilization attribution ------------------------------------------------

def test_duty_attribution_math():
    prev = {"t": 0.0, "compute": 0.0, "counts": {"a": 0.0, "b": 0.0}}
    cur = {"t": 4.0, "compute": 2.0,
           "counts": {"a": 10.0, "b": 30.0}}
    vals = pw._attribute(prev, cur)
    assert vals == {"a": pytest.approx(0.125),
                    "b": pytest.approx(0.375)}
    # overlap-inclusive compute can exceed wall: clamp at 1.0
    sat = pw._attribute(prev, {"t": 1.0, "compute": 5.0,
                               "counts": {"a": 10.0}})
    assert sat == {"a": 1.0}
    # no batches in the window: every known target reads 0
    idle = pw._attribute(cur, {"t": 8.0, "compute": 2.0,
                               "counts": {"a": 10.0, "b": 30.0}})
    assert idle == {"a": 0.0, "b": 0.0}


def test_duty_gauge_live_on_scrape():
    ex = BatchedExecutor(lambda x: (x * 2.0,), min_bucket=8)
    try:
        pw.duty_cycles(force=True)  # window anchor
        for _ in range(3):
            ex(np.ones((8, 4), np.float32))
        vals = pw.duty_cycles(force=True)
        assert "default" in vals
        assert 0.0 <= vals["default"] <= 1.0
        text = tm.prometheus_text()
        assert 'synapseml_executor_duty_cycle{device="default"}' in text
    finally:
        ex.close()


# -- /debug/memory over HTTP ------------------------------------------------

def test_debug_memory_endpoint_and_gate(monkeypatch):
    def pipeline(table):
        replies = np.empty(table.num_rows, dtype=object)
        for i, v in enumerate(table["value"]):
            replies[i] = make_reply({"echo": v})
        return table.with_column("reply", replies)

    cs = ContinuousServer("perfwatch_mem", pipeline, max_batch=8).start()
    try:
        host = cs.url.split("//")[1].rstrip("/")
        status, body = _get(f"http://{host}/debug/memory")
        assert status == 200
        snap = json.loads(body)
        assert len(snap["devices"]) == 8
        assert all("bytes_in_use" in d for d in snap["devices"])
        assert "totals" in snap
        # the whole-surface lockdown covers the new endpoint too
        monkeypatch.setenv("SYNAPSEML_DEBUG_ENDPOINTS", "0")
        status, _ = _get(f"http://{host}/debug/memory")
        assert status == 403
    finally:
        cs.stop()


def test_jax_free_server_does_not_init_backend():
    """A pure-numpy serving front-end must not force-initialize the
    jax backend just by binding a port (on a TPU host, libtpu is
    exclusive — a router process grabbing the chips would starve its
    scorer sibling): WorkerServer registers the memory gauges lazily,
    only when a backend already exists."""
    prog = (
        "import numpy as np\n"
        "from synapseml_tpu.io.serving import WorkerServer\n"
        "ws = WorkerServer('jaxfree')\n"
        "import sys\n"
        "jax = sys.modules.get('jax')\n"
        "from jax._src import xla_bridge as xb\n"
        "assert not xb._backends, 'server construction initialized "
        "a jax backend'\n"
        "ws.stop()\n"
        "print('jax-free ok')\n"
    )
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    out = subprocess.run([sys.executable, "-c", prog], env=env,
                         capture_output=True, text=True, timeout=HARD,
                         cwd=ROOT)
    if "has no attribute '_backends'" in out.stderr:
        pytest.skip("jax moved the private backend table")
    assert out.returncode == 0, out.stdout + out.stderr
    assert "jax-free ok" in out.stdout


# -- acceptance e2e: drifted request through serving ------------------------

def test_e2e_recompile_through_serving_metrics_flight_log():
    """ISSUE-10 acceptance: ONE run in which a shape-drifted request
    after warmup produces the counter increment on /metrics, a
    `recompile` event in /debug/flight, and the matching structlog
    line."""
    buf = io.StringIO()
    slog.set_mode("json", level="info", stream=buf)
    ex = BatchedExecutor(lambda x: (x * 3.0 + 1.0,), min_bucket=8)
    ex.warmup([((2,), np.float32)], buckets=[8])

    def pipeline(table):
        feats = np.stack([np.asarray(v["x"], np.float32)
                          for v in table["value"]])
        (out,) = ex(feats)
        replies = np.empty(table.num_rows, dtype=object)
        for i in range(table.num_rows):
            replies[i] = make_reply({"y": out[i].tolist()})
        return table.with_column("reply", replies)

    cs = ContinuousServer("perfwatch_e2e", pipeline, max_batch=8).start()
    try:
        host = cs.url.split("//")[1].rstrip("/")
        status, _ = _post(cs.url, {"x": [1.0, 2.0]})  # warmed shape
        assert status == 200
        _, before_text = _get(f"http://{host}/metrics")
        before = before_text.decode()
        status, _ = _post(cs.url, {"x": [1.0] * 5})  # drifted shape
        assert status == 200
        _, after_text = _get(f"http://{host}/metrics")
        after = after_text.decode()

        def total(text):
            return sum(
                float(ln.rsplit(" ", 1)[1]) for ln in text.splitlines()
                if ln.startswith("synapseml_executor_recompiles_total"))

        assert total(after) == total(before) + 1
        assert 'reason="shape_drift"' in after
        _, flight = _get(f"http://{host}/debug/flight")
        evs = [e for e in json.loads(flight)["events"]
               if e["event"] == "recompile"]
        assert evs and evs[0]["reason"] == "shape_drift"
        lines = [json.loads(ln) for ln in
                 buf.getvalue().splitlines() if ln]
        assert any(ln["event"] == "recompile"
                   and ln["reason"] == "shape_drift" for ln in lines)
    finally:
        cs.stop()
        ex.close()


# -- bench regression gate --------------------------------------------------

def _run(tp, lat):
    return {"metric": "tp_metric", "value": tp, "unit": "images/sec",
            "vs_baseline": 1.0,
            "secondary": [{"metric": "lat_metric", "value": lat,
                           "unit": "ms", "vs_baseline": 1.0}]}


_BASELINE = {"defaults": {"tolerance": 0.15},
             "metrics": {
                 "tp_metric": {"value": 100.0, "unit": "images/sec",
                               "tolerance": 0.15},
                 "lat_metric": {"value": 10.0, "unit": "ms",
                                "tolerance": 0.15}}}


def test_bench_check_passes_jittered_flat_history():
    from tools.ci.bench_check import evaluate

    # ±10% jitter around a flat baseline: min-of-N + the 15% band must
    # stay quiet
    runs = [_run(92.0, 10.9), _run(108.0, 9.2), _run(97.0, 10.4)]
    rows, regressions = evaluate(runs, _BASELINE)
    assert [r["status"] for r in rows] == ["ok", "ok"]
    assert regressions == []


def test_bench_check_flags_20pct_regression():
    from tools.ci.bench_check import evaluate

    # a consistent 20% step past the 15% tolerance — every run is
    # worse, so min-of-N cannot rescue it
    runs = [_run(80.0, 12.4), _run(79.0, 12.1), _run(81.0, 12.6)]
    rows, regressions = evaluate(runs, _BASELINE)
    assert {r["metric"] for r in regressions} \
        == {"tp_metric", "lat_metric"}
    assert all(r["status"] == "regressed" for r in regressions)


def test_bench_check_missing_metric_is_a_failure():
    from tools.ci.bench_check import evaluate

    runs = [{"metric": "tp_metric", "value": 100.0,
             "unit": "images/sec"}]  # lat_metric vanished
    rows, regressions = evaluate(runs, _BASELINE)
    assert [r["metric"] for r in regressions] == ["lat_metric"]
    assert regressions[0]["status"] == "missing"


def test_bench_check_cli_exit_codes(tmp_path):
    base = tmp_path / "baseline.json"
    hist = tmp_path / "history.jsonl"
    base.write_text(json.dumps(_BASELINE))
    flat1 = tmp_path / "flat1.json"
    flat2 = tmp_path / "flat2.json"
    flat1.write_text(json.dumps(_run(95.0, 10.5)))
    flat2.write_text(json.dumps(_run(103.0, 9.8)))
    script = os.path.join(ROOT, "tools", "ci", "bench_check.py")
    ok = subprocess.run(
        [sys.executable, script, "--baseline", str(base), "--history",
         str(hist), "--n", "2", str(flat1), str(flat2)],
        capture_output=True, text=True, timeout=HARD, cwd=ROOT)
    assert ok.returncode == 0, ok.stdout + ok.stderr
    # history accumulated one strict-JSON line per run
    assert len(hist.read_text().splitlines()) == 2
    bad = tmp_path / "bad.json"
    bad.write_text(json.dumps(_run(80.0, 12.5)))  # injected 20% step
    fail = subprocess.run(
        [sys.executable, script, "--baseline", str(base), "--history",
         str(hist), "--n", "1", str(bad)],
        capture_output=True, text=True, timeout=HARD, cwd=ROOT)
    assert fail.returncode == 2, fail.stdout + fail.stderr
    assert "regression" in fail.stdout


def test_bench_check_loose_throughput_tolerance_still_trips():
    from tools.ci.bench_check import evaluate

    # tolerance >= 1.0 on a higher-is-better metric would put the raw
    # limit at/below 0 and disable the gate; the clamp keeps a
    # collapse detectable
    baseline = {"metrics": {"tp_metric": {"value": 100.0,
                                          "unit": "images/sec",
                                          "tolerance": 1.5}}}
    _rows, regressions = evaluate([_run(1.0, 10.0)], baseline)
    assert [r["metric"] for r in regressions] == ["tp_metric"]


def test_bench_group_selection_honors_caller_order():
    import bench

    sel = bench._select_groups(["cold_start", "serving", "cold_start"])
    assert [g.name for g in sel] == ["cold_start", "serving"]
    # the default full run keeps registry order (resnet50 headline)
    full = bench._select_groups([g.name for g in bench.BENCH_GROUPS])
    assert [g.name for g in full][0] == "resnet50"


def test_bench_check_write_baseline_roundtrip(tmp_path):
    from tools.ci.bench_check import evaluate, write_baseline

    runs = [_run(95.0, 10.4), _run(101.0, 9.7)]
    base = write_baseline(str(tmp_path / "b.json"), runs,
                          default_tolerance=0.3)
    assert base["metrics"]["tp_metric"]["value"] == 101.0  # max-of-N
    assert base["metrics"]["lat_metric"]["value"] == 9.7   # min-of-N
    _rows, regressions = evaluate(runs, base)
    assert regressions == []
    reread = json.loads((tmp_path / "b.json").read_text())
    assert reread["metrics"] == base["metrics"]


def test_bench_finite_nan_null_convention():
    import bench

    out = bench._finite({"value": float("nan"),
                         "nested": [1.0, float("inf"), {"x": 2.5}]})
    assert out["value"] is None
    assert out["nested"][1] is None and out["nested"][2]["x"] == 2.5
    # the payload must survive a strict parse
    json.loads(json.dumps(out, allow_nan=False))


def test_bench_payload_merges_headline_detail():
    import bench

    entries = [{"metric": "serving_cold_start_first_batch_ms",
                "value": 400.0, "unit": "ms", "vs_baseline": 2.0,
                "detail": {"cold_ms": 800.0, "warm_ms": 400.0}},
               {"metric": "other", "value": 1.0, "unit": "x",
                "vs_baseline": 1.0}]
    run_detail = {"donated_buffers_not_usable_warnings": 0,
                  "telemetry": {}}
    payload = bench._compose_payload(entries, run_detail)
    # the headline's own A/B keys survive alongside the run detail
    assert payload["detail"]["cold_ms"] == 800.0
    assert payload["detail"]["donated_buffers_not_usable_warnings"] == 0
    assert [e["metric"] for e in payload["secondary"]] == ["other"]
    # detail-less headline (the full run's resnet50): run detail only
    plain = bench._compose_payload(
        [{"metric": "m", "value": 1.0, "unit": "x"}], run_detail)
    assert plain["detail"] == run_detail


def test_duty_cycles_ttl_serves_one_window():
    ex = BatchedExecutor(lambda x: (x * 2.0,), min_bucket=8)
    try:
        pw.duty_cycles(force=True)
        ex(np.ones((8, 4), np.float32))
        first = pw.duty_cycles(force=True)
        # inside the TTL every reader shares the SAME evaluation — a
        # second reader must not advance the window to a microsecond
        # span and zero the gauges
        assert pw.duty_cycles() is first
        assert pw.duty_cycles() is first
    finally:
        ex.close()


def test_bench_groups_fast_subset_is_valid():
    import bench

    names = [g.name for g in bench.BENCH_GROUPS]
    assert len(names) == len(set(names))
    assert set(bench.FAST_GROUPS) < set(names)
    assert names[0] == "resnet50"  # the headline group stays first
    # round-15 registry metadata: every group carries the description
    # + metric names --list prints and the kind perf_report keys on
    for g in bench.BENCH_GROUPS:
        assert g.kind in ("device", "host")
        assert g.describe and g.metrics


# -- donation-warning hygiene (ISSUE-10 satellite) --------------------------

def test_mlp_ladder_donation_emits_zero_unusable_warnings():
    """The BENCH_r05-tail scenario, pinned at zero under the current
    executor: an MLP-shaped program (no output aliases its
    (bucket, 16) input) warmed and scored across the 8..64 bucket
    ladder with donation forced ON must emit no 'donated buffers were
    not usable' warnings — the eval_shape mask donates only aliasable
    inputs, so the unusable annotation never reaches XLA
    (docs/perf.md "Donation-warning tail: final attribution")."""
    w = jnp.asarray(np.random.default_rng(0).normal(
        size=(16, 4)).astype(np.float32))

    def mlp(x):
        logits = x @ w
        return logits, jnp.argmax(logits, axis=1)

    fallback_before = E._M_DONATE_FB.value
    with warnings.catch_warnings(record=True) as rec:
        warnings.simplefilter("always")
        ex = BatchedExecutor(mlp, min_bucket=8, max_bucket=64,
                             donate=True)
        try:
            ex.warmup([((16,), np.float32)])
            for n in (5, 20, 40):  # buckets 8, 32, 64 — the r05 legs
                logits, pred = ex(np.random.default_rng(n).normal(
                    size=(n, 16)).astype(np.float32))
                assert logits.shape == (n, 4) and pred.shape == (n,)
        finally:
            ex.close()
    unusable = [str(x.message) for x in rec
                if "donated buffers were not usable"
                in str(x.message).lower()]
    assert unusable == []
    # and the masks really were computed (not skipped): all-False here
    assert all(m == (False,) for m in ex._donate_masks.values())
    assert E._M_DONATE_FB.value == fallback_before  # no eval_shape fail


def test_aliasable_program_still_donates_without_warning():
    with warnings.catch_warnings(record=True) as rec:
        warnings.simplefilter("always")
        ex = BatchedExecutor(lambda x: (x * 2.0,), min_bucket=8,
                             max_bucket=8, donate=True)
        try:
            (out,) = ex(np.ones((5, 16), np.float32))
            np.testing.assert_allclose(out, np.full((5, 16), 2.0))
        finally:
            ex.close()
    assert not [x for x in rec if "donated buffers were not usable"
                in str(x.message).lower()]
    # donation was actually annotated — the zero-warning result above
    # is hygiene, not a disabled feature
    assert any(True in m for m in ex._donate_masks.values())


def test_eval_shape_failure_degrades_to_donate_nothing(monkeypatch):
    ex = BatchedExecutor(lambda x: (x * 2.0,), min_bucket=8,
                         donate=True)
    try:
        before = E._M_DONATE_FB.value

        def boom(*a, **k):
            raise RuntimeError("platform plugin misbehaving")

        monkeypatch.setattr(jax, "eval_shape", boom)
        mask = ex._donate_mask_for_sig((((8, 16), "float32"),))
        assert mask == (False,)  # donate NOTHING, never donate-all
        assert E._M_DONATE_FB.value == before + 1
    finally:
        ex.close()
