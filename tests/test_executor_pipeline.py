"""Async submit/drain executor pipeline tests: thread-safe concurrent
submission, in-order streaming under mixed bucket sizes, exception
propagation through futures, and clean shutdown with batches in flight.

These are the structural guarantees the serving + ONNXModel hot paths
lean on (runtime/executor.py submit/stream/close); a deadlock here would
hang tier-1, so CI runs this file under a hard timeout
(tools/ci/smoke_pipeline.sh).
"""
import threading
import time

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from synapseml_tpu.runtime.executor import BatchedExecutor, ExecutorFuture


def test_submit_returns_future_with_call_identical_result():
    ex = BatchedExecutor(lambda x: (x * 2.0,), min_bucket=4)
    x = np.arange(11, dtype=np.float32)
    fut = ex.submit(x)
    assert isinstance(fut, ExecutorFuture)
    (y,) = fut.result()
    (y_call,) = ex(x)
    np.testing.assert_array_equal(y, y_call)
    assert fut.done() and fut.exception() is None


def test_submit_multi_chunk_concatenates_in_order():
    # 40 rows at max_bucket 8 -> 5 chunks; the future must assemble them
    # in submission order exactly like the historical __call__
    ex = BatchedExecutor(lambda x: (x + 1.0,), min_bucket=8, max_bucket=8)
    x = np.arange(40, dtype=np.float32)
    (y,) = ex.submit(x).result()
    np.testing.assert_allclose(y, x + 1.0)


def test_concurrent_submit_from_many_threads():
    """Thread-safety: N threads submitting distinct data concurrently
    each get exactly their own answer back."""
    ex = BatchedExecutor(lambda x: (x * 3.0,), min_bucket=4, max_bucket=8)
    n_threads, per_thread = 8, 6
    results = {}
    lock = threading.Lock()

    def worker(t):
        mine = []
        for k in range(per_thread):
            x = (np.arange(3 + (t + k) % 9, dtype=np.float32)
                 + 100.0 * t + 10.0 * k)
            (y,) = ex.submit(x).result()
            mine.append((x, y))
        with lock:
            results[t] = mine

    threads = [threading.Thread(target=worker, args=(t,))
               for t in range(n_threads)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=60)
    assert len(results) == n_threads
    for mine in results.values():
        for x, y in mine:
            np.testing.assert_allclose(y, x * 3.0)


def test_stream_in_order_mixed_bucket_sizes():
    """stream() yields per-item results in submission order even when
    items land in different shape buckets (different compile cache
    entries, different device times)."""
    ex = BatchedExecutor(lambda x: (x - 1.0,), min_bucket=4, max_bucket=32)
    sizes = [3, 17, 1, 32, 9, 4, 27, 2]
    items = [np.full(s, float(i), np.float32) for i, s in enumerate(sizes)]
    outs = list(ex.stream((a,) for a in items))
    assert len(outs) == len(items)
    for i, (got,) in enumerate(outs):
        assert got.shape == (sizes[i],)
        np.testing.assert_allclose(got, items[i] - 1.0)


def test_stream_accepts_bare_arrays_and_overlaps_producer():
    """A generator item's host work runs while earlier items compute:
    the stream holds pipeline_depth items in flight, so the producer is
    pulled ahead of the consumer."""
    ex = BatchedExecutor(lambda x: (x * 2.0,), min_bucket=4,
                         pipeline_depth=2)
    produced = []

    def gen():
        for i in range(5):
            produced.append(i)
            yield np.full(4, float(i), np.float32)

    seen = 0
    for i, (y,) in enumerate(ex.stream(gen())):
        np.testing.assert_allclose(y, 2.0 * i)
        seen += 1
        # depth-2 window: by the time item k is yielded, the producer
        # has been advanced past it (unless exhausted)
        assert len(produced) >= min(5, i + 2)
    assert seen == 5 and produced == list(range(5))


def test_exception_from_jitted_fn_propagates_through_future():
    def bad_fn(x):
        raise RuntimeError("scorer exploded")

    ex = BatchedExecutor(bad_fn, min_bucket=4)
    fut = ex.submit(np.ones(3, np.float32))
    with pytest.raises(RuntimeError, match="scorer exploded"):
        fut.result()
    assert isinstance(fut.exception(), RuntimeError)
    # __call__ surfaces the same error synchronously
    with pytest.raises(RuntimeError, match="scorer exploded"):
        ex(np.ones(3, np.float32))


def test_exception_does_not_wedge_pipeline():
    """A failing batch must not deadlock or poison the pipeline: the
    depth slot it held is released and later submits still complete."""
    state = {"fail": True}

    def fn(x):
        if state["fail"]:
            raise ValueError("transient")
        return (x + 5.0,)

    ex = BatchedExecutor(fn, min_bucket=4, pipeline_depth=2)
    futs = [ex.submit(np.ones(4, np.float32)) for _ in range(4)]
    for f in futs:
        with pytest.raises(ValueError, match="transient"):
            f.result()
    state["fail"] = False
    ex._jits.clear()  # drop the traced-and-failed cache entry
    for _ in range(4):  # more than pipeline_depth: slots were released
        (y,) = ex(np.ones(4, np.float32))
        np.testing.assert_allclose(y, 6.0)


def test_fetch_error_propagates_and_pipeline_survives():
    ex = BatchedExecutor(lambda x: (x * 2.0,), min_bucket=4)
    orig_fetch = ex._fetch
    boom = [True]

    def fetch(out, n, bucket):
        if boom[0]:
            boom[0] = False
            raise OSError("D2H transport dropped")
        return orig_fetch(out, n, bucket)

    ex._fetch = fetch
    with pytest.raises(OSError, match="transport dropped"):
        ex.submit(np.ones(4, np.float32)).result()
    (y,) = ex(np.ones(4, np.float32))
    np.testing.assert_allclose(y, 2.0)


def test_close_drains_inflight_batches():
    """Clean shutdown: close() lets already-submitted batches complete
    (their futures resolve with real results), then refuses new work."""
    ex = BatchedExecutor(lambda x: (x + 2.0,), min_bucket=4, max_bucket=4,
                         pipeline_depth=2)
    gate = threading.Event()
    orig_fetch = ex._fetch

    def slow_fetch(out, n, bucket):
        gate.wait(10)  # hold batches in flight until close() is underway
        return orig_fetch(out, n, bucket)

    ex._fetch = slow_fetch
    futs = [ex.submit(np.full(4, float(i), np.float32)) for i in range(3)]
    closer = threading.Thread(target=lambda: ex.close(wait=True))
    closer.start()
    time.sleep(0.05)
    gate.set()
    closer.join(timeout=30)
    assert not closer.is_alive(), "close(wait=True) did not finish"
    for i, f in enumerate(futs):
        (y,) = f.result(timeout=10)
        np.testing.assert_allclose(y, i + 2.0)
    with pytest.raises(RuntimeError, match="closed"):
        ex.submit(np.ones(4, np.float32))
    ex.close()  # idempotent


def test_close_before_first_submit():
    ex = BatchedExecutor(lambda x: (x,), min_bucket=4)
    ex.close()
    with pytest.raises(RuntimeError, match="closed"):
        ex.submit(np.ones(4, np.float32))


def test_dropped_executor_reaps_pipeline_threads():
    """An executor evicted from a jit cache must not leak its parked
    pipeline threads: the weakref finalizer shuts them down."""
    import gc

    ex = BatchedExecutor(lambda x: (x * 2.0,), min_bucket=4)
    ex(np.ones(4, np.float32))  # start the pipeline
    threads = list(ex._pipeline.threads)
    assert all(t.is_alive() for t in threads)
    del ex
    gc.collect()
    deadline = time.monotonic() + 10
    while time.monotonic() < deadline and any(t.is_alive() for t in threads):
        time.sleep(0.02)
    assert not any(t.is_alive() for t in threads), \
        "pipeline threads leaked after executor GC"


def test_future_add_done_callback_fires_once_after_last_chunk():
    ex = BatchedExecutor(lambda x: (x,), min_bucket=4, max_bucket=4)
    fired = []
    fut = ex.submit(np.arange(12, dtype=np.float32))  # 3 chunks
    fut.add_done_callback(lambda f: fired.append(f.done()))
    fut.result()
    deadline = time.monotonic() + 5
    while time.monotonic() < deadline and not fired:
        time.sleep(0.01)
    assert fired == [True]


def test_submit_empty_batch_learns_structure():
    ex = BatchedExecutor(lambda x: (x * 2.0,), min_bucket=4)
    (y,) = ex.submit(np.zeros((0, 3), np.float32)).result()
    assert y.shape == (0, 3)


def test_cross_caller_overlap_one_fetch_does_not_stall_dispatch():
    """The dedicated drain thread: while caller A's fetch blocks, caller
    B's batch must still be dispatched (the cross-caller overlap the
    serving scorers rely on)."""
    ex = BatchedExecutor(lambda x: (x * 2.0,), min_bucket=4, max_bucket=4,
                         pipeline_depth=2)
    first_fetch_started = threading.Event()
    release_first_fetch = threading.Event()
    dispatched = []
    orig_fetch, orig_dispatch = ex._fetch, ex._dispatch

    def fetch(out, n, bucket):
        if not first_fetch_started.is_set():
            first_fetch_started.set()
            assert release_first_fetch.wait(30)
        return orig_fetch(out, n, bucket)

    def dispatch(arrays, n, bucket, **kw):
        dispatched.append(n)
        return orig_dispatch(arrays, n, bucket, **kw)

    ex._fetch, ex._fetch_orig = fetch, orig_fetch
    ex._dispatch = dispatch
    fut_a = ex.submit(np.ones(4, np.float32))
    assert first_fetch_started.wait(10)
    fut_b = ex.submit(np.full(4, 7.0, np.float32))
    deadline = time.monotonic() + 10
    while time.monotonic() < deadline and len(dispatched) < 2:
        time.sleep(0.01)
    # B dispatched while A's fetch is still blocked inside device_get
    assert len(dispatched) == 2, dispatched
    release_first_fetch.set()
    np.testing.assert_allclose(fut_a.result()[0], 2.0)
    np.testing.assert_allclose(fut_b.result()[0], 14.0)
