"""Serving layer tests: real HTTP through a pipeline with a model scorer,
reply routing, epoch replay, consolidation — the HTTPv2Suite /
DistributedHTTPSuite analogue (ref: core/src/test/scala/.../io/split2/,
430+423 LoC of real-server suites).
"""
import json
import threading
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

from synapseml_tpu.data.table import Table
from synapseml_tpu.io.serving import (ContinuousServer, HTTPSourceStateHolder,
                                      WorkerServer, make_reply, parse_request,
                                      requests_to_table, send_replies)


def _post(url, obj, timeout=30):
    req = urllib.request.Request(
        url, data=json.dumps(obj).encode(), method="POST",
        headers={"Content-Type": "application/json"})
    with urllib.request.urlopen(req, timeout=timeout) as r:
        return r.status, json.loads(r.read().decode())


def test_worker_server_round_trip():
    srv = WorkerServer("t_rt")
    try:
        results = {}

        def client():
            results["resp"] = _post(srv.url, {"x": 5})

        ct = threading.Thread(target=client)
        ct.start()
        batch = srv.get_batch(max_rows=4, timeout=5.0)
        assert len(batch) == 1
        table = parse_request(requests_to_table(batch))
        assert table["value"][0] == {"x": 5}
        table = table.with_column(
            "reply", np.array([make_reply({"y": 10})], dtype=object))
        assert send_replies(srv, table) == 1
        ct.join(timeout=5)
        assert results["resp"] == (200, {"y": 10})
    finally:
        srv.stop()


def test_get_batch_linger_coalesces_concurrent_requests():
    """With a linger window, a concurrent burst lands in ONE batch (one
    amortized device round trip) instead of serial singletons; with
    linger=0 the drain takes only what is immediately available."""
    srv = WorkerServer("t_linger")
    try:
        n = 8
        barrier = threading.Barrier(n + 1)
        results = [None] * n

        def client(i):
            barrier.wait()
            # stagger arrivals across a few ms like real concurrency
            time.sleep(0.002 * i)
            results[i] = _post(srv.url, {"i": i})

        threads = [threading.Thread(target=client, args=(i,))
                   for i in range(n)]
        for t in threads:
            t.start()
        barrier.wait()
        batch = srv.get_batch(max_rows=64, timeout=5.0, linger=0.5)
        assert len(batch) == n, f"linger should coalesce all {n}, got {len(batch)}"
        table = requests_to_table(batch)
        replies = np.array([make_reply({"ok": True})] * n, dtype=object)
        send_replies(srv, table.with_column("reply", replies))
        for t in threads:
            t.join(timeout=5)
        assert all(r == (200, {"ok": True}) for r in results)
    finally:
        srv.stop()


def test_continuous_server_pipeline_with_model_scorer():
    """End-to-end: real HTTP requests -> pipeline containing a jax-scored
    model -> replies (the serving north-star path)."""
    from synapseml_tpu.onnx import ONNXModel, zoo

    model = ONNXModel(model_bytes=zoo.mlp([4, 8], num_classes=3, seed=3),
                      argmax_output_col="pred")

    def pipeline(table: Table) -> Table:
        feats = np.stack([np.asarray(v["features"], np.float32)
                          for v in table["value"]])
        scored = model.transform(Table({"input": feats}))
        replies = np.empty(table.num_rows, dtype=object)
        for i in range(table.num_rows):
            replies[i] = make_reply({"pred": int(scored["pred"][i])})
        return table.with_column("reply", replies)

    cs = ContinuousServer("t_model", pipeline, max_batch=16).start()
    try:
        rng = np.random.default_rng(0)
        feats = rng.normal(size=(12, 4)).astype(np.float32)
        statuses, preds = [], []
        lock = threading.Lock()

        def client(i):
            st, body = _post(cs.url, {"features": feats[i].tolist()})
            with lock:
                statuses.append(st)
                preds.append((i, body["pred"]))

        threads = [threading.Thread(target=client, args=(i,))
                   for i in range(12)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=30)
        assert cs.errors == []
        assert statuses == [200] * 12
        # replies must match direct model scoring per row
        direct = model.transform(Table({"input": feats}))["pred"]
        for i, p in preds:
            assert p == int(direct[i])
    finally:
        cs.stop()


def test_serving_latency_single_row():
    """Round-trip latency through a trivial pipeline — the reference claims
    'sub-millisecond' for the serving hop alone; assert a loose bound that
    catches structural regressions (polling, lock convoys)."""
    def pipeline(table: Table) -> Table:
        replies = np.empty(table.num_rows, dtype=object)
        for i, v in enumerate(table["value"]):
            replies[i] = make_reply({"ok": v["n"]})
        return table.with_column("reply", replies)

    cs = ContinuousServer("t_lat", pipeline, max_batch=1).start()
    try:
        _post(cs.url, {"n": 0})  # warm
        lat = []
        for i in range(20):
            t0 = time.perf_counter()
            st, body = _post(cs.url, {"n": i})
            lat.append(time.perf_counter() - t0)
            assert st == 200 and body["ok"] == i
        p50 = sorted(lat)[len(lat) // 2]
        assert p50 < 0.25, f"p50 serving latency {p50 * 1000:.1f}ms"
    finally:
        cs.stop()


def test_epoch_replay_on_worker_restart():
    """Uncommitted requests are replayed after a simulated task retry
    (ref: HTTPSourceV2.scala:488-505 recoveredPartitions)."""
    srv = WorkerServer("t_replay", reply_timeout=30.0)
    try:
        results = {}

        def client():
            results["resp"] = _post(srv.url, {"job": 1}, timeout=30)

        ct = threading.Thread(target=client)
        ct.start()
        batch = srv.get_batch(timeout=5.0)
        assert len(batch) == 1
        # worker "dies" before replying or committing; retry recovers
        recovered = srv.recover()
        assert recovered == 1
        batch2 = srv.get_batch(timeout=5.0)
        assert len(batch2) == 1
        assert batch2[0].rid == batch[0].rid
        table = requests_to_table(batch2).with_column(
            "reply", np.array([make_reply({"done": True})], dtype=object))
        send_replies(srv, table)
        srv.commit(batch2[0].epoch)
        ct.join(timeout=10)
        assert results["resp"] == (200, {"done": True})
        # committed epochs do not replay
        assert srv.recover() == 0
    finally:
        srv.stop()


def test_pipeline_error_returns_500_and_keeps_serving():
    calls = {"n": 0}

    def pipeline(table: Table) -> Table:
        calls["n"] += 1
        if calls["n"] == 1:
            raise RuntimeError("transient scorer failure")
        replies = np.empty(table.num_rows, dtype=object)
        for i in range(table.num_rows):
            replies[i] = make_reply({"ok": True})
        return table.with_column("reply", replies)

    cs = ContinuousServer("t_err", pipeline, max_batch=1).start()
    try:
        with pytest.raises(urllib.error.HTTPError) as ei:
            _post(cs.url, {"a": 1})
        assert ei.value.code == 500
        st, body = _post(cs.url, {"a": 2})
        assert st == 200 and body["ok"] is True
    finally:
        cs.stop()


def test_registry_shared_server():
    s1 = HTTPSourceStateHolder.get_or_create_server("t_reg")
    s2 = HTTPSourceStateHolder.get_or_create_server("t_reg")
    assert s1 is s2
    HTTPSourceStateHolder.remove("t_reg")



def test_keepalive_roundtrip_is_submillisecond():
    """Persistent-connection replies must not hit the Nagle/delayed-ACK
    stall (~40ms per request before the buffered-write + TCP_NODELAY
    fix); the reference's headline claim is sub-millisecond continuous
    serving (README.md:22)."""
    from synapseml_tpu.utils.profiling import serving_echo_latency

    lat = serving_echo_latency(samples=100, warmup=20, name="t_keepalive")
    p50 = lat[50]
    # the stall this guards against is ~40ms per request; the median of
    # 100 samples clears 25ms even on an oversubscribed CI box
    assert p50 < 0.025, f"keep-alive p50 {p50*1e3:.1f}ms — Nagle stall?"


def test_distributed_server_round_robin_and_resize():
    """Serving v1 analogue: one shared server, requests round-robin
    across channels; resize disperses orphaned requests
    (ref: DistributedHTTPSource.scala MultiChannelMap:27-80)."""
    from synapseml_tpu.io.serving import DistributedServer

    ds = DistributedServer("t_dist", n_channels=3)
    try:
        results = {}
        threads = []

        def client(i):
            results[i] = _post(ds.url, {"i": i})

        # 8 requests: rotates _add_index to 2, so the follow-up request
        # lands on channel 2 — the one the shrink below removes
        for i in range(8):
            th = threading.Thread(target=client, args=(i,))
            th.start()
            threads.append(th)

        # wait until the distributor has fanned out all 8 requests
        deadline = time.monotonic() + 5.0
        while time.monotonic() < deadline and sum(
                ds.channels.channel(c).qsize() for c in range(3)) < 8:
            time.sleep(0.01)

        # round-robin: channels get 3 / 3 / 2 of the 8 requests
        per_channel = []
        got = []
        for c in range(3):
            batch = ds.get_batch(c, max_rows=10, timeout=5.0)
            per_channel.append(len(batch))
            got.extend(batch)
        assert per_channel == [3, 3, 2]

        for cr in got:
            body = json.loads(cr.request.entity.decode())
            ds.reply_to(cr.rid, make_reply({"ok": body["i"]}))
        for th in threads:
            th.join(timeout=5)
        assert sorted(r[1]["ok"] for r in results.values()) == list(range(8))

        # elastic shrink: request 99 parks on channel 2, which the resize
        # removes — it must re-disperse to a surviving channel, not drop
        t2 = threading.Thread(target=client, args=(99,))
        t2.start()
        deadline = time.monotonic() + 5.0
        while time.monotonic() < deadline and \
                ds.channels.channel(2).qsize() < 1:
            time.sleep(0.01)
        assert ds.channels.channel(2).qsize() == 1
        ds.update_n_channels(1)
        batch = ds.get_batch(0, max_rows=4, timeout=5.0)
        assert len(batch) == 1
        ds.reply_to(batch[0].rid, make_reply({"ok": 99}))
        t2.join(timeout=5)
        assert results[99][1]["ok"] == 99
    finally:
        ds.stop()


def test_distributed_server_replay_and_ownership():
    """Channel consumption records epochs, so a dead shard's batch is
    replayable; a second DistributedServer on the same name is refused."""
    from synapseml_tpu.io.serving import DistributedServer

    ds = DistributedServer("t_dist2", n_channels=2)
    try:
        with pytest.raises(ValueError, match="already has"):
            DistributedServer("t_dist2", n_channels=2)

        results = {}

        def client():
            results["r"] = _post(ds.url, {"v": 7}, timeout=30)

        th = threading.Thread(target=client)
        th.start()
        batch = ds.get_batch(0, max_rows=4, timeout=5.0) or \
            ds.get_batch(1, max_rows=4, timeout=5.0)
        assert len(batch) == 1
        # shard "dies" before replying: recover() replays through the
        # distributor back onto a channel
        assert ds.server.recover() == 1
        batch2 = ds.get_batch(0, max_rows=4, timeout=5.0) or \
            ds.get_batch(1, max_rows=4, timeout=5.0)
        assert len(batch2) == 1 and batch2[0].rid == batch[0].rid
        ds.reply_to(batch2[0].rid, make_reply({"done": True}))
        th.join(timeout=10)
        assert results["r"] == (200, {"done": True})
    finally:
        ds.stop()


def test_pipelined_scoring_overlaps_device_time():
    """The two-stage pipeline + N scoring workers must overlap batch
    collection AND scoring. Deflaked (round 15): the original
    assertion compared WALL CLOCKS (pipelined < 0.8 x serial,
    best-of-2 per leg) — it tolerated the race that an oversubscribed
    2-core CI box's scheduler can stall the pipelined leg's second
    scorer thread past the margin, so both legs' best runs could land
    on load spikes and invert the ratio. The overlap is now observed
    EVENT-DRIVEN inside the scorer itself: the pipelined leg must
    reach >=2 concurrent pipeline_fn calls (two micro-batches
    genuinely in flight at once — the architectural claim), the
    serial leg must never exceed 1 (proof the comparison leg is
    actually serial). Concurrency inside the 250 ms sleep window is
    immune to absolute wall time; the only residual assumption is
    that worker pickup skew stays under the 250 ms 'device' time
    (round 17: widened from 100 ms, which a loaded tier-1 box
    occasionally exceeded).
    Also asserts the adaptive path commits every merged epoch (no
    request is left replayable after its reply)."""
    state = {"active": 0, "max_active": 0}
    state_lock = threading.Lock()

    def slow_pipeline(table: Table) -> Table:
        with state_lock:
            state["active"] += 1
            state["max_active"] = max(state["max_active"],
                                      state["active"])
        # 250ms "device" (round 17: widened from 100ms — the residual
        # tier-1 flake, see repo-test-baseline): under a full-suite
        # run on a 2-core box the second scorer's pickup skew was
        # occasionally observed past 100ms, reading max_active==1 on
        # the pipelined leg. 250ms is an order of magnitude over the
        # tens-of-ms scheduler jitter an oversubscribed box injects
        # while keeping the test ~2s; do NOT re-narrow without a
        # loaded-box soak
        time.sleep(0.25)
        with state_lock:
            state["active"] -= 1
        replies = np.empty(table.num_rows, dtype=object)
        for i in range(table.num_rows):
            replies[i] = make_reply({"ok": True})
        return table.with_column("reply", replies)

    def run(pipelined):
        name = f"t_overlap_{pipelined}"
        with state_lock:
            state["active"] = 0
            state["max_active"] = 0
        # linger 20ms + a client barrier: the 8 posts land near-
        # simultaneously and coalesce into exactly two micro-batches
        # even when thread startup is staggered by a loaded CI box —
        # ragged arrival would split them into 3-4 batches, which the
        # concurrency assert tolerates (any 2 batches overlapping is
        # enough) where the old wall-ratio did not
        cs = ContinuousServer(name, slow_pipeline, max_batch=4,
                              batch_linger=0.02, pipelined=pipelined,
                              scoring_workers=2).start()
        try:
            _post(cs.url, {"warm": 1})
            results = [None] * 8
            barrier = threading.Barrier(8)

            def client(i):
                barrier.wait(timeout=30)
                results[i] = _post(cs.url, {"i": i})

            threads = [threading.Thread(target=client, args=(i,))
                       for i in range(8)]
            for t in threads:
                t.start()
            for t in threads:
                t.join(timeout=30)
            assert all(r is not None and r[0] == 200 for r in results)
            # every drained epoch was committed -> nothing replayable
            assert cs.server.recover() == 0
            with state_lock:
                return state["max_active"]
        finally:
            cs.stop()

    serial_conc = run(False)
    pipe_conc = run(True)
    # serial: one loop thread collects AND scores — structurally never
    # two pipeline_fn calls at once; pipelined + 2 scoring workers:
    # both micro-batches score inside the same 250 ms window
    assert serial_conc == 1, serial_conc
    assert pipe_conc >= 2, pipe_conc


def test_reply_send_runs_off_the_scoring_thread():
    """Pipelined mode is a 3-stage pipeline: reply serialization +
    epoch commits for batch k run on the dedicated reply thread while
    the scorer moves on to batch k+1 — so pipeline_fn and send_replies
    must execute on DIFFERENT threads (the serial path keeps them on
    one)."""
    import synapseml_tpu.io.serving as serving_mod

    score_threads, reply_threads = set(), set()
    orig_send = serving_mod.send_replies

    def recording_send(server, table, reply_col="reply", id_col="id"):
        reply_threads.add(threading.get_ident())
        return orig_send(server, table, reply_col, id_col)

    def pipeline(table: Table) -> Table:
        score_threads.add(threading.get_ident())
        replies = np.empty(table.num_rows, dtype=object)
        for i, v in enumerate(table["value"]):
            replies[i] = make_reply({"ok": v["n"]})
        return table.with_column("reply", replies)

    serving_mod.send_replies = recording_send
    cs = ContinuousServer("t_reply_thread", pipeline, max_batch=4).start()
    try:
        for i in range(6):
            st, body = _post(cs.url, {"n": i})
            assert st == 200 and body["ok"] == i
        assert cs.errors == []
        assert score_threads and reply_threads
        assert score_threads.isdisjoint(reply_threads), (
            score_threads, reply_threads)
        # commits flowed through the reply stage: nothing replayable
        assert cs.server.recover() == 0
    finally:
        cs.stop()
        serving_mod.send_replies = orig_send


def test_scored_batches_flush_real_replies_on_stop():
    """stop() must deliver REAL replies for batches that were already
    scored but still parked in the reply queue — only unscored handoff
    batches fast-fail with 503."""
    gate = threading.Event()

    def pipeline(table: Table) -> Table:
        replies = np.empty(table.num_rows, dtype=object)
        for i, v in enumerate(table["value"]):
            replies[i] = make_reply({"ok": v["n"]})
        out = table.with_column("reply", replies)
        gate.set()  # scored: from here the reply stage owns the batch
        return out

    cs = ContinuousServer("t_flush_stop", pipeline, max_batch=4).start()
    try:
        results = {}

        def client():
            results["r"] = _post(cs.url, {"n": 42}, timeout=30)

        th = threading.Thread(target=client)
        th.start()
        assert gate.wait(10)
        cs.stop()  # reply thread drains the scored batch before exiting
        th.join(timeout=10)
        assert results["r"] == (200, {"ok": 42})
    finally:
        HTTPSourceStateHolder.remove("t_flush_stop")


def test_exact_commit_preserves_earlier_inflight_epochs():
    """Concurrent scorers finish epochs out of order: committing epoch 4
    must NOT prune epoch 3's replay history (the cumulative prune is the
    serial loop's semantics only) — recover() still replays epoch 3."""
    from synapseml_tpu.io.serving import WorkerServer

    ws = WorkerServer("t_exact_commit")
    try:
        results = {}

        def client(i):
            results[i] = _post(ws.url, {"i": i}, timeout=30)

        threads = [threading.Thread(target=client, args=(i,))
                   for i in range(2)]
        for t in threads:
            t.start()
        b3 = ws.get_batch(max_rows=1, timeout=5.0)   # epoch N
        b4 = ws.get_batch(max_rows=1, timeout=5.0)   # epoch N+1
        assert len(b3) == 1 and len(b4) == 1
        # worker scoring b4 finishes FIRST and commits exactly
        ws.reply_to(b4[0].rid, make_reply({"ok": 4}))
        ws.commit(b4[0].epoch, exact=True)
        # b3's scorer dies before replying: its epoch must still replay
        assert ws.recover() == 1
        b3r = ws.get_batch(max_rows=1, timeout=5.0)
        assert b3r and b3r[0].rid == b3[0].rid
        ws.reply_to(b3r[0].rid, make_reply({"ok": 3}))
        ws.commit(b3r[0].epoch, exact=True)
        for t in threads:
            t.join(timeout=10)
        assert sorted(r[0] for r in results.values()) == [200, 200]
    finally:
        ws.stop()


def test_drain_queue_coalesce_window_batches_staggered_arrivals():
    """Deadline-based coalescing: requests arriving WITHIN the window of
    the first request's arrival ride the same batch — the knob that lets
    concurrent low-QPS clients share one device round trip."""
    import queue as _q

    from synapseml_tpu.io.http import HTTPRequestData
    from synapseml_tpu.io.serving import CachedRequest, _drain_queue

    q: "_q.Queue" = _q.Queue()
    q.put(CachedRequest("a", HTTPRequestData(url="/", method="POST", headers={}, entity=b"1")))

    def late():
        time.sleep(0.05)
        q.put(CachedRequest("b", HTTPRequestData(url="/", method="POST", headers={}, entity=b"2")))

    t = threading.Thread(target=late)
    t.start()
    out = _drain_queue(q, max_rows=8, timeout=0.5, coalesce=0.3)
    t.join()
    assert [cr.rid for cr in out] == ["a", "b"]
    # without a window the drain takes what's there: the late request
    # would have ridden the NEXT batch
    q2: "_q.Queue" = _q.Queue()
    q2.put(CachedRequest("a", HTTPRequestData(url="/", method="POST", headers={}, entity=b"1")))
    out2 = _drain_queue(q2, max_rows=8, timeout=0.5)
    assert [cr.rid for cr in out2] == ["a"]


def test_drain_queue_coalesce_deadline_is_arrival_anchored():
    """A request that already sat in the queue longer than the window
    (busy scorer) must pay ZERO extra delay — the deadline anchors at
    arrival, unlike linger which restarts at observation."""
    import queue as _q

    from synapseml_tpu.io.http import HTTPRequestData
    from synapseml_tpu.io.serving import CachedRequest, _drain_queue

    q: "_q.Queue" = _q.Queue()
    q.put(CachedRequest("old", HTTPRequestData(url="/", method="POST", headers={}, entity=b"1")))
    time.sleep(0.25)  # request ages past the window
    t0 = time.monotonic()
    out = _drain_queue(q, max_rows=8, timeout=0.5, coalesce=0.2)
    elapsed = time.monotonic() - t0
    assert [cr.rid for cr in out] == ["old"]
    assert elapsed < 0.15, f"aged request paid {elapsed:.3f}s extra wait"


def test_continuous_server_batch_coalesce_amortizes_concurrent_clients():
    """End-to-end: with batch_coalesce on, N near-simultaneous clients
    score as FEWER pipeline_fn invocations than requests (micro-batch
    amortization), and every client still gets its own reply."""
    calls = []

    def pipeline(table):
        calls.append(table.num_rows)
        replies = np.empty(table.num_rows, dtype=object)
        for i, v in enumerate(table["value"]):
            replies[i] = make_reply({"echo": v})
        return table.with_column("reply", replies)

    cs = ContinuousServer("t_coalesce", pipeline, max_batch=16,
                          batch_coalesce=0.15, pipelined=False).start()
    try:
        assert cs.batch_coalesce == 0.15
        n_clients = 6
        results = {}
        barrier = threading.Barrier(n_clients)

        def client(i):
            barrier.wait()
            results[i] = _post(cs.url, {"i": i})

        threads = [threading.Thread(target=client, args=(i,))
                   for i in range(n_clients)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=30)
        assert len(results) == n_clients
        assert all(r[0] == 200 and r[1]["echo"]["i"] == i
                   for i, r in results.items())
        assert sum(calls) == n_clients
        assert len(calls) < n_clients, (
            f"coalescing never batched: {calls}")
    finally:
        cs.stop()


def test_drain_queue_coalesce_backlog_still_sweeps():
    """An EXPIRED window must not degrade batching: with a backlog whose
    head already aged past the coalesce window, the drain still sweeps
    everything instantly available (like coalesce=0), instead of
    returning a singleton per device round trip."""
    import queue as _q

    from synapseml_tpu.io.http import HTTPRequestData
    from synapseml_tpu.io.serving import CachedRequest, _drain_queue

    q: "_q.Queue" = _q.Queue()
    for i in range(10):
        q.put(CachedRequest(str(i), HTTPRequestData(
            url="/", method="POST", headers={}, entity=b"x")))
    time.sleep(0.25)  # head ages past the window
    t0 = time.monotonic()
    out = _drain_queue(q, max_rows=64, timeout=0.5, coalesce=0.2)
    elapsed = time.monotonic() - t0
    assert [cr.rid for cr in out] == [str(i) for i in range(10)]
    assert elapsed < 0.15, f"expired-window sweep waited {elapsed:.3f}s"
