"""RL003 good: tmp-then-replace — readers never see a torn write."""
import json
import os


def save(path, payload):
    tmp = path + ".tmp"
    with open(tmp, "w") as fh:
        json.dump(payload, fh)
    os.replace(tmp, path)
