"""EV001 good: reads a knob the registry documents."""
import os


def flag():
    return os.environ.get("SYNAPSEML_TELEMETRY", "") != "0"
