"""JH005 good: everything needed is read before donation (or taken
from the returned value)."""
import jax


def step(params, grads):
    norm = params["w"].sum()         # read BEFORE the donating dispatch
    update = jax.jit(apply_update, donate_argnums=(0,))
    new_params = update(params, grads)
    return new_params, norm + new_params["w"].sum()


def apply_update(params, grads):
    return {"w": params["w"] - grads["w"]}
