"""PL001 good: the wrapper bounds its footprint against a VMEM budget."""
import jax

_VMEM_BUDGET_BYTES = 16 * 1024 * 1024


def scale_rows(x):
    from jax.experimental import pallas as pl

    if 2 * x.size * x.dtype.itemsize > _VMEM_BUDGET_BYTES:
        raise ValueError("block footprint exceeds the VMEM budget")

    def kernel(x_ref, o_ref):
        o_ref[...] = x_ref[...] * 2.0

    return pl.pallas_call(
        kernel,
        out_shape=jax.ShapeDtypeStruct(x.shape, x.dtype),
    )(x)
