"""Cross-module twins with a consistent lock order, half B."""
import threading

from tests.fixtures.analysis.good import crossmod_a

LOCK_B = threading.Lock()
_FEED = []


def publish(key):
    with LOCK_B:
        _FEED.append(key)


def rollup():
    snap = crossmod_a.snapshot()  # LOCK_A taken and RELEASED first
    with LOCK_B:
        return snap, list(_FEED)
