"""JH003 good: static args are hashable (tuples, scalars)."""
from functools import partial

import jax


@partial(jax.jit, static_argnums=(1,))
def windowed(x, sizes=(8, 16)):
    return x


def run(x):
    g = jax.jit(windowed, static_argnums=(1,))
    return g(x, (32, 64))
