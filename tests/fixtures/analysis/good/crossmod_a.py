"""Cross-module twins with a consistent lock order, half A.

Same shape as the bad pair, but crossmod_b.rollup reads the snapshot
BEFORE taking LOCK_B — every path orders LOCK_A before LOCK_B.
"""
import threading

from tests.fixtures.analysis.good import crossmod_b

LOCK_A = threading.Lock()
_TABLE = {}


def refresh(key, value):
    with LOCK_A:
        _TABLE[key] = value
        crossmod_b.publish(key)


def snapshot():
    with LOCK_A:
        return dict(_TABLE)
