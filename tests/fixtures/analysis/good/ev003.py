"""EV003 good: the documented spelling of the knob."""
import os


def enabled():
    return os.environ.get("SYNAPSEML_TRACE", "") == "1"
