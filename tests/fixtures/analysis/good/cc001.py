"""CC001 good: every cross-thread write holds the owning lock."""
import threading


class Pipeline:
    def __init__(self):
        self._lock = threading.Lock()
        self.processed = 0
        self.last_error = None       # synlint: shared

    def start(self):
        threading.Thread(target=self._worker_supervised, daemon=True).start()

    def _worker_supervised(self):
        with self._lock:
            self.processed += 1

    def reset(self):
        with self._lock:
            self.processed = 0
            self.last_error = None
