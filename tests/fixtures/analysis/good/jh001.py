"""JH001 good: the hot path stays non-blocking; syncs live on the
drain side's _fetch (not a dispatch/drain-loop name)."""
import numpy as np


def _dispatch(self, arrays, bucket):
    # np.asarray on a HOST input (not device-tainted) is fine
    staged = [np.asarray(a) for a in arrays]
    out = self._jit_for(len(staged))(*staged)
    return out, bucket


def fetch(self, out):
    import jax

    return jax.device_get(out)
