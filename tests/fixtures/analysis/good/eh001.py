"""EH001 good: BaseException is recorded, then re-raised."""


def drain(q, log):
    try:
        return q.get()
    except BaseException as e:
        log.record("drain_failed", error=repr(e))
        raise
