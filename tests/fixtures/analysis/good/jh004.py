"""JH004 good: state flows through arguments and returns."""
import jax


class Model:
    @jax.jit
    def forward(self, x):
        y = x * 2                    # locals are fine
        return y


@jax.jit
def count(x, total):
    return x, total + x.sum()        # carry state functionally
