"""RL002 good: the registration has a matching unregister in-module."""
from synapseml_tpu.runtime import telemetry as _tm


class Server:
    def start(self):
        _tm.gauge_fn("queue_depth", lambda: self.depth())
        return self

    def stop(self):
        _tm.unregister("queue_depth")
