"""EH002 good: the silent swallow carries its rationale inline."""


def refresh(cache):
    try:
        cache.load()
    except Exception:  # noqa: BLE001 - refresh is best-effort
        pass
