"""JH002 good: branching on static properties or via lax.cond."""
from functools import partial

import jax
import jax.numpy as jnp


@jax.jit
def scale(x, threshold):
    return jnp.where(threshold > 0, x * threshold, x)


@partial(jax.jit, static_argnames=("causal",))
def attend(x, causal):
    if causal:                       # static arg: legal python branch
        return x - 1
    if x.ndim > 2:                   # .ndim is static under trace
        return x.sum(-1)
    if x is None:                    # identity check: static for tracers
        return x
    return x
