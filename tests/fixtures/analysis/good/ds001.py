"""DS001 good: the same nesting acquired directly — the static model
orders the pair, so the observed edge in the sidecar is covered."""
from synapseml_tpu.runtime.locksan import make_lock

_A = make_lock("ds001:_A")
_B = make_lock("ds001:_B")


def flush():
    with _A:
        with _B:
            pass
