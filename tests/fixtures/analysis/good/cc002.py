"""CC002 good: one global acquisition order."""
import threading

_A_LOCK = threading.Lock()
_B_LOCK = threading.Lock()


def transfer():
    with _A_LOCK:
        with _B_LOCK:
            pass


def refund():
    with _A_LOCK:
        with _B_LOCK:
            pass
