"""RL001 good: the thread body runs under a supervision wrapper."""
import threading


class Poller:
    def _supervised(self):
        while True:
            self.tick()

    def start(self):
        self._thread = threading.Thread(target=self._supervised,
                                        daemon=True)
        self._thread.start()
