"""CC003 good: waits happen outside; the lock only guards the dict."""
import queue
import threading
import time

_LOCK = threading.Lock()
_Q = queue.Queue()
_CACHE = {}


def consume(fut, fn, args, key):
    item = _Q.get(timeout=1.0)
    res = fut.result()
    time.sleep(0.1)
    exe = fn.lower(*args).compile()
    with _LOCK:
        got = _CACHE.get(key)        # dict.get: not a blocking call
        if got is None:
            _CACHE[key] = exe
    return item, res, got
