"""Cross-module lock cycle, half B: LOCK_B -> (crossmod_a) LOCK_A."""
import threading

from tests.fixtures.analysis.bad import crossmod_a

LOCK_B = threading.Lock()
_FEED = []


def publish(key):
    with LOCK_B:
        _FEED.append(key)


def rollup():
    with LOCK_B:
        # acquires LOCK_A while LOCK_B is held: the inverse of
        # crossmod_a.refresh's ordering — a deadlock when both run
        return crossmod_a.snapshot(), list(_FEED)
