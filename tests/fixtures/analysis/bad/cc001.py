"""CC001 bad: shared fields written off-lock from thread functions."""
import threading


class Pipeline:
    def __init__(self):
        self._lock = threading.Lock()
        self.processed = 0
        self.last_error = None       # synlint: shared

    def start(self):
        threading.Thread(target=self._worker, daemon=True).start()

    def _worker(self):
        self.processed += 1          # CC001: unguarded, also written below

    def reset(self):
        self.processed = 0           # CC001: second unguarded writer
        self.last_error = None       # CC001: annotated shared, no lock
