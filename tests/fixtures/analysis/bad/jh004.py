"""JH004 bad: side effects inside jitted functions."""
import jax

_STATS = {"calls": 0}


class Model:
    @jax.jit
    def forward(self, x):
        self.last_batch = x          # JH004: self mutation under jit
        _STATS["calls"] += 1         # JH004: module-global mutation
        return x * 2


@jax.jit
def count(x):
    global _TOTAL
    _TOTAL = x.sum()                 # JH004: global write under jit
    return x
