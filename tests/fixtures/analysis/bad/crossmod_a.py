"""Cross-module lock cycle, half A: LOCK_A -> (crossmod_b) LOCK_B.

Neither file is flaggable alone — each function takes ONE lock and the
second acquisition happens behind a call into the other module. Only
the whole-program pass, resolving ``crossmod_b.publish`` through the
import table and closing over its acquisitions, sees the inverse
ordering against crossmod_b.rollup.
"""
import threading

from tests.fixtures.analysis.bad import crossmod_b

LOCK_A = threading.Lock()
_TABLE = {}


def refresh(key, value):
    with LOCK_A:
        _TABLE[key] = value
        crossmod_b.publish(key)  # acquires LOCK_B while LOCK_A is held


def snapshot():
    with LOCK_A:
        return dict(_TABLE)
