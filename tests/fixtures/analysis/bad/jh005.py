"""JH005 bad: donated buffer read after dispatch."""
import jax


def step(params, grads):
    update = jax.jit(apply_update, donate_argnums=(0,))
    new_params = update(params, grads)
    norm = params["w"].sum()         # JH005: params was donated above
    return new_params, norm


def apply_update(params, grads):
    return {"w": params["w"] - grads["w"]}
