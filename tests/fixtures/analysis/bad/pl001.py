"""PL001 bad: pallas_call launched with no VMEM-budget guard."""
import jax


def scale_rows(x):
    from jax.experimental import pallas as pl

    def kernel(x_ref, o_ref):
        o_ref[...] = x_ref[...] * 2.0

    return pl.pallas_call(  # PL001: nothing bounds the block bytes
        kernel,
        out_shape=jax.ShapeDtypeStruct(x.shape, x.dtype),
    )(x)
