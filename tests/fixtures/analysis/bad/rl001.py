"""RL001 bad: long-lived thread started outside a supervision boundary."""
import threading


class Poller:
    def start(self):
        self._thread = threading.Thread(target=self._loop,  # RL001
                                        daemon=True)
        self._thread.start()

    def _loop(self):
        while True:
            self.tick()
