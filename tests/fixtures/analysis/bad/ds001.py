"""DS001 bad: the inner lock is reached through a dict alias, so the
static CC002 model never orders the pair — but the runtime saw the
edge (the ``ds001.observed.json`` sidecar), which makes it a model
gap."""
from synapseml_tpu.runtime.locksan import make_lock

_A = make_lock("ds001:_A")
_B = make_lock("ds001:_B")
_REGISTRY = {"b": _B}


def flush():
    with _A:
        with _REGISTRY["b"]:        # dynamically _A -> _B; statically opaque
            pass
