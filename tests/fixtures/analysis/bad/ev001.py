"""EV001 bad: reads a knob docs/knobs.md has no row for."""
import os


def flag():
    return os.environ.get("SYNAPSEML_NOT_IN_TABLE", "") == "1"
