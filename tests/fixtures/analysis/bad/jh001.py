"""JH001 bad: host syncs inside a dispatch hot path."""
import jax
import numpy as np


def _dispatch(self, arrays, bucket):
    out = self._jit_for(len(arrays))(*arrays)
    out.block_until_ready()          # JH001: sync stalls the pipeline
    host = np.asarray(out)           # JH001: D2H on a device value
    loss = float(out)                # JH001: scalar sync
    jax.device_get(out)              # JH001: explicit blocking fetch
    return host, loss
