"""RL003 bad: tmp-file write never finalized by an atomic rename."""
import json


def save(path, payload):
    tmp = path + ".tmp"
    with open(tmp, "w") as fh:  # RL003: no os.replace in this function
        json.dump(payload, fh)
