"""CC002 bad: two lock-order cycles — AB/BA and a self-reacquire."""
import threading

_A_LOCK = threading.Lock()
_B_LOCK = threading.Lock()


def transfer():
    with _A_LOCK:
        with _B_LOCK:                # A -> B
            pass


def refund():
    with _B_LOCK:
        with _A_LOCK:                # CC002: B -> A closes the cycle
            pass


def reenter():
    with _A_LOCK:
        with _A_LOCK:                # CC002: non-reentrant re-acquire
            pass
