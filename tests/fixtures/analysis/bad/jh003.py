"""JH003 bad: non-hashable values in static positions."""
from functools import partial

import jax
import numpy as np


@partial(jax.jit, static_argnums=(1,))
def windowed(x, sizes=[8, 16]):      # JH003: list default for static arg
    return x


def run(x):
    g = jax.jit(windowed, static_argnums=(1,))
    return g(x, [32, 64])            # JH003: list passed in static slot
