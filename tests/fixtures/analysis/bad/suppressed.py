"""Suppression fixture: the same JH001 violation three ways — bare (a
finding), same-line disable, and previous-line disable. Exactly ONE
finding must survive."""


def _dispatch(self, arrays):
    out = self._jit_for(1)(*arrays)
    out.block_until_ready()                     # survives: no directive
    out.block_until_ready()                     # synlint: disable=JH001
    # synlint: disable=JH001
    out.block_until_ready()
    return out
