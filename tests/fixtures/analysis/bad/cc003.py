"""CC003 bad: blocking work inside critical sections."""
import queue
import threading
import time

_LOCK = threading.Lock()
_Q = queue.Queue()


def consume(fut, fn, args):
    with _LOCK:
        item = _Q.get(timeout=1.0)   # CC003: queue wait under lock
        res = fut.result()           # CC003: future wait under lock
        time.sleep(0.1)              # CC003: sleep under lock
        exe = fn.lower(*args).compile()  # CC003: XLA compile under lock
    return item, res, exe
