"""JH002 bad: python control flow on traced values."""
from functools import partial

import jax
import jax.numpy as jnp


@jax.jit
def scale(x, threshold):
    if threshold > 0:                # JH002: tracer in `if`
        return x * threshold
    return x


@partial(jax.jit, static_argnums=(1,))
def clip_loop(x, n):
    while x.sum() > n:               # JH002: tracer in `while` (x traced)
        x = x * 0.5
    return x
