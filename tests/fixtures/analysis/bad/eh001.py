"""EH001 bad: bare except swallows BaseException (faults.ThreadKilled)."""


def drain(q):
    try:
        return q.get()
    except:  # noqa: E722 - EH001: an injected kill vanishes here
        return None
