"""EH002 bad: broad except with a silent body and no rationale."""


def refresh(cache):
    try:
        cache.load()
    except Exception:
        pass
