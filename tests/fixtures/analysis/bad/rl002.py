"""RL002 bad: instance-scope gauge_fn registration with no unregister."""
from synapseml_tpu.runtime import telemetry as _tm


class Server:
    def start(self):
        _tm.gauge_fn("queue_depth", lambda: self.depth())  # RL002
        return self
