"""EV003 bad: typo'd knob prefix reads the default forever."""
import os


def enabled():
    return os.environ.get("SYNAPSML_TRACE", "") == "1"  # missing E
