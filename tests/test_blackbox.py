"""Incident-grade observability surfaces (docs/observability.md):
flight recorder ring + triggered dumps, /debug endpoints, structured
JSON-lines logging with rid round-trip, and SLO burn-rate math.

Discipline matches tests/test_faults.py: every blocking wait rides a
HARD timeout so a regression fails fast instead of wedging the suite
(this file runs inside tools/ci/smoke_pipeline.sh's wall clock).
"""
import glob
import io
import json
import threading
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

from synapseml_tpu.data.table import Table
from synapseml_tpu.io.serving import (ContinuousServer,
                                      DistributedServer,
                                      MultiChannelMap, WorkerServer,
                                      make_reply)
from synapseml_tpu.io.http import HTTPRequestData
from synapseml_tpu.io.serving import CachedRequest
from synapseml_tpu.runtime import blackbox as bb
from synapseml_tpu.runtime import faults as flt
from synapseml_tpu.runtime import slo
from synapseml_tpu.runtime import structlog as slog
from synapseml_tpu.runtime import telemetry as tm

HARD = 30.0  # hard wall for any blocking wait: hang -> fast red X


@pytest.fixture(autouse=True)
def _clean_observability(tmp_path):
    """Fresh recorder + silent logs per test; dumps land in tmp."""
    flt.deactivate()
    prev_mode = slog.set_mode("")
    bb.set_dump_dir(str(tmp_path / "flight"))
    bb.configure(capacity=bb.DEFAULT_CAPACITY, min_dump_interval_s=0.0)
    bb.reset()
    yield
    flt.deactivate()
    slog.set_mode(prev_mode[0], level=prev_mode[1])
    bb.set_dump_dir(None)
    bb.configure(capacity=bb.DEFAULT_CAPACITY,
                 min_dump_interval_s=10.0)
    bb.reset()


def _get(url, timeout=HARD):
    with urllib.request.urlopen(
            urllib.request.Request(url), timeout=timeout) as r:
        return r.status, r.read()


def _post(url, obj, timeout=HARD, headers=None):
    hdrs = {"Content-Type": "application/json"}
    hdrs.update(headers or {})
    req = urllib.request.Request(url, data=json.dumps(obj).encode(),
                                 method="POST", headers=hdrs)
    try:
        with urllib.request.urlopen(req, timeout=timeout) as r:
            return r.status, dict(r.headers), r.read()
    except urllib.error.HTTPError as e:
        body = e.read()
        return e.code, dict(e.headers), body


def _echo_pipeline(table: Table) -> Table:
    replies = np.empty(table.num_rows, dtype=object)
    for i, v in enumerate(table["value"]):
        replies[i] = make_reply({"echo": v})
    return table.with_column("reply", replies)


def _cr(rid: str) -> CachedRequest:
    return CachedRequest(rid, HTTPRequestData(url="/", method="POST"))


# -- flight recorder ring ---------------------------------------------------

def test_ring_bounds_and_eviction():
    bb.configure(capacity=8)
    for i in range(20):
        bb.record("evt", idx=i)
    events = bb.snapshot(stacks=False)["events"]
    assert len(events) == 8
    assert [e["idx"] for e in events] == list(range(12, 20))
    seqs = [e["seq"] for e in events]
    assert seqs == sorted(seqs)  # monotone seq survives eviction


def test_record_fields_and_kill_switch():
    bb.record("rich", rid="r-1", channel=3, level="warn", n=2,
              rids=["a", "b"])
    (ev,) = bb.snapshot(stacks=False)["events"]
    assert ev["rid"] == "r-1" and ev["channel"] == 3
    assert ev["level"] == "warn" and ev["rids"] == ["a", "b"]
    assert ev["ts"] > 0 and "mono" in ev
    prev = bb.set_enabled(False)
    try:
        bb.record("dropped")
        assert bb.trigger("dropped_too") is None
        assert len(bb.snapshot(stacks=False)["events"]) == 1
    finally:
        bb.set_enabled(prev)


def test_snapshot_carries_threads_and_telemetry():
    bb.record("x")
    snap = bb.snapshot()
    names = {t["name"] for t in snap["threads"]}
    assert "MainThread" in names
    main = next(t for t in snap["threads"] if t["name"] == "MainThread")
    assert any("test_blackbox" in fr["file"] for fr in main["stack"])
    assert "counters" in snap["telemetry"]


def test_trigger_dumps_and_debounces(tmp_path):
    bb.configure(min_dump_interval_s=60.0)
    path = bb.trigger("unit_trip", channel=2, extra="ctx")
    assert path is not None
    # the write is async (triggers sit on failure paths);
    # last_dump_path flips once the file is fully on disk
    deadline = time.monotonic() + HARD
    while bb.last_dump_path() != path and time.monotonic() < deadline:
        time.sleep(0.01)
    assert bb.last_dump_path() == path
    with open(path) as fh:
        d = json.load(fh)
    assert d["trigger"]["reason"] == "unit_trip"
    assert d["trigger"]["channel"] == 2
    assert d["events"][-1]["event"] == "unit_trip"
    assert d["threads"]  # per-thread stacks ride every dump
    # second trigger inside the window: event recorded, NO second dump
    assert bb.trigger("unit_trip") is None
    assert len(glob.glob(str(tmp_path / "flight" / "flight-*"))) == 1
    assert bb.last_dump_path() == path


# -- breaker trip -> auto dump, redisperse rids -----------------------------

def test_redisperse_records_rids():
    m = MultiChannelMap(3)
    rids = [f"p{i}" for i in range(5)]
    for r in rids:
        m.channel(0).put(_cr(r))
    assert m.set_channel_enabled(0, False) == 5
    evs = [e for e in bb.snapshot(stacks=False)["events"]
           if e["event"] == "redisperse"]
    assert evs and evs[-1]["channel"] == 0 and evs[-1]["n"] == 5
    assert set(evs[-1]["rids"]) <= set(rids)


def test_breaker_trip_auto_dumps_with_thread_stacks():
    flt.activate("compute.channel0", prob=1.0)
    ds = DistributedServer("bb_trip", n_channels=2,
                           breaker_threshold=1, probe_interval=5.0)
    ds.serve(_echo_pipeline, max_batch=4)
    try:
        # first scored batch on channel 0 fails -> trip (threshold 1)
        # -> failover to channel 1 -> the client still sees 200
        st, hdrs, body = _post(ds.url, {"x": [1.0]})
        assert st == 200, (st, body)
        deadline = time.monotonic() + HARD
        while bb.last_dump_path() is None and \
                time.monotonic() < deadline:
            time.sleep(0.02)
        path = bb.last_dump_path()
        assert path, "breaker trip produced no flight dump"
        with open(path) as fh:
            d = json.load(fh)
        kinds = [e["event"] for e in d["events"]]
        assert "breaker_trip" in kinds
        assert "breaker_transition" in kinds
        trip = next(e for e in d["events"]
                    if e["event"] == "breaker_trip")
        assert trip["channel"] == 0 and trip["server"] == "bb_trip"
        names = {t["name"] for t in d["threads"]}
        assert any(n.startswith("chan-scorer-bb_trip") for n in names)
        # the failover for the SAME batch lands in the ring right
        # after the trip dump; the live snapshot must carry its rid
        live = [e for e in bb.snapshot(stacks=False)["events"]
                if e["event"] == "failover"]
        assert live and live[-1]["channel"] == 0
        assert hdrs["X-Request-Id"] in live[-1]["rids"]
    finally:
        flt.deactivate()
        ds.stop()


def test_executor_pipeline_break_records_event():
    from synapseml_tpu.runtime.executor import BatchedExecutor
    from synapseml_tpu.runtime.faults import PipelineBrokenError

    ex = BatchedExecutor(lambda x: (x * 2.0,), min_bucket=4)
    try:
        flt.activate("thread_kill.drain", times=1)
        exc = ex.submit(np.ones((3, 2), np.float32)).exception(
            timeout=HARD)
        assert isinstance(exc, PipelineBrokenError)
        deadline = time.monotonic() + HARD
        while time.monotonic() < deadline:
            evs = [e for e in bb.snapshot(stacks=False)["events"]
                   if e["event"] == "pipeline_break"]
            if evs:
                break
            time.sleep(0.02)
        assert evs, "pipeline break never hit the flight ring"
        assert "drain" in evs[-1]["thread"]
        deadline = time.monotonic() + HARD
        while bb.last_dump_path() is None and \
                time.monotonic() < deadline:
            time.sleep(0.02)
        assert bb.last_dump_path() is not None
    finally:
        flt.deactivate()
        ex.close(wait=False)


# -- debug endpoints over HTTP ----------------------------------------------

def test_debug_flight_and_threads_endpoints():
    bb.record("marker", rid="dbg-1")
    srv = WorkerServer("bb_debug")
    try:
        base = f"http://{srv.host}:{srv.port}"
        st, body = _get(f"{base}/debug/flight")
        assert st == 200
        snap = json.loads(body)
        assert any(e.get("rid") == "dbg-1" for e in snap["events"])
        assert snap["threads"] and snap["telemetry"]
        st, body = _get(f"{base}/debug/threads")
        assert st == 200
        names = {t["name"] for t in json.loads(body)}
        assert "serving-bb_debug" in names  # the accept loop itself
        for t in json.loads(body):
            assert {"name", "ident", "daemon", "stack"} <= set(t)
    finally:
        srv.stop()


def test_debug_endpoints_gate(monkeypatch):
    # SYNAPSEML_DEBUG_ENDPOINTS=0 locks the whole /debug surface down:
    # thread stacks and event history are internals no unauthenticated
    # client should read from a hardened deployment
    monkeypatch.setenv("SYNAPSEML_DEBUG_ENDPOINTS", "0")
    srv = WorkerServer("bb_gated")
    try:
        base = f"http://{srv.host}:{srv.port}"
        for path in ("/debug/flight", "/debug/threads",
                     "/debug/profile?ms=10"):
            try:
                st, _ = _get(f"{base}{path}")
            except urllib.error.HTTPError as e:
                st = e.code
            assert st == 403, path
        # /metrics and /span stay open — they expose no stacks
        st, _ = _get(f"{base}/metrics")
        assert st == 200
    finally:
        srv.stop()


def test_debug_profile_bounded_gated_single_flight(monkeypatch):
    srv = WorkerServer("bb_prof")
    try:
        base = f"http://{srv.host}:{srv.port}"
        st, body = _get(f"{base}/debug/profile?ms=40")
        assert st == 200
        rep = json.loads(body)
        assert rep["ms"] == 40.0 and "trace_dir" in rep
        assert rep["seconds"] >= 0.04
        # bounded: out-of-range windows clamp instead of DoS-ing
        st, body = _get(f"{base}/debug/profile?ms=-5")
        assert json.loads(body)["ms"] == 1.0  # clamped low end
        # single-flight: hold the lock, concurrent request gets 409
        results = {}

        def long_profile():
            try:
                _get(f"{base}/debug/profile?ms=1500")
                results["first"] = 200
            except urllib.error.HTTPError as e:
                results["first"] = e.code

        t = threading.Thread(target=long_profile, daemon=True)
        t.start()
        time.sleep(0.3)  # the long profile is inside its window
        try:
            st2, _ = _get(f"{base}/debug/profile?ms=10")
        except urllib.error.HTTPError as e:
            st2 = e.code
        assert st2 == 409
        t.join(timeout=HARD)
        assert not t.is_alive() and results["first"] == 200
        # gate: disabled surface answers 403, runs nothing
        monkeypatch.setenv("SYNAPSEML_DEBUG_PROFILE", "0")
        try:
            st3, _ = _get(f"{base}/debug/profile?ms=10")
        except urllib.error.HTTPError as e:
            st3 = e.code
        assert st3 == 403
    finally:
        srv.stop()


# -- structured logging + rid round trip ------------------------------------

def test_structlog_schema_text_and_levels():
    buf = io.StringIO()
    slog.set_mode("json", level="info", stream=buf)
    slog.log("debug", "below_floor", rid="x")  # filtered
    slog.log("warn", "kept", rid="r9", channel=1, n=3)
    lines = [ln for ln in buf.getvalue().splitlines() if ln]
    assert len(lines) == 1
    rec = json.loads(lines[0])
    assert rec["event"] == "kept" and rec["level"] == "warn"
    assert rec["rid"] == "r9" and rec["channel"] == 1 and rec["n"] == 3
    assert rec["ts"] > 0
    # text mode renders the same record human-readably
    buf2 = io.StringIO()
    slog.set_mode("text", stream=buf2)
    slog.log("info", "human", rid="r10")
    assert "human" in buf2.getvalue() and "rid=r10" in buf2.getvalue()
    with pytest.raises(ValueError):
        slog.set_mode("yaml")


def test_client_request_id_round_trip_through_serving():
    buf = io.StringIO()
    slog.set_mode("json", level="debug", stream=buf)
    cs = ContinuousServer("bb_rid", _echo_pipeline, max_batch=4).start()
    try:
        st, hdrs, body = _post(cs.url, {"x": 1},
                               headers={"X-Request-Id": "caller-abc.1"})
        assert st == 200
        # the caller's id IS the rid: echoed on the reply, names the
        # span, and correlates the structured log lines
        assert hdrs["X-Request-Id"] == "caller-abc.1"
        assert tm.get_span("caller-abc.1") is not None
        recs = [json.loads(ln) for ln in buf.getvalue().splitlines()
                if ln.startswith("{")]
        mine = [r for r in recs if r.get("rid") == "caller-abc.1"]
        assert {"request", "reply"} <= {r["event"] for r in mine}
        # a malformed id (length cap) falls back to a minted uuid,
        # still echoed so the caller sees the substitution
        st, hdrs, _ = _post(cs.url, {"x": 2},
                            headers={"X-Request-Id": "y" * 300})
        assert st == 200
        assert hdrs["X-Request-Id"] != "y" * 300
        assert len(hdrs["X-Request-Id"]) == 32
    finally:
        cs.stop()


def test_request_id_echoed_on_shed_paths():
    # max_queue=0: every enqueue sheds 429 — the shed reply must still
    # carry the caller's id (and Retry-After)
    srv = WorkerServer("bb_shed", max_queue=0)
    try:
        st, hdrs, _ = _post(f"http://{srv.host}:{srv.port}/", {"x": 1},
                            headers={"X-Request-Id": "shed-me-7"})
        assert st == 429
        assert hdrs["X-Request-Id"] == "shed-me-7"
        assert int(hdrs["Retry-After"]) >= 1
        srv.begin_drain()
        st, hdrs, _ = _post(f"http://{srv.host}:{srv.port}/", {"x": 1},
                            headers={"X-Request-Id": "drain-me-8"})
        assert st == 503
        assert hdrs["X-Request-Id"] == "drain-me-8"
        shed_evs = [e["event"] for e in
                    bb.snapshot(stacks=False)["events"]]
        assert "shed_queue" in shed_evs and "shed_drain" in shed_evs
    finally:
        srv.stop()


# -- SLO math ---------------------------------------------------------------

def test_slo_availability_math():
    assert slo.availability({}) == 1.0
    assert slo.availability({200: 99, 500: 1}) == pytest.approx(0.99)
    assert slo.availability({200: 50, 503: 25, 504: 25}) == \
        pytest.approx(0.5)
    # 4xx are deliberate answers, not availability losses
    assert slo.availability({200: 1, 400: 7, 429: 2}) == 1.0
    # unparseable status buckets count bad
    assert slo.availability({"error": 1, 200: 1}) == pytest.approx(0.5)


def test_slo_fraction_le_against_known_histogram():
    bounds = (0.1, 0.2, 0.4)
    # counts: [<=0.1, <=0.2, <=0.4, overflow]
    assert slo.fraction_le(bounds, [0, 0, 0, 0], 0.2) == 1.0
    assert slo.fraction_le(bounds, [4, 4, 0, 0], 0.2) == 1.0
    assert slo.fraction_le(bounds, [4, 0, 0, 4], 0.2) == \
        pytest.approx(0.5)  # overflow bucket never counts good
    # interpolation: threshold halfway through the (0.2, 0.4] bucket
    # credits half its observations
    assert slo.fraction_le(bounds, [0, 0, 10, 0], 0.3) == \
        pytest.approx(0.5)
    # matches the telemetry Histogram's own aggregation layout
    h = tm.Histogram("synapseml_t_slo_hist", (), buckets=bounds)
    for v in (0.05, 0.15, 0.15, 0.3, 0.9):
        h.observe(v)
    counts, _, _ = h._aggregate()
    assert slo.fraction_le(bounds, counts, 0.2) == pytest.approx(3 / 5)


def test_slo_burn_rate_math():
    assert slo.burn_rate(1.0, 0.999) == 0.0
    # 2% bad against a 1% budget burns 2x
    assert slo.burn_rate(0.98, 0.99) == pytest.approx(2.0)
    assert slo.burn_rate(0.999, 0.999) == pytest.approx(1.0)
    assert slo.burn_rate(0.5, 1.0) == float("inf")
    assert slo.burn_rate(1.0, 1.0) == 0.0


def test_server_slo_gauges_on_scrape():
    srv = WorkerServer("bb_slo")
    try:
        srv.slo_availability_target = 0.99
        srv.slo_latency_target = 0.99
        srv.slo_latency_threshold_s = 0.25
        # synthesize a known reply/latency history: 98 good + 2 bad,
        # latencies split around the threshold
        srv._reply_counter(200).inc(98)
        srv._reply_counter(500).inc(2)
        for _ in range(8):
            srv._m_roundtrip.observe(0.01)
        for _ in range(2):
            srv._m_roundtrip.observe(5.0)
        gauges = tm.snapshot()["gauges"]

        def g(name):
            return gauges[
                f'synapseml_{name}{{server="bb_slo"}}']

        assert g("serving_slo_availability") == pytest.approx(0.98)
        assert g("serving_slo_availability_burn_rate") == \
            pytest.approx(2.0)
        assert g("serving_slo_latency_good_fraction") == \
            pytest.approx(0.8)
        assert g("serving_slo_latency_burn_rate") == \
            pytest.approx(20.0)
        assert g("serving_slo_latency_threshold_ms") == \
            pytest.approx(250.0)
        text = tm.prometheus_text()
        assert 'synapseml_serving_slo_availability{server="bb_slo"}' \
            in text
    finally:
        srv.stop()
    # stopped server unhooks its SLO samplers (scrape-after-stop)
    assert 'server="bb_slo"' not in "".join(
        k for k in tm.snapshot()["gauges"])


# -- loadgen SLO assertion mode + JSON results ------------------------------

def test_loadgen_out_json_and_slo_assertion(tmp_path):
    from tools.loadgen import evaluate_slo, main as loadgen_main

    cs = ContinuousServer("bb_loadgen", _echo_pipeline,
                          max_batch=8).start()
    try:
        out = str(tmp_path / "results.json")
        rc = loadgen_main([
            "--url", cs.url, "--rps", "40", "--duration", "0.5",
            "--shapes", "2", "--seed", "5", "--out", out,
            "--slo-p99-ms", "20000", "--slo-availability", "0.9"])
        assert rc == 0
        with open(out) as fh:
            res = json.load(fh)
        assert res["hung"] == 0 and res["slo"]["pass"]
        assert res["slo"]["p99"]["pass"]
        assert res["slo"]["availability"]["observed"] >= 0.9
        # impossible p99 objective: assertion mode fails with exit 2
        rc = loadgen_main([
            "--url", cs.url, "--rps", "40", "--duration", "0.3",
            "--seed", "6", "--out", out, "--slo-p99-ms", "0.000001"])
        assert rc == 2
        with open(out) as fh:
            assert not json.load(fh)["slo"]["pass"]
        # evaluate_slo is pure over a summary dict
        v = evaluate_slo({"scheduled": 10, "hung": 0,
                          "by_status": {"200": 9, "503": 1},
                          "latency_ok_s": {99.0: 0.050}},
                         slo_p99_ms=100.0, slo_availability=0.95)
        assert v["p99"]["pass"] and not v["availability"]["pass"]
        assert not v["pass"]
    finally:
        cs.stop()
