"""Native C++ bridge tests (NativeLoader analogue,
ref: core/src/main/java/com/microsoft/ml/spark/core/env/NativeLoader.java:28-140;
SWIG array streaming, SURVEY.md §3.1 HOT LOOP #1)."""
import numpy as np
import pytest

from synapseml_tpu import native
from synapseml_tpu.utils.hashing import (hash_token, hash_tokens_batch,
                                         murmur3_32)

pytestmark = pytest.mark.skipif(
    not native.available(), reason="no C++ toolchain for the native bridge")


def test_murmur_bit_exact_with_python():
    tokens = ["", "a", "ab", "abc", "abcd", "abcde", "hello world",
              "émoji ☃ bytes", "x" * 257] + [f"t{i}" for i in range(100)]
    for seed in (0, 42, 0xDEADBEEF):
        for t in tokens:
            assert native.murmur3_32(t.encode(), seed) == murmur3_32(t, seed)
        batch = native.murmur3_32_batch(tokens, seed)
        for i, t in enumerate(tokens):
            assert int(batch[i]) == murmur3_32(t, seed)


def test_hash_tokens_batch_uses_native_and_matches():
    toks = [f"feature_{i}" for i in range(500)]
    got = hash_tokens_batch(toks, seed=7)
    want = [murmur3_32(t, 7) for t in toks]
    np.testing.assert_array_equal(got, want)
    # scalar memoized path agrees too
    assert hash_token("feature_3", 7) == want[3]


def test_csv_parser_matches_numpy():
    rng = np.random.default_rng(0)
    mat = rng.normal(size=(50, 8))
    text = "\n".join(",".join(f"{v:.17g}" for v in row) for row in mat)
    vals, rows = native.parse_csv_floats(text.encode())
    assert rows == 50
    np.testing.assert_allclose(vals.reshape(50, 8), mat)


def test_csv_parser_missing_and_garbage():
    vals, rows = native.parse_csv_floats(b"1,,3\nx,5,\n")
    assert rows == 2
    assert vals[0] == 1 and np.isnan(vals[1]) and vals[2] == 3
    assert np.isnan(vals[3]) and vals[4] == 5 and np.isnan(vals[5])


def test_unroll_matches_python():
    from synapseml_tpu.image.ops import unroll_chw as py_unroll

    img = np.random.default_rng(1).integers(0, 256, (9, 6, 3)).astype(np.uint8)
    np.testing.assert_array_equal(native.unroll_chw(img), py_unroll(img))
    gray = img[..., 0]
    np.testing.assert_array_equal(native.unroll_chw(gray), py_unroll(gray))


def test_loader_caches_artifact():
    import os

    from synapseml_tpu.native import loader

    lib1 = loader.load()
    lib2 = loader.load()
    assert lib1 is lib2
    assert os.path.exists(os.path.join(loader._CACHE_DIR, loader._LIB_NAME))
    assert lib1.synapse_abi_version() == loader._ABI_VERSION


def _hist_reference(binned, data, B):
    import jax
    import jax.numpy as jnp

    oh = jax.nn.one_hot(np.asarray(binned), B, dtype=jnp.float32)
    return np.asarray(jnp.einsum("nfb,nc->fbc", oh, data,
                                 precision=jax.lax.Precision.HIGHEST))


def test_pallas_histogram_interpreter_parity():
    """The kernel body's numerics, exercised UNCONDITIONALLY via the
    pallas interpreter — the same arithmetic the chip executes, minus the
    Mosaic compile. Guards the kernel against bit-rot on CPU CI."""
    import jax
    import jax.numpy as jnp

    from synapseml_tpu.gbdt import pallas_kernels as pk

    rng = np.random.default_rng(3)
    n, f, B = 3000, 5, 64
    binned = jnp.asarray(rng.integers(0, B, (n, f)), jnp.int32)
    data = jnp.asarray(rng.normal(size=(n, 3)), jnp.float32)
    got = np.asarray(jax.jit(
        lambda b, d: pk.histogram_tpu(b, d, B, interpret=True))(
        binned, data))
    want = _hist_reference(binned, data, B)
    np.testing.assert_allclose(got, want, rtol=2e-4, atol=2e-4)
    # non-multiple-of-_TN row counts exercise the zero-pad path; weighted
    # rows (mask folded into data) exercise the accumulate
    n2 = 700
    data2 = data[:n2].at[5:].mul(0.0)
    got2 = np.asarray(pk.histogram_tpu(binned[:n2], data2, B,
                                       interpret=True))
    np.testing.assert_allclose(got2, _hist_reference(binned[:n2], data2, B),
                               rtol=2e-4, atol=2e-4)


def test_pallas_histogram_routing_and_chip_parity():
    """No skips: on a CPU backend the probe must say "unavailable" so the
    grower routes to the XLA formulation; where a TPU backend is present
    the Mosaic-compiled kernel must match the reference. Chip execution
    and the kernel-vs-fallback decision are additionally recorded by
    bench.py's histogram micro-bench on the real device."""
    import jax
    import jax.numpy as jnp

    from synapseml_tpu.gbdt import pallas_kernels as pk

    if jax.default_backend() != "tpu":
        assert pk.available() is False  # router must take the XLA path
        return
    assert pk.available() is True
    rng = np.random.default_rng(3)
    n, f, B = 3000, 5, 64
    binned = jnp.asarray(rng.integers(0, B, (n, f)), jnp.int32)
    data = jnp.asarray(rng.normal(size=(n, 3)), jnp.float32)
    got = np.asarray(jax.jit(
        lambda b, d: pk.histogram_tpu(b, d, B))(binned, data))
    np.testing.assert_allclose(got, _hist_reference(binned, data, B),
                               rtol=2e-4, atol=2e-4)
